"""Multi-process cluster: roles as OS processes over the serialized wire.

The reference runs every role in its own `fdbserver` process connected by
FlowTransport (fdbserver/worker.actor.cpp:2305-2811 spawns role actors;
fdbrpc/FlowTransport.actor.cpp carries the RPCs). This module is that
deployment shape for this framework: `python -m
foundationdb_tpu.cluster.multiprocess --role {resolver,tlog,storage}`
serves one role over wire.transport (UDS by default), and ProxyPipeline
in the parent process runs the commit pipeline against them:

    client -> GRV (sequencer, in-proxy) -> commit batching -> version
    allocation -> ResolveTransactionBatchRequest over the wire (version
    chain: prevVersion ordering, Resolver.actor.cpp:269-290) -> TLog push
    -> storage apply -> client reply

The deterministic simulator remains the other backend of the same role
interfaces (sim tests never fork processes) — the reference's
one-abstraction-two-backends discipline.

Role processes NEVER touch the TPU unless RESOLVER_BACKEND=tpu is set:
the default resolver backend is the native C++ skip-list conflict set
(no jax import at all in children).
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import os
import subprocess
import sys
from typing import Any, Optional

from foundationdb_tpu.cluster.grv_proxy import GrvThrottledError  # noqa: F401
from foundationdb_tpu.utils.probes import code_probe, declare
from foundationdb_tpu.models.types import (
    CommitTransaction,
    ResolveTransactionBatchReply,
    ResolveTransactionBatchRequest,
    TransactionResult,
)
from foundationdb_tpu.wire import codec, transport

declare("controller.elastic_recruit")
declare("controller.elastic_scale_down")

# ---------------------------------------------------------------------------
# Well-known endpoint tokens (the WellKnownEndpoints.h analog).

TOKEN_RESOLVE = 0x0101
TOKEN_TLOG_PUSH = 0x0201
TOKEN_TLOG_PEEK = 0x0202
TOKEN_STORAGE_APPLY = 0x0301
TOKEN_STORAGE_GET = 0x0302
TOKEN_STORAGE_SNAPSHOT = 0x0303
TOKEN_PING = 0x0401


# ---------------------------------------------------------------------------
# Small wire messages, declared field-by-field (codec discipline: explicit
# layouts, stable ids).

_WRITERS = {
    "u8": codec.w_u8,
    "u32": codec.w_u32,
    "i64": codec.w_i64,
    "bytes": codec.w_bytes,
    "str": codec.w_str,
    "bool": codec.w_bool,
}
_READERS = {
    "u8": codec.r_u8,
    "u32": codec.r_u32,
    "i64": codec.r_i64,
    "bytes": codec.r_bytes,
    "str": codec.r_str,
    "bool": codec.r_bool,
}


def _w_mutlist(out, ms):
    codec.w_u32(out, len(ms))
    for m in ms:
        codec.w_mutation(out, m)


def _r_mutlist(buf, off):
    n, off = codec.r_u32(buf, off)
    ms = []
    for _ in range(n):
        m, off = codec.r_mutation(buf, off)
        ms.append(m)
    return ms, off


def _w_optbytes(out, v):
    codec.w_bool(out, v is not None)
    codec.w_bytes(out, v or b"")


def _r_optbytes(buf, off):
    present, off = codec.r_bool(buf, off)
    v, off = codec.r_bytes(buf, off)
    return (v if present else None), off


def _w_kvlist(out, kvs):
    codec.w_u32(out, len(kvs))
    for k, v in kvs:
        codec.w_bytes(out, k)
        codec.w_bytes(out, v)


def _r_kvlist(buf, off):
    n, off = codec.r_u32(buf, off)
    kvs = []
    for _ in range(n):
        k, off = codec.r_bytes(buf, off)
        v, off = codec.r_bytes(buf, off)
        kvs.append((k, v))
    return kvs, off


_WRITERS["mutlist"] = _w_mutlist
_READERS["mutlist"] = _r_mutlist
_WRITERS["optbytes"] = _w_optbytes
_READERS["optbytes"] = _r_optbytes
_WRITERS["kvlist"] = _w_kvlist
_READERS["kvlist"] = _r_kvlist


def _message(type_id: int, name: str, fields: list[tuple]):
    # a field is (name, kind) or (name, kind, default); wire layout is
    # the field order either way (defaults are a constructor nicety for
    # fields appended to an existing message, e.g. TLogPush.epoch).
    # Sequence defaults are spelled as tuples (dataclasses reject
    # mutable defaults) but materialize as LISTS so a default-constructed
    # message compares equal to its decode roundtrip — every list-kind
    # reader returns a list.
    def _spec(f):
        if len(f) == 2:
            return f[0]
        default = f[2]
        if isinstance(default, (tuple, list)):
            return (f[0], "object",
                    dataclasses.field(default_factory=lambda d=default: list(d)))
        return (f[0], "object", default)

    cls = dataclasses.make_dataclass(name, [_spec(f) for f in fields])
    kinds = [(f[0], f[1]) for f in fields]

    def enc(out, m, _fields=kinds):
        for f, kind in _fields:
            _WRITERS[kind](out, getattr(m, f))

    def dec(buf, off, _fields=kinds, _cls=cls):
        vals = []
        for _f, kind in _fields:
            v, off = _READERS[kind](buf, off)
            vals.append(v)
        return _cls(*vals), off

    codec.register(type_id, cls, enc, dec)
    return cls


Ping = _message(0x0201, "Ping", [("payload", "bytes")])
Pong = _message(0x0202, "Pong", [("payload", "bytes")])
TLogPush = _message(
    0x0210,
    "TLogPush",
    # epoch (default 0 = unfenced): generation fencing — after a
    # recovery locks the log at epoch E, pushes carrying an older epoch
    # are rejected with the retryable stale-epoch error (the
    # reference's tlog epoch lock). Appended with a default so legacy
    # single-generation callers/WAL replay are unchanged.
    [("version", "i64"), ("prev_version", "i64"), ("mutations", "mutlist"),
     ("epoch", "i64", 0)],
)
TLogPushReply = _message(0x0211, "TLogPushReply", [("durable_version", "i64")])
TLogPeek = _message(0x0212, "TLogPeek", [("after_version", "i64")])
TLogPeekReply = _message(
    0x0213, "TLogPeekReply", [("version", "i64"), ("mutations", "mutlist")]
)


def _w_i64list(out, vs):
    codec.w_u32(out, len(vs))
    for v in vs:
        codec.w_i64(out, v)


def _r_i64list(buf, off):
    n, off = codec.r_u32(buf, off)
    vs = []
    for _ in range(n):
        v, off = codec.r_i64(buf, off)
        vs.append(v)
    return vs, off


def _w_mutgroups(out, gs):
    codec.w_u32(out, len(gs))
    for g in gs:
        _w_mutlist(out, g)


def _r_mutgroups(buf, off):
    n, off = codec.r_u32(buf, off)
    gs = []
    for _ in range(n):
        g, off = _r_mutlist(buf, off)
        gs.append(g)
    return gs, off


_WRITERS["i64list"] = _w_i64list
_READERS["i64list"] = _r_i64list
_WRITERS["mutgroups"] = _w_mutgroups
_READERS["mutgroups"] = _r_mutgroups

TLogPeekBatchReq = _message(
    0x0214, "TLogPeekBatchReq",
    [("after_version", "i64"), ("max_entries", "u32")],
)
TLogPeekBatchReply = _message(
    0x0215, "TLogPeekBatchReply",
    [("versions", "i64list"), ("groups", "mutgroups")],
)
TOKEN_TLOG_PEEK_BATCH = 0x0204
StorageApply = _message(
    0x0220, "StorageApply", [("version", "i64"), ("mutations", "mutlist")]
)
StorageApplyReply = _message(
    0x0221, "StorageApplyReply",
    # durable=1 only when the store write-ahead-logs its applies (has a
    # data_dir): the proxy applier pops the tlog ONLY on durable acks —
    # popping against a memory-only store would erase the one durable
    # copy of committed mutations (code review r13)
    [("durable_version", "i64"), ("durable", "u8", 0)],
)
StorageGet = _message(
    0x0222, "StorageGet", [("key", "bytes"), ("version", "i64")]
)
StorageGetReply = _message(0x0223, "StorageGetReply", [("value", "optbytes")])
StorageSnapshotReq = _message(
    0x0224, "StorageSnapshotReq", [("version", "i64")]
)
StorageSnapshotReply = _message(
    0x0225, "StorageSnapshotReply", [("version", "i64"), ("kvs", "kvlist")]
)


def _w_byteslist(out, bs):
    codec.w_u32(out, len(bs))
    for b in bs:
        codec.w_bytes(out, b)


def _r_byteslist(buf, off):
    n, off = codec.r_u32(buf, off)
    bs = []
    for _ in range(n):
        b, off = codec.r_bytes(buf, off)
        bs.append(b)
    return bs, off


def _w_optbyteslist(out, vs):
    codec.w_u32(out, len(vs))
    for v in vs:
        _w_optbytes(out, v)


def _r_optbyteslist(buf, off):
    n, off = codec.r_u32(buf, off)
    vs = []
    for _ in range(n):
        v, off = _r_optbytes(buf, off)
        vs.append(v)
    return vs, off


def _w_strlist(out, vs):
    codec.w_u32(out, len(vs))
    for v in vs:
        codec.w_str(out, v)


def _r_strlist(buf, off):
    n, off = codec.r_u32(buf, off)
    vs = []
    for _ in range(n):
        v, off = codec.r_str(buf, off)
        vs.append(v)
    return vs, off


_WRITERS["byteslist"] = _w_byteslist
_READERS["byteslist"] = _r_byteslist
_WRITERS["optbyteslist"] = _w_optbyteslist
_READERS["optbyteslist"] = _r_optbyteslist
_WRITERS["strlist"] = _w_strlist
_READERS["strlist"] = _r_strlist

# Batched storage reads: every read the proxy process coalesces in one
# event-loop turn rides ONE wire roundtrip (keys[i] is served at
# versions[i] — exact MVCC semantics per key; the server waits once for
# max(versions)). The single-get RPC path stays for point reads.
StorageGetBatch = _message(
    0x0226, "StorageGetBatch",
    [("versions", "i64list"), ("keys", "byteslist")],
)
StorageGetBatchReply = _message(
    0x0227, "StorageGetBatchReply", [("values", "optbyteslist")]
)
# Batched version-ordered applies: the pipeline's applier drains its
# queue in one RPC (one WAL group fsync when persistent), keeping the
# storage version close behind the committed version so versioned
# reads don't stall on a one-RPC-per-version apply chain.
StorageApplyBatch = _message(
    0x0228, "StorageApplyBatch",
    # prev_versions (optional, same length as versions): the global
    # version chain under N commit proxies — the apply for versions[i]
    # waits until the store has applied prev_versions[i], so interleaved
    # per-proxy appliers reconstruct sequencer grant order server-side.
    # Empty = legacy single-proxy mode (queue order IS version order).
    # This frame is wire-only (the storage WAL persists StorageApply
    # records), so growing it does not touch on-disk compatibility.
    [("versions", "i64list"), ("groups", "mutgroups"),
     ("prev_versions", "i64list", ())],
)
TOKEN_STORAGE_GET_BATCH = 0x0305
TOKEN_STORAGE_APPLY_BATCH = 0x0306
RoleVersionReq = _message(0x0230, "RoleVersionReq", [("pad", "u8")])
RoleVersionReply = _message(0x0231, "RoleVersionReply", [("version", "i64")])

# Saturation telemetry (fdbtop / wire_cluster_status): every spawned
# role answers StatusRequest with its status block — role kind, version,
# and the `qos` sensor dict — as a JSON document. The status schema IS
# a JSON document end to end (the reference's status JSON,
# fdbclient/Schemas.cpp); a field-by-field wire layout here would only
# re-derive JSON at the reader and ossify the sensor set.
StatusRequest = _message(0x0240, "StatusRequest", [("pad", "u8")])
StatusReply = _message(0x0241, "StatusReply", [("payload", "str")])

# Admission control over the wire (Ratekeeper.actor.cpp:475
# GetRateInfoRequest): the front door (ProxyPipeline's GRV path)
# periodically fetches the transactions-per-second budget from the
# ratekeeper role process. JSON payload for the same reason as
# StatusReply: the budget document (budget + binding limiter +
# fail-safe state) is a status-schema slice, not a hot-path message.
GetRateInfoRequest = _message(0x0242, "GetRateInfoRequest", [("pad", "u8")])
GetRateInfoReply = _message(0x0243, "GetRateInfoReply", [("payload", "str")])

# ---------------------------------------------------------------------------
# Wire-cluster lifecycle frames (the worker / cluster-controller shape:
# fdbserver/worker.actor.cpp's RegisterWorkerRequest + the
# Initialize*Request streams). Control-plane payloads are JSON
# documents for the same reason StatusReply is: topology and
# recruitment descriptors are status-schema slices, not hot-path
# messages, and a field-by-field layout would ossify the conf.

_WRITERS["txn"] = codec.w_commit_transaction
_READERS["txn"] = codec.r_commit_transaction

# worker -> controller: "I exist, here is my socket" (re-sent on a
# cadence; doubles as the worker's liveness beacon)
RegisterWorker = _message(
    0x0250, "RegisterWorker", [("payload", "str")]
)
RegisterWorkerReply = _message(
    0x0251, "RegisterWorkerReply", [("payload", "str")]
)
# controller -> worker: host this role at this generation (the
# Initialize*Request analog; kind/epoch/config in the JSON payload)
InitializeRole = _message(0x0252, "InitializeRole", [("payload", "str")])
InitializeRoleReply = _message(
    0x0253, "InitializeRoleReply", [("payload", "str")]
)
# anyone -> controller: the current generation's topology (epoch,
# recovery state, role -> worker socket map)
TopologyRequest = _message(0x0254, "TopologyRequest", [("pad", "u8")])
TopologyReply = _message(0x0255, "TopologyReply", [("payload", "str")])
# controller -> tlog: lock the log at a new epoch (recovery step 1) —
# returns the durable version the recovery version derives from; all
# later pushes at an older epoch are fenced
# recovery_version (default -1 = phase one): the recovery walk's
# two-phase lock. Phase one (no recovery_version) bumps the epoch and
# reports the durable version; phase two re-locks at the same epoch
# with the computed recovery version, advancing the tlog's version
# floor past the old generation so parked per-tag chain waiters drain
# as duplicates instead of wedging. Never persisted — safe to extend.
TLogLock = _message(
    0x0256, "TLogLock",
    [("epoch", "i64"), ("recovery_version", "i64", -1),
     ("partitioned", "u32", 0)],
)
TLogLockReply = _message(
    0x0257, "TLogLockReply",
    [("epoch", "i64"), ("durable_version", "i64")],
)
# client -> proxy worker (the NativeAPI front door over the wire):
# GRV, versioned point read, and commit — so the commit/GRV proxies
# are killable OS processes like every other role
ClientGrvRequest = _message(0x0258, "ClientGrvRequest", [("pad", "u8")])
ClientGrvReply = _message(0x0259, "ClientGrvReply", [("version", "i64")])
ClientCommitRequest = _message(
    0x025A, "ClientCommitRequest", [("txn", "txn")]
)
ClientCommitReply = _message(
    0x025B, "ClientCommitReply", [("version", "i64")]
)
ClientReadRequest = _message(
    0x025C, "ClientReadRequest", [("key", "bytes"), ("version", "i64")]
)
ClientReadReply = _message(
    0x025D, "ClientReadReply", [("value", "optbytes")]
)
# controller -> storage (recovery): replay the locked tlog's tail above
# your durable version BEFORE the new generation opens — the old
# generation's apply queue died with its proxy, and the first new-
# generation apply would otherwise jump storage.version past the
# missing tail forever (found by the first chaos run: 375 committed
# keys missing post-recovery).
# tlog_addresses (optional): extra tlogs beyond tlog_address for the
# tag-partitioned log system — catch-up k-way merges the peek streams
# by version. recovery_version (default -1): after replay, advance the
# store's version floor to the new generation's recovery version so the
# first chained apply (prev = recovery_version) finds its predecessor.
StorageCatchUp = _message(
    0x025E, "StorageCatchUp",
    [("tlog_address", "str"), ("tlog_addresses", "strlist", ()),
     ("recovery_version", "i64", -1)],
)
StorageCatchUpReply = _message(
    0x025F, "StorageCatchUpReply", [("version", "i64")]
)
# proxy applier -> tlog: storage has durably applied through `version`
# — the log prefix at or below it is dead weight (recovery replays it
# for nothing; the drill measured tlog re-init time growing with run
# length) and is popped, the reference's pop-on-storage-durable.
TLogPop = _message(
    0x0260, "TLogPop", [("version", "i64"), ("epoch", "i64", 0)]
)
TLogPopReply = _message(
    0x0261, "TLogPopReply", [("durable_version", "i64")]
)
# monitor -> controller: PUSH-ON-DEATH (ISSUE 14): the supervising
# monitor reaps a dead worker child (SIGCHLD) and tells the controller
# IMMEDIATELY, so death detection costs one supervision poll instead of
# HEARTBEAT_MISSES consecutive status polls — the PR-13 drill measured
# time-to-recover detection-dominated (~1s of heartbeat misses).
# Heartbeats remain the backstop for deaths the monitor cannot see
# (wedged-but-alive processes, a dead monitor).
WorkerDeath = _message(0x0262, "WorkerDeath", [("payload", "str")])
WorkerDeathReply = _message(
    0x0263, "WorkerDeathReply", [("payload", "str")]
)
# ratekeeper -> proxy: PUSH-BASED RATE UPDATE (ISSUE 15, the PR-14
# push-frame shape applied to the budget): when a control cycle moves
# the budget past the push hysteresis (or flips the binding limiter),
# the ratekeeper pushes the fresh GetRateInfo payload to every proxy
# instead of waiting out the proxy's poll cadence — budget staleness
# during overload ONSET drops from the fetch interval to one control
# cycle. Polling remains the backstop (a dead pusher degrades to the
# exact pre-r15 behavior, including the fail-safe decay).
RateUpdate = _message(0x0264, "RateUpdate", [("payload", "str")])
RateUpdateReply = _message(0x0265, "RateUpdateReply", [("payload", "str")])
# proxy -> sequencer (ISSUE 19, the MasterInterface shape): version-
# batch allotment moves behind an RPC so N commit proxies share one
# global version chain. Each grant carries (prev_version, version) —
# the proxy hands prev_version to every resolver, which orders
# interleaved proxy batches exactly as a single proxy would. `tags`
# declares which tag-partitioned tlogs this batch will push to;
# `tag_prevs` returns the per-tag previous version for each declared
# tag so the per-tlog chains stay gapless even though a tlog only sees
# the versions that own its tags. Proxies number requests from 1;
# the sequencer replays cached grants for duplicate request_nums and
# grants in request_num order per proxy (the reference's
# GetCommitVersionRequest discipline).
GetCommitVersionRequest = _message(
    0x0266, "GetCommitVersionRequest",
    [("proxy_id", "str"), ("request_num", "u32"),
     ("most_recent_processed", "u32"), ("epoch", "i64"),
     ("tags", "i64list", ())],
)
GetCommitVersionReply = _message(
    0x0267, "GetCommitVersionReply",
    [("version", "i64"), ("prev_version", "i64"), ("request_num", "u32"),
     ("tag_prevs", "i64list", ())],
)
# proxy -> sequencer: report a committed version BEFORE acking the
# client, so any later GRV (from any proxy) observes it. version=-1 is
# a pure read — the GRV path fetches the live committed version from
# the sequencer instead of trusting one proxy's local view.
ReportRawCommittedVersionRequest = _message(
    0x0268, "ReportRawCommittedVersionRequest",
    [("version", "i64"), ("epoch", "i64")],
)
ReportRawCommittedVersionReply = _message(
    0x0269, "ReportRawCommittedVersionReply", [("live_version", "i64")]
)

TOKEN_TLOG_VERSION = 0x0203
TOKEN_STORAGE_VERSION = 0x0304
TOKEN_RESOLVER_VERSION = 0x0102
TOKEN_STATUS = 0x0501
TOKEN_GET_RATE_INFO = 0x0502
TOKEN_TLOG_LOCK = 0x0205
TOKEN_TLOG_POP = 0x0206
# lifecycle control plane
TOKEN_REGISTER_WORKER = 0x0601
TOKEN_INIT_ROLE = 0x0602
TOKEN_TOPOLOGY = 0x0603
TOKEN_WORKER_DEATH = 0x0604
TOKEN_RATE_UPDATE = 0x0605
# client front door (proxy worker)
TOKEN_CLIENT_GRV = 0x0701
TOKEN_CLIENT_COMMIT = 0x0702
TOKEN_CLIENT_READ = 0x0703
TOKEN_STORAGE_CATCHUP = 0x0307
# sequencer role (version-batch allotment)
TOKEN_GET_COMMIT_VERSION = 0x0801
TOKEN_REPORT_COMMITTED = 0x0802
TOKEN_SEQUENCER_VERSION = 0x0803


# ---------------------------------------------------------------------------
# Role servers.


def _fence_epoch(req, role) -> None:
    """Generation fencing shared by every fenced endpoint: unless the
    request carries `role`'s exact epoch, count the reject and raise
    the retryable stale-epoch error (cluster/generation.py). Requests
    without an epoch field fence as epoch 0 — the unfenced legacy
    deployment matches an unfenced role."""
    req_epoch = getattr(req, "epoch", 0)
    if req_epoch != role.epoch:
        from foundationdb_tpu.cluster.generation import stale_epoch_message

        role.stale_epoch_rejects += 1
        raise transport.RemoteError(
            stale_epoch_message(req_epoch, role.epoch)
        )


def default_resolver_boundaries(n: int) -> list[bytes]:
    """Even byte-prefix keyspace split for n resolvers: the n-1
    interior boundary keys. The SAME formula as
    parallel/sharding.default_boundaries (pinned equal in
    tests/test_elasticity.py) — duplicated here so the controller's
    control-plane process never pays the jax import that module
    carries."""
    if not 1 <= n <= 256:
        raise ValueError(f"resolver count must be in [1, 256], got {n}")
    return [bytes([(256 * (i + 1)) // n]) for i in range(n - 1)]


def resolver_key_ranges(boundaries: list[bytes]) -> list[tuple]:
    """[(lo, hi_or_None)] partitions from n-1 interior split keys (the
    parallel/sharding.default_boundaries shape): resolver i owns
    [lo_i, hi_i), the last partition is unbounded above."""
    lows = [b""] + list(boundaries)
    highs = list(boundaries) + [None]
    return list(zip(lows, highs))


def clip_transactions(txns, lo: bytes, hi) -> list:
    """The multi-resolver split (ISSUE 15): each resolver sees only the
    conflict-range pieces inside its key partition — the reference's
    ResolutionRequestBuilder (CommitProxyServer.actor.cpp:105-261),
    exactly the clip `testing/oracle.MultiResolverOracle` models and
    the mesh-sharded kernel runs on device. Slot alignment is
    preserved: every transaction appears at its index in every
    resolver's batch (the verdict min-combine needs aligned slots); a
    txn with no local READS is a local blind write and votes COMMITTED
    (its clipped local writes still merge into that resolver's history
    on a local commit — the reference's phantom-commit semantics,
    pinned against MultiResolverOracle in tests). Applies to the
    stripped conflict-metadata hop only — mutations never travel on
    the resolve hop."""

    def clip(ranges):
        out = []
        for b, e in ranges:
            cb = b if b > lo else lo
            ce = e if hi is None or e < hi else hi
            if cb < ce:
                out.append((cb, ce))
        return out

    return [
        CommitTransaction(
            read_conflict_ranges=clip(t.read_conflict_ranges),
            write_conflict_ranges=clip(t.write_conflict_ranges),
            read_snapshot=t.read_snapshot,
            report_conflicting_keys=t.report_conflicting_keys,
            debug_id=t.debug_id,
        )
        for t in txns
    ]


def _decode_alloc_count(txns) -> int:
    """Per-batch count of the Python objects a per-transaction frame
    decode materializes — the columnar path's structural ZERO on jitted
    backends, ledger-gated by bench_pipeline (resolve_decode_allocs_
    per_txn). Mirrors r_commit_transaction's allocation sites exactly:
    per txn the CommitTransaction + its two range lists; per conflict
    range the tuple + two bytes keys; per mutation the Mutation + two
    bytes params."""
    n = 0
    for t in txns:
        n += 3 + 3 * (
            len(t.read_conflict_ranges) + len(t.write_conflict_ranges)
        ) + 3 * len(t.mutations)
    return n


class ResolverRole:
    """Wire-served resolver: version-chained conflict resolution.

    Reproduces the resolveBatch ordering contract
    (fdbserver/Resolver.actor.cpp:269-290,496): requests wait until the
    resolver's version reaches req.prev_version, resolve, then advance to
    req.version — so out-of-order arrivals from concurrent proxies are
    serialized into the global commit order. Duplicate requests (same
    version) replay the recorded reply (:515-530).
    """

    def __init__(self, backend: str = "native", window: int = 5_000_000,
                 epoch: int = 0, compute_cost_per_txn: float = 0.0):
        self.version = -1
        self.window = window
        #: modeled per-transaction compute seconds (the wire twin of the
        #: sim Resolver.sim_compute_cost_per_txn, PR 8): awaited per
        #: batch AFTER the real resolve, scaled by the txns that carry
        #: LOCAL conflict work — under the multi-resolver split each
        #: resolver pays only for its partition's rows, so the
        #: elasticity drill's goodput genuinely scales with recruits.
        #: 0.0 (production default) is a strict no-op.
        self.compute_cost_per_txn = float(compute_cost_per_txn or 0.0)
        #: generation fencing: a recruited resolver belongs to ONE
        #: recovery generation; batches carrying any other epoch are
        #: rejected retryably (cluster/generation.py). 0 = unfenced
        #: standalone deployment (legacy spawn_role without a
        #: controller) — requests default to epoch 0 and match.
        self.epoch = epoch
        self.stale_epoch_rejects = 0
        self._cond: asyncio.Condition | None = None
        self._replies: dict[int, ResolveTransactionBatchReply] = {}
        self._backend = backend
        # -- saturation sensors: the reference resolver's exact four
        # distributions (Resolver.actor.cpp resolverLatencyDist /
        # queueWaitLatencyDist / computeTimeDist / queueDepthDist) on
        # the WALL clock — this is a real OS process, there is no
        # virtual clock to be deterministic against
        from foundationdb_tpu.utils.metrics import LatencySample

        from foundationdb_tpu.utils.metrics import TimerSmoother

        self._waiting = 0  # requests parked on the version chain
        # -- columnar-vs-object structural accounting (r12): the
        # "two copies" claim as gated numbers, surfaced in status() and
        # landed in the perf ledger by bench_pipeline. `copies` counts
        # full key-data materializations between the wire frame payload
        # and the conflict backend's input (each site documented where
        # it increments); `decode_allocs` counts per-transaction Python
        # objects the decode materialized (the columnar path's
        # structural zero on jitted backends).
        self.path_stats = {
            "columnar_batches": 0,
            "object_batches": 0,
            "txns": 0,
            "copies": 0,
            "decode_allocs": 0,
        }
        # -- conflict-range key sample (ISSUE 20): the wire twin of the
        # sim resolver's ResolutionBalancer sample — begin keys by touch
        # count, decayed at the shared sampling.KEY_SAMPLE_LIMIT
        self._key_sample: dict[bytes, int] = {}
        self.queue_depth = LatencySample("queueDepth")
        self.queue_wait_latency = LatencySample("queueWaitLatency")
        self.compute_time = LatencySample("computeTime")
        self.resolver_latency = LatencySample("resolverLatency")
        # busy-fraction smoother (the Ratekeeper's resolver-occupancy
        # input): compute seconds accumulate as a rate — a resolver
        # spending ~every wall second inside _resolve_now reads ~1.0.
        # This is the signal that catches few-huge-batch saturation,
        # where queue DEPTH stays deceptively small because the
        # blocking compute keeps arrivals out of the parked count.
        self.occupancy = TimerSmoother(2.0)
        if backend == "native":
            from foundationdb_tpu.models.conflict_set import (
                KernelStageMetrics,
            )
            from foundationdb_tpu.native import NativeSkipListConflictSet

            self._cs = NativeSkipListConflictSet(window=window)
            # the native skip list has no stage split, but the kernel
            # panel must still render (fdbtop pins it): compute seconds
            # land in the "kernel" stage and the compile-cache counters
            # are process-global anyway
            self._kernel_metrics = KernelStageMetrics()
        elif backend in ("cpu", "tpu", "tpu-force"):
            from foundationdb_tpu.config import KernelConfig

            cfg_env = os.environ.get("RESOLVER_KERNEL", "")
            kcfg = KernelConfig(
                max_key_bytes=16,
                max_txns=1024,
                max_reads=4096,
                max_writes=4096,
                history_capacity=1 << 16,
                window_versions=window,
            ) if not cfg_env else eval(cfg_env)  # noqa: S307 (operator-supplied)
            if getattr(kcfg, "n_shards", 0) > 1:
                # the mesh-sharded tiered kernel needs its devices
                # BEFORE the first backend init in this role process —
                # which happens during the conflict_set IMPORT below
                # (ops/keys.py runs an eager op at module scope), so the
                # virtual-device flag must land before that import. On a
                # real TPU slice the devices already exist.
                from foundationdb_tpu.parallel.mesh import (
                    ensure_host_device_count,
                )

                ensure_host_device_count(kcfg.n_shards)
            from foundationdb_tpu.models.conflict_set import (
                KernelStageMetrics,
                make_conflict_set,
            )

            self._cs = make_conflict_set(kcfg, backend)
            self._kernel_metrics = (
                getattr(self._cs, "metrics", None) or KernelStageMetrics()
            )
            self._warm_compile(kcfg, backend)
        else:
            raise ValueError(f"unknown resolver backend {backend!r}")

    def _warm_compile(self, kcfg, backend: str) -> None:
        """Compile the resolver kernels at ROLE STARTUP, not on the
        first commit batch: a cold jit compile (seconds) landing inside
        the first resolve request was the wire-mode tpu-force p50
        pathology (PIPELINE_r06: 18.9s) — the stall hid in commit
        latency where no ledger attributed it. A throwaway conflict set
        with the same config drives every padded-shape kernel through
        the shared module-level jit cache (shapes are G-independent, so
        one dummy resolve covers all batch sizes), and the measured
        seconds land in KernelStageMetrics.compile where cluster_status
        and commit_debug can see them."""
        import time as _time

        from foundationdb_tpu.models.conflict_set import make_conflict_set

        t0 = _time.perf_counter()
        scratch = make_conflict_set(kcfg, backend)
        scratch.resolve(
            [
                CommitTransaction(
                    read_conflict_ranges=[(b"\x00warm", b"\x00warm\x00")],
                    write_conflict_ranges=[(b"\x00warm", b"\x00warm\x00")],
                    read_snapshot=0,
                )
            ],
            1,
        )
        dt = _time.perf_counter() - t0
        metrics = getattr(self._cs, "metrics", None)
        if metrics is not None:
            metrics.compile.sample(dt)
            metrics.counters.add("warmCompiles")
        # per-signature compile seconds in the process-global compile
        # observability block (utils/compile_cache.stats)
        from foundationdb_tpu.utils import compile_cache as _cc

        _cc.record_compile(
            f"resolver_warm/{backend}/txns={kcfg.max_txns}", dt
        )
        from foundationdb_tpu.utils.trace import SEV_INFO, TraceEvent

        TraceEvent("ResolverWarmCompile", severity=SEV_INFO).detail(
            "Backend", backend
        ).detail("Seconds", round(dt, 3)).log()

    def _cond_lazy(self) -> asyncio.Condition:
        if self._cond is None:
            self._cond = asyncio.Condition()
        return self._cond

    async def resolve(self, req: ResolveTransactionBatchRequest):
        # generation fence FIRST, before the version-chain wait: a
        # stale-generation batch must bounce immediately (its proxy is
        # dead or fenced), never park on a version chain the new
        # generation restarted far above it
        _fence_epoch(req, self)
        # span context propagated ACROSS the process boundary: the
        # request's (trace_id, span_id) pair arrived over the UDS wire
        # (wire/codec.py), and this role's resolveBatch span chains to
        # it — one trace spanning proxy and resolver OS processes.
        span = None
        if req.span is not None:
            from foundationdb_tpu.utils.spans import Span, SpanContext

            span = Span(
                "Resolver.resolveBatch", parent=SpanContext(*req.span)
            ).attribute("Version", req.version)
        if req.debug_id is not None:
            from foundationdb_tpu.utils import commit_debug as _cdbg
            from foundationdb_tpu.utils import trace as _tr

            _tr.g_trace_batch.add_event(
                "CommitDebug", req.debug_id, _cdbg.RESOLVER_BEFORE
            )
        try:
            return await self._resolve_ordered(req)
        finally:
            if req.debug_id is not None:
                _tr.g_trace_batch.add_event(
                    "CommitDebug", req.debug_id, _cdbg.RESOLVER_AFTER
                )
            if span is not None:
                span.finish()

    async def _resolve_ordered(self, req: ResolveTransactionBatchRequest):
        import time as _time

        t_arrive = _time.perf_counter()
        cond = self._cond_lazy()
        async with cond:
            self._waiting += 1
            self.queue_depth.sample(self._waiting)
            try:
                await cond.wait_for(
                    lambda: self.version >= req.prev_version
                )
            finally:
                self._waiting -= 1
            self.queue_wait_latency.sample(_time.perf_counter() - t_arrive)
            if req.version <= self.version:
                # duplicate (proxy retry): replay the recorded reply
                reply = self._replies.get(req.version)
                if reply is None:
                    raise transport.RemoteError(
                        f"version {req.version} already resolved and expired"
                    )
                return reply
            if req.debug_id is not None:
                from foundationdb_tpu.utils import commit_debug as _cdbg
                from foundationdb_tpu.utils import trace as _tr

                # past the version-chain wait (the reference's orderer):
                # the next mark is ColumnarDecode, so the waterfall's
                # columnar_decode stage brackets exactly the frame ->
                # kernel-tensor work
                _tr.g_trace_batch.add_event(
                    "CommitDebug", req.debug_id, _cdbg.RESOLVER_AFTER_ORDERER
                )
            t_compute = _time.perf_counter()
            reply = self._resolve_now(req)
            if self.compute_cost_per_txn > 0.0:
                # modeled compute rides the version chain exactly like
                # real compute (successors wait on the condition), but
                # as an await so the role process keeps serving status
                # polls — occupancy and compute_time absorb it below,
                # which is what makes the Ratekeeper's resolver_busy
                # attribution (and the elasticity drill's plateau) real
                await asyncio.sleep(
                    self.compute_cost_per_txn * self._local_txns(req)
                )
            dt_compute = _time.perf_counter() - t_compute
            self.compute_time.sample(dt_compute)
            self.occupancy.add_delta(dt_compute)
            self.resolver_latency.sample(_time.perf_counter() - t_arrive)
            self._replies[req.version] = reply
            # retain a bounded replay window
            floor = req.version - self.window
            self._replies = {
                v: r for v, r in self._replies.items() if v >= floor
            }
            self.version = req.version
            cond.notify_all()
            return reply

    def _local_txns(self, req) -> int:
        """Transactions in this batch carrying LOCAL conflict work —
        the modeled-compute multiplier. Under the proxy-side
        multi-resolver split, foreign-partition txns arrive with zero
        ranges (slot-aligned local blind writes) and cost nothing."""
        if isinstance(req, codec.ResolveBatchColumnar):
            cols = req.cols
            return sum(
                1 for i in range(cols.n_txns)
                if int(cols.read_counts[i]) + int(cols.write_counts[i]) > 0
            )
        return sum(
            1 for t in req.transactions
            if t.read_conflict_ranges or t.write_conflict_ranges
        )

    def _trace_columnar_decode(self, req) -> None:
        """The Resolver.resolveBatch.ColumnarDecode micro-event: fired
        the moment the columnar frame has become the backend's input
        (kernel tensors on jitted backends, reconstructed objects on
        the object fallback) — with AfterOrderer as the opening mark,
        the waterfall's columnar_decode stage is exactly the decode."""
        if req.debug_id is None:
            return
        from foundationdb_tpu.utils import commit_debug as _cdbg
        from foundationdb_tpu.utils import trace as _tr

        _tr.g_trace_batch.add_event(
            "CommitDebug", req.debug_id, _cdbg.RESOLVER_COLUMNAR_DECODE
        )

    def _columnar_to_objects(self, req) -> list:
        """The object fallback shared by every object-consuming backend
        (native skip list, CPU oracle): reconstruct exact transactions
        from the lossless blob — ONE blob -> objects copy, allocations
        counted honestly — and mark the decode stage. One helper so the
        ledger-gated accounting can never diverge between backends."""
        from foundationdb_tpu.utils import packing as _packing

        txns = _packing.columnar_to_transactions(req.cols)
        self.path_stats["copies"] += 1
        self.path_stats["decode_allocs"] += _decode_alloc_count(txns)
        self._trace_columnar_decode(req)
        return txns

    def _note_key_sample(self, req) -> None:
        """Feed the conflict-range key sample from BOTH frame kinds
        without materializing transactions: the columnar blob's
        canonical key order (read begins, read ends, write begins,
        write ends — packing._KEY_ORDER_DOC) lets the begin keys slice
        straight out of the key_lens offsets."""
        from foundationdb_tpu.cluster import sampling as _sampling

        sample = self._key_sample
        if isinstance(req, codec.ResolveBatchColumnar):
            cols = req.cols
            if len(cols.key_lens) == 0:
                return
            import numpy as _np

            offs = _np.concatenate(
                ([0], _np.cumsum(cols.key_lens, dtype=_np.int64))
            )
            blob = bytes(cols.key_blob)
            nr, nw = cols.n_reads, cols.n_writes
            for i in (*range(nr), *range(2 * nr, 2 * nr + nw)):
                b = blob[offs[i]:offs[i + 1]]
                sample[b] = sample.get(b, 0) + 1
        else:
            for t in req.transactions:
                for b, _e in t.read_conflict_ranges + t.write_conflict_ranges:
                    sample[b] = sample.get(b, 0) + 1
        if len(sample) > _sampling.KEY_SAMPLE_LIMIT:
            _sampling.decay_key_sample(sample)

    def _resolve_now(self, req) -> ResolveTransactionBatchReply:
        columnar = isinstance(req, codec.ResolveBatchColumnar)
        stats = self.path_stats
        self._note_key_sample(req)
        if columnar:
            stats["columnar_batches"] += 1
            stats["txns"] += req.cols.n_txns
        else:
            stats["object_batches"] += 1
            stats["txns"] += len(req.transactions)
            # the object frame already materialized per-txn objects
            # inside codec.decode (the transport dispatch): one
            # payload -> objects copy plus the per-txn allocations
            stats["copies"] += 1
            stats["decode_allocs"] += _decode_alloc_count(req.transactions)
        if self._backend == "native":
            import time as _time

            txns = (
                self._columnar_to_objects(req) if columnar
                else req.transactions
            )
            t0 = _time.perf_counter()
            verdicts = self._cs.resolve(txns, req.version)
            self._kernel_metrics.kernel.sample(_time.perf_counter() - t0)
            self._kernel_metrics.counters.add("resolveBatches")
            committed = [TransactionResult(int(v)) for v in verdicts]
            ckr: dict[int, list[int]] = {}
        else:
            jitted = hasattr(self._cs, "pack_columnar_batch")
            if columnar and jitted:
                # THE columnar win: wire bytes -> device tensors with
                # TWO copies total — the blob -> padded-tensor scatter
                # (pack_columnar_batch) and the host -> device transfer
                # inside the dispatch. No per-txn objects ever exist.
                batch = self._cs.pack_columnar_batch(req.cols, req.version)
                self._trace_columnar_decode(req)
                stats["copies"] += 2
                res = self._cs.resolve_columnar_packed(req.cols, batch)
            elif columnar:
                # CPU-oracle backend: object-consuming fallback
                res = self._cs.resolve(
                    self._columnar_to_objects(req), req.version
                )
            else:
                if jitted:
                    # object path on a jitted backend: pack_batch
                    # re-flattens the decoded objects (+1) and the
                    # dispatch transfers (+1) on top of the decode copy
                    stats["copies"] += 2
                res = self._cs.resolve(req.transactions, req.version)
            committed = res.verdicts
            ckr = res.conflicting_key_ranges
        return ResolveTransactionBatchReply(
            committed=committed,
            conflicting_key_range_map=ckr,
            state_mutations=[],
            debug_id=req.debug_id,
        )

    def status(self) -> dict:
        """StatusRequest payload: role kind, version, and the qos
        sensor block (the four reference distributions + kernel
        occupancy on jitted backends)."""
        qos = {
            "queue_depth": self._waiting,
            "occupancy": self.occupancy.smooth_rate(),
            "queue_depth_dist": self.queue_depth.as_dict(),
            "queue_wait_dist": self.queue_wait_latency.as_dict(),
            "compute_time_dist": self.compute_time.as_dict(),
            "resolver_latency_dist": self.resolver_latency.as_dict(),
        }
        # the kernel panel is ALWAYS present (fdbtop pins it): jitted
        # backends report their conflict set's stage metrics, native
        # the role-owned block (compute seconds + process-global
        # compile-cache counters)
        qos["kernel"] = self._kernel_metrics.qos()
        # columnar-vs-object frame accounting (r12): bench_pipeline
        # reads this to land the structural copy/alloc metrics
        qos["resolve_path"] = dict(self.path_stats)
        qos["stale_epoch_rejects"] = self.stale_epoch_rejects
        # conflict-range key sample (ISSUE 20): identical block shape
        # to the sim resolver's — sampling.key_sample_qos is shared
        from foundationdb_tpu.cluster import sampling as _sampling

        qos["key_sample"] = _sampling.key_sample_qos(self._key_sample)
        return {
            "role": "resolver",
            "version": self.version,
            "backend": self._backend,
            "epoch": self.epoch,
            "qos": qos,
        }


def _looks_sealed(blob: bytes) -> bool:
    try:
        from foundationdb_tpu.crypto.blob_cipher import is_encrypted
    except ImportError:
        # crypto stack not installed (the header sniff is defense in
        # depth BEHIND the fsynced ENCRYPTION_MODE marker, which is
        # still enforced): without `cryptography` this host can never
        # have sealed a record, so nothing local can look sealed — and
        # a dir copied from an encrypted host still trips the marker.
        return False
    return is_encrypted(blob)


def _check_encryption_marker(data_dir: str, encryption) -> None:
    """Persisted encryption mode (the reference persists
    encryptionAtRestMode in the database configuration and refuses mode
    flips — DatabaseConfiguration.h): a store written encrypted must
    never be opened unencrypted, or sealed bytes would be served as
    data. Sniffing record magic alone can false-positive on user bytes;
    the marker is deterministic."""
    marker = os.path.join(data_dir, "ENCRYPTION_MODE")
    if encryption is not None:
        if not os.path.exists(marker):
            # fsync file AND directory: the data records are all
            # fsynced, so the marker must be at least as durable — a
            # power loss that keeps sealed records but drops the
            # marker would downgrade the store silently
            with open(marker, "w") as f:
                f.write("aes-256-ctr\n")
                f.flush()
                os.fsync(f.fileno())
            dfd = os.open(data_dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
    elif os.path.exists(marker):
        raise RuntimeError(
            f"{data_dir} was written with encryption-at-rest; "
            "restart the role with --encrypt (and the same KMS)"
        )


def _decode_tlog_record(blob: bytes):
    """Decode one tlog WAL record, accepting the pre-epoch layout.

    The wire is protected by the PROTOCOL_VERSION handshake, but disk
    records are not version-gated: a data dir written before the epoch
    field (protocol 0007) holds 3-field TLogPush frames, and the
    cross-version restart discipline (tests/fixtures/ondisk_r*) says a
    newer build must open them. Legacy records replay at epoch 0 — the
    recovery lock re-fences the log before any new-generation push."""
    try:
        return codec.decode(blob)
    except codec.CodecError:
        buf = memoryview(blob)
        tid, off = codec.r_u16(buf, 0)
        if tid != 0x0210:
            raise
        version, off = codec.r_i64(buf, off)
        prev, off = codec.r_i64(buf, off)
        muts, off = _r_mutlist(buf, off)
        if off != len(buf):
            raise
        return TLogPush(
            version=version, prev_version=prev, mutations=muts, epoch=0
        )


class TLogRole:
    """Wire-served transaction log: version-ordered append + peek.

    With a data dir, every push rides the native DiskQueue
    (native/diskqueue.cpp — the fdbserver/DiskQueue.actor.cpp role):
    frames are fsynced BEFORE the push is acked (tLogCommit discipline,
    TLogServer.actor.cpp:2311), and a restart recovers exactly the acked
    entries via the crc-checked recovery scan.
    """

    def __init__(self, data_dir: str | None = None, encryption=None,
                 epoch: int = 0, partitioned: bool = False):
        self.entries: list[tuple[int, list]] = []  # (version, mutations)
        self.version = -1
        self._dq = None
        #: tag-partitioned mode (ISSUE 19): this tlog owns a key-range
        #: tag and sees only the versions that touch it, pushed by N
        #: proxies concurrently — a push whose per-tag prev_version is
        #: ahead of us PARKS on the chain condition until its
        #: predecessor lands (or recovery advances the floor), instead
        #: of relying on the single-proxy serialized-push invariant.
        self.partitioned = partitioned
        self._chain_cond: asyncio.Condition | None = None
        self._chain_waiters = 0
        #: generation fencing (the reference's tlog epoch lock): after
        #: lock(E), pushes at an older epoch are rejected retryably —
        #: no old in-flight batch can slip in a commit post-recovery.
        #: 0 = unfenced legacy deployment.
        self.epoch = epoch
        self.stale_epoch_rejects = 0
        # -- saturation sensors (the Ratekeeper's TLogQueueInfo inputs):
        # retained queue bytes through a wall-clock smoother — this is
        # a real OS process, the reference's Smoother(timer()) shape
        from foundationdb_tpu.utils.metrics import TimerSmoother

        self._queue_bytes = 0
        self.smoothed_queue_bytes = TimerSmoother(1.0)
        self.smoothed_input_bytes = TimerSmoother(1.0)
        # the tlog persists the SAME mutation bytes storage seals — an
        # unencrypted tlog disk would hollow out the at-rest guarantee
        # (code review r5); whole records are sealed here (no ordering
        # constraint on tlog frames, unlike LSM keys)
        self._enc = encryption if data_dir else None
        #: disk-queue seq per pushed version: the pop boundary lookup
        self._seq_by_version: list[tuple[int, int]] = []
        self._data_dir = data_dir
        if data_dir:
            from foundationdb_tpu.native import DiskQueue

            os.makedirs(data_dir, exist_ok=True)
            _check_encryption_marker(data_dir, self._enc)
            if self._enc is not None:
                # first push must not block the loop on a KMS trip
                self._enc.prefetch()
            self._dq = DiskQueue(os.path.join(data_dir, "tlog"))
            for _seq, blob in self._dq.recovered:
                if self._enc is not None:
                    blob = self._enc.open(blob)
                elif _looks_sealed(blob):
                    raise RuntimeError(
                        "sealed tlog record but encryption is disabled"
                    )
                rec = _decode_tlog_record(blob)
                self.entries.append((rec.version, list(rec.mutations)))
                self.version = max(self.version, rec.version)
                self._seq_by_version.append((rec.version, _seq))
            # the popped-version marker: a fully-popped log must still
            # restart at its durable HEAD version — the recovery
            # version derives from it, and a regressed version would
            # let a new generation allocate versions below committed
            # data (found by the save-and-kill restart test)
            self.version = max(self.version, self._read_popped_marker())
            self._queue_bytes = sum(
                8 + len(m.param1) + len(m.param2)
                for _v, ms in self.entries for m in ms
            )
            self.smoothed_queue_bytes.set_total(self._queue_bytes)

    async def lock(self, req: "TLogLock") -> "TLogLockReply":
        """The recovery lock (recovery step 1, the coordinated-state +
        tlog epoch lock): advance to the new generation — every push
        still carrying an older epoch is fenced from here on — and
        return the durable version the recovery version derives from."""
        if req.epoch < self.epoch:
            from foundationdb_tpu.cluster.generation import (
                stale_epoch_message,
            )

            raise transport.RemoteError(
                stale_epoch_message(req.epoch, self.epoch)
            )
        self.epoch = req.epoch
        if req.partitioned:
            # scale-out recovery onto a SURVIVING tlog: the lock turns
            # the per-tag chain wait on (the role instance outlives the
            # topology change that made pushes arrive out of order)
            self.partitioned = True
        durable = self.version
        if req.recovery_version >= 0:
            # phase two of the two-phase recovery lock: advance the
            # version floor past the old generation so the new
            # generation's first push (prev = a per-tag version the old
            # generation owned) finds its predecessor, and wake parked
            # chain waiters — they re-check the epoch and drain as
            # stale rather than wedging across the generation bump.
            self.version = max(self.version, req.recovery_version)
        await self._chain_wake()
        return TLogLockReply(epoch=self.epoch, durable_version=durable)

    def _chain(self) -> asyncio.Condition:
        if self._chain_cond is None:
            self._chain_cond = asyncio.Condition()
        return self._chain_cond

    async def _chain_wake(self) -> None:
        if self._chain_cond is not None:
            async with self._chain_cond:
                self._chain_cond.notify_all()

    async def push(self, req: TLogPush) -> TLogPushReply:
        # generation fence: a locked log rejects the old generation's
        # pushes (and a not-yet-locked log rejects a future
        # generation's — the recovery always locks first)
        _fence_epoch(req, self)
        if self.partitioned and req.prev_version > self.version:
            # tag-partitioned chain wait: the predecessor version for
            # this tlog's tag hasn't landed yet (another proxy owns
            # it). Park until it does, or until a recovery bumps the
            # epoch / advances the floor — bounded so a dead
            # predecessor proxy surfaces as a retryable stall instead
            # of a silent wedge.
            cond = self._chain()
            epoch0 = self.epoch
            self._chain_waiters += 1
            try:
                async with cond:
                    await asyncio.wait_for(
                        cond.wait_for(
                            lambda: self.version >= req.prev_version
                            or self.epoch != epoch0
                        ),
                        timeout=10.0,
                    )
            except asyncio.TimeoutError:
                raise transport.RemoteError(
                    "tlog chain stall: prev_version "
                    f"{req.prev_version} never arrived (retryable)"
                )
            finally:
                self._chain_waiters -= 1
            _fence_epoch(req, self)
        if req.version <= self.version:
            # duplicate push: idempotent ack (proxy retry after lost
            # reply; in partitioned mode also a pre-recovery push
            # overtaken by the recovery-version floor)
            return TLogPushReply(durable_version=self.version)
        # Forward version skips are legal: the proxy serializes pushes and
        # versions are consumed by failed batches and by recovery (a batch
        # resolved but lost in a crash window leaves prev_version above
        # our recovered version — the reference's recovery likewise
        # restarts the chain above lastEpochEnd). Only regressions are
        # rejected (the <= check above).
        if self._dq is not None:
            blob = codec.encode(req)
            if self._enc is not None:
                blob = self._enc.seal(blob)
            seq = self._dq.push(blob)
            if self._dq.commit() is None:
                # fsync/pwrite failed: the data is NOT durable — refuse
                # the ack rather than lie (tLogCommit discipline)
                raise transport.RemoteError("tlog disk commit failed")
            self._seq_by_version.append((req.version, seq))
        self.entries.append((req.version, list(req.mutations)))
        self.version = req.version
        nb = sum(
            8 + len(m.param1) + len(m.param2) for m in req.mutations
        )
        self._queue_bytes += nb
        self.smoothed_input_bytes.add_delta(nb)
        self.smoothed_queue_bytes.set_total(self._queue_bytes)
        if self.partitioned:
            await self._chain_wake()
        return TLogPushReply(durable_version=self.version)

    def status(self) -> dict:
        """StatusRequest payload: retained queue depth/bytes (smoothed
        + instantaneous) and the durable version — the wire analog of
        the sim tlog's `saturation()` block."""
        return {
            "role": "log",
            "version": self.version,
            "epoch": self.epoch,
            "qos": {
                "queue_mutations": sum(
                    len(ms) for _v, ms in self.entries
                ),
                "queue_bytes": self._queue_bytes,
                "smoothed_queue_bytes": (
                    self.smoothed_queue_bytes.smooth_total()
                ),
                "input_bytes_per_s": (
                    self.smoothed_input_bytes.smooth_rate()
                ),
                "entries": len(self.entries),
                "stale_epoch_rejects": self.stale_epoch_rejects,
                "partitioned": self.partitioned,
                "chain_waiters": self._chain_waiters,
            },
        }

    async def pop(self, req: "TLogPop") -> "TLogPopReply":
        """Pop the log prefix at or below `version` (storage has it
        durably): retained entries, queue bytes, AND the disk queue
        shrink, so a restart's recovery scan replays only the tail
        between storage-durable and the head — the reference tlog's
        pop-on-storage-durable discipline. `self.version` (the
        recovery-version source) is unaffected."""
        _fence_epoch(req, self)
        import bisect

        cut = bisect.bisect_right(
            self.entries, req.version, key=lambda e: e[0]
        )
        if cut:
            dropped = self.entries[:cut]
            self.entries = self.entries[cut:]
            self._queue_bytes -= sum(
                8 + len(m.param1) + len(m.param2)
                for _v, ms in dropped for m in ms
            )
            self.smoothed_queue_bytes.set_total(self._queue_bytes)
        if self._dq is not None and self._seq_by_version:
            last_seq = None
            kept = []
            for v, s in self._seq_by_version:
                if v <= req.version:
                    last_seq = s
                else:
                    kept.append((v, s))
            if last_seq is not None:
                if not kept:
                    # the pop empties the retained queue: persist the
                    # durable HEAD version FIRST — a restart of a
                    # fully-popped log must come back at the head,
                    # never -1 (the recovery version derives from it
                    # and must not regress below committed data).
                    # Marker-then-pop: a crash between the two leaves
                    # both sources present (max() is unaffected). With
                    # a surviving tail the scan restores the head on
                    # its own, so the fsync is skipped — no per-drain
                    # disk sync while the applier lags the head.
                    await asyncio.get_event_loop().run_in_executor(
                        None, self._write_popped_marker, self.version
                    )
                self._dq.pop(last_seq + 1)
                self._dq.commit()
                self._seq_by_version = kept
        return TLogPopReply(durable_version=self.version)

    def _marker_path(self) -> str:
        return os.path.join(self._data_dir, "POPPED_VERSION")

    def _read_popped_marker(self) -> int:
        try:
            with open(self._marker_path()) as f:
                return int(f.read().strip())
        except (FileNotFoundError, ValueError):
            return -1

    def _write_popped_marker(self, version: int) -> None:
        tmp = self._marker_path() + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{version}\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._marker_path())

    def close_disk(self) -> None:
        """Release the disk queue (a replaced role must not hold the
        files a re-initialized successor on the same worker reopens)."""
        if self._dq is not None:
            try:
                self._dq.close()
            except Exception:
                pass
            self._dq = None

    async def peek(self, req: TLogPeek) -> TLogPeekReply:
        i = self._first_after(req.after_version)
        if i < len(self.entries):
            v, muts = self.entries[i]
            return TLogPeekReply(version=v, mutations=muts)
        return TLogPeekReply(version=-1, mutations=[])

    async def peek_batch(self, req: "TLogPeekBatchReq") -> "TLogPeekBatchReply":
        """Batched tail read for storage catch-up: all entries above
        after_version, bounded by max_entries (linear restart, not the
        one-RPC-per-version quadratic walk)."""
        i = self._first_after(req.after_version)
        chunk = self.entries[i : i + req.max_entries]
        return TLogPeekBatchReply(
            versions=[v for v, _m in chunk],
            groups=[m for _v, m in chunk],
        )

    def _first_after(self, after_version: int) -> int:
        """Binary search: entries are version-ascending by construction."""
        import bisect

        return bisect.bisect_right(
            self.entries, after_version, key=lambda e: e[0]
        )

    async def get_version(self, req: RoleVersionReq) -> RoleVersionReply:
        return RoleVersionReply(version=self.version)


class SequencerRole:
    """Wire-served sequencer (the reference's master/MasterInterface):
    version-batch allotment behind an RPC so N commit proxies share one
    global version chain. Wraps the sim Sequencer state machine
    (cluster/sequencer.py — in-order per-proxy grants, duplicate-replay
    cache, live-committed notification) over a wall-clock scheduler.

    On top of the shared machine it tracks the PER-TAG previous
    version: each grant declares which tag-partitioned tlogs the batch
    will push to, and the reply carries that tag's previous granted
    version so every tlog sees a gapless chain even though it only
    receives the versions owning its tag."""

    def __init__(self, *, epoch: int = 0, recovery_version: int = 0,
                 n_tags: int = 1):
        import time as _time

        from foundationdb_tpu.cluster.sequencer import Sequencer
        from foundationdb_tpu.utils.metrics import TimerSmoother

        class _WallClock:
            def now(self):
                return _time.monotonic()

            async def delay(self, seconds):
                await asyncio.sleep(seconds)

        self.epoch = epoch
        self.stale_epoch_rejects = 0
        self.recovery_version = recovery_version
        self.n_tags = n_tags
        self._seq = Sequencer(_WallClock(), recovery_version=recovery_version)
        #: tag -> last granted version touching it (missing = the
        #: recovery version: the two-phase lock advanced every tlog's
        #: floor there, so the first push per tag chains off it)
        self._tag_prev: dict[int, int] = {}
        #: version -> tag_prevs granted with it (duplicate grants must
        #: replay the SAME per-tag prevs); bounded FIFO
        self._grant_cache: dict[int, list[int]] = {}
        self.grants = 0
        self.smoothed_grants = TimerSmoother(1.0)

    async def get_commit_version(
        self, req: "GetCommitVersionRequest"
    ) -> "GetCommitVersionReply":
        _fence_epoch(req, self)
        rep = await self._seq.get_commit_version(
            req.proxy_id, req.request_num, req.most_recent_processed
        )
        if rep is None:
            raise transport.RemoteError(
                "sequencer: request_num below most_recent_processed"
            )
        tags = list(req.tags or ())
        if rep.version in self._grant_cache:
            tag_prevs = self._grant_cache[rep.version]
        else:
            # a fresh grant: snapshot each declared tag's prev and
            # advance it to this version — synchronously (no await
            # between the sequencer's grant and this bookkeeping), so
            # concurrent grants see prevs in grant order
            tag_prevs = [
                self._tag_prev.get(t, self.recovery_version) for t in tags
            ]
            for t in tags:
                self._tag_prev[t] = rep.version
            self._grant_cache[rep.version] = tag_prevs
            while len(self._grant_cache) > 4096:
                self._grant_cache.pop(next(iter(self._grant_cache)))
            self.grants += 1
            self.smoothed_grants.add_delta(1)
        return GetCommitVersionReply(
            version=rep.version,
            prev_version=rep.prev_version,
            request_num=rep.request_num,
            tag_prevs=tag_prevs,
        )

    async def report_committed(
        self, req: "ReportRawCommittedVersionRequest"
    ) -> "ReportRawCommittedVersionReply":
        _fence_epoch(req, self)
        if req.version >= 0:
            self._seq.report_live_committed_version(req.version)
        return ReportRawCommittedVersionReply(
            live_version=self._seq.get_live_committed_version()
        )

    async def get_version(self, req: RoleVersionReq) -> RoleVersionReply:
        """The allocated head — recovery derives the new generation's
        recovery version from it so granted-but-never-pushed versions
        can never be re-granted (the sim recovery does the same)."""
        return RoleVersionReply(version=self._seq.version)

    def status(self) -> dict:
        return {
            "role": "sequencer",
            "version": self._seq.version,
            "epoch": self.epoch,
            "qos": {
                "grants": self.grants,
                "grants_per_s": self.smoothed_grants.smooth_rate(),
                "live_committed_version": (
                    self._seq.get_live_committed_version()
                ),
                "tags": self.n_tags,
                "proxies_seen": len(self._seq._proxies),
                "stale_epoch_rejects": self.stale_epoch_rejects,
            },
        }


class StorageRole:
    """Wire-served storage: versioned point store (SET mutations)."""

    MUT_SET = 0
    MUT_CLEAR_RANGE = 1

    #: checkpoint every N applied versions when persistent
    CHECKPOINT_INTERVAL = 8

    #: memtable budget before the LSM engine flushes (bytes)
    LSM_FLUSH_BYTES = 4 << 20

    def __init__(self, data_dir: str | None = None, engine: str = "memory",
                 window: int = 5_000_000, encryption=None):
        # Encryption-at-rest (crypto/at_rest.StorageEncryption): every
        # SET value is sealed ONCE, in the executor, before it reaches
        # the WAL, the store, or a checkpoint — so no crypto runs on
        # the event loop under the apply lock and nothing is encrypted
        # twice (code review r5). Keys stay plaintext (run/checkpoint
        # ordering); reads open values through the cipher cache
        # (mixed-mode: plaintext legacy records pass through).
        self._enc = encryption if data_dir else None
        if self._enc is not None:
            # prefetch both cipher identities so the seal path starts
            # warm; a REST KMS still pays one refresh trip per
            # ENCRYPT_KEY_REFRESH_INTERVAL, off the hot path
            encryption.prefetch()
        # key -> list[(version, value|None)] ascending  (memory engine)
        self.history: dict[bytes, list[tuple[int, Optional[bytes]]]] = {}
        # the empty store is readable at version 0 (a GRV before any commit
        # must not block behind the first apply)
        self.version = 0
        self._cond: asyncio.Condition | None = None
        self._data_dir = data_dir
        self._applies_since_ckpt = 0
        # Incremental durability (KeyValueStoreMemory's discipline,
        # fdbserver/KeyValueStoreMemory.actor.cpp): every apply streams
        # its mutations to a local DiskQueue and fsyncs BEFORE acking
        # durable_version (the tlog pops on that ack — without the log,
        # acked-but-not-yet-checkpointed data died with the process).
        # Checkpoints become periodic compactions that pop the log
        # prefix; restart = load checkpoint + replay only the log tail.
        self._dq = None
        self._seq_by_version: list[tuple[int, int]] = []
        # Serializes write-ahead logging: the fsync runs in an executor
        # OUTSIDE the read condition lock (reads must not stall behind
        # the disk), so without this lock two concurrent apply() calls
        # could persist log records out of version order and replay
        # would skip the lower version (ADVICE r3).
        self._log_lock: asyncio.Lock | None = None
        self.replayed_on_restart = 0
        # Persistent engine selection (the reference's storage-engine
        # knob, fdbserver/worker.actor.cpp openKVStore): "memory" =
        # KeyValueStoreMemory-class (RAM dict + WAL + checkpoint blob);
        # "lsm" = the native versioned LSM (native/vlsm.cpp — data >
        # RAM, restart ∝ WAL tail, at-version reads off disk runs).
        self.engine = engine
        self._lsm = None
        self.window = window
        # -- saturation sensors: smoothed apply bandwidth + batch-size
        # distribution (the version LAG vs the committed head is joined
        # at assembly time — status.py assemble_status — because only
        # the parent pipeline knows the head, Status.actor.cpp's shape)
        from foundationdb_tpu.utils.metrics import (
            LatencySample,
            TimerSmoother,
        )

        self.smoothed_input_bytes = TimerSmoother(1.0)
        self.apply_batch_size = LatencySample("applyBatchMutations")
        self._applies = 0
        # -- skew sensors (ISSUE 20): byteSample + busiest-tag pair.
        # Wall-entropy seed and wall-clock smoothers (wire role — no
        # virtual clock exists here, and nothing traced depends on it)
        from foundationdb_tpu.cluster import sampling as _sampling

        self.byte_sample = _sampling.ByteSample()
        self.read_tags = _sampling.TagCounter()
        self.write_tags = _sampling.TagCounter()
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            _check_encryption_marker(data_dir, encryption)
            from foundationdb_tpu import native

            self._dq = native.DiskQueue(os.path.join(data_dir, "mutlog"))
            if engine == "lsm":
                self._lsm = native.VersionedLsm(
                    os.path.join(data_dir, "kvstore"), window=window
                )
                self.version = self._lsm.durable_version
            else:
                self._load_checkpoint()
            self._replay_local_log()
        elif engine == "lsm":
            raise ValueError("engine='lsm' requires a data_dir")

    # -- durable-version checkpointing (storageserver durableVersion
    # discipline: persist at a version, replay the tlog tail on restart) --

    async def aclose_disk(self) -> None:
        """close_disk serialized with the WAL lock: an in-flight
        apply's _log_apply_durably runs on an EXECUTOR thread inside
        the native queue — freeing the handles under it would be a
        use-after-free. (A live-but-slow store can be replaced on its
        own worker: heartbeat misses under fsync load + singleton
        re-recruit.)"""
        async with self._log_lock_lazy():
            self.close_disk()

    def close_disk(self) -> None:
        """Release the WAL + LSM handles (a replaced role must not hold
        the files a re-initialized successor on the same worker
        reopens)."""
        if self._dq is not None:
            try:
                self._dq.close()
            except Exception:
                pass
            self._dq = None
        if self._lsm is not None:
            try:
                self._lsm.close()
            except Exception:
                pass
            self._lsm = None

    def _ckpt_path(self) -> str:
        return os.path.join(self._data_dir, "storage.ckpt")

    def _serialize_checkpoint(self) -> bytes:
        out = codec.WriteBuffer()
        codec.w_i64(out, self.version)
        kvs = []
        for k, hist in self.history.items():
            value = None
            for v, val in hist:
                if v <= self.version:
                    value = val
            if value is not None:
                kvs.append((k, value))
        _w_kvlist(out, kvs)
        return out.getvalue()

    def _write_checkpoint_blob(self, blob: bytes) -> None:
        # values inside the blob are already sealed (seal-once at apply)
        tmp = self._ckpt_path() + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._ckpt_path())  # atomic install

    def _checkpoint(self) -> None:
        self._write_checkpoint_blob(self._serialize_checkpoint())

    def _load_checkpoint(self) -> None:
        try:
            with open(self._ckpt_path(), "rb") as f:
                blob = memoryview(f.read())
        except FileNotFoundError:
            return
        version, off = codec.r_i64(blob, 0)
        kvs, _off = _r_kvlist(blob, off)
        self.version = version
        self.history = {k: [(version, v)] for k, v in kvs}

    # -- the mutation log (incremental durability) -----------------------
    # Records are codec-encoded StorageApply messages — the same
    # registered wire codec the RPC layer uses (TLogRole logs its
    # DiskQueue records the same way; no second serialization path).

    def _seal_values(self, req):
        """Seal every SET value of a StorageApply (the ONE place values
        are encrypted — WAL, store and checkpoints all carry the sealed
        bytes from here on). Runs in the executor."""
        return StorageApply(
            version=req.version,
            mutations=[
                codec.Mutation(m.op, m.param1, self._enc.seal(m.param2))
                if m.op == self.MUT_SET
                else m
                for m in req.mutations
            ],
        )

    def _replay_local_log(self) -> None:
        """Restart: replay the log tail above the checkpoint — cost
        proportional to the tail, not the dataset. (Values inside the
        records are sealed; they are stored as-is and opened on read.)"""
        for seq, blob in self._dq.recovered:
            if self._enc is None and _looks_sealed(blob):
                # defense in depth behind the fsynced marker: codec
                # records never start with the cipher magic, so a
                # whole-sealed blob here means a lost marker (note the
                # seal-once format stores sealed VALUES inside plain
                # codec records — for those only the marker protects)
                raise RuntimeError(
                    "sealed storage WAL record but encryption is disabled"
                )
            rec = codec.decode(blob)
            if rec.version > self.version:
                self._apply_mutations(rec.version, rec.mutations)
                self.version = rec.version
                self.replayed_on_restart += 1
            self._seq_by_version.append((rec.version, seq))

    def _log_apply_durably(self, reqs: list) -> None:
        """Write-ahead + fsync a group of versions' mutations (one
        fsync per group — catch-up batches amortize it). Runs in the
        executor, BEFORE the in-memory apply and the ack."""
        seqs = [
            (req.version, self._dq.push(codec.encode(req)))
            for req in reqs
        ]
        if self._dq.commit() is None:
            # fsync/pwrite failed: the data is NOT durable — refuse the
            # ack rather than lie (the tLogCommit discipline; the tlog
            # pops on our durable_version ack)
            raise transport.RemoteError("storage mutation-log commit failed")
        self._seq_by_version.extend(seqs)

    def _compact_log(self, ckpt_version: int) -> None:
        """After a checkpoint at ckpt_version is durably installed, the
        log prefix at or below it is dead: pop it (the restart replay
        shrinks back to the new tail)."""
        last_seq = None
        kept = []
        for v, s in self._seq_by_version:
            if v <= ckpt_version:
                last_seq = s
            else:
                kept.append((v, s))
        if last_seq is not None:
            self._dq.pop(last_seq + 1)
            self._dq.commit()
            self._seq_by_version = kept

    def _apply_mutations(self, version: int, mutations) -> None:
        from foundationdb_tpu.cluster.sampling import tag_of_key

        self._applies += 1
        self.apply_batch_size.sample(len(mutations))
        self.smoothed_input_bytes.add_delta(sum(
            8 + len(m.param1) + len(m.param2) for m in mutations
        ))
        # skew sensors see every engine's apply stream (the byteSample
        # estimates the LIVE keyspace; clears drop their span)
        for m in mutations:
            nb = 8 + len(m.param1) + len(m.param2)
            self.write_tags.note(tag_of_key(m.param1), nb)
            if m.op == self.MUT_SET:
                self.byte_sample.note_write(m.param1, m.param2)
            elif m.op == self.MUT_CLEAR_RANGE:
                self.byte_sample.erase_range(m.param1, m.param2)
        if self._lsm is not None:
            # values arrive pre-sealed (seal-once in apply/catch-up);
            # keys stay plaintext for run ordering (crypto/at_rest.py)
            self._lsm.apply(
                version, [(m.op, m.param1, m.param2) for m in mutations]
            )
            return
        for m in mutations:
            if m.op == self.MUT_SET:
                self.history.setdefault(m.param1, []).append(
                    (version, m.param2)
                )
            elif m.op == self.MUT_CLEAR_RANGE:
                for k in list(self.history):
                    if m.param1 <= k < m.param2:
                        self.history[k].append((version, None))

    async def catch_up_from_tlog(self, tlog_address: str) -> None:
        """Replay the tlog tail above our durable version (the restart
        path of storageserver.actor.cpp:9117's pull loop) in batched
        chunks — linear in tail length."""
        conn = transport.RpcConnection(tlog_address, tls=_tls_from_env())
        await conn.connect()
        try:
            while True:
                try:
                    rep = await conn.call(
                        TOKEN_TLOG_PEEK_BATCH,
                        TLogPeekBatchReq(
                            after_version=self.version, max_entries=256
                        ),
                        timeout=30.0,
                    )
                except (transport.TransportError, ConnectionError,
                        asyncio.TimeoutError) as e:
                    # classify for the recovery caller: catch-up is
                    # retryable against a fresh tlog address
                    raise transport.RemoteError(
                        f"tlog catch-up from {tlog_address} failed: {e!r}"
                    ) from e
                if not rep.versions:
                    break
                reqs = [
                    StorageApply(version=v, mutations=muts)
                    for v, muts in zip(rep.versions, rep.groups)
                    if v > self.version
                ]
                if reqs and self._enc is not None:
                    loop = asyncio.get_event_loop()
                    reqs = await loop.run_in_executor(
                        None, lambda rs: [self._seal_values(r) for r in rs],
                        reqs,
                    )
                if reqs and self._dq is not None:
                    # group commit: ONE fsync per peek chunk, not per
                    # version — restart catch-up stays O(chunks) fsyncs
                    await self._log_durably(reqs)
                for req in reqs:
                    await self._apply_logged(req)
        finally:
            await conn.close()

    def _log_lock_lazy(self) -> asyncio.Lock:
        if self._log_lock is None:
            self._log_lock = asyncio.Lock()
        return self._log_lock

    def _cond_lazy(self) -> asyncio.Condition:
        if self._cond is None:
            self._cond = asyncio.Condition()
        return self._cond

    async def apply(self, req: StorageApply) -> StorageApplyReply:
        # WRITE-AHEAD: fsync the mutations to the local log BEFORE the
        # in-memory apply and the ack — durable_version must imply
        # durability (the tlog pops on it). The fsync runs OUTSIDE the
        # condition lock so reads at already-applied versions never
        # stall behind the disk; a stale/duplicate record logged by a
        # lost race is skipped idempotently on replay.
        if req.version > self.version:
            if self._enc is not None:
                # seal-once, off the event loop (code review r5)
                req = await asyncio.get_event_loop().run_in_executor(
                    None, self._seal_values, req
                )
            if self._dq is not None:
                await self._log_durably([req])
        return await self._apply_logged(req)

    async def apply_batch(self, req: "StorageApplyBatch") -> StorageApplyReply:
        """Version-ordered group apply (the pipeline applier's drain):
        one sealing pass, ONE write-ahead group fsync (when persistent)
        and one ordered in-memory apply sweep for the whole chunk —
        the storage-side twin of the tlog's group commit.

        With `prev_versions` (N commit proxies), each contiguous run of
        the chunk first waits for its predecessor version to land: the
        global sequencer chain is reconstructed server-side, so
        interleaved per-proxy appliers can never apply out of order
        (the WAL stays version-ascending, which replay depends on)."""
        prevs = list(req.prev_versions or ())
        if prevs and len(prevs) == len(req.versions):
            return await self._apply_batch_chained(req, prevs)
        reqs = [
            StorageApply(version=v, mutations=m)
            for v, m in zip(req.versions, req.groups)
            if v > self.version
        ]
        return await self._apply_run(reqs)

    async def _apply_run(self, reqs: list) -> StorageApplyReply:
        if reqs and self._enc is not None:
            loop = asyncio.get_event_loop()
            reqs = await loop.run_in_executor(
                None, lambda rs: [self._seal_values(r) for r in rs], reqs
            )
        if reqs and self._dq is not None:
            await self._log_durably(reqs)
        rep = None
        for r in reqs:
            rep = await self._apply_logged(r)
        return rep if rep is not None else StorageApplyReply(
            durable_version=self.version,
            durable=1 if self._dq is not None else 0,
        )

    async def _apply_batch_chained(self, req, prevs) -> StorageApplyReply:
        rep = None
        cond = self._cond_lazy()
        i, n = 0, len(req.versions)
        while i < n:
            # a contiguous run: each item's prev is the previous item
            j = i
            while j + 1 < n and prevs[j + 1] == req.versions[j]:
                j += 1
            run_prev = prevs[i]
            try:
                async with cond:
                    await asyncio.wait_for(
                        cond.wait_for(lambda: self.version >= run_prev),
                        timeout=10.0,
                    )
            except asyncio.TimeoutError:
                # the predecessor's proxy died mid-window: surface a
                # retryable stall — recovery's catch-up advances the
                # floor past the gap and re-drives us from the tlogs
                raise transport.RemoteError(
                    f"storage chain stall: prev_version {run_prev} "
                    "never applied (retryable)"
                )
            rep = await self._apply_run([
                StorageApply(version=v, mutations=m)
                for v, m in zip(req.versions[i:j + 1], req.groups[i:j + 1])
                if v > self.version
            ]) or rep
            i = j + 1
        return rep if rep is not None else StorageApplyReply(
            durable_version=self.version,
            durable=1 if self._dq is not None else 0,
        )

    async def _log_durably(self, reqs: list) -> None:
        """Run the write-ahead fsync in the executor under a per-store
        lock: log records must hit the disk in version order (replay
        skips any version at or below the restart cursor, so an
        out-of-order pair would silently drop the lower one)."""
        async with self._log_lock_lazy():
            await asyncio.get_event_loop().run_in_executor(
                None, self._log_apply_durably, reqs
            )

    async def _apply_logged(self, req: StorageApply) -> StorageApplyReply:
        cond = self._cond_lazy()
        async with cond:
            if req.version > self.version:
                self._apply_mutations(req.version, req.mutations)
                self.version = req.version
                if self._data_dir and self._lsm is not None:
                    self._applies_since_ckpt += 1
                    if (
                        self._applies_since_ckpt >= self.CHECKPOINT_INTERVAL
                        or self._lsm.mem_bytes > self.LSM_FLUSH_BYTES
                    ):
                        self._applies_since_ckpt = 0
                        # LSM checkpoint: flush the memtable to a durable
                        # run (fsync off the loop), advance the MVCC GC
                        # floor, pop the WAL prefix the run now covers
                        lsm = self._lsm

                        def lsm_flush():
                            durable = lsm.flush()
                            lsm.set_floor(durable - self.window)
                            self._compact_log(durable)

                        # _compact_log pops the native WAL DiskQueue and
                        # swaps _seq_by_version; a concurrent apply()'s
                        # _log_apply_durably pushes the SAME queue from
                        # another executor thread and the native queue
                        # does no internal locking — serialize through
                        # _log_lock (ADVICE r4)
                        async with self._log_lock_lazy():
                            await asyncio.get_event_loop().run_in_executor(
                                None, lsm_flush
                            )
                elif self._data_dir:
                    self._applies_since_ckpt += 1
                    if self._applies_since_ckpt >= self.CHECKPOINT_INTERVAL:
                        self._applies_since_ckpt = 0
                        # checkpoint = compaction: serialize under the
                        # lock (consistent view), install + pop the log
                        # prefix off the event loop
                        blob = self._serialize_checkpoint()
                        ckpt_version = self.version

                        def install():
                            self._write_checkpoint_blob(blob)
                            self._compact_log(ckpt_version)

                        # same WAL push/pop race as the LSM branch above:
                        # _compact_log must not run concurrently with
                        # _log_apply_durably on the unlocked native queue
                        async with self._log_lock_lazy():
                            await asyncio.get_event_loop().run_in_executor(
                                None, install
                            )
                cond.notify_all()
            return StorageApplyReply(
                durable_version=self.version,
                durable=1 if self._dq is not None else 0,
            )

    async def get_version(self, req: RoleVersionReq) -> RoleVersionReply:
        return RoleVersionReply(version=self.version)

    async def catch_up(self, req: "StorageCatchUp") -> "StorageCatchUpReply":
        """Recovery catch-up (controller-driven): replay the locked
        tlogs' tails above our durable version NOW, before the new
        generation's first apply can advance our version past them. The
        pull is idempotent per version, so a straggler apply from the
        dying generation racing this is harmless (chained applies
        self-order through the prev wait)."""
        addrs = [req.tlog_address] + list(req.tlog_addresses or ())
        if len(addrs) > 1:
            await self.catch_up_from_tlogs(addrs)
        else:
            await self.catch_up_from_tlog(req.tlog_address)
        if req.recovery_version >= 0:
            await self.advance_floor(req.recovery_version)
        return StorageCatchUpReply(version=self.version)

    async def advance_floor(self, recovery_version: int) -> None:
        """Advance the version floor to the new generation's recovery
        version and wake read/chain waiters: versions between the old
        generation's tail and the recovery version can never carry data
        (the sequencer grants above the gap), and the first chained
        apply of the new generation waits on prev == recovery_version."""
        cond = self._cond_lazy()
        async with cond:
            if recovery_version > self.version:
                self.version = recovery_version
                cond.notify_all()

    async def catch_up_from_tlogs(self, addresses: list) -> None:
        """Tag-partitioned catch-up: each tlog holds only the versions
        owning its tag, so the union of the tails IS the commit history
        above our durable version — k-way merge the peek streams by
        version and apply in merged order (the WAL must stay
        version-ascending)."""
        conns = []
        try:
            for a in addresses:
                c = transport.RpcConnection(a, tls=_tls_from_env())
                await c.connect()
                conns.append((a, c))
            n = len(conns)
            cursors = [self.version] * n
            buffers: list[list] = [[] for _ in conns]
            done = [False] * n
            while True:
                for i, (a, c) in enumerate(conns):
                    if done[i] or buffers[i]:
                        continue
                    try:
                        rep = await c.call(
                            TOKEN_TLOG_PEEK_BATCH,
                            TLogPeekBatchReq(
                                after_version=cursors[i], max_entries=256
                            ),
                            timeout=30.0,
                        )
                    except (transport.TransportError, ConnectionError,
                            asyncio.TimeoutError) as e:
                        raise transport.RemoteError(
                            f"tlog catch-up from {a} failed: {e!r}"
                        ) from e
                    if not rep.versions:
                        done[i] = True
                        continue
                    cursors[i] = rep.versions[-1]
                    buffers[i] = list(zip(rep.versions, rep.groups))
                if not any(buffers):
                    break
                # Merge by version until a stream needs a refill. A
                # version spanning several tags appears in EVERY owning
                # tlog (with that tag's mutations) — same-version heads
                # are combined into one apply, never dropped.
                chunk = []
                while len(chunk) < 256:
                    if any(not done[i] and not buffers[i] for i in range(n)):
                        break
                    live = [i for i in range(n) if buffers[i]]
                    if not live:
                        break
                    vmin = min(buffers[i][0][0] for i in live)
                    muts = []
                    for i in live:
                        if buffers[i][0][0] == vmin:
                            muts.extend(buffers[i].pop(0)[1])
                    chunk.append((vmin, muts))
                await self._apply_run([
                    StorageApply(version=v, mutations=muts)
                    for v, muts in chunk
                    if v > self.version
                ])
        finally:
            for _a, c in conns:
                await c.close()

    def status(self) -> dict:
        """StatusRequest payload: apply bandwidth, batch-size
        distribution, and the store size — the wire analog of the sim
        storage's `saturation()` block (version lag vs the committed
        head is joined at assembly time)."""
        return {
            "role": "storage",
            "version": self.version,
            "engine": self.engine,
            "qos": {
                "applies": self._applies,
                "apply_batch_mutations": self.apply_batch_size.as_dict(),
                "input_bytes_per_s": (
                    self.smoothed_input_bytes.smooth_rate()
                ),
                "keys": len(self.history),
                # skew sensors (ISSUE 20) — same schema as the sim
                # storage's saturation() block
                "sampled_bytes": self.byte_sample.total_bytes(),
                "sample_keys": self.byte_sample.count,
                "hot_ranges": self.byte_sample.hot_ranges(),
                "busiest_read_tag": self.read_tags.busiest(),
                "busiest_write_tag": self.write_tags.busiest(),
            },
        }

    async def get(self, req: StorageGet) -> StorageGetReply:
        from foundationdb_tpu.cluster.sampling import tag_of_key

        self.read_tags.note(tag_of_key(req.key), len(req.key))
        cond = self._cond_lazy()
        async with cond:
            await cond.wait_for(lambda: self.version >= req.version)
        if self._lsm is not None:
            # disk preads off the event loop: a cold read must not stall
            # unrelated requests
            # read AND open (decrypt + possible by-id KMS fetch) in the
            # executor: neither disk preads nor a KMS round trip may
            # stall the event loop (code review r5)
            # plain pass-through when encryption is off: the marker
            # check at startup guarantees the store is unencrypted, and
            # user values may legitimately start with the header magic
            def read_open():
                v = self._lsm.get(req.key, req.version)
                if v is None or self._enc is None:
                    return v
                return self._enc.open(v)

            value = await asyncio.get_event_loop().run_in_executor(
                None, read_open
            )
            return StorageGetReply(value=value)
        hist = self.history.get(req.key, [])
        value = None
        for v, val in hist:
            if v <= req.version:
                value = val
            else:
                break
        if value is not None and self._enc is not None:
            # decrypt (and a possible cold by-id KMS fetch) off the
            # loop — same discipline as the LSM read closures
            value = await asyncio.get_event_loop().run_in_executor(
                None, self._enc.open, value
            )
        return StorageGetReply(value=value)

    def _get_at(self, key: bytes, version: int):
        """Newest value <= version from the in-memory history (still
        sealed when encryption is on)."""
        value = None
        for v, val in self.history.get(key, []):
            if v <= version:
                value = val
            else:
                break
        return value

    async def get_batch(self, req: "StorageGetBatch") -> "StorageGetBatchReply":
        """Coalesced reads: ONE version wait (max of the batch), then
        every key served at ITS OWN requested version — exact MVCC
        semantics, one wire roundtrip for a whole event-loop turn's
        worth of proxy-process reads."""
        from foundationdb_tpu.cluster.sampling import tag_of_key

        for k in req.keys:
            self.read_tags.note(tag_of_key(k), len(k))
        vmax = max(req.versions) if req.versions else 0
        cond = self._cond_lazy()
        async with cond:
            await cond.wait_for(lambda: self.version >= vmax)
        if self._lsm is not None:
            # preads + decrypt off the loop, one executor hop per batch
            def read_open_all():
                out = []
                for k, rv in zip(req.keys, req.versions):
                    v = self._lsm.get(k, rv)
                    if v is not None and self._enc is not None:
                        v = self._enc.open(v)
                    out.append(v)
                return out

            values = await asyncio.get_event_loop().run_in_executor(
                None, read_open_all
            )
            return StorageGetBatchReply(values=values)
        values = [
            self._get_at(k, rv) for k, rv in zip(req.keys, req.versions)
        ]
        if self._enc is not None:
            values = await asyncio.get_event_loop().run_in_executor(
                None,
                lambda vs: [
                    self._enc.open(v) if v is not None else None for v in vs
                ],
                values,
            )
        return StorageGetBatchReply(values=values)

    async def snapshot(self, req: StorageSnapshotReq) -> StorageSnapshotReply:
        cond = self._cond_lazy()
        async with cond:
            await cond.wait_for(lambda: self.version >= req.version)
        if self._lsm is not None:
            # range + per-value open() together in the executor — a
            # full-dataset decrypt inline on the loop would stall every
            # unrelated request proportionally to dataset size
            def range_open():
                rows = self._lsm.range(b"", b"", req.version)
                if self._enc is None:
                    return rows
                return [(k, self._enc.open(v)) for k, v in rows]

            kvs = await asyncio.get_event_loop().run_in_executor(
                None, range_open
            )
            return StorageSnapshotReply(version=self.version, kvs=kvs)
        kvs = []
        for k, hist in sorted(self.history.items()):
            value = None
            for v, val in hist:
                if v <= req.version:
                    value = val  # leaves the newest value <= version
            if value is not None:
                kvs.append((k, value))
        if self._enc is not None:
            # full-dataset decrypt belongs in the executor (the sealed
            # kvs list is already materialized, so the loop may mutate
            # history freely meanwhile)
            kvs = await asyncio.get_event_loop().run_in_executor(
                None,
                lambda rows: [(k, self._enc.open(v)) for k, v in rows],
                kvs,
            )
        return StorageSnapshotReply(version=self.version, kvs=kvs)


class RatekeeperRole:
    """Wire-mode Ratekeeper: `fdbserver/Ratekeeper.actor.cpp` as an OS
    process. Polls every peer role's StatusRequest for its saturation
    sensors (the same qos blocks fdbtop renders), drives the SAME
    `AdmissionController` law the sim Ratekeeper runs, and serves the
    live budget over GetRateInfo. Robustness contract: a peer that
    stops answering simply contributes no sensors this interval; when
    NO peer answers, the law's fail-safe decay engages (budget decays
    toward the conservative floor) — and a consumer that cannot reach
    THIS process applies its own decay (ProxyPipeline._rate_fetcher),
    so a dead ratekeeper never freezes the cluster at full speed."""

    def __init__(self, peers: list[str], *, interval: float = 0.25,
                 controller: str | None = None):
        import time as _time

        from foundationdb_tpu.cluster.ratekeeper import AdmissionController

        self.peers = [p for p in peers if p]
        self.interval = interval
        self.law = AdmissionController(clock=_time.monotonic)
        self._conns: dict[str, transport.RpcConnection] = {}
        self._task: asyncio.Task | None = None
        self.polls = 0
        self.poll_failures = 0
        # -- live peer discovery (the frozen-peer-list bugfix): with a
        # cluster controller configured, the peer set RE-RESOLVES from
        # the controller's live topology every control cycle, so a
        # re-recruited resolver's occupancy feed rejoins the admission
        # law the cycle after recovery instead of never. The static
        # `peers` list remains the controller-less fallback (and the
        # bootstrap set while the controller is still recruiting).
        self._controller_addr = controller
        self._controller_conns: dict = {}  # _cached_call cache
        self.peer_refreshes = 0
        self.topology_epoch = 0
        # -- push-based rate updates (ISSUE 15): when a control cycle
        # moves the budget past the hysteresis threshold (or flips the
        # binding limiter / staleness), the fresh GetRateInfo payload
        # is PUSHED to every proxy in the topology instead of waiting
        # out the proxies' poll cadence. Threshold semantics mirror the
        # law's own hysteresis discipline: small drift never floods the
        # wire, overload onset lands in one control cycle.
        self.push_threshold = 0.15
        self.rate_pushes = 0
        self.rate_push_failures = 0
        self._proxy_addrs: list[str] = []
        self._last_pushed: dict | None = None
        #: last cycle's observed GRV admission rate (the law's
        #: actualTps input) — surfaced in status so the wire feedback
        #: path is testable end to end
        self.observed_grv_per_s = 0.0

    async def start(self) -> None:
        self._task = asyncio.ensure_future(self._poll_loop())

    async def stop(self) -> None:
        """Cancel the poll loop and close every cached peer/controller
        connection — a worker re-recruiting over this role must not
        leak one socket per polled peer per recovery."""
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        await _close_all(self._conns)
        await _close_all(self._controller_conns)

    async def _poll_one(self, path: str) -> dict:
        import json as _json

        conn = self._conns.get(path)
        if conn is None:
            conn = transport.RpcConnection(path, tls=_tls_from_env())
            await conn.connect(retries=1)
            self._conns[path] = conn
        # classification boundary is _poll_loop's gather with
        # return_exceptions=True: a failed poll counts poll_failures
        # and invalidates the cached connection there
        reply = await conn.call(  # flowcheck: ignore[wire.unclassified-error]
            TOKEN_STATUS, StatusRequest(pad=0), timeout=2.0
        )
        return _json.loads(reply.payload)

    async def _refresh_peers(self) -> None:
        """Re-resolve the peer list from the controller topology (one
        TopologyRequest per control cycle). Failures keep the last
        known peer set — a dead controller degrades to static peers,
        and the law's own staleness decay covers dead sensors."""
        import json as _json

        if self._controller_addr is None:
            return
        try:
            reply = await _cached_call(
                self._controller_conns, self._controller_addr,
                TOKEN_TOPOLOGY, TopologyRequest(pad=0),
                timeout=2.0, retries=1,
            )
            topo = _json.loads(reply.payload)
        except asyncio.CancelledError:
            raise
        except Exception:
            return
        peers = sorted(
            {
                entry["address"]
                for entry in topo.get("roles", {}).values()
                if entry.get("kind") != "ratekeeper"
            }
        )
        self._proxy_addrs = sorted(
            {
                entry["address"]
                for entry in topo.get("roles", {}).values()
                if entry.get("kind") == "proxy"
            }
        )
        if peers and peers != self.peers:
            # drop cached connections to peers that left the topology
            for gone in set(self._conns) - set(peers):
                conn = self._conns.pop(gone)
                try:
                    await conn.close()
                except Exception:
                    pass
            self.peers = peers
            self.peer_refreshes += 1
        self.topology_epoch = int(topo.get("epoch", 0))

    async def _poll_loop(self) -> None:
        from foundationdb_tpu.cluster.status import _QOS_SLOT

        while True:
            await self._refresh_peers()
            slots: dict = {
                "tlogs": {}, "storages": {}, "resolvers": {},
                "proxies": {},
            }
            answered = 0
            current_tps = 0.0
            # polls are independent I/O and go out CONCURRENTLY: one
            # hung peer (2s call timeout) bounds the cycle at the
            # slowest single peer, not the sum — a serial loop would
            # stretch the control cadence ~Nx while the served budget
            # sat frozen at its last (possibly full-speed) value
            results = await asyncio.gather(
                *(self._poll_one(p) for p in self.peers),
                return_exceptions=True,
            )
            for path, block in zip(self.peers, results):
                if isinstance(block, BaseException):
                    self.poll_failures += 1
                    conn = self._conns.pop(path, None)
                    if conn is not None:
                        try:
                            await conn.close()
                        except Exception:
                            pass
                    continue
                name = os.path.basename(path)
                if name.endswith(".sock"):
                    name = name[: -len(".sock")]
                answered += 1
                slot = _QOS_SLOT.get(block.get("role", ""))
                if slot in slots:
                    slots[slot][name] = block.get("qos", {})
                # the parent pipeline's status socket embeds its GRV
                # block (a process block: role + qos): its served-GRV
                # rate is the law's actualTps
                grv = block.get("grv_proxy")
                if grv:
                    current_tps = max(
                        current_tps,
                        float(grv.get("qos", {}).get("grv_per_s", 0.0)),
                    )
            self.polls += 1
            self.observed_grv_per_s = current_tps
            if answered == 0:
                # total sensor dropout: fail safe, never full speed
                self.law.decay()
            else:
                self.law.update(slots, current_tps=current_tps)
            await self._maybe_push_rate()
            await asyncio.sleep(self.interval)

    def _push_due(self) -> bool:
        """Hysteresis: push only when the budget moved by more than
        push_threshold relative to the last delivered value, or the
        binding limiter / staleness flipped — overload ONSET is exactly
        a limiter flip plus a large budget drop, so it always pushes."""
        info = self.law.rate_info()
        last = self._last_pushed
        if last is None:
            return True
        budget = info["transactions_per_second_limit"]
        moved = abs(budget - last["budget"]) > (
            self.push_threshold * max(last["budget"], self.law.min_tps)
        )
        return (
            moved
            or info["budget_limited_by"]["name"] != last["limiter"]
            or bool(info["budget_stale"]) != last["stale"]
        )

    async def _maybe_push_rate(self) -> None:
        import json as _json

        if not self._proxy_addrs or not self._push_due():
            return
        info = self.law.rate_info()
        # fence stamp: the generation this pusher believes is live
        # (ProxyRole.rate_update rejects a mismatch — a superseded
        # ratekeeper cannot override the new generation's budget)
        info["epoch"] = self.topology_epoch
        payload = _json.dumps(info)
        # pushes go out CONCURRENTLY, like the sensor polls above: one
        # dead/hung proxy (2s call timeout) bounds this step at the
        # slowest single push, not the sum — a serial loop would stall
        # the control cadence on exactly the overload-onset cycles the
        # push exists to speed up
        results = await asyncio.gather(
            *(
                _cached_call(
                    self._conns, addr, TOKEN_RATE_UPDATE,
                    RateUpdate(payload=payload), timeout=2.0, retries=1,
                )
                for addr in self._proxy_addrs
            ),
            return_exceptions=True,
        )
        delivered = False
        for res in results:
            if isinstance(res, asyncio.CancelledError):
                raise res
            if isinstance(res, BaseException):
                # a proxy that can't be pushed still has its poll loop
                # (the backstop) — count and continue
                self.rate_push_failures += 1
            else:
                self.rate_pushes += 1
                delivered = True
        if delivered:
            self._last_pushed = {
                "budget": info["transactions_per_second_limit"],
                "limiter": info["budget_limited_by"]["name"],
                "stale": bool(info["budget_stale"]),
            }

    async def get_rate_info(
        self, _req: GetRateInfoRequest
    ) -> GetRateInfoReply:
        import json as _json

        return GetRateInfoReply(payload=_json.dumps(self.law.rate_info()))

    def status(self) -> dict:
        return {
            "role": "ratekeeper",
            "qos": {
                **self.law.rate_info(),
                "peer_polls": self.polls,
                "peer_poll_failures": self.poll_failures,
                "peers": len(self.peers),
                "peer_refreshes": self.peer_refreshes,
                "topology_epoch": self.topology_epoch,
                "observed_grv_per_s": self.observed_grv_per_s,
                "rate_pushes": self.rate_pushes,
                "rate_push_failures": self.rate_push_failures,
            },
        }


# ---------------------------------------------------------------------------
# Wire-cluster lifecycle: the worker / cluster-controller shape.
#
# The reference runs ONE binary (`fdbserver`) whose worker dispatch loop
# (fdbserver/worker.actor.cpp:2305-2811) can host any role in response
# to the cluster controller's Initialize*Request streams, and the
# ClusterController rebuilds the transaction system as a unit in a new
# generation on any failure (ClusterRecovery.actor.cpp). The classes
# below are that deployment shape for this framework: WorkerRole hosts
# any role behind a token dispatch, ClusterControllerRole recruits a
# declarative topology onto registered workers, heartbeats them, and
# runs the cluster/generation.py recovery walk on any transaction-path
# death — the same state machine the sim ClusterController
# (cluster/recovery.py) walks, so sim and wire cannot drift.


async def _cached_call(conns: dict, address, token: int, msg, *,
                       timeout: float = 30.0, retries: int = 2,
                       delay: float = 0.05, on_fail=None):
    """One RPC over a cached connection: lazily connect, call, and on
    ANY failure invalidate the cache entry (closing the connection)
    and run `on_fail(address)` before re-raising — the shared
    connect/call/invalidate contract of every control-plane caller
    (controller → worker, ratekeeper/client → controller)."""
    try:
        conn = conns.get(address)
        if conn is None:
            conn = transport.RpcConnection(address, tls=_tls_from_env())
            await conn.connect(retries=retries, delay=delay)
            conns[address] = conn
        return await conn.call(token, msg, timeout=timeout)
    except Exception:
        old = conns.pop(address, None)
        if old is not None:
            try:
                await old.close()
            except Exception:
                pass
        if on_fail is not None:
            on_fail(address)
        raise


async def _close_all(conns: dict) -> None:
    for conn in list(conns.values()):
        try:
            await conn.close()
        except Exception:
            pass
    conns.clear()


class ProxyRole:
    """The commit+GRV proxy as a recruitable, killable worker role.

    Wraps ProxyPipeline behind the client front-door RPCs
    (ClientGrv/ClientCommit/ClientRead), so clients reach the commit
    path over the wire like every other hop and a kill -9 of the proxy
    is survivable: the controller recruits a replacement in the next
    generation and the NEW proxy's first batch carries the conservative
    whole-keyspace blind write (cluster/generation.py), aborting every
    in-flight transaction whose snapshot predates recovery."""

    def __init__(self, spec: dict):
        self.spec = spec
        self.epoch = int(spec.get("epoch", 0))
        self.start_version = int(spec.get("start_version", 0))
        self.recovered = False
        self.pipeline: ProxyPipeline | None = None
        self._conns: list[transport.RpcConnection] = []
        #: rate pushes rejected by the epoch fence (a superseded
        #: ratekeeper still pushing) — surfaced in status
        self.stale_rate_pushes = 0

    async def start(self) -> None:
        topo = self.spec["topology"]
        # partial-recruit cleanup: a failed later connect must not leak
        # the connections already opened (a recruit raced a kill here
        # leaks one socket per retry otherwise)
        opened: list[transport.RpcConnection] = []
        try:
            resolvers = []
            for a in topo["resolvers"]:
                c = await connect(a)
                opened.append(c)
                resolvers.append(c)
            # tag-partitioned log system (ISSUE 19): "tlogs" lists every
            # tlog address; "tlog" stays as the first for back-compat
            tlogs = []
            for a in topo.get("tlogs") or [topo["tlog"]]:
                c = await connect(a)
                opened.append(c)
                tlogs.append(c)
            storage = await connect(topo["storage"])
            opened.append(storage)
            sequencer = None
            if topo.get("sequencer"):
                sequencer = await connect(topo["sequencer"])
                opened.append(sequencer)
            rk = None
            if topo.get("ratekeeper"):
                rk = await connect(topo["ratekeeper"])
                opened.append(rk)
        except BaseException:
            for c in opened:
                try:
                    await c.close()
                except Exception:
                    pass
            raise
        self._conns = opened
        # resolver partition boundaries (hex-encoded in the topology
        # JSON; the controller re-derives them on every resolver-count
        # change — the elastic-recruit path's multi-resolver split)
        boundaries = [
            bytes.fromhex(h)
            for h in topo.get("resolver_boundaries") or []
        ]
        tlog_boundaries = [
            bytes.fromhex(h)
            for h in topo.get("tlog_boundaries") or []
        ]
        self.pipeline = ProxyPipeline(
            resolvers,
            tlogs[0],
            storage,
            batch_interval=float(self.spec.get("batch_interval", 0.002)),
            max_batch=int(self.spec.get("max_batch", 512)),
            start_version=self.start_version,
            epoch=self.epoch,
            ratekeeper=rk,
            trace=bool(self.spec.get("trace", False)),
            resolver_boundaries=boundaries or None,
            sequencer=sequencer,
            proxy_id=str(self.spec.get("proxy_id", "proxy0")),
            tlogs=tlogs,
            tlog_boundaries=tlog_boundaries or None,
        )
        self.pipeline.start()
        if self.spec.get("recover", True):
            # the recovery transaction: the new generation's FIRST
            # batch is the conservative whole-keyspace blind write —
            # it pushes the log (and storage) past the recovery
            # version so reads don't stall, and registers the write
            # that aborts every pre-recovery snapshot
            from foundationdb_tpu.cluster.generation import (
                conservative_recovery_transaction,
            )

            await self.pipeline.commit(
                conservative_recovery_transaction(self.start_version)
            )
        self.recovered = True

    async def stop(self) -> None:
        if self.pipeline is not None:
            await self.pipeline.stop()
        for c in self._conns:
            try:
                await c.close()
            except Exception:
                pass
        self._conns = []

    async def client_grv(self, _req: "ClientGrvRequest") -> "ClientGrvReply":
        try:
            v = await self.pipeline.get_read_version()
        except GrvThrottledError:
            # marker-carrying RemoteError: ClusterClient re-raises the
            # typed retryable error client-side
            raise transport.RemoteError("grv_throttled")
        return ClientGrvReply(version=v)

    async def client_commit(
        self, req: "ClientCommitRequest"
    ) -> "ClientCommitReply":
        try:
            v = await self.pipeline.commit(req.txn)
        except NotCommittedError as e:
            raise transport.RemoteError(f"not_committed: {e}")
        return ClientCommitReply(version=v)

    async def client_read(self, req: "ClientReadRequest") -> "ClientReadReply":
        v = await self.pipeline.read(req.key, req.version)
        return ClientReadReply(value=v)

    async def rate_update(self, req: "RateUpdate") -> "RateUpdateReply":
        """Push-based budget delivery (ISSUE 15): the ratekeeper calls
        this the cycle the budget moves past its push hysteresis; the
        pipeline applies it exactly like a poll result. The poll loop
        keeps running as the backstop.

        EPOCH-FENCED like every other control frame: the pusher stamps
        its topology epoch, and a mismatch is rejected retryably — a
        superseded-but-alive ratekeeper (re-recruited away after a
        clog) must not keep overriding the live generation's budget
        (its pushes would even clear the fail-safe staleness a dead
        feed is supposed to engage). Epoch 0 == unfenced standalone
        deployment, matching the resolve/tlog fencing convention."""
        import json as _json

        info = _json.loads(req.payload)
        push_epoch = int(info.get("epoch", 0))
        if push_epoch != self.epoch:
            from foundationdb_tpu.cluster.generation import (
                stale_epoch_message,
            )

            self.stale_rate_pushes += 1
            raise transport.RemoteError(
                stale_epoch_message(push_epoch, self.epoch)
            )
        self.pipeline.apply_rate_info(info)
        self.pipeline.rate_pushes_applied += 1
        return RateUpdateReply(payload=_json.dumps({"ok": True}))

    def status(self) -> dict:
        block = _pipeline_status_blocks(self.pipeline)
        payload = block["proxy0"]
        payload["grv_proxy"] = block["grv_proxy0"]
        payload["epoch"] = self.epoch
        payload["recovered"] = self.recovered
        payload["stale_rate_pushes"] = self.stale_rate_pushes
        payload["proxy_id"] = str(self.spec.get("proxy_id", "proxy0"))
        return payload


class WorkerRole:
    """One process that can host any role behind a dispatch loop — the
    fdbserver worker. Every role token is registered up front against a
    dispatcher that routes to the currently hosted role object;
    InitializeRole (the Initialize*Request analog) installs or REPLACES
    a role at a given generation, which is exactly what recovery needs:
    re-initializing a resolver builds a brand-new ResolverRole with
    EMPTY conflict state. A background beacon registers this worker
    with the cluster controller (RegisterWorker) on a cadence — it
    doubles as the liveness signal and re-announces after a monitor
    restart."""

    BEACON_INTERVAL = 0.5

    def __init__(self, worker_id: str, address: str,
                 controller: str | None = None):
        self.worker_id = worker_id
        self.address = address
        self.controller = controller
        self.roles: dict[str, object] = {}  # kind -> hosted role object
        self.role_epochs: dict[str, int] = {}
        self.initializations = 0
        self._reg_task: asyncio.Task | None = None
        self._reg_conn: transport.RpcConnection | None = None

    async def start(self) -> None:
        if self.controller:
            self._reg_task = asyncio.ensure_future(self._register_loop())

    async def stop(self) -> None:
        """Release everything the worker owns: the registration beacon
        task, its controller connection, and every hosted role — the
        ownership hook the res.* pass (and the per-process census)
        require of any store-on-self acquire."""
        task = self._reg_task
        self._reg_task = None
        if task is not None:
            task.cancel()
            await asyncio.gather(task, return_exceptions=True)
        conn = self._reg_conn
        self._reg_conn = None
        if conn is not None:
            try:
                await conn.close()
            except Exception:
                pass
        for kind in list(self.roles):
            old = self.roles.pop(kind)
            self.role_epochs.pop(kind, None)
            if isinstance(old, (ProxyRole, RatekeeperRole)):
                await old.stop()
            elif isinstance(old, StorageRole):
                await old.aclose_disk()
            elif hasattr(old, "close_disk"):
                old.close_disk()

    async def _register_loop(self) -> None:
        import json as _json

        while True:
            try:
                conn = self._reg_conn
                if conn is None:
                    conn = transport.RpcConnection(
                        self.controller, tls=_tls_from_env()
                    )
                    await conn.connect(retries=1)
                    self._reg_conn = conn
                await conn.call(
                    TOKEN_REGISTER_WORKER,
                    RegisterWorker(payload=_json.dumps({
                        "worker_id": self.worker_id,
                        "address": self.address,
                        "pid": os.getpid(),
                        "roles": dict(self.role_epochs),
                    })),
                    timeout=2.0,
                )
            except asyncio.CancelledError:
                raise
            except Exception:
                conn = self._reg_conn
                self._reg_conn = None
                if conn is not None:
                    try:
                        await conn.close()
                    except Exception:
                        pass
            await asyncio.sleep(self.BEACON_INTERVAL)

    def role(self, kind: str):
        r = self.roles.get(kind)
        if r is None:
            # retryable: the controller hasn't recruited this role here
            # (or a monitor-restarted worker lost it — the controller's
            # heartbeat sees the mismatch and recovers)
            raise transport.RemoteError(
                f"worker_not_initialized: no {kind} hosted on "
                f"{self.worker_id}"
            )
        return r

    async def init_role(self, req: "InitializeRole") -> "InitializeRoleReply":
        import json as _json

        spec = _json.loads(req.payload)
        kind = spec["kind"]
        epoch = int(spec.get("epoch", 0))
        old = self.roles.pop(kind, None)
        self.role_epochs.pop(kind, None)
        if isinstance(old, (ProxyRole, RatekeeperRole)):
            await old.stop()
        elif isinstance(old, StorageRole):
            # storage WAL writes run on executor threads: close under
            # the log lock (use-after-free in the native queue
            # otherwise) — and BEFORE the successor (possibly on this
            # same worker) re-opens the data dir
            await old.aclose_disk()
        elif old is not None and hasattr(old, "close_disk"):
            # the tlog's disk ops all run on the event loop; a plain
            # close cannot interleave with a push
            old.close_disk()
        role, info = await self._build_role(kind, epoch, spec)
        self.roles[kind] = role
        self.role_epochs[kind] = epoch
        self.initializations += 1
        from foundationdb_tpu.utils.trace import SEV_INFO, TraceEvent

        TraceEvent("WorkerRoleInitialized", severity=SEV_INFO).detail(
            "WorkerId", self.worker_id
        ).detail("Kind", kind).detail("Epoch", epoch).log()
        return InitializeRoleReply(payload=_json.dumps({
            "ok": True, "kind": kind, "epoch": epoch,
            "worker_id": self.worker_id, **info,
        }))

    async def _build_role(self, kind: str, epoch: int, spec: dict):
        if kind == "resolver":
            if spec.get("resolver_kernel"):
                os.environ["RESOLVER_KERNEL"] = spec["resolver_kernel"]
            role = ResolverRole(
                backend=spec.get("backend", "native"), epoch=epoch,
                compute_cost_per_txn=float(
                    spec.get("compute_cost_per_txn") or 0.0
                ),
            )
            return role, {}
        if kind == "tlog":
            role = TLogRole(
                data_dir=spec.get("data_dir"), epoch=epoch,
                partitioned=bool(spec.get("partitioned", False)),
            )
            return role, {"durable_version": role.version}
        if kind == "sequencer":
            role = SequencerRole(
                epoch=epoch,
                recovery_version=int(spec.get("recovery_version", 0)),
                n_tags=int(spec.get("n_tags", 1)),
            )
            return role, {"version": role._seq.version}
        if kind == "storage":
            role = StorageRole(
                data_dir=spec.get("data_dir"),
                engine=spec.get("storage_engine", "memory"),
            )
            if spec.get("tlog_address"):
                addrs = [spec["tlog_address"]] + list(
                    spec.get("tlog_addresses") or ()
                )
                if len(addrs) > 1:
                    await role.catch_up_from_tlogs(addrs)
                else:
                    await role.catch_up_from_tlog(spec["tlog_address"])
            rv = int(spec.get("recovery_version", -1))
            if rv >= 0:
                await role.advance_floor(rv)
            return role, {"durable_version": role.version}
        if kind == "ratekeeper":
            role = RatekeeperRole(
                spec.get("peers") or [],
                controller=spec.get("controller") or self.controller,
            )
            await role.start()
            return role, {}
        if kind == "proxy":
            role = ProxyRole(spec)
            await role.start()
            return role, {"recovered": role.recovered}
        raise transport.RemoteError(f"unknown role kind {kind!r}")

    def status(self) -> dict:
        base = {
            "worker_id": self.worker_id,
            "hosted": sorted(self.roles),
            "role_epochs": dict(self.role_epochs),
            "initializations": self.initializations,
        }
        if len(self.roles) == 1:
            # the common one-role-per-worker shape: report AS the
            # hosted role so fdbtop / the ratekeeper / the controller
            # heartbeat read the role's sensors straight off the
            # worker's socket
            (kind, role), = self.roles.items()
            block = role.status()
            block.update(base)
            return block
        return {"role": "worker", "idle": not self.roles, **base,
                "qos": {"hosted": sorted(self.roles),
                        **{k: r.status().get("qos", {})
                           for k, r in self.roles.items()}}}

    def register_tokens(self, server: transport.RpcServer) -> None:
        """The dispatch loop: every role token routes through the
        hosted-role map, so one worker binary serves whatever it is
        recruited as (the fdbserver shape)."""

        def route(kind: str, method: str):
            async def handler(req, _kind=kind, _method=method):
                return await getattr(self.role(_kind), _method)(req)

            return handler

        server.register(TOKEN_INIT_ROLE, self.init_role)
        server.register(TOKEN_RESOLVE, route("resolver", "resolve"))

        async def resolver_version(_req: RoleVersionReq) -> RoleVersionReply:
            return RoleVersionReply(version=self.role("resolver").version)

        server.register(TOKEN_RESOLVER_VERSION, resolver_version)
        server.register(TOKEN_TLOG_PUSH, route("tlog", "push"))
        server.register(TOKEN_TLOG_PEEK, route("tlog", "peek"))
        server.register(TOKEN_TLOG_PEEK_BATCH, route("tlog", "peek_batch"))
        server.register(TOKEN_TLOG_VERSION, route("tlog", "get_version"))
        server.register(TOKEN_TLOG_LOCK, route("tlog", "lock"))
        server.register(TOKEN_TLOG_POP, route("tlog", "pop"))
        server.register(TOKEN_STORAGE_APPLY, route("storage", "apply"))
        server.register(
            TOKEN_STORAGE_APPLY_BATCH, route("storage", "apply_batch")
        )
        server.register(TOKEN_STORAGE_GET, route("storage", "get"))
        server.register(TOKEN_STORAGE_GET_BATCH, route("storage", "get_batch"))
        server.register(TOKEN_STORAGE_SNAPSHOT, route("storage", "snapshot"))
        server.register(TOKEN_STORAGE_VERSION, route("storage", "get_version"))
        server.register(TOKEN_STORAGE_CATCHUP, route("storage", "catch_up"))
        server.register(
            TOKEN_GET_RATE_INFO, route("ratekeeper", "get_rate_info")
        )
        server.register(TOKEN_CLIENT_GRV, route("proxy", "client_grv"))
        server.register(TOKEN_CLIENT_COMMIT, route("proxy", "client_commit"))
        server.register(TOKEN_CLIENT_READ, route("proxy", "client_read"))
        server.register(TOKEN_RATE_UPDATE, route("proxy", "rate_update"))
        server.register(
            TOKEN_GET_COMMIT_VERSION, route("sequencer", "get_commit_version")
        )
        server.register(
            TOKEN_REPORT_COMMITTED, route("sequencer", "report_committed")
        )
        server.register(
            TOKEN_SEQUENCER_VERSION, route("sequencer", "get_version")
        )


class ClusterControllerRole:
    """The cluster state owner: recruits a declarative topology onto
    registered workers, heartbeats them over the StatusRequest
    plumbing, and on any transaction-path death runs the reference
    recovery walk (cluster/generation.py GenerationState — the SAME
    state machine the sim ClusterController drives): bump the
    generation, lock the durable tlog and take the recovery version
    from it, recruit NEW resolvers with EMPTY conflict state, recruit
    the new proxy generation whose first batch is the conservative
    whole-keyspace blind write, and re-open for business. Storage and
    the tlog's durable state survive recovery untouched; a dead
    controller is itself survivable — the monitor restarts it, it
    re-learns workers from their beacons and (epoch persisted in the
    state file) always recovers into a strictly newer generation."""

    #: consecutive heartbeat misses before a role is declared dead — a
    #: kill -9'd worker fails its poll in milliseconds (connection
    #: refused), so detection stays fast; the margin is for a LIVE
    #: worker whose event loop stalls a poll under load
    HEARTBEAT_MISSES = 3
    #: a worker whose beacon is older than this is not live
    WORKER_TTL = 3.0

    def __init__(self, conf: dict, *, state_file: str | None = None,
                 check_interval: float = 0.25):
        import time as _time

        from foundationdb_tpu.cluster.generation import GenerationState

        self.conf = conf
        self.check_interval = check_interval
        self.state_file = state_file
        self.gen = GenerationState(
            epoch=self._load_epoch(), clock=_time.time
        )
        self.workers: dict[str, dict] = {}  # id -> beacon info
        self.assignments: dict[str, dict] = {}  # role name -> placement
        self.recoveries_completed = 0
        self.last_recovery_s: float | None = None
        self.last_recovery_reason: str | None = None
        #: monitor push-on-death notifications received (ISSUE 14) —
        #: the chaos smoke pins that the push path, not the heartbeat
        #: backstop, is what detects a SIGKILL'd worker
        self.death_notifications = 0
        self._needs_recovery = True  # initial recruitment IS a recovery
        self._recovery_reason = "initial_recruitment"
        self._miss_counts: dict[str, int] = {}
        self._conns: dict[str, transport.RpcConnection] = {}
        self._task: asyncio.Task | None = None
        # -- elastic topology (ISSUE 15): when the Ratekeeper's binding
        # limiter names resolver occupancy/queueing for `elastic_streak`
        # consecutive control intervals (the law's own binding_streak
        # counter, read off the ratekeeper's heartbeat status), the
        # controller plans a topology with ONE MORE resolver and drives
        # the normal generation-bumped recovery walk to recruit it live
        # — the reference's configuration-change-causes-recovery
        # discipline, with Ratekeeper turned from a brake into a
        # scaling signal. Capped at elastic_max_resolvers; OFF by
        # default (conf "elastic": true arms it).
        self.elastic_enabled = bool(conf.get("elastic", False))
        self.elastic_max_resolvers = int(
            conf.get("elastic_max_resolvers", 2)
        )
        #: commit-path scale-out (ISSUE 19): the SAME trigger machinery
        #: drives proxy recruitment off the proxy-queue limiter — the
        #: _plan + clip machinery generalizes verbatim
        self.elastic_max_proxies = int(conf.get("elastic_max_proxies", 2))
        self.elastic_streak = int(conf.get("elastic_streak", 4))
        #: limiter names that mean "another resolver would help"
        self.ELASTIC_RESOLVER_REASONS = ("resolver_busy", "resolver_queue")
        #: limiter names that mean "another commit proxy would help"
        self.ELASTIC_PROXY_REASONS = ("commit_proxy_queue", "proxy_queue")
        self.elastic_recruits = 0
        self.elastic_last_streak = 0
        self.elastic_last_limiter = None
        # -- elastic scale-down (ISSUE 19 satellite): when the binding
        # limiter has been "workload" (= nothing structural binds; the
        # offered load itself is the ceiling) for elastic_scale_down_
        # streak consecutive control intervals, ONE above-baseline
        # elastic role is retired through the same recovery walk. The
        # baseline is the conf as DECLARED (captured before any
        # persisted elastic override), so scale-down never cuts below
        # what the operator asked for.
        self.elastic_scale_down_streak = int(
            conf.get("elastic_scale_down_streak",
                     max(4, 2 * self.elastic_streak))
        )
        self._elastic_baseline = {
            "resolvers": int(conf.get("resolvers", 1)),
            "proxies": int(conf.get("proxies", 1)),
        }
        self.elastic_scale_downs = 0
        self._workload_streak_observed = 0
        self._workload_gate = self.elastic_scale_down_streak
        # -- persisted elastic topology (ISSUE 19 satellite): a
        # controller kill -9 must not forget fleet size — the planned
        # counts ride the state file next to the epoch and are re-
        # applied over the conf here, before the first _plan()
        for kind_key, count in (self._load_state().get(
                "topology") or {}).items():
            if kind_key in ("resolvers", "proxies", "tlogs"):
                try:
                    self.conf[kind_key] = max(
                        int(self.conf.get(kind_key, 1)), int(count)
                    )
                except (TypeError, ValueError):
                    pass
        self._rk_qos: dict = {}
        #: the streak value a trigger must reach. Normally
        #: elastic_streak; after a recruit it is raised to
        #: (streak-at-recruit + elastic_streak) because the surviving
        #: ratekeeper's law carries its streak ACROSS the recovery — a
        #: still-binding limiter must hold for elastic_streak FRESH
        #: post-recruit intervals (proof the previous recruit didn't
        #: help) before the next one, never chain off the old streak.
        #: A streak reset observed in between restores the normal gate.
        self._elastic_gate = self.elastic_streak
        self._elastic_last_observed = 0
        #: set by worker_death to cut the supervision loop's sleep short
        #: — a pushed death starts the recovery walk on the next loop
        #: iteration, not up to check_interval later
        self._wake = asyncio.Event()

    # -- epoch persistence (the coordinated-state analog) ---------------

    def _load_state(self) -> dict:
        import json as _json

        if self.state_file and os.path.exists(self.state_file):
            try:
                with open(self.state_file) as f:
                    doc = _json.load(f)
                    return doc if isinstance(doc, dict) else {}
            except Exception:
                return {}
        return {}

    def _load_epoch(self) -> int:
        try:
            return int(self._load_state().get("epoch", 0))
        except (TypeError, ValueError):
            return 0

    def _persist_epoch(self, epoch: int) -> None:
        import json as _json

        if not self.state_file:
            return
        tmp = self.state_file + ".tmp"
        with open(tmp, "w") as f:
            # the planned elastic topology persists NEXT TO the epoch
            # (ISSUE 19 satellite): a restarted controller re-applies
            # these counts over its conf, so a kill -9 between an
            # elastic recruit and the next one never forgets fleet size
            _json.dump({
                "epoch": epoch,
                "topology": {
                    "resolvers": int(self.conf.get("resolvers", 1)),
                    "proxies": int(self.conf.get("proxies", 1)),
                    "tlogs": int(self.conf.get("tlogs", 1)),
                },
            }, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.state_file)

    # -- RPC surface -----------------------------------------------------

    async def register_worker(
        self, req: "RegisterWorker"
    ) -> "RegisterWorkerReply":
        import json as _json
        import time as _time

        info = _json.loads(req.payload)
        self.workers[info["worker_id"]] = {
            **info, "last_seen": _time.monotonic(),
        }
        return RegisterWorkerReply(payload=_json.dumps(
            {"ok": True, "epoch": self.gen.epoch}
        ))

    async def worker_death(self, req: "WorkerDeath") -> "WorkerDeathReply":
        """Monitor push-on-death (ISSUE 14): the monitor reaped this
        worker's process, so every role it hosted is dead NOW — no need
        to wait out HEARTBEAT_MISSES failed polls. Transaction-path
        roles flag the recovery walk immediately (reason "push:<roles>"
        — the chaos smoke pins the prefix); singletons get their miss
        count pre-loaded so the next supervision pass re-recruits on
        its FIRST failed poll. The wake event cuts the loop's sleep."""
        import json as _json

        from foundationdb_tpu.utils.trace import SEV_WARN_ALWAYS, TraceEvent

        info = _json.loads(req.payload)
        wid = info.get("worker_id")
        self.death_notifications += 1
        self.workers.pop(wid, None)
        dead = sorted(
            n for n, a in self.assignments.items()
            if a["worker_id"] == wid
        )
        txn_dead = [
            n for n in dead
            if self.assignments[n]["kind"]
            in ("proxy", "resolver", "tlog", "sequencer")
        ]
        TraceEvent(
            "WorkerDeathPushed", severity=SEV_WARN_ALWAYS
        ).detail("Worker", wid).detail(
            "Roles", ",".join(dead) or "none"
        ).detail("Epoch", self.gen.epoch).log()
        if txn_dead and not self._needs_recovery:
            self._needs_recovery = True
            self._recovery_reason = "push:" + ",".join(txn_dead)
        for n in dead:
            # singletons (and txn roles, harmlessly): one more failed
            # poll — not three — declares them dead in the heartbeat
            self._miss_counts[n] = self.HEARTBEAT_MISSES
        self._wake.set()
        return WorkerDeathReply(payload=_json.dumps(
            {"ok": True, "roles": dead}
        ))

    def topology_doc(self) -> dict:
        return {
            "epoch": self.gen.epoch,
            "state": self.gen.status,
            "recovery_version": self.gen.recovery_version,
            "recoveries_completed": self.recoveries_completed,
            "roles": {
                name: {
                    "kind": a["kind"],
                    "address": a["address"],
                    "worker": a["worker_id"],
                    "epoch": a["epoch"],
                    "pid": self.workers.get(a["worker_id"], {}).get("pid"),
                }
                for name, a in self.assignments.items()
            },
        }

    async def topology(self, _req: "TopologyRequest") -> "TopologyReply":
        import json as _json

        return TopologyReply(payload=_json.dumps(self.topology_doc()))

    def status(self) -> dict:
        import time as _time

        now = _time.monotonic()
        return {
            "role": "cluster_controller",
            "epoch": self.gen.epoch,
            "qos": {
                "epoch": self.gen.epoch,
                "recovery_state": self.gen.status,
                "recovery_version": self.gen.recovery_version,
                "recoveries_completed": self.recoveries_completed,
                "last_recovery_s": self.last_recovery_s,
                "last_recovery_reason": self.last_recovery_reason,
                "death_notifications": self.death_notifications,
                # elastic topology (ISSUE 15) — the fdbtop panel's and
                # the drill's observability surface
                "elastic_enabled": self.elastic_enabled,
                "elastic_recruits": self.elastic_recruits,
                "elastic_streak_needed": self.elastic_streak,
                "elastic_last_streak": self.elastic_last_streak,
                "elastic_last_limiter": self.elastic_last_limiter,
                "elastic_scale_downs": self.elastic_scale_downs,
                "resolvers_planned": int(self.conf.get("resolvers", 1)),
                "proxies_planned": int(self.conf.get("proxies", 1)),
                "tlogs_planned": int(self.conf.get("tlogs", 1)),
                "partitioned": self._partitioned(),
                # the last recovery's phase-one lock width: a one-of-N
                # tlog kill shows survivors < total (per-tag quorum)
                "last_tlog_lock": getattr(self, "last_tlog_lock", None),
                "workers_registered": len(self.workers),
                "workers_live": len(self._live_workers()),
                "roles_recruited": len(self.assignments),
                "recovery_timeline": self.gen.timeline_dicts(),
                "workers": {
                    wid: {
                        "pid": w.get("pid"),
                        "age_s": round(now - w["last_seen"], 3),
                        "roles": w.get("roles", {}),
                    }
                    for wid, w in self.workers.items()
                },
            },
        }

    # -- recruitment planning --------------------------------------------

    def _partitioned(self) -> bool:
        """True when the commit path runs in scale-out mode (ISSUE 19):
        a sequencer role owns version allotment, pushes carry the
        chained prev_versions, and the tlogs run their per-tag chain
        wait. Any of N>1 proxies, N>1 tlogs, or an explicit conf
        "sequencer": true turns it on; the default single-proxy
        topology keeps the legacy local-allocation path byte-
        identical."""
        return (
            int(self.conf.get("proxies", 1)) > 1
            or int(self.conf.get("tlogs", 1)) > 1
            or bool(self.conf.get("sequencer", False))
        )

    def _role_names(self) -> list[tuple[str, str]]:
        """(role name, kind) pairs of the declarative topology, in
        recruitment order: durable logs first (the recovery version
        source), then storage, the sequencer (scale-out mode), the
        resolvers, ratekeeper, proxies last (proxy0's init commits the
        recovery transaction)."""
        names: list[tuple[str, str]] = []
        for i in range(int(self.conf.get("tlogs", 1))):
            names.append((f"tlog{i}", "tlog"))
        names.append(("storage0", "storage"))
        if self._partitioned():
            names.append(("sequencer0", "sequencer"))
        for i in range(int(self.conf.get("resolvers", 1))):
            names.append((f"resolver{i}", "resolver"))
        if self.conf.get("ratekeeper", True):
            names.append(("ratekeeper0", "ratekeeper"))
        for i in range(int(self.conf.get("proxies", 1))):
            names.append((f"proxy{i}", "proxy"))
        return names

    def _live_workers(self) -> dict[str, dict]:
        import time as _time

        now = _time.monotonic()
        return {
            wid: w for wid, w in self.workers.items()
            if now - w["last_seen"] <= self.WORKER_TTL
        }

    def _plan(self) -> dict[str, dict]:
        """Assign each role a live worker (one role per worker, so a
        kill -9 takes out exactly one role). Placement preference:
        (1) the current assignment when its worker is still live;
        (2) a live worker whose BEACON already reports hosting the
        kind — the re-adoption path: a restarted controller has no
        assignment memory, and recruiting a durable role away from the
        worker that still holds its disk queue open would double-open
        the data dir (found by the controller-kill chaos scenario);
        (3) an idle live worker; (4) any live worker. Raises if the
        live worker set cannot host the topology — the caller retries
        after the monitor has restarted the dead workers."""
        live = self._live_workers()
        taken: set[str] = set()
        plan: dict[str, dict] = {}
        for name, kind in self._role_names():
            cur = self.assignments.get(name)
            wid = None
            if cur and cur["worker_id"] in live \
                    and cur["worker_id"] not in taken:
                wid = cur["worker_id"]
            if wid is None:
                for cand in sorted(live):
                    if cand not in taken \
                            and kind in (live[cand].get("roles") or {}):
                        wid = cand
                        break
            if wid is None:
                for cand in sorted(live):
                    if cand not in taken \
                            and not (live[cand].get("roles") or {}):
                        wid = cand
                        break
            if wid is None:
                for cand in sorted(live):
                    if cand not in taken:
                        wid = cand
                        break
            if wid is None:
                raise RuntimeError(
                    f"not enough live workers: need "
                    f"{len(self._role_names())}, have {len(live)}"
                )
            taken.add(wid)
            plan[name] = {
                "kind": kind,
                "worker_id": wid,
                "address": live[wid]["address"],
                "epoch": self.gen.epoch,
            }
        return plan

    def _hosted_epoch(self, worker_id: str, kind: str) -> int:
        """The epoch a surviving role was initialized at, from its
        worker's beacon — what heartbeats will compare against."""
        w = self._live_workers().get(worker_id) or {}
        return int((w.get("roles") or {}).get(kind, 0))

    def _suspect_worker(self, address: str) -> None:
        """Drop a worker we failed to reach from the registry: its
        beacon ages in every ~0.5s, so a LIVE worker re-appears almost
        immediately, while a kill -9 corpse stops poisoning the
        recruitment plan NOW instead of after the beacon TTL (found by
        the first chaos run: recovery retried into the dead worker for
        a full TTL before re-planning)."""
        for wid, w in list(self.workers.items()):
            if w.get("address") == address:
                self.workers.pop(wid, None)

    async def _worker_call(self, address: str, token: int, msg,
                           *, timeout: float = 30.0):
        return await _cached_call(
            self._conns, address, token, msg,
            timeout=timeout, on_fail=self._suspect_worker,
        )

    async def _init_role(self, placement: dict, spec: dict, *,
                         timeout: float = 120.0) -> dict:
        import json as _json

        reply = await self._worker_call(
            placement["address"], TOKEN_INIT_ROLE,
            InitializeRole(payload=_json.dumps({
                "kind": placement["kind"],
                "epoch": placement["epoch"],
                **spec,
            })),
            timeout=timeout,
        )
        return _json.loads(reply.payload)

    # -- the recovery walk ----------------------------------------------

    async def _recover(self) -> None:
        import time as _time

        from foundationdb_tpu.cluster import generation as gen

        t0 = _time.monotonic()
        reason = self._recovery_reason
        epoch = self.gen.begin_recovery(floor=self._load_epoch())
        self._persist_epoch(epoch)
        # wait until the monitor has restarted enough workers to host
        # the topology (the beacons re-announce them)
        while True:
            try:
                plan = self._plan()
                break
            except RuntimeError:
                await asyncio.sleep(self.check_interval)
        conf = self.conf
        self.gen.transition(gen.LOCKING_OLD_TRANSACTION_SERVERS,
                            Reason=reason)
        # 1. The durable logs: keep each where it lives (or re-host it
        #    from its per-index data dir), then LOCK at the new epoch —
        #    old-generation pushes are fenced from here on, and the
        #    lock replies carry the durable versions recovery derives
        #    from. Scale-out mode (ISSUE 19) runs the TWO-PHASE per-tag
        #    quorum walk: phase one locks the LIVE tlogs immediately
        #    (killing one of N stalls only its tags for the re-host
        #    window — the survivors' lock is the quorum), phase two
        #    re-locks everything with the computed recovery version so
        #    every per-tag version floor advances past the old
        #    generation as a unit.
        n_tlogs = int(conf.get("tlogs", 1))
        partitioned = self._partitioned()
        tlog_places = [plan[f"tlog{i}"] for i in range(n_tlogs)]
        base_tlog_dir = conf.get("tlog_data_dir")

        def _tlog_dir(i: int):
            if not base_tlog_dir:
                return None
            return base_tlog_dir if i == 0 else f"{base_tlog_dir}-{i}"

        part_flag = 1 if partitioned else 0
        survivor_idx: set[int] = set()
        for i, place in enumerate(tlog_places):
            if self._worker_hosts(place["worker_id"], "tlog"):
                # survivor (current assignment OR a restarted
                # controller's beacon re-adoption): keep the epoch it
                # was INITIALIZED at — the worker's role_epochs is what
                # heartbeats compare, and the fencing epoch advances
                # via the lock below (a re-stamped assignment here made
                # every later heartbeat a mismatch and cascaded
                # spurious recoveries)
                place["epoch"] = self._hosted_epoch(
                    place["worker_id"], "tlog"
                )
                survivor_idx.add(i)
        # phase one: fence the survivors NOW (concurrently)
        locks = await asyncio.gather(*(
            self._worker_call(
                tlog_places[i]["address"], TOKEN_TLOG_LOCK,
                TLogLock(epoch=epoch, partitioned=part_flag),
            )
            for i in sorted(survivor_idx)
        ))
        durables = [lk.durable_version for lk in locks]
        # the quorum surface (chaos drill pin): how many tlogs the
        # phase-one lock needed vs the topology width — a one-of-N
        # kill must show survivors < total with recovery proceeding
        self.last_tlog_lock = {
            "survivors": len(survivor_idx), "total": n_tlogs,
        }
        if partitioned:
            # the OLD sequencer's head (best effort): versions it
            # GRANTED but no tlog ever saw must stay below the new
            # floor, or the fresh sequencer could re-issue them
            old_seq = self.assignments.get("sequencer0")
            if old_seq is not None and self._worker_hosts(
                    old_seq["worker_id"], "sequencer"):
                try:
                    r = await self._worker_call(
                        old_seq["address"], TOKEN_SEQUENCER_VERSION,
                        RoleVersionReq(pad=0), timeout=2.0,
                    )
                    durables.append(r.version)
                except Exception:
                    pass
        # re-host dead tlogs from their data dirs (the WAL replay
        # restores each tag's durable state) and lock them on arrival
        for i, place in enumerate(tlog_places):
            if i in survivor_idx:
                continue
            await self._init_role(place, {
                "data_dir": _tlog_dir(i),
                "partitioned": partitioned,
            })
            lk = await self._worker_call(
                place["address"], TOKEN_TLOG_LOCK,
                TLogLock(epoch=epoch, partitioned=part_flag),
            )
            durables.append(lk.durable_version)
        recovery_version = gen.recovery_version_for(*durables)
        self.gen.recovery_version = recovery_version
        self.gen.transition(gen.RECRUITING_TRANSACTION_SERVERS,
                            RecoveryVersion=recovery_version)
        if partitioned:
            # phase two: advance every per-tag version floor to the
            # recovery version — the new generation's first push per
            # tag (prev = recovery version) finds its predecessor, and
            # parked chain waiters drain as stale instead of wedging
            # across the generation bump
            await asyncio.gather(*(
                self._worker_call(
                    p["address"], TOKEN_TLOG_LOCK,
                    TLogLock(epoch=epoch,
                             recovery_version=recovery_version,
                             partitioned=part_flag),
                )
                for p in tlog_places
            ))
        tlog = tlog_places[0]
        tlog_addresses = [p["address"] for p in tlog_places]
        # 2. Storage's durable state survives recovery, but its APPLY
        #    FEED died with the old proxy: it must replay the locked
        #    tlog's tail BEFORE the new generation's first apply can
        #    advance its version past the gap. A dead storage is
        #    re-hosted from its durable dir (the init catch-up does the
        #    same replay).
        storage = plan["storage0"]
        # scale-out mode also hands storage the recovery version: its
        # apply chain's floor must advance past the old generation so
        # the first new-generation chained apply (prev = a version the
        # old generation owned) finds its predecessor
        storage_rv = recovery_version if partitioned else -1
        if self._worker_hosts(storage["worker_id"], "storage"):
            storage["epoch"] = self._hosted_epoch(
                storage["worker_id"], "storage"
            )
            await self._worker_call(
                storage["address"], TOKEN_STORAGE_CATCHUP,
                StorageCatchUp(
                    tlog_address=tlog_addresses[0],
                    tlog_addresses=tlog_addresses[1:],
                    recovery_version=storage_rv,
                ),
            )
        else:
            await self._init_role(storage, {
                "data_dir": conf.get("storage_data_dir"),
                "storage_engine": conf.get("storage_engine", "memory"),
                "tlog_address": tlog_addresses[0],
                "tlog_addresses": tlog_addresses[1:],
                "recovery_version": storage_rv,
            })
        # 3. NEW resolvers, EMPTY conflict state — always rebuilt, even
        #    on surviving workers (resolvers are stateless across
        #    recoveries; correctness comes from the conservative abort).
        #    Each boots with the empty batch at the recovery version so
        #    the new proxy's version chain finds them ready.
        resolver_places = [
            p for n, p in sorted(plan.items()) if p["kind"] == "resolver"
        ]
        for place in resolver_places:
            await self._init_role(place, {
                "backend": conf.get("backend", "native"),
                "resolver_kernel": conf.get("resolver_kernel"),
                "compute_cost_per_txn": conf.get("resolver_compute_cost"),
            })
            await self._worker_call(
                place["address"], TOKEN_RESOLVE,
                ResolveTransactionBatchRequest(
                    prev_version=-1,
                    version=recovery_version,
                    last_received_version=-1,
                    epoch=epoch,
                ),
            )
        # 4. Ratekeeper: a singleton, re-recruited only if dead (it
        #    re-resolves peers from our topology each control cycle).
        #    The resolver-count change (elastic recruit or conf edit)
        #    RE-DERIVES the keyspace split here: N resolvers get the
        #    even byte-prefix boundaries (the ResolutionBalancer's
        #    key-sample feed is the remaining headroom), and the new
        #    proxy clips every batch to them — so a recruit genuinely
        #    divides conflict work instead of broadcasting it N times.
        # 3b. The sequencer (scale-out mode): ALWAYS rebuilt fresh at
        #     the recovery version — a surviving old instance carries
        #     the fenced generation's grant state, and the per-tag
        #     chains must restart at the new floor. n_tags = the tlog
        #     count (the tag partition IS the tlog partition).
        seq_place = None
        if partitioned:
            seq_place = plan["sequencer0"]
            await self._init_role(seq_place, {
                "recovery_version": recovery_version,
                "n_tags": n_tlogs,
            })
        topo_addrs = {
            "resolvers": [p["address"] for p in resolver_places],
            "resolver_boundaries": [
                b.hex()
                for b in default_resolver_boundaries(len(resolver_places))
            ],
            "tlog": tlog["address"],
            "storage": storage["address"],
        }
        if partitioned:
            topo_addrs["tlogs"] = tlog_addresses
            topo_addrs["tlog_boundaries"] = [
                b.hex() for b in default_resolver_boundaries(n_tlogs)
            ]
            topo_addrs["sequencer"] = seq_place["address"]
        if "ratekeeper0" in plan:
            rk = plan["ratekeeper0"]
            if self._worker_hosts(rk["worker_id"], "ratekeeper"):
                # survivor keeps its init epoch
                rk["epoch"] = self._hosted_epoch(
                    rk["worker_id"], "ratekeeper"
                )
            else:
                await self._init_role(rk, {
                    "peers": [*tlog_addresses, storage["address"],
                              *topo_addrs["resolvers"]],
                })
            topo_addrs["ratekeeper"] = rk["address"]
        # 5. The new proxy generation: proxy0's start() commits the
        #    conservative recovery transaction as the FIRST batch (the
        #    sequencer grants it the first version of the generation),
        #    then the remaining proxies join the shared version chain
        #    concurrently — they never recover, only commit.
        self.gen.transition(gen.RECOVERY_TRANSACTION)
        proxy_places = [
            (n, p) for n, p in sorted(plan.items())
            if p["kind"] == "proxy"
        ]

        def _proxy_spec(name: str, recover: bool) -> dict:
            return {
                "topology": topo_addrs,
                "start_version": recovery_version,
                "recover": recover,
                "proxy_id": name,
                "batch_interval": conf.get("batch_interval", 0.002),
                "max_batch": conf.get("max_batch", 512),
                "trace": bool(conf.get("trace", False)),
            }

        name0, proxy = proxy_places[0]
        info = await self._init_role(proxy, _proxy_spec(name0, True))
        if not info.get("recovered"):
            raise RuntimeError(f"proxy recruitment did not recover: {info}")
        if len(proxy_places) > 1:
            await asyncio.gather(*(
                self._init_role(p, _proxy_spec(n, False))
                for n, p in proxy_places[1:]
            ))
        self.gen.transition(gen.ACCEPTING_COMMITS)
        self.assignments = plan
        self._miss_counts.clear()
        self.recoveries_completed += 1
        self.last_recovery_s = round(_time.monotonic() - t0, 3)
        self.last_recovery_reason = reason
        self.gen.transition(
            gen.FULLY_RECOVERED,
            RecoverySeconds=self.last_recovery_s,
            Reason=reason,
        )

    def _worker_hosts(self, worker_id: str, kind: str) -> bool:
        """True if the worker's latest beacon reports hosting `kind` —
        a monitor-restarted worker re-registers with an EMPTY role map,
        which is how the controller learns a kill -9 took the role with
        it even though the socket answers again."""
        w = self._live_workers().get(worker_id)
        return bool(w) and kind in (w.get("roles") or {})

    # -- heartbeat + supervision loop ------------------------------------

    async def _heartbeat(self) -> list[str]:
        """One heartbeat pass over the recruited topology (concurrent
        StatusRequest polls; reusing the StatusRequest plumbing means
        heartbeats double as sensor reads). A role is dead after
        HEARTBEAT_MISSES consecutive misses, where a miss is a failed
        poll OR a worker that answers but no longer hosts the role at
        the recruited epoch (restarted corpse)."""
        import json as _json

        async def poll(name: str, a: dict):
            try:
                reply = await self._worker_call(
                    a["address"], TOKEN_STATUS, StatusRequest(pad=0),
                    timeout=2.0,
                )
                block = _json.loads(reply.payload)
            except Exception:
                return name, False
            if a["kind"] == "ratekeeper":
                # heartbeats double as sensor reads: the ratekeeper's
                # qos carries the law's budget + binding_streak — the
                # elasticity trigger's input (stale entries age out via
                # the budget_stale flag the law itself sets)
                self._rk_qos = block.get("qos") or {}
            hosted = block.get("role_epochs") or {}
            return name, hosted.get(a["kind"]) == a["epoch"]

        results = await asyncio.gather(
            *(poll(n, a) for n, a in self.assignments.items())
        )
        dead = []
        for name, ok in results:
            if ok:
                self._miss_counts[name] = 0
                continue
            self._miss_counts[name] = self._miss_counts.get(name, 0) + 1
            if self._miss_counts[name] >= self.HEARTBEAT_MISSES:
                dead.append(name)
        return dead

    async def run(self) -> None:
        from foundationdb_tpu.utils.trace import (
            SEV_WARN_ALWAYS,
            TraceEvent,
        )

        while True:
            try:
                if self._needs_recovery:
                    await self._recover()
                    self._needs_recovery = False
                else:
                    dead = await self._heartbeat()
                    txn_dead = [
                        n for n in dead
                        if self.assignments[n]["kind"]
                        in ("proxy", "resolver", "tlog", "sequencer")
                    ]
                    for name in dead:
                        TraceEvent(
                            "ControllerRoleDead", severity=SEV_WARN_ALWAYS
                        ).detail("Role", name).detail(
                            "Kind", self.assignments[name]["kind"]
                        ).detail("Epoch", self.gen.epoch).log()
                        # the dead role's worker is suspect until its
                        # beacon re-announces it (a kill -9 corpse must
                        # not be re-planned into the next generation)
                        self.workers.pop(
                            self.assignments[name]["worker_id"], None
                        )
                    if txn_dead and not self._needs_recovery:
                        # the transaction system recovers AS A UNIT —
                        # never patched (the reference's key recovery
                        # property). Guarded like worker_death's flag:
                        # a push that landed while this heartbeat pass
                        # was in flight already set the reason, and the
                        # in-flight results must not overwrite its
                        # "push:" attribution (the chaos gate pins it)
                        self._needs_recovery = True
                        self._recovery_reason = ",".join(sorted(txn_dead))
                    else:
                        for name in dead:
                            await self._rerecruit_singleton(name)
                        if not dead:
                            # only a HEALTHY pass may scale: a dying
                            # role's missing occupancy feed can read as
                            # a saturated survivor for a cycle
                            self._elastic_check()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                TraceEvent(
                    "ControllerLoopError", severity=SEV_WARN_ALWAYS
                ).detail("Error", repr(e)).log()
            # interruptible sleep: a pushed worker death (worker_death)
            # wakes the loop immediately instead of up to a full
            # check_interval later
            try:
                await asyncio.wait_for(
                    self._wake.wait(), self.check_interval
                )
            except asyncio.TimeoutError:
                pass
            self._wake.clear()

    def _elastic_check(self) -> None:
        """The elasticity trigger (ISSUE 15): read the admission law's
        binding_streak off the ratekeeper's last heartbeat status; when
        a resolver-shaped limiter has been binding for elastic_streak
        consecutive control intervals (and the budget is not running on
        stale sensors), plan a topology with ONE MORE resolver and flag
        the generation-bumped recovery walk — the recruit happens
        through the exact code path any configuration change takes, so
        epoch fencing, the conservative abort and the boundary
        re-derivation all apply unchanged."""
        from foundationdb_tpu.cluster.generation import elastic_reason

        if not self.elastic_enabled or self._needs_recovery:
            return
        qos = self._rk_qos or {}
        streak = qos.get("binding_streak") or {}
        limiter = streak.get("name")
        self.elastic_last_limiter = limiter
        # the limiter name routes the SAME trigger machinery to the
        # role kind that would relieve it (ISSUE 19: the proxy-queue
        # limiter recruits commit proxies exactly like resolvers)
        if limiter in self.ELASTIC_RESOLVER_REASONS:
            kind, conf_key, cap = (
                "resolver", "resolvers", self.elastic_max_resolvers
            )
        elif limiter in self.ELASTIC_PROXY_REASONS:
            kind, conf_key, cap = (
                "proxy", "proxies", self.elastic_max_proxies
            )
        else:
            if limiter == "workload" and not qos.get("budget_stale"):
                # nothing structural binds: the workload itself is the
                # ceiling — feed the scale-down streak (ISSUE 19
                # satellite) while the recruit gate resets below
                self._scale_down_check(streak)
            else:
                self._workload_streak_observed = 0
                self._workload_gate = self.elastic_scale_down_streak
            self.elastic_last_streak = 0
            self._elastic_last_observed = 0
            self._elastic_gate = self.elastic_streak
            return
        self._workload_streak_observed = 0
        self._workload_gate = self.elastic_scale_down_streak
        if qos.get("budget_stale"):
            self.elastic_last_streak = 0
            self._elastic_last_observed = 0
            self._elastic_gate = self.elastic_streak
            return
        self.elastic_last_streak = int(streak.get("intervals", 0))
        if self.elastic_last_streak < self._elastic_last_observed:
            # the law's streak restarted since the last look (the
            # limiter released and re-engaged): the post-recruit gate
            # no longer applies — this is a fresh signal
            self._elastic_gate = self.elastic_streak
        self._elastic_last_observed = self.elastic_last_streak
        if self.elastic_last_streak < self._elastic_gate:
            return
        current = int(self.conf.get(conf_key, 1))
        if current >= cap:
            return
        from foundationdb_tpu.utils.trace import SEV_WARN_ALWAYS, TraceEvent

        self.conf[conf_key] = current + 1
        self.elastic_recruits += 1
        # the snapshot that fired this trigger must not fire the next
        # one: drop it, AND raise the gate past the law's surviving
        # streak — the ratekeeper outlives the recovery walk with its
        # counter intact, so the next recruit needs elastic_streak
        # FRESH intervals on top (or a reset, handled above)
        self._rk_qos = {}
        self._elastic_gate = self.elastic_last_streak + self.elastic_streak
        self._needs_recovery = True
        self._recovery_reason = elastic_reason(kind, current + 1)
        # cut the supervision sleep short, like a pushed worker death:
        # the recovery walk (loop top) starts next iteration, not up
        # to check_interval later
        self._wake.set()
        code_probe(True, "controller.elastic_recruit")
        TraceEvent(
            "ElasticRecruitPlanned", severity=SEV_WARN_ALWAYS
        ).detail("Kind", kind).detail(
            "From", current
        ).detail("To", current + 1).detail(
            "Limiter", limiter
        ).detail("StreakIntervals", self.elastic_last_streak).detail(
            "Epoch", self.gen.epoch
        ).log()

    def _scale_down_check(self, streak: dict) -> None:
        """The OFF direction of elasticity (ISSUE 19 satellite): when
        the admission law reports "workload" as the binding limiter —
        the offered load is the ceiling, nothing structural binds —
        for elastic_scale_down_streak consecutive control intervals,
        retire ONE above-baseline elastic role through the same
        generation-bumped recovery walk the recruit took. The baseline
        is the conf as declared by the operator (captured before the
        persisted elastic override), so scale-down never cuts below
        the configured topology; a gate mirrors the recruit gate so a
        ratekeeper streak surviving the walk cannot chain-retire the
        whole fleet in consecutive passes."""
        from foundationdb_tpu.cluster.generation import elastic_reason
        from foundationdb_tpu.utils.trace import SEV_WARN_ALWAYS, TraceEvent

        intervals = int(streak.get("intervals", 0))
        if intervals < self._workload_streak_observed:
            # the cold streak restarted: fresh signal, normal gate
            self._workload_gate = self.elastic_scale_down_streak
        self._workload_streak_observed = intervals
        if intervals < self._workload_gate:
            return
        for kind, conf_key in (
            ("proxy", "proxies"), ("resolver", "resolvers")
        ):
            current = int(self.conf.get(conf_key, 1))
            if current <= self._elastic_baseline[conf_key]:
                continue
            self.conf[conf_key] = current - 1
            self.elastic_scale_downs += 1
            self._rk_qos = {}
            self._workload_gate = (
                intervals + self.elastic_scale_down_streak
            )
            self._needs_recovery = True
            self._recovery_reason = elastic_reason(kind, current - 1)
            self._wake.set()
            code_probe(True, "controller.elastic_scale_down")
            TraceEvent(
                "ElasticScaleDownPlanned", severity=SEV_WARN_ALWAYS
            ).detail("Kind", kind).detail(
                "From", current
            ).detail("To", current - 1).detail(
                "StreakIntervals", intervals
            ).detail("Epoch", self.gen.epoch).log()
            return

    async def _rerecruit_singleton(self, name: str) -> None:
        """Non-transaction-path roles (storage, ratekeeper) re-recruit
        alone, no generation bump — the reference re-replicates /
        re-recruits singletons without a recovery."""
        kind = self.assignments[name]["kind"]
        live = self._live_workers()
        used = {
            a["worker_id"] for n, a in self.assignments.items() if n != name
        }
        # RE-ADOPT first: a live worker whose beacon still reports
        # hosting the kind is a slow-but-alive instance that missed
        # its polls, not a corpse — recruiting a durable role onto a
        # DIFFERENT worker while it still holds the data dir open
        # would double-open the WAL (code review r13). The beacon
        # re-announces within ~0.5s, so by the time the miss threshold
        # trips, a live instance is visible here.
        for wid in sorted(live):
            if wid not in used and kind in (live[wid].get("roles") or {}):
                self.assignments[name] = {
                    "kind": kind, "worker_id": wid,
                    "address": live[wid]["address"],
                    "epoch": self._hosted_epoch(wid, kind),
                }
                self._miss_counts[name] = 0
                return
        wid = next(
            (w for w in sorted(live) if w not in used), None
        )
        if wid is None:
            return  # monitor hasn't restarted a worker yet; next pass
        place = {
            "kind": kind, "worker_id": wid,
            "address": live[wid]["address"], "epoch": self.gen.epoch,
        }
        conf = self.conf
        if kind == "storage":
            tlog = self.assignments.get("tlog0")
            await self._init_role(place, {
                "data_dir": conf.get("storage_data_dir"),
                "storage_engine": conf.get("storage_engine", "memory"),
                "tlog_address": tlog["address"] if tlog else None,
            })
        elif kind == "ratekeeper":
            await self._init_role(place, {"peers": []})
        else:
            return
        self.assignments[name] = place
        self._miss_counts[name] = 0


class ClusterRecoveringError(Exception):
    """The cluster is between generations; retry after recovery."""


class CommitUnknownError(Exception):
    """The commit's fate is unknown (connection/generation lost mid-
    flight) — the commit_unknown_result contract: the transaction may
    or may not have committed; only an idempotent replay or a readback
    can tell."""


class ClusterClient:
    """Client-side lifecycle handle: discovers the proxy generation
    through the controller topology and survives recoveries. GRV and
    reads retry transparently across generations (they are stateless);
    commit is ONE attempt — a connection lost mid-commit surfaces
    CommitUnknownError (the reference's commit_unknown_result) because
    the batch may have logged before the crash."""

    #: process-wide client counter: successive clients start their
    #: front-door rotation at successive proxies, so a fleet of
    #: clients spreads across an N-proxy generation (ISSUE 19)
    _rr_seq = 0

    def __init__(self, controller_address: str, *,
                 recovery_timeout: float = 60.0):
        self.controller_address = controller_address
        self.recovery_timeout = recovery_timeout
        self._rr = ClusterClient._rr_seq
        ClusterClient._rr_seq += 1
        self._ctrl_conns: dict = {}  # _cached_call cache (controller)
        self._proxy: transport.RpcConnection | None = None
        #: strong refs to detached close() tasks (the loop only keeps
        #: weak task refs — without this a close could be GC'd unrun)
        self._closing: set = set()
        #: serializes _refresh: N coroutines losing the generation at
        #: once must produce ONE probe connection, not N (the census
        #: gate caught the stampede leaking every non-winner's conn)
        self._refresh_lock = asyncio.Lock()
        self.epoch = 0
        self.proxy_address: str | None = None
        self.refreshes = 0

    async def connect(self) -> None:
        # drop any current proxy first: connect() means "re-resolve the
        # generation", never "reuse whatever is cached"
        self._drop_proxy()
        await self._refresh()

    async def close(self) -> None:
        await _close_all(self._ctrl_conns)
        if self._proxy is not None:
            try:
                await self._proxy.close()
            except Exception:
                pass
        self._proxy = None
        if self._closing:
            await asyncio.gather(
                *list(self._closing), return_exceptions=True
            )

    def _drop_proxy(self) -> None:
        """Forget the current proxy connection, CLOSING it — error
        paths must not leak one transport per generation change."""
        conn = self._proxy
        self._proxy = None
        if conn is not None:
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                return
            t = loop.create_task(conn.close())
            # detached close: the loop holds only weak task refs —
            # anchor it until done or it can be GC'd before running
            self._closing.add(t)
            t.add_done_callback(self._closing.discard)

    async def topology(self) -> dict:
        import json as _json

        reply = await _cached_call(
            self._ctrl_conns, self.controller_address,
            TOKEN_TOPOLOGY, TopologyRequest(pad=0), timeout=2.0,
        )
        return _json.loads(reply.payload)

    async def _refresh(self) -> dict:
        """Poll the controller until the cluster is fully recovered and
        the proxy front door answers; reconnect to it. Bounded by
        recovery_timeout."""
        import time as _time

        from foundationdb_tpu.cluster import generation as gen

        deadline = _time.monotonic() + self.recovery_timeout
        async with self._refresh_lock:
            if self._proxy is not None:
                # a concurrent refresher won while we waited on the
                # lock: its liveness probe just passed, so reuse its
                # connection — N callers must not stampede N probes
                return {"state": gen.FULLY_RECOVERED,
                        "epoch": self.epoch}
            while True:
                topo = None
                try:
                    topo = await self.topology()
                except Exception:
                    pass
                if topo and topo.get("state") == gen.FULLY_RECOVERED:
                    proxies = [
                        e for _n, e in sorted(
                            (topo.get("roles") or {}).items()
                        )
                        if e["kind"] == "proxy"
                    ]
                    proxy = (
                        proxies[self._rr % len(proxies)]
                        if proxies else None
                    )
                    if proxy is not None:
                        conn = None
                        try:
                            conn = transport.RpcConnection(
                                proxy["address"], tls=_tls_from_env()
                            )
                            await conn.connect(retries=2, delay=0.05)
                            # liveness probe: the socket may be a
                            # corpse the controller hasn't noticed yet
                            await conn.call(
                                TOKEN_CLIENT_GRV,
                                ClientGrvRequest(pad=0),
                                timeout=5.0,
                            )
                            alive = True
                        except transport.RemoteError as e:
                            # a throttled front door IS alive
                            alive = "grv_throttled" in str(e)
                        except Exception:
                            alive = False
                        if alive:
                            self._proxy = conn
                            self.proxy_address = proxy["address"]
                            self.epoch = int(topo["epoch"])
                            self.refreshes += 1
                            return topo
                        # rotate: the next attempt probes a different
                        # proxy of the generation, not the same corpse
                        self._rr += 1
                        if conn is not None:
                            try:
                                await conn.close()
                            except Exception:
                                pass
                if _time.monotonic() > deadline:
                    raise ClusterRecoveringError(
                        f"no recovered generation within "
                        f"{self.recovery_timeout}s (topology: "
                        f"{topo and topo.get('state')})"
                    )
                await asyncio.sleep(0.1)

    async def _retryable_call(self, token: int, msg, *,
                              timeout: float = 30.0):
        """GRV/read path: retry through generation changes until the
        recovery timeout. Typed retryable errors (grv_throttled) pass
        through to the caller's backoff."""
        import time as _time

        deadline = _time.monotonic() + self.recovery_timeout
        while True:
            conn = self._proxy
            try:
                if conn is None:
                    await self._refresh()
                    conn = self._proxy
                return await conn.call(token, msg, timeout=timeout)
            except transport.RemoteError as e:
                s = str(e)
                if "grv_throttled" in s:
                    raise GrvThrottledError()
                if "not_committed" in s:
                    raise NotCommittedError(s)
                # stale epoch / failed pipeline / uninitialized worker:
                # the generation is changing under us
                self._drop_proxy()
            except (transport.TransportError, ConnectionError,
                    asyncio.TimeoutError):
                self._drop_proxy()
            if _time.monotonic() > deadline:
                raise ClusterRecoveringError(
                    f"rpc {token:#x} found no live generation within "
                    f"{self.recovery_timeout}s"
                )
            await asyncio.sleep(0.05)

    async def get_read_version(self) -> int:
        reply = await self._retryable_call(
            TOKEN_CLIENT_GRV, ClientGrvRequest(pad=0)
        )
        return reply.version

    async def read(self, key: bytes, version: int) -> Optional[bytes]:
        reply = await self._retryable_call(
            TOKEN_CLIENT_READ, ClientReadRequest(key=key, version=version)
        )
        return reply.value

    async def commit(self, txn: CommitTransaction, *,
                     timeout: float = 30.0) -> int:
        """ONE commit attempt. NotCommittedError = definitely aborted
        (safe to retry at a fresh snapshot); CommitUnknownError = the
        request was SENT and the generation/connection died mid-flight
        (only a readback can tell); ClusterRecoveringError = the
        request was never sent (no recovered generation reachable) —
        definitely not committed, safe to retry outright."""
        conn = self._proxy
        if conn is None:
            # connection setup failures happen BEFORE anything is
            # sent: surface the retryable recovering error, never
            # "unknown" — callers must not pay readback cost for a
            # commit that provably never left this process
            await self._refresh()
            conn = self._proxy
        try:
            reply = await conn.call(
                TOKEN_CLIENT_COMMIT, ClientCommitRequest(txn=txn),
                timeout=timeout,
            )
            return reply.version
        except transport.RemoteError as e:
            s = str(e)
            if "not_committed" in s:
                raise NotCommittedError(s)
            if "grv_throttled" in s:
                raise GrvThrottledError()
            self._drop_proxy()
            from foundationdb_tpu.cluster.generation import is_stale_epoch

            if is_stale_epoch(s):
                # a generation-fence rejection happens BEFORE anything
                # is appended (resolver and tlog both fence ahead of
                # the log), so this commit provably did not land —
                # retryable, no readback needed
                raise ClusterRecoveringError(s)
            raise CommitUnknownError(s)
        except (transport.TransportError, ConnectionError,
                asyncio.TimeoutError) as e:
            self._drop_proxy()
            raise CommitUnknownError(repr(e))


async def _serve_role(
    role_name: str,
    address,
    backend: str,
    data_dir: str | None = None,
    tlog_address: str | None = None,
    storage_engine: str = "memory",
    encrypt: bool = False,
    trace_file: str | None = None,
    peers: list[str] | None = None,
    controller: str | None = None,
    worker_id: str | None = None,
    cluster_conf: str | None = None,
    state_file: str | None = None,
) -> None:
    if role_name == "controller" and not trace_file:
        # monitor-spawned controllers have no per-role conf line for
        # tracing; the env var is how the chaos drill captures the
        # recovery epoch timeline (MasterRecoveryState events) durably
        trace_file = os.environ.get("FDBTPU_CONTROLLER_TRACE")
    if trace_file:
        # per-process trace sink (the reference's one-trace-file-per-
        # fdbserver): micro-events and spans land in a JSONL file that
        # scripts/commit_debug.py merges with the other roles' files —
        # cross-process timelines from a wire-mode run
        import time as _time

        from foundationdb_tpu.utils import spans as _spans
        from foundationdb_tpu.utils import trace as _tr

        sink = _tr.TraceLog(
            min_severity=_tr.SEV_DEBUG, clock=_time.time, path=trace_file
        )
        _tr.install(
            sink, _tr.TraceBatch(clock=_time.time, logger=sink, enabled=True)
        )
        _spans.set_exporter(_spans.SpanExporter(trace_log=sink))
    server = transport.RpcServer(address, tls=_tls_from_env())

    async def ping(msg: Ping) -> Pong:
        return Pong(payload=msg.payload)

    server.register(TOKEN_PING, ping)
    # --encrypt is the only switch that reaches this child process:
    # spawn_role translates the launcher's ENABLE_ENCRYPTION knob into
    # the flag (a knob read in a fresh child interpreter would always
    # be the default — dead configuration). Encryption is meaningless
    # without a data dir (nothing at rest).
    encryption = None
    if encrypt and data_dir:
        from foundationdb_tpu.crypto.at_rest import default_encryption

        encryption = default_encryption(
            kms_endpoint=os.environ.get("FDB_TPU_KMS")
        )
    if role_name == "resolver":
        role = ResolverRole(backend=backend)
        server.register(TOKEN_RESOLVE, role.resolve)

        async def rv(req: RoleVersionReq) -> RoleVersionReply:
            return RoleVersionReply(version=role.version)

        server.register(TOKEN_RESOLVER_VERSION, rv)
    elif role_name == "tlog":
        role = TLogRole(data_dir=data_dir, encryption=encryption)
        server.register(TOKEN_TLOG_PUSH, role.push)
        server.register(TOKEN_TLOG_PEEK, role.peek)
        server.register(TOKEN_TLOG_PEEK_BATCH, role.peek_batch)
        server.register(TOKEN_TLOG_VERSION, role.get_version)
        server.register(TOKEN_TLOG_LOCK, role.lock)
        server.register(TOKEN_TLOG_POP, role.pop)
    elif role_name == "storage":
        role = StorageRole(
            data_dir=data_dir, engine=storage_engine, encryption=encryption
        )
        if tlog_address:
            await role.catch_up_from_tlog(tlog_address)
        server.register(TOKEN_STORAGE_APPLY, role.apply)
        server.register(TOKEN_STORAGE_APPLY_BATCH, role.apply_batch)
        server.register(TOKEN_STORAGE_GET, role.get)
        server.register(TOKEN_STORAGE_GET_BATCH, role.get_batch)
        server.register(TOKEN_STORAGE_SNAPSHOT, role.snapshot)
        server.register(TOKEN_STORAGE_VERSION, role.get_version)
        server.register(TOKEN_STORAGE_CATCHUP, role.catch_up)
    elif role_name == "sequencer":
        role = SequencerRole()
        server.register(TOKEN_GET_COMMIT_VERSION, role.get_commit_version)
        server.register(TOKEN_REPORT_COMMITTED, role.report_committed)
        server.register(TOKEN_SEQUENCER_VERSION, role.get_version)
    elif role_name == "ratekeeper":
        role = RatekeeperRole(peers or [], controller=controller)
        server.register(TOKEN_GET_RATE_INFO, role.get_rate_info)
        await role.start()
    elif role_name == "worker":
        role = WorkerRole(
            worker_id or os.path.basename(str(address)),
            str(address),
            controller=controller,
        )
        role.register_tokens(server)
        await role.start()
    elif role_name == "controller":
        import json as _json

        conf: dict = {}
        if cluster_conf:
            with open(cluster_conf) as f:
                conf = _json.load(f)
        role = ClusterControllerRole(conf, state_file=state_file)
        server.register(TOKEN_REGISTER_WORKER, role.register_worker)
        server.register(TOKEN_TOPOLOGY, role.topology)
        server.register(TOKEN_WORKER_DEATH, role.worker_death)
        role._task = asyncio.ensure_future(role.run())
    else:
        raise ValueError(f"unknown role {role_name!r}")

    # saturation telemetry: EVERY spawned role answers StatusRequest
    # with its status block (fdbtop / wire_cluster_status poll this)
    import json as _json

    from foundationdb_tpu.runtime import census as _census

    async def status(_req: StatusRequest) -> StatusReply:
        blk = role.status()
        # per-process resource census: this role process's own live
        # fds/connections/servers plus its asyncio task count — the
        # leak gate's gauges, per role, for fdbtop's columns
        blk["census"] = {
            **_census.snapshot(),
            "tasks": len(asyncio.all_tasks()),
        }
        return StatusReply(payload=_json.dumps(blk))

    server.register(TOKEN_STATUS, status)
    await server.start()
    try:
        # run until killed
        await asyncio.Event().wait()
    finally:
        # normally unreachable except by cancellation (SIGTERM tears
        # the whole process down) — but a clean close here means the
        # in-process drills' census sees the listener go away
        await server.close()


# ---------------------------------------------------------------------------
# Launcher (parent side).


@dataclasses.dataclass
class RoleProcess:
    name: str
    address: str
    proc: subprocess.Popen

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()


def spawn_role(
    name: str,
    socket_dir: str,
    *,
    backend: str = "native",
    index: int = 0,
    data_dir: str | None = None,
    tlog_address: str | None = None,
    storage_engine: str = "memory",
    encrypt: bool = False,
    trace_file: str | None = None,
    peers: list[str] | None = None,
    controller: str | None = None,
    worker_id: str | None = None,
    cluster_conf: str | None = None,
    state_file: str | None = None,
) -> RoleProcess:
    """Start one role as a child OS process serving a UDS in socket_dir.

    Children run with JAX_PLATFORMS=cpu and a clean PYTHONPATH so they can
    never claim a TPU tunnel (the TPU belongs to the resolver process only
    when explicitly requested via backend='tpu')."""
    address = os.path.join(socket_dir, f"{name}{index}.sock")
    env = dict(os.environ)
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    if backend not in ("tpu", "tpu-force"):
        env["PYTHONPATH"] = repo_root
        env["JAX_PLATFORMS"] = "cpu"
    else:
        # tpu children keep their platform env (the tunnel sitecustomize
        # stays on PYTHONPATH) but still need the package importable
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [
        sys.executable,
        "-m",
        "foundationdb_tpu.cluster.multiprocess",
        "--role",
        name,
        "--address",
        address,
        "--backend",
        backend,
    ]
    if data_dir:
        cmd += ["--data-dir", data_dir]
    if trace_file:
        cmd += ["--trace-file", trace_file]
    if peers:
        # ratekeeper: the role sockets whose StatusRequest sensors feed
        # the admission law
        cmd += ["--peers", ",".join(peers)]
    if controller:
        cmd += ["--controller", controller]
    if worker_id:
        cmd += ["--worker-id", worker_id]
    if cluster_conf:
        cmd += ["--cluster-conf", cluster_conf]
    if state_file:
        cmd += ["--state-file", state_file]
    if tlog_address:
        cmd += ["--tlog-address", tlog_address]
    if storage_engine != "memory":
        cmd += ["--storage-engine", storage_engine]
    # knob propagation: the child is a fresh interpreter with default
    # knobs, so the launcher's ENABLE_ENCRYPTION must travel as the
    # explicit flag (code review r5 — a knob read only child-side is
    # dead configuration)
    if not encrypt:
        from foundationdb_tpu.utils.knobs import SERVER_KNOBS

        encrypt = bool(SERVER_KNOBS.ENABLE_ENCRYPTION)
    if encrypt:
        cmd += ["--encrypt"]
    proc = subprocess.Popen(cmd, env=env)
    return RoleProcess(name=name, address=address, proc=proc)


# ---------------------------------------------------------------------------
# The commit pipeline (parent process: sequencer + proxy + client API).


class NotCommittedError(Exception):
    pass


class AsyncNotified:
    """Monotone value with when_at_least — the runtime/flow `Notified`
    (NotifiedVersion) for asyncio: the wire pipeline's batch-ordering
    chains wait on it exactly like the simulated proxy's
    latest_batch_resolving / latest_batch_logging chains."""

    def __init__(self, value: int = 0):
        self._value = value
        self._waiters: list[tuple[int, asyncio.Future]] = []

    def get(self) -> int:
        return self._value

    def set(self, value: int) -> None:
        if value < self._value:
            raise ValueError(
                f"Notified must not decrease: {value} < {self._value}"
            )
        self._value = value
        still = []
        for threshold, fut in self._waiters:
            if fut.done():
                continue
            if threshold <= value:
                fut.set_result(value)
            else:
                still.append((threshold, fut))
        self._waiters = still

    async def when_at_least(self, threshold: int) -> int:
        if self._value >= threshold:
            return self._value
        fut = asyncio.get_running_loop().create_future()
        self._waiters.append((threshold, fut))
        return await fut


class PipelineFailedError(Exception):
    """A predecessor batch died mid-chain; this proxy generation is
    broken (the in-process CommitProxy's `failed` discipline)."""


# A/B toggle for the resolve-hop payload (measurement): 1 = conflict
# metadata only (default), 0 = full transactions incl. mutations.
_RESOLVE_STRIP = os.environ.get("RESOLVE_STRIP", "1") != "0"


def _resolve_columnar_default() -> bool:
    """A/B toggle for the resolve-hop FRAME (r12): 1 (default) = the
    columnar ResolveBatchColumnar frame — conflict metadata packed ONCE
    at the proxy as flat little-endian arrays + one key blob, decoded
    resolver-side with np.frombuffer straight into kernel tensors; 0 =
    the per-transaction object frame (the escape hatch, and the PR-11
    baseline path for A/B runs). Columnar applies only to the STRIPPED
    conflict-metadata hop: with RESOLVE_STRIP=0 (full transactions
    incl. mutations on the wire) the object frame always runs. Read at
    pipeline construction so one process can A/B both paths."""
    return os.environ.get("RESOLVE_COLUMNAR", "1") != "0"


class ProxyPipeline:
    """Sequencer + commit proxy over wire-connected roles.

    The 5-phase commitBatch pipeline
    (fdbserver/CommitProxyServer.actor.cpp:2516-2555) against remote
    resolver/tlog/storage processes, STAGE-OVERLAPPED: successive batches
    run concurrently through resolve -> tlog-push -> reply, ordered only
    at the Notified-chain handoffs — batch N+1's resolution is on the
    wire while batch N is logging (the resolver serializes versions by
    the prev_version chain server-side), its tlog push waits only for
    batch N's push, and client replies fire as soon as the batch's own
    push is durable. Storage applies ride a third ordered chain BEHIND
    the replies (reads wait for the storage version they need, so
    lagging applies cost read latency, never correctness) — the
    reference's storage lag. Batching is adaptive (cluster/batching.py):
    the accumulation interval shrinks while batches fill early and the
    count/bytes targets follow measured resolve+log seconds. GRV serves
    the last tlog-durable version (commit-before-GRV visibility).
    """

    def __init__(
        self,
        resolvers: list[transport.RpcConnection],
        tlog: transport.RpcConnection,
        storage: transport.RpcConnection,
        *,
        version_step: int = 1000,
        batch_interval: float = 0.002,
        max_batch: int = 512,
        start_version: int = 0,
        trace: bool = False,
        pipeline_depth: int = None,
        ratekeeper: transport.RpcConnection = None,
        rate_fetch_interval: float = 0.25,
        max_grv_queue: int = None,
        resolve_columnar: bool = None,
        epoch: int = 0,
        resolver_boundaries: list = None,
        sequencer: transport.RpcConnection = None,
        proxy_id: str = "proxy0",
        tlogs: list = None,
        tlog_boundaries: list = None,
    ):
        from foundationdb_tpu.cluster.batching import AdaptiveBatchSizer
        from foundationdb_tpu.utils.knobs import SERVER_KNOBS as _K

        self.resolvers = resolvers
        # -- commit-path scale-out (ISSUE 19): with a sequencer
        # connection, version allotment moves behind GetCommitVersion —
        # N proxy processes share the global chain, each handing the
        # grant's (prev_version, version) to the resolvers. With
        # `tlogs` + boundaries, pushes are TAG-PARTITIONED: each batch
        # pushes only to the tlogs owning its mutations' key ranges,
        # chained per tag by the grant's tag_prevs. Without a
        # sequencer, the legacy single-proxy local allocation runs
        # byte-identically.
        self.sequencer = sequencer
        self.proxy_id = proxy_id
        self._tlogs = list(tlogs) if tlogs else [tlog]
        self.tlog = self._tlogs[0]
        if tlog_boundaries and len(self._tlogs) > 1:
            if len(tlog_boundaries) != len(self._tlogs) - 1:
                raise ValueError(
                    f"{len(self._tlogs)} tlog(s) need "
                    f"{len(self._tlogs) - 1} boundary key(s), got "
                    f"{len(tlog_boundaries)}"
                )
            self._tlog_ranges = resolver_key_ranges(list(tlog_boundaries))
        else:
            self._tlog_ranges = None
        self._seq_request_num = 0
        self._seq_processed = 0
        self.version_grants = 0
        # GRV live-committed coalescer (sequencer mode): waiters that
        # arrive while a fetch is in flight ride the NEXT round, so a
        # GRV issued after a commit ack can never observe an older
        # snapshot of the sequencer's live committed version
        self._grv_waiters: list = []
        self._grv_fetching = False
        self.storage = storage
        # -- multi-resolver keyspace split (ISSUE 15): with N > 1
        # resolvers and boundaries (N-1 interior split keys, re-derived
        # by the controller on every resolver-count change), each
        # resolver receives the batch with its conflict ranges CLIPPED
        # to its partition (clip_transactions — the reference's
        # ResolutionRequestBuilder), so per-resolver conflict work
        # scales down with recruits. No boundaries (or a single
        # resolver) keeps the pre-r15 full-broadcast behavior.
        if resolver_boundaries and len(resolvers) > 1:
            if len(resolver_boundaries) != len(resolvers) - 1:
                raise ValueError(
                    f"{len(resolvers)} resolver(s) need "
                    f"{len(resolvers) - 1} boundary key(s), got "
                    f"{len(resolver_boundaries)}"
                )
            self._resolver_ranges = resolver_key_ranges(
                list(resolver_boundaries)
            )
        else:
            self._resolver_ranges = None
        #: this proxy generation's recovery epoch, stamped on every
        #: resolve frame and tlog push — resolvers/tlogs of another
        #: generation reject them retryably (stale_epoch), so a fenced
        #: old proxy can never slip a commit in after recovery
        self.epoch = epoch
        # columnar resolve frame (r12): pack the batch's conflict
        # metadata ONCE into flat arrays + one key blob at batch-build
        # time (the layout the resolver's kernel packer consumes), so
        # the resolve hop is wire bytes -> device tensors with two
        # copies total. None = the RESOLVE_COLUMNAR env default; the
        # object frame still runs with RESOLVE_STRIP=0 (mutations must
        # travel) regardless.
        self._columnar = (
            _resolve_columnar_default()
            if resolve_columnar is None
            else bool(resolve_columnar)
        ) and _RESOLVE_STRIP
        # -- admission control (the wire GRV front door): the budget is
        # fetched from the ratekeeper role over GetRateInfo and enforced
        # as an arrival-spacing token bucket with a burst cap; requests
        # whose backlog would exceed the bounded queue are SHED with the
        # retryable grv_throttled error (same contract as the sim
        # GrvProxy). No ratekeeper connection == unthrottled.
        self._rk_conn = ratekeeper
        self._rate_interval = rate_fetch_interval
        self.max_grv_queue = (
            max_grv_queue if max_grv_queue is not None
            else _K.GRV_PROXY_MAX_QUEUE
        )
        from foundationdb_tpu.cluster.ratekeeper import FAILSAFE_TAU

        self._rate_limit = float("inf")
        self._rate_floor = 1e4
        self._rate_tau = FAILSAFE_TAU
        self._rate_info: dict = {}
        self._rate_stale = False
        self._rate_failures = 0
        self._rate_task: asyncio.Task | None = None
        self._grv_next_slot = 0.0
        self.grv_sheds = 0
        self.grv_throttle_waits = 0
        #: push-based rate updates applied (ISSUE 15): the ratekeeper
        #: pushes GetRateInfo deltas past a hysteresis threshold; the
        #: poll loop stays as the backstop
        self.rate_pushes_applied = 0
        self.version_step = version_step
        self.batch_interval = batch_interval
        self.max_batch = max_batch
        self.batch_sizer = AdaptiveBatchSizer(
            interval=batch_interval,
            min_interval=min(
                batch_interval, _K.COMMIT_TRANSACTION_BATCH_INTERVAL_MIN
            ),
            # unlike the in-process proxy (whose window only shrinks, to
            # keep existing sim schedules), the wire pipeline's window
            # may GROW to the MAX knob: under a slow resolver (kernel
            # dispatch cost) the latency-fraction rule earns bigger
            # batches that amortize the per-dispatch cost
            max_interval=max(
                batch_interval, _K.COMMIT_TRANSACTION_BATCH_INTERVAL_MAX
            ),
            target_count=max_batch,
            max_count=max(
                max_batch, _K.COMMIT_TRANSACTION_BATCH_COUNT_MAX
            ),
            max_bytes=_K.COMMIT_TRANSACTION_BATCH_BYTES_MAX,
            latency_budget=_K.COMMIT_BATCH_STAGE_LATENCY_BUDGET,
            alpha=_K.COMMIT_TRANSACTION_BATCH_INTERVAL_SMOOTHER_ALPHA,
            latency_fraction=_K.COMMIT_TRANSACTION_BATCH_INTERVAL_LATENCY_FRACTION,
        )
        #: commit-path tracing: batches carry span contexts + debug ids
        #: over the wire to the resolver processes, and this process
        #: emits the CommitProxy.* micro-events (enable the global
        #: trace sinks — e.g. a TraceLog file — to persist them)
        self.trace = trace
        self._batch_seq = 0
        # a recovering proxy passes start_version = max(tlog version,
        # resolver version) so allocation resumes strictly above anything
        # any role has seen (the reference's recovery version semantics)
        self.committed_version = start_version
        self.prev_version = -1 if start_version == 0 else start_version
        self._last_allocated = start_version
        # the resolve/push version chain: batch N+1's prev_version is
        # batch N's version, assigned synchronously at spawn
        self._chain_prev = self.prev_version
        self._queue: list[tuple[CommitTransaction, asyncio.Future]] = []
        self._batcher_task: asyncio.Task | None = None
        # batch-ordering chain (batch numbers, 1-based)
        self._latest_batch_logging = AsyncNotified(0)
        self._inflight: set[asyncio.Task] = set()
        self._depth = asyncio.Semaphore(
            pipeline_depth
            if pipeline_depth is not None
            else _K.MAX_PIPELINED_COMMIT_BATCHES
        )
        self.failed: Optional[BaseException] = None
        self._loop: asyncio.AbstractEventLoop | None = None
        # ordered apply queue: (version, mutations, prev_version)
        # appended in commit order at reply time, drained by ONE
        # applier task in batched StorageApplyBatch RPCs — replies never wait on storage, and
        # the storage version trails the committed version by at most
        # one drain roundtrip (the reference's bounded storage lag)
        self._apply_queue: list[tuple[int, list, int]] = []
        self._apply_event: asyncio.Event | None = None
        self._applier_task: asyncio.Task | None = None
        self.applied_version = start_version
        self._last_enqueued_apply = start_version
        # read coalescer: every read issued in the same event-loop turn
        # rides one StorageGetBatch RPC (per-key versions, exact MVCC)
        self._read_pending: list = []
        self._read_flush_scheduled = False
        # -- saturation sensors (the parent process plays BOTH proxies
        # in wire mode: commit batching here, GRV at get_read_version)
        from foundationdb_tpu.utils.metrics import TimerSmoother

        self._batches_inflight = 0
        self.smoothed_queue_depth = TimerSmoother(1.0)
        self.smoothed_grv_rate = TimerSmoother(1.0)
        self.grvs_served = 0
        # busiest-write-tag tracker (ISSUE 20): the commit-side
        # TransactionTagCounter twin — wall clock, like every other
        # wire-role sensor
        from foundationdb_tpu.cluster.sampling import TagCounter

        self.write_tags = TagCounter()

    def start(self) -> None:
        self._loop = asyncio.get_event_loop()
        self._apply_event = asyncio.Event()
        self._batcher_task = asyncio.ensure_future(self._batcher())
        self._applier_task = asyncio.ensure_future(self._applier())
        if self._rk_conn is not None:
            self._rate_task = asyncio.ensure_future(self._rate_fetcher())

    async def stop(self) -> None:
        if self._rate_task:
            self._rate_task.cancel()
            try:
                await self._rate_task
            except asyncio.CancelledError:
                pass
            self._rate_task = None
        if self._batcher_task:
            self._batcher_task.cancel()
            try:
                await self._batcher_task
            except asyncio.CancelledError:
                pass
            self._batcher_task = None
        # drain in-flight batches: their replies must not die with the
        # pipeline (and tests must not leak pending tasks)
        if self._inflight:
            await asyncio.gather(
                *list(self._inflight), return_exceptions=True
            )
        # flush the apply queue so storage converges to committed state
        # before the roles go down (consistency checks snapshot here);
        # applied_version advances only after the batch RPC is acked, so
        # this cannot cancel a drain mid-roundtrip
        if self._applier_task:
            while (
                self.applied_version < self._last_enqueued_apply
                and self.failed is None
                and not self._applier_task.done()
            ):
                self._apply_event.set()
                await asyncio.sleep(0.001)
            self._applier_task.cancel()
            try:
                await self._applier_task
            except asyncio.CancelledError:
                pass
            self._applier_task = None

    async def _rate_fetcher(self) -> None:
        """Budget-fetch loop (GetRateInfoRequest cadence). A ratekeeper
        that stops answering FAILS SAFE: after two consecutive misses
        the effective budget decays exponentially toward the
        conservative floor — a dead ratekeeper must clamp the front
        door, never freeze it at full speed."""
        import json as _json
        import math as _math
        import time as _time

        last = _time.monotonic()
        while True:
            now = _time.monotonic()
            dt = max(0.0, now - last)
            last = now
            try:
                rep = await self._rk_conn.call(
                    TOKEN_GET_RATE_INFO, GetRateInfoRequest(pad=0),
                    timeout=2.0,
                )
                self.apply_rate_info(_json.loads(rep.payload))
            except asyncio.CancelledError:
                raise
            except Exception:
                self._rate_failures += 1
                if self._rate_failures >= 2:
                    self._rate_stale = True
                    if self._rate_limit == float("inf"):
                        self._rate_limit = self._rate_floor
                    else:
                        self._rate_limit = max(
                            self._rate_floor,
                            self._rate_limit
                            * _math.exp(-dt / self._rate_tau),
                        )
            await asyncio.sleep(self._rate_interval)

    def apply_rate_info(self, info: dict) -> None:
        """Apply one GetRateInfo payload — shared by the poll loop and
        the ratekeeper's push path (ISSUE 15). A push counts as a fresh
        feed: it clears the staleness/decay state exactly like a
        successful poll, so during overload onset the enforced budget
        tracks the control loop at one control-cycle latency instead of
        the fetch cadence."""
        self._rate_limit = float(info["transactions_per_second_limit"])
        self._rate_floor = float(info.get("failsafe_tps", self._rate_floor))
        self._rate_tau = float(info.get("failsafe_tau", self._rate_tau))
        self._rate_info = info
        self._rate_failures = 0
        self._rate_stale = False

    def _grv_backlog(self) -> int:
        """Requests currently parked in the admission throttle (the
        token schedule's lead over now, in request slots) — the wire
        GRV front door's queue-depth sensor."""
        import time as _time

        rate = self._rate_limit
        if self._rk_conn is None or rate == float("inf"):
            return 0
        return max(
            0, int((self._grv_next_slot - _time.monotonic()) * rate)
        )

    async def _grv_admit(self) -> None:
        """Arrival-spacing token bucket: each admit takes the next
        1/rate-spaced slot; the slot may lag `now` by up to the burst
        allowance (0.1s of budget), and a backlog past the bounded
        queue sheds with the retryable grv_throttled error."""
        import time as _time

        from foundationdb_tpu.cluster.grv_proxy import GrvThrottledError

        rate = self._rate_limit
        if rate == float("inf"):
            return
        rate = max(rate, 1e-3)
        now = _time.monotonic()
        burst = max(1.0, rate * 0.1)
        slot = max(self._grv_next_slot, now - burst / rate) + 1.0 / rate
        backlog = slot - now
        if backlog * rate > self.max_grv_queue:
            # the slot is NOT consumed: a shed request must not push
            # the schedule further out for the next arrival
            self.grv_sheds += 1
            raise GrvThrottledError()
        self._grv_next_slot = slot
        if backlog > 0:
            self.grv_throttle_waits += 1
            await asyncio.sleep(backlog)

    async def get_read_version(self) -> int:
        if self._rk_conn is not None:
            # admission control gates HERE and only here: an admitted
            # transaction's resolve/commit path is byte-identical to
            # the unthrottled one (decision parity)
            await self._grv_admit()
        self.grvs_served += 1
        self.smoothed_grv_rate.add_delta(1.0)
        if self.sequencer is not None:
            # N proxies: this proxy's local committed head misses the
            # other proxies' commits — serve the sequencer's live
            # committed version (coalesced: one in-flight fetch serves
            # every waiter of its round)
            return max(
                await self._live_committed(), self.committed_version
            )
        return self.committed_version

    async def _live_committed(self) -> int:
        loop = self._loop or asyncio.get_event_loop()
        fut = loop.create_future()
        self._grv_waiters.append(fut)
        if not self._grv_fetching:
            self._grv_fetching = True
            t = asyncio.ensure_future(self._live_committed_rounds())
            self._inflight.add(t)
            t.add_done_callback(self._inflight.discard)
        return await fut

    async def _live_committed_rounds(self) -> None:
        """Serve queued GRV waiters in rounds: a waiter only rides a
        fetch that STARTS after it queued, so commit-then-GRV ordering
        holds across proxies (the commit was reported to the sequencer
        before its client ack)."""
        try:
            while self._grv_waiters:
                waiters, self._grv_waiters = self._grv_waiters, []
                try:
                    rep = await self.sequencer.call(
                        TOKEN_REPORT_COMMITTED,
                        ReportRawCommittedVersionRequest(
                            version=-1, epoch=self.epoch
                        ),
                        timeout=5.0,
                    )
                    for f in waiters:
                        if not f.done():
                            f.set_result(rep.live_version)
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    for f in waiters:
                        if not f.done():
                            f.set_exception(transport.RemoteError(
                                f"grv live-committed fetch: {e!r}"
                            ))
        finally:
            self._grv_fetching = False
            for f in self._grv_waiters:
                if not f.done():
                    f.set_exception(transport.RemoteError(
                        "grv live-committed fetch cancelled"
                    ))
            self._grv_waiters = []

    # -- saturation sensors ------------------------------------------------

    def saturation(self) -> dict:
        """The wire commit proxy's qos block: in-flight batch depth
        (the stage-overlap window), queued requests (smoothed +
        instantaneous), the apply backlog behind the replies, and the
        AdaptiveBatchSizer's live interval/count/bytes targets."""
        return {
            "inflight_batches": self._batches_inflight,
            "queued_requests": len(self._queue),
            "smoothed_queued_requests": (
                self.smoothed_queue_depth.smooth_total()
            ),
            "batches_started": self._batch_seq,
            "batches_logged": self._latest_batch_logging.get(),
            "apply_backlog_versions": max(
                0, self._last_enqueued_apply - self.applied_version
            ),
            "apply_queue_batches": len(self._apply_queue),
            "read_backlog_keys": len(self._read_pending),
            "batch_sizer": self.batch_sizer.as_dict(),
            "failed": self.failed is not None,
            "version_grants": self.version_grants,
            "tag_partitioned": self._tlog_ranges is not None,
            "busiest_write_tag": self.write_tags.busiest(),
        }

    def grv_saturation(self) -> dict:
        """The wire GRV front door's qos block (this process serves
        read versions directly off the committed head)."""
        return {
            # the admission throttle's backlog: callers parked inside
            # _grv_admit waiting for their token slot. Without a
            # ratekeeper the front door answers synchronously (the
            # read-coalescer backlog is the proxy block's
            # read_backlog_keys) — then this is genuinely 0.
            "queued_requests": self._grv_backlog(),
            "grvs_served": self.grvs_served,
            "grv_per_s": self.smoothed_grv_rate.smooth_rate(),
            "committed_version": self.committed_version,
            "applied_version": self.applied_version,
            # admission-control surface (None == unthrottled: no
            # ratekeeper connection configured)
            "transactions_per_second_limit": (
                self._rate_limit
                if self._rate_limit != float("inf") else None
            ),
            "budget_limited_by": self._rate_info.get("budget_limited_by"),
            "budget_stale": self._rate_stale,
            "sheds": self.grv_sheds,
            "throttle_waits": self.grv_throttle_waits,
            "rate_pushes_applied": self.rate_pushes_applied,
            "max_queue": self.max_grv_queue,
        }

    async def commit(self, txn: CommitTransaction) -> int:
        """Returns the commit version or raises NotCommittedError."""
        loop = self._loop or asyncio.get_event_loop()
        fut = loop.create_future()
        if self.failed is not None:
            fut.set_exception(
                transport.RemoteError(
                    f"commit pipeline failed: {self.failed!r}"
                )
            )
            return await fut
        # busiest-write-tag sensor: note at the front door (per offered
        # mutation, like the reference proxy's TransactionTagCounter —
        # throttling decisions must see load BEFORE conflict verdicts)
        from foundationdb_tpu.cluster.sampling import tag_of_key

        for m in txn.mutations:
            key = getattr(m, "param1", None)
            if key is None and isinstance(m, (tuple, list)) and len(m) >= 3:
                key = m[1]
            if not isinstance(key, bytes):
                continue
            val = getattr(m, "param2", None)
            if val is None and isinstance(m, (tuple, list)) and len(m) >= 3:
                val = m[2]
            nb = 8 + len(key) + (len(val) if isinstance(val, bytes) else 0)
            self.write_tags.note(tag_of_key(key), nb)
        self._queue.append((txn, fut))
        return await fut

    async def read(self, key: bytes, version: int) -> Optional[bytes]:
        """Versioned point read, coalesced: reads enqueued in the same
        event-loop turn go out as ONE StorageGetBatch roundtrip (each
        key still served at its own version server-side)."""
        loop = self._loop or asyncio.get_event_loop()
        fut = loop.create_future()
        self._read_pending.append((key, version, fut))
        if not self._read_flush_scheduled:
            self._read_flush_scheduled = True
            loop.call_soon(self._flush_reads)
        return await fut

    def _flush_reads(self) -> None:
        self._read_flush_scheduled = False
        pending, self._read_pending = self._read_pending, []
        if pending:
            t = asyncio.ensure_future(self._read_batch(pending))
            self._inflight.add(t)
            t.add_done_callback(self._inflight.discard)

    async def _read_batch(self, pending) -> None:
        try:
            rep = await self.storage.call(
                TOKEN_STORAGE_GET_BATCH,
                StorageGetBatch(
                    versions=[v for _k, v, _f in pending],
                    keys=[k for k, _v, _f in pending],
                ),
                timeout=30.0,
            )
            for (_k, _v, fut), val in zip(pending, rep.values):
                if not fut.done():
                    fut.set_result(val)
        except Exception as e:
            for _k, _v, fut in pending:
                if not fut.done():
                    fut.set_exception(
                        transport.RemoteError(f"read batch: {e!r}")
                    )

    async def _applier(self) -> None:
        """Single ordered drain of the apply queue: many versions per
        StorageApplyBatch RPC. Append order IS commit order (appends
        happen synchronously after each batch's logging-chain set)."""
        while True:
            await self._apply_event.wait()
            self._apply_event.clear()
            while self._apply_queue:
                q, self._apply_queue = self._apply_queue, []
                try:
                    apply_rep = await self.storage.call(
                        TOKEN_STORAGE_APPLY_BATCH,
                        StorageApplyBatch(
                            versions=[v for v, _m, _p in q],
                            groups=[m for _v, m, _p in q],
                            # sequencer mode: ship the global grant
                            # chain so storage orders interleaved
                            # per-proxy appliers; legacy mode sends no
                            # prevs (queue order IS version order and
                            # failed batches legally hole the chain)
                            prev_versions=(
                                [p for _v, _m, p in q]
                                if self.sequencer is not None else ()
                            ),
                        ),
                        timeout=30.0,
                    )
                except Exception as e:
                    if self.failed is None:
                        self.failed = e
                    return
                self.applied_version = q[-1][0]
                if self.trace:
                    from foundationdb_tpu.utils import commit_debug as _cdbg
                    from foundationdb_tpu.utils import trace as _tr

                    for v, m, _p in q:
                        if m:
                            _tr.g_trace_batch.add_event(
                                "CommitDebug", _cdbg.version_id(v),
                                _cdbg.STORAGE_APPLIED,
                            )
                # storage holds this prefix DURABLY (reply durable=1 —
                # the store write-ahead-logs its applies): pop the
                # tlog so its disk queue stays tail-sized (restart
                # recovery cost ∝ tail, not history). A memory-only
                # store never earns a pop: the tlog would be the only
                # durable copy of committed mutations. Advisory — a
                # pop failure (e.g. a mid-recovery fence) must never
                # fail the pipeline — and LAST in the drain round, so
                # a teardown cancellation parked here can't eat the
                # batch's trace events above.
                if not getattr(apply_rep, "durable", 0):
                    continue
                for tl in self._tlogs:
                    try:
                        await tl.call(
                            TOKEN_TLOG_POP,
                            TLogPop(
                                version=self.applied_version,
                                epoch=self.epoch,
                            ),
                            timeout=5.0,
                        )
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        pass

    async def _batcher(self) -> None:
        from foundationdb_tpu.cluster.batching import commit_txn_bytes

        while True:
            await asyncio.sleep(self.batch_sizer.interval)
            if not self._queue:
                continue
            sizer = self.batch_sizer
            count_target = min(sizer.target_count, self.max_batch)
            take, nbytes = 0, 0
            for txn, _f in self._queue:
                if take >= count_target or nbytes >= sizer.target_bytes:
                    break
                take += 1
                nbytes += commit_txn_bytes(txn)
            batch, self._queue = self._queue[:take], self._queue[take:]
            was_full = bool(self._queue) or take >= count_target
            if was_full:
                sizer.batch_full()
            else:
                sizer.batch_underfull(take)
            # bounded pipeline depth: acquire BEFORE allocating the
            # version so a stalled chain backpressures the batcher
            # instead of growing an unbounded in-flight set
            await self._depth.acquire()
            self._batch_seq += 1
            num = self._batch_seq
            # phase 1, at spawn: version allocation. Sequencer mode
            # awaits a GetCommitVersion grant — the batcher is the sole
            # caller, so request_nums are issued in order and the
            # resolve/push stages of successive batches still overlap
            # (only the allotment RPC is serial, as in the reference).
            # Legacy mode allocates locally, synchronously (monotonic
            # across failed attempts — a dead batch consumed its
            # version; the reference master never re-hands one).
            tag_info = None
            if self.sequencer is not None:
                tags = self._batch_tags([t for t, _f in batch])
                try:
                    grant = await self._get_commit_version(tags)
                except Exception as e:
                    # an unreachable sequencer breaks the chain for
                    # this proxy generation: fail fast and retryably
                    if self.failed is None:
                        self.failed = e
                    for _txn, fut in batch:
                        if not fut.done():
                            fut.set_exception(transport.RemoteError(
                                f"commit pipeline: {e!r}"
                            ))
                    self._depth.release()
                    self._batch_seq -= 1
                    return
                version, prev_version = grant.version, grant.prev_version
                self._last_allocated = version
                self._chain_prev = version
                tag_info = (tags, dict(zip(tags, grant.tag_prevs)))
            else:
                version = (
                    max(self.committed_version, self._last_allocated)
                    + self.version_step
                )
                self._last_allocated = version
                prev_version, self._chain_prev = self._chain_prev, version
            t = asyncio.ensure_future(
                self._commit_batch(batch, num, prev_version, version,
                                   was_full, tag_info)
            )
            self._inflight.add(t)
            self._batches_inflight += 1
            self.smoothed_queue_depth.set_total(len(self._queue))

            def _done(_f, t=t):
                self._inflight.discard(t)
                self._batches_inflight -= 1
                self._depth.release()

            t.add_done_callback(_done)

    async def _commit_batch(
        self, batch, num, prev_version, version, was_full, tag_info=None
    ) -> None:
        try:
            await self._commit_batch_traced(
                batch, num, prev_version, version, was_full, tag_info
            )
        except Exception as e:
            # A hole in the version chain breaks this proxy generation:
            # fail the batch's clients, mark the pipeline failed, and
            # advance the ordering chains so successors fail fast
            # instead of wedging on when_at_least forever.
            if self.failed is None:
                self.failed = e
            for _txn, fut in batch:
                if not fut.done():
                    fut.set_exception(
                        transport.RemoteError(f"commit pipeline: {e!r}")
                    )
            if num > self._latest_batch_logging.get():
                self._latest_batch_logging.set(num)

    async def _commit_batch_traced(
        self, batch, num, prev_version, version, was_full, tag_info=None
    ) -> None:
        if not self.trace:
            await self._commit_batch_impl(
                batch, num, prev_version, version, was_full, None, None,
                tag_info,
            )
            return
        from foundationdb_tpu.utils import commit_debug as _cdbg
        from foundationdb_tpu.utils import trace as _tr
        from foundationdb_tpu.utils.spans import Span

        dbg = f"pipe-b{num}"
        for t, _f in batch:
            if t.debug_id is not None:
                _tr.g_trace_batch.add_attach(
                    "CommitAttachID", t.debug_id, dbg
                )
        _tr.g_trace_batch.add_event("CommitDebug", dbg, _cdbg.BATCH_BEFORE)
        with Span("ProxyPipeline.commitBatch") as span:
            span.attribute("Txns", len(batch))
            await self._commit_batch_impl(
                batch, num, prev_version, version, was_full, dbg, span,
                tag_info,
            )

    # -- tag partitioning (ISSUE 19) -----------------------------------

    def _tag_of_key(self, key: bytes) -> int:
        """The tlog index owning `key` — the same even byte-prefix
        partition formula as the resolver split (the ranges come from
        default_resolver_boundaries over the tlog count)."""
        for i, (lo, hi) in enumerate(self._tlog_ranges):
            if key >= lo and (hi is None or key < hi):
                return i
        return len(self._tlog_ranges) - 1

    def _mutation_tags(self, m) -> list:
        """Owning tlog indices for one mutation: a SET has one owner; a
        CLEAR_RANGE touches every partition it intersects."""
        if m.op == StorageRole.MUT_CLEAR_RANGE:
            out = []
            for i, (lo, hi) in enumerate(self._tlog_ranges):
                if m.param1 < (hi if hi is not None else m.param1 + b"\x00") \
                        and (m.param2 > lo):
                    out.append(i)
            return out
        return [self._tag_of_key(m.param1)]

    def _batch_tags(self, txns) -> list:
        """Declared tags for a batch = owners of every txn's mutations,
        computed BEFORE resolution (an aborted txn's declared tag still
        gets its empty push — the per-tag chain must stay gapless
        whether or not the data survives the conflict check)."""
        if self._tlog_ranges is None:
            return [0] if len(self._tlogs) == 1 else list(
                range(len(self._tlogs))
            )
        tags = set()
        for t in txns:
            for m in t.mutations:
                tags.update(self._mutation_tags(m))
        if not tags:
            tags.add(0)  # empty batches keep tag 0's chain warm
        return sorted(tags)

    def _split_mutations(self, mutations, tags) -> dict:
        """Partition a batch's committed mutations by owning tlog.
        CLEAR_RANGEs are CLIPPED to each owner's range so recovery's
        multi-tlog merge concatenates disjoint pieces."""
        groups = {t: [] for t in tags}
        if self._tlog_ranges is None:
            for t in tags:
                groups[t] = list(mutations)
            return groups
        for m in mutations:
            if m.op == StorageRole.MUT_CLEAR_RANGE:
                for i in self._mutation_tags(m):
                    if i not in groups:
                        continue
                    lo, hi = self._tlog_ranges[i]
                    cb = m.param1 if m.param1 > lo else lo
                    ce = (
                        m.param2 if hi is None or m.param2 < hi else hi
                    )
                    if cb < ce:
                        groups[i].append(
                            codec.Mutation(m.op, cb, ce)
                        )
            else:
                i = self._tag_of_key(m.param1)
                if i in groups:
                    groups[i].append(m)
        return groups

    async def _get_commit_version(self, tags):
        self._seq_request_num += 1
        rn = self._seq_request_num
        # classification boundary is the batcher's grant try/except:
        # a failed grant fails the batch's clients retryably
        rep = await self.sequencer.call(  # flowcheck: ignore[wire.unclassified-error]
            TOKEN_GET_COMMIT_VERSION,
            GetCommitVersionRequest(
                proxy_id=self.proxy_id,
                request_num=rn,
                most_recent_processed=self._seq_processed,
                epoch=self.epoch,
                tags=tags,
            ),
            timeout=30.0,
        )
        self._seq_processed = rn
        self.version_grants += 1
        return rep

    async def _commit_batch_impl(
        self, batch, num, prev_version, version, was_full, dbg, span,
        tag_info=None,
    ) -> None:
        if self.failed is not None:
            raise PipelineFailedError(repr(self.failed))
        loop = asyncio.get_event_loop()
        txns = [t for t, _f in batch]
        if dbg is not None:
            from foundationdb_tpu.utils import commit_debug as _cdbg
            from foundationdb_tpu.utils import trace as _tr

            _tr.g_trace_batch.add_event(
                "CommitDebug", dbg, _cdbg.BATCH_GOT_VERSION
            )
        # phase 2: resolution — fired IMMEDIATELY (no wait on batch N:
        # the resolver's own prev_version chain serializes versions
        # server-side, Resolver.actor.cpp:269-290), so batch N+1's
        # resolve overlaps batch N's logging. All resolvers see the full
        # batch; verdicts min-combine (CommitProxyServer:1551-1567).
        # The resolve hop carries CONFLICT METADATA only — ranges, read
        # snapshot, per-txn debug id — never the data mutations, which
        # stay proxy-side for the tlog push (the resolver's verdict
        # doesn't read them): mutation bytes off the wire roughly
        # halves resolve encode+decode for write-heavy batches. On the
        # columnar path (default) that metadata packs ONCE into the
        # flat interval-array layout the resolver kernel consumes —
        # per-txn counts + versions + one joined key blob — instead of
        # per-txn objects the resolver would re-flatten.
        # the multi-resolver split applies on the stripped
        # conflict-metadata hop only: with RESOLVE_STRIP=0 (mutations
        # on the wire for A/B) every resolver still needs the full
        # transactions, so the split degrades to the broadcast
        if self._resolver_ranges is not None and _RESOLVE_STRIP:
            txn_views = [
                clip_transactions(txns, lo, hi)
                for lo, hi in self._resolver_ranges
            ]
        else:
            txn_views = None
        span_tuple = span.context.as_tuple() if span is not None else None
        if self._columnar:
            from foundationdb_tpu.utils import packing as _packing

            def columnar_req(view):
                return codec.ResolveBatchColumnar(
                    prev_version=prev_version,
                    version=version,
                    last_received_version=prev_version,
                    epoch=self.epoch,
                    cols=_packing.pack_columnar(view),
                    debug_id=dbg,
                    span=span_tuple,
                )

            if txn_views is None:
                reqs = [columnar_req(txns)] * len(self.resolvers)
            else:
                reqs = [columnar_req(view) for view in txn_views]
            if dbg is not None:
                _tr.g_trace_batch.add_event(
                    "CommitDebug", dbg, _cdbg.PROXY_COLUMNAR_PACK
                )
        else:
            def object_req(view):
                return ResolveTransactionBatchRequest(
                    prev_version=prev_version,
                    version=version,
                    last_received_version=prev_version,
                    epoch=self.epoch,
                    transactions=view,
                    debug_id=dbg,
                    span=span_tuple,
                )

            if txn_views is not None:
                reqs = [object_req(view) for view in txn_views]
            elif _RESOLVE_STRIP:
                reqs = [object_req([
                    CommitTransaction(
                        read_conflict_ranges=t.read_conflict_ranges,
                        write_conflict_ranges=t.write_conflict_ranges,
                        read_snapshot=t.read_snapshot,
                        report_conflicting_keys=t.report_conflicting_keys,
                        debug_id=t.debug_id,
                    )
                    for t in txns
                ])] * len(self.resolvers)
            else:
                reqs = [object_req(txns)] * len(self.resolvers)
        t_resolve = loop.time()
        # classification boundary is _commit_batch: any pipeline
        # exception marks self.failed and fans RemoteError("commit
        # pipeline: ...") out to every queued client future
        replies = await asyncio.gather(
            *(r.call(TOKEN_RESOLVE, req, timeout=30.0)  # flowcheck: ignore[wire.unclassified-error]
              for r, req in zip(self.resolvers, reqs))
        )
        resolve_s = loop.time() - t_resolve
        if dbg is not None:
            _tr.g_trace_batch.add_event(
                "CommitDebug", dbg, _cdbg.BATCH_AFTER_RESOLUTION
            )
        verdicts = [
            min(int(rep.committed[i]) for rep in replies)
            for i in range(len(txns))
        ]
        # phase 3: collect committed mutations
        mutations = []
        for t, v in zip(txns, verdicts):
            if v == TransactionResult.COMMITTED:
                mutations.extend(t.mutations)
        # phase 4: log — ordered at the logging chain hand-off only
        if dbg is not None:
            _tr.TraceEvent(
                "CommitDebugVersion", severity=_tr.SEV_DEBUG
            ).detail("ID", dbg).detail("Version", version).detail(
                "Messages", 1 if mutations else 0
            ).log()
        await self._latest_batch_logging.when_at_least(num - 1)
        if self.failed is not None:
            raise PipelineFailedError(repr(self.failed))
        t_log = loop.time()
        # classification boundary is _commit_batch (same fan-out as the
        # resolve gather above)
        if tag_info is not None:
            # tag-partitioned push: each declared tlog gets ONLY its
            # tag's mutations, chained by the grant's per-tag prev.
            # Declared-but-empty tags (mutations died in the conflict
            # check or clipped empty) still get their empty push — the
            # per-tag chain must advance for every granted version that
            # declared the tag, or a later push would wedge on the gap.
            tags, tag_prevs = tag_info
            groups = self._split_mutations(mutations, tags)
            await asyncio.gather(*(
                self._tlogs[tg].call(  # flowcheck: ignore[wire.unclassified-error]
                    TOKEN_TLOG_PUSH,
                    TLogPush(
                        version=version,
                        prev_version=tag_prevs[tg],
                        mutations=groups[tg],
                        epoch=self.epoch,
                    ),
                    timeout=30.0,
                )
                for tg in tags
            ))
        else:
            await self.tlog.call(  # flowcheck: ignore[wire.unclassified-error]
                TOKEN_TLOG_PUSH,
                TLogPush(
                    version=version,
                    prev_version=prev_version,
                    mutations=mutations,
                    epoch=self.epoch,
                ),
                timeout=30.0,
            )
        if self.sequencer is not None:
            # report BEFORE the client replies: any later GRV — from
            # ANY proxy — must observe this version (the reference's
            # ReportRawCommittedVersion ordering)
            await self.sequencer.call(  # flowcheck: ignore[wire.unclassified-error]
                TOKEN_REPORT_COMMITTED,
                ReportRawCommittedVersionRequest(
                    version=version, epoch=self.epoch
                ),
                timeout=30.0,
            )
        log_s = loop.time() - t_log
        if dbg is not None:
            _tr.g_trace_batch.add_event(
                "CommitDebug", dbg, _cdbg.TLOG_AFTER_COMMIT
            )
            _tr.g_trace_batch.add_event(
                "CommitDebug", dbg, _cdbg.BATCH_AFTER_LOG_PUSH
            )
        self.prev_version = version
        self.committed_version = version
        # guarded like the error path: a FAILED successor batch advances
        # the chain past us (fail-fast for its own successors), and an
        # unguarded set(num) here would raise Notified-must-not-decrease
        # AFTER our push is durable — turning a committed batch into a
        # client error and skipping its storage apply while
        # committed_version already advanced (reads at our GRV would
        # wedge server-side until the RPC timeout)
        if num > self._latest_batch_logging.get():
            self._latest_batch_logging.set(num)
        self.batch_sizer.observe_stage_latency(
            resolve_s + log_s, full=was_full
        )
        # phase 5: replies fire as soon as OUR push is durable — no
        # wait for storage. The chain hand-off above makes replies
        # version-ordered: batch N's reply loop runs synchronously
        # after set(num=N) and before N+1 can resume from its wait.
        for (txn, fut), v in zip(batch, verdicts):
            if fut.done():
                continue
            if v == TransactionResult.COMMITTED:
                fut.set_result(version)
            else:
                fut.set_exception(NotCommittedError(TransactionResult(v).name))
        # phase 6: storage apply rides the applier's ordered queue
        # BEHIND the replies (the storage pull loop collapsed into a
        # batched ordered push; versioned reads wait server-side for the
        # version they need, so a lagging apply costs read latency,
        # never correctness). Appended with no await since the logging
        # set above — queue order IS commit order.
        self._apply_queue.append((version, mutations, prev_version))
        self._last_enqueued_apply = version
        self._apply_event.set()


def _tls_from_env():
    """Cluster TLS the way the reference's fdbserver picks it up from
    TLSConfig/environment (flow/TLSConfig.actor.cpp:
    TLS_CERTIFICATE_FILE etc.): FDB_TPU_TLS_DIR names a directory with
    ca.crt + node.crt/node.key (crypto.tls.make_test_tls layout); all
    roles and clients then speak mutual TLS under that CA."""
    tls_dir = os.environ.get("FDB_TPU_TLS_DIR")
    if not tls_dir:
        return None
    from foundationdb_tpu.crypto.tls import TLSConfig

    return TLSConfig(
        ca_file=os.path.join(tls_dir, "ca.crt"),
        cert_file=os.path.join(tls_dir, "node.crt"),
        key_file=os.path.join(tls_dir, "node.key"),
    )


async def connect(address, **kw) -> transport.RpcConnection:
    conn = transport.RpcConnection(address, tls=_tls_from_env())
    # generous default retry budget: a tpu-force resolver role warm-
    # compiles its kernels BEFORE binding the socket (so the compile
    # stall can never hide inside the first commit batch), which can
    # take tens of seconds on a cold jit cache
    kw.setdefault("retries", 1200)
    await conn.connect(**kw)
    return conn


# ---------------------------------------------------------------------------
# Wire-mode status aggregation (the fdbtop substrate).


def _pipeline_status_blocks(pipeline: "ProxyPipeline") -> dict[str, dict]:
    """The parent process's own process blocks: it plays both proxies
    in wire mode (commit batching + the GRV front door)."""
    from foundationdb_tpu.runtime import census as _census

    try:
        tasks = len(asyncio.all_tasks())
    except RuntimeError:  # no running loop (sync status dump)
        tasks = 0
    return {
        "proxy0": {
            "role": "commit_proxy",
            "committed_version": pipeline.committed_version,
            "qos": pipeline.saturation(),
            # the parent process's own resource census (the role
            # processes each report theirs via _serve_role's handler)
            "census": {**_census.snapshot(), "tasks": tasks},
        },
        "grv_proxy0": {
            "role": "grv_proxy",
            "qos": pipeline.grv_saturation(),
        },
    }


async def wire_cluster_status(
    roles: dict[str, transport.RpcConnection],
    pipeline: "ProxyPipeline" = None,
    *,
    lag_target: float = 2_000_000.0,
) -> dict:
    """Reference-shaped status JSON for a wire-mode cluster: one
    StatusRequest RPC per role process, plus the parent pipeline's own
    proxy blocks, assembled through the SAME qos math as the sim
    `cluster_status()` (cluster/status.py assemble_status)."""
    import json as _json

    from foundationdb_tpu.cluster.status import assemble_status

    procs: dict[str, dict] = {}
    for name, conn in roles.items():
        try:
            reply = await conn.call(
                TOKEN_STATUS, StatusRequest(pad=0), timeout=30.0
            )
        except (transport.TransportError, ConnectionError,
                asyncio.TimeoutError) as e:
            # classify: a status poll of one dead role names the role
            # instead of surfacing a raw socket error to the CLI
            raise transport.RemoteError(
                f"status poll of role {name!r} failed: {e!r}"
            ) from e
        procs[name] = _json.loads(reply.payload)
    if pipeline is not None:
        procs.update(_pipeline_status_blocks(pipeline))
    return assemble_status(procs, lag_target=lag_target)


def serve_status(
    socket_dir: str, pipeline: "ProxyPipeline"
) -> transport.RpcServer:
    """Parent-side status endpoint: an RpcServer on proxy0.sock in the
    role socket dir, answering StatusRequest with the pipeline's OWN
    proxy blocks — so an external fdbtop polling the socket dir sees
    the commit/GRV proxy sensors next to the role processes' (the
    parent is just another process with a status socket). Caller must
    `await server.start()` and close it at teardown."""
    import json as _json

    address = os.path.join(socket_dir, "proxy0.sock")
    server = transport.RpcServer(address, tls=_tls_from_env())

    async def status(_req: StatusRequest) -> StatusReply:
        blocks = _pipeline_status_blocks(pipeline)
        payload = blocks["proxy0"]
        # the GRV block rides along; fdbtop splits it out into its own
        # process row (one socket, both proxy roles)
        payload["grv_proxy"] = blocks["grv_proxy0"]
        return StatusReply(payload=_json.dumps(payload))

    server.register(TOKEN_STATUS, status)
    return server


def main() -> None:
    # autotune trial hook (ISSUE 15): role PROCESSES apply the same
    # FDBTPU_KNOB_OVERRIDES env points as the bench_pipeline parent —
    # a server-knob trial consumed inside a spawned role (resolver /
    # tlog / storage) must actually take effect in that process, not
    # silently run defaults while the ledger row claims otherwise
    from foundationdb_tpu.utils.knobs import SERVER_KNOBS

    SERVER_KNOBS.apply_env_overrides()
    ap = argparse.ArgumentParser()
    ap.add_argument("--role", required=True)
    ap.add_argument("--address", required=True)
    ap.add_argument("--backend", default="native")
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--tlog-address", default=None)
    ap.add_argument("--storage-engine", default="memory",
                    choices=("memory", "lsm"))
    ap.add_argument("--encrypt", action="store_true")
    ap.add_argument("--trace-file", default=None)
    ap.add_argument("--peers", default=None,
                    help="ratekeeper: comma list of peer role sockets "
                         "to poll StatusRequest sensors from")
    ap.add_argument("--controller", default=None,
                    help="worker/ratekeeper: the cluster controller's "
                         "socket (workers register + ratekeeper "
                         "re-resolves peers from its topology)")
    ap.add_argument("--worker-id", default=None,
                    help="worker: stable identity in RegisterWorker")
    ap.add_argument("--cluster-conf", default=None,
                    help="controller: JSON file with the declarative "
                         "topology (resolvers, backend, data dirs)")
    ap.add_argument("--state-file", default=None,
                    help="controller: persisted epoch (the coordinated-"
                         "state analog) so a restarted controller "
                         "always recovers into a newer generation")
    args = ap.parse_args()
    asyncio.run(
        _serve_role(
            args.role,
            args.address,
            args.backend,
            data_dir=args.data_dir,
            tlog_address=args.tlog_address,
            storage_engine=args.storage_engine,
            encrypt=args.encrypt,
            trace_file=args.trace_file,
            peers=args.peers.split(",") if args.peers else None,
            controller=args.controller,
            worker_id=args.worker_id,
            cluster_conf=args.cluster_conf,
            state_file=args.state_file,
        )
    )


if __name__ == "__main__":
    main()
