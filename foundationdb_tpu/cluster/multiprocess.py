"""Multi-process cluster: roles as OS processes over the serialized wire.

The reference runs every role in its own `fdbserver` process connected by
FlowTransport (fdbserver/worker.actor.cpp:2305-2811 spawns role actors;
fdbrpc/FlowTransport.actor.cpp carries the RPCs). This module is that
deployment shape for this framework: `python -m
foundationdb_tpu.cluster.multiprocess --role {resolver,tlog,storage}`
serves one role over wire.transport (UDS by default), and ProxyPipeline
in the parent process runs the commit pipeline against them:

    client -> GRV (sequencer, in-proxy) -> commit batching -> version
    allocation -> ResolveTransactionBatchRequest over the wire (version
    chain: prevVersion ordering, Resolver.actor.cpp:269-290) -> TLog push
    -> storage apply -> client reply

The deterministic simulator remains the other backend of the same role
interfaces (sim tests never fork processes) — the reference's
one-abstraction-two-backends discipline.

Role processes NEVER touch the TPU unless RESOLVER_BACKEND=tpu is set:
the default resolver backend is the native C++ skip-list conflict set
(no jax import at all in children).
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import os
import subprocess
import sys
from typing import Any, Optional

from foundationdb_tpu.cluster.grv_proxy import GrvThrottledError  # noqa: F401
from foundationdb_tpu.models.types import (
    CommitTransaction,
    ResolveTransactionBatchReply,
    ResolveTransactionBatchRequest,
    TransactionResult,
)
from foundationdb_tpu.wire import codec, transport

# ---------------------------------------------------------------------------
# Well-known endpoint tokens (the WellKnownEndpoints.h analog).

TOKEN_RESOLVE = 0x0101
TOKEN_TLOG_PUSH = 0x0201
TOKEN_TLOG_PEEK = 0x0202
TOKEN_STORAGE_APPLY = 0x0301
TOKEN_STORAGE_GET = 0x0302
TOKEN_STORAGE_SNAPSHOT = 0x0303
TOKEN_PING = 0x0401


# ---------------------------------------------------------------------------
# Small wire messages, declared field-by-field (codec discipline: explicit
# layouts, stable ids).

_WRITERS = {
    "u8": codec.w_u8,
    "u32": codec.w_u32,
    "i64": codec.w_i64,
    "bytes": codec.w_bytes,
    "str": codec.w_str,
    "bool": codec.w_bool,
}
_READERS = {
    "u8": codec.r_u8,
    "u32": codec.r_u32,
    "i64": codec.r_i64,
    "bytes": codec.r_bytes,
    "str": codec.r_str,
    "bool": codec.r_bool,
}


def _w_mutlist(out, ms):
    codec.w_u32(out, len(ms))
    for m in ms:
        codec.w_mutation(out, m)


def _r_mutlist(buf, off):
    n, off = codec.r_u32(buf, off)
    ms = []
    for _ in range(n):
        m, off = codec.r_mutation(buf, off)
        ms.append(m)
    return ms, off


def _w_optbytes(out, v):
    codec.w_bool(out, v is not None)
    codec.w_bytes(out, v or b"")


def _r_optbytes(buf, off):
    present, off = codec.r_bool(buf, off)
    v, off = codec.r_bytes(buf, off)
    return (v if present else None), off


def _w_kvlist(out, kvs):
    codec.w_u32(out, len(kvs))
    for k, v in kvs:
        codec.w_bytes(out, k)
        codec.w_bytes(out, v)


def _r_kvlist(buf, off):
    n, off = codec.r_u32(buf, off)
    kvs = []
    for _ in range(n):
        k, off = codec.r_bytes(buf, off)
        v, off = codec.r_bytes(buf, off)
        kvs.append((k, v))
    return kvs, off


_WRITERS["mutlist"] = _w_mutlist
_READERS["mutlist"] = _r_mutlist
_WRITERS["optbytes"] = _w_optbytes
_READERS["optbytes"] = _r_optbytes
_WRITERS["kvlist"] = _w_kvlist
_READERS["kvlist"] = _r_kvlist


def _message(type_id: int, name: str, fields: list[tuple[str, str]]):
    cls = dataclasses.make_dataclass(name, [f for f, _ in fields])

    def enc(out, m, _fields=fields):
        for f, kind in _fields:
            _WRITERS[kind](out, getattr(m, f))

    def dec(buf, off, _fields=fields, _cls=cls):
        vals = []
        for _f, kind in _fields:
            v, off = _READERS[kind](buf, off)
            vals.append(v)
        return _cls(*vals), off

    codec.register(type_id, cls, enc, dec)
    return cls


Ping = _message(0x0201, "Ping", [("payload", "bytes")])
Pong = _message(0x0202, "Pong", [("payload", "bytes")])
TLogPush = _message(
    0x0210,
    "TLogPush",
    [("version", "i64"), ("prev_version", "i64"), ("mutations", "mutlist")],
)
TLogPushReply = _message(0x0211, "TLogPushReply", [("durable_version", "i64")])
TLogPeek = _message(0x0212, "TLogPeek", [("after_version", "i64")])
TLogPeekReply = _message(
    0x0213, "TLogPeekReply", [("version", "i64"), ("mutations", "mutlist")]
)


def _w_i64list(out, vs):
    codec.w_u32(out, len(vs))
    for v in vs:
        codec.w_i64(out, v)


def _r_i64list(buf, off):
    n, off = codec.r_u32(buf, off)
    vs = []
    for _ in range(n):
        v, off = codec.r_i64(buf, off)
        vs.append(v)
    return vs, off


def _w_mutgroups(out, gs):
    codec.w_u32(out, len(gs))
    for g in gs:
        _w_mutlist(out, g)


def _r_mutgroups(buf, off):
    n, off = codec.r_u32(buf, off)
    gs = []
    for _ in range(n):
        g, off = _r_mutlist(buf, off)
        gs.append(g)
    return gs, off


_WRITERS["i64list"] = _w_i64list
_READERS["i64list"] = _r_i64list
_WRITERS["mutgroups"] = _w_mutgroups
_READERS["mutgroups"] = _r_mutgroups

TLogPeekBatchReq = _message(
    0x0214, "TLogPeekBatchReq",
    [("after_version", "i64"), ("max_entries", "u32")],
)
TLogPeekBatchReply = _message(
    0x0215, "TLogPeekBatchReply",
    [("versions", "i64list"), ("groups", "mutgroups")],
)
TOKEN_TLOG_PEEK_BATCH = 0x0204
StorageApply = _message(
    0x0220, "StorageApply", [("version", "i64"), ("mutations", "mutlist")]
)
StorageApplyReply = _message(
    0x0221, "StorageApplyReply", [("durable_version", "i64")]
)
StorageGet = _message(
    0x0222, "StorageGet", [("key", "bytes"), ("version", "i64")]
)
StorageGetReply = _message(0x0223, "StorageGetReply", [("value", "optbytes")])
StorageSnapshotReq = _message(
    0x0224, "StorageSnapshotReq", [("version", "i64")]
)
StorageSnapshotReply = _message(
    0x0225, "StorageSnapshotReply", [("version", "i64"), ("kvs", "kvlist")]
)


def _w_byteslist(out, bs):
    codec.w_u32(out, len(bs))
    for b in bs:
        codec.w_bytes(out, b)


def _r_byteslist(buf, off):
    n, off = codec.r_u32(buf, off)
    bs = []
    for _ in range(n):
        b, off = codec.r_bytes(buf, off)
        bs.append(b)
    return bs, off


def _w_optbyteslist(out, vs):
    codec.w_u32(out, len(vs))
    for v in vs:
        _w_optbytes(out, v)


def _r_optbyteslist(buf, off):
    n, off = codec.r_u32(buf, off)
    vs = []
    for _ in range(n):
        v, off = _r_optbytes(buf, off)
        vs.append(v)
    return vs, off


_WRITERS["byteslist"] = _w_byteslist
_READERS["byteslist"] = _r_byteslist
_WRITERS["optbyteslist"] = _w_optbyteslist
_READERS["optbyteslist"] = _r_optbyteslist

# Batched storage reads: every read the proxy process coalesces in one
# event-loop turn rides ONE wire roundtrip (keys[i] is served at
# versions[i] — exact MVCC semantics per key; the server waits once for
# max(versions)). The single-get RPC path stays for point reads.
StorageGetBatch = _message(
    0x0226, "StorageGetBatch",
    [("versions", "i64list"), ("keys", "byteslist")],
)
StorageGetBatchReply = _message(
    0x0227, "StorageGetBatchReply", [("values", "optbyteslist")]
)
# Batched version-ordered applies: the pipeline's applier drains its
# queue in one RPC (one WAL group fsync when persistent), keeping the
# storage version close behind the committed version so versioned
# reads don't stall on a one-RPC-per-version apply chain.
StorageApplyBatch = _message(
    0x0228, "StorageApplyBatch",
    [("versions", "i64list"), ("groups", "mutgroups")],
)
TOKEN_STORAGE_GET_BATCH = 0x0305
TOKEN_STORAGE_APPLY_BATCH = 0x0306
RoleVersionReq = _message(0x0230, "RoleVersionReq", [("pad", "u8")])
RoleVersionReply = _message(0x0231, "RoleVersionReply", [("version", "i64")])

# Saturation telemetry (fdbtop / wire_cluster_status): every spawned
# role answers StatusRequest with its status block — role kind, version,
# and the `qos` sensor dict — as a JSON document. The status schema IS
# a JSON document end to end (the reference's status JSON,
# fdbclient/Schemas.cpp); a field-by-field wire layout here would only
# re-derive JSON at the reader and ossify the sensor set.
StatusRequest = _message(0x0240, "StatusRequest", [("pad", "u8")])
StatusReply = _message(0x0241, "StatusReply", [("payload", "str")])

# Admission control over the wire (Ratekeeper.actor.cpp:475
# GetRateInfoRequest): the front door (ProxyPipeline's GRV path)
# periodically fetches the transactions-per-second budget from the
# ratekeeper role process. JSON payload for the same reason as
# StatusReply: the budget document (budget + binding limiter +
# fail-safe state) is a status-schema slice, not a hot-path message.
GetRateInfoRequest = _message(0x0242, "GetRateInfoRequest", [("pad", "u8")])
GetRateInfoReply = _message(0x0243, "GetRateInfoReply", [("payload", "str")])

TOKEN_TLOG_VERSION = 0x0203
TOKEN_STORAGE_VERSION = 0x0304
TOKEN_RESOLVER_VERSION = 0x0102
TOKEN_STATUS = 0x0501
TOKEN_GET_RATE_INFO = 0x0502


# ---------------------------------------------------------------------------
# Role servers.


def _decode_alloc_count(txns) -> int:
    """Per-batch count of the Python objects a per-transaction frame
    decode materializes — the columnar path's structural ZERO on jitted
    backends, ledger-gated by bench_pipeline (resolve_decode_allocs_
    per_txn). Mirrors r_commit_transaction's allocation sites exactly:
    per txn the CommitTransaction + its two range lists; per conflict
    range the tuple + two bytes keys; per mutation the Mutation + two
    bytes params."""
    n = 0
    for t in txns:
        n += 3 + 3 * (
            len(t.read_conflict_ranges) + len(t.write_conflict_ranges)
        ) + 3 * len(t.mutations)
    return n


class ResolverRole:
    """Wire-served resolver: version-chained conflict resolution.

    Reproduces the resolveBatch ordering contract
    (fdbserver/Resolver.actor.cpp:269-290,496): requests wait until the
    resolver's version reaches req.prev_version, resolve, then advance to
    req.version — so out-of-order arrivals from concurrent proxies are
    serialized into the global commit order. Duplicate requests (same
    version) replay the recorded reply (:515-530).
    """

    def __init__(self, backend: str = "native", window: int = 5_000_000):
        self.version = -1
        self.window = window
        self._cond: asyncio.Condition | None = None
        self._replies: dict[int, ResolveTransactionBatchReply] = {}
        self._backend = backend
        # -- saturation sensors: the reference resolver's exact four
        # distributions (Resolver.actor.cpp resolverLatencyDist /
        # queueWaitLatencyDist / computeTimeDist / queueDepthDist) on
        # the WALL clock — this is a real OS process, there is no
        # virtual clock to be deterministic against
        from foundationdb_tpu.utils.metrics import LatencySample

        from foundationdb_tpu.utils.metrics import TimerSmoother

        self._waiting = 0  # requests parked on the version chain
        # -- columnar-vs-object structural accounting (r12): the
        # "two copies" claim as gated numbers, surfaced in status() and
        # landed in the perf ledger by bench_pipeline. `copies` counts
        # full key-data materializations between the wire frame payload
        # and the conflict backend's input (each site documented where
        # it increments); `decode_allocs` counts per-transaction Python
        # objects the decode materialized (the columnar path's
        # structural zero on jitted backends).
        self.path_stats = {
            "columnar_batches": 0,
            "object_batches": 0,
            "txns": 0,
            "copies": 0,
            "decode_allocs": 0,
        }
        self.queue_depth = LatencySample("queueDepth")
        self.queue_wait_latency = LatencySample("queueWaitLatency")
        self.compute_time = LatencySample("computeTime")
        self.resolver_latency = LatencySample("resolverLatency")
        # busy-fraction smoother (the Ratekeeper's resolver-occupancy
        # input): compute seconds accumulate as a rate — a resolver
        # spending ~every wall second inside _resolve_now reads ~1.0.
        # This is the signal that catches few-huge-batch saturation,
        # where queue DEPTH stays deceptively small because the
        # blocking compute keeps arrivals out of the parked count.
        self.occupancy = TimerSmoother(2.0)
        if backend == "native":
            from foundationdb_tpu.models.conflict_set import (
                KernelStageMetrics,
            )
            from foundationdb_tpu.native import NativeSkipListConflictSet

            self._cs = NativeSkipListConflictSet(window=window)
            # the native skip list has no stage split, but the kernel
            # panel must still render (fdbtop pins it): compute seconds
            # land in the "kernel" stage and the compile-cache counters
            # are process-global anyway
            self._kernel_metrics = KernelStageMetrics()
        elif backend in ("cpu", "tpu", "tpu-force"):
            from foundationdb_tpu.config import KernelConfig

            cfg_env = os.environ.get("RESOLVER_KERNEL", "")
            kcfg = KernelConfig(
                max_key_bytes=16,
                max_txns=1024,
                max_reads=4096,
                max_writes=4096,
                history_capacity=1 << 16,
                window_versions=window,
            ) if not cfg_env else eval(cfg_env)  # noqa: S307 (operator-supplied)
            if getattr(kcfg, "n_shards", 0) > 1:
                # the mesh-sharded tiered kernel needs its devices
                # BEFORE the first backend init in this role process —
                # which happens during the conflict_set IMPORT below
                # (ops/keys.py runs an eager op at module scope), so the
                # virtual-device flag must land before that import. On a
                # real TPU slice the devices already exist.
                from foundationdb_tpu.parallel.mesh import (
                    ensure_host_device_count,
                )

                ensure_host_device_count(kcfg.n_shards)
            from foundationdb_tpu.models.conflict_set import (
                KernelStageMetrics,
                make_conflict_set,
            )

            self._cs = make_conflict_set(kcfg, backend)
            self._kernel_metrics = (
                getattr(self._cs, "metrics", None) or KernelStageMetrics()
            )
            self._warm_compile(kcfg, backend)
        else:
            raise ValueError(f"unknown resolver backend {backend!r}")

    def _warm_compile(self, kcfg, backend: str) -> None:
        """Compile the resolver kernels at ROLE STARTUP, not on the
        first commit batch: a cold jit compile (seconds) landing inside
        the first resolve request was the wire-mode tpu-force p50
        pathology (PIPELINE_r06: 18.9s) — the stall hid in commit
        latency where no ledger attributed it. A throwaway conflict set
        with the same config drives every padded-shape kernel through
        the shared module-level jit cache (shapes are G-independent, so
        one dummy resolve covers all batch sizes), and the measured
        seconds land in KernelStageMetrics.compile where cluster_status
        and commit_debug can see them."""
        import time as _time

        from foundationdb_tpu.models.conflict_set import make_conflict_set

        t0 = _time.perf_counter()
        scratch = make_conflict_set(kcfg, backend)
        scratch.resolve(
            [
                CommitTransaction(
                    read_conflict_ranges=[(b"\x00warm", b"\x00warm\x00")],
                    write_conflict_ranges=[(b"\x00warm", b"\x00warm\x00")],
                    read_snapshot=0,
                )
            ],
            1,
        )
        dt = _time.perf_counter() - t0
        metrics = getattr(self._cs, "metrics", None)
        if metrics is not None:
            metrics.compile.sample(dt)
            metrics.counters.add("warmCompiles")
        # per-signature compile seconds in the process-global compile
        # observability block (utils/compile_cache.stats)
        from foundationdb_tpu.utils import compile_cache as _cc

        _cc.record_compile(
            f"resolver_warm/{backend}/txns={kcfg.max_txns}", dt
        )
        from foundationdb_tpu.utils.trace import SEV_INFO, TraceEvent

        TraceEvent("ResolverWarmCompile", severity=SEV_INFO).detail(
            "Backend", backend
        ).detail("Seconds", round(dt, 3)).log()

    def _cond_lazy(self) -> asyncio.Condition:
        if self._cond is None:
            self._cond = asyncio.Condition()
        return self._cond

    async def resolve(self, req: ResolveTransactionBatchRequest):
        # span context propagated ACROSS the process boundary: the
        # request's (trace_id, span_id) pair arrived over the UDS wire
        # (wire/codec.py), and this role's resolveBatch span chains to
        # it — one trace spanning proxy and resolver OS processes.
        span = None
        if req.span is not None:
            from foundationdb_tpu.utils.spans import Span, SpanContext

            span = Span(
                "Resolver.resolveBatch", parent=SpanContext(*req.span)
            ).attribute("Version", req.version)
        if req.debug_id is not None:
            from foundationdb_tpu.utils import commit_debug as _cdbg
            from foundationdb_tpu.utils import trace as _tr

            _tr.g_trace_batch.add_event(
                "CommitDebug", req.debug_id, _cdbg.RESOLVER_BEFORE
            )
        try:
            return await self._resolve_ordered(req)
        finally:
            if req.debug_id is not None:
                _tr.g_trace_batch.add_event(
                    "CommitDebug", req.debug_id, _cdbg.RESOLVER_AFTER
                )
            if span is not None:
                span.finish()

    async def _resolve_ordered(self, req: ResolveTransactionBatchRequest):
        import time as _time

        t_arrive = _time.perf_counter()
        cond = self._cond_lazy()
        async with cond:
            self._waiting += 1
            self.queue_depth.sample(self._waiting)
            try:
                await cond.wait_for(
                    lambda: self.version >= req.prev_version
                )
            finally:
                self._waiting -= 1
            self.queue_wait_latency.sample(_time.perf_counter() - t_arrive)
            if req.version <= self.version:
                # duplicate (proxy retry): replay the recorded reply
                reply = self._replies.get(req.version)
                if reply is None:
                    raise transport.RemoteError(
                        f"version {req.version} already resolved and expired"
                    )
                return reply
            if req.debug_id is not None:
                from foundationdb_tpu.utils import commit_debug as _cdbg
                from foundationdb_tpu.utils import trace as _tr

                # past the version-chain wait (the reference's orderer):
                # the next mark is ColumnarDecode, so the waterfall's
                # columnar_decode stage brackets exactly the frame ->
                # kernel-tensor work
                _tr.g_trace_batch.add_event(
                    "CommitDebug", req.debug_id, _cdbg.RESOLVER_AFTER_ORDERER
                )
            t_compute = _time.perf_counter()
            reply = self._resolve_now(req)
            dt_compute = _time.perf_counter() - t_compute
            self.compute_time.sample(dt_compute)
            self.occupancy.add_delta(dt_compute)
            self.resolver_latency.sample(_time.perf_counter() - t_arrive)
            self._replies[req.version] = reply
            # retain a bounded replay window
            floor = req.version - self.window
            self._replies = {
                v: r for v, r in self._replies.items() if v >= floor
            }
            self.version = req.version
            cond.notify_all()
            return reply

    def _trace_columnar_decode(self, req) -> None:
        """The Resolver.resolveBatch.ColumnarDecode micro-event: fired
        the moment the columnar frame has become the backend's input
        (kernel tensors on jitted backends, reconstructed objects on
        the object fallback) — with AfterOrderer as the opening mark,
        the waterfall's columnar_decode stage is exactly the decode."""
        if req.debug_id is None:
            return
        from foundationdb_tpu.utils import commit_debug as _cdbg
        from foundationdb_tpu.utils import trace as _tr

        _tr.g_trace_batch.add_event(
            "CommitDebug", req.debug_id, _cdbg.RESOLVER_COLUMNAR_DECODE
        )

    def _columnar_to_objects(self, req) -> list:
        """The object fallback shared by every object-consuming backend
        (native skip list, CPU oracle): reconstruct exact transactions
        from the lossless blob — ONE blob -> objects copy, allocations
        counted honestly — and mark the decode stage. One helper so the
        ledger-gated accounting can never diverge between backends."""
        from foundationdb_tpu.utils import packing as _packing

        txns = _packing.columnar_to_transactions(req.cols)
        self.path_stats["copies"] += 1
        self.path_stats["decode_allocs"] += _decode_alloc_count(txns)
        self._trace_columnar_decode(req)
        return txns

    def _resolve_now(self, req) -> ResolveTransactionBatchReply:
        columnar = isinstance(req, codec.ResolveBatchColumnar)
        stats = self.path_stats
        if columnar:
            stats["columnar_batches"] += 1
            stats["txns"] += req.cols.n_txns
        else:
            stats["object_batches"] += 1
            stats["txns"] += len(req.transactions)
            # the object frame already materialized per-txn objects
            # inside codec.decode (the transport dispatch): one
            # payload -> objects copy plus the per-txn allocations
            stats["copies"] += 1
            stats["decode_allocs"] += _decode_alloc_count(req.transactions)
        if self._backend == "native":
            import time as _time

            txns = (
                self._columnar_to_objects(req) if columnar
                else req.transactions
            )
            t0 = _time.perf_counter()
            verdicts = self._cs.resolve(txns, req.version)
            self._kernel_metrics.kernel.sample(_time.perf_counter() - t0)
            self._kernel_metrics.counters.add("resolveBatches")
            committed = [TransactionResult(int(v)) for v in verdicts]
            ckr: dict[int, list[int]] = {}
        else:
            jitted = hasattr(self._cs, "pack_columnar_batch")
            if columnar and jitted:
                # THE columnar win: wire bytes -> device tensors with
                # TWO copies total — the blob -> padded-tensor scatter
                # (pack_columnar_batch) and the host -> device transfer
                # inside the dispatch. No per-txn objects ever exist.
                batch = self._cs.pack_columnar_batch(req.cols, req.version)
                self._trace_columnar_decode(req)
                stats["copies"] += 2
                res = self._cs.resolve_columnar_packed(req.cols, batch)
            elif columnar:
                # CPU-oracle backend: object-consuming fallback
                res = self._cs.resolve(
                    self._columnar_to_objects(req), req.version
                )
            else:
                if jitted:
                    # object path on a jitted backend: pack_batch
                    # re-flattens the decoded objects (+1) and the
                    # dispatch transfers (+1) on top of the decode copy
                    stats["copies"] += 2
                res = self._cs.resolve(req.transactions, req.version)
            committed = res.verdicts
            ckr = res.conflicting_key_ranges
        return ResolveTransactionBatchReply(
            committed=committed,
            conflicting_key_range_map=ckr,
            state_mutations=[],
            debug_id=req.debug_id,
        )

    def status(self) -> dict:
        """StatusRequest payload: role kind, version, and the qos
        sensor block (the four reference distributions + kernel
        occupancy on jitted backends)."""
        qos = {
            "queue_depth": self._waiting,
            "occupancy": self.occupancy.smooth_rate(),
            "queue_depth_dist": self.queue_depth.as_dict(),
            "queue_wait_dist": self.queue_wait_latency.as_dict(),
            "compute_time_dist": self.compute_time.as_dict(),
            "resolver_latency_dist": self.resolver_latency.as_dict(),
        }
        # the kernel panel is ALWAYS present (fdbtop pins it): jitted
        # backends report their conflict set's stage metrics, native
        # the role-owned block (compute seconds + process-global
        # compile-cache counters)
        qos["kernel"] = self._kernel_metrics.qos()
        # columnar-vs-object frame accounting (r12): bench_pipeline
        # reads this to land the structural copy/alloc metrics
        qos["resolve_path"] = dict(self.path_stats)
        return {
            "role": "resolver",
            "version": self.version,
            "backend": self._backend,
            "qos": qos,
        }


def _looks_sealed(blob: bytes) -> bool:
    from foundationdb_tpu.crypto.blob_cipher import is_encrypted

    return is_encrypted(blob)


def _check_encryption_marker(data_dir: str, encryption) -> None:
    """Persisted encryption mode (the reference persists
    encryptionAtRestMode in the database configuration and refuses mode
    flips — DatabaseConfiguration.h): a store written encrypted must
    never be opened unencrypted, or sealed bytes would be served as
    data. Sniffing record magic alone can false-positive on user bytes;
    the marker is deterministic."""
    marker = os.path.join(data_dir, "ENCRYPTION_MODE")
    if encryption is not None:
        if not os.path.exists(marker):
            # fsync file AND directory: the data records are all
            # fsynced, so the marker must be at least as durable — a
            # power loss that keeps sealed records but drops the
            # marker would downgrade the store silently
            with open(marker, "w") as f:
                f.write("aes-256-ctr\n")
                f.flush()
                os.fsync(f.fileno())
            dfd = os.open(data_dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
    elif os.path.exists(marker):
        raise RuntimeError(
            f"{data_dir} was written with encryption-at-rest; "
            "restart the role with --encrypt (and the same KMS)"
        )


class TLogRole:
    """Wire-served transaction log: version-ordered append + peek.

    With a data dir, every push rides the native DiskQueue
    (native/diskqueue.cpp — the fdbserver/DiskQueue.actor.cpp role):
    frames are fsynced BEFORE the push is acked (tLogCommit discipline,
    TLogServer.actor.cpp:2311), and a restart recovers exactly the acked
    entries via the crc-checked recovery scan.
    """

    def __init__(self, data_dir: str | None = None, encryption=None):
        self.entries: list[tuple[int, list]] = []  # (version, mutations)
        self.version = -1
        self._dq = None
        # -- saturation sensors (the Ratekeeper's TLogQueueInfo inputs):
        # retained queue bytes through a wall-clock smoother — this is
        # a real OS process, the reference's Smoother(timer()) shape
        from foundationdb_tpu.utils.metrics import TimerSmoother

        self._queue_bytes = 0
        self.smoothed_queue_bytes = TimerSmoother(1.0)
        self.smoothed_input_bytes = TimerSmoother(1.0)
        # the tlog persists the SAME mutation bytes storage seals — an
        # unencrypted tlog disk would hollow out the at-rest guarantee
        # (code review r5); whole records are sealed here (no ordering
        # constraint on tlog frames, unlike LSM keys)
        self._enc = encryption if data_dir else None
        if data_dir:
            from foundationdb_tpu.native import DiskQueue

            os.makedirs(data_dir, exist_ok=True)
            _check_encryption_marker(data_dir, self._enc)
            if self._enc is not None:
                # first push must not block the loop on a KMS trip
                self._enc.prefetch()
            self._dq = DiskQueue(os.path.join(data_dir, "tlog"))
            for _seq, blob in self._dq.recovered:
                if self._enc is not None:
                    blob = self._enc.open(blob)
                elif _looks_sealed(blob):
                    raise RuntimeError(
                        "sealed tlog record but encryption is disabled"
                    )
                rec = codec.decode(blob)
                self.entries.append((rec.version, list(rec.mutations)))
                self.version = max(self.version, rec.version)
            self._queue_bytes = sum(
                8 + len(m.param1) + len(m.param2)
                for _v, ms in self.entries for m in ms
            )
            self.smoothed_queue_bytes.set_total(self._queue_bytes)

    async def push(self, req: TLogPush) -> TLogPushReply:
        if req.version <= self.version:
            # duplicate push: idempotent ack (proxy retry after lost reply)
            return TLogPushReply(durable_version=self.version)
        # Forward version skips are legal: the proxy serializes pushes and
        # versions are consumed by failed batches and by recovery (a batch
        # resolved but lost in a crash window leaves prev_version above
        # our recovered version — the reference's recovery likewise
        # restarts the chain above lastEpochEnd). Only regressions are
        # rejected (the <= check above).
        if self._dq is not None:
            blob = codec.encode(req)
            if self._enc is not None:
                blob = self._enc.seal(blob)
            self._dq.push(blob)
            if self._dq.commit() is None:
                # fsync/pwrite failed: the data is NOT durable — refuse
                # the ack rather than lie (tLogCommit discipline)
                raise transport.RemoteError("tlog disk commit failed")
        self.entries.append((req.version, list(req.mutations)))
        self.version = req.version
        nb = sum(
            8 + len(m.param1) + len(m.param2) for m in req.mutations
        )
        self._queue_bytes += nb
        self.smoothed_input_bytes.add_delta(nb)
        self.smoothed_queue_bytes.set_total(self._queue_bytes)
        return TLogPushReply(durable_version=self.version)

    def status(self) -> dict:
        """StatusRequest payload: retained queue depth/bytes (smoothed
        + instantaneous) and the durable version — the wire analog of
        the sim tlog's `saturation()` block."""
        return {
            "role": "log",
            "version": self.version,
            "qos": {
                "queue_mutations": sum(
                    len(ms) for _v, ms in self.entries
                ),
                "queue_bytes": self._queue_bytes,
                "smoothed_queue_bytes": (
                    self.smoothed_queue_bytes.smooth_total()
                ),
                "input_bytes_per_s": (
                    self.smoothed_input_bytes.smooth_rate()
                ),
                "entries": len(self.entries),
            },
        }

    async def peek(self, req: TLogPeek) -> TLogPeekReply:
        i = self._first_after(req.after_version)
        if i < len(self.entries):
            v, muts = self.entries[i]
            return TLogPeekReply(version=v, mutations=muts)
        return TLogPeekReply(version=-1, mutations=[])

    async def peek_batch(self, req: "TLogPeekBatchReq") -> "TLogPeekBatchReply":
        """Batched tail read for storage catch-up: all entries above
        after_version, bounded by max_entries (linear restart, not the
        one-RPC-per-version quadratic walk)."""
        i = self._first_after(req.after_version)
        chunk = self.entries[i : i + req.max_entries]
        return TLogPeekBatchReply(
            versions=[v for v, _m in chunk],
            groups=[m for _v, m in chunk],
        )

    def _first_after(self, after_version: int) -> int:
        """Binary search: entries are version-ascending by construction."""
        import bisect

        return bisect.bisect_right(
            self.entries, after_version, key=lambda e: e[0]
        )

    async def get_version(self, req: RoleVersionReq) -> RoleVersionReply:
        return RoleVersionReply(version=self.version)


class StorageRole:
    """Wire-served storage: versioned point store (SET mutations)."""

    MUT_SET = 0
    MUT_CLEAR_RANGE = 1

    #: checkpoint every N applied versions when persistent
    CHECKPOINT_INTERVAL = 8

    #: memtable budget before the LSM engine flushes (bytes)
    LSM_FLUSH_BYTES = 4 << 20

    def __init__(self, data_dir: str | None = None, engine: str = "memory",
                 window: int = 5_000_000, encryption=None):
        # Encryption-at-rest (crypto/at_rest.StorageEncryption): every
        # SET value is sealed ONCE, in the executor, before it reaches
        # the WAL, the store, or a checkpoint — so no crypto runs on
        # the event loop under the apply lock and nothing is encrypted
        # twice (code review r5). Keys stay plaintext (run/checkpoint
        # ordering); reads open values through the cipher cache
        # (mixed-mode: plaintext legacy records pass through).
        self._enc = encryption if data_dir else None
        if self._enc is not None:
            # prefetch both cipher identities so the seal path starts
            # warm; a REST KMS still pays one refresh trip per
            # ENCRYPT_KEY_REFRESH_INTERVAL, off the hot path
            encryption.prefetch()
        # key -> list[(version, value|None)] ascending  (memory engine)
        self.history: dict[bytes, list[tuple[int, Optional[bytes]]]] = {}
        # the empty store is readable at version 0 (a GRV before any commit
        # must not block behind the first apply)
        self.version = 0
        self._cond: asyncio.Condition | None = None
        self._data_dir = data_dir
        self._applies_since_ckpt = 0
        # Incremental durability (KeyValueStoreMemory's discipline,
        # fdbserver/KeyValueStoreMemory.actor.cpp): every apply streams
        # its mutations to a local DiskQueue and fsyncs BEFORE acking
        # durable_version (the tlog pops on that ack — without the log,
        # acked-but-not-yet-checkpointed data died with the process).
        # Checkpoints become periodic compactions that pop the log
        # prefix; restart = load checkpoint + replay only the log tail.
        self._dq = None
        self._seq_by_version: list[tuple[int, int]] = []
        # Serializes write-ahead logging: the fsync runs in an executor
        # OUTSIDE the read condition lock (reads must not stall behind
        # the disk), so without this lock two concurrent apply() calls
        # could persist log records out of version order and replay
        # would skip the lower version (ADVICE r3).
        self._log_lock: asyncio.Lock | None = None
        self.replayed_on_restart = 0
        # Persistent engine selection (the reference's storage-engine
        # knob, fdbserver/worker.actor.cpp openKVStore): "memory" =
        # KeyValueStoreMemory-class (RAM dict + WAL + checkpoint blob);
        # "lsm" = the native versioned LSM (native/vlsm.cpp — data >
        # RAM, restart ∝ WAL tail, at-version reads off disk runs).
        self.engine = engine
        self._lsm = None
        self.window = window
        # -- saturation sensors: smoothed apply bandwidth + batch-size
        # distribution (the version LAG vs the committed head is joined
        # at assembly time — status.py assemble_status — because only
        # the parent pipeline knows the head, Status.actor.cpp's shape)
        from foundationdb_tpu.utils.metrics import (
            LatencySample,
            TimerSmoother,
        )

        self.smoothed_input_bytes = TimerSmoother(1.0)
        self.apply_batch_size = LatencySample("applyBatchMutations")
        self._applies = 0
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            _check_encryption_marker(data_dir, encryption)
            from foundationdb_tpu import native

            self._dq = native.DiskQueue(os.path.join(data_dir, "mutlog"))
            if engine == "lsm":
                self._lsm = native.VersionedLsm(
                    os.path.join(data_dir, "kvstore"), window=window
                )
                self.version = self._lsm.durable_version
            else:
                self._load_checkpoint()
            self._replay_local_log()
        elif engine == "lsm":
            raise ValueError("engine='lsm' requires a data_dir")

    # -- durable-version checkpointing (storageserver durableVersion
    # discipline: persist at a version, replay the tlog tail on restart) --

    def _ckpt_path(self) -> str:
        return os.path.join(self._data_dir, "storage.ckpt")

    def _serialize_checkpoint(self) -> bytes:
        out = codec.WriteBuffer()
        codec.w_i64(out, self.version)
        kvs = []
        for k, hist in self.history.items():
            value = None
            for v, val in hist:
                if v <= self.version:
                    value = val
            if value is not None:
                kvs.append((k, value))
        _w_kvlist(out, kvs)
        return out.getvalue()

    def _write_checkpoint_blob(self, blob: bytes) -> None:
        # values inside the blob are already sealed (seal-once at apply)
        tmp = self._ckpt_path() + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._ckpt_path())  # atomic install

    def _checkpoint(self) -> None:
        self._write_checkpoint_blob(self._serialize_checkpoint())

    def _load_checkpoint(self) -> None:
        try:
            with open(self._ckpt_path(), "rb") as f:
                blob = memoryview(f.read())
        except FileNotFoundError:
            return
        version, off = codec.r_i64(blob, 0)
        kvs, _off = _r_kvlist(blob, off)
        self.version = version
        self.history = {k: [(version, v)] for k, v in kvs}

    # -- the mutation log (incremental durability) -----------------------
    # Records are codec-encoded StorageApply messages — the same
    # registered wire codec the RPC layer uses (TLogRole logs its
    # DiskQueue records the same way; no second serialization path).

    def _seal_values(self, req):
        """Seal every SET value of a StorageApply (the ONE place values
        are encrypted — WAL, store and checkpoints all carry the sealed
        bytes from here on). Runs in the executor."""
        return StorageApply(
            version=req.version,
            mutations=[
                codec.Mutation(m.op, m.param1, self._enc.seal(m.param2))
                if m.op == self.MUT_SET
                else m
                for m in req.mutations
            ],
        )

    def _replay_local_log(self) -> None:
        """Restart: replay the log tail above the checkpoint — cost
        proportional to the tail, not the dataset. (Values inside the
        records are sealed; they are stored as-is and opened on read.)"""
        for seq, blob in self._dq.recovered:
            if self._enc is None and _looks_sealed(blob):
                # defense in depth behind the fsynced marker: codec
                # records never start with the cipher magic, so a
                # whole-sealed blob here means a lost marker (note the
                # seal-once format stores sealed VALUES inside plain
                # codec records — for those only the marker protects)
                raise RuntimeError(
                    "sealed storage WAL record but encryption is disabled"
                )
            rec = codec.decode(blob)
            if rec.version > self.version:
                self._apply_mutations(rec.version, rec.mutations)
                self.version = rec.version
                self.replayed_on_restart += 1
            self._seq_by_version.append((rec.version, seq))

    def _log_apply_durably(self, reqs: list) -> None:
        """Write-ahead + fsync a group of versions' mutations (one
        fsync per group — catch-up batches amortize it). Runs in the
        executor, BEFORE the in-memory apply and the ack."""
        seqs = [
            (req.version, self._dq.push(codec.encode(req)))
            for req in reqs
        ]
        if self._dq.commit() is None:
            # fsync/pwrite failed: the data is NOT durable — refuse the
            # ack rather than lie (the tLogCommit discipline; the tlog
            # pops on our durable_version ack)
            raise transport.RemoteError("storage mutation-log commit failed")
        self._seq_by_version.extend(seqs)

    def _compact_log(self, ckpt_version: int) -> None:
        """After a checkpoint at ckpt_version is durably installed, the
        log prefix at or below it is dead: pop it (the restart replay
        shrinks back to the new tail)."""
        last_seq = None
        kept = []
        for v, s in self._seq_by_version:
            if v <= ckpt_version:
                last_seq = s
            else:
                kept.append((v, s))
        if last_seq is not None:
            self._dq.pop(last_seq + 1)
            self._dq.commit()
            self._seq_by_version = kept

    def _apply_mutations(self, version: int, mutations) -> None:
        self._applies += 1
        self.apply_batch_size.sample(len(mutations))
        self.smoothed_input_bytes.add_delta(sum(
            8 + len(m.param1) + len(m.param2) for m in mutations
        ))
        if self._lsm is not None:
            # values arrive pre-sealed (seal-once in apply/catch-up);
            # keys stay plaintext for run ordering (crypto/at_rest.py)
            self._lsm.apply(
                version, [(m.op, m.param1, m.param2) for m in mutations]
            )
            return
        for m in mutations:
            if m.op == self.MUT_SET:
                self.history.setdefault(m.param1, []).append(
                    (version, m.param2)
                )
            elif m.op == self.MUT_CLEAR_RANGE:
                for k in list(self.history):
                    if m.param1 <= k < m.param2:
                        self.history[k].append((version, None))

    async def catch_up_from_tlog(self, tlog_address: str) -> None:
        """Replay the tlog tail above our durable version (the restart
        path of storageserver.actor.cpp:9117's pull loop) in batched
        chunks — linear in tail length."""
        conn = transport.RpcConnection(tlog_address, tls=_tls_from_env())
        await conn.connect()
        try:
            while True:
                rep = await conn.call(
                    TOKEN_TLOG_PEEK_BATCH,
                    TLogPeekBatchReq(
                        after_version=self.version, max_entries=256
                    ),
                )
                if not rep.versions:
                    break
                reqs = [
                    StorageApply(version=v, mutations=muts)
                    for v, muts in zip(rep.versions, rep.groups)
                    if v > self.version
                ]
                if reqs and self._enc is not None:
                    loop = asyncio.get_event_loop()
                    reqs = await loop.run_in_executor(
                        None, lambda rs: [self._seal_values(r) for r in rs],
                        reqs,
                    )
                if reqs and self._dq is not None:
                    # group commit: ONE fsync per peek chunk, not per
                    # version — restart catch-up stays O(chunks) fsyncs
                    await self._log_durably(reqs)
                for req in reqs:
                    await self._apply_logged(req)
        finally:
            await conn.close()

    def _log_lock_lazy(self) -> asyncio.Lock:
        if self._log_lock is None:
            self._log_lock = asyncio.Lock()
        return self._log_lock

    def _cond_lazy(self) -> asyncio.Condition:
        if self._cond is None:
            self._cond = asyncio.Condition()
        return self._cond

    async def apply(self, req: StorageApply) -> StorageApplyReply:
        # WRITE-AHEAD: fsync the mutations to the local log BEFORE the
        # in-memory apply and the ack — durable_version must imply
        # durability (the tlog pops on it). The fsync runs OUTSIDE the
        # condition lock so reads at already-applied versions never
        # stall behind the disk; a stale/duplicate record logged by a
        # lost race is skipped idempotently on replay.
        if req.version > self.version:
            if self._enc is not None:
                # seal-once, off the event loop (code review r5)
                req = await asyncio.get_event_loop().run_in_executor(
                    None, self._seal_values, req
                )
            if self._dq is not None:
                await self._log_durably([req])
        return await self._apply_logged(req)

    async def apply_batch(self, req: "StorageApplyBatch") -> StorageApplyReply:
        """Version-ordered group apply (the pipeline applier's drain):
        one sealing pass, ONE write-ahead group fsync (when persistent)
        and one ordered in-memory apply sweep for the whole chunk —
        the storage-side twin of the tlog's group commit."""
        reqs = [
            StorageApply(version=v, mutations=m)
            for v, m in zip(req.versions, req.groups)
            if v > self.version
        ]
        if reqs and self._enc is not None:
            loop = asyncio.get_event_loop()
            reqs = await loop.run_in_executor(
                None, lambda rs: [self._seal_values(r) for r in rs], reqs
            )
        if reqs and self._dq is not None:
            await self._log_durably(reqs)
        rep = None
        for r in reqs:
            rep = await self._apply_logged(r)
        return rep if rep is not None else StorageApplyReply(
            durable_version=self.version
        )

    async def _log_durably(self, reqs: list) -> None:
        """Run the write-ahead fsync in the executor under a per-store
        lock: log records must hit the disk in version order (replay
        skips any version at or below the restart cursor, so an
        out-of-order pair would silently drop the lower one)."""
        async with self._log_lock_lazy():
            await asyncio.get_event_loop().run_in_executor(
                None, self._log_apply_durably, reqs
            )

    async def _apply_logged(self, req: StorageApply) -> StorageApplyReply:
        cond = self._cond_lazy()
        async with cond:
            if req.version > self.version:
                self._apply_mutations(req.version, req.mutations)
                self.version = req.version
                if self._data_dir and self._lsm is not None:
                    self._applies_since_ckpt += 1
                    if (
                        self._applies_since_ckpt >= self.CHECKPOINT_INTERVAL
                        or self._lsm.mem_bytes > self.LSM_FLUSH_BYTES
                    ):
                        self._applies_since_ckpt = 0
                        # LSM checkpoint: flush the memtable to a durable
                        # run (fsync off the loop), advance the MVCC GC
                        # floor, pop the WAL prefix the run now covers
                        lsm = self._lsm

                        def lsm_flush():
                            durable = lsm.flush()
                            lsm.set_floor(durable - self.window)
                            self._compact_log(durable)

                        # _compact_log pops the native WAL DiskQueue and
                        # swaps _seq_by_version; a concurrent apply()'s
                        # _log_apply_durably pushes the SAME queue from
                        # another executor thread and the native queue
                        # does no internal locking — serialize through
                        # _log_lock (ADVICE r4)
                        async with self._log_lock_lazy():
                            await asyncio.get_event_loop().run_in_executor(
                                None, lsm_flush
                            )
                elif self._data_dir:
                    self._applies_since_ckpt += 1
                    if self._applies_since_ckpt >= self.CHECKPOINT_INTERVAL:
                        self._applies_since_ckpt = 0
                        # checkpoint = compaction: serialize under the
                        # lock (consistent view), install + pop the log
                        # prefix off the event loop
                        blob = self._serialize_checkpoint()
                        ckpt_version = self.version

                        def install():
                            self._write_checkpoint_blob(blob)
                            self._compact_log(ckpt_version)

                        # same WAL push/pop race as the LSM branch above:
                        # _compact_log must not run concurrently with
                        # _log_apply_durably on the unlocked native queue
                        async with self._log_lock_lazy():
                            await asyncio.get_event_loop().run_in_executor(
                                None, install
                            )
                cond.notify_all()
            return StorageApplyReply(durable_version=self.version)

    async def get_version(self, req: RoleVersionReq) -> RoleVersionReply:
        return RoleVersionReply(version=self.version)

    def status(self) -> dict:
        """StatusRequest payload: apply bandwidth, batch-size
        distribution, and the store size — the wire analog of the sim
        storage's `saturation()` block (version lag vs the committed
        head is joined at assembly time)."""
        return {
            "role": "storage",
            "version": self.version,
            "engine": self.engine,
            "qos": {
                "applies": self._applies,
                "apply_batch_mutations": self.apply_batch_size.as_dict(),
                "input_bytes_per_s": (
                    self.smoothed_input_bytes.smooth_rate()
                ),
                "keys": len(self.history),
            },
        }

    async def get(self, req: StorageGet) -> StorageGetReply:
        cond = self._cond_lazy()
        async with cond:
            await cond.wait_for(lambda: self.version >= req.version)
        if self._lsm is not None:
            # disk preads off the event loop: a cold read must not stall
            # unrelated requests
            # read AND open (decrypt + possible by-id KMS fetch) in the
            # executor: neither disk preads nor a KMS round trip may
            # stall the event loop (code review r5)
            # plain pass-through when encryption is off: the marker
            # check at startup guarantees the store is unencrypted, and
            # user values may legitimately start with the header magic
            def read_open():
                v = self._lsm.get(req.key, req.version)
                if v is None or self._enc is None:
                    return v
                return self._enc.open(v)

            value = await asyncio.get_event_loop().run_in_executor(
                None, read_open
            )
            return StorageGetReply(value=value)
        hist = self.history.get(req.key, [])
        value = None
        for v, val in hist:
            if v <= req.version:
                value = val
            else:
                break
        if value is not None and self._enc is not None:
            # decrypt (and a possible cold by-id KMS fetch) off the
            # loop — same discipline as the LSM read closures
            value = await asyncio.get_event_loop().run_in_executor(
                None, self._enc.open, value
            )
        return StorageGetReply(value=value)

    def _get_at(self, key: bytes, version: int):
        """Newest value <= version from the in-memory history (still
        sealed when encryption is on)."""
        value = None
        for v, val in self.history.get(key, []):
            if v <= version:
                value = val
            else:
                break
        return value

    async def get_batch(self, req: "StorageGetBatch") -> "StorageGetBatchReply":
        """Coalesced reads: ONE version wait (max of the batch), then
        every key served at ITS OWN requested version — exact MVCC
        semantics, one wire roundtrip for a whole event-loop turn's
        worth of proxy-process reads."""
        vmax = max(req.versions) if req.versions else 0
        cond = self._cond_lazy()
        async with cond:
            await cond.wait_for(lambda: self.version >= vmax)
        if self._lsm is not None:
            # preads + decrypt off the loop, one executor hop per batch
            def read_open_all():
                out = []
                for k, rv in zip(req.keys, req.versions):
                    v = self._lsm.get(k, rv)
                    if v is not None and self._enc is not None:
                        v = self._enc.open(v)
                    out.append(v)
                return out

            values = await asyncio.get_event_loop().run_in_executor(
                None, read_open_all
            )
            return StorageGetBatchReply(values=values)
        values = [
            self._get_at(k, rv) for k, rv in zip(req.keys, req.versions)
        ]
        if self._enc is not None:
            values = await asyncio.get_event_loop().run_in_executor(
                None,
                lambda vs: [
                    self._enc.open(v) if v is not None else None for v in vs
                ],
                values,
            )
        return StorageGetBatchReply(values=values)

    async def snapshot(self, req: StorageSnapshotReq) -> StorageSnapshotReply:
        cond = self._cond_lazy()
        async with cond:
            await cond.wait_for(lambda: self.version >= req.version)
        if self._lsm is not None:
            # range + per-value open() together in the executor — a
            # full-dataset decrypt inline on the loop would stall every
            # unrelated request proportionally to dataset size
            def range_open():
                rows = self._lsm.range(b"", b"", req.version)
                if self._enc is None:
                    return rows
                return [(k, self._enc.open(v)) for k, v in rows]

            kvs = await asyncio.get_event_loop().run_in_executor(
                None, range_open
            )
            return StorageSnapshotReply(version=self.version, kvs=kvs)
        kvs = []
        for k, hist in sorted(self.history.items()):
            value = None
            for v, val in hist:
                if v <= req.version:
                    value = val  # leaves the newest value <= version
            if value is not None:
                kvs.append((k, value))
        if self._enc is not None:
            # full-dataset decrypt belongs in the executor (the sealed
            # kvs list is already materialized, so the loop may mutate
            # history freely meanwhile)
            kvs = await asyncio.get_event_loop().run_in_executor(
                None,
                lambda rows: [(k, self._enc.open(v)) for k, v in rows],
                kvs,
            )
        return StorageSnapshotReply(version=self.version, kvs=kvs)


class RatekeeperRole:
    """Wire-mode Ratekeeper: `fdbserver/Ratekeeper.actor.cpp` as an OS
    process. Polls every peer role's StatusRequest for its saturation
    sensors (the same qos blocks fdbtop renders), drives the SAME
    `AdmissionController` law the sim Ratekeeper runs, and serves the
    live budget over GetRateInfo. Robustness contract: a peer that
    stops answering simply contributes no sensors this interval; when
    NO peer answers, the law's fail-safe decay engages (budget decays
    toward the conservative floor) — and a consumer that cannot reach
    THIS process applies its own decay (ProxyPipeline._rate_fetcher),
    so a dead ratekeeper never freezes the cluster at full speed."""

    def __init__(self, peers: list[str], *, interval: float = 0.25):
        import time as _time

        from foundationdb_tpu.cluster.ratekeeper import AdmissionController

        self.peers = [p for p in peers if p]
        self.interval = interval
        self.law = AdmissionController(clock=_time.monotonic)
        self._conns: dict[str, transport.RpcConnection] = {}
        self._task: asyncio.Task | None = None
        self.polls = 0
        self.poll_failures = 0
        #: last cycle's observed GRV admission rate (the law's
        #: actualTps input) — surfaced in status so the wire feedback
        #: path is testable end to end
        self.observed_grv_per_s = 0.0

    async def start(self) -> None:
        self._task = asyncio.ensure_future(self._poll_loop())

    async def _poll_one(self, path: str) -> dict:
        import json as _json

        conn = self._conns.get(path)
        if conn is None:
            conn = transport.RpcConnection(path, tls=_tls_from_env())
            await conn.connect(retries=1)
            self._conns[path] = conn
        reply = await conn.call(
            TOKEN_STATUS, StatusRequest(pad=0), timeout=2.0
        )
        return _json.loads(reply.payload)

    async def _poll_loop(self) -> None:
        from foundationdb_tpu.cluster.status import _QOS_SLOT

        while True:
            slots: dict = {
                "tlogs": {}, "storages": {}, "resolvers": {},
                "proxies": {},
            }
            answered = 0
            current_tps = 0.0
            # polls are independent I/O and go out CONCURRENTLY: one
            # hung peer (2s call timeout) bounds the cycle at the
            # slowest single peer, not the sum — a serial loop would
            # stretch the control cadence ~Nx while the served budget
            # sat frozen at its last (possibly full-speed) value
            results = await asyncio.gather(
                *(self._poll_one(p) for p in self.peers),
                return_exceptions=True,
            )
            for path, block in zip(self.peers, results):
                if isinstance(block, BaseException):
                    self.poll_failures += 1
                    conn = self._conns.pop(path, None)
                    if conn is not None:
                        try:
                            await conn.close()
                        except Exception:
                            pass
                    continue
                name = os.path.basename(path)
                if name.endswith(".sock"):
                    name = name[: -len(".sock")]
                answered += 1
                slot = _QOS_SLOT.get(block.get("role", ""))
                if slot in slots:
                    slots[slot][name] = block.get("qos", {})
                # the parent pipeline's status socket embeds its GRV
                # block (a process block: role + qos): its served-GRV
                # rate is the law's actualTps
                grv = block.get("grv_proxy")
                if grv:
                    current_tps = max(
                        current_tps,
                        float(grv.get("qos", {}).get("grv_per_s", 0.0)),
                    )
            self.polls += 1
            self.observed_grv_per_s = current_tps
            if answered == 0:
                # total sensor dropout: fail safe, never full speed
                self.law.decay()
            else:
                self.law.update(slots, current_tps=current_tps)
            await asyncio.sleep(self.interval)

    async def get_rate_info(
        self, _req: GetRateInfoRequest
    ) -> GetRateInfoReply:
        import json as _json

        return GetRateInfoReply(payload=_json.dumps(self.law.rate_info()))

    def status(self) -> dict:
        return {
            "role": "ratekeeper",
            "qos": {
                **self.law.rate_info(),
                "peer_polls": self.polls,
                "peer_poll_failures": self.poll_failures,
                "peers": len(self.peers),
                "observed_grv_per_s": self.observed_grv_per_s,
            },
        }


async def _serve_role(
    role_name: str,
    address,
    backend: str,
    data_dir: str | None = None,
    tlog_address: str | None = None,
    storage_engine: str = "memory",
    encrypt: bool = False,
    trace_file: str | None = None,
    peers: list[str] | None = None,
) -> None:
    if trace_file:
        # per-process trace sink (the reference's one-trace-file-per-
        # fdbserver): micro-events and spans land in a JSONL file that
        # scripts/commit_debug.py merges with the other roles' files —
        # cross-process timelines from a wire-mode run
        import time as _time

        from foundationdb_tpu.utils import spans as _spans
        from foundationdb_tpu.utils import trace as _tr

        sink = _tr.TraceLog(
            min_severity=_tr.SEV_DEBUG, clock=_time.time, path=trace_file
        )
        _tr.install(
            sink, _tr.TraceBatch(clock=_time.time, logger=sink, enabled=True)
        )
        _spans.set_exporter(_spans.SpanExporter(trace_log=sink))
    server = transport.RpcServer(address, tls=_tls_from_env())

    async def ping(msg: Ping) -> Pong:
        return Pong(payload=msg.payload)

    server.register(TOKEN_PING, ping)
    # --encrypt is the only switch that reaches this child process:
    # spawn_role translates the launcher's ENABLE_ENCRYPTION knob into
    # the flag (a knob read in a fresh child interpreter would always
    # be the default — dead configuration). Encryption is meaningless
    # without a data dir (nothing at rest).
    encryption = None
    if encrypt and data_dir:
        from foundationdb_tpu.crypto.at_rest import default_encryption

        encryption = default_encryption(
            kms_endpoint=os.environ.get("FDB_TPU_KMS")
        )
    if role_name == "resolver":
        role = ResolverRole(backend=backend)
        server.register(TOKEN_RESOLVE, role.resolve)

        async def rv(req: RoleVersionReq) -> RoleVersionReply:
            return RoleVersionReply(version=role.version)

        server.register(TOKEN_RESOLVER_VERSION, rv)
    elif role_name == "tlog":
        role = TLogRole(data_dir=data_dir, encryption=encryption)
        server.register(TOKEN_TLOG_PUSH, role.push)
        server.register(TOKEN_TLOG_PEEK, role.peek)
        server.register(TOKEN_TLOG_PEEK_BATCH, role.peek_batch)
        server.register(TOKEN_TLOG_VERSION, role.get_version)
    elif role_name == "storage":
        role = StorageRole(
            data_dir=data_dir, engine=storage_engine, encryption=encryption
        )
        if tlog_address:
            await role.catch_up_from_tlog(tlog_address)
        server.register(TOKEN_STORAGE_APPLY, role.apply)
        server.register(TOKEN_STORAGE_APPLY_BATCH, role.apply_batch)
        server.register(TOKEN_STORAGE_GET, role.get)
        server.register(TOKEN_STORAGE_GET_BATCH, role.get_batch)
        server.register(TOKEN_STORAGE_SNAPSHOT, role.snapshot)
        server.register(TOKEN_STORAGE_VERSION, role.get_version)
    elif role_name == "ratekeeper":
        role = RatekeeperRole(peers or [])
        server.register(TOKEN_GET_RATE_INFO, role.get_rate_info)
        await role.start()
    else:
        raise ValueError(f"unknown role {role_name!r}")

    # saturation telemetry: EVERY spawned role answers StatusRequest
    # with its status block (fdbtop / wire_cluster_status poll this)
    import json as _json

    async def status(_req: StatusRequest) -> StatusReply:
        return StatusReply(payload=_json.dumps(role.status()))

    server.register(TOKEN_STATUS, status)
    await server.start()
    # run until killed
    await asyncio.Event().wait()


# ---------------------------------------------------------------------------
# Launcher (parent side).


@dataclasses.dataclass
class RoleProcess:
    name: str
    address: str
    proc: subprocess.Popen

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()


def spawn_role(
    name: str,
    socket_dir: str,
    *,
    backend: str = "native",
    index: int = 0,
    data_dir: str | None = None,
    tlog_address: str | None = None,
    storage_engine: str = "memory",
    encrypt: bool = False,
    trace_file: str | None = None,
    peers: list[str] | None = None,
) -> RoleProcess:
    """Start one role as a child OS process serving a UDS in socket_dir.

    Children run with JAX_PLATFORMS=cpu and a clean PYTHONPATH so they can
    never claim a TPU tunnel (the TPU belongs to the resolver process only
    when explicitly requested via backend='tpu')."""
    address = os.path.join(socket_dir, f"{name}{index}.sock")
    env = dict(os.environ)
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    if backend not in ("tpu", "tpu-force"):
        env["PYTHONPATH"] = repo_root
        env["JAX_PLATFORMS"] = "cpu"
    else:
        # tpu children keep their platform env (the tunnel sitecustomize
        # stays on PYTHONPATH) but still need the package importable
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [
        sys.executable,
        "-m",
        "foundationdb_tpu.cluster.multiprocess",
        "--role",
        name,
        "--address",
        address,
        "--backend",
        backend,
    ]
    if data_dir:
        cmd += ["--data-dir", data_dir]
    if trace_file:
        cmd += ["--trace-file", trace_file]
    if peers:
        # ratekeeper: the role sockets whose StatusRequest sensors feed
        # the admission law
        cmd += ["--peers", ",".join(peers)]
    if tlog_address:
        cmd += ["--tlog-address", tlog_address]
    if storage_engine != "memory":
        cmd += ["--storage-engine", storage_engine]
    # knob propagation: the child is a fresh interpreter with default
    # knobs, so the launcher's ENABLE_ENCRYPTION must travel as the
    # explicit flag (code review r5 — a knob read only child-side is
    # dead configuration)
    if not encrypt:
        from foundationdb_tpu.utils.knobs import SERVER_KNOBS

        encrypt = bool(SERVER_KNOBS.ENABLE_ENCRYPTION)
    if encrypt:
        cmd += ["--encrypt"]
    proc = subprocess.Popen(cmd, env=env)
    return RoleProcess(name=name, address=address, proc=proc)


# ---------------------------------------------------------------------------
# The commit pipeline (parent process: sequencer + proxy + client API).


class NotCommittedError(Exception):
    pass


class AsyncNotified:
    """Monotone value with when_at_least — the runtime/flow `Notified`
    (NotifiedVersion) for asyncio: the wire pipeline's batch-ordering
    chains wait on it exactly like the simulated proxy's
    latest_batch_resolving / latest_batch_logging chains."""

    def __init__(self, value: int = 0):
        self._value = value
        self._waiters: list[tuple[int, asyncio.Future]] = []

    def get(self) -> int:
        return self._value

    def set(self, value: int) -> None:
        if value < self._value:
            raise ValueError(
                f"Notified must not decrease: {value} < {self._value}"
            )
        self._value = value
        still = []
        for threshold, fut in self._waiters:
            if fut.done():
                continue
            if threshold <= value:
                fut.set_result(value)
            else:
                still.append((threshold, fut))
        self._waiters = still

    async def when_at_least(self, threshold: int) -> int:
        if self._value >= threshold:
            return self._value
        fut = asyncio.get_running_loop().create_future()
        self._waiters.append((threshold, fut))
        return await fut


class PipelineFailedError(Exception):
    """A predecessor batch died mid-chain; this proxy generation is
    broken (the in-process CommitProxy's `failed` discipline)."""


# A/B toggle for the resolve-hop payload (measurement): 1 = conflict
# metadata only (default), 0 = full transactions incl. mutations.
_RESOLVE_STRIP = os.environ.get("RESOLVE_STRIP", "1") != "0"


def _resolve_columnar_default() -> bool:
    """A/B toggle for the resolve-hop FRAME (r12): 1 (default) = the
    columnar ResolveBatchColumnar frame — conflict metadata packed ONCE
    at the proxy as flat little-endian arrays + one key blob, decoded
    resolver-side with np.frombuffer straight into kernel tensors; 0 =
    the per-transaction object frame (the escape hatch, and the PR-11
    baseline path for A/B runs). Columnar applies only to the STRIPPED
    conflict-metadata hop: with RESOLVE_STRIP=0 (full transactions
    incl. mutations on the wire) the object frame always runs. Read at
    pipeline construction so one process can A/B both paths."""
    return os.environ.get("RESOLVE_COLUMNAR", "1") != "0"


class ProxyPipeline:
    """Sequencer + commit proxy over wire-connected roles.

    The 5-phase commitBatch pipeline
    (fdbserver/CommitProxyServer.actor.cpp:2516-2555) against remote
    resolver/tlog/storage processes, STAGE-OVERLAPPED: successive batches
    run concurrently through resolve -> tlog-push -> reply, ordered only
    at the Notified-chain handoffs — batch N+1's resolution is on the
    wire while batch N is logging (the resolver serializes versions by
    the prev_version chain server-side), its tlog push waits only for
    batch N's push, and client replies fire as soon as the batch's own
    push is durable. Storage applies ride a third ordered chain BEHIND
    the replies (reads wait for the storage version they need, so
    lagging applies cost read latency, never correctness) — the
    reference's storage lag. Batching is adaptive (cluster/batching.py):
    the accumulation interval shrinks while batches fill early and the
    count/bytes targets follow measured resolve+log seconds. GRV serves
    the last tlog-durable version (commit-before-GRV visibility).
    """

    def __init__(
        self,
        resolvers: list[transport.RpcConnection],
        tlog: transport.RpcConnection,
        storage: transport.RpcConnection,
        *,
        version_step: int = 1000,
        batch_interval: float = 0.002,
        max_batch: int = 512,
        start_version: int = 0,
        trace: bool = False,
        pipeline_depth: int = None,
        ratekeeper: transport.RpcConnection = None,
        rate_fetch_interval: float = 0.25,
        max_grv_queue: int = None,
        resolve_columnar: bool = None,
    ):
        from foundationdb_tpu.cluster.batching import AdaptiveBatchSizer
        from foundationdb_tpu.utils.knobs import SERVER_KNOBS as _K

        self.resolvers = resolvers
        self.tlog = tlog
        self.storage = storage
        # columnar resolve frame (r12): pack the batch's conflict
        # metadata ONCE into flat arrays + one key blob at batch-build
        # time (the layout the resolver's kernel packer consumes), so
        # the resolve hop is wire bytes -> device tensors with two
        # copies total. None = the RESOLVE_COLUMNAR env default; the
        # object frame still runs with RESOLVE_STRIP=0 (mutations must
        # travel) regardless.
        self._columnar = (
            _resolve_columnar_default()
            if resolve_columnar is None
            else bool(resolve_columnar)
        ) and _RESOLVE_STRIP
        # -- admission control (the wire GRV front door): the budget is
        # fetched from the ratekeeper role over GetRateInfo and enforced
        # as an arrival-spacing token bucket with a burst cap; requests
        # whose backlog would exceed the bounded queue are SHED with the
        # retryable grv_throttled error (same contract as the sim
        # GrvProxy). No ratekeeper connection == unthrottled.
        self._rk_conn = ratekeeper
        self._rate_interval = rate_fetch_interval
        self.max_grv_queue = (
            max_grv_queue if max_grv_queue is not None
            else _K.GRV_PROXY_MAX_QUEUE
        )
        from foundationdb_tpu.cluster.ratekeeper import FAILSAFE_TAU

        self._rate_limit = float("inf")
        self._rate_floor = 1e4
        self._rate_tau = FAILSAFE_TAU
        self._rate_info: dict = {}
        self._rate_stale = False
        self._rate_failures = 0
        self._rate_task: asyncio.Task | None = None
        self._grv_next_slot = 0.0
        self.grv_sheds = 0
        self.grv_throttle_waits = 0
        self.version_step = version_step
        self.batch_interval = batch_interval
        self.max_batch = max_batch
        self.batch_sizer = AdaptiveBatchSizer(
            interval=batch_interval,
            min_interval=min(
                batch_interval, _K.COMMIT_TRANSACTION_BATCH_INTERVAL_MIN
            ),
            # unlike the in-process proxy (whose window only shrinks, to
            # keep existing sim schedules), the wire pipeline's window
            # may GROW to the MAX knob: under a slow resolver (kernel
            # dispatch cost) the latency-fraction rule earns bigger
            # batches that amortize the per-dispatch cost
            max_interval=max(
                batch_interval, _K.COMMIT_TRANSACTION_BATCH_INTERVAL_MAX
            ),
            target_count=max_batch,
            max_count=max(
                max_batch, _K.COMMIT_TRANSACTION_BATCH_COUNT_MAX
            ),
            max_bytes=_K.COMMIT_TRANSACTION_BATCH_BYTES_MAX,
            latency_budget=_K.COMMIT_BATCH_STAGE_LATENCY_BUDGET,
            alpha=_K.COMMIT_TRANSACTION_BATCH_INTERVAL_SMOOTHER_ALPHA,
            latency_fraction=_K.COMMIT_TRANSACTION_BATCH_INTERVAL_LATENCY_FRACTION,
        )
        #: commit-path tracing: batches carry span contexts + debug ids
        #: over the wire to the resolver processes, and this process
        #: emits the CommitProxy.* micro-events (enable the global
        #: trace sinks — e.g. a TraceLog file — to persist them)
        self.trace = trace
        self._batch_seq = 0
        # a recovering proxy passes start_version = max(tlog version,
        # resolver version) so allocation resumes strictly above anything
        # any role has seen (the reference's recovery version semantics)
        self.committed_version = start_version
        self.prev_version = -1 if start_version == 0 else start_version
        self._last_allocated = start_version
        # the resolve/push version chain: batch N+1's prev_version is
        # batch N's version, assigned synchronously at spawn
        self._chain_prev = self.prev_version
        self._queue: list[tuple[CommitTransaction, asyncio.Future]] = []
        self._batcher_task: asyncio.Task | None = None
        # batch-ordering chain (batch numbers, 1-based)
        self._latest_batch_logging = AsyncNotified(0)
        self._inflight: set[asyncio.Task] = set()
        self._depth = asyncio.Semaphore(
            pipeline_depth
            if pipeline_depth is not None
            else _K.MAX_PIPELINED_COMMIT_BATCHES
        )
        self.failed: Optional[BaseException] = None
        self._loop: asyncio.AbstractEventLoop | None = None
        # ordered apply queue: (version, mutations) appended in commit
        # order at reply time, drained by ONE applier task in batched
        # StorageApplyBatch RPCs — replies never wait on storage, and
        # the storage version trails the committed version by at most
        # one drain roundtrip (the reference's bounded storage lag)
        self._apply_queue: list[tuple[int, list]] = []
        self._apply_event: asyncio.Event | None = None
        self._applier_task: asyncio.Task | None = None
        self.applied_version = start_version
        self._last_enqueued_apply = start_version
        # read coalescer: every read issued in the same event-loop turn
        # rides one StorageGetBatch RPC (per-key versions, exact MVCC)
        self._read_pending: list = []
        self._read_flush_scheduled = False
        # -- saturation sensors (the parent process plays BOTH proxies
        # in wire mode: commit batching here, GRV at get_read_version)
        from foundationdb_tpu.utils.metrics import TimerSmoother

        self._batches_inflight = 0
        self.smoothed_queue_depth = TimerSmoother(1.0)
        self.smoothed_grv_rate = TimerSmoother(1.0)
        self.grvs_served = 0

    def start(self) -> None:
        self._loop = asyncio.get_event_loop()
        self._apply_event = asyncio.Event()
        self._batcher_task = asyncio.ensure_future(self._batcher())
        self._applier_task = asyncio.ensure_future(self._applier())
        if self._rk_conn is not None:
            self._rate_task = asyncio.ensure_future(self._rate_fetcher())

    async def stop(self) -> None:
        if self._rate_task:
            self._rate_task.cancel()
            try:
                await self._rate_task
            except asyncio.CancelledError:
                pass
            self._rate_task = None
        if self._batcher_task:
            self._batcher_task.cancel()
            try:
                await self._batcher_task
            except asyncio.CancelledError:
                pass
            self._batcher_task = None
        # drain in-flight batches: their replies must not die with the
        # pipeline (and tests must not leak pending tasks)
        if self._inflight:
            await asyncio.gather(
                *list(self._inflight), return_exceptions=True
            )
        # flush the apply queue so storage converges to committed state
        # before the roles go down (consistency checks snapshot here);
        # applied_version advances only after the batch RPC is acked, so
        # this cannot cancel a drain mid-roundtrip
        if self._applier_task:
            while (
                self.applied_version < self._last_enqueued_apply
                and self.failed is None
                and not self._applier_task.done()
            ):
                self._apply_event.set()
                await asyncio.sleep(0.001)
            self._applier_task.cancel()
            try:
                await self._applier_task
            except asyncio.CancelledError:
                pass
            self._applier_task = None

    async def _rate_fetcher(self) -> None:
        """Budget-fetch loop (GetRateInfoRequest cadence). A ratekeeper
        that stops answering FAILS SAFE: after two consecutive misses
        the effective budget decays exponentially toward the
        conservative floor — a dead ratekeeper must clamp the front
        door, never freeze it at full speed."""
        import json as _json
        import math as _math
        import time as _time

        last = _time.monotonic()
        while True:
            now = _time.monotonic()
            dt = max(0.0, now - last)
            last = now
            try:
                rep = await self._rk_conn.call(
                    TOKEN_GET_RATE_INFO, GetRateInfoRequest(pad=0),
                    timeout=2.0,
                )
                info = _json.loads(rep.payload)
                self._rate_limit = float(
                    info["transactions_per_second_limit"]
                )
                self._rate_floor = float(
                    info.get("failsafe_tps", self._rate_floor)
                )
                self._rate_tau = float(
                    info.get("failsafe_tau", self._rate_tau)
                )
                self._rate_info = info
                self._rate_failures = 0
                self._rate_stale = False
            except asyncio.CancelledError:
                raise
            except Exception:
                self._rate_failures += 1
                if self._rate_failures >= 2:
                    self._rate_stale = True
                    if self._rate_limit == float("inf"):
                        self._rate_limit = self._rate_floor
                    else:
                        self._rate_limit = max(
                            self._rate_floor,
                            self._rate_limit
                            * _math.exp(-dt / self._rate_tau),
                        )
            await asyncio.sleep(self._rate_interval)

    def _grv_backlog(self) -> int:
        """Requests currently parked in the admission throttle (the
        token schedule's lead over now, in request slots) — the wire
        GRV front door's queue-depth sensor."""
        import time as _time

        rate = self._rate_limit
        if self._rk_conn is None or rate == float("inf"):
            return 0
        return max(
            0, int((self._grv_next_slot - _time.monotonic()) * rate)
        )

    async def _grv_admit(self) -> None:
        """Arrival-spacing token bucket: each admit takes the next
        1/rate-spaced slot; the slot may lag `now` by up to the burst
        allowance (0.1s of budget), and a backlog past the bounded
        queue sheds with the retryable grv_throttled error."""
        import time as _time

        from foundationdb_tpu.cluster.grv_proxy import GrvThrottledError

        rate = self._rate_limit
        if rate == float("inf"):
            return
        rate = max(rate, 1e-3)
        now = _time.monotonic()
        burst = max(1.0, rate * 0.1)
        slot = max(self._grv_next_slot, now - burst / rate) + 1.0 / rate
        backlog = slot - now
        if backlog * rate > self.max_grv_queue:
            # the slot is NOT consumed: a shed request must not push
            # the schedule further out for the next arrival
            self.grv_sheds += 1
            raise GrvThrottledError()
        self._grv_next_slot = slot
        if backlog > 0:
            self.grv_throttle_waits += 1
            await asyncio.sleep(backlog)

    async def get_read_version(self) -> int:
        if self._rk_conn is not None:
            # admission control gates HERE and only here: an admitted
            # transaction's resolve/commit path is byte-identical to
            # the unthrottled one (decision parity)
            await self._grv_admit()
        self.grvs_served += 1
        self.smoothed_grv_rate.add_delta(1.0)
        return self.committed_version

    # -- saturation sensors ------------------------------------------------

    def saturation(self) -> dict:
        """The wire commit proxy's qos block: in-flight batch depth
        (the stage-overlap window), queued requests (smoothed +
        instantaneous), the apply backlog behind the replies, and the
        AdaptiveBatchSizer's live interval/count/bytes targets."""
        return {
            "inflight_batches": self._batches_inflight,
            "queued_requests": len(self._queue),
            "smoothed_queued_requests": (
                self.smoothed_queue_depth.smooth_total()
            ),
            "batches_started": self._batch_seq,
            "batches_logged": self._latest_batch_logging.get(),
            "apply_backlog_versions": max(
                0, self._last_enqueued_apply - self.applied_version
            ),
            "apply_queue_batches": len(self._apply_queue),
            "read_backlog_keys": len(self._read_pending),
            "batch_sizer": self.batch_sizer.as_dict(),
            "failed": self.failed is not None,
        }

    def grv_saturation(self) -> dict:
        """The wire GRV front door's qos block (this process serves
        read versions directly off the committed head)."""
        return {
            # the admission throttle's backlog: callers parked inside
            # _grv_admit waiting for their token slot. Without a
            # ratekeeper the front door answers synchronously (the
            # read-coalescer backlog is the proxy block's
            # read_backlog_keys) — then this is genuinely 0.
            "queued_requests": self._grv_backlog(),
            "grvs_served": self.grvs_served,
            "grv_per_s": self.smoothed_grv_rate.smooth_rate(),
            "committed_version": self.committed_version,
            "applied_version": self.applied_version,
            # admission-control surface (None == unthrottled: no
            # ratekeeper connection configured)
            "transactions_per_second_limit": (
                self._rate_limit
                if self._rate_limit != float("inf") else None
            ),
            "budget_limited_by": self._rate_info.get("budget_limited_by"),
            "budget_stale": self._rate_stale,
            "sheds": self.grv_sheds,
            "throttle_waits": self.grv_throttle_waits,
            "max_queue": self.max_grv_queue,
        }

    async def commit(self, txn: CommitTransaction) -> int:
        """Returns the commit version or raises NotCommittedError."""
        loop = self._loop or asyncio.get_event_loop()
        fut = loop.create_future()
        if self.failed is not None:
            fut.set_exception(
                transport.RemoteError(
                    f"commit pipeline failed: {self.failed!r}"
                )
            )
            return await fut
        self._queue.append((txn, fut))
        return await fut

    async def read(self, key: bytes, version: int) -> Optional[bytes]:
        """Versioned point read, coalesced: reads enqueued in the same
        event-loop turn go out as ONE StorageGetBatch roundtrip (each
        key still served at its own version server-side)."""
        loop = self._loop or asyncio.get_event_loop()
        fut = loop.create_future()
        self._read_pending.append((key, version, fut))
        if not self._read_flush_scheduled:
            self._read_flush_scheduled = True
            loop.call_soon(self._flush_reads)
        return await fut

    def _flush_reads(self) -> None:
        self._read_flush_scheduled = False
        pending, self._read_pending = self._read_pending, []
        if pending:
            t = asyncio.ensure_future(self._read_batch(pending))
            self._inflight.add(t)
            t.add_done_callback(self._inflight.discard)

    async def _read_batch(self, pending) -> None:
        try:
            rep = await self.storage.call(
                TOKEN_STORAGE_GET_BATCH,
                StorageGetBatch(
                    versions=[v for _k, v, _f in pending],
                    keys=[k for k, _v, _f in pending],
                ),
            )
            for (_k, _v, fut), val in zip(pending, rep.values):
                if not fut.done():
                    fut.set_result(val)
        except Exception as e:
            for _k, _v, fut in pending:
                if not fut.done():
                    fut.set_exception(
                        transport.RemoteError(f"read batch: {e!r}")
                    )

    async def _applier(self) -> None:
        """Single ordered drain of the apply queue: many versions per
        StorageApplyBatch RPC. Append order IS commit order (appends
        happen synchronously after each batch's logging-chain set)."""
        while True:
            await self._apply_event.wait()
            self._apply_event.clear()
            while self._apply_queue:
                q, self._apply_queue = self._apply_queue, []
                try:
                    await self.storage.call(
                        TOKEN_STORAGE_APPLY_BATCH,
                        StorageApplyBatch(
                            versions=[v for v, _m in q],
                            groups=[m for _v, m in q],
                        ),
                    )
                except Exception as e:
                    if self.failed is None:
                        self.failed = e
                    return
                self.applied_version = q[-1][0]
                if self.trace:
                    from foundationdb_tpu.utils import commit_debug as _cdbg
                    from foundationdb_tpu.utils import trace as _tr

                    for v, m in q:
                        if m:
                            _tr.g_trace_batch.add_event(
                                "CommitDebug", _cdbg.version_id(v),
                                _cdbg.STORAGE_APPLIED,
                            )

    async def _batcher(self) -> None:
        from foundationdb_tpu.cluster.batching import commit_txn_bytes

        while True:
            await asyncio.sleep(self.batch_sizer.interval)
            if not self._queue:
                continue
            sizer = self.batch_sizer
            count_target = min(sizer.target_count, self.max_batch)
            take, nbytes = 0, 0
            for txn, _f in self._queue:
                if take >= count_target or nbytes >= sizer.target_bytes:
                    break
                take += 1
                nbytes += commit_txn_bytes(txn)
            batch, self._queue = self._queue[:take], self._queue[take:]
            was_full = bool(self._queue) or take >= count_target
            if was_full:
                sizer.batch_full()
            else:
                sizer.batch_underfull(take)
            # bounded pipeline depth: acquire BEFORE allocating the
            # version so a stalled chain backpressures the batcher
            # instead of growing an unbounded in-flight set
            await self._depth.acquire()
            self._batch_seq += 1
            num = self._batch_seq
            # phase 1, synchronous at spawn: version allocation
            # (monotonic across failed attempts — a dead batch consumed
            # its version; the reference master never re-hands one) and
            # the prev_version chain hand-off, in batch order.
            version = (
                max(self.committed_version, self._last_allocated)
                + self.version_step
            )
            self._last_allocated = version
            prev_version, self._chain_prev = self._chain_prev, version
            t = asyncio.ensure_future(
                self._commit_batch(batch, num, prev_version, version,
                                   was_full)
            )
            self._inflight.add(t)
            self._batches_inflight += 1
            self.smoothed_queue_depth.set_total(len(self._queue))

            def _done(_f, t=t):
                self._inflight.discard(t)
                self._batches_inflight -= 1
                self._depth.release()

            t.add_done_callback(_done)

    async def _commit_batch(
        self, batch, num, prev_version, version, was_full
    ) -> None:
        try:
            await self._commit_batch_traced(
                batch, num, prev_version, version, was_full
            )
        except Exception as e:
            # A hole in the version chain breaks this proxy generation:
            # fail the batch's clients, mark the pipeline failed, and
            # advance the ordering chains so successors fail fast
            # instead of wedging on when_at_least forever.
            if self.failed is None:
                self.failed = e
            for _txn, fut in batch:
                if not fut.done():
                    fut.set_exception(
                        transport.RemoteError(f"commit pipeline: {e!r}")
                    )
            if num > self._latest_batch_logging.get():
                self._latest_batch_logging.set(num)

    async def _commit_batch_traced(
        self, batch, num, prev_version, version, was_full
    ) -> None:
        if not self.trace:
            await self._commit_batch_impl(
                batch, num, prev_version, version, was_full, None, None
            )
            return
        from foundationdb_tpu.utils import commit_debug as _cdbg
        from foundationdb_tpu.utils import trace as _tr
        from foundationdb_tpu.utils.spans import Span

        dbg = f"pipe-b{num}"
        for t, _f in batch:
            if t.debug_id is not None:
                _tr.g_trace_batch.add_attach(
                    "CommitAttachID", t.debug_id, dbg
                )
        _tr.g_trace_batch.add_event("CommitDebug", dbg, _cdbg.BATCH_BEFORE)
        with Span("ProxyPipeline.commitBatch") as span:
            span.attribute("Txns", len(batch))
            await self._commit_batch_impl(
                batch, num, prev_version, version, was_full, dbg, span
            )

    async def _commit_batch_impl(
        self, batch, num, prev_version, version, was_full, dbg, span
    ) -> None:
        if self.failed is not None:
            raise PipelineFailedError(repr(self.failed))
        loop = asyncio.get_event_loop()
        txns = [t for t, _f in batch]
        if dbg is not None:
            from foundationdb_tpu.utils import commit_debug as _cdbg
            from foundationdb_tpu.utils import trace as _tr

            _tr.g_trace_batch.add_event(
                "CommitDebug", dbg, _cdbg.BATCH_GOT_VERSION
            )
        # phase 2: resolution — fired IMMEDIATELY (no wait on batch N:
        # the resolver's own prev_version chain serializes versions
        # server-side, Resolver.actor.cpp:269-290), so batch N+1's
        # resolve overlaps batch N's logging. All resolvers see the full
        # batch; verdicts min-combine (CommitProxyServer:1551-1567).
        # The resolve hop carries CONFLICT METADATA only — ranges, read
        # snapshot, per-txn debug id — never the data mutations, which
        # stay proxy-side for the tlog push (the resolver's verdict
        # doesn't read them): mutation bytes off the wire roughly
        # halves resolve encode+decode for write-heavy batches. On the
        # columnar path (default) that metadata packs ONCE into the
        # flat interval-array layout the resolver kernel consumes —
        # per-txn counts + versions + one joined key blob — instead of
        # per-txn objects the resolver would re-flatten.
        if self._columnar:
            from foundationdb_tpu.utils import packing as _packing

            req = codec.ResolveBatchColumnar(
                prev_version=prev_version,
                version=version,
                last_received_version=prev_version,
                cols=_packing.pack_columnar(txns),
                debug_id=dbg,
                span=span.context.as_tuple() if span is not None else None,
            )
            if dbg is not None:
                _tr.g_trace_batch.add_event(
                    "CommitDebug", dbg, _cdbg.PROXY_COLUMNAR_PACK
                )
        else:
            req = ResolveTransactionBatchRequest(
                prev_version=prev_version,
                version=version,
                last_received_version=prev_version,
                transactions=(
                    [
                        CommitTransaction(
                            read_conflict_ranges=t.read_conflict_ranges,
                            write_conflict_ranges=t.write_conflict_ranges,
                            read_snapshot=t.read_snapshot,
                            report_conflicting_keys=t.report_conflicting_keys,
                            debug_id=t.debug_id,
                        )
                        for t in txns
                    ]
                    if _RESOLVE_STRIP
                    else txns
                ),
                debug_id=dbg,
                span=span.context.as_tuple() if span is not None else None,
            )
        t_resolve = loop.time()
        replies = await asyncio.gather(
            *(r.call(TOKEN_RESOLVE, req) for r in self.resolvers)
        )
        resolve_s = loop.time() - t_resolve
        if dbg is not None:
            _tr.g_trace_batch.add_event(
                "CommitDebug", dbg, _cdbg.BATCH_AFTER_RESOLUTION
            )
        verdicts = [
            min(int(rep.committed[i]) for rep in replies)
            for i in range(len(txns))
        ]
        # phase 3: collect committed mutations
        mutations = []
        for t, v in zip(txns, verdicts):
            if v == TransactionResult.COMMITTED:
                mutations.extend(t.mutations)
        # phase 4: log — ordered at the logging chain hand-off only
        if dbg is not None:
            _tr.TraceEvent(
                "CommitDebugVersion", severity=_tr.SEV_DEBUG
            ).detail("ID", dbg).detail("Version", version).detail(
                "Messages", 1 if mutations else 0
            ).log()
        await self._latest_batch_logging.when_at_least(num - 1)
        if self.failed is not None:
            raise PipelineFailedError(repr(self.failed))
        t_log = loop.time()
        await self.tlog.call(
            TOKEN_TLOG_PUSH,
            TLogPush(
                version=version,
                prev_version=prev_version,
                mutations=mutations,
            ),
        )
        log_s = loop.time() - t_log
        if dbg is not None:
            _tr.g_trace_batch.add_event(
                "CommitDebug", dbg, _cdbg.TLOG_AFTER_COMMIT
            )
            _tr.g_trace_batch.add_event(
                "CommitDebug", dbg, _cdbg.BATCH_AFTER_LOG_PUSH
            )
        self.prev_version = version
        self.committed_version = version
        # guarded like the error path: a FAILED successor batch advances
        # the chain past us (fail-fast for its own successors), and an
        # unguarded set(num) here would raise Notified-must-not-decrease
        # AFTER our push is durable — turning a committed batch into a
        # client error and skipping its storage apply while
        # committed_version already advanced (reads at our GRV would
        # wedge server-side until the RPC timeout)
        if num > self._latest_batch_logging.get():
            self._latest_batch_logging.set(num)
        self.batch_sizer.observe_stage_latency(
            resolve_s + log_s, full=was_full
        )
        # phase 5: replies fire as soon as OUR push is durable — no
        # wait for storage. The chain hand-off above makes replies
        # version-ordered: batch N's reply loop runs synchronously
        # after set(num=N) and before N+1 can resume from its wait.
        for (txn, fut), v in zip(batch, verdicts):
            if fut.done():
                continue
            if v == TransactionResult.COMMITTED:
                fut.set_result(version)
            else:
                fut.set_exception(NotCommittedError(TransactionResult(v).name))
        # phase 6: storage apply rides the applier's ordered queue
        # BEHIND the replies (the storage pull loop collapsed into a
        # batched ordered push; versioned reads wait server-side for the
        # version they need, so a lagging apply costs read latency,
        # never correctness). Appended with no await since the logging
        # set above — queue order IS commit order.
        self._apply_queue.append((version, mutations))
        self._last_enqueued_apply = version
        self._apply_event.set()


def _tls_from_env():
    """Cluster TLS the way the reference's fdbserver picks it up from
    TLSConfig/environment (flow/TLSConfig.actor.cpp:
    TLS_CERTIFICATE_FILE etc.): FDB_TPU_TLS_DIR names a directory with
    ca.crt + node.crt/node.key (crypto.tls.make_test_tls layout); all
    roles and clients then speak mutual TLS under that CA."""
    tls_dir = os.environ.get("FDB_TPU_TLS_DIR")
    if not tls_dir:
        return None
    from foundationdb_tpu.crypto.tls import TLSConfig

    return TLSConfig(
        ca_file=os.path.join(tls_dir, "ca.crt"),
        cert_file=os.path.join(tls_dir, "node.crt"),
        key_file=os.path.join(tls_dir, "node.key"),
    )


async def connect(address, **kw) -> transport.RpcConnection:
    conn = transport.RpcConnection(address, tls=_tls_from_env())
    # generous default retry budget: a tpu-force resolver role warm-
    # compiles its kernels BEFORE binding the socket (so the compile
    # stall can never hide inside the first commit batch), which can
    # take tens of seconds on a cold jit cache
    kw.setdefault("retries", 1200)
    await conn.connect(**kw)
    return conn


# ---------------------------------------------------------------------------
# Wire-mode status aggregation (the fdbtop substrate).


def _pipeline_status_blocks(pipeline: "ProxyPipeline") -> dict[str, dict]:
    """The parent process's own process blocks: it plays both proxies
    in wire mode (commit batching + the GRV front door)."""
    return {
        "proxy0": {
            "role": "commit_proxy",
            "committed_version": pipeline.committed_version,
            "qos": pipeline.saturation(),
        },
        "grv_proxy0": {
            "role": "grv_proxy",
            "qos": pipeline.grv_saturation(),
        },
    }


async def wire_cluster_status(
    roles: dict[str, transport.RpcConnection],
    pipeline: "ProxyPipeline" = None,
    *,
    lag_target: float = 2_000_000.0,
) -> dict:
    """Reference-shaped status JSON for a wire-mode cluster: one
    StatusRequest RPC per role process, plus the parent pipeline's own
    proxy blocks, assembled through the SAME qos math as the sim
    `cluster_status()` (cluster/status.py assemble_status)."""
    import json as _json

    from foundationdb_tpu.cluster.status import assemble_status

    procs: dict[str, dict] = {}
    for name, conn in roles.items():
        reply = await conn.call(TOKEN_STATUS, StatusRequest(pad=0))
        procs[name] = _json.loads(reply.payload)
    if pipeline is not None:
        procs.update(_pipeline_status_blocks(pipeline))
    return assemble_status(procs, lag_target=lag_target)


def serve_status(
    socket_dir: str, pipeline: "ProxyPipeline"
) -> transport.RpcServer:
    """Parent-side status endpoint: an RpcServer on proxy0.sock in the
    role socket dir, answering StatusRequest with the pipeline's OWN
    proxy blocks — so an external fdbtop polling the socket dir sees
    the commit/GRV proxy sensors next to the role processes' (the
    parent is just another process with a status socket). Caller must
    `await server.start()` and close it at teardown."""
    import json as _json

    address = os.path.join(socket_dir, "proxy0.sock")
    server = transport.RpcServer(address, tls=_tls_from_env())

    async def status(_req: StatusRequest) -> StatusReply:
        blocks = _pipeline_status_blocks(pipeline)
        payload = blocks["proxy0"]
        # the GRV block rides along; fdbtop splits it out into its own
        # process row (one socket, both proxy roles)
        payload["grv_proxy"] = blocks["grv_proxy0"]
        return StatusReply(payload=_json.dumps(payload))

    server.register(TOKEN_STATUS, status)
    return server


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--role", required=True)
    ap.add_argument("--address", required=True)
    ap.add_argument("--backend", default="native")
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--tlog-address", default=None)
    ap.add_argument("--storage-engine", default="memory",
                    choices=("memory", "lsm"))
    ap.add_argument("--encrypt", action="store_true")
    ap.add_argument("--trace-file", default=None)
    ap.add_argument("--peers", default=None,
                    help="ratekeeper: comma list of peer role sockets "
                         "to poll StatusRequest sensors from")
    args = ap.parse_args()
    asyncio.run(
        _serve_role(
            args.role,
            args.address,
            args.backend,
            data_dir=args.data_dir,
            tlog_address=args.tlog_address,
            storage_engine=args.storage_engine,
            encrypt=args.encrypt,
            trace_file=args.trace_file,
            peers=args.peers.split(",") if args.peers else None,
        )
    )


if __name__ == "__main__":
    main()
