"""GrvProxy: batched read-version service.

Behavioral mirror of `fdbserver/GrvProxyServer.actor.cpp`:

* Requests queue and are answered in batches (`transactionStarter` :824)
  on a short interval — one live-committed-version fetch serves the whole
  batch (the reference's GRV batching amortizes the master round-trip and
  the TLog epoch-liveness quorum).
* The reply version is the Sequencer's live committed version
  (`getLiveCommittedVersion` :617): every commit at or below it is
  durable, so reads at this version are causally consistent.
* Admission control (Ratekeeper budget, :364) hooks in as a configurable
  per-batch budget; the v0 Ratekeeper grants infinity.
"""

from __future__ import annotations

from foundationdb_tpu.runtime.flow import Promise, PromiseStream, Scheduler
from foundationdb_tpu.utils.metrics import CounterCollection


class GrvProxyFailedError(Exception):
    """Retryable: this GRV proxy generation died (recovery replaced it);
    the client's retry loop re-resolves the current generation."""


class GrvProxy:
    def __init__(
        self,
        sched: Scheduler,
        sequencer,
        *,
        ratekeeper=None,
        batch_interval: float = 0.001,
    ):
        self.sched = sched
        self.sequencer = sequencer
        self.ratekeeper = ratekeeper
        self.batch_interval = batch_interval
        self.requests = PromiseStream()
        self.counters = CounterCollection(
            "GrvProxyMetrics", ["txnRequestIn", "txnRequestOut", "grvBatches"]
        )
        self._pending: list[Promise] = []
        self._task = None
        self._armed = None  # the starter's in-flight stream waiter

    def start(self) -> None:
        self._task = self.sched.spawn(self._starter(), name="grv-starter")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        # Fail everything queued or batched: a dangling read-version
        # promise would strand its client forever across a recovery.
        for p in self._pending:
            if not p.is_set:
                p.send_error(GrvProxyFailedError())
        self._pending = []
        # A request delivered into the starter's armed stream waiter but
        # not yet consumed (the cancel landed between send() and the
        # task's resumption) is invisible to both _pending and the
        # queue — recover it from the tracked waiter.
        if self._armed is not None:
            if self._armed.is_ready and not self._armed.is_error:
                p = self._armed.get()
                if not p.is_set:
                    p.send_error(GrvProxyFailedError())
            self._armed = None
        queue = self.requests.stream._queue
        while queue:
            p = queue.pop(0)
            if not p.is_set:
                p.send_error(GrvProxyFailedError())

    def get_read_version(self) -> Promise:
        p = Promise()
        self.counters.add("txnRequestIn")
        if self._task is None:
            # Stopped proxy (the recovery window between the old
            # generation stopping and the new one starting): a request
            # queued into the dead stream would strand its client
            # forever — fail fast with the retryable error instead.
            p.send_error(GrvProxyFailedError())
            return p
        self.requests.send(p)
        return p

    async def _starter(self) -> None:
        # Token bucket fed by the Ratekeeper budget (transactionStarter's
        # "transactionRate" accounting, GrvProxyServer.actor.cpp:824).
        pending = self._pending
        tokens = 0.0
        last = self.sched.now()
        while True:
            if not pending:
                self._armed = self.requests.stream.next()
                pending.append(await self._armed)
                self._armed = None
            await self.sched.delay(self.batch_interval)
            while True:
                ok, p = self.requests.stream.try_next()
                if not ok:
                    break
                pending.append(p)

            now = self.sched.now()
            if self.ratekeeper is not None:
                tps = self.ratekeeper.get_rate_info()
                tokens = min(
                    tokens + tps * (now - last), max(tps * 0.1, 1.0)
                )
            else:
                tokens = float(len(pending))
            last = now
            n = min(len(pending), int(tokens))
            if n == 0:
                continue
            tokens -= n
            batch = pending[:n]
            del pending[:n]
            version = self.sequencer.get_live_committed_version()
            self.counters.add("grvBatches")
            for p in batch:
                self.counters.add("txnRequestOut")
                p.send(version)
