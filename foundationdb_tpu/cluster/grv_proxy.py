"""GrvProxy: batched read-version service.

Behavioral mirror of `fdbserver/GrvProxyServer.actor.cpp`:

* Requests queue and are answered in batches (`transactionStarter` :824)
  on a short interval — one live-committed-version fetch serves the whole
  batch (the reference's GRV batching amortizes the master round-trip and
  the TLog epoch-liveness quorum).
* The reply version is the Sequencer's live committed version
  (`getLiveCommittedVersion` :617): every commit at or below it is
  durable, so reads at this version are causally consistent.
* Admission control (Ratekeeper budget, :364) hooks in as a configurable
  per-batch budget; the v0 Ratekeeper grants infinity.
"""

from __future__ import annotations

from foundationdb_tpu.runtime.flow import Promise, PromiseStream, Scheduler
from foundationdb_tpu.utils import commit_debug as _cd
from foundationdb_tpu.utils import trace as _trace
from foundationdb_tpu.utils.metrics import (
    GRV_LATENCY_BANDS,
    CounterCollection,
    LatencyBands,
    LatencySample,
)
from foundationdb_tpu.utils.probes import declare

declare("ratekeeper.tag_throttled", "grv.throttled")


class GrvProxyFailedError(Exception):
    """Retryable: this GRV proxy generation died (recovery replaced it);
    the client's retry loop re-resolves the current generation."""


class GrvThrottledError(Exception):
    """Retryable: the GRV queue is over its bound under admission
    control — the front door SHEDS the request instead of queueing it
    unboundedly (the reference's GRV proxy drops requests past
    START_TRANSACTION_MAX_QUEUE_SIZE the same way). Clients back off
    and retry; offered load past capacity degrades into delayed admits
    plus retryable sheds, never into an unbounded promise queue."""


class GrvProxy:
    def __init__(
        self,
        sched: Scheduler,
        sequencer,
        *,
        ratekeeper=None,
        batch_interval: float = 0.001,
        max_queue: int = None,
    ):
        self.sched = sched
        self.sequencer = sequencer
        self.ratekeeper = ratekeeper
        self.batch_interval = batch_interval
        from foundationdb_tpu.utils.knobs import SERVER_KNOBS as _SK

        #: bounded GRV queue: requests past this depth are SHED with the
        #: retryable GrvThrottledError instead of queued (overload must
        #: degrade gracefully, not accumulate an unbounded promise list)
        self.max_queue = (
            max_queue if max_queue is not None
            else _SK.GRV_PROXY_MAX_QUEUE
        )
        # fail-safe state: when the Ratekeeper's budget goes STALE (the
        # loop died or stopped updating), the effective budget decays
        # toward the Ratekeeper's conservative floor instead of
        # freezing at the last (possibly full-speed) value
        self._failsafe_budget: float | None = None
        self._effective_tps: float = float("inf")
        self._budget_stale = False
        # Adaptive GRV batching (GrvProxyServer's START_TRANSACTION_
        # BATCH_* discipline): the accumulation interval shrinks while
        # requests keep arriving faster than batches go out and relaxes
        # when the queue drains underfull — same controller as the
        # commit proxy (cluster/batching.py), knob-bounded.
        from foundationdb_tpu.cluster.batching import AdaptiveBatchSizer
        from foundationdb_tpu.utils.knobs import SERVER_KNOBS as _K

        # max_interval capped at the ctor interval: the controller only
        # shrinks the window under load; idle cadence is unchanged
        self.batch_sizer = AdaptiveBatchSizer(
            interval=batch_interval,
            min_interval=min(
                batch_interval, _K.START_TRANSACTION_BATCH_INTERVAL_MIN
            ),
            max_interval=min(
                batch_interval, _K.START_TRANSACTION_BATCH_INTERVAL_MAX
            ),
            target_count=_K.START_TRANSACTION_BATCH_COUNT_MAX,
            max_count=_K.START_TRANSACTION_BATCH_COUNT_MAX,
            alpha=_K.START_TRANSACTION_BATCH_INTERVAL_SMOOTHER_ALPHA,
        )
        self.requests = PromiseStream()
        self.counters = CounterCollection(
            "GrvProxyMetrics",
            ["txnRequestIn", "txnRequestOut", "grvBatches", "grvShed"],
        )
        # GRV latency distribution + reference-style latency bands
        # (GrvProxyServer.actor.cpp grvLatencyBands), in virtual time
        self.grv_latency = LatencySample("grvLatency")
        self.latency_bands = LatencyBands(
            "GRVLatencyMetrics", GRV_LATENCY_BANDS
        )
        self._pending: list[Promise] = []
        self._task = None
        self._armed = None  # the starter's in-flight stream waiter
        self._tag_tokens: dict[str, float] = {}  # per-tag throttle buckets

    def start(self) -> None:
        self._task = self.sched.spawn(self._starter(), name="grv-starter")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        # Fail everything queued or batched: a dangling read-version
        # promise would strand its client forever across a recovery.
        for p in self._pending:
            if not p.is_set:
                p.send_error(GrvProxyFailedError())
        self._pending = []
        # A request delivered into the starter's armed stream waiter but
        # not yet consumed (the cancel landed between send() and the
        # task's resumption) is invisible to both _pending and the
        # queue — recover it from the tracked waiter.
        if self._armed is not None:
            if self._armed.is_ready and not self._armed.is_error:
                p = self._armed.get()
                if not p.is_set:
                    p.send_error(GrvProxyFailedError())
            self._armed = None
        queue = self.requests.stream._queue
        while queue:
            p = queue.pop(0)
            if not p.is_set:
                p.send_error(GrvProxyFailedError())

    def saturation(self) -> dict:
        """The GRV proxy's qos sensor block: read-version queue depth
        (requests admitted but not yet answered — the front-door queue
        the Ratekeeper budget throttles), the live batch-sizer targets,
        and the tags currently metered by a throttle bucket."""
        tps = self._effective_tps
        return {
            "queued_requests": (
                len(self._pending) + len(self.requests.stream._queue)
            ),
            "max_queue": self.max_queue,
            "transactions_per_second_limit": (
                tps if tps != float("inf") else None
            ),
            "budget_stale": self._budget_stale,
            "sheds": self.counters.get("grvShed"),
            "batch_sizer": self.batch_sizer.as_dict(),
            "throttled_tags": sorted(
                t for t, tok in self._tag_tokens.items()
                if tok != float("inf")
            ),
        }

    def get_read_version(self, tag: str = None) -> Promise:
        """tag: optional transaction tag; tagged requests are metered
        against the Ratekeeper's per-tag quota (GlobalTagThrottler's
        enforcement point) on top of the global budget."""
        p = Promise()
        # normalize falsy tags (e.g. "") to None: the admit loop and the
        # refill set must agree on what counts as "tagged", or an
        # empty-string tag reaches the bucket dict without a bucket
        p.tag = tag or None
        p.debug_id = None  # the client sets it before yielding (tracing)
        p.grv_start = self.sched.now()
        self.counters.add("txnRequestIn")
        if self._task is None:
            # Stopped proxy (the recovery window between the old
            # generation stopping and the new one starting): a request
            # queued into the dead stream would strand its client
            # forever — fail fast with the retryable error instead.
            p.send_error(GrvProxyFailedError())
            return p
        if (
            self.max_queue is not None
            and len(self._pending) + len(self.requests.stream._queue)
            >= self.max_queue
        ):
            # bounded front-door queue: shed with the retryable
            # throttle error — delayed-or-shed at GRV is the ONLY
            # admission-control enforcement point (decision parity:
            # an admitted transaction resolves identically to the
            # unthrottled path)
            from foundationdb_tpu.utils.probes import code_probe

            self.counters.add("grvShed")
            code_probe(True, "grv.throttled")
            p.send_error(GrvThrottledError())
            return p
        self.requests.send(p)
        return p

    async def _starter(self) -> None:
        # Token bucket fed by the Ratekeeper budget (transactionStarter's
        # "transactionRate" accounting, GrvProxyServer.actor.cpp:824).
        # Queue accesses go through self._pending directly: stop()
        # REASSIGNS the list after failing the queued promises, and a
        # pre-await alias here would keep feeding the dead list if a
        # step ever interleaved with stop() (flow.stale-read-across-wait
        # caught the alias; cancellation only masks it today).
        tokens = 0.0
        last = self.sched.now()
        while True:
            if not self._pending:
                self._armed = self.requests.stream.next()
                # await FIRST, then touch the queue: in
                # `self._pending.append(await ...)` the bound method
                # holds the pre-await list object, which is exactly the
                # stale alias this function no longer keeps (stop()
                # reassigns the list while we are suspended here)
                p = await self._armed
                self._pending.append(p)
                self._armed = None
            await self.sched.delay(self.batch_sizer.interval)
            while True:
                ok, p = self.requests.stream.try_next()
                if not ok:
                    break
                self._pending.append(p)

            now = self.sched.now()
            dt = now - last
            last = now
            if self.ratekeeper is not None:
                tps = self.ratekeeper.get_rate_info()
                # fail-safe: a dead/flapping Ratekeeper (control loop
                # not updating) must not be trusted at full speed — the
                # effective budget decays toward the conservative
                # failsafe floor until fresh budgets flow again
                age_fn = getattr(self.ratekeeper, "budget_age", None)
                stale_after = 4.0 * getattr(
                    self.ratekeeper, "interval", 0.25
                )
                stale = (
                    age_fn is not None and age_fn(now) > stale_after
                )
                if stale:
                    import math as _math

                    from foundationdb_tpu.cluster.ratekeeper import (
                        FAILSAFE_TAU,
                    )
                    from foundationdb_tpu.utils.probes import code_probe

                    floor = getattr(
                        self.ratekeeper, "failsafe_tps", 10.0
                    )
                    tau = getattr(
                        self.ratekeeper, "failsafe_tau", FAILSAFE_TAU
                    )
                    if self._failsafe_budget is None:
                        self._failsafe_budget = max(tps, floor)
                        code_probe(True, "ratekeeper.failsafe")
                    self._failsafe_budget = max(
                        floor,
                        self._failsafe_budget
                        * _math.exp(-max(dt, 0.0) / tau),
                    )
                    tps = min(tps, self._failsafe_budget)
                else:
                    self._failsafe_budget = None
                self._budget_stale = stale
                self._effective_tps = tps
                # token bucket with a burst cap: at most ~100ms of
                # budget (never less than one token) accumulates idle
                tokens = min(
                    tokens + tps * dt, max(tps * 0.1, 1.0)
                )
            else:
                self._budget_stale = False
                self._effective_tps = float("inf")
                tokens = float(len(self._pending))
            n = min(len(self._pending), int(tokens))
            if n == 0:
                continue
            tokens -= n
            batch = self._pending[:n]
            del self._pending[:n]
            # per-tag metering: requests over their tag's quota are
            # deferred back to the queue (the tag throttle delays, never
            # drops — GlobalTagThrottler semantics)
            if self.ratekeeper is not None and any(
                getattr(p, "tag", None) for p in batch
            ):
                from foundationdb_tpu.utils.probes import code_probe

                # refill each tag's bucket ONCE per interval (not per
                # request — that would scale the quota by queue depth)
                tags = {p.tag for p in batch if getattr(p, "tag", None)}
                for tag in tags:
                    quota = self.ratekeeper.get_tag_quota(tag)
                    if quota == float("inf"):
                        self._tag_tokens[tag] = float("inf")
                        continue
                    self._tag_tokens[tag] = min(
                        self._tag_tokens.get(tag, 0.0)
                        + quota * max(dt, 1e-9),
                        max(quota * 0.5, 1.0),
                    )
                admit, defer = [], []
                for p in batch:
                    tag = getattr(p, "tag", None)
                    if tag is None or self._tag_tokens[tag] >= 1.0:
                        if tag is not None:
                            self._tag_tokens[tag] -= 1.0
                            # busyness signal for the auto tag throttler
                            self.ratekeeper.note_tag_admission(tag)
                        admit.append(p)
                    else:
                        code_probe(True, "ratekeeper.tag_throttled")
                        defer.append(p)
                # deferred requests were never started: refund their
                # global tokens so a throttled tag flood cannot starve
                # untagged traffic
                tokens += len(defer)
                self._pending.extend(defer)
                batch = admit
                if not batch:
                    continue
            version = self.sequencer.get_live_committed_version()
            self.counters.add("grvBatches")
            ctx = next(
                (p.span_ctx for p in batch
                 if getattr(p, "span_ctx", None) is not None),
                None,
            )
            if ctx is not None:
                # one span per GRV batch, parented on the first traced
                # request's client span (the commitBatch discipline)
                from foundationdb_tpu.utils.spans import Span

                with Span(
                    "GrvProxy.transactionStarter", parent=ctx,
                    clock=self.sched.now,
                ) as s:
                    s.attribute("Txns", len(batch))
            for p in batch:
                self.counters.add("txnRequestOut")
                dt = now - getattr(p, "grv_start", now)
                self.grv_latency.sample(dt)
                self.latency_bands.add(dt)
                if getattr(p, "debug_id", None) is not None:
                    _trace.g_trace_batch.add_event(
                        "TransactionDebug", p.debug_id, _cd.GRV_REPLY
                    )
                p.send(version)
            # interval feedback: requests still waiting after a dispatch
            # mean the window is too long (shrink toward the MIN knob);
            # a drained queue relaxes it back to the configured cadence
            if self._pending or self.requests.stream._queue:
                self.batch_sizer.batch_full()
            else:
                self.batch_sizer.batch_underfull(len(batch))
