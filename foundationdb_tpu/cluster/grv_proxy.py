"""GrvProxy: batched read-version service.

Behavioral mirror of `fdbserver/GrvProxyServer.actor.cpp`:

* Requests queue and are answered in batches (`transactionStarter` :824)
  on a short interval — one live-committed-version fetch serves the whole
  batch (the reference's GRV batching amortizes the master round-trip and
  the TLog epoch-liveness quorum).
* The reply version is the Sequencer's live committed version
  (`getLiveCommittedVersion` :617): every commit at or below it is
  durable, so reads at this version are causally consistent.
* Admission control (Ratekeeper budget, :364) hooks in as a configurable
  per-batch budget; the v0 Ratekeeper grants infinity.
"""

from __future__ import annotations

from foundationdb_tpu.runtime.flow import Promise, PromiseStream, Scheduler
from foundationdb_tpu.utils.metrics import CounterCollection


class GrvProxy:
    def __init__(
        self,
        sched: Scheduler,
        sequencer,
        *,
        batch_interval: float = 0.001,
        rate_budget_per_batch: int = 1 << 30,
    ):
        self.sched = sched
        self.sequencer = sequencer
        self.batch_interval = batch_interval
        self.rate_budget_per_batch = rate_budget_per_batch
        self.requests = PromiseStream()
        self.counters = CounterCollection(
            "GrvProxyMetrics", ["txnRequestIn", "txnRequestOut", "grvBatches"]
        )
        self._task = None

    def start(self) -> None:
        self._task = self.sched.spawn(self._starter(), name="grv-starter")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    def get_read_version(self) -> Promise:
        p = Promise()
        self.counters.add("txnRequestIn")
        self.requests.send(p)
        return p

    async def _starter(self) -> None:
        while True:
            first = await self.requests.stream.next()
            batch = [first]
            await self.sched.delay(self.batch_interval)
            while (
                len(batch) < self.rate_budget_per_batch
                and not self.requests.stream.is_empty()
            ):
                batch.append(await self.requests.stream.next())
            version = self.sequencer.get_live_committed_version()
            self.counters.add("grvBatches")
            for p in batch:
                self.counters.add("txnRequestOut")
                p.send(version)
