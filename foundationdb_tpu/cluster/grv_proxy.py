"""GrvProxy: batched read-version service.

Behavioral mirror of `fdbserver/GrvProxyServer.actor.cpp`:

* Requests queue and are answered in batches (`transactionStarter` :824)
  on a short interval — one live-committed-version fetch serves the whole
  batch (the reference's GRV batching amortizes the master round-trip and
  the TLog epoch-liveness quorum).
* The reply version is the Sequencer's live committed version
  (`getLiveCommittedVersion` :617): every commit at or below it is
  durable, so reads at this version are causally consistent.
* Admission control (Ratekeeper budget, :364) hooks in as a configurable
  per-batch budget; the v0 Ratekeeper grants infinity.
"""

from __future__ import annotations

from foundationdb_tpu.runtime.flow import Promise, PromiseStream, Scheduler
from foundationdb_tpu.utils import commit_debug as _cd
from foundationdb_tpu.utils import trace as _trace
from foundationdb_tpu.utils.metrics import (
    GRV_LATENCY_BANDS,
    CounterCollection,
    LatencyBands,
    LatencySample,
)
from foundationdb_tpu.utils.probes import declare

declare("ratekeeper.tag_throttled")


class GrvProxyFailedError(Exception):
    """Retryable: this GRV proxy generation died (recovery replaced it);
    the client's retry loop re-resolves the current generation."""


class GrvProxy:
    def __init__(
        self,
        sched: Scheduler,
        sequencer,
        *,
        ratekeeper=None,
        batch_interval: float = 0.001,
    ):
        self.sched = sched
        self.sequencer = sequencer
        self.ratekeeper = ratekeeper
        self.batch_interval = batch_interval
        # Adaptive GRV batching (GrvProxyServer's START_TRANSACTION_
        # BATCH_* discipline): the accumulation interval shrinks while
        # requests keep arriving faster than batches go out and relaxes
        # when the queue drains underfull — same controller as the
        # commit proxy (cluster/batching.py), knob-bounded.
        from foundationdb_tpu.cluster.batching import AdaptiveBatchSizer
        from foundationdb_tpu.utils.knobs import SERVER_KNOBS as _K

        # max_interval capped at the ctor interval: the controller only
        # shrinks the window under load; idle cadence is unchanged
        self.batch_sizer = AdaptiveBatchSizer(
            interval=batch_interval,
            min_interval=min(
                batch_interval, _K.START_TRANSACTION_BATCH_INTERVAL_MIN
            ),
            max_interval=min(
                batch_interval, _K.START_TRANSACTION_BATCH_INTERVAL_MAX
            ),
            target_count=_K.START_TRANSACTION_BATCH_COUNT_MAX,
            max_count=_K.START_TRANSACTION_BATCH_COUNT_MAX,
            alpha=_K.START_TRANSACTION_BATCH_INTERVAL_SMOOTHER_ALPHA,
        )
        self.requests = PromiseStream()
        self.counters = CounterCollection(
            "GrvProxyMetrics", ["txnRequestIn", "txnRequestOut", "grvBatches"]
        )
        # GRV latency distribution + reference-style latency bands
        # (GrvProxyServer.actor.cpp grvLatencyBands), in virtual time
        self.grv_latency = LatencySample("grvLatency")
        self.latency_bands = LatencyBands(
            "GRVLatencyMetrics", GRV_LATENCY_BANDS
        )
        self._pending: list[Promise] = []
        self._task = None
        self._armed = None  # the starter's in-flight stream waiter
        self._tag_tokens: dict[str, float] = {}  # per-tag throttle buckets

    def start(self) -> None:
        self._task = self.sched.spawn(self._starter(), name="grv-starter")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        # Fail everything queued or batched: a dangling read-version
        # promise would strand its client forever across a recovery.
        for p in self._pending:
            if not p.is_set:
                p.send_error(GrvProxyFailedError())
        self._pending = []
        # A request delivered into the starter's armed stream waiter but
        # not yet consumed (the cancel landed between send() and the
        # task's resumption) is invisible to both _pending and the
        # queue — recover it from the tracked waiter.
        if self._armed is not None:
            if self._armed.is_ready and not self._armed.is_error:
                p = self._armed.get()
                if not p.is_set:
                    p.send_error(GrvProxyFailedError())
            self._armed = None
        queue = self.requests.stream._queue
        while queue:
            p = queue.pop(0)
            if not p.is_set:
                p.send_error(GrvProxyFailedError())

    def saturation(self) -> dict:
        """The GRV proxy's qos sensor block: read-version queue depth
        (requests admitted but not yet answered — the front-door queue
        the Ratekeeper budget throttles), the live batch-sizer targets,
        and the tags currently metered by a throttle bucket."""
        return {
            "queued_requests": (
                len(self._pending) + len(self.requests.stream._queue)
            ),
            "batch_sizer": self.batch_sizer.as_dict(),
            "throttled_tags": sorted(
                t for t, tok in self._tag_tokens.items()
                if tok != float("inf")
            ),
        }

    def get_read_version(self, tag: str = None) -> Promise:
        """tag: optional transaction tag; tagged requests are metered
        against the Ratekeeper's per-tag quota (GlobalTagThrottler's
        enforcement point) on top of the global budget."""
        p = Promise()
        # normalize falsy tags (e.g. "") to None: the admit loop and the
        # refill set must agree on what counts as "tagged", or an
        # empty-string tag reaches the bucket dict without a bucket
        p.tag = tag or None
        p.debug_id = None  # the client sets it before yielding (tracing)
        p.grv_start = self.sched.now()
        self.counters.add("txnRequestIn")
        if self._task is None:
            # Stopped proxy (the recovery window between the old
            # generation stopping and the new one starting): a request
            # queued into the dead stream would strand its client
            # forever — fail fast with the retryable error instead.
            p.send_error(GrvProxyFailedError())
            return p
        self.requests.send(p)
        return p

    async def _starter(self) -> None:
        # Token bucket fed by the Ratekeeper budget (transactionStarter's
        # "transactionRate" accounting, GrvProxyServer.actor.cpp:824).
        # Queue accesses go through self._pending directly: stop()
        # REASSIGNS the list after failing the queued promises, and a
        # pre-await alias here would keep feeding the dead list if a
        # step ever interleaved with stop() (flow.stale-read-across-wait
        # caught the alias; cancellation only masks it today).
        tokens = 0.0
        last = self.sched.now()
        while True:
            if not self._pending:
                self._armed = self.requests.stream.next()
                # await FIRST, then touch the queue: in
                # `self._pending.append(await ...)` the bound method
                # holds the pre-await list object, which is exactly the
                # stale alias this function no longer keeps (stop()
                # reassigns the list while we are suspended here)
                p = await self._armed
                self._pending.append(p)
                self._armed = None
            await self.sched.delay(self.batch_sizer.interval)
            while True:
                ok, p = self.requests.stream.try_next()
                if not ok:
                    break
                self._pending.append(p)

            now = self.sched.now()
            if self.ratekeeper is not None:
                tps = self.ratekeeper.get_rate_info()
                tokens = min(
                    tokens + tps * (now - last), max(tps * 0.1, 1.0)
                )
            else:
                tokens = float(len(self._pending))
            dt = now - last
            last = now
            n = min(len(self._pending), int(tokens))
            if n == 0:
                continue
            tokens -= n
            batch = self._pending[:n]
            del self._pending[:n]
            # per-tag metering: requests over their tag's quota are
            # deferred back to the queue (the tag throttle delays, never
            # drops — GlobalTagThrottler semantics)
            if self.ratekeeper is not None and any(
                getattr(p, "tag", None) for p in batch
            ):
                from foundationdb_tpu.utils.probes import code_probe

                # refill each tag's bucket ONCE per interval (not per
                # request — that would scale the quota by queue depth)
                tags = {p.tag for p in batch if getattr(p, "tag", None)}
                for tag in tags:
                    quota = self.ratekeeper.get_tag_quota(tag)
                    if quota == float("inf"):
                        self._tag_tokens[tag] = float("inf")
                        continue
                    self._tag_tokens[tag] = min(
                        self._tag_tokens.get(tag, 0.0)
                        + quota * max(dt, 1e-9),
                        max(quota * 0.5, 1.0),
                    )
                admit, defer = [], []
                for p in batch:
                    tag = getattr(p, "tag", None)
                    if tag is None or self._tag_tokens[tag] >= 1.0:
                        if tag is not None:
                            self._tag_tokens[tag] -= 1.0
                            # busyness signal for the auto tag throttler
                            self.ratekeeper.note_tag_admission(tag)
                        admit.append(p)
                    else:
                        code_probe(True, "ratekeeper.tag_throttled")
                        defer.append(p)
                # deferred requests were never started: refund their
                # global tokens so a throttled tag flood cannot starve
                # untagged traffic
                tokens += len(defer)
                self._pending.extend(defer)
                batch = admit
                if not batch:
                    continue
            version = self.sequencer.get_live_committed_version()
            self.counters.add("grvBatches")
            ctx = next(
                (p.span_ctx for p in batch
                 if getattr(p, "span_ctx", None) is not None),
                None,
            )
            if ctx is not None:
                # one span per GRV batch, parented on the first traced
                # request's client span (the commitBatch discipline)
                from foundationdb_tpu.utils.spans import Span

                with Span(
                    "GrvProxy.transactionStarter", parent=ctx,
                    clock=self.sched.now,
                ) as s:
                    s.attribute("Txns", len(batch))
            for p in batch:
                self.counters.add("txnRequestOut")
                dt = now - getattr(p, "grv_start", now)
                self.grv_latency.sample(dt)
                self.latency_bands.add(dt)
                if getattr(p, "debug_id", None) is not None:
                    _trace.g_trace_batch.add_event(
                        "TransactionDebug", p.debug_id, _cd.GRV_REPLY
                    )
                p.send(version)
            # interval feedback: requests still waiting after a dispatch
            # mean the window is too long (shrink toward the MIN knob);
            # a drained queue relaxes it back to the configured cadence
            if self._pending or self.requests.stream._queue:
                self.batch_sizer.batch_full()
            else:
                self.batch_sizer.batch_underfull(len(batch))
