"""DataDistribution: shard tracking and the MoveKeys protocol.

Behavioral mirror of the reference's DD subsystem in miniature
(fdbserver/DataDistribution.actor.cpp shard tracker + DDRelocationQueue;
fdbserver/MoveKeys.actor.cpp for the authoritative move protocol;
storage-side fetchKeys at storageserver.actor.cpp:7378):

MoveKeys of [begin, end) from its owner to `dest`:
  1. **Dual-tag**: commit proxies start tagging the range's mutations to
     BOTH owners (the reference's serverKeys intermediate state), so the
     destination's log stream is complete from some version Vd onward.
  2. **Fence**: a barrier commit through a proxy pins Vd and guarantees
     every later commit is dual-tagged.
  3. **fetchKeys**: the destination buffers its incoming mutations for
     the range and fetches a snapshot at Vf >= Vd from the old owner.
  4. **Install**: snapshot + buffered mutations > Vf replay in order;
     the destination is now complete and current.
  5. **Flip**: the keyServers ShardMap routes the range to `dest`;
     dual-tagging stops; the old owner drops the range's data.

The control loop balances by key count (the reference balances by bytes
via storage metrics): when the largest storage server holds more than
`imbalance_ratio` times the smallest's keys, its largest shard moves.
"""

from __future__ import annotations

from foundationdb_tpu.models.types import CommitTransaction
from foundationdb_tpu.runtime.flow import ActorCancelled, Scheduler
from foundationdb_tpu.utils.metrics import CounterCollection
from foundationdb_tpu.utils.trace import TraceEvent


class DataDistributor:
    def __init__(self, cluster, *, interval: float = 1.0,
                 imbalance_ratio: float = 2.0):
        self.cluster = cluster
        self.sched: Scheduler = cluster.sched
        self.interval = interval
        self.imbalance_ratio = imbalance_ratio
        self.counters = CounterCollection("DDMetrics", ["loops", "moves"])
        self._task = None
        self._moving = False

    def start(self) -> None:
        self._task = self.sched.spawn(self._loop(), name="data-distributor")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    # -- MoveKeys ---------------------------------------------------------

    async def move_shard(self, begin: bytes, end: bytes, dest: int) -> None:
        """Move [begin, end) to storage server `dest` (end=None -> +inf)."""
        cluster = self.cluster
        shard_map = cluster.key_servers
        src_owners = {
            owner for _b, _e, owner in shard_map.segments_in(
                begin, end if end is not None else b"\xff" * 64
            )
        }
        if src_owners == {dest}:
            return
        self._moving = True
        try:
            dest_ss = cluster.storage_servers[dest]
            fence_end = end if end is not None else b"\xff" * 64

            # 1+2. dual-tag on every proxy, then fence so Vd is pinned.
            for p in cluster.commit_proxies:
                p.extra_tag_ranges.append((begin, fence_end, dest))
            dest_ss.begin_fetch(begin, fence_end)
            fence = await cluster.commit_proxies[0].commit(
                CommitTransaction()
            ).future
            vd = fence.version

            # 3. fetch the snapshot at Vf >= Vd from the current owners.
            items: list = []
            for b, e, owner in shard_map.segments_in(begin, fence_end):
                if owner == dest:
                    continue
                src = cluster.client_storages[owner]
                items.extend(await src.get_key_values(b, e, vd))

            # 4. install + replay buffer.
            dest_ss.install_shard(begin, fence_end, items, vd)

            # 5. flip routing; stop dual-tagging; old owners drop data.
            old_segments = shard_map.segments_in(begin, fence_end)
            shard_map.move(begin, end, dest)
            for p in cluster.commit_proxies:
                if (begin, fence_end, dest) in p.extra_tag_ranges:
                    p.extra_tag_ranges.remove((begin, fence_end, dest))
            for b, e, owner in old_segments:
                if owner != dest:
                    cluster.storage_servers[owner].drop_shard(b, e)
            self.counters.add("moves")
            TraceEvent("RelocateShard").detail("Begin", begin).detail(
                "End", fence_end
            ).detail("Dest", dest).log()
        finally:
            self._moving = False

    # -- shard tracker / balancer loop ------------------------------------

    def key_counts(self) -> list[int]:
        return [len(ss._keys) for ss in self.cluster.storage_servers]

    async def _loop(self) -> None:
        try:
            while True:
                await self.sched.delay(self.interval)
                self.counters.add("loops")
                if self._moving:
                    continue
                counts = self.key_counts()
                if len(counts) < 2 or sum(counts) == 0:
                    continue
                big = max(range(len(counts)), key=lambda i: counts[i])
                small = min(range(len(counts)), key=lambda i: counts[i])
                if counts[big] <= self.imbalance_ratio * max(counts[small], 1):
                    continue
                # move the upper half of the big server's largest segment
                segs = [
                    (b, e) for b, e, owner in self.cluster.key_servers.ranges()
                    if owner == big
                ]
                if not segs:
                    continue
                b, e = segs[0]
                ss = self.cluster.storage_servers[big]
                keys = [k for k in ss._keys
                        if k >= b and (e is None or k < e)]
                if len(keys) < 2:
                    continue
                mid = keys[len(keys) // 2]
                await self.move_shard(mid, e, small)
        except ActorCancelled:
            raise
