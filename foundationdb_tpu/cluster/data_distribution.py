"""DataDistribution: shard tracking and the MoveKeys protocol.

Behavioral mirror of the reference's DD subsystem in miniature
(fdbserver/DataDistribution.actor.cpp shard tracker + DDRelocationQueue;
fdbserver/MoveKeys.actor.cpp for the authoritative move protocol;
storage-side fetchKeys at storageserver.actor.cpp:7378):

MoveKeys of [begin, end) from its owner to `dest`:
  1. **Dual-tag**: commit proxies start tagging the range's mutations to
     BOTH owners (the reference's serverKeys intermediate state), so the
     destination's log stream is complete from some version Vd onward.
  2. **Fence**: a barrier commit through a proxy pins Vd and guarantees
     every later commit is dual-tagged.
  3. **fetchKeys**: the destination buffers its incoming mutations for
     the range and fetches a snapshot at Vf >= Vd from the old owner.
  4. **Install**: snapshot + buffered mutations > Vf replay in order;
     the destination is now complete and current.
  5. **Flip**: the keyServers ShardMap routes the range to `dest`;
     dual-tagging stops; the old owner drops the range's data.

The control loop balances by key count (the reference balances by bytes
via storage metrics): when the largest storage server holds more than
`imbalance_ratio` times the smallest's keys, its largest shard moves.
"""

from __future__ import annotations

from foundationdb_tpu.models.types import CommitTransaction
from foundationdb_tpu.runtime.flow import ActorCancelled, Scheduler
from foundationdb_tpu.utils.metrics import CounterCollection
from foundationdb_tpu.utils.trace import TraceEvent


class DataDistributor:
    def __init__(self, cluster, *, interval: float = 1.0,
                 imbalance_ratio: float = 2.0):
        self.cluster = cluster
        self.sched: Scheduler = cluster.sched
        self.interval = interval
        self.imbalance_ratio = imbalance_ratio
        self.counters = CounterCollection("DDMetrics", ["loops", "moves"])
        self._task = None
        self._moving = False

    def start(self) -> None:
        self._task = self.sched.spawn(self._loop(), name="data-distributor")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    # -- MoveKeys ---------------------------------------------------------

    async def _fence(self) -> int:
        """Commit an empty barrier transaction through a LIVE proxy and
        return its version. The r5 2000-seed ensemble found the original
        fence (pinned to commit_proxies[0]) hanging forever when that
        proxy was killed mid-move — with the flip already done, the old
        owners then never dropped and served stale data indefinitely.
        This fence retries across proxies AND across proxy generations
        (recovery rebuilds cluster.commit_proxies), with a timeout on
        each attempt: a proxy that dies mid-commit leaves its reply
        future unresolved forever.

        One fence version V* suffices to bound ALL earlier commits: the
        tlog's prev_version chain totally orders versions, so a storage
        server at version >= V* has applied every commit below V*."""
        from foundationdb_tpu.runtime.flow import any_of

        while True:
            live = [
                p for p in self.cluster.commit_proxies
                if getattr(p, "failed", None) is None
            ]
            for p in live:
                fut = p.commit(CommitTransaction()).future
                try:
                    await any_of([fut, self.sched.delay(0.5)])
                except Exception:
                    # this proxy failed the barrier; count it and try the
                    # next (a fence that spins here shows up in counters)
                    self.counters.add("fence_retries")
                    continue
                if fut.is_ready:
                    try:
                        return fut.get().version
                    except Exception:
                        self.counters.add("fence_retries")
                        continue
                # timed out (proxy died mid-commit): next candidate
            # no live proxy answered: recovery is (or will be)
            # recruiting a new generation — wait and re-read the list
            await self.sched.delay(0.05)

    async def move_shard(self, begin: bytes, end: bytes, dest) -> None:
        """Move [begin, end) to team `dest` — an int or a tuple of server
        ids (end=None -> +inf). Each joining member fetches the segment;
        each leaving member drops it after the post-flip fence."""
        from foundationdb_tpu.cluster.shardmap import _team

        cluster = self.cluster
        shard_map = cluster.key_servers
        dest_team = _team(dest)
        fence_end = end if end is not None else b"\xff" * 64
        # (segment, old_team, joining members) — only joiners fetch;
        # members already on the team keep applying normally
        moving = []
        for b, e, team in shard_map.segments_in(begin, fence_end):
            joiners = tuple(s for s in dest_team if s not in team)
            if team != dest_team:
                moving.append((b, e, team, joiners))
        if not moving:
            return
        self._moving = True
        tagged = False
        flipped = False
        fetching: list[tuple[bytes, bytes, int]] = []
        try:
            # 1+2. dual-tag the moving segments to every joiner (on the
            # SHARED shard map: every proxy of every generation consults
            # it) + start buffering, then fence so Vd is pinned.
            for b, e, _team, joiners in moving:
                for j in joiners:
                    shard_map.extra_tag_ranges.append((b, e, j))
                    cluster.storage_servers[j].begin_fetch(b, e)
                    fetching.append((b, e, j))
            tagged = True
            vd = await self._fence()

            # 3+4. fetch each segment's snapshot at Vd from a live old
            # member and install it on every joiner. A fully-dead old
            # team means the data is unrecoverable — fail (and unwind)
            # rather than hang on a frozen server.
            from foundationdb_tpu.cluster.storage import TransactionTooOld

            for b, e, team, joiners in moving:
                for _attempt in range(8):
                    src_id = next(
                        (s for s in team if cluster.storage_live[s]), None
                    )
                    if src_id is None:
                        raise RuntimeError(
                            f"no live replica of [{b!r}, {e!r}) to fetch from"
                        )
                    src = cluster.client_storages[src_id]
                    try:
                        items = await src.get_key_values(b, e, vd)
                        break
                    except TransactionTooOld:
                        # the source GC'd past Vd while we waited on it
                        # (a lagging replica catches up a > MVCC-window
                        # span in one pull batch): re-fence and fetch at
                        # a fresher version — fetchKeys' retry-with-
                        # higher-version loop (storageserver.actor.cpp
                        # fetchKeys / fetch_keys_too_old). Dual-tagging
                        # is already in force, so any newer fence stays
                        # a consistent snapshot point for this segment.
                        vd = await self._fence()
                else:
                    raise RuntimeError(
                        f"fetch of [{b!r}, {e!r}) kept falling below the "
                        f"source's MVCC window"
                    )
                for j in joiners:
                    cluster.storage_servers[j].install_shard(b, e, items, vd)
                    fetching.remove((b, e, j))

            # 5a. CEDE before the flip: versions not yet in the log may
            # have their mutations tagged AFTER the flip (allocation and
            # tagging are separate steps in the proxy), i.e. to the new
            # team only — so leavers must refuse reads above the LOGGED
            # version (WrongShardServerError -> client re-resolves).
            # Everything at or below the logged version was tagged while
            # the old map was in force, so the leaver is complete there.
            # The sequencer's allocation counter is NOT a safe ceiling:
            # the r5 2000-seed ensemble caught a commit whose version was
            # allocated pre-flip but tagged post-flip slipping under it.
            # Without any ceiling, a read between the flip and the
            # eventual drop returned silently stale data.
            v_cede = cluster.tlog.version.get()
            for b, e, team, _joiners in moving:
                for leaver in team:
                    if leaver not in dest_team:
                        cluster.storage_servers[leaver].cede_shard(
                            b, e, v_cede
                        )
            # 5b. flip routing; stop dual-tagging.
            shard_map.move(begin, end, dest_team)
            flipped = True
            for b, e, _team, joiners in moving:
                for j in joiners:
                    if (b, e, j) in shard_map.extra_tag_ranges:
                        shard_map.extra_tag_ranges.remove((b, e, j))

            # 6. Leaving members drop their data — but only once they
            #    have applied every mutation tagged to them before the
            #    flip. One post-flip fence version bounds them (the
            #    tlog's prev_version chain totally orders commits), and
            #    _fence survives dead proxies and generation changes.
            vmax = await self._fence()
            for b, e, team, _joiners in moving:
                for leaver in team:
                    if leaver not in dest_team:
                        # deliberate fire-and-forget: the move is complete
                        # either way; a crashed drop surfaces through the
                        # scheduler's unhandled-error ledger (soak fails
                        # the seed) and the consistency check
                        self.sched.spawn(  # flowcheck: ignore[actor.fire-and-forget]
                            self._drop_after(leaver, b, e, vmax),
                            name=f"dd-drop-{leaver}",
                        )
            self.counters.add("moves")
            TraceEvent("RelocateShard").detail("Begin", begin).detail(
                "End", fence_end
            ).detail("Dest", str(dest_team)).log()
        except BaseException:
            if tagged:
                for b, e, _team, joiners in moving:
                    for j in joiners:
                        if (b, e, j) in shard_map.extra_tag_ranges:
                            shard_map.extra_tag_ranges.remove((b, e, j))
            if flipped:
                # cancelled AFTER the flip (e.g. mid post-flip fence):
                # the new team is authoritative and the leavers already
                # ceded — they must still DROP, or they hold the range's
                # live keys forever (consistency check failure). Waiting
                # to v_cede is sound: every tagged-to-leaver version is
                # at or below it from the flip on, and a drop is safe
                # any time after the flip (reads re-resolve loudly).
                for b, e, team, _joiners in moving:
                    for leaver in team:
                        if leaver not in dest_team:
                            # same fire-and-forget contract as the main
                            # path above (unhandled-error ledger)
                            self.sched.spawn(  # flowcheck: ignore[actor.fire-and-forget]
                                self._drop_after(leaver, b, e, v_cede),
                                name=f"dd-drop-{leaver}",
                            )
            else:
                # nothing flipped: the old team remains authoritative —
                # discard fetch buffers
                for b, e, j in fetching:
                    cluster.storage_servers[j].cancel_fetch(b, e)
            raise
        finally:
            self._moving = False

    async def _drop_after(self, owner: int, b: bytes, e: bytes, version: int):
        # Re-resolve the CURRENT server object each wait: a reboot
        # replaces cluster.storage_servers[owner], and a waiter pinned
        # to the dead object would never drop — the rebooted server
        # would then serve the moved range's stale values to clients
        # with stale location caches (code-review r4).
        while self.cluster.storage_servers[owner].version.get() < version:
            # poll, never pin: an unbounded when_at_least on an object
            # that dies mid-wait would strand this waiter forever
            await self.sched.delay(0.02)
        self.cluster.storage_servers[owner].drop_shard(b, e)

    async def repair(self, dead: int, replacement: int = None) -> int:
        """Re-replicate every shard that lost `dead` (DDTeamCollection's
        team repair after a storage failure): each affected segment gets
        a live server not already on its team — the preferred
        `replacement` when possible, any other live server otherwise, or
        the team simply shrinks when no candidate exists. Returns the
        number of segments repaired."""
        cluster = self.cluster
        sm = cluster.key_servers
        repaired = 0
        for b, e, team in list(sm.ranges()):
            if dead not in team:
                continue
            if not any(cluster.storage_live[s] for s in team):
                # every replica dead: unrecoverable without a reboot —
                # leave the team for reboot_storage to revive
                TraceEvent("TeamUnrecoverable").detail("Begin", b).log()
                continue
            candidates = [
                s for s in range(len(cluster.storage_servers))
                if cluster.storage_live[s] and s not in team
            ]
            # locality-aware repair: prefer replacements that keep the
            # team satisfying the replication policy (PolicyAcross zones)
            policy = getattr(cluster.config, "replication_policy", None)
            localities = getattr(cluster.config, "storage_localities", None)
            if policy is not None and localities is not None:
                from foundationdb_tpu.cluster.locality import validate_team

                keep = tuple(s for s in team if s != dead)
                good = [
                    c for c in candidates
                    if validate_team(keep + (c,), localities, policy)
                ]
                if good:
                    candidates = good
            if replacement in candidates:
                pick = replacement
            elif candidates:
                pick = candidates[0]
            else:
                pick = None  # no spare server: drop to a smaller team
            new_team = tuple(
                pick if s == dead else s for s in team
                if not (s == dead and pick is None)
            )
            await self.move_shard(b, e, new_team)
            repaired += 1
        if repaired and all(dead not in t for t in sm.owners):
            # fully decommissioned: release the dead tag's log backlog
            # (the reference's exclusion -> tlog pop path)
            cluster.tlog.pop(dead, 1 << 62)
        return repaired

    # -- shard tracker / balancer loop ------------------------------------

    def key_counts(self) -> list[int]:
        # live keys only — the versioned store retains cleared keys'
        # histories until GC, which must not count as load
        return [ss._live_count for ss in self.cluster.storage_servers]

    async def _loop(self) -> None:
        try:
            while True:
                await self.sched.delay(self.interval)
                self.counters.add("loops")
                if self._moving:
                    continue
                # auto-balancing only steers single-replica maps; with
                # teams, rebalancing choices belong to team repair logic
                if any(len(t) > 1 for t in self.cluster.key_servers.owners):
                    continue
                counts = self.key_counts()
                if len(counts) < 2 or sum(counts) == 0:
                    continue
                big = max(range(len(counts)), key=lambda i: counts[i])
                small = min(range(len(counts)), key=lambda i: counts[i])
                if counts[big] <= self.imbalance_ratio * max(counts[small], 1):
                    continue
                # move the upper half of the big server's LARGEST segment
                ss = self.cluster.storage_servers[big]
                data = ss._data  # live view
                best, best_keys = None, []
                for b, e, owner in self.cluster.key_servers.ranges():
                    if owner != (big,):
                        continue
                    keys = sorted(
                        k for k in data if k >= b and (e is None or k < e)
                    )
                    if len(keys) > len(best_keys):
                        best, best_keys = (b, e), keys
                if best is None or len(best_keys) < 2:
                    continue
                mid = best_keys[len(best_keys) // 2]
                await self.move_shard(mid, best[1], small)
        except ActorCancelled:
            raise
