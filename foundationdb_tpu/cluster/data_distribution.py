"""DataDistribution: shard tracking and the MoveKeys protocol.

Behavioral mirror of the reference's DD subsystem in miniature
(fdbserver/DataDistribution.actor.cpp shard tracker + DDRelocationQueue;
fdbserver/MoveKeys.actor.cpp for the authoritative move protocol;
storage-side fetchKeys at storageserver.actor.cpp:7378):

MoveKeys of [begin, end) from its owner to `dest`:
  1. **Dual-tag**: commit proxies start tagging the range's mutations to
     BOTH owners (the reference's serverKeys intermediate state), so the
     destination's log stream is complete from some version Vd onward.
  2. **Fence**: a barrier commit through a proxy pins Vd and guarantees
     every later commit is dual-tagged.
  3. **fetchKeys**: the destination buffers its incoming mutations for
     the range and fetches a snapshot at Vf >= Vd from the old owner.
  4. **Install**: snapshot + buffered mutations > Vf replay in order;
     the destination is now complete and current.
  5. **Flip**: the keyServers ShardMap routes the range to `dest`;
     dual-tagging stops; the old owner drops the range's data.

The control loop balances by key count (the reference balances by bytes
via storage metrics): when the largest storage server holds more than
`imbalance_ratio` times the smallest's keys, its largest shard moves.
"""

from __future__ import annotations

from foundationdb_tpu.models.types import CommitTransaction
from foundationdb_tpu.runtime.flow import ActorCancelled, Scheduler
from foundationdb_tpu.utils.metrics import CounterCollection
from foundationdb_tpu.utils.trace import TraceEvent


class DataDistributor:
    def __init__(self, cluster, *, interval: float = 1.0,
                 imbalance_ratio: float = 2.0):
        self.cluster = cluster
        self.sched: Scheduler = cluster.sched
        self.interval = interval
        self.imbalance_ratio = imbalance_ratio
        self.counters = CounterCollection("DDMetrics", ["loops", "moves"])
        self._task = None
        self._moving = False

    def start(self) -> None:
        self._task = self.sched.spawn(self._loop(), name="data-distributor")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    # -- MoveKeys ---------------------------------------------------------

    async def move_shard(self, begin: bytes, end: bytes, dest: int) -> None:
        """Move [begin, end) to storage server `dest` (end=None -> +inf)."""
        cluster = self.cluster
        shard_map = cluster.key_servers
        fence_end = end if end is not None else b"\xff" * 64
        # only the segments dest does NOT already own actually move —
        # dest-owned spans keep applying their mutations normally
        moving = [
            (b, e, owner)
            for b, e, owner in shard_map.segments_in(begin, fence_end)
            if owner != dest
        ]
        if not moving:
            return
        self._moving = True
        dest_ss = cluster.storage_servers[dest]
        tagged = False
        fetching: list[tuple[bytes, bytes]] = []
        try:
            # 1+2. dual-tag the moving segments on every proxy + start
            # buffering on dest, then fence so Vd is pinned.
            for b, e, _o in moving:
                for p in cluster.commit_proxies:
                    p.extra_tag_ranges.append((b, e, dest))
                dest_ss.begin_fetch(b, e)
                fetching.append((b, e))
            tagged = True
            fence = await cluster.commit_proxies[0].commit(
                CommitTransaction()
            ).future
            vd = fence.version

            # 3+4. fetch each segment's snapshot at Vd and install it.
            for b, e, owner in moving:
                src = cluster.client_storages[owner]
                items = await src.get_key_values(b, e, vd)
                dest_ss.install_shard(b, e, items, vd)
                fetching.remove((b, e))

            # 5. flip routing; stop dual-tagging.
            shard_map.move(begin, end, dest)
            for b, e, _o in moving:
                for p in cluster.commit_proxies:
                    if (b, e, dest) in p.extra_tag_ranges:
                        p.extra_tag_ranges.remove((b, e, dest))

            # 6. Old owners drop their data — but only once they have
            #    applied every mutation that was tagged to them before
            #    the flip. A post-flip fence through every proxy bounds
            #    those versions; each old owner waits past it.
            fences = [
                p.commit(CommitTransaction()).future
                for p in cluster.commit_proxies
            ]
            vmax = 0
            for f in fences:
                reply = await f
                vmax = max(vmax, reply.version)
            for b, e, owner in moving:
                self.sched.spawn(
                    self._drop_after(owner, b, e, vmax),
                    name=f"dd-drop-{owner}",
                )
            self.counters.add("moves")
            TraceEvent("RelocateShard").detail("Begin", begin).detail(
                "End", fence_end
            ).detail("Dest", dest).log()
        except BaseException:
            # unwind: stop dual-tagging, discard fetch buffers — the
            # old owners remain authoritative, nothing was flipped
            if tagged:
                for b, e, _o in moving:
                    for p in cluster.commit_proxies:
                        if (b, e, dest) in p.extra_tag_ranges:
                            p.extra_tag_ranges.remove((b, e, dest))
            for b, e in fetching:
                dest_ss.cancel_fetch(b, e)
            raise
        finally:
            self._moving = False

    async def _drop_after(self, owner: int, b: bytes, e: bytes, version: int):
        ss = self.cluster.storage_servers[owner]
        await ss.version.when_at_least(version)
        ss.drop_shard(b, e)

    # -- shard tracker / balancer loop ------------------------------------

    def key_counts(self) -> list[int]:
        # live keys only — the versioned store retains cleared keys'
        # histories until GC, which must not count as load
        return [ss._live_count for ss in self.cluster.storage_servers]

    async def _loop(self) -> None:
        try:
            while True:
                await self.sched.delay(self.interval)
                self.counters.add("loops")
                if self._moving:
                    continue
                counts = self.key_counts()
                if len(counts) < 2 or sum(counts) == 0:
                    continue
                big = max(range(len(counts)), key=lambda i: counts[i])
                small = min(range(len(counts)), key=lambda i: counts[i])
                if counts[big] <= self.imbalance_ratio * max(counts[small], 1):
                    continue
                # move the upper half of the big server's LARGEST segment
                ss = self.cluster.storage_servers[big]
                data = ss._data  # live view
                best, best_keys = None, []
                for b, e, owner in self.cluster.key_servers.ranges():
                    if owner != big:
                        continue
                    keys = sorted(
                        k for k in data if k >= b and (e is None or k < e)
                    )
                    if len(keys) > len(best_keys):
                        best, best_keys = (b, e), keys
                if best is None or len(best_keys) < 2:
                    continue
                mid = best_keys[len(best_keys) // 2]
                await self.move_shard(mid, best[1], small)
        except ActorCancelled:
            raise
