"""ShardMap: the keyServers mapping — key range -> owning storage server.

Behavioral mirror of the reference's `keyServers/` system mapping
(fdbclient/SystemData.cpp; consulted by proxies when tagging mutations,
CommitProxyServer.actor.cpp:1861, and by clients when routing reads):
a sorted list of boundaries with an owner per segment, supporting the
shard split/move operations DataDistribution performs via MoveKeys
(fdbserver/MoveKeys.actor.cpp).
"""

from __future__ import annotations

import bisect


class ShardMap:
    def __init__(self, boundaries: list[bytes], owners: list[int]):
        """segment i = [boundaries[i-1], boundaries[i]) owned by owners[i];
        boundaries has len(owners)-1 interior split keys."""
        if len(owners) != len(boundaries) + 1:
            raise ValueError("need len(owners) == len(boundaries) + 1")
        self.boundaries = list(boundaries)
        self.owners = list(owners)

    @classmethod
    def even(cls, boundaries: list[bytes]) -> "ShardMap":
        return cls(boundaries, list(range(len(boundaries) + 1)))

    # -- lookup (keyServers reads) ----------------------------------------

    def shard_of(self, key: bytes) -> int:
        return self.owners[bisect.bisect_right(self.boundaries, key)]

    def shards_of_range(self, begin: bytes, end: bytes) -> list[int]:
        lo = bisect.bisect_right(self.boundaries, begin)
        hi = bisect.bisect_left(self.boundaries, end)
        return sorted(set(self.owners[lo : hi + 1]))

    def ranges(self) -> list[tuple[bytes, bytes, int]]:
        """[(begin, end, owner)]; end=None for the last segment."""
        out = []
        for i, owner in enumerate(self.owners):
            b = self.boundaries[i - 1] if i > 0 else b""
            e = self.boundaries[i] if i < len(self.boundaries) else None
            out.append((b, e, owner))
        return out

    def segments_in(self, begin: bytes, end: bytes):
        """Segments (clipped) intersecting [begin, end)."""
        out = []
        for b, e, owner in self.ranges():
            cb = max(b, begin)
            ce = end if e is None else min(e, end)
            if cb < ce:
                out.append((cb, ce, owner))
        return out

    # -- mutation (MoveKeys) ----------------------------------------------

    def split(self, key: bytes) -> None:
        """Insert a boundary at `key` (no ownership change)."""
        i = bisect.bisect_right(self.boundaries, key)
        if i > 0 and self.boundaries[i - 1] == key:
            return
        self.boundaries.insert(i, key)
        self.owners.insert(i, self.owners[i])

    def move(self, begin: bytes, end: bytes, new_owner: int) -> None:
        """Assign [begin, end) to new_owner (splitting as needed);
        end=None means to the end of the keyspace."""
        if begin:
            self.split(begin)
        if end is not None:
            self.split(end)
        # After splitting, every segment lies entirely in or out of range.
        for i in range(len(self.owners)):
            seg_begin = self.boundaries[i - 1] if i > 0 else b""
            if seg_begin >= begin and (end is None or seg_begin < end):
                self.owners[i] = new_owner
        self._coalesce()

    def _coalesce(self) -> None:
        """Merge adjacent segments with the same owner."""
        i = 0
        while i < len(self.boundaries):
            if self.owners[i] == self.owners[i + 1]:
                del self.boundaries[i]
                del self.owners[i + 1]
            else:
                i += 1
