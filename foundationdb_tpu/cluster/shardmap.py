"""ShardMap: the keyServers mapping — key range -> owning storage team.

Behavioral mirror of the reference's `keyServers/` system mapping
(fdbclient/SystemData.cpp; consulted by proxies when tagging mutations,
CommitProxyServer.actor.cpp:1861, and by clients when routing reads):
a sorted list of boundaries with an owner TEAM per segment (the
reference's storage teams — every replica of a shard receives its
mutations and can serve its reads), supporting the split/move operations
DataDistribution performs via MoveKeys (fdbserver/MoveKeys.actor.cpp).

Owners are tuples of server ids; single-replica maps are teams of one.
"""

from __future__ import annotations

import bisect


def _team(owner) -> tuple:
    return tuple(owner) if isinstance(owner, (tuple, list)) else (owner,)


class ShardMap:
    def __init__(self, boundaries: list[bytes], owners: list):
        """segment i = [boundaries[i-1], boundaries[i]) owned by team
        owners[i]; boundaries has len(owners)-1 interior split keys."""
        if len(owners) != len(boundaries) + 1:
            raise ValueError("need len(owners) == len(boundaries) + 1")
        # MoveKeys dual-tag state (the serverKeys intermediate state):
        # mutations in [begin, end) ALSO tag to `tag` while a move is in
        # flight. Lives on the SHARED map — not on the proxies — so a
        # recovery that recruits a new proxy generation cannot silently
        # drop in-flight dual-tagging (the r5 2000-seed ensemble found
        # exactly that data loss).
        self.extra_tag_ranges: list[tuple[bytes, bytes, int]] = []
        self.boundaries = list(boundaries)
        self.owners = [_team(o) for o in owners]

    @classmethod
    def even(cls, boundaries: list[bytes], *, replication: int = 1,
             n_servers: int = None, localities: dict = None,
             policy=None) -> "ShardMap":
        """Even key split. With `localities` (server id -> LocalityData)
        and a replication `policy` (cluster/locality.py), every team is
        built to satisfy the policy — replicas across distinct failure
        domains, DDTeamCollection-style — rotating the preference so load
        spreads. Without a policy: simple rotation (legacy behavior).
        """
        n_shards = len(boundaries) + 1
        n_servers = n_servers or n_shards
        if replication > n_servers:
            raise ValueError(
                f"replication {replication} > n_servers {n_servers} would "
                "put the same server on a team twice"
            )
        if policy is not None:
            from foundationdb_tpu.cluster.locality import build_team

            assert localities is not None, "policy needs localities"
            server_ids = sorted(localities)
            owners = [
                build_team(
                    localities, policy,
                    prefer=tuple(
                        server_ids[(i + j) % len(server_ids)]
                        for j in range(len(server_ids))
                    ),
                )
                for i in range(n_shards)
            ]
        else:
            owners = [
                tuple((i + j) % n_servers for j in range(replication))
                for i in range(n_shards)
            ]
        return cls(boundaries, owners)

    # -- lookup (keyServers reads) ----------------------------------------

    def team_of(self, key: bytes) -> tuple:
        return self.owners[bisect.bisect_right(self.boundaries, key)]

    def range_of(self, key: bytes) -> tuple[bytes, bytes, tuple]:
        """(begin, end, team) of the FULL shard containing `key`; end is
        b"" for the last segment (unbounded). The client location cache
        stores whole shard ranges — a clipped sub-range would make range
        reads crawl key-by-key (getKeyLocation returns the full shard
        boundary in the reference too, NativeAPI.actor.cpp:2969)."""
        i = bisect.bisect_right(self.boundaries, key)
        b = self.boundaries[i - 1] if i > 0 else b""
        e = self.boundaries[i] if i < len(self.boundaries) else b""
        return b, e, self.owners[i]

    def shard_of(self, key: bytes) -> int:
        """Primary member of the owning team (single-replica callers)."""
        return self.team_of(key)[0]

    def teams_of_range(self, begin: bytes, end: bytes) -> list[tuple]:
        lo = bisect.bisect_right(self.boundaries, begin)
        hi = bisect.bisect_left(self.boundaries, end)
        return sorted(set(self.owners[lo : hi + 1]))

    def tags_of_range(self, begin: bytes, end: bytes) -> list[int]:
        """Every server holding any part of [begin, end)."""
        out = set()
        for team in self.teams_of_range(begin, end):
            out.update(team)
        return sorted(out)

    def shards_of_range(self, begin: bytes, end: bytes) -> list[int]:
        """Primary members only (single-replica read routing)."""
        return sorted({t[0] for t in self.teams_of_range(begin, end)})

    def ranges(self) -> list[tuple[bytes, bytes, int]]:
        """[(begin, end, owner)]; end=None for the last segment."""
        out = []
        for i, owner in enumerate(self.owners):
            b = self.boundaries[i - 1] if i > 0 else b""
            e = self.boundaries[i] if i < len(self.boundaries) else None
            out.append((b, e, owner))
        return out

    def segments_in(self, begin: bytes, end: bytes):
        """Segments (clipped) intersecting [begin, end)."""
        out = []
        for b, e, owner in self.ranges():
            cb = max(b, begin)
            ce = end if e is None else min(e, end)
            if cb < ce:
                out.append((cb, ce, owner))
        return out

    # -- mutation (MoveKeys) ----------------------------------------------

    def split(self, key: bytes) -> None:
        """Insert a boundary at `key` (no ownership change)."""
        i = bisect.bisect_right(self.boundaries, key)
        if i > 0 and self.boundaries[i - 1] == key:
            return
        self.boundaries.insert(i, key)
        self.owners.insert(i, self.owners[i])

    def move(self, begin: bytes, end: bytes, new_owner) -> None:
        """Assign [begin, end) to team new_owner (splitting as needed);
        end=None means to the end of the keyspace."""
        new_owner = _team(new_owner)
        if not new_owner or len(set(new_owner)) != len(new_owner):
            raise ValueError(f"invalid team {new_owner!r}")
        if begin:
            self.split(begin)
        if end is not None:
            self.split(end)
        # After splitting, every segment lies entirely in or out of range.
        for i in range(len(self.owners)):
            seg_begin = self.boundaries[i - 1] if i > 0 else b""
            if seg_begin >= begin and (end is None or seg_begin < end):
                self.owners[i] = new_owner
        self._coalesce()

    def _coalesce(self) -> None:
        """Merge adjacent segments with the same owner."""
        i = 0
        while i < len(self.boundaries):
            if self.owners[i] == self.owners[i + 1]:
                del self.boundaries[i]
                del self.owners[i + 1]
            else:
                i += 1
