"""Cluster recovery: rebuild the transaction system in a new generation.

Behavioral mirror of `fdbserver/ClusterRecovery.actor.cpp` +
`ClusterController.actor.cpp` (states in RecoveryState.h:31-41),
compressed to the essentials:

* A ClusterController actor watches the transaction-path roles; any
  commit-proxy failure (our `proxy.failed` latch — the stand-in for
  waitFailure) triggers a full recovery, exactly as in the reference:
  the transaction system is recovered as a unit, never patched.
* Recovery: stop the old generation's proxies/GRV, pick the recovery
  version (the durable log's version — reads stay correct), recruit NEW
  resolvers with EMPTY conflict state (the reference's key fact:
  resolvers are stateless across recoveries, Resolver.actor.cpp builds a
  fresh ConflictSet; correctness holds because in-flight transactions
  with pre-recovery read snapshots are aborted conservatively), recruit
  new proxies at the next epoch, and re-open for business.
* Conservative abort of in-flight txns: the first batch of the new
  generation carries a blind write over the whole keyspace, so any
  transaction whose snapshot predates recovery conflicts — the same
  effect the reference gets from the recovery transaction's version
  bump + lastEpochEnd conflict range (ApplyMetadataMutation /
  CommitProxyServer recovery handling).

Storage servers and the TLog survive recovery untouched (their state is
durable); only the stateless roles are rebuilt.
"""

from __future__ import annotations

from foundationdb_tpu.cluster.commit_proxy import CommitProxy
from foundationdb_tpu.cluster.coordination import LeaderElection
from foundationdb_tpu.utils.probes import code_probe, declare

declare("recovery.epoch_lock_failed", "recovery.completed",
        "recovery.leadership_lost")
from foundationdb_tpu.cluster import generation as gen
from foundationdb_tpu.cluster.generation import GenerationState
from foundationdb_tpu.cluster.grv_proxy import GrvProxy
from foundationdb_tpu.cluster.sequencer import Sequencer
from foundationdb_tpu.models.types import ResolveTransactionBatchRequest
from foundationdb_tpu.resolver import Resolver
from foundationdb_tpu.runtime.flow import ActorCancelled, Scheduler, all_of
from foundationdb_tpu.utils.metrics import CounterCollection
from foundationdb_tpu.utils.trace import TraceEvent


class ClusterController:
    """Failure watcher + recovery driver (the CC's recovery loop).

    The generation/epoch state machine is SHARED with the wire cluster
    controller (cluster/generation.py — the wire twin lives in
    cluster/multiprocess.py ClusterControllerRole): same recovery-state
    vocabulary, same recovery-version rule, same conservative-abort
    range, same MasterRecoveryState trace shape — so the sim and wire
    recoveries cannot drift."""

    def __init__(self, cluster, *, check_interval: float = 0.05,
                 cc_id: str = "cc0"):
        self.cluster = cluster
        self.check_interval = check_interval
        self.gen = GenerationState(epoch=1, clock=cluster.sched.now)
        self.counters = CounterCollection("CCMetrics", ["recoveries", "checks"])
        self._task = None
        self._recovering = False
        # Leadership + epoch locks go through the coordination quorum
        # (Coordination.actor.cpp / LeaderElection.actor.cpp): recovery is
        # gated on holding the lease and committing the epoch bump through
        # a majority of coordinators.
        self.elector = LeaderElection(
            cluster.sched, cluster.coordinators, cc_id,
            lease=50 * check_interval,
        )
        self.lease = None

    @property
    def epoch(self) -> int:
        return self.gen.epoch

    @epoch.setter
    def epoch(self, value: int) -> None:
        self.gen.epoch = value

    def start(self) -> None:
        self._task = self.cluster.sched.spawn(
            self._watch(), name="cluster-controller"
        )

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    async def _watch(self) -> None:
        try:
            while True:
                await self.cluster.sched.delay(self.check_interval)
                self.counters.add("checks")
                if self._recovering:
                    continue
                # hold (or regain) the leader lease before acting as CC
                if self.lease is None:
                    self.lease = await self.elector.try_become_leader()
                    if self.lease is None:
                        continue  # quorum down or another leader is live
                elif self.lease.expires < self.cluster.sched.now() + \
                        10 * self.check_interval:
                    # _watch is self.lease's only writer: renew()
                    # round-trips the current lease through the elector
                    # with no concurrent mutator to lose an update to
                    self.lease = await self.elector.renew(self.lease)  # flowcheck: ignore[flow.rmw-across-wait]
                    if self.lease is None:
                        code_probe(True, "recovery.leadership_lost")
                        continue  # deposed; must re-win before recovering
                if any(p.failed is not None for p in self.cluster.commit_proxies):
                    await self.recover()
        except ActorCancelled:
            raise

    async def recover(self) -> int:
        """Run one full recovery; returns the new epoch."""
        self._recovering = True
        try:
            cluster = self.cluster
            sched: Scheduler = cluster.sched
            # 0. Epoch lock through the coordination quorum: commit the
            #    bumped epoch (riding the leader lease register) through a
            #    majority BEFORE touching the transaction system. A
            #    deposed CC fails here and must not recover; a minority of
            #    dead coordinators does not block this.
            if self.lease is None:
                self.lease = await self.elector.try_become_leader()
            bumped = None
            if self.lease is not None:
                bumped = await self.elector.bump_epoch(self.lease)
            if bumped is None:
                code_probe(True, "recovery.epoch_lock_failed")
                TraceEvent("RecoveryEpochLockFailed").detail(
                    "Epoch", self.epoch).log()
                self.lease = None
                self._recovering = False
                return self.epoch
            self.lease = bumped
            # the shared state machine: bump to max(epoch+1, quorum
            # epoch) and emit reading_transaction_system_state
            self.gen.begin_recovery(floor=bumped.epoch - 1)
            self.counters.add("recoveries")

            # 1. Stop the old generation and LOCK the log system: pushes
            #    from the old epoch now fail with tlog_stopped, so no old
            #    in-flight batch can slip in a commit after this point
            #    (the reference's coordinated-state lock + tlog epoch
            #    lock). Their clients get commit_unknown_result.
            self.gen.transition(gen.LOCKING_OLD_TRANSACTION_SERVERS)
            for p in cluster.commit_proxies:
                p.stop()
            cluster.grv_proxy.stop()
            cluster.balancer.stop()
            cluster.tlog.lock(self.epoch)

            # 2. Recovery version: strictly above anything the old
            #    generation could have allocated, plus a safety gap
            #    (lastEpochEnd + MAX_VERSIONS_IN_FLIGHT in the reference)
            #    so old and new versions can never collide — the rule is
            #    the shared generation.recovery_version_for.
            recovery_version = gen.recovery_version_for(
                cluster.tlog.version.get(), cluster.sequencer.version
            )
            self.gen.recovery_version = recovery_version
            # Complete the old epoch at the recovery version so the first
            # new-generation push chains (lastEpochEnd).
            cluster.tlog.lock(self.epoch, recovery_version)
            cluster.sequencer = Sequencer(
                sched, recovery_version=recovery_version
            )

            # 3. New resolvers, empty conflict state.
            self.gen.transition(gen.RECRUITING_TRANSACTION_SERVERS,
                                RecoveryVersion=recovery_version)
            cfg = cluster.config
            cluster.resolvers = [
                Resolver(
                    sched,
                    cfg.kernel_config,
                    resolver_id=i,
                    resolver_count=cfg.n_resolvers,
                    commit_proxy_count=cfg.n_commit_proxies,
                    init_version=-1,
                    backend=cfg.resolver_backend,
                )
                for i in range(cfg.n_resolvers)
            ]
            boots = [
                sched.spawn(
                    r.resolve(
                        ResolveTransactionBatchRequest(
                            prev_version=-1,
                            version=recovery_version,
                            last_received_version=-1,
                            transactions=[],
                        )
                    )
                ).done
                for r in cluster.resolvers
            ]
            await all_of(boots)

            # 4. Recruit the new generation's proxies and GRV.
            cluster.build_proxies(epoch=self.epoch)
            for p in cluster.commit_proxies:
                p.last_received_version = recovery_version
                # Conservative abort of pre-recovery snapshots: the first
                # batch writes the whole keyspace (the shared range —
                # the wire ProxyRole commits the same write as its
                # recovery transaction).
                p.conservative_writes.append(gen.CONSERVATIVE_ABORT_RANGE)
                p.start()
            cluster.grv_proxy = GrvProxy(
                sched, cluster.sequencer, ratekeeper=cluster.ratekeeper
            )
            cluster.grv_proxy.start()
            cluster.ratekeeper.sequencer = cluster.sequencer
            cluster.balancer.resolvers = cluster.resolvers
            cluster.balancer.commit_proxies = cluster.commit_proxies
            cluster.balancer.start()

            # 5. The recovery transaction: an immediate empty commit
            #    pushes the log (and so every storage server) past the
            #    recovery version — without it, reads at the new read
            #    version would stall until the first client commit
            #    (the reference's recoveryTransactionVersion commit).
            self.gen.transition(gen.RECOVERY_TRANSACTION)
            from foundationdb_tpu.models.types import CommitTransaction

            await cluster.commit_proxies[0].commit(CommitTransaction()).future

            code_probe(True, "recovery.completed")
            self.gen.transition(gen.ACCEPTING_COMMITS)
            self.gen.transition(gen.FULLY_RECOVERED)
            return self.epoch
        finally:
            self._recovering = False
