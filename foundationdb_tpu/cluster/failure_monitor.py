"""Ping-driven address-level failure monitoring, shared cluster-wide.

Behavioral mirror of fdbrpc/FailureMonitor.actor.cpp + the cluster
controller's failureDetectionServer: every registered endpoint is pinged
on an interval; an endpoint that has not answered for `failure_delay`
(virtual) seconds is marked FAILED in a view every consumer shares
(clients skip failed replicas, the ratekeeper drops them from its lag
set, data distribution repairs their teams); a ping answered after a
failure marks it live again.

Two detection paths, as in the reference:

* the PING LOOP (this module) — catches silent deaths and network
  partitions (pings ride the SimNetwork when the cluster runs under
  simulation, so a partitioned-but-alive process is correctly seen as
  failed from the controller's vantage);
* CLIENT REPORTS (`report_failed`) — a request that throws
  ProcessFailedError marks the endpoint failed immediately, the
  IFailureMonitor::endpointNotFound fast path that keeps client
  failover latency at one round trip instead of one detection window.
"""

from __future__ import annotations

from typing import Callable

from foundationdb_tpu.runtime.flow import ActorCancelled, Scheduler
from foundationdb_tpu.utils.probes import declare, code_probe

declare("failmon.detected_by_ping", "failmon.recovered")


class ProcessFailedError(Exception):
    """A request reached a dead process (connection refused / reset).

    Clients catch this, report the endpoint to the failure monitor, and
    fail over to another replica — the loadBalance error path."""


class FailureMonitor:
    def __init__(
        self,
        sched: Scheduler,
        *,
        ping_interval: float = 0.05,
        failure_delay: float = 0.15,
    ):
        self.sched = sched
        self.ping_interval = ping_interval
        self.failure_delay = failure_delay
        # addr -> async ping callable (returns truthy when alive; raising
        # or returning falsy counts as a miss)
        self._pings: dict[str, Callable] = {}
        self._last_ok: dict[str, float] = {}
        self._failed: dict[str, bool] = {}
        self._reported_at: dict[str, float] = {}
        # addr -> callbacks fired on (addr, failed) state transitions
        self._on_change: list[Callable] = []
        self._task = None

    # -- registry ---------------------------------------------------------

    def register(self, addr: str, ping: Callable) -> None:
        self._pings[addr] = ping
        self._last_ok[addr] = self.sched.now()
        self._failed.setdefault(addr, False)

    def on_change(self, cb: Callable) -> None:
        self._on_change.append(cb)

    # -- the shared view --------------------------------------------------

    def is_failed(self, addr: str) -> bool:
        return self._failed.get(addr, False)

    def report_failed(self, addr: str) -> None:
        """Client fast path: a request just failed against this address."""
        self._set(addr, True)
        # an explicit report opens a COOLDOWN: the ping loop may not
        # mark the address live again until failure_delay has passed
        # since the report, so a flapping process (answers pings, errors
        # on requests) cannot oscillate back into the read path every
        # ping interval
        self._last_ok[addr] = -1e18
        self._reported_at[addr] = self.sched.now()

    def report_alive(self, addr: str) -> None:
        """A replacement process came up at this address (reboot)."""
        self._last_ok[addr] = self.sched.now()
        self._reported_at.pop(addr, None)
        self._set(addr, False)

    def _set(self, addr: str, failed: bool) -> None:
        if self._failed.get(addr) == failed:
            return
        self._failed[addr] = failed
        for cb in self._on_change:
            cb(addr, failed)

    # -- the ping loop ----------------------------------------------------

    def start(self) -> None:
        if self._task is None:
            self._task = self.sched.spawn(self._loop(), name="failmon")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        try:
            while True:
                await self.sched.delay(self.ping_interval)
                now = self.sched.now()
                for addr, ping in list(self._pings.items()):
                    ok = False
                    try:
                        ok = bool(await ping())
                    except ActorCancelled:
                        raise
                    except Exception:
                        ok = False  # partitioned / dead / erroring
                    if ok:
                        self._last_ok[addr] = now
                        in_cooldown = (
                            now - self._reported_at.get(addr, -1e18)
                            < self.failure_delay
                        )
                        if self._failed.get(addr) and not in_cooldown:
                            code_probe(True, "failmon.recovered")
                            self._set(addr, False)
                    elif (
                        not self._failed.get(addr)
                        and now - self._last_ok[addr] >= self.failure_delay
                    ):
                        code_probe(True, "failmon.detected_by_ping")
                        self._set(addr, True)
        except ActorCancelled:
            raise
