"""Ratekeeper: cluster-wide admission control.

Behavioral mirror of `fdbserver/Ratekeeper.actor.cpp`: a control loop
samples the health of the whole write pipeline and computes a
transactions-per-second budget; GRV proxies fetch the budget
(`GetRateInfoRequest`, served at :475) and release read versions no
faster than that, which throttles new transactions at the front door —
the one place a transaction can be delayed without violating MVCC.

The r8 control law is the reference's multi-input shape, consuming the
PR-7 saturation sensors end to end:

* per-tlog smoothed queue bytes vs `TLOG_QUEUE_BYTES_TARGET`
  (TLogQueueInfo -> limitReason log_server_write_queue),
* per-storage version lag vs the MVCC window (StorageQueueInfo ->
  storage_server_durability_lag),
* per-resolver busy fraction (the occupancy Smoother over compute
  seconds — resolver_busy) and version-chain queue depth
  (resolver_queue),
* per-commit-proxy queued requests (commit_proxy_queue).

Each limiter derives a TPS limit; the budget is the MIN across
limiters, the binding limiter is named with the SAME reason vocabulary
as the status section's `performance_limited_by`
(cluster/status.py QOS_REASONS), and budget movement is smoothed with
hysteresis (engage past target, release only below `release_frac` of
target; multiplicative decrease, bounded increase) so the loop cannot
flap between full speed and clamp across a noisy sensor.

Robustness contract: the loop itself fails SAFE. A stale sensor feed
(`sensor dropout`) decays the budget toward a conservative floor
(`failsafe_tps`) instead of freezing at full speed; an all-dead storage
set clamps to `min_tps` (a cluster with zero live replicas must not
admit at `max_tps` because its dead sensors read zero lag); and the
CONSUMERS (sim GrvProxy, wire ProxyPipeline) apply the same decay when
the Ratekeeper itself dies or stops answering — see
`GrvProxy._starter` and `ProxyPipeline._rate_fetcher`.

The pure law lives in `AdmissionController` so the sim `Ratekeeper`
(direct object sensors) and the wire `RatekeeperRole`
(cluster/multiprocess.py, StatusRequest-polled sensors) share one
implementation.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

from foundationdb_tpu.cluster.status import (
    PROXY_QUEUE_TARGET,
    RESOLVER_QUEUE_TARGET,
    TLOG_QUEUE_BYTES_TARGET,
)
from foundationdb_tpu.runtime.flow import ActorCancelled, Scheduler
from foundationdb_tpu.utils.metrics import CounterCollection, Smoother
from foundationdb_tpu.utils.probes import code_probe, declare

declare(
    "ratekeeper.throttled",
    "ratekeeper.auto_tag_throttled",
    "ratekeeper.auto_tag_lifted",
    "ratekeeper.failsafe",
)

#: resolver busy-fraction (occupancy Smoother) at which resolution is
#: the limiter; 1.0 == compute occupies the entire wall clock
RESOLVER_BUSY_TARGET = 0.85

#: e-folding time of the fail-safe budget decay — ONE constant for all
#: three decay paths (the law's own stale-feed decay, the sim
#: GrvProxy's dead-ratekeeper decay, the wire ProxyPipeline's
#: fetch-failure decay; the wire consumer receives it in the
#: GetRateInfo payload so tuning the law tunes every consumer)
FAILSAFE_TAU = 0.5


class AdmissionController:
    """The multi-input admission-control law, deployment-agnostic.

    `update(slots, current_tps=...)` consumes one reading of the
    cluster's qos sensor blocks (the same per-role `saturation()` dicts
    `cluster/status.qos_pressures` scores) and moves the budget;
    `decay(...)` is the fail-safe direction for a stale feed. State:
    the smoothed budget, the per-reason hysteresis engagement set, and
    the binding-limiter attribution (`limited_by`, one vocabulary with
    status `performance_limited_by`).
    """

    def __init__(
        self,
        *,
        clock: Callable[[], float],
        max_tps: float = 1e7,
        min_tps: float = 10.0,
        lag_target: float = 2_000_000.0,   # versions (~2s)
        lag_limit: float = 4_500_000.0,    # near the 5s MVCC window
        tlog_queue_target: float = float(TLOG_QUEUE_BYTES_TARGET),
        resolver_busy_target: float = RESOLVER_BUSY_TARGET,
        resolver_queue_target: float = float(RESOLVER_QUEUE_TARGET),
        proxy_queue_target: float = float(PROXY_QUEUE_TARGET),
        release_frac: float = 0.8,
        growth_factor: float = 2.0,
        failsafe_tps: float = None,
        failsafe_tau: float = FAILSAFE_TAU,
    ):
        self.clock = clock
        self.max_tps = max_tps
        self.min_tps = min_tps
        self.lag_target = lag_target
        self.lag_limit = lag_limit
        self.tlog_queue_target = tlog_queue_target
        self.resolver_busy_target = resolver_busy_target
        self.resolver_queue_target = resolver_queue_target
        self.proxy_queue_target = proxy_queue_target
        #: hysteresis: a limiter engages at pressure >= 1.0 and releases
        #: only once pressure drops below release_frac — oscillation
        #: across the target boundary cannot flap the budget
        self.release_frac = release_frac
        self.growth_factor = growth_factor
        #: the conservative fail-safe floor the budget decays toward
        #: when the sensor feed is stale (never below min_tps, never a
        #: full-speed freeze)
        self.failsafe_tps = (
            failsafe_tps
            if failsafe_tps is not None
            else max(min_tps, max_tps * 1e-3)
        )
        self.failsafe_tau = failsafe_tau
        self.tps_budget = max_tps
        #: engaged limiters (hysteresis state), keyed by reason id
        self._engaged: set[str] = set()
        self.limited_by = {
            "name": "workload",
            "reason_server_id": "",
            "tps_limit": max_tps,
        }
        #: consecutive control intervals the SAME limiter has been
        #: binding — the elasticity trigger's input (ISSUE 15: a
        #: resolver_busy streak past the controller's threshold recruits
        #: another resolver; one counter in the law so sim and wire
        #: consumers read the identical signal). "workload" streaks are
        #: tracked too (they read as "nothing is binding for N
        #: intervals" — the scale-down signal a future PR could spend).
        self.binding_streak = {"name": "workload", "intervals": 0}
        self.stale = False
        self._decay_from = clock()

    # -- limiter scoring ---------------------------------------------------

    def _hard_limit(self, value: float, target: float, limit: float) -> bool:
        return limit > target and value >= limit

    def _candidates(self, slots: dict) -> list[tuple[str, str, float, float]]:
        """(reason, process, value, target) per sensor reading — the
        same (process, reason, score) shape status.qos_pressures emits,
        with the raw value kept so the hard-clamp check can compare
        against an absolute limit (storage lag vs the MVCC window)."""
        out = []
        for name, q in (slots.get("tlogs") or {}).items():
            out.append((
                "log_server_write_queue", name,
                float(q.get("smoothed_queue_bytes", 0.0)),
                self.tlog_queue_target,
            ))
        for name, q in (slots.get("storages") or {}).items():
            out.append((
                "storage_server_durability_lag", name,
                float(q.get("version_lag_versions",
                            q.get("apply_lag_versions", 0))),
                self.lag_target,
            ))
        for name, q in (slots.get("resolvers") or {}).items():
            out.append((
                "resolver_busy", name,
                float(q.get("occupancy", 0.0)),
                self.resolver_busy_target,
            ))
            out.append((
                "resolver_queue", name,
                float(q.get("queue_depth", 0)),
                self.resolver_queue_target,
            ))
        for name, q in (slots.get("proxies") or {}).items():
            out.append((
                "commit_proxy_queue", name,
                float(q.get("queued_requests", 0)),
                self.proxy_queue_target,
            ))
        return out

    # -- the control step --------------------------------------------------

    def update(
        self,
        slots: Optional[dict],
        *,
        current_tps: float = 0.0,
        live_storage: Optional[int] = None,
    ) -> float:
        """One control interval: score every limiter, move the budget.

        `slots` is {"tlogs"/"storages"/"resolvers"/"proxies": {name:
        qos block}} or None for a stale/absent sensor feed (fail-safe).
        `current_tps` is the observed admission rate (the GRV proxies'
        released txn/s) — the base the multiplicative decrease scales,
        the reference's actualTps. `live_storage` (when known) guards
        the all-dead case: zero live replicas is a fail-safe clamp, not
        a zero-lag green light.
        """
        now = self.clock()
        if slots is None:
            return self._decay_locked(now)
        self._decay_from = now
        if live_storage is not None and live_storage == 0:
            # every storage replica dead: worst_lag over an empty live
            # set reads 0.0, which the old law took as "healthy" and
            # admitted at max_tps — an all-dead cluster must clamp to
            # the floor until a replica reports back (fail-safe)
            self.stale = False
            self._engaged.add("ratekeeper_failsafe")
            self.tps_budget = self.min_tps
            self.limited_by = {
                "name": "ratekeeper_failsafe",
                "reason_server_id": "",
                "tps_limit": self.min_tps,
            }
            self._note_binding("ratekeeper_failsafe")
            code_probe(True, "ratekeeper.failsafe")
            return self.tps_budget
        self.stale = False
        self._engaged.discard("ratekeeper_failsafe")

        base = min(self.tps_budget, max(current_tps, self.min_tps))
        raw = self.max_tps
        binding = ("workload", "", self.max_tps)
        for reason, proc, value, target in self._candidates(slots):
            if target <= 0:
                continue
            pressure = value / target
            hard = (
                reason == "storage_server_durability_lag"
                and self._hard_limit(value, self.lag_target, self.lag_limit)
            )
            # hysteresis state is per (reason, PROCESS): one healthy
            # tlog must not release the engagement its overloaded peer
            # holds in the band between release_frac and the target
            key = f"{reason}@{proc}"
            if pressure >= 1.0 or hard:
                self._engaged.add(key)
            elif pressure < self.release_frac:
                self._engaged.discard(key)
            if key not in self._engaged:
                continue
            if hard:
                limit = self.min_tps
            else:
                # multiplicative: scale the observed admission rate by
                # the overshoot (the reference's queue-model form:
                # limitTps ~ actualTps * target/actual); while engaged
                # below target this drifts the budget UP gently
                # (factor > 1) instead of snapping to full speed
                limit = max(
                    self.min_tps,
                    base * min(self.growth_factor, 1.0 / max(pressure, 0.5)),
                )
            if limit < raw:
                raw = limit
                binding = (reason, proc, limit)
        if raw < self.tps_budget:
            # throttle fast: the budget drops to the binding limit at
            # once (queues are already over target)
            self.tps_budget = max(self.min_tps, raw)
        else:
            # recover MULTIPLICATIVELY (anti-windup is bounded, not
            # instant): at most growth_factor x per interval, so
            # release after a long clamp doubles back toward capacity
            # instead of leaping to max_tps and re-collapsing — full
            # speed returns within ~log2(max/min) intervals (~20 for
            # the defaults) once every limiter releases
            self.tps_budget = min(
                raw,
                self.max_tps,
                self.tps_budget * self.growth_factor + self.min_tps,
            )
        if self.tps_budget >= self.max_tps:
            binding = ("workload", "", self.max_tps)
        self.limited_by = {
            "name": binding[0],
            "reason_server_id": binding[1],
            "tps_limit": binding[2],
        }
        self._note_binding(binding[0])
        return self.tps_budget

    def _note_binding(self, name: str) -> None:
        """Advance the binding-limiter streak: +1 while the same reason
        stays binding, reset to 1 on a change. Streaks key on the
        REASON only (not the process): two saturated resolvers trading
        the worst-occupancy crown are one continuous resolver_busy
        signal, which is exactly when recruiting another helps."""
        if self.binding_streak["name"] == name:
            self.binding_streak["intervals"] += 1
        else:
            self.binding_streak = {"name": name, "intervals": 1}

    def _decay_locked(self, now: float) -> float:
        dt = max(0.0, now - self._decay_from)
        self._decay_from = now
        if self.tps_budget > self.failsafe_tps:
            self.tps_budget = max(
                self.failsafe_tps,
                self.tps_budget * math.exp(-dt / self.failsafe_tau),
            )
        self.stale = True
        self.limited_by = {
            "name": "ratekeeper_failsafe",
            "reason_server_id": "",
            "tps_limit": self.tps_budget,
        }
        # a stale feed interrupts whatever streak was building: the
        # elasticity trigger must never recruit off dead sensors
        self._note_binding("ratekeeper_failsafe")
        code_probe(True, "ratekeeper.failsafe")
        return self.tps_budget

    def decay(self) -> float:
        """Fail-safe: no (fresh) sensors this interval — the budget
        decays toward the conservative floor instead of freezing at its
        last (possibly full-speed) value."""
        return self._decay_locked(self.clock())

    def rate_info(self) -> dict:
        """The GetRateInfo reply payload (sim and wire share it)."""
        return {
            "transactions_per_second_limit": self.tps_budget,
            "budget_limited_by": dict(self.limited_by),
            "binding_streak": dict(self.binding_streak),
            "budget_stale": self.stale,
            "failsafe_tps": self.failsafe_tps,
            "failsafe_tau": self.failsafe_tau,
            "max_tps": self.max_tps,
            "min_tps": self.min_tps,
        }


class Ratekeeper:
    def __init__(
        self,
        sched: Scheduler,
        sequencer,
        storage_servers: list,
        *,
        interval: float = 0.25,
        lag_target: float = 2_000_000,   # versions (~2s)
        lag_limit: float = 4_500_000,    # near the 5s MVCC window: hard clamp
        max_tps: float = 1e7,
        min_tps: float = 10.0,
        liveness: list = None,  # shared storage_live list (or None = all live)
        tlog_system=None,        # cluster LogSystem (queue-bytes sensors)
        resolvers: list = None,  # Resolver objects (occupancy sensors)
        proxies: Callable[[], list] = None,  # live commit-proxy list supplier
        grv_proxies: Callable[[], list] = None,  # admission-rate source
    ):
        self.sched = sched
        self.sequencer = sequencer
        self.storage_servers = storage_servers
        self.liveness = liveness
        self.interval = interval
        self.law = AdmissionController(
            clock=sched.now,
            max_tps=max_tps,
            min_tps=min_tps,
            lag_target=lag_target,
            lag_limit=lag_limit,
        )
        self.tlog_system = tlog_system
        self.resolvers = resolvers or []
        self._proxies = proxies or (lambda: [])
        self._grv_proxies = grv_proxies or (lambda: [])
        #: fault hook (sensor_dropout scenarios): True makes the loop's
        #: sensor read return None, so the fail-safe decay engages
        self.sensor_dropout = False
        #: virtual-clock timestamp of the last completed control loop —
        #: consumers (GrvProxy) treat an old value as a dead/flapping
        #: Ratekeeper and decay their budget toward the fail-safe floor
        self.last_loop_time = sched.now()
        self.counters = CounterCollection("RkMetrics", ["loops", "throttled"])
        # smoothed observed admission rate (GRV released txn/s) — the
        # law's actualTps input
        self._admit_smoother = Smoother(2.0 * interval, clock=sched.now)
        self._admit_last = 0
        # GlobalTagThrottler: per-transaction-tag TPS quotas. Two tiers,
        # like the reference (fdbserver/GlobalTagThrottler.actor.cpp):
        # MANAGEMENT quotas (set_tag_quota) and AUTO quotas derived from
        # observed busyness — when the pipeline is stressed (lag past
        # target), a tag dominating admissions gets throttled to its
        # fair share scaled by the stress factor; healthy intervals
        # relax the auto quota back until it lifts. Enforcement stays at
        # the GRV proxies; get_tag_quota returns the tighter tier.
        self.tag_quotas: dict[str, float] = {}
        self.auto_tag_quotas: dict[str, float] = {}
        #: a tag is "dominant" past this share of interval admissions
        self.auto_throttle_share = 0.4
        self.min_tag_tps = 1.0
        self._tag_admissions: dict[str, int] = {}
        self._task = None

    # law-config passthroughs: existing consumers (soak's slow_storage
    # scenario, tests) tune rk.lag_target / rk.max_tps directly
    @property
    def lag_target(self) -> float:
        return self.law.lag_target

    @lag_target.setter
    def lag_target(self, v: float) -> None:
        self.law.lag_target = v

    @property
    def lag_limit(self) -> float:
        return self.law.lag_limit

    @lag_limit.setter
    def lag_limit(self, v: float) -> None:
        self.law.lag_limit = v

    @property
    def max_tps(self) -> float:
        return self.law.max_tps

    @max_tps.setter
    def max_tps(self, v: float) -> None:
        self.law.max_tps = v

    @property
    def min_tps(self) -> float:
        return self.law.min_tps

    @min_tps.setter
    def min_tps(self, v: float) -> None:
        self.law.min_tps = v

    @property
    def failsafe_tps(self) -> float:
        return self.law.failsafe_tps

    @property
    def failsafe_tau(self) -> float:
        return self.law.failsafe_tau

    @property
    def tps_budget(self) -> float:
        return self.law.tps_budget

    @tps_budget.setter
    def tps_budget(self, v: float) -> None:
        self.law.tps_budget = v

    def start(self) -> None:
        self._task = self.sched.spawn(self._loop(), name="ratekeeper")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    def worst_lag(self) -> float:
        # dead replicas don't count: their frozen versions would throttle
        # the cluster forever (the reference excludes failed servers from
        # rate computation the same way). The all-dead direction is NOT
        # handled here — an empty live set returns 0.0, which the law
        # must treat as fail-safe, never as "no lag" (see update()).
        head = self.sequencer.live_committed.get()
        return max(
            (
                head - ss.version.get()
                for i, ss in enumerate(self.storage_servers)
                if self.liveness is None or self.liveness[i]
            ),
            default=0.0,
        )

    def _live_storage_count(self) -> Optional[int]:
        if self.liveness is None:
            return None
        return sum(1 for alive in self.liveness if alive)

    def get_rate_info(self) -> float:
        """GetRateInfoRequest: the current per-second txn budget."""
        return self.law.tps_budget

    def rate_info(self) -> dict:
        """The full GetRateInfo payload (budget + binding limiter)."""
        return self.law.rate_info()

    def budget_age(self, now: float) -> float:
        """Seconds since the control loop last ran — the consumers'
        staleness signal (a dead Ratekeeper's budget must not be
        trusted at full speed forever)."""
        return max(0.0, now - self.last_loop_time)

    def _read_sensors(self) -> Optional[dict]:
        """One reading of every role's saturation sensors, shaped as
        the law's slot dict. None when the feed is down (fault hook)."""
        if self.sensor_dropout:
            return None
        head = self.sequencer.live_committed.get()
        slots: dict = {"tlogs": {}, "storages": {}, "resolvers": {},
                       "proxies": {}}
        if self.tlog_system is not None:
            for i, t in enumerate(self.tlog_system.tlogs):
                if self.tlog_system.live[i]:
                    slots["tlogs"][f"tlog{i}"] = {
                        "smoothed_queue_bytes":
                            t.smoothed_queue_bytes.smooth_total(),
                    }
        for i, ss in enumerate(self.storage_servers):
            if self.liveness is None or self.liveness[i]:
                slots["storages"][f"storage{i}"] = {
                    "version_lag_versions": max(
                        0.0, head - ss.version.get()
                    ),
                }
        for i, r in enumerate(self.resolvers):
            slots["resolvers"][f"resolver{i}"] = {
                "occupancy": r.occupancy.smooth_rate(),
                "queue_depth": r.version.num_waiting(),
            }
        for i, p in enumerate(self._proxies()):
            slots["proxies"][getattr(p, "proxy_id", f"proxy{i}")] = {
                "queued_requests": p.saturation().get("queued_requests", 0),
            }
        return slots

    def _observed_admit_tps(self) -> float:
        released = sum(
            g.counters.get("txnRequestOut") for g in self._grv_proxies()
        )
        self._admit_smoother.add_delta(max(0, released - self._admit_last))
        self._admit_last = released
        return self._admit_smoother.smooth_rate()

    def status(self) -> dict:
        """The Ratekeeper's slice of the status `qos` section (the
        reference surfaces transactions_per_second_limit and the
        throttled-tag set the same way, Status.actor.cpp): the live
        budget, its bounds, the binding limiter (one vocabulary with
        performance_limited_by), the control inputs, and both quota
        tiers — so the admission-control loop is observable."""
        lag = self.worst_lag()
        return {
            **self.law.rate_info(),
            "worst_storage_lag_versions": lag,
            "lag_target_versions": self.lag_target,
            "lag_limit_versions": self.lag_limit,
            "admit_tps": self._admit_smoother.smooth_rate(),
            "throttled_intervals": self.counters.get("throttled"),
            "control_loops": self.counters.get("loops"),
            "tag_quotas": dict(self.tag_quotas),
            "auto_tag_quotas": dict(self.auto_tag_quotas),
        }

    def set_tag_quota(self, tag: str, tps: float) -> None:
        """Management surface: cap a transaction tag's start rate."""
        self.tag_quotas[tag] = tps

    def get_tag_quota(self, tag: str) -> float:
        return min(
            self.tag_quotas.get(tag, float("inf")),
            self.auto_tag_quotas.get(tag, float("inf")),
        )

    def note_tag_admission(self, tag: str) -> None:
        """GRV proxies report each admitted tagged request: the busyness
        signal the auto throttler derives quotas from."""
        self._tag_admissions[tag] = self._tag_admissions.get(tag, 0) + 1

    def _auto_quota_floor(self, tag: str) -> float:
        """The auto tier's floor for one tag: never below min_tag_tps,
        and never undercutting an EXPLICIT management quota — repeated
        stressed intervals used to ratchet the auto quota monotonically
        below what the operator deliberately granted via
        set_tag_quota (the management tier already caps the tag; auto
        pushing further starves it with no operator action to blame)."""
        floor = self.min_tag_tps
        mgmt = self.tag_quotas.get(tag)
        if mgmt is not None:
            floor = max(floor, mgmt)
        return floor

    def _update_auto_tag_quotas(self, lag: float) -> None:
        admissions = self._tag_admissions
        self._tag_admissions = {}
        total = sum(admissions.values())
        if lag > self.lag_target and total > 0:
            stress = min(
                1.0,
                (lag - self.lag_target) / (self.lag_limit - self.lag_target),
            )
            for tag, n in admissions.items():
                if n / total < self.auto_throttle_share:
                    continue
                rate = n / self.interval
                floor = self._auto_quota_floor(tag)
                # throttle the dominant tag toward its stressed fair
                # share; repeated stressed intervals ratchet it down —
                # but never through the floor (min_tag_tps, and any
                # explicit management quota)
                target = max(floor, rate * (1.0 - stress) * 0.5)
                cur = self.auto_tag_quotas.get(tag, float("inf"))
                self.auto_tag_quotas[tag] = max(floor, min(cur, target))
                code_probe(True, "ratekeeper.auto_tag_throttled")
        elif lag <= self.lag_target and self.auto_tag_quotas:
            # healthy interval: relax each auto quota; lift it once it
            # stops binding (2x headroom over the tag's observed rate)
            for tag in list(self.auto_tag_quotas):
                q = self.auto_tag_quotas[tag] * 2.0
                rate = admissions.get(tag, 0) / self.interval
                if q > max(rate * 2.0, self.min_tag_tps * 4):
                    del self.auto_tag_quotas[tag]
                    code_probe(True, "ratekeeper.auto_tag_lifted")
                else:
                    self.auto_tag_quotas[tag] = q

    async def _loop(self) -> None:
        try:
            while True:
                await self.sched.delay(self.interval)
                self.counters.add("loops")
                lag = self.worst_lag()
                self._update_auto_tag_quotas(lag)
                self.law.update(
                    self._read_sensors(),
                    current_tps=self._observed_admit_tps(),
                    live_storage=self._live_storage_count(),
                )
                self.last_loop_time = self.sched.now()
                if self.law.tps_budget < self.law.max_tps:
                    self.counters.add("throttled")
                    code_probe(True, "ratekeeper.throttled")
        except ActorCancelled:
            raise
