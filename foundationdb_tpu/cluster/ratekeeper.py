"""Ratekeeper: cluster-wide admission control.

Behavioral mirror of `fdbserver/Ratekeeper.actor.cpp`: a control loop
samples the health of the write pipeline (here: storage-server version
lag behind the sequencer — the v0 stand-in for storage/TLog queue bytes)
and computes a transactions-per-second budget; GRV proxies fetch the
budget (`GetRateInfoRequest`, served at :475) and release read versions
no faster than that, which throttles new transactions at the front door
— the same backpressure point the reference uses.

The control law is a simplified version of the reference's: full speed
while the worst storage lag is under `lag_target`, then multiplicative
backoff toward `min_rate` as lag approaches `lag_limit`.
"""

from __future__ import annotations

from foundationdb_tpu.runtime.flow import ActorCancelled, Scheduler
from foundationdb_tpu.utils.metrics import CounterCollection
from foundationdb_tpu.utils.probes import code_probe, declare

declare("ratekeeper.throttled", "ratekeeper.auto_tag_throttled")


class Ratekeeper:
    def __init__(
        self,
        sched: Scheduler,
        sequencer,
        storage_servers: list,
        *,
        interval: float = 0.25,
        lag_target: float = 2_000_000,   # versions (~2s)
        lag_limit: float = 4_500_000,    # near the 5s MVCC window: hard clamp
        max_tps: float = 1e7,
        min_tps: float = 10.0,
        liveness: list = None,  # shared storage_live list (or None = all live)
    ):
        self.sched = sched
        self.sequencer = sequencer
        self.storage_servers = storage_servers
        self.liveness = liveness
        self.interval = interval
        self.lag_target = lag_target
        self.lag_limit = lag_limit
        self.max_tps = max_tps
        self.min_tps = min_tps
        self.tps_budget = max_tps
        self.counters = CounterCollection("RkMetrics", ["loops", "throttled"])
        # GlobalTagThrottler: per-transaction-tag TPS quotas. Two tiers,
        # like the reference (fdbserver/GlobalTagThrottler.actor.cpp):
        # MANAGEMENT quotas (set_tag_quota) and AUTO quotas derived from
        # observed busyness — when the pipeline is stressed (lag past
        # target), a tag dominating admissions gets throttled to its
        # fair share scaled by the stress factor; healthy intervals
        # relax the auto quota back until it lifts. Enforcement stays at
        # the GRV proxies; get_tag_quota returns the tighter tier.
        self.tag_quotas: dict[str, float] = {}
        self.auto_tag_quotas: dict[str, float] = {}
        #: a tag is "dominant" past this share of interval admissions
        self.auto_throttle_share = 0.4
        self.min_tag_tps = 1.0
        self._tag_admissions: dict[str, int] = {}
        self._task = None

    def start(self) -> None:
        self._task = self.sched.spawn(self._loop(), name="ratekeeper")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    def worst_lag(self) -> float:
        # dead replicas don't count: their frozen versions would throttle
        # the cluster forever (the reference excludes failed servers from
        # rate computation the same way)
        head = self.sequencer.live_committed.get()
        return max(
            (
                head - ss.version.get()
                for i, ss in enumerate(self.storage_servers)
                if self.liveness is None or self.liveness[i]
            ),
            default=0.0,
        )

    def get_rate_info(self) -> float:
        """GetRateInfoRequest: the current per-second txn budget."""
        return self.tps_budget

    def status(self) -> dict:
        """The Ratekeeper's slice of the status `qos` section (the
        reference surfaces transactions_per_second_limit and the
        throttled-tag set the same way, Status.actor.cpp): the live
        budget, its bounds, the control inputs, and both quota tiers —
        so the admission-control loop is observable from day one."""
        lag = self.worst_lag()
        return {
            "transactions_per_second_limit": self.tps_budget,
            "max_tps": self.max_tps,
            "min_tps": self.min_tps,
            "worst_storage_lag_versions": lag,
            "lag_target_versions": self.lag_target,
            "lag_limit_versions": self.lag_limit,
            "throttled_intervals": self.counters.get("throttled"),
            "control_loops": self.counters.get("loops"),
            "tag_quotas": dict(self.tag_quotas),
            "auto_tag_quotas": dict(self.auto_tag_quotas),
        }

    def set_tag_quota(self, tag: str, tps: float) -> None:
        """Management surface: cap a transaction tag's start rate."""
        self.tag_quotas[tag] = tps

    def get_tag_quota(self, tag: str) -> float:
        return min(
            self.tag_quotas.get(tag, float("inf")),
            self.auto_tag_quotas.get(tag, float("inf")),
        )

    def note_tag_admission(self, tag: str) -> None:
        """GRV proxies report each admitted tagged request: the busyness
        signal the auto throttler derives quotas from."""
        self._tag_admissions[tag] = self._tag_admissions.get(tag, 0) + 1

    def _update_auto_tag_quotas(self, lag: float) -> None:
        admissions = self._tag_admissions
        self._tag_admissions = {}
        total = sum(admissions.values())
        if lag > self.lag_target and total > 0:
            stress = min(
                1.0,
                (lag - self.lag_target) / (self.lag_limit - self.lag_target),
            )
            for tag, n in admissions.items():
                if n / total < self.auto_throttle_share:
                    continue
                rate = n / self.interval
                # throttle the dominant tag toward its stressed fair
                # share; repeated stressed intervals ratchet it down
                target = max(self.min_tag_tps, rate * (1.0 - stress) * 0.5)
                cur = self.auto_tag_quotas.get(tag, float("inf"))
                self.auto_tag_quotas[tag] = min(cur, target)
                code_probe(True, "ratekeeper.auto_tag_throttled")
        elif lag <= self.lag_target and self.auto_tag_quotas:
            # healthy interval: relax each auto quota; lift it once it
            # stops binding (2x headroom over the tag's observed rate)
            for tag in list(self.auto_tag_quotas):
                q = self.auto_tag_quotas[tag] * 2.0
                rate = admissions.get(tag, 0) / self.interval
                if q > max(rate * 2.0, self.min_tag_tps * 4):
                    del self.auto_tag_quotas[tag]
                else:
                    self.auto_tag_quotas[tag] = q

    async def _loop(self) -> None:
        try:
            while True:
                await self.sched.delay(self.interval)
                self.counters.add("loops")
                lag = self.worst_lag()
                self._update_auto_tag_quotas(lag)
                if lag <= self.lag_target:
                    self.tps_budget = self.max_tps
                elif lag >= self.lag_limit:
                    self.tps_budget = self.min_tps
                    self.counters.add("throttled")
                    code_probe(True, "ratekeeper.throttled")
                else:
                    frac = (self.lag_limit - lag) / (
                        self.lag_limit - self.lag_target
                    )
                    self.tps_budget = max(self.min_tps, self.max_tps * frac)
                    self.counters.add("throttled")
                    code_probe(True, "ratekeeper.throttled")
        except ActorCancelled:
            raise
