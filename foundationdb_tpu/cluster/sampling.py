"""Hot-key / hot-tag sampling: the keyspace-skew sensing substrate.

Behavioral mirror of the reference's two skew sensors:

* **ByteSample** — `StorageMetrics`' byteSample
  (fdbserver/StorageMetrics.actor.cpp `isKeyValueInSample`): every
  written key is sampled with probability proportional to its
  key+value size (`size / ((key_len + OVERHEAD) * FACTOR)`), and a
  sampled key is stored with weight `size / min(1, p)` so the sample's
  weight sum is an unbiased estimator of true bytes over ANY key
  range. Membership is decided by a keyed hash of the key — NOT an rng
  stream — so the sample set is a pure function of (seed, key, size):
  bit-identical per sim seed regardless of arrival order, exactly the
  property the soak determinism pin (`--status-probe`) needs. Wire
  roles seed from wall entropy (`seed=None`) like the reference's
  process-local hash salt.
* **TransactionTagCounter** — the busiest read/write tag tracker
  (fdbserver/TransactionTagCounter.cpp): per-tag Smoother-decayed byte
  rates with a bounded tag table (lowest-rate half evicted on
  overflow), reporting the top-K busiest tags and each tag's fraction
  of total traffic. Clock-injection discipline per PR 7: sim roles
  pass the virtual `sched.now`, wire roles fall back to TimerSmoother.

The range-sum query is O(log n): the sample lives in a treap whose
priorities are hash-derived (deterministic — no rng — so tree SHAPE is
also a pure function of the sample set) and whose nodes carry subtree
weight sums, split/merged per query like the reference's
`StorageMetricSample` indexedmap.

Tags are derived from key prefixes (`tenant/...`, the tenant layer's
convention) at the sensor site, so no wire frame grows a tag field —
the sensors see exactly the bytes that already flow.
"""

from __future__ import annotations

import hashlib
import struct
from typing import Iterator, Optional

from foundationdb_tpu.utils.probes import code_probe, declare

declare(
    "sampling.byte_sample_gc",
    "sampling.hot_range_attributed",
    "sampling.tag_counter_rollover",
)

#: reference knobs (Knobs.cpp BYTE_SAMPLING_FACTOR / _OVERHEAD): a
#: key+value of `size` bytes is sampled w.p.
#: size / ((key_len + OVERHEAD) * FACTOR)
BYTE_SAMPLING_FACTOR = 250
BYTE_SAMPLING_OVERHEAD = 100
#: sample entries per storage role before the deterministic halving GC
BYTE_SAMPLE_CAPACITY = 32768

#: tag-prefix derivation: `tenant/rest-of-key` -> tag "tenant"
TAG_SEPARATOR = b"/"
MAX_TAG_LENGTH = 24
#: tenant.py TENANT_DATA_PREFIX (redeclared: tenant.py imports cluster
#: modules and sampling must stay leaf-importable from utils tests)
_TENANT_DATA_PREFIX = b"\x1e"

#: a top-1 tag/range owning at least this fraction of traffic is a
#: HOTSPOT; a uniform workload over >= 3 tags/ranges sits well below it
DOMINANCE_FRAC = 0.5
#: minimum sampled keys behind a hot-RANGE verdict: a 2-key sample can
#: put half its weight anywhere — that's noise, not skew (the tag
#: channel has no such floor; its rates integrate every byte)
HOT_RANGE_MIN_KEYS = 8


def printable(key: bytes) -> str:
    """JSON/terminal-safe rendering of a key: ascii stays, everything
    else escapes — deterministic and reversible enough for a human."""
    return "".join(
        chr(c) if 32 <= c < 127 else "\\x%02x" % c for c in key
    )


def tag_of_key(key: bytes) -> Optional[str]:
    """The transaction tag a key's traffic accrues to: the prefix
    before the first `/` (tenant-layer convention; the `\\x1e` tenant
    data prefix is stripped first). Keys without a short prefix are
    untagged (None) — they count toward totals but never toward a
    tag, so an unprefixed workload can't fake a busiest tag."""
    if key[:1] == _TENANT_DATA_PREFIX:
        key = key[1:]
    i = key.find(TAG_SEPARATOR, 0, MAX_TAG_LENGTH + 1)
    if i <= 0:
        return None
    return printable(key[:i])


def _hash_channels(seed: int, key: bytes) -> tuple[float, int]:
    """Two independent deterministic channels from one keyed digest:
    (membership uniform in [0, 1), treap priority int)."""
    d = hashlib.blake2b(
        key, digest_size=16, key=struct.pack("<Q", seed & (2**64 - 1))
    ).digest()
    u = int.from_bytes(d[:8], "little") / 2.0**64
    prio = int.from_bytes(d[8:], "little")
    return u, prio


# ---------------------------------------------------------------------------
# The augmented treap: ordered map key -> weight with subtree sums.


class _Node:
    __slots__ = ("key", "size", "p", "u", "prio", "weight", "sum",
                 "count", "left", "right")

    def __init__(self, key: bytes, size: int, p: float, u: float,
                 prio: int, weight: float):
        self.key = key
        self.size = size
        self.p = p
        self.u = u
        self.prio = prio
        self.weight = weight
        self.sum = weight
        self.count = 1
        self.left: Optional[_Node] = None
        self.right: Optional[_Node] = None


def _upd(n: _Node) -> _Node:
    n.sum = n.weight
    n.count = 1
    if n.left is not None:
        n.sum += n.left.sum
        n.count += n.left.count
    if n.right is not None:
        n.sum += n.right.sum
        n.count += n.right.count
    return n


def _merge(a: Optional[_Node], b: Optional[_Node]) -> Optional[_Node]:
    if a is None:
        return b
    if b is None:
        return a
    if a.prio >= b.prio:
        a.right = _merge(a.right, b)
        return _upd(a)
    b.left = _merge(a, b.left)
    return _upd(b)


def _split(n: Optional[_Node], key: bytes):
    """(keys < key, keys >= key)."""
    if n is None:
        return None, None
    if n.key < key:
        l, r = _split(n.right, key)
        n.right = l
        return _upd(n), r
    l, r = _split(n.left, key)
    n.left = r
    return l, _upd(n)


def _walk(n: Optional[_Node]) -> Iterator[_Node]:
    if n is None:
        return
    yield from _walk(n.left)
    yield n
    yield from _walk(n.right)


class ByteSample:
    """Deterministic size-proportional key sample with O(log n)
    sampled-bytes-in-range queries (the StorageMetrics byteSample)."""

    def __init__(self, seed: Optional[int] = None, *,
                 factor: int = BYTE_SAMPLING_FACTOR,
                 overhead: int = BYTE_SAMPLING_OVERHEAD,
                 capacity: int = BYTE_SAMPLE_CAPACITY):
        if seed is None:
            # wire roles: wall entropy, like the reference's per-process
            # hash salt (sim roles MUST pass their derived seed)
            import os

            seed = int.from_bytes(os.urandom(8), "little")  # flowcheck: ignore[determinism]
        self.seed = seed & (2**64 - 1)
        self.factor = factor
        self.overhead = overhead
        self.capacity = capacity
        #: global membership scale: halved by each GC round so the
        #: sample re-converges to capacity instead of thrashing
        self.scale = 1.0
        self.gc_rounds = 0
        self.writes_seen = 0
        self._root: Optional[_Node] = None

    # -- mutation hooks ----------------------------------------------------

    def note_write(self, key: bytes, value: bytes = b"") -> None:
        """A set/atomic landed: resample the key at its new size (the
        old entry, if any, is replaced — sizes change on overwrite)."""
        self.writes_seen += 1
        size = len(key) + len(value)
        p = size / ((len(key) + self.overhead) * self.factor)
        u, prio = _hash_channels(self.seed, key)
        self.erase(key)
        eff = p * self.scale
        if u < eff:
            weight = size / min(1.0, eff)
            l, r = _split(self._root, key)
            self._root = _merge(
                _merge(l, _Node(key, size, p, u, prio, weight)), r
            )
            if self.count > self.capacity:
                self._gc()

    def erase(self, key: bytes) -> None:
        l, r = _split(self._root, key)
        m, r = _split(r, key + b"\x00")
        del m  # the exact-key node, if sampled
        self._root = _merge(l, r)

    def erase_range(self, begin: bytes, end: bytes) -> None:
        l, r = _split(self._root, begin)
        _m, r = _split(r, end)
        self._root = _merge(l, r)

    # -- queries -----------------------------------------------------------

    @property
    def count(self) -> int:
        return self._root.count if self._root is not None else 0

    def total_bytes(self) -> int:
        return int(round(self._root.sum)) if self._root is not None else 0

    def sampled_bytes(self, begin: bytes = b"",
                      end: Optional[bytes] = None) -> int:
        """Estimated true bytes in [begin, end) (end=None: to +inf) —
        the subtree weight sum, O(log n) via two splits."""
        l, r = _split(self._root, begin)
        if end is None:
            m, rest = r, None
        else:
            m, rest = _split(r, end)
        total = m.sum if m is not None else 0.0
        self._root = _merge(_merge(l, m), rest)
        return int(round(total))

    def items(self) -> list[tuple[bytes, float]]:
        """(key, weight) in key order — O(n); status-poll cadence."""
        return [(n.key, n.weight) for n in _walk(self._root)]

    def hot_ranges(self, max_ranges: int = 8) -> list[dict]:
        """Sampled-byte density grouped by key prefix (tag prefix when
        present, first-byte bucket otherwise): the keyspace heatmap's
        rows, sorted hottest first. `frac` is each range's share of
        this sample's total weight."""
        groups: dict[str, list] = {}
        for n in _walk(self._root):
            label = tag_of_key(n.key)
            if label is None:
                label = "%02x" % n.key[0] if n.key else ""
            g = groups.get(label)
            if g is None:
                groups[label] = [n.weight, n.key, n.key, 1]
            else:
                g[0] += n.weight
                g[3] += 1
                if n.key > g[2]:
                    g[2] = n.key
        total = sum(g[0] for g in groups.values())
        rows = [
            {
                "range": label,
                "begin": printable(g[1]),
                "end": printable(g[2]),
                "bytes": int(round(g[0])),
                "keys": g[3],
                "frac": round(g[0] / total, 4) if total > 0 else 0.0,
            }
            for label, g in groups.items()
        ]
        rows.sort(key=lambda r: (-r["bytes"], r["range"]))
        return rows[:max_ranges]

    # -- GC ----------------------------------------------------------------

    def _gc(self) -> None:
        """Deterministic down-sampling: halve the membership scale and
        keep exactly the entries whose hash still clears it — the
        surviving sample is the sample a half-rate collector would have
        built, weights doubled accordingly."""
        while self.count > self.capacity:
            code_probe(True, "sampling.byte_sample_gc")
            before = self.count
            self.scale /= 2.0
            self.gc_rounds += 1
            survivors = [
                n for n in _walk(self._root)
                if n.u < n.p * self.scale
            ]
            self._root = None
            for n in survivors:
                eff = n.p * self.scale
                node = _Node(n.key, n.size, n.p, n.u, n.prio,
                             n.size / min(1.0, eff))
                l, r = _split(self._root, n.key)
                self._root = _merge(_merge(l, node), r)
            from foundationdb_tpu.utils.trace import TraceEvent

            TraceEvent("ByteSampleGC").detail(
                "Before", before
            ).detail("After", self.count).detail(
                "Scale", self.scale
            ).log()

    # -- checkpoint / resume ----------------------------------------------

    def snapshot(self) -> dict:
        """Durable state for a storage reboot (hash channels recompute
        from the seed, so only sizes need persisting)."""
        return {
            "seed": self.seed,
            "factor": self.factor,
            "overhead": self.overhead,
            "capacity": self.capacity,
            "scale": self.scale,
            "gc_rounds": self.gc_rounds,
            "writes_seen": self.writes_seen,
            "items": [(n.key, n.size) for n in _walk(self._root)],
        }

    def restore(self, snap: dict) -> None:
        self.seed = snap["seed"]
        self.factor = snap["factor"]
        self.overhead = snap["overhead"]
        self.capacity = snap["capacity"]
        self.scale = snap["scale"]
        self.gc_rounds = snap["gc_rounds"]
        self.writes_seen = snap["writes_seen"]
        self._root = None
        for key, size in snap["items"]:
            p = size / ((len(key) + self.overhead) * self.factor)
            u, prio = _hash_channels(self.seed, key)
            eff = p * self.scale
            node = _Node(key, size, p, u, prio,
                         size / min(1.0, eff))
            l, r = _split(self._root, key)
            self._root = _merge(_merge(l, node), r)


# ---------------------------------------------------------------------------
# TransactionTagCounter: top-K busiest tags by smoothed byte rate.


class TagCounter:
    """Bounded per-tag byte-rate tracker (the reference's
    TransactionTagCounter). Sim roles inject the virtual clock
    (`clock=sched.now`); wire roles omit it and get TimerSmoother."""

    def __init__(self, *, k: int = 4, capacity: int = 32,
                 folding_time: float = 5.0, clock=None):
        self.k = k
        self.capacity = capacity
        self.folding_time = folding_time
        self._clock = clock
        self._rates: dict[str, object] = {}
        self._total = self._new_smoother()
        self.rollovers = 0
        self.notes = 0
        #: deterministic lifetime byte counter (the perf-ledger input:
        #: no smoothing, so it is a pure function of the workload)
        self.bytes_noted = 0

    def _new_smoother(self):
        from foundationdb_tpu.utils.metrics import Smoother, TimerSmoother

        if self._clock is not None:
            return Smoother(self.folding_time, clock=self._clock)
        return TimerSmoother(self.folding_time)

    def note(self, tag: Optional[str], nbytes: int) -> None:
        self.notes += 1
        self.bytes_noted += nbytes
        self._total.add_delta(nbytes)
        if tag is None:
            return
        sm = self._rates.get(tag)
        if sm is None:
            if len(self._rates) >= self.capacity:
                self._rollover()
            sm = self._rates[tag] = self._new_smoother()
        sm.add_delta(nbytes)

    def _rollover(self) -> None:
        """Tag table overflow: evict the colder half (ties broken by
        name — deterministic under the virtual clock)."""
        code_probe(True, "sampling.tag_counter_rollover")
        ranked = sorted(
            self._rates.items(),
            key=lambda kv: (kv[1].smooth_rate(), kv[0]),
        )
        for tag, _sm in ranked[: max(1, len(ranked) // 2)]:
            del self._rates[tag]
        self.rollovers += 1

    def top(self, k: Optional[int] = None) -> list[dict]:
        total = self._total.smooth_rate()
        rows = sorted(
            (
                {
                    "tag": tag,
                    "bytes_per_s": round(sm.smooth_rate(), 3),
                    "frac": (
                        round(sm.smooth_rate() / total, 4)
                        if total > 1e-12 else 0.0
                    ),
                }
                for tag, sm in self._rates.items()
            ),
            key=lambda r: (-r["bytes_per_s"], r["tag"]),
        )
        return rows[: (k if k is not None else self.k)]

    def busiest(self) -> dict:
        """The top-1 row — schema-stable: always a dict, tag None when
        nothing tagged has flowed yet (fdbtop pins the field)."""
        rows = self.top(1)
        if not rows:
            return {"tag": None, "bytes_per_s": 0.0, "frac": 0.0}
        return rows[0]


# ---------------------------------------------------------------------------
# Conflict-range key sample: shared by the sim and wire resolvers so
# both report the identical qos block (the ResolutionBalancer's split
# input, Resolver.actor.cpp:337-344).

#: key-sample capacity before decay (matches resolver.KEY_SAMPLE_LIMIT)
KEY_SAMPLE_LIMIT = 4096


def decay_key_sample(sample: dict, limit: int = KEY_SAMPLE_LIMIT) -> None:
    """In-place: halve all counts dropping zeros; if the key set itself
    is still too wide, keep the heaviest half. Hot boundaries survive
    decay by construction while memory stays O(limit) forever."""
    kept = {k: c // 2 for k, c in sample.items() if c // 2 > 0}
    if len(kept) > limit:
        top = sorted(kept.items(), key=lambda kv: -kv[1])
        kept = dict(top[: limit // 2])
    sample.clear()
    sample.update(kept)


def key_sample_qos(sample: dict, top_n: int = 4) -> dict:
    """The key-sample sensor block: sample width plus the top
    conflict-range begin keys by touch count (printable, bounded — a
    status document, not a dump)."""
    top = sorted(sample.items(), key=lambda kv: (-kv[1], kv[0]))[:top_n]
    return {
        "keys": len(sample),
        "top": [{"key": printable(k), "count": c} for k, c in top],
    }


# ---------------------------------------------------------------------------
# Attribution: the skew-drill gate's verdict from an assembled status.


def attribute_hotspot(status: dict, *,
                      threshold: float = DOMINANCE_FRAC) -> dict:
    """Name the dominant tag/range from a status document's cluster
    rollup, or nothing: a top-1 owning >= `threshold` of its traffic
    is attributed, anything flatter is not. Both the zipf drill (must
    attribute the injected tenant) and the uniform drill (must NOT)
    gate on this one rule."""
    cluster = status.get("cluster", status) or {}
    tags = cluster.get("busiest_tags") or []
    ranges = cluster.get("hot_ranges") or []
    hot_tag = (
        tags[0] if tags and tags[0].get("frac", 0.0) >= threshold
        else None
    )
    hot_range = (
        ranges[0]
        if ranges
        and ranges[0].get("frac", 0.0) >= threshold
        # support floor: a near-empty byte sample puts large fractions
        # behind single keys — no verdict without HOT_RANGE_MIN_KEYS
        and ranges[0].get("keys", HOT_RANGE_MIN_KEYS) >= HOT_RANGE_MIN_KEYS
        else None
    )
    attributed = hot_tag is not None or hot_range is not None
    code_probe(attributed, "sampling.hot_range_attributed")
    if attributed:
        from foundationdb_tpu.utils.trace import TraceEvent

        TraceEvent("HotRangeAttributed").detail(
            "Tag", hot_tag["tag"] if hot_tag else None
        ).detail(
            "Range", hot_range["range"] if hot_range else None
        ).log()
    return {
        "attributed": attributed,
        "hot_tag": hot_tag,
        "hot_range": hot_range,
        "threshold": threshold,
    }
