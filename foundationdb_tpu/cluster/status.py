"""Cluster status: the machine-readable health/metrics document.

Behavioral mirror of `fdbserver/Status.actor.cpp` (schema shape from
fdbclient/Schemas.cpp): one JSON-able dict aggregating every role's
counters, versions, latencies, and configuration — what `fdbcli status`
and monitoring consume. The `processes` section carries one entry per
role instance (role kind, version, counters, latency distributions);
`cluster.latency_bands` rolls the reference-style commit/GRV/read bands
up across role instances; `cluster.resolver_kernel` surfaces the TPU
resolver's always-on kernel stage metrics (models/conflict_set.py
KernelStageMetrics)."""

from __future__ import annotations

from typing import Any


def _merge_bands(bands_list) -> dict[str, int]:
    """Sum LatencyBands dicts across role instances (identical edges by
    construction — the thresholds are module constants)."""
    out: dict[str, int] = {}
    for b in bands_list:
        for k, v in b.as_dict().items():
            out[k] = out.get(k, 0) + v
    return out


def _kernel_section(resolver) -> dict[str, Any]:
    cs = resolver.conflict_set
    metrics = getattr(cs, "metrics", None)
    if metrics is None:
        return {"backend": "unrouted"}
    return {
        "backend": type(cs).__name__,
        **metrics.as_dict(),
    }


def cluster_status(cluster) -> dict[str, Any]:
    seq = cluster.sequencer
    cfg = cluster.config
    data = {
        "cluster": {
            "configuration": {
                "commit_proxies": len(cluster.commit_proxies),
                "grv_proxies": cfg.n_grv_proxies,
                "resolvers": len(cluster.resolvers),
                "storage_servers": len(cluster.storage_servers),
                "logs": cfg.n_tlogs,
                "coordinators": cfg.n_coordinators,
                "resolver_backend": cfg.resolver_backend or "tpu",
            },
            "datacenter_lag": {"versions": 0},
            "latest_version": seq.version,
            "live_committed_version": seq.live_committed.get(),
            "qos": {
                "transactions_per_second_limit": cluster.ratekeeper.tps_budget,
                "worst_storage_lag_versions": cluster.ratekeeper.worst_lag(),
            },
            "workload": {
                "transactions": {
                    "committed": sum(
                        p.counters.get("txnCommitOut")
                        for p in cluster.commit_proxies
                    ),
                    "conflicted": sum(
                        p.counters.get("txnConflicts")
                        for p in cluster.commit_proxies
                    ),
                    "started": sum(
                        p.counters.get("txnCommitIn")
                        for p in cluster.commit_proxies
                    ),
                },
                "grv": cluster.grv_proxy.counters.as_dict(),
            },
            # reference-style latency bands (fdbrpc/Stats.h LatencyBands
            # -> the status schema's latency_statistics buckets), rolled
            # up across role instances
            "latency_bands": {
                "commit": _merge_bands(
                    p.latency_bands for p in cluster.commit_proxies
                ),
                "grv": _merge_bands([cluster.grv_proxy.latency_bands]),
                "read": _merge_bands(
                    ss.read_latency_bands for ss in cluster.storage_servers
                ),
            },
            # the TPU resolver's always-on kernel stage metrics
            # (pack/transfer/kernel/fence, tier occupancy, compactions,
            # latch/fallback counts, overflow events)
            "resolver_kernel": {
                f"resolver{r.resolver_id}": _kernel_section(r)
                for r in cluster.resolvers
            },
            "processes": {},
        }
    }
    procs = data["cluster"]["processes"]
    for i, r in enumerate(cluster.resolvers):
        procs[f"resolver{i}"] = {
            "role": "resolver",
            "version": r.version.get(),
            "counters": r.counters.as_dict(),
            "latency": {
                "resolver": r.resolver_latency.as_dict(),
                "queue_wait": r.queue_wait_latency.as_dict(),
                "compute": r.compute_time.as_dict(),
            },
            "kernel": _kernel_section(r),
            "total_state_bytes": r.total_state_bytes,
        }
    for i, p in enumerate(cluster.commit_proxies):
        procs[f"proxy{i}"] = {
            "role": "commit_proxy",
            "committed_version": p.committed_version.get(),
            "counters": p.counters.as_dict(),
            "latency": {"commit": p.commit_latency.as_dict()},
            "latency_bands": p.latency_bands.as_dict(),
            "failed": p.failed is not None,
        }
    procs["grv_proxy0"] = {
        "role": "grv_proxy",
        "counters": cluster.grv_proxy.counters.as_dict(),
        "latency": {"grv": cluster.grv_proxy.grv_latency.as_dict()},
        "latency_bands": cluster.grv_proxy.latency_bands.as_dict(),
    }
    for i, ss in enumerate(cluster.storage_servers):
        procs[f"storage{i}"] = {
            "role": "storage",
            "version": ss.version.get(),
            "durable_version": ss.durable_version,
            "keys": len(ss._keys),
            "latency": {"read": ss.read_latency.as_dict()},
            "latency_bands": ss.read_latency_bands.as_dict(),
            "live": cluster.storage_live[i],
        }
    for i in range(cfg.n_tlogs):
        procs[f"tlog{i}"] = {
            "role": "log",
            "version": cluster.tlog.tlogs[i].version.get(),
            "live": bool(cluster.tlog.live[i]),
        }
    procs["sequencer"] = {"role": "master", "version": seq.version}
    return data
