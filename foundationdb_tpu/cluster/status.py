"""Cluster status: the machine-readable health/metrics document.

Behavioral mirror of `fdbserver/Status.actor.cpp` (schema shape from
fdbclient/Schemas.cpp): one JSON-able dict aggregating every role's
counters, versions, latencies, and configuration — what `fdbcli status`
and monitoring consume. The `processes` section carries one entry per
role instance (role kind, version, counters, latency distributions, and
a `qos` saturation block from the role's `saturation()` sensors);
`cluster.latency_bands` rolls the reference-style commit/GRV/read bands
up across role instances; `cluster.resolver_kernel` surfaces the TPU
resolver's always-on kernel stage metrics (models/conflict_set.py
KernelStageMetrics); `cluster.qos` is the reference's qos section —
worst storage/tlog queue health, worst version lag, the Ratekeeper's
live budget, and `performance_limited_by` naming the process class
closest to saturation. The same qos math serves the wire-mode
aggregation (cluster/multiprocess.py `wire_cluster_status`) so fdbtop
renders one schema for both deployment shapes."""

from __future__ import annotations

from typing import Any

# ---------------------------------------------------------------------------
# Saturation budgets: the denominators that turn raw sensor readings into
# comparable pressure scores (the reference's analogs live in ServerKnobs —
# TARGET_BYTES_PER_TLOG, MAX_TL_SS_VERSION_DIFFERENCE, ...). Status readers
# expect stable semantics, so these are module constants, not knobs.

#: retained tlog queue bytes at which the log counts as saturated
#: (the reference throttles toward TARGET_BYTES_PER_TLOG = 2.4 GB; the
#: sim tlog spills to its simdisk long before that, so the budget here
#: is sized to the in-memory retention the spill discipline allows)
TLOG_QUEUE_BYTES_TARGET = 64 << 20
#: resolver batches waiting on the version chain at which resolution is
#: the bottleneck (the wire pipeline caps in-flight batches at the
#: MAX_PIPELINED_COMMIT_BATCHES knob = 8; a full chain means every
#: pipeline slot is parked on the resolver)
RESOLVER_QUEUE_TARGET = 8
#: commit requests queued at one proxy before admission is overdue
PROXY_QUEUE_TARGET = 4096
#: GRV requests queued at the front door before reads are being gated
GRV_QUEUE_TARGET = 4096

#: performance_limited_by reason ids (the reference's limitReason names,
#: Ratekeeper.actor.cpp limitReasonName[]) -> human description
QOS_REASONS = {
    "workload": "The database is not being saturated by the workload.",
    "storage_server_durability_lag": (
        "Storage server durability lag is approaching the MVCC window."
    ),
    "log_server_write_queue": (
        "The write queue at a log server is approaching its budget."
    ),
    "resolver_queue": (
        "Commit batches are queueing on conflict resolution."
    ),
    "resolver_busy": (
        "Conflict-resolution compute is saturating a resolver."
    ),
    "commit_proxy_queue": (
        "Commit requests are queueing at a commit proxy."
    ),
    "grv_proxy_queue": (
        "Read-version requests are queueing at the GRV proxy."
    ),
    # the Ratekeeper's fail-safe direction (one vocabulary: the budget's
    # binding limiter and performance_limited_by share these ids)
    "ratekeeper_failsafe": (
        "The Ratekeeper's sensor feed is stale or no storage replica is "
        "live; admission is clamped toward the fail-safe floor."
    ),
}


def performance_limited_by(
    candidates: list[tuple[str, str, float]],
) -> dict[str, Any]:
    """The status schema's `performance_limited_by` block.

    `candidates` are (process_name, reason_id, score) with score
    normalized against that sensor's budget (1.0 = at budget). The
    worst score past 0.5 names the limiting process; below that the
    cluster is workload-limited (the reference's healthy default)."""
    name, reason, score = "", "workload", 0.0
    for proc, rid, s in candidates:
        if s > score:
            name, reason, score = proc, rid, s
    if score < 0.5:
        name, reason = "", "workload"
    return {
        "name": reason,
        "description": QOS_REASONS[reason],
        "reason_server_id": name,
        "pressure": round(score, 4),
    }


def qos_pressures(
    tlogs: dict[str, dict],
    storages: dict[str, dict],
    resolvers: dict[str, dict],
    proxies: dict[str, dict],
    grvs: dict[str, dict],
    *,
    lag_target: float,
) -> list[tuple[str, str, float]]:
    """Normalized saturation candidates from per-process qos blocks
    (one shared scoring path for sim and wire assembly). Each block is
    the role's `saturation()` dict; missing keys score zero so partial
    wire blocks degrade to 'not limiting', never crash the status."""
    out = []
    for name, q in tlogs.items():
        out.append((
            name, "log_server_write_queue",
            q.get("smoothed_queue_bytes", 0.0) / TLOG_QUEUE_BYTES_TARGET,
        ))
    for name, q in storages.items():
        out.append((
            name, "storage_server_durability_lag",
            q.get("version_lag_versions", q.get("apply_lag_versions", 0))
            / max(lag_target, 1.0),
        ))
    for name, q in resolvers.items():
        out.append((
            name, "resolver_queue",
            q.get("queue_depth", 0) / RESOLVER_QUEUE_TARGET,
        ))
        # busy fraction: the Ratekeeper's actual resolver input. A
        # saturated resolver forms few, huge batches — queue depth stays
        # low while compute occupies ~the whole wall clock, so the
        # queue candidate alone mis-attributes to 'workload'.
        out.append((name, "resolver_busy", q.get("occupancy", 0.0)))
    for name, q in proxies.items():
        out.append((
            name, "commit_proxy_queue",
            q.get("queued_requests", 0) / PROXY_QUEUE_TARGET,
        ))
    for name, q in grvs.items():
        out.append((
            name, "grv_proxy_queue",
            q.get("queued_requests", 0) / GRV_QUEUE_TARGET,
        ))
    return out


def qos_section(
    tlogs: dict[str, dict],
    storages: dict[str, dict],
    resolvers: dict[str, dict],
    proxies: dict[str, dict],
    grvs: dict[str, dict],
    *,
    lag_target: float,
    ratekeeper: dict | None = None,
) -> dict[str, Any]:
    """The reference's status `qos` section from per-process qos blocks:
    worst storage/tlog queue health, worst version lag, the limiting
    process, and (when present) the Ratekeeper's live budget — ONE
    assembly path shared by the sim `cluster_status()` and the wire-mode
    aggregation, so fdbtop renders one schema for both."""

    def _worst(blocks: dict[str, dict], key: str, default=0):
        vals = [q.get(key, default) for q in blocks.values()]
        return max(vals) if vals else default

    cands = qos_pressures(
        tlogs, storages, resolvers, proxies, grvs, lag_target=lag_target
    )
    limited = performance_limited_by(cands)
    out: dict[str, Any] = {
        "worst_queue_bytes_log_server": _worst(tlogs, "queue_bytes"),
        "worst_smoothed_queue_bytes_log_server": _worst(
            tlogs, "smoothed_queue_bytes", 0.0
        ),
        "worst_durability_lag_log_server": _worst(
            tlogs, "durability_lag_versions"
        ),
        "worst_version_lag_storage_server": _worst(
            storages, "version_lag_versions"
        ),
        "worst_queue_depth_resolver": _worst(resolvers, "queue_depth"),
        "worst_occupancy_resolver": _worst(resolvers, "occupancy", 0.0),
        "worst_queued_requests_commit_proxy": _worst(
            proxies, "queued_requests"
        ),
        "worst_queued_requests_grv_proxy": _worst(grvs, "queued_requests"),
        "limiting_process": limited["reason_server_id"],
        "performance_limited_by": limited,
    }
    if ratekeeper is not None:
        out.update(ratekeeper)
    return out


def sampling_rollup(
    storages: dict[str, dict],
    proxies: dict[str, dict],
) -> dict[str, Any]:
    """`cluster.busiest_tags` + `cluster.hot_ranges` from per-role qos
    blocks (ISSUE 20) — ONE rollup path shared by the sim
    `cluster_status()` and the wire `assemble_status`, so the
    skew-attribution gate reads the same document shape on both.

    Tag fractions are re-normalized GLOBALLY: each role's busiest-tag
    row carries its LOCAL frac (share of that role's traffic), which
    can be high on a storage role that merely owns few shards — so the
    rollup reconstructs each role's total rate as `bytes_per_s / frac`
    and divides the tag's summed rate by the summed totals. A uniform
    workload therefore stays flat at cluster level even when individual
    storage roles see locally-dominant tags."""
    tag_rate: dict[str, float] = {}
    denom = 0.0
    rows = [
        (q.get(field) or {})
        for q in list(storages.values()) + list(proxies.values())
        for field in ("busiest_read_tag", "busiest_write_tag")
    ]
    for row in rows:
        rate = float(row.get("bytes_per_s") or 0.0)
        frac = float(row.get("frac") or 0.0)
        denom += rate / frac if frac > 1e-9 else rate
        tag = row.get("tag")
        if tag is not None:
            tag_rate[tag] = tag_rate.get(tag, 0.0) + rate
    busiest_tags = sorted(
        (
            {
                "tag": t,
                "bytes_per_s": round(r, 3),
                "frac": round(r / denom, 4) if denom > 1e-9 else 0.0,
            }
            for t, r in tag_rate.items()
        ),
        key=lambda r: (-r["bytes_per_s"], r["tag"]),
    )[:8]
    # hot ranges: merge the storage samples' rows by range label —
    # bytes sum, bounds widen, frac re-normalized over the merged total
    ranges: dict[str, list] = {}
    for q in storages.values():
        for row in q.get("hot_ranges") or []:
            label = row.get("range", "")
            g = ranges.get(label)
            b = int(row.get("bytes") or 0)
            k = int(row.get("keys") or 0)
            if g is None:
                ranges[label] = [
                    b, row.get("begin", ""), row.get("end", ""), k
                ]
            else:
                g[0] += b
                g[1] = min(g[1], row.get("begin", ""))
                g[2] = max(g[2], row.get("end", ""))
                g[3] += k
    total = sum(g[0] for g in ranges.values())
    hot_ranges = sorted(
        (
            {
                "range": label,
                "begin": g[1],
                "end": g[2],
                "bytes": g[0],
                "keys": g[3],
                "frac": round(g[0] / total, 4) if total > 0 else 0.0,
            }
            for label, g in ranges.items()
        ),
        key=lambda r: (-r["bytes"], r["range"]),
    )[:8]
    return {"busiest_tags": busiest_tags, "hot_ranges": hot_ranges}


#: role kind (the per-process "role" field) -> the qos_section argument
#: slot its block feeds; unknown kinds simply don't contribute pressure
_QOS_SLOT = {
    "log": "tlogs",
    "storage": "storages",
    "resolver": "resolvers",
    "commit_proxy": "proxies",
    "grv_proxy": "grvs",
}


def assemble_status(
    processes: dict[str, dict],
    *,
    lag_target: float = 2_000_000.0,
    ratekeeper: dict | None = None,
    cluster_extra: dict | None = None,
) -> dict[str, Any]:
    """Assemble a reference-shaped status document from per-process
    blocks — the wire-mode path (cluster/multiprocess.py
    `wire_cluster_status` and scripts/fdbtop.py): each block is one
    role's StatusReply payload `{"role": kind, "qos": {...}, ...}`.
    Blocks with unknown roles or missing qos keys degrade to
    'not limiting' — a half-started cluster still renders."""
    slots: dict[str, dict[str, dict]] = {
        "tlogs": {}, "storages": {}, "resolvers": {},
        "proxies": {}, "grvs": {},
    }
    for name, block in processes.items():
        slot = _QOS_SLOT.get(block.get("role", ""))
        if slot is not None:
            # the live dict, so the join below lands in the document
            slots[slot][name] = block.setdefault("qos", {})
        elif block.get("role") == "ratekeeper" and ratekeeper is None:
            # a wire RatekeeperRole's status block IS the qos
            # ratekeeper payload (budget, binding limiter, fail-safe
            # state) — merge it like the sim path merges rk.status()
            ratekeeper = block.get("qos", {})
    # version-lag join: a storage process doesn't know the committed
    # head — derive it from the proxy/log blocks (the reference's
    # Status.actor.cpp joins the same way) and fill
    # version_lag_versions into any storage block missing it
    head = 0
    for block in processes.values():
        if block.get("role") == "commit_proxy":
            head = max(head, block.get("committed_version", 0))
        elif block.get("role") == "log":
            head = max(head, block.get("version", 0))
    for name, q in slots["storages"].items():
        if "version_lag_versions" not in q:
            v = processes[name].get("version")
            if v is not None:
                q["version_lag_versions"] = max(0, head - v)
    data: dict[str, Any] = {
        "cluster": {
            "qos": qos_section(
                slots["tlogs"], slots["storages"], slots["resolvers"],
                slots["proxies"], slots["grvs"],
                lag_target=lag_target, ratekeeper=ratekeeper,
            ),
            "processes": processes,
            # keyspace-skew rollup (ISSUE 20): the skew-attribution
            # gate's input, shared math with the sim path
            **sampling_rollup(slots["storages"], slots["proxies"]),
        }
    }
    if cluster_extra:
        data["cluster"].update(cluster_extra)
    return data


def _merge_bands(bands_list) -> dict[str, int]:
    """Sum LatencyBands dicts across role instances (identical edges by
    construction — the thresholds are module constants)."""
    out: dict[str, int] = {}
    for b in bands_list:
        for k, v in b.as_dict().items():
            out[k] = out.get(k, 0) + v
    return out


def _compile_cache_section() -> dict[str, Any]:
    from foundationdb_tpu.utils import compile_cache

    return compile_cache.stats()


def _census_snapshot(sched=None) -> dict[str, int]:
    from foundationdb_tpu.runtime import census

    return census.snapshot(sched)


def _kernel_section(resolver) -> dict[str, Any]:
    cs = resolver.conflict_set
    metrics = getattr(cs, "metrics", None)
    if metrics is None:
        return {"backend": "unrouted"}
    return {
        "backend": type(cs).__name__,
        **metrics.as_dict(),
    }


def cluster_status(cluster) -> dict[str, Any]:
    seq = cluster.sequencer
    cfg = cluster.config
    rk = cluster.ratekeeper
    # per-role saturation blocks (each role's `saturation()` sensors);
    # the storage blocks gain the CLUSTER-level version lag here — the
    # distance behind the sequencer head is derivable only where the
    # head is known (Status.actor.cpp does the same join)
    tlog_qos = {
        f"tlog{i}": cluster.tlog.tlogs[i].saturation()
        for i in range(cfg.n_tlogs)
    }
    storage_qos = {
        f"storage{i}": {
            **ss.saturation(),
            "version_lag_versions": max(0, seq.version - ss.version.get()),
        }
        for i, ss in enumerate(cluster.storage_servers)
    }
    resolver_qos = {
        f"resolver{i}": r.saturation()
        for i, r in enumerate(cluster.resolvers)
    }
    proxy_qos = {
        f"proxy{i}": p.saturation()
        for i, p in enumerate(cluster.commit_proxies)
    }
    grv_qos = {"grv_proxy0": cluster.grv_proxy.saturation()}
    data = {
        "cluster": {
            "configuration": {
                "commit_proxies": len(cluster.commit_proxies),
                "grv_proxies": cfg.n_grv_proxies,
                "resolvers": len(cluster.resolvers),
                "storage_servers": len(cluster.storage_servers),
                "logs": cfg.n_tlogs,
                "coordinators": cfg.n_coordinators,
                "resolver_backend": cfg.resolver_backend or "tpu",
            },
            "datacenter_lag": {"versions": 0},
            "latest_version": seq.version,
            "live_committed_version": seq.live_committed.get(),
            # the reference's qos section (Schemas.cpp `qos`): worst
            # queue/lag across role instances, the limiting process,
            # and the Ratekeeper's live budget + quota tiers
            "qos": qos_section(
                tlog_qos, storage_qos, resolver_qos, proxy_qos, grv_qos,
                lag_target=rk.lag_target, ratekeeper=rk.status(),
            ),
            # run-loop utilization + slow-task ledger (WALL-clock by
            # design: it measures how busy this OS process's loop is;
            # status readers surface it, traced output never does)
            "run_loop": cluster.sched.run_loop_stats(),
            # live resource census (runtime/census.py): fds straight
            # off /proc, transport gauges, the Scheduler's live-task
            # count — the leak gate's gauges, surfaced for operators.
            # Status-only, like run_loop: never lands in traces.
            "census": _census_snapshot(sched=cluster.sched),
            "workload": {
                "transactions": {
                    "committed": sum(
                        p.counters.get("txnCommitOut")
                        for p in cluster.commit_proxies
                    ),
                    "conflicted": sum(
                        p.counters.get("txnConflicts")
                        for p in cluster.commit_proxies
                    ),
                    "started": sum(
                        p.counters.get("txnCommitIn")
                        for p in cluster.commit_proxies
                    ),
                },
                "grv": cluster.grv_proxy.counters.as_dict(),
            },
            # reference-style latency bands (fdbrpc/Stats.h LatencyBands
            # -> the status schema's latency_statistics buckets), rolled
            # up across role instances
            "latency_bands": {
                "commit": _merge_bands(
                    p.latency_bands for p in cluster.commit_proxies
                ),
                "grv": _merge_bands([cluster.grv_proxy.latency_bands]),
                "read": _merge_bands(
                    ss.read_latency_bands for ss in cluster.storage_servers
                ),
            },
            # the TPU resolver's always-on kernel stage metrics
            # (pack/transfer/kernel/fence, tier occupancy, compactions,
            # latch/fallback counts, overflow events)
            "resolver_kernel": {
                f"resolver{r.resolver_id}": _kernel_section(r)
                for r in cluster.resolvers
            },
            # process-global compile observability (ISSUE 10): the
            # persistent-cache hit/miss counters, backend-compile
            # seconds, and per-signature compile times — the "why did
            # that batch stall" panel for cold-jit pathologies
            "compile_cache": _compile_cache_section(),
            # keyspace-skew rollup (ISSUE 20): busiest_tags (globally
            # re-normalized tag fractions) + hot_ranges (merged storage
            # byte-sample density) — same math as the wire assembly
            **sampling_rollup(storage_qos, proxy_qos),
            "processes": {},
        }
    }
    procs = data["cluster"]["processes"]
    for i, r in enumerate(cluster.resolvers):
        procs[f"resolver{i}"] = {
            "role": "resolver",
            "version": r.version.get(),
            "counters": r.counters.as_dict(),
            "latency": {
                "resolver": r.resolver_latency.as_dict(),
                "queue_wait": r.queue_wait_latency.as_dict(),
                "compute": r.compute_time.as_dict(),
            },
            "kernel": _kernel_section(r),
            "total_state_bytes": r.total_state_bytes,
            "qos": resolver_qos[f"resolver{i}"],
        }
    for i, p in enumerate(cluster.commit_proxies):
        procs[f"proxy{i}"] = {
            "role": "commit_proxy",
            "committed_version": p.committed_version.get(),
            "counters": p.counters.as_dict(),
            "latency": {"commit": p.commit_latency.as_dict()},
            "latency_bands": p.latency_bands.as_dict(),
            "failed": p.failed is not None,
            "qos": proxy_qos[f"proxy{i}"],
        }
    procs["grv_proxy0"] = {
        "role": "grv_proxy",
        "counters": cluster.grv_proxy.counters.as_dict(),
        "latency": {"grv": cluster.grv_proxy.grv_latency.as_dict()},
        "latency_bands": cluster.grv_proxy.latency_bands.as_dict(),
        "qos": grv_qos["grv_proxy0"],
    }
    for i, ss in enumerate(cluster.storage_servers):
        procs[f"storage{i}"] = {
            "role": "storage",
            "version": ss.version.get(),
            "durable_version": ss.durable_version,
            "keys": len(ss._keys),
            "latency": {"read": ss.read_latency.as_dict()},
            "latency_bands": ss.read_latency_bands.as_dict(),
            "live": cluster.storage_live[i],
            "qos": storage_qos[f"storage{i}"],
        }
    for i in range(cfg.n_tlogs):
        procs[f"tlog{i}"] = {
            "role": "log",
            "version": cluster.tlog.tlogs[i].version.get(),
            "live": bool(cluster.tlog.live[i]),
            "qos": tlog_qos[f"tlog{i}"],
        }
    procs["sequencer"] = {
        "role": "master",
        "version": seq.version,
        # the sequencer's saturation surface: how far live-committed
        # visibility trails allocation (a growing gap means committed
        # batches aren't reporting back — the recovery-fence symptom)
        "qos": {
            "version": seq.version,
            "live_committed_version": seq.live_committed.get(),
            "allocation_gap_versions": max(
                0, seq.version - seq.live_committed.get()
            ),
        },
    }
    return data
