"""Cluster status: the machine-readable health/metrics document.

Behavioral mirror of `fdbserver/Status.actor.cpp` (schema shape from
fdbclient/Schemas.cpp): one JSON-able dict aggregating every role's
counters, versions, latencies, and configuration — what `fdbcli status`
and monitoring consume.
"""

from __future__ import annotations

from typing import Any


def cluster_status(cluster) -> dict[str, Any]:
    seq = cluster.sequencer
    data = {
        "cluster": {
            "configuration": {
                "commit_proxies": len(cluster.commit_proxies),
                "grv_proxies": 1,
                "resolvers": len(cluster.resolvers),
                "storage_servers": len(cluster.storage_servers),
                "resolver_backend": "tpu",
            },
            "datacenter_lag": {"versions": 0},
            "latest_version": seq.version,
            "live_committed_version": seq.live_committed.get(),
            "qos": {
                "transactions_per_second_limit": cluster.ratekeeper.tps_budget,
                "worst_storage_lag_versions": cluster.ratekeeper.worst_lag(),
            },
            "workload": {
                "transactions": {
                    "committed": sum(
                        p.counters.get("txnCommitOut")
                        for p in cluster.commit_proxies
                    ),
                    "conflicted": sum(
                        p.counters.get("txnConflicts")
                        for p in cluster.commit_proxies
                    ),
                    "started": sum(
                        p.counters.get("txnCommitIn")
                        for p in cluster.commit_proxies
                    ),
                },
                "grv": cluster.grv_proxy.counters.as_dict(),
            },
            "processes": {},
        }
    }
    procs = data["cluster"]["processes"]
    for i, r in enumerate(cluster.resolvers):
        procs[f"resolver{i}"] = {
            "role": "resolver",
            "version": r.version.get(),
            "counters": r.counters.as_dict(),
            "latency": {
                "resolver": r.resolver_latency.as_dict(),
                "queue_wait": r.queue_wait_latency.as_dict(),
                "compute": r.compute_time.as_dict(),
            },
            "total_state_bytes": r.total_state_bytes,
        }
    for i, p in enumerate(cluster.commit_proxies):
        procs[f"proxy{i}"] = {
            "role": "commit_proxy",
            "committed_version": p.committed_version.get(),
            "counters": p.counters.as_dict(),
            "failed": p.failed is not None,
        }
    for i, ss in enumerate(cluster.storage_servers):
        procs[f"storage{i}"] = {
            "role": "storage",
            "version": ss.version.get(),
            "durable_version": ss.durable_version,
            "keys": len(ss._keys),
        }
    procs["tlog0"] = {"role": "log", "version": cluster.tlog.version.get()}
    procs["sequencer"] = {"role": "master", "version": seq.version}
    return data
