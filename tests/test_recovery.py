"""Cluster recovery tests: transaction-system failure -> new generation.

Mirrors the reference's recovery contract (ClusterRecovery.actor.cpp,
SURVEY.md §5.3): stateless roles (proxies, resolvers, sequencer) are
rebuilt as a unit, resolvers restart with empty conflict state, durable
state (tlog, storage) survives, in-flight pre-recovery snapshots are
conservatively aborted, and clients ride through via the retry loop.
"""

import pytest

from foundationdb_tpu.cluster.commit_proxy import (
    CommitUnknownResult,
    NotCommitted,
)
from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster


def run(sched, coro):
    return sched.run_until(sched.spawn(coro).done)


@pytest.fixture
def world():
    sched, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=2, n_resolvers=2, n_storage=2)
    )
    yield sched, cluster, db
    cluster.stop()


def break_proxy(cluster):
    """Simulate a proxy process death mid-operation."""
    p = cluster.commit_proxies[0]
    p.failed = RuntimeError("simulated proxy crash")
    p.stop()


def test_recovery_preserves_data_and_resumes(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        for i in range(5):
            txn.set(b"pre%d" % i, b"v%d" % i)
        await txn.commit()

        break_proxy(cluster)
        await sched.delay(1.0)  # controller notices + recovers
        assert cluster.controller.epoch == 2

        # new generation accepts commits; old data survived
        async def w(txn):
            txn.set(b"post", b"1")

        await db.run(w)
        txn = db.create_transaction()
        pre = await txn.get_range(b"pre", b"prf")
        post = await txn.get(b"post")
        return pre, post

    pre, post = run(sched, body())
    assert len(pre) == 5
    assert post == b"1"


def test_recovery_aborts_stale_snapshots(world):
    sched, cluster, db = world

    async def body():
        init = db.create_transaction()
        init.set(b"stale", b"0")
        await init.commit()

        # txn reads before recovery, commits after -> must abort
        t1 = db.create_transaction()
        await t1.get(b"stale")
        t1.set(b"other", b"x")

        break_proxy(cluster)
        await sched.delay(1.0)
        assert cluster.controller.epoch == 2

        try:
            await t1.commit()
            return "committed"
        except (NotCommitted, CommitUnknownResult):
            return "aborted"

    assert run(sched, body()) == "aborted"


def test_resolvers_rebuilt_empty(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        txn.set(b"k", b"v")
        await txn.commit()
        old_resolvers = list(cluster.resolvers)

        break_proxy(cluster)
        await sched.delay(1.0)

        assert all(r not in old_resolvers for r in cluster.resolvers)
        # fresh conflict state: post-recovery snapshots read/commit fine
        async def w(txn):
            assert await txn.get(b"k") == b"v"
            txn.set(b"k", b"v2")

        await db.run(w)
        txn = db.create_transaction()
        return await txn.get(b"k")

    assert run(sched, body()) == b"v2"


def test_repeated_recoveries(world):
    sched, cluster, db = world

    async def body():
        for round_ in range(3):
            async def w(txn, round_=round_):
                txn.set(b"r%d" % round_, b"x")

            await db.run(w)
            break_proxy(cluster)
            await sched.delay(1.0)
        txn = db.create_transaction()
        return await txn.get_range(b"r", b"s")

    items = run(sched, body())
    assert len(items) == 3
    assert cluster.controller.epoch == 4
