"""Encryption-at-rest: cipher cache, auth, rotation, KMS connectors.

Mirrors the reference's BlobCipher unit suite
(fdbclient/BlobCipher.cpp TESTCASE "/blobCipher/...": roundtrip,
header auth-token mismatch on tamper, key-cache identity) plus the
EncryptKeyProxy/KMS split (fdbserver/EncryptKeyProxy.actor.cpp,
SimKmsConnector / RESTKmsConnector).
"""

import pytest

pytest.importorskip("cryptography")

from foundationdb_tpu.cluster.encrypt_key_proxy import EncryptKeyProxy
from foundationdb_tpu.cluster.kms import (
    KmsError,
    RestKmsConnector,
    SimKmsConnector,
    serve_stub_kms,
)
from foundationdb_tpu.crypto import (
    AuthTokenError,
    BlobCipherKeyCache,
    decrypt,
    encrypt,
)
from foundationdb_tpu.crypto.blob_cipher import (
    SYSTEM_DOMAIN_ID,
    CipherKeyNotFoundError,
    is_encrypted,
)


def make_proxy(**kw):
    return EncryptKeyProxy(SimKmsConnector(), refresh_interval=600, **kw)


def seal(proxy, payload, key):
    """Encrypt under the hardened discipline: the header-auth cipher
    is always the SYSTEM domain's (BlobCipher.cpp:256 — decrypt refuses
    any other auth identity, see test_forged_header_* below)."""
    return encrypt(payload, key, proxy.get_latest_cipher(SYSTEM_DOMAIN_ID))


def test_roundtrip_and_header_identity():
    proxy = make_proxy()
    key = proxy.get_latest_cipher(7)
    blob = seal(proxy, b"hello at rest", key)
    assert is_encrypted(blob)
    assert b"hello at rest" not in blob
    assert decrypt(blob, proxy.cache) == b"hello at rest"


def test_tamper_raises_auth_token_error():
    proxy = make_proxy()
    key = proxy.get_latest_cipher(1)
    blob = bytearray(seal(proxy, b"payload" * 100, key))
    blob[-1] ^= 0x40  # flip a ciphertext bit
    with pytest.raises(AuthTokenError):
        decrypt(bytes(blob), proxy.cache)
    # header tamper (different domain id) also refuses
    blob2 = bytearray(seal(proxy, b"x", key))
    blob2[6] ^= 0x01
    with pytest.raises((AuthTokenError, CipherKeyNotFoundError)):
        decrypt(bytes(blob2), proxy.cache)


def test_wrong_key_refuses():
    proxy_a, proxy_b = make_proxy(), EncryptKeyProxy(
        SimKmsConnector(b"other-kms"), refresh_interval=600
    )
    key_a = proxy_a.get_latest_cipher(1)
    proxy_b.get_latest_cipher(1)
    blob = seal(proxy_a, b"secret", key_a)
    # proxy_b's cache has domain 1 but a DIFFERENT derived key identity
    # (different salt) -> not found; forcing its key as auth -> mismatch
    with pytest.raises((AuthTokenError, CipherKeyNotFoundError)):
        decrypt(blob, proxy_b.cache)


def test_rotation_old_records_still_decrypt():
    kms = SimKmsConnector()
    proxy = EncryptKeyProxy(kms, refresh_interval=0)  # refresh every call
    k1 = proxy.get_latest_cipher(3)
    old = seal(proxy, b"written under base 1", k1)
    kms.rotate(3)
    k2 = proxy.get_latest_cipher(3)
    assert k2.base_id == k1.base_id + 1
    new = seal(proxy, b"written under base 2", k2)
    # both generations decrypt from the same cache
    assert decrypt(old, proxy.cache) == b"written under base 1"
    assert decrypt(new, proxy.cache) == b"written under base 2"


def test_by_id_fetch_after_cache_loss():
    """A restarted process holds records naming (baseId, salt) pairs its
    fresh cache has never seen — the by-id KMS path must rebuild them."""
    kms = SimKmsConnector()
    proxy = EncryptKeyProxy(kms, refresh_interval=600)
    key = proxy.get_latest_cipher(5)
    blob = seal(proxy, b"survives restart", key)

    fresh = EncryptKeyProxy(kms, refresh_interval=600)
    from foundationdb_tpu.crypto.blob_cipher import EncryptHeader

    hdr = EncryptHeader.unpack(blob)
    fresh.get_cipher_by_id(hdr.domain_id, hdr.base_id, hdr.salt)
    fresh.get_cipher_by_id(
        hdr.header_domain_id, hdr.header_base_id, hdr.header_salt
    )
    assert decrypt(blob, fresh.cache) == b"survives restart"


def test_revoked_base_key():
    kms = SimKmsConnector()
    proxy = EncryptKeyProxy(kms, refresh_interval=600)
    key = proxy.get_latest_cipher(9)
    kms.revoke(9, key.base_id)
    fresh = EncryptKeyProxy(kms, refresh_interval=600)
    with pytest.raises(KmsError):
        fresh.get_cipher_by_id(9, key.base_id, key.salt)


def test_proxy_caches_kms_round_trips():
    proxy = make_proxy()
    for _ in range(10):
        proxy.get_latest_cipher(1)
        proxy.get_latest_cipher(2)
    assert proxy.fetches == 2  # one per domain


def test_rest_kms_stub_server():
    srv, port = serve_stub_kms()
    try:
        rest = RestKmsConnector(f"127.0.0.1:{port}")
        proxy = EncryptKeyProxy(rest, refresh_interval=600)
        key = proxy.get_latest_cipher(11)
        blob = seal(proxy, b"over REST", key)
        assert decrypt(blob, proxy.cache) == b"over REST"
        # rotation via REST; by-id fetch of the old generation still works
        rest.rotate(11)
        proxy2 = EncryptKeyProxy(rest, refresh_interval=600)
        k2 = proxy2.get_latest_cipher(11)
        assert k2.base_id == key.base_id + 1
        proxy2.get_cipher_by_id(key.domain_id, key.base_id, key.salt)
        from foundationdb_tpu.crypto.blob_cipher import EncryptHeader as _EH

        hdr = _EH.unpack(blob)
        proxy2.get_cipher_by_id(
            hdr.header_domain_id, hdr.header_base_id, hdr.header_salt
        )
        assert decrypt(blob, proxy2.cache) == b"over REST"
    finally:
        srv.shutdown()


def test_empty_and_large_payloads():
    proxy = make_proxy()
    key = proxy.get_latest_cipher(0)
    for payload in (b"", b"\x00" * 1024, bytes(range(256)) * 4096):
        assert decrypt(seal(proxy, payload, key), proxy.cache) == payload


def test_rotation_survives_fresh_kms_connector():
    """A restarted process builds a FRESH SimKmsConnector; records sealed
    under a rotated (higher) base id must still be recoverable — the
    secrets are deterministic, so by-id serving must not be capped by
    the fresh process's counter (code review r5)."""
    kms = SimKmsConnector()
    kms.rotate(4)  # base id 2
    proxy = EncryptKeyProxy(kms, refresh_interval=600)
    key = proxy.get_latest_cipher(4)
    assert key.base_id == 2
    blob = seal(proxy, b"post-rotation", key)

    fresh = EncryptKeyProxy(SimKmsConnector(), refresh_interval=600)
    fresh.get_cipher_by_id(key.domain_id, key.base_id, key.salt)
    from foundationdb_tpu.crypto.blob_cipher import EncryptHeader as _EH

    hdr = _EH.unpack(blob)
    fresh.get_cipher_by_id(
        hdr.header_domain_id, hdr.header_base_id, hdr.header_salt
    )
    assert decrypt(blob, fresh.cache) == b"post-rotation"
    # by-id serving must NOT mutate the rotation counter (unverified
    # on-disk ids steering KMS state — second review pass): the fresh
    # connector still encrypts new data under ITS latest generation,
    # and old records stay decryptable by id
    bid, _ = fresh.kms.fetch_base_key(4)
    assert bid == 1


def test_nonblocking_seal_uses_stale_key_and_refreshes():
    """The seal path never blocks on the KMS: past the refresh deadline
    it seals under the stale key while a background refresh runs."""
    import time as _time

    class SlowKms(SimKmsConnector):
        def __init__(self):
            super().__init__()
            self.calls = 0

        def fetch_base_key(self, domain_id):
            self.calls += 1
            if self.calls > 1:
                _time.sleep(0.2)  # a slow KMS after the first fetch
            return super().fetch_base_key(domain_id)

    kms = SlowKms()
    proxy = EncryptKeyProxy(kms, refresh_interval=0.01)
    k1 = proxy.get_latest_cipher(1)
    _time.sleep(0.02)  # k1 is now past refresh
    t0 = _time.perf_counter()
    k2 = proxy.get_latest_cipher_nonblocking(1)
    took = _time.perf_counter() - t0
    assert took < 0.1, f"seal path blocked on the KMS ({took:.3f}s)"
    assert k2.salt == k1.salt  # the stale key, served immediately
    # the background refresh eventually lands a fresh key
    deadline = _time.time() + 2
    while _time.time() < deadline:
        cur = proxy.cache.latest_any(1)
        if cur.salt != k1.salt:
            break
        _time.sleep(0.02)
    assert proxy.cache.latest_any(1).salt != k1.salt


def test_expired_latest_forces_fresh_derivation():
    """expire_interval < refresh_interval: once the latest key expires,
    the NEXT seal must derive a fresh key — sealing under the expired
    key would produce durably unreadable records (code review r5)."""
    import time as _time

    proxy = EncryptKeyProxy(
        SimKmsConnector(), refresh_interval=600, expire_interval=0.05
    )
    k1 = proxy.get_latest_cipher(1)
    _time.sleep(0.06)
    k2 = proxy.get_latest_cipher(1)       # blocking path
    assert k2.salt != k1.salt             # re-derived, not the expired key
    k3 = proxy.get_latest_cipher_nonblocking(1)
    assert k3.salt != k1.salt
    blob = seal(proxy, b"readable", k3)
    assert decrypt(blob, proxy.cache) == b"readable"


def test_forged_header_auth_domain_rejected():
    """The auth-key confusion regression (BlobCipher.cpp:256): the
    header is unauthenticated until the token verifies, so a forger
    holding any NON-system domain key must not get to name it as the
    header-auth cipher — the forged record would otherwise verify
    against the forger's own key."""
    proxy = make_proxy()
    attacker_key = proxy.get_latest_cipher(7)
    forged = encrypt(b"evil payload", attacker_key, attacker_key)
    with pytest.raises(AuthTokenError, match="auth domain"):
        decrypt(forged, proxy.cache)
    # an explicitly supplied auth key bypasses the cache lookup and
    # stays the caller's responsibility — unchanged contract
    assert decrypt(forged, proxy.cache, attacker_key) == b"evil payload"


def test_cross_domain_record_rejected_by_expected_domain():
    """A validly sealed record RELOCATED across domains must refuse to
    open for a store configured with a different domain."""
    proxy = make_proxy()
    key7 = proxy.get_latest_cipher(7)
    blob = seal(proxy, b"domain 7 data", key7)
    ok = decrypt(blob, proxy.cache, expected_domain_id=7)
    assert ok == b"domain 7 data"
    with pytest.raises(AuthTokenError, match="text domain"):
        decrypt(blob, proxy.cache, expected_domain_id=8)


def test_storage_encryption_refuses_foreign_records():
    """StorageEncryption.open validates the header's cipher details
    BEFORE any KMS fetch: a forged auth identity and a cross-domain
    text identity are both refused."""
    from foundationdb_tpu.crypto.at_rest import StorageEncryption

    proxy = make_proxy()
    enc = StorageEncryption(proxy, domain_id=1)
    sealed = enc.seal(b"mine")
    assert enc.open(sealed) == b"mine"
    # forged auth identity (attacker-controlled header cipher details)
    attacker_key = proxy.get_latest_cipher(1)
    forged = encrypt(b"evil", attacker_key, attacker_key)
    with pytest.raises(AuthTokenError, match="auth domain"):
        enc.open(forged)
    # cross-domain relocation: sealed for domain 2, opened by domain 1
    other = StorageEncryption(proxy, domain_id=2)
    with pytest.raises(AuthTokenError, match="text domain"):
        enc.open(other.seal(b"not yours"))
