"""The saturation-spec SLO gate, both directions (ISSUE-8 acceptance):
with admission control ON, offered load ramped to 3x capacity keeps
commit p99 in band and goodput >= min_goodput_frac of peak; with the
ratekeeper disconnected the SAME ramp must violate the gate. Plus the
wire-mode admission plumbing: the ratekeeper role process serves
GetRateInfo off polled StatusRequest sensors, the ProxyPipeline
enforces it at its GRV front door, and a dead ratekeeper process
decays fail-safe."""

from __future__ import annotations

import asyncio
import json

from foundationdb_tpu.testing.saturation import (
    load_saturation_config,
    run_saturation,
)


def test_saturation_config_loads_from_spec():
    cfg = load_saturation_config()
    assert cfg["compute_cost_per_txn"] > 0
    assert cfg["min_goodput_frac"] >= 0.7  # the graded SLO floor
    assert max(cfg["ramp"]) >= 3.0         # ramp reaches 3x capacity
    assert max(cfg["quick_ramp"]) >= 3.0


def test_saturation_gate_passes_with_admission_control():
    rep = run_saturation(admission=True, quick=True)
    assert rep["slo"]["passed"], rep["slo"]["violations"]
    over = [s for s in rep["steps"]
            if s["multiplier"] >= rep["config"]["overload_from"]]
    assert over, "quick ramp has no overload step"
    for s in over:
        # overload was real (offered genuinely exceeded capacity) and
        # the front door genuinely shed
        assert s["offered"] > rep["capacity_tps"] * 1.2
        assert s["shed"] > 0
        # degradation was graceful: goodput held
        assert s["goodput_tps"] >= (
            rep["config"]["min_goodput_frac"] * rep["peak_goodput_tps"]
        )
    # the ratekeeper attributed the clamp with the shared vocabulary
    rk = rep["ratekeeper"]
    assert rk["transactions_per_second_limit"] < rk["max_tps"] * 1.0 or (
        rk["throttled_intervals"] > 0
    )
    json.dumps(rep)  # report is a JSON document end to end


def test_saturation_gate_violated_without_admission_control():
    """The inverse direction: the gate must have TEETH — the identical
    ramp with the ratekeeper disconnected collapses (p99 out of band
    and/or goodput below the floor) and the gate reports it."""
    rep = run_saturation(admission=False, quick=True)
    assert not rep["slo"]["passed"], (
        "unthrottled overload passed the gate: the ramp is not "
        "saturating and the SLO is vacuous"
    )
    assert rep["slo"]["violations"]
    # the collapse is the MVCC-window kind the ratekeeper exists to
    # prevent: p99 blows past the band on the overload step
    over = [s for s in rep["steps"]
            if s["multiplier"] >= rep["config"]["overload_from"]]
    assert any(
        s["commit_p99_s"] > rep["config"]["commit_p99_band_s"]
        for s in over
    )
    # nothing was shed — every request was admitted into the collapse
    assert all(s["shed"] == 0 for s in rep["steps"])


def test_saturation_run_is_deterministic():
    a = run_saturation(admission=True, quick=True, seed=7)
    b = run_saturation(admission=True, quick=True, seed=7)
    assert a == b


# ---------------------------------------------------------------------------
# Wire mode: the ratekeeper role process + ProxyPipeline enforcement.


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_wire_ratekeeper_role_budget_and_failsafe(tmp_path):
    """End to end over real OS processes: the ratekeeper role polls
    StatusRequest sensors and serves GetRateInfo; the pipeline's GRV
    front door fetches it, enforces the token bucket + bounded-queue
    shed, and decays fail-safe when the ratekeeper process dies."""
    from foundationdb_tpu.cluster import multiprocess as mp

    import os

    procs = [
        mp.spawn_role("resolver", str(tmp_path)),
        mp.spawn_role("tlog", str(tmp_path)),
        mp.spawn_role("storage", str(tmp_path)),
    ]
    rk_proc = mp.spawn_role(
        "ratekeeper", str(tmp_path),
        # includes the parent's status socket: the embedded GRV block's
        # served rate is the law's actualTps feedback
        peers=[p.address for p in procs]
        + [os.path.join(str(tmp_path), "proxy0.sock")],
    )
    procs.append(rk_proc)

    async def scenario():
        resolver = await mp.connect(procs[0].address)
        tlog = await mp.connect(procs[1].address)
        storage = await mp.connect(procs[2].address)
        rk = await mp.connect(rk_proc.address)
        # 1) the role answers GetRateInfo with the law's payload
        rep = await rk.call(
            mp.TOKEN_GET_RATE_INFO, mp.GetRateInfoRequest(pad=0)
        )
        info = json.loads(rep.payload)
        assert "transactions_per_second_limit" in info
        assert info["budget_limited_by"]["name"] in (
            "workload", "ratekeeper_failsafe",
        )
        # ... and StatusRequest, as a ratekeeper-role process block
        srep = await rk.call(mp.TOKEN_STATUS, mp.StatusRequest(pad=0))
        block = json.loads(srep.payload)
        assert block["role"] == "ratekeeper"
        assert "transactions_per_second_limit" in block["qos"]
        # 2) the pipeline fetches the budget and commits normally
        pipe = mp.ProxyPipeline(
            [resolver], tlog, storage, batch_interval=0.001,
            ratekeeper=rk, rate_fetch_interval=0.05,
        )
        pipe.start()
        server = mp.serve_status(str(tmp_path), pipe)
        await server.start()
        from foundationdb_tpu.models.types import CommitTransaction
        from foundationdb_tpu.wire.codec import Mutation

        for i in range(5):
            k = b"rk%02d" % i
            rv = await pipe.get_read_version()
            await pipe.commit(CommitTransaction(
                read_conflict_ranges=[(k, k + b"\x00")],
                write_conflict_ranges=[(k, k + b"\x00")],
                read_snapshot=rv,
                mutations=[Mutation(0, k, b"v")],
            ))
        await asyncio.sleep(0.3)  # a few fetch cycles
        assert pipe._rate_info, "pipeline never fetched a budget"
        assert not pipe._rate_stale
        # the actualTps feedback path: the role polled the parent's
        # status socket and extracted the served-GRV rate from the
        # embedded grv block (regression: reading it at the wrong
        # nesting level left the law's actualTps pinned at 0)
        observed = 0.0
        for _ in range(60):
            srep2 = await rk.call(mp.TOKEN_STATUS, mp.StatusRequest(pad=0))
            observed = json.loads(srep2.payload)["qos"].get(
                "observed_grv_per_s", 0.0
            )
            if observed > 0.0:
                break
            rv = await pipe.get_read_version()  # keep the rate warm
            await asyncio.sleep(0.1)
        assert observed > 0.0, (
            "ratekeeper role never observed the pipeline's GRV rate"
        )
        # 3) enforcement: a clamped budget + tiny queue sheds with the
        # retryable error (locally forced — the wire contract is the
        # enforcement mechanics, the law itself is unit-tested)
        pipe._rate_limit = 20.0
        pipe.max_grv_queue = 2
        sheds = 0
        grvs = [
            asyncio.ensure_future(pipe.get_read_version())
            for _ in range(30)
        ]
        for g in grvs:
            try:
                await g
            except mp.GrvThrottledError:
                sheds += 1
        assert sheds > 0 and pipe.grv_sheds == sheds
        assert pipe.grv_saturation()["sheds"] == sheds
        # 4) fail-safe: kill the ratekeeper PROCESS — after fetch
        # failures the budget decays toward the floor, never unthrottles
        pipe._rate_limit = 1e6
        pipe.max_grv_queue = 8192
        rk_proc.stop()
        for _ in range(100):
            await asyncio.sleep(0.1)
            if pipe._rate_stale and pipe._rate_limit <= pipe._rate_floor:
                break
        assert pipe._rate_stale, "dead ratekeeper never detected"
        assert pipe._rate_limit <= pipe._rate_floor
        await pipe.stop()
        await server.close()
        for c in (resolver, tlog, storage, rk):
            await c.close()

    try:
        _run(scenario())
    finally:
        for p in procs:
            p.stop()
