"""Backup/restore, versionstamps, and CLI tests."""

import pytest

from foundationdb_tpu.cli import CliSession
from foundationdb_tpu.cluster.backup import BackupAgent, BackupContainer
from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster


def run(sched, coro):
    return sched.run_until(sched.spawn(coro).done)


@pytest.fixture
def world():
    sched, cluster, db = open_cluster(ClusterConfig(n_storage=2))
    yield sched, cluster, db
    cluster.stop()


def test_snapshot_restore_roundtrip(world):
    sched, cluster, db = world
    agent = BackupAgent(db, BackupContainer())

    async def body():
        txn = db.create_transaction()
        for i in range(20):
            txn.set(b"bk%02d" % i, b"v%d" % i)
        await txn.commit()

        v = await agent.snapshot()

        # post-snapshot damage: must be undone by restore
        txn = db.create_transaction()
        txn.clear_range(b"bk00", b"bk99")
        txn.set(b"junk", b"x")
        await txn.commit()

        await agent.restore()
        txn = db.create_transaction()
        items = await txn.get_range(b"", b"\xff")
        return v, items

    v, items = run(sched, body())
    assert v > 0
    assert [k for k, _ in items] == [b"bk%02d" % i for i in range(20)]


def test_log_backup_point_in_time(world):
    sched, cluster, db = world
    agent = BackupAgent(db, BackupContainer())

    async def body():
        txn = db.create_transaction()
        txn.set(b"pit", b"one")
        await txn.commit()

        await agent.snapshot()
        agent.start_log_backup(cluster)

        txn = db.create_transaction()
        txn.set(b"pit", b"two")
        txn.add(b"pitctr", 7)
        await txn.commit()
        mid_version = txn.committed_version

        await sched.delay(0.1)  # let the backup worker drain the log

        txn = db.create_transaction()
        txn.set(b"pit", b"three")
        await txn.commit()
        await sched.delay(0.1)
        agent.stop_log_backup()

        # restore to the mid point: "two" visible, "three" not
        await agent.restore(target_version=mid_version)
        txn = db.create_transaction()
        return await txn.get(b"pit"), await txn.get(b"pitctr")

    pit, ctr = run(sched, body())
    assert pit == b"two"
    assert ctr == (7).to_bytes(8, "little")


def test_versionstamped_key_and_value(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        txn.set_versionstamped_key(b"log/", b"/end", b"payload")
        txn.set_versionstamped_value(b"last", b"at=")
        v = await txn.commit()
        stamp = txn.versionstamp

        txn = db.create_transaction()
        items = await txn.get_range(b"log/", b"log0")
        last = await txn.get(b"last")
        return v, stamp, items, last

    v, stamp, items, last = run(sched, body())
    assert len(stamp) == 10
    assert int.from_bytes(stamp[:8], "big") == v
    assert items == [(b"log/" + stamp + b"/end", b"payload")]
    assert last == b"at=" + stamp


def test_cli_commands(world):
    sched, cluster, db = world
    cli = CliSession(cluster, db)

    async def body():
        out = []
        out.append(await cli.run_command("set k v"))        # blocked
        out.append(await cli.run_command("writemode on"))
        out.append(await cli.run_command("set k v"))
        out.append(await cli.run_command("get k"))
        out.append(await cli.run_command("getrange a z"))
        out.append(await cli.run_command("clear k"))
        out.append(await cli.run_command("get k"))
        out.append(await cli.run_command("status"))
        out.append(await cli.run_command("status json"))
        out.append(await cli.run_command("bogus"))
        return out

    (blocked, _, set_ok, get_ok, rng, clr, gone, status, status_json,
     unknown) = run(sched, body())
    assert blocked.startswith("ERROR: writemode")
    assert set_ok == "Committed"
    assert get_ok == "`k' is `v'"
    assert "`k' is `v'" in rng
    assert clr == "Committed"
    assert gone == "`k': not found"
    assert "resolver_backend    - tpu" in status
    assert '"resolvers"' in status_json
    assert unknown.startswith("ERROR: unknown command")


def test_cli_backup_restore(tmp_path, world):
    sched, cluster, db = world
    cli = CliSession(cluster, db)
    path = str(tmp_path / "bk")

    async def body():
        await cli.run_command("writemode on")
        await cli.run_command("set persist me")
        out1 = await cli.run_command(f"backup {path}")
        await cli.run_command("clear persist")
        out2 = await cli.run_command(f"restore {path}")
        out3 = await cli.run_command("get persist")
        return out1, out2, out3

    out1, out2, out3 = run(sched, body())
    assert out1.startswith("Snapshot complete")
    assert out2.startswith("Restored")
    assert out3 == "`persist' is `me'"


def test_cli_tenant_knob_consistency_move(world):
    sched, cluster, db = world
    cli = CliSession(cluster, db)

    async def body():
        out = []
        await cli.run_command("writemode on")
        out.append(await cli.run_command("tenant create projA"))
        out.append(await cli.run_command("tenant list"))
        out.append(await cli.run_command("setknob MAX_THING 42"))
        out.append(await cli.run_command("getknobs"))
        out.append(await cli.run_command("set mk v"))
        out.append(await cli.run_command("moveshard mk ml 1"))
        await sched.delay(0.2)  # let the move's deferred drop settle
        out.append(await cli.run_command("consistencycheck"))
        out.append(await cli.run_command("tenant delete projA"))
        return out

    (created, listed, knob_set, knobs, _set, moved, check,
     deleted) = run(sched, body())
    assert "created" in created
    assert listed == "projA"
    assert knob_set == "Knob MAX_THING set"
    assert "MAX_THING = 42" in knobs
    assert moved.startswith("Moved")
    assert check.startswith("Consistency check OK")
    assert "deleted" in deleted
