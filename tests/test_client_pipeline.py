"""Client-side commit pipelining + GRV prefetch (PR-6 tentpole 3c).

The NativeAPI overlap disciplines: prefetch_read_version issues the GRV
request without awaiting (read-set building overlaps the batch
roundtrip), and CommitPipeline keeps up to `depth` commits from one
client in flight behind the proxy's batch pipeline.
"""

import pytest

from foundationdb_tpu.cluster.commit_proxy import NotCommitted
from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster


def run(sched, coro):
    return sched.run_until(sched.spawn(coro).done)


@pytest.fixture(scope="module")
def world():
    sched, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=2, n_resolvers=1, n_storage=2)
    )
    yield sched, cluster, db
    cluster.stop()


def test_grv_prefetch_overlaps_and_pins_version(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        txn.set(b"prefetch-k", b"v0")
        await txn.commit()

        txn2 = db.create_transaction()
        t0 = sched.now()
        txn2.prefetch_read_version()  # issued, NOT awaited
        assert txn2._read_version is None  # still in flight
        # simulated read-set building while the GRV batch is in flight
        await sched.delay(0.05)
        rv = await txn2.get_read_version()
        # the in-flight reply was consumed, not a second request
        assert txn2._grv_promise is None
        assert rv == await txn2.get_read_version()  # pinned
        assert await txn2.get(b"prefetch-k") == b"v0"
        # prefetch after pin is a no-op
        txn2.prefetch_read_version()
        assert txn2._grv_promise is None
        return sched.now() - t0

    assert run(sched, body()) >= 0.05


def test_commit_pipeline_depth_and_order(world):
    sched, cluster, db = world

    async def body():
        pipe = db.commit_pipeline(depth=3)
        futs = []
        for i in range(9):
            txn = db.create_transaction()
            txn.set(b"pl-%d" % i, b"x%d" % i)
            futs.append(await pipe.submit(txn))
            # windowed backpressure: never more than `depth` outstanding
            assert len(pipe._inflight) <= 3
        await pipe.drain()
        versions = [await f for f in futs]
        # all committed (blind writes -> no conflicts); submit order
        # does NOT imply version order across round-robin proxies —
        # that freedom is exactly what pipelining exploits
        assert all(v > 0 for v in versions)
        check = db.create_transaction()
        for i in range(9):
            assert await check.get(b"pl-%d" % i) == b"x%d" % i
        return len(set(versions))

    # pipelined commits actually shared batches: 9 commits landed in
    # fewer than 9 distinct versions (>=1 batch carried several)
    assert run(sched, body()) < 9


def test_commit_pipeline_conflict_surfaces_on_handle(world):
    sched, cluster, db = world

    async def body():
        setup = db.create_transaction()
        setup.set(b"cp-conflict", b"base")
        await setup.commit()

        a = db.create_transaction()
        b = db.create_transaction()
        assert await a.get(b"cp-conflict") == b"base"
        assert await b.get(b"cp-conflict") == b"base"
        a.set(b"cp-conflict", b"from-a")
        b.set(b"cp-conflict", b"from-b")
        pipe = db.commit_pipeline(depth=2)
        fa = await pipe.submit(a)
        fb = await pipe.submit(b)
        await pipe.drain()
        outcomes = []
        for f in (fa, fb):
            try:
                await f
                outcomes.append("committed")
            except NotCommitted:
                outcomes.append("conflicted")
        return sorted(outcomes)

    # exactly one of the two RMWs wins; the loser's error arrives on
    # ITS handle (drain never swallows it)
    assert run(sched, body()) == ["committed", "conflicted"]
