"""Named soak specs: every checked-in spec loads, round-trips, names
only manifest probes, and actually runs (the TOML-driven tester
contract, fdbserver/tester.actor.cpp readTOMLTests_impl)."""

import dataclasses

import pytest

from foundationdb_tpu.analysis.manifest import load_manifest
from foundationdb_tpu.testing.spec import (
    FAULT_FIELDS,
    SoakSpec,
    SpecError,
    derive_plan_fields,
    list_specs,
    load_spec,
)

REQUIRED_SPECS = {
    "default", "api_correctness", "recovery_storm",
    "network_chaos", "storage_stress", "smoke",
}


def test_spec_inventory():
    names = set(list_specs())
    assert REQUIRED_SPECS <= names, (
        f"missing checked-in specs: {REQUIRED_SPECS - names}"
    )
    assert len(names) >= 5


@pytest.mark.parametrize("name", sorted(REQUIRED_SPECS))
def test_spec_loads_and_roundtrips(name):
    spec = load_spec(name)
    assert spec.name == name and spec.description
    # dict round-trip is lossless
    again = SoakSpec.from_dict(spec.to_dict())
    assert again == spec
    assert again.to_dict() == spec.to_dict()


@pytest.mark.parametrize("name", sorted(REQUIRED_SPECS))
def test_spec_expected_probes_are_declared(name):
    """Per-spec probe expectations plug into the canonical manifest:
    a spec naming a probe the tree never declares is a typo that would
    silently never be accounted."""
    manifest = set(load_manifest())
    spec = load_spec(name)
    unknown = set(spec.expected_probes) - manifest
    assert not unknown, (
        f"spec {name} expects probes missing from "
        f"analysis/probe_manifest.json: {sorted(unknown)}"
    )


def test_every_fault_class_covered_by_some_spec():
    """The union of checked-in specs keeps every fault class alive:
    retiring a fault from ALL specs means the ensemble never exercises
    it again — that must be a loud, reviewed decision."""
    alive = set()
    for name in list_specs():
        spec = load_spec(name)
        alive |= {f for f in FAULT_FIELDS if spec.faults[f] > 0}
    assert alive == set(FAULT_FIELDS), (
        f"fault classes no spec reaches: {set(FAULT_FIELDS) - alive}"
    )


def test_plan_derivation_is_deterministic_and_bounded():
    spec = load_spec("default")
    for seed in range(20):
        a = derive_plan_fields(seed, spec)
        b = derive_plan_fields(seed, spec)
        assert a == b
        t = spec.topology
        assert t["storage"][0] <= a["n_storage"] <= t["storage"][1]
        assert a["replication"] <= a["n_storage"]
        assert t["rounds"][0] <= a["rounds"] <= t["rounds"][1]
        assert a["resolver_backend"] in spec.policy["resolver_backends"]
    # plans genuinely vary across seeds
    assert len({str(derive_plan_fields(s, spec)) for s in range(12)}) >= 8


def test_probability_extremes_are_honored():
    spec = load_spec("default")
    on = dataclasses.replace(
        spec, faults={f: 1.0 for f in spec.faults}
    ).validate()
    off = dataclasses.replace(
        spec, faults={f: 0.0 for f in spec.faults}
    ).validate()
    for seed in (0, 7, 33):
        a = derive_plan_fields(seed, on)
        b = derive_plan_fields(seed, off)
        assert all(a[f] for f in FAULT_FIELDS)
        assert not any(b[f] for f in FAULT_FIELDS)
        # an edit to fault probabilities must not reshuffle unrelated
        # draws (the canonical-order discipline)
        assert a["n_storage"] == b["n_storage"]
        assert a["rounds"] == b["rounds"]
        assert a["resolver_backend"] == b["resolver_backend"]


def test_malformed_specs_are_refused():
    spec = load_spec("default")
    with pytest.raises(SpecError):
        load_spec("no_such_spec")
    with pytest.raises(SpecError):
        d = spec.to_dict()
        d["faults"]["kill_proxy"] = 1.5  # not a probability
        SoakSpec.from_dict(d)
    with pytest.raises(SpecError):
        d = spec.to_dict()
        d["faults"]["warp_drive"] = 0.5  # unknown fault class
        SoakSpec.from_dict(d)
    with pytest.raises(SpecError):
        d = spec.to_dict()
        d["topology"]["storage"] = [3, 2]  # inverted range
        SoakSpec.from_dict(d)
    with pytest.raises(SpecError):
        d = spec.to_dict()
        d["policy"]["resolver_backends"] = ["gpu"]  # unknown backend
        SoakSpec.from_dict(d)
    with pytest.raises(SpecError):
        d = spec.to_dict()
        del d["policy"]["audit"]  # the auditor knob is mandatory
        SoakSpec.from_dict(d)
    with pytest.raises(SpecError):
        d = spec.to_dict()
        d["policy"]["audit"] = "yes"  # must be a real bool
        SoakSpec.from_dict(d)


def test_every_spec_arms_the_interleaving_auditor():
    """All checked-in ensembles audit by default: turning the auditor
    off is a per-spec decision that must be visible in a diff."""
    for name in list_specs():
        assert load_spec(name).policy["audit"] is True, name


@pytest.mark.parametrize("name", sorted(REQUIRED_SPECS - {"api_correctness"}))
def test_spec_smoke_one_short_seed(name):
    """One short seed per checked-in spec: the spec loads, plans, runs
    under its fault mix and passes every model check. (api_correctness
    smokes in test_api_workload with the kernel marker — its seeds can
    pick the tpu backend and compile.)"""
    from foundationdb_tpu.testing import soak

    spec = load_spec(name).with_overrides(rounds=(5, 8), api_rounds=5)
    sig = soak.run_seed(1, spec=spec)
    assert sig[1] > 0  # the seed committed work


@pytest.mark.kernel
def test_api_correctness_spec_smoke_tpu_seed():
    """One api_correctness seed on the tpu-force backend: the JAX
    conflict kernel inside the fault ensemble (compile-heavy)."""
    from foundationdb_tpu.testing import soak
    from foundationdb_tpu.testing.soak import plan_for_seed

    spec = load_spec("api_correctness").with_overrides(
        rounds=(5, 8), api_rounds=5
    )
    seed = next(
        s for s in range(64)
        if plan_for_seed(s, spec).resolver_backend == "tpu-force"
    )
    sig = soak.run_seed(seed, spec=spec)
    assert sig[1] > 0 and sig[7] is not None


def test_probe_budgets_schema_and_gating():
    """[probes.budgets]: per-spec expected-probe occurrence rates — a
    budgeted rare probe only gates sweeps big enough that the budget
    predicts PROBE_GATE_MIN_EXPECTED occurrences; unbudgeted probes
    gate any sweep (the pre-budget behavior)."""
    from foundationdb_tpu.testing.spec import PROBE_GATE_MIN_EXPECTED

    spec = load_spec("api_correctness")
    budgets = dict(spec.probe_budgets)
    # the motivating probe carries its measured ~2/100-seed rate
    assert budgets.get("workload.api_unknown_resolved") == pytest.approx(
        0.02
    )
    rare = "workload.api_unknown_resolved"
    threshold = PROBE_GATE_MIN_EXPECTED / budgets[rare]
    assert rare not in spec.gated_probes(1)          # smoke sweep: safe
    assert rare not in spec.gated_probes(int(threshold) - 1)
    assert rare in spec.gated_probes(int(threshold))  # full sweep: gates
    # every unbudgeted expected probe gates even a 1-seed sweep
    unbudgeted = set(spec.expected_probes) - set(budgets)
    assert unbudgeted <= spec.gated_probes(1)
    # roundtrip carries budgets
    assert SoakSpec.from_dict(spec.to_dict()).probe_budgets == (
        spec.probe_budgets
    )


def test_probe_budgets_are_validated():
    spec = load_spec("api_correctness")
    with pytest.raises(SpecError):
        d = spec.to_dict()
        # a budget for a probe the spec doesn't expect is a typo
        d["probes"]["budgets"] = {"workload.no_such_probe": 0.02}
        SoakSpec.from_dict(d)
    with pytest.raises(SpecError):
        d = spec.to_dict()
        d["probes"]["budgets"] = {"workload.api_unknown_resolved": 0.0}
        SoakSpec.from_dict(d)
    with pytest.raises(SpecError):
        d = spec.to_dict()
        d["probes"]["budgets"] = {"workload.api_unknown_resolved": 2.0}
        SoakSpec.from_dict(d)
