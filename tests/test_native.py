"""C++ native conflict set: build, semantics, and three-way parity.

The native library is an independent implementation of the ConflictBatch
contract; here it is cross-checked against the Python oracle on random
workloads (three-way parity with the JAX kernel happens transitively via
test_conflict_parity.py, which pins kernel == oracle).
"""

import numpy as np
import pytest

from foundationdb_tpu.testing.oracle import ConflictOracle, OracleTxn
from foundationdb_tpu.testing.workloads import WorkloadConfig, make_batch

native = pytest.importorskip("foundationdb_tpu.native")


@pytest.fixture(scope="module")
def lib():
    try:
        native.load()
    except native.NativeBuildError as e:  # no g++ in env
        pytest.skip(f"native build unavailable: {e}")
    return native


def to_oracle(txns):
    return [
        OracleTxn(
            read_conflict_ranges=t.read_conflict_ranges,
            write_conflict_ranges=t.write_conflict_ranges,
            read_snapshot=t.read_snapshot,
        )
        for t in txns
    ]


def test_native_basic_semantics(lib):
    from foundationdb_tpu.models.types import CommitTransaction

    cs = native.NativeConflictSet(window=1000)
    v = cs.resolve(
        [CommitTransaction(write_conflict_ranges=[(b"a", b"b")])], 10
    )
    assert v.tolist() == [3]
    v = cs.resolve(
        [
            CommitTransaction(
                read_conflict_ranges=[(b"a", b"b")], read_snapshot=5
            )
        ],
        20,
    )
    assert v.tolist() == [0]  # stale read of the v10 write
    v = cs.resolve(
        [
            CommitTransaction(
                read_conflict_ranges=[(b"a", b"b")], read_snapshot=20
            )
        ],
        30,
    )
    assert v.tolist() == [3]
    # tooOld: snapshot below the MVCC window
    v = cs.resolve(
        [
            CommitTransaction(
                read_conflict_ranges=[(b"x", b"y")], read_snapshot=-2000
            )
        ],
        1500,
    )
    assert v.tolist() == [1]


def test_native_intra_batch_order(lib):
    from foundationdb_tpu.models.types import CommitTransaction

    cs = native.NativeConflictSet(window=1000)
    batch = [
        CommitTransaction(write_conflict_ranges=[(b"k", b"l")]),
        CommitTransaction(
            read_conflict_ranges=[(b"k", b"l")], read_snapshot=5
        ),
        # reads of later writes do NOT conflict
        CommitTransaction(read_conflict_ranges=[(b"z", b"zz")], read_snapshot=5),
        CommitTransaction(write_conflict_ranges=[(b"z", b"zz")]),
    ]
    v = cs.resolve(batch, 10)
    assert v.tolist() == [3, 0, 3, 3]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_native_oracle_parity_random(lib, seed):
    cfg = WorkloadConfig(
        n_txns=40, keyspace=64, key_width=6, stale_fraction=0.05, zipf=1.2
    )
    window = 500
    cs = native.NativeConflictSet(window=window)
    oracle = ConflictOracle(window=window)
    rng = np.random.default_rng(seed)
    version = 0
    for _ in range(15):
        version += int(rng.integers(1, 60))
        txns = make_batch(rng, cfg, version, window)
        got = cs.resolve(txns, version).tolist()
        want = oracle.resolve(to_oracle(txns), version).verdicts
        assert got == want
