"""C++ native conflict set: build, semantics, and three-way parity.

The native library is an independent implementation of the ConflictBatch
contract; here it is cross-checked against the Python oracle on random
workloads (three-way parity with the JAX kernel happens transitively via
test_conflict_parity.py, which pins kernel == oracle).
"""

import numpy as np
import pytest

from foundationdb_tpu.testing.oracle import ConflictOracle, OracleTxn
from foundationdb_tpu.testing.workloads import WorkloadConfig, make_batch

native = pytest.importorskip("foundationdb_tpu.native")


@pytest.fixture(scope="module")
def lib():
    try:
        native.load()
    except native.NativeBuildError as e:  # no g++ in env
        pytest.skip(f"native build unavailable: {e}")
    return native


def to_oracle(txns):
    return [
        OracleTxn(
            read_conflict_ranges=t.read_conflict_ranges,
            write_conflict_ranges=t.write_conflict_ranges,
            read_snapshot=t.read_snapshot,
        )
        for t in txns
    ]


def test_native_basic_semantics(lib):
    from foundationdb_tpu.models.types import CommitTransaction

    cs = native.NativeConflictSet(window=1000)
    v = cs.resolve(
        [CommitTransaction(write_conflict_ranges=[(b"a", b"b")])], 10
    )
    assert v.tolist() == [3]
    v = cs.resolve(
        [
            CommitTransaction(
                read_conflict_ranges=[(b"a", b"b")], read_snapshot=5
            )
        ],
        20,
    )
    assert v.tolist() == [0]  # stale read of the v10 write
    v = cs.resolve(
        [
            CommitTransaction(
                read_conflict_ranges=[(b"a", b"b")], read_snapshot=20
            )
        ],
        30,
    )
    assert v.tolist() == [3]
    # tooOld: snapshot below the MVCC window
    v = cs.resolve(
        [
            CommitTransaction(
                read_conflict_ranges=[(b"x", b"y")], read_snapshot=-2000
            )
        ],
        1500,
    )
    assert v.tolist() == [1]


def test_native_intra_batch_order(lib):
    from foundationdb_tpu.models.types import CommitTransaction

    cs = native.NativeConflictSet(window=1000)
    batch = [
        CommitTransaction(write_conflict_ranges=[(b"k", b"l")]),
        CommitTransaction(
            read_conflict_ranges=[(b"k", b"l")], read_snapshot=5
        ),
        # reads of later writes do NOT conflict
        CommitTransaction(read_conflict_ranges=[(b"z", b"zz")], read_snapshot=5),
        CommitTransaction(write_conflict_ranges=[(b"z", b"zz")]),
    ]
    v = cs.resolve(batch, 10)
    assert v.tolist() == [3, 0, 3, 3]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_native_oracle_parity_random(lib, seed):
    cfg = WorkloadConfig(
        n_txns=40, keyspace=64, key_width=6, stale_fraction=0.05, zipf=1.2
    )
    window = 500
    cs = native.NativeConflictSet(window=window)
    oracle = ConflictOracle(window=window)
    rng = np.random.default_rng(seed)
    version = 0
    for _ in range(15):
        version += int(rng.integers(1, 60))
        txns = make_batch(rng, cfg, version, window)
        got = cs.resolve(txns, version).tolist()
        want = oracle.resolve(to_oracle(txns), version).verdicts
        assert got == want


# ---------------------------------------------------------------------------
# Skip-list baseline (native/skiplist.cpp): same contract, the reference's
# algorithm class (pyramids, radix point sort, bitset intra sweep). Must
# agree with the oracle AND the ordered-map native model everywhere.


@pytest.fixture(scope="module")
def sl_lib():
    try:
        native.load_skiplist()
    except native.NativeBuildError as e:
        pytest.skip(f"native build unavailable: {e}")
    return native


def test_skiplist_basic_semantics(sl_lib):
    from foundationdb_tpu.models.types import CommitTransaction

    cs = native.NativeSkipListConflictSet(window=1000)
    v = cs.resolve(
        [CommitTransaction(write_conflict_ranges=[(b"a", b"b")])], 10
    )
    assert v.tolist() == [3]
    v = cs.resolve(
        [CommitTransaction(read_conflict_ranges=[(b"a", b"b")], read_snapshot=5)],
        20,
    )
    assert v.tolist() == [0]
    v = cs.resolve(
        [CommitTransaction(read_conflict_ranges=[(b"a", b"b")], read_snapshot=20)],
        30,
    )
    assert v.tolist() == [3]
    v = cs.resolve(
        [CommitTransaction(read_conflict_ranges=[(b"x", b"y")], read_snapshot=-2000)],
        1500,
    )
    assert v.tolist() == [1]


def test_skiplist_intra_batch_order(sl_lib):
    from foundationdb_tpu.models.types import CommitTransaction

    cs = native.NativeSkipListConflictSet(window=1000)
    batch = [
        CommitTransaction(write_conflict_ranges=[(b"k", b"l")]),
        CommitTransaction(read_conflict_ranges=[(b"k", b"l")], read_snapshot=5),
        CommitTransaction(read_conflict_ranges=[(b"z", b"zz")], read_snapshot=5),
        CommitTransaction(write_conflict_ranges=[(b"z", b"zz")]),
    ]
    v = cs.resolve(batch, 10)
    assert v.tolist() == [3, 0, 3, 3]


def test_skiplist_shorter_key_ordering(sl_lib):
    """Keys that share a prefix but differ in length (the radix-fallback
    path) must honor shorter-before-longer ordering."""
    from foundationdb_tpu.models.types import CommitTransaction

    cs = native.NativeSkipListConflictSet(window=1000)
    long_a = b"a" * 12  # beyond the 8-byte radix prefix
    v = cs.resolve(
        [CommitTransaction(write_conflict_ranges=[(b"a", long_a)])], 10
    )
    assert v.tolist() == [3]
    # read [a*10, a*11) sits inside [a, a*12): stale read conflicts
    v = cs.resolve(
        [CommitTransaction(read_conflict_ranges=[(b"a" * 10, b"a" * 11)],
                           read_snapshot=5)],
        20,
    )
    assert v.tolist() == [0]
    # read [a*12, a*13) is outside
    v = cs.resolve(
        [CommitTransaction(read_conflict_ranges=[(long_a, b"a" * 13)],
                           read_snapshot=5)],
        30,
    )
    assert v.tolist() == [3]


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_skiplist_oracle_parity_random(sl_lib, seed):
    cfg = WorkloadConfig(
        n_txns=40, keyspace=64, key_width=6, stale_fraction=0.05, zipf=1.2
    )
    window = 500
    cs = native.NativeSkipListConflictSet(window=window)
    oracle = ConflictOracle(window=window)
    rng = np.random.default_rng(seed + 100)
    version = 0
    for _ in range(20):
        version += int(rng.integers(1, 60))
        txns = make_batch(rng, cfg, version, window)
        got = cs.resolve(txns, version).tolist()
        want = oracle.resolve(to_oracle(txns), version).verdicts
        assert got == want


def test_skiplist_gc_windowing(sl_lib):
    """Long-running stream: history size must stay bounded by the window
    (the amortized removeBefore budget keeps up with inserts)."""
    from foundationdb_tpu.models.types import CommitTransaction

    window = 200
    cs = native.NativeSkipListConflictSet(window=window)
    rng = np.random.default_rng(7)
    sizes = []
    for i in range(200):
        version = (i + 1) * 10
        txns = [
            CommitTransaction(
                write_conflict_ranges=[
                    (int(x).to_bytes(4, "big"), int(x + 3).to_bytes(4, "big"))
                ],
            )
            for x in rng.integers(0, 500, size=8)
        ]
        cs.resolve(txns, version)
        sizes.append(cs.history_size)
    # window covers 20 batches x <=16 boundaries: steady state must not grow
    assert sizes[-1] < 2000, sizes[-1]
    assert max(sizes[-50:]) <= max(sizes[50:100]) + 500
