"""Force tests onto a virtual 8-device CPU mesh (no TPU needed in CI).

Must set the env vars before jax is imported anywhere in the test process.
"""

import os

# Force-override: the environment pins JAX_PLATFORMS=axon (the TPU tunnel);
# unit tests must run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

from foundationdb_tpu.parallel.mesh import TPU_PLUGIN_TRIGGER  # noqa: E402

# Subprocesses spawned by tests (multiprocess roles, the hermetic dryrun
# child) must not have the tunnel sitecustomize claim a TPU at their
# interpreter start either.
os.environ.pop(TPU_PLUGIN_TRIGGER, None)

import jax  # noqa: E402

# In the bench environment the sitecustomize already ran jax.config.update
# ("jax_platforms", "axon,cpu") at interpreter start, which BEATS the env
# var above; re-pin by the same mechanism before any backend initializes.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def random_key(rng, max_len=8, alphabet=4) -> bytes:
    n = int(rng.integers(0, max_len + 1))
    return bytes(rng.integers(0, alphabet, size=n, dtype=np.uint8))


def random_range(rng, max_len=8, alphabet=4):
    while True:
        a, b = random_key(rng, max_len, alphabet), random_key(rng, max_len, alphabet)
        if a != b:
            return (min(a, b), max(a, b))
