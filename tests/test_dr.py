"""DR to a second cluster (the fdbdr / DatabaseBackupAgent role).

An agent snapshots + continuously replicates the primary into a locked
secondary; switchover locks the source, drains, and unlocks the
secondary — which then serves as the primary. The replication stream is
the tlog's full-stream tag (each mutation exactly once, in order), so
replicated sources don't double-apply atomics. Both clusters run in one
deterministic scheduler.
"""

import pytest

from foundationdb_tpu.cluster.commit_proxy import DatabaseLockedError
from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.cluster.dr import DestinationLockedError, DrAgent


def _pair(src_kw=None):
    from foundationdb_tpu.runtime.flow import Scheduler

    sched = Scheduler(sim=True)
    kw = {"n_commit_proxies": 1, "n_storage": 2, **(src_kw or {})}
    _s1, src_cluster, src_db = open_cluster(ClusterConfig(**kw), sched=sched)
    _s2, dst_cluster, dst_db = open_cluster(
        ClusterConfig(n_commit_proxies=1, n_storage=2), sched=sched
    )
    return sched, src_cluster, src_db, dst_cluster, dst_db


def drive(sched, coro):
    t = sched.spawn(coro, name="drive")
    sched.run_until(t.done)
    return t.done.get()


def test_dr_replicates_and_switches_over():
    # replication_factor=2 on the source: the full-stream tag must yield
    # each mutation ONCE (per-storage tags carry one copy per replica)
    sched, src_cluster, src_db, dst_cluster, dst_db = _pair(
        {"n_storage": 2, "replication_factor": 2}
    )
    agent = DrAgent(src_cluster, src_db, dst_db)

    async def go():
        # pre-start data: must arrive via the initial snapshot (the log
        # no longer holds it)
        t = src_db.create_transaction()
        t.set(b"pre-existing", b"data")
        await t.commit()

        await agent.start()
        # destination refuses ordinary writes while DR owns it — both
        # via the client fast-path and via a FRESH client handle (the
        # proxy-side txn-state-store check)
        t = dst_db.create_transaction()
        t.set(b"rogue", b"write")
        with pytest.raises(DestinationLockedError):
            await t.commit()
        fresh = dst_cluster.database()
        t = fresh.create_transaction()
        t.set(b"rogue2", b"write")
        with pytest.raises(DatabaseLockedError):
            await t.commit()

        for i in range(20):
            t = src_db.create_transaction()
            t.set(b"user%02d" % (i % 7), b"v%d" % i)
            if i % 5 == 0:
                t.atomic_op("add", b"counter", (1).to_bytes(8, "little"))
            await t.commit()
        t = src_db.create_transaction()
        t.clear_range(b"user03", b"user05")
        await t.commit()

        final = await agent.switchover()
        assert final >= agent.applied_version

        # the retired source is LOCKED: acknowledged commits can never
        # race past the drain point
        t = src_db.create_transaction()
        t.set(b"late", b"write")
        with pytest.raises((DestinationLockedError, DatabaseLockedError)):
            await t.commit()

        ts = src_db.create_transaction()
        src_data = dict(await ts.get_range(b"a", b"z"))
        src_ctr = await ts.get(b"counter")
        td = dst_db.create_transaction()
        dst_data = dict(await td.get_range(b"a", b"z"))
        dst_ctr = await td.get(b"counter")
        assert dst_data == src_data and len(src_data) > 0
        # atomics applied exactly once despite 2x-replicated source:
        assert int.from_bytes(dst_ctr, "little") == 4
        assert dst_ctr == src_ctr
        assert b"user03" not in dst_data and b"user04" not in dst_data
        assert dst_data[b"pre-existing"] == b"data"

        # the destination accepts writes post-switchover
        t = dst_db.create_transaction()
        t.set(b"after", b"switch")
        await t.commit()
        t = dst_db.create_transaction()
        assert await t.get(b"after") == b"switch"
        return True

    assert drive(sched, go())
    src_cluster.stop()
    dst_cluster.stop()


def test_dr_agent_restart_resumes_from_watermark():
    sched, src_cluster, src_db, dst_cluster, dst_db = _pair()
    agent = DrAgent(src_cluster, src_db, dst_db)

    async def go():
        await agent.start()
        for i in range(8):
            t = src_db.create_transaction()
            t.set(b"k%02d" % i, b"v%d" % i)
            await t.commit()
        await agent.drain_to(src_cluster.tlog.version.get())
        first_mark = agent.applied_version
        agent.stop()  # pause: the consumer registration stays

        for i in range(8, 14):
            t = src_db.create_transaction()
            t.set(b"k%02d" % i, b"v%d" % i)
            await t.commit()

        # a FRESH agent resumes from the destination's durable watermark
        agent2 = DrAgent(src_cluster, src_db, dst_db)
        await agent2.start()
        assert agent2.applied_version == first_mark
        final = await agent2.switchover()
        assert final > first_mark
        t = dst_db.create_transaction()
        got = dict(await t.get_range(b"k", b"l"))
        assert got == {b"k%02d" % i: b"v%d" % i for i in range(14)}
        return True

    assert drive(sched, go())
    src_cluster.stop()
    dst_cluster.stop()
