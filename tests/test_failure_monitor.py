"""Failure monitor + client location cache (the round-2/3 carried
fdbrpc debts).

* FailureMonitor (cluster/failure_monitor.py): ping-driven address-level
  liveness shared cluster-wide (fdbrpc/FailureMonitor.actor.cpp) — a
  SILENT kill is detected by the ping loop; a partitioned-but-alive
  process looks dead from the controller's vantage; recovery marks it
  live again. Client requests that hit a dead process report it
  immediately (the loadBalance fast path).
* LocationCache (cluster/client.py): reads resolve key locations from a
  client cache; after a shard moves, the stale entry sends the read to
  the OLD owner, which answers wrong_shard_server; the client
  invalidates + re-resolves (fdbclient/NativeAPI.actor.cpp:2969-3097).
"""

from __future__ import annotations

import pytest

from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster


def run(sched, coro):
    t = sched.spawn(coro)
    sched.run_until(t.done)
    return t.done.get()


@pytest.fixture
def world():
    sched, cluster, db = open_cluster(
        ClusterConfig(n_storage=3, replication_factor=2)
    )
    yield sched, cluster, db
    cluster.stop()


def test_silent_kill_detected_by_ping_loop(world):
    sched, cluster, db = world
    assert cluster.storage_live == [True, True, True]
    cluster.kill_storage_silent(1)
    # nobody told the cluster; the monitor's ping loop must notice
    assert cluster.storage_live[1] is True

    async def wait_detect():
        for _ in range(100):
            await sched.delay(0.05)
            if not cluster.storage_live[1]:
                return True
        return False

    assert run(sched, wait_detect())
    assert cluster.failure_monitor.is_failed("storage1")


def test_reads_fail_over_via_client_report(world):
    sched, cluster, db = world

    victim = cluster.key_servers.team_of(b"fm-key")[0]

    async def body():
        txn = db.create_transaction()
        txn.set(b"fm-key", b"alive")
        await txn.commit()
        # silent kill of a team member, then read immediately — BEFORE
        # the ping loop's detection window. Replica rotation reaches the
        # dead member within a team's worth of reads; that read reports
        # it and fails over inside the same call.
        cluster.kill_storage_silent(victim)
        vals = []
        for _ in range(4):
            txn = db.create_transaction()
            vals.append(await txn.get(b"fm-key"))
        return vals

    assert run(sched, body()) == [b"alive"] * 4
    assert cluster.failure_monitor.is_failed(f"storage{victim}")


def test_reboot_marks_alive_again(world):
    sched, cluster, db = world
    cluster.kill_storage(2)
    assert cluster.storage_live[2] is False
    cluster.reboot_storage(2)
    assert cluster.storage_live[2] is True

    async def stays_live():
        await sched.delay(0.5)  # several ping intervals
        return cluster.storage_live[2]

    assert run(sched, stays_live())


def test_partition_looks_like_failure_until_healed():
    sched, cluster, db = open_cluster(
        ClusterConfig(n_storage=2, replication_factor=2, sim_seed=7)
    )
    try:
        cluster.net.partition("cc", "storage1")

        async def wait_for(value):
            for _ in range(200):
                await sched.delay(0.05)
                if cluster.storage_live[1] is value:
                    return True
            return False

        assert run(sched, wait_for(False))  # partitioned => failed
        cluster.net.heal("cc", "storage1")
        assert run(sched, wait_for(True))   # healed => recovered
    finally:
        cluster.stop()


def test_location_cache_hits_and_wrong_shard_invalidation(world):
    sched, cluster, db = world
    dd = cluster.data_distributor
    cache = db.location_cache

    async def body():
        txn = db.create_transaction()
        for i in range(20):
            txn.set(b"lc%02d" % i, b"v%d" % i)
        await txn.commit()

        # prime the cache
        txn = db.create_transaction()
        assert await txn.get(b"lc07") == b"v7"
        misses0 = cache.misses
        txn = db.create_transaction()
        assert await txn.get(b"lc07") == b"v7"
        assert cache.misses == misses0  # second read: cache hit
        assert cache.hits > 0

        # move the shard away; the cached location is now STALE
        old_team = cluster.key_servers.team_of(b"lc07")
        dest = next(
            s for s in range(len(cluster.storage_servers))
            if s not in old_team
        )
        await dd.move_shard(b"lc00", b"lc99", dest)
        await sched.delay(0.1)  # let the old owner drop the range

        inval0 = cache.invalidations
        txn = db.create_transaction()
        got = await txn.get(b"lc07")
        # the read succeeded THROUGH the stale entry: old owner answered
        # wrong_shard_server, the entry was invalidated, the retry
        # re-resolved to the new owner
        assert got == b"v7"
        assert cache.invalidations > inval0
        # and the refreshed entry routes straight there next time
        m0 = cache.misses
        txn = db.create_transaction()
        assert await txn.get(b"lc07") == b"v7"
        assert cache.misses == m0
        return True

    assert run(sched, body())


def test_location_cache_range_reads_recover(world):
    sched, cluster, db = world
    dd = cluster.data_distributor

    async def body():
        txn = db.create_transaction()
        for i in range(10):
            txn.set(b"rr%02d" % i, b"v%d" % i)
        await txn.commit()
        txn = db.create_transaction()
        assert len(await txn.get_range(b"rr", b"rs")) == 10  # prime cache

        old_team = cluster.key_servers.team_of(b"rr05")
        dest = next(
            s for s in range(len(cluster.storage_servers))
            if s not in old_team
        )
        await dd.move_shard(b"rr03", b"rr08", dest)
        await sched.delay(0.1)

        txn = db.create_transaction()
        items = await txn.get_range(b"rr", b"rs")
        assert [k for k, _ in items] == [b"rr%02d" % i for i in range(10)]
        return True

    assert run(sched, body())
