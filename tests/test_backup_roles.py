"""Backup roles: S3-class blob store, per-epoch BackupWorkers, and
parallel restore.

Reference capabilities matched: fdbclient/S3BlobStore.actor.cpp (an
object store speaking REST is a first-class backup medium),
fdbserver/BackupWorker.actor.cpp (per-epoch log tailing, displacement
on recovery with chained watermarks), and the parallel restore roles
(RestoreController/Loader/Applier — restore sharded across appliers
with clear-splitting at shard bounds).
"""

import pytest

from foundationdb_tpu.cluster.backup import BackupAgent, BackupContainer
from foundationdb_tpu.cluster.blob_store import (
    BlobStoreContainer,
    serve_blob_store,
)
from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.cluster.restore import ParallelRestore


@pytest.fixture
def world():
    sched, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=2, n_resolvers=1, n_storage=2)
    )
    yield sched, cluster, db
    cluster.stop()


def drive(sched, coro):
    t = sched.spawn(coro, name="drive")
    sched.run_until(t.done)
    return t.done.get()


# ---------------------------------------------------------------------------
# Blob store (S3 class)


def test_blob_store_object_roundtrip(tmp_path):
    srv, port = serve_blob_store(str(tmp_path / "objs"))
    try:
        c = BlobStoreContainer(f"127.0.0.1:{port}", bucket="b1")
        c.write_file("snapshots/0001/manifest", {"version": 1, "files": 0})
        c.write_file("snapshots/0001/range_000000", [[b"k", b"v"]])
        c.write_file("logs/0002", {"0002": []})
        assert c.read_file("snapshots/0001/manifest")["version"] == 1
        assert c.read_file("snapshots/0001/range_000000") == [[b"k", b"v"]]
        assert c.list_files("snapshots/") == [
            "snapshots/0001/manifest", "snapshots/0001/range_000000",
        ]
        c.delete_file("logs/0002")
        assert c.list_files("logs/") == []
        with pytest.raises(FileNotFoundError):
            c.read_file("logs/0002")
    finally:
        srv.shutdown()


def test_blob_store_persists_across_server_restart(tmp_path):
    objdir = str(tmp_path / "objs")
    srv, port = serve_blob_store(objdir)
    c = BlobStoreContainer(f"127.0.0.1:{port}")
    c.write_file("durable/file", {"x": 1})
    srv.shutdown()

    srv2, port2 = serve_blob_store(objdir)
    try:
        c2 = BlobStoreContainer(f"127.0.0.1:{port2}")
        assert c2.read_file("durable/file") == {"x": 1}
    finally:
        srv2.shutdown()


def test_backup_restore_through_blob_store(tmp_path, world):
    """The full backup/restore cycle with the OBJECT STORE as the
    medium — what the reference does against S3."""
    sched, cluster, db = world
    srv, port = serve_blob_store(str(tmp_path / "objs"))
    try:
        cont = BlobStoreContainer(f"127.0.0.1:{port}")
        agent = BackupAgent(db, cont)

        async def body():
            t = db.create_transaction()
            for i in range(20):
                t.set(b"bk%02d" % i, b"bv%d" % i)
            await t.commit()
            await agent.snapshot()
            t = db.create_transaction()
            t.clear_range(b"", b"\xff")
            await t.commit()
            await agent.restore()
            t = db.create_transaction()
            return await t.get_range(b"bk", b"bl")

        items = drive(sched, body())
        assert len(items) == 20
        assert items[0] == (b"bk00", b"bv0")
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# BackupWorker displacement across recovery


def test_backup_worker_survives_recovery(world):
    """Log backup continues across a cluster recovery: the old epoch's
    worker drains and hands its watermark to the next epoch's worker —
    every acked commit before AND after the recovery restores."""
    sched, cluster, db = world
    cont = BackupContainer()
    agent = BackupAgent(db, cont)

    async def body():
        await agent.snapshot()
        agent.start_log_backup(cluster)
        t = db.create_transaction()
        for i in range(5):
            t.set(b"pre%d" % i, b"v%d" % i)
        await t.commit()
        await sched.delay(0.2)

        # break a proxy -> controller recovers -> epoch bumps
        p = cluster.commit_proxies[0]
        p.failed = RuntimeError("simulated crash")
        p.stop()
        await sched.delay(1.0)
        assert cluster.controller.epoch >= 2

        t = db.create_transaction()
        for i in range(5):
            t.set(b"post%d" % i, b"w%d" % i)
        await t.commit()
        await sched.delay(0.5)  # new worker catches up
        agent.stop_log_backup()

        # the displaced worker handed off (probe) and log files span
        # both epochs
        from foundationdb_tpu.utils import probes

        hits = probes.snapshot()
        assert hits.get("backup_worker.displaced"), hits

        # wipe and restore: both generations' commits come back
        t = db.create_transaction()
        t.clear_range(b"", b"\xff")
        await t.commit()
        await agent.restore()
        t = db.create_transaction()
        pre = await t.get_range(b"pre", b"prf")
        post = await t.get_range(b"post", b"posu")
        return pre, post

    pre, post = drive(sched, body())
    assert len(pre) == 5 and len(post) == 5


# ---------------------------------------------------------------------------
# Parallel restore


def _agent_with_data(sched, db, *, n=200):
    cont = BackupContainer()
    agent = BackupAgent(db, cont)

    async def load():
        t = db.create_transaction()
        for i in range(n):
            t.set(b"pk%06d" % i, b"pv%d" % i)
        await t.commit()

    drive(sched, load())
    return cont, agent


def test_parallel_restore_matches_sequential(world):
    sched, cluster, db = world
    cont, agent = _agent_with_data(sched, db)

    async def body():
        await agent.snapshot()
        agent.start_log_backup(cluster)
        # post-snapshot mutations incl. a clear spanning shard bounds
        t = db.create_transaction()
        t.set(b"pk000050", b"UPDATED")
        t.clear_range(b"pk000100", b"pk000150")
        t.add(b"counter", 7)
        await t.commit()
        await sched.delay(0.3)
        agent.stop_log_backup()

        t = db.create_transaction()
        t.clear_range(b"", b"\xff")
        await t.commit()

        stats = await ParallelRestore(db, cont, n_appliers=4).run()
        t = db.create_transaction()
        rows = await t.get_range(b"", b"\xff")
        return stats, dict(rows)

    stats, rows = drive(sched, body())
    assert stats.appliers >= 2  # genuinely sharded
    assert stats.mutations_applied > 0
    assert rows[b"pk000050"] == b"UPDATED"
    assert b"pk000100" not in rows and b"pk000149" not in rows
    assert rows[b"pk000151"] == b"pv151"
    import struct

    assert struct.unpack("<q", rows[b"counter"])[0] == 7
    # every surviving snapshot key present
    assert rows[b"pk000000"] == b"pv0"
    assert rows[b"pk000199"] == b"pv199"


def test_parallel_restore_target_version(world):
    sched, cluster, db = world
    cont, agent = _agent_with_data(sched, db, n=10)

    async def body():
        await agent.snapshot()
        agent.start_log_backup(cluster)
        t = db.create_transaction()
        t.set(b"early", b"1")
        v_early = await t.commit()
        t = db.create_transaction()
        t.set(b"late", b"2")
        await t.commit()
        await sched.delay(0.3)
        agent.stop_log_backup()

        t = db.create_transaction()
        t.clear_range(b"", b"\xff")
        await t.commit()
        stats = await ParallelRestore(db, cont, n_appliers=3).run(
            target_version=v_early
        )
        t = db.create_transaction()
        early = await t.get(b"early")
        late = await t.get(b"late")
        return stats, early, late

    stats, early, late = drive(sched, body())
    assert early == b"1"
    assert late is None
    assert stats.restored_version <= stats.snapshot_version + 10**9
