"""Versioned-read (snapshot isolation) tests for the storage engine.

The property under test: a read AT version v returns the state as of v
even if newer commits have applied — what makes read-only transactions
(committed client-side with no conflict check) serializable, and what
the reference's VersionedMap provides (VersionedMap.h).
"""

import pytest

from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster


def run(sched, coro):
    return sched.run_until(sched.spawn(coro).done)


@pytest.fixture
def world():
    sched, cluster, db = open_cluster(ClusterConfig(n_storage=2))
    yield sched, cluster, db
    cluster.stop()


def test_read_only_txn_sees_stable_snapshot(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        txn.set(b"a", b"1")
        txn.set(b"b", b"1")
        await txn.commit()

        # reader pins a version by reading `a`...
        reader = db.create_transaction()
        a1 = await reader.get(b"a", snapshot=True)

        # ...then a writer commits a consistent update to both keys...
        writer = db.create_transaction()
        writer.set(b"a", b"2")
        writer.set(b"b", b"2")
        await writer.commit()

        # ...and the reader must still see the OLD b (same snapshot),
        # not the new value — even though storage already applied v2.
        b1 = await reader.get(b"b", snapshot=True)
        rng = await reader.get_range(b"a", b"c", snapshot=True)

        fresh = db.create_transaction()
        b2 = await fresh.get(b"b")
        return a1, b1, rng, b2

    a1, b1, rng, b2 = run(sched, body())
    assert (a1, b1) == (b"1", b"1")          # consistent old snapshot
    assert rng == [(b"a", b"1"), (b"b", b"1")]
    assert b2 == b"2"                        # new txns see the new state


def test_snapshot_sees_clears_at_version(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        txn.set(b"gone", b"x")
        await txn.commit()

        reader = db.create_transaction()
        await reader.get_read_version()

        deleter = db.create_transaction()
        deleter.clear(b"gone")
        await deleter.commit()

        old_view = await reader.get(b"gone", snapshot=True)
        new_view = await db.create_transaction().get(b"gone")
        return old_view, new_view

    old_view, new_view = run(sched, body())
    assert old_view == b"x"   # still visible at the old version
    assert new_view is None


def test_atomic_history_at_versions(world):
    sched, cluster, db = world

    async def body():
        versions = []
        for _ in range(3):
            txn = db.create_transaction()
            txn.add(b"ctr", 1)
            versions.append(await txn.commit())
        ss = cluster.storage_servers[
            cluster.key_servers.shard_of(b"ctr")
        ]
        return versions, [
            await ss.get_value(b"ctr", v) for v in versions
        ]

    versions, views = run(sched, body())
    assert [int.from_bytes(v, "little") for v in views] == [1, 2, 3]


def test_gc_raises_floor_and_rejects_ancient_reads(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        txn.set(b"old", b"1")
        await txn.commit()
        v_old = txn.committed_version

        # advance far beyond the MVCC window (5M versions ~ 5s); two
        # rounds because a single version allocation clamps at the
        # window size (MAX_READ_TRANSACTION_LIFE_VERSIONS)
        for _ in range(2):
            await sched.delay(6.0)
            txn = db.create_transaction()
            txn.set(b"new", b"1")
            await txn.commit()

        await sched.delay(0.1)  # let the storage update loop apply + GC
        ss = cluster.storage_servers[cluster.key_servers.shard_of(b"old")]
        from foundationdb_tpu.cluster.storage import TransactionTooOld

        try:
            await ss.get_value(b"old", v_old)
            return "served", None
        except TransactionTooOld:
            # the value itself survives GC (only history below the floor
            # collapses); fresh reads still see it
            fresh = await db.create_transaction().get(b"old")
            return "too_old", fresh

    outcome, fresh = run(sched, body())
    assert outcome == "too_old"
    assert fresh == b"1"


def test_gc_passing_waited_version_raises_too_old():
    """Regression (soak seeds 1122/1171, found by the api workload's
    model check): a reader whose version check passed BEFORE the wait
    must re-validate after it — a lagging replica catching up applies a
    huge version span in one pull batch, the MVCC floor passes the
    waited-for version mid-wait, and serving anyway returns a silently
    PARTIAL state at that version (keys whose surviving post-GC entry
    sits above it vanish). The read must raise transaction_too_old so
    the client retries at a fresh version."""
    from foundationdb_tpu.cluster.storage import StorageServer, TransactionTooOld
    from foundationdb_tpu.cluster.tlog import TLog, TLogCommitRequest
    from foundationdb_tpu.runtime.flow import Scheduler

    sched = Scheduler(sim=True)
    tlog = TLog(sched)
    ss = StorageServer(sched, tlog, tag=0, window_versions=1000)
    ss.start()

    async def body():
        await tlog.commit(TLogCommitRequest(
            prev_version=0, version=10,
            messages={0: [("set", b"k1", b"v1"), ("set", b"k2", b"v2")]},
        ))
        await sched.delay(0.05)  # ss applies version 10
        # wedge the pull loop (the lagging replica)
        ss.slowdown = 5.0
        await sched.delay(0.01)
        # a read at a CURRENTLY-valid version starts waiting...
        reader = sched.spawn(ss.get_key_values(b"k", b"l", 500))
        # ...while commits race far past it: 500 ends up below the
        # MVCC floor (2500 - window 1000) by the time ss catches up
        prev = 10
        for v in range(500, 2600, 100):
            await tlog.commit(TLogCommitRequest(
                prev_version=prev, version=v,
                messages={0: [("set", b"k1", b"v@%d" % v)]},
            ))
            prev = v
        ss.slowdown = 0.0
        await sched.delay(6.0)  # catch-up: one pull batch spans it all
        try:
            got = await reader.done
        except TransactionTooOld:
            return "too_old"
        return got

    result = sched.run_until(sched.spawn(body()).done)
    assert result == "too_old", (
        f"read below the post-catch-up MVCC floor served a partial "
        f"state instead of raising: {result!r}"
    )
    ss.stop()
