"""Simulation-mode tests: deterministic faults against the live cluster.

The reference's core test strategy (SURVEY.md §4): run the whole
distributed system in one deterministic process, inject network faults,
and check invariants — reruns with the same seed reproduce the same
execution exactly.
"""

import numpy as np
import pytest

from foundationdb_tpu.cluster.commit_proxy import NotCommitted
from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.runtime.flow import all_of
from foundationdb_tpu.sim.network import PartitionedError


def run(sched, coro):
    return sched.run_until(sched.spawn(coro).done)


def build(seed=0, **kw):
    kw.setdefault("n_commit_proxies", 2)
    kw.setdefault("n_resolvers", 2)
    kw.setdefault("n_storage", 2)
    return open_cluster(ClusterConfig(sim_seed=seed, **kw))


async def _mixed_workload(sched, db, rounds, seed):
    """ConflictRange-style model check (fdbserver/workloads/
    ConflictRange.actor.cpp): random reads/writes on a bounded keyspace,
    every read cross-checked against an in-memory model of committed
    state."""
    rng = np.random.default_rng(seed)
    model: dict[bytes, bytes] = {}
    committed = aborted = 0
    for i in range(rounds):
        txn = db.create_transaction()
        try:
            nk = int(rng.integers(1, 4))
            # reads first: verify against the model
            for _ in range(int(rng.integers(0, 3))):
                a, b = sorted(rng.integers(0, 40, size=2).tolist())
                got = await txn.get_range(b"k%02d" % a, b"k%02d" % (b + 1))
                want = sorted(
                    (k, v) for k, v in model.items()
                    if b"k%02d" % a <= k < b"k%02d" % (b + 1)
                )
                assert got == want, f"round {i}: read mismatch"
            writes = {}
            for _ in range(nk):
                k = b"k%02d" % int(rng.integers(0, 40))
                if rng.random() < 0.2:
                    e = k + b"\xff"
                    txn.clear_range(k, e)
                    writes[("clear", k, e)] = None
                else:
                    v = b"v%d" % i
                    txn.set(k, v)
                    writes[("set", k, v)] = None
            await txn.commit()
            committed += 1
            for op in writes:
                if op[0] == "set":
                    model[op[1]] = op[2]
                else:
                    for k in [k for k in model if op[1] <= k < op[2]]:
                        del model[k]
        except NotCommitted:
            aborted += 1
    return committed, aborted, model


def test_deterministic_reruns_identical():
    """Two fresh clusters with the same seed must execute identically."""

    def one_run():
        sched, cluster, db = build(seed=42)
        out = run(sched, _mixed_workload(sched, db, 25, seed=7))
        end_time = sched.now()
        counters = [p.counters.as_dict() for p in cluster.commit_proxies]
        cluster.stop()
        return out, end_time, counters

    assert one_run() == one_run()


def test_clogging_slows_but_preserves_correctness():
    sched, cluster, db = build(seed=1)
    # clog both proxies' links to resolver 0 heavily
    cluster.net.clog_pair("proxy0", "resolver0", 0.5)
    cluster.net.clog_pair("proxy1", "resolver0", 0.8)
    committed, aborted, model = run(
        sched, _mixed_workload(sched, db, 20, seed=3)
    )
    assert committed > 0
    # after the clog, state must equal the model
    async def verify():
        txn = db.create_transaction()
        got = dict(await txn.get_range(b"k", b"l"))
        return got
    got = run(sched, verify())
    assert got == model
    cluster.stop()


def test_partition_breaks_proxy_then_recovery_heals():
    from foundationdb_tpu.cluster.commit_proxy import CommitUnknownResult

    sched, cluster, db = build(seed=2)
    cluster.net.partition("proxy0", "resolver1")
    cluster.net.partition("proxy1", "resolver1")

    async def attempt():
        txn = db.create_transaction()
        txn.set(b"\xf0px", b"1")  # resolver 1's partition
        try:
            await txn.commit()
            return "committed"
        except CommitUnknownResult:
            return "unknown-result"

    assert run(sched, attempt()) == "unknown-result"
    cluster.net.heal("proxy0", "resolver1")
    cluster.net.heal("proxy1", "resolver1")
    # The cluster controller notices the broken proxy and recovers a new
    # generation; the retry loop rides through.
    async def after():
        await db.run(lambda txn: _set(txn, b"\xf0post", b"1"))
        txn = db.create_transaction()
        return await txn.get(b"\xf0post")

    assert run(sched, after()) == b"1"
    assert cluster.controller.epoch >= 2
    cluster.stop()


async def _set(txn, k, v):
    txn.set(k, v)


def test_storage_reboot_resumes_from_durable_state():
    sched, cluster, db = build(seed=3)

    async def body():
        txn = db.create_transaction()
        for i in range(10):
            txn.set(b"s%02d" % i, b"v%d" % i)
        await txn.commit()

        cluster.reboot_storage(0)
        cluster.reboot_storage(1)

        txn = db.create_transaction()
        txn.set(b"s99", b"after-reboot")
        await txn.commit()

        txn = db.create_transaction()
        return await txn.get_range(b"s", b"t")

    items = run(sched, body())
    assert len(items) == 11
    assert (b"s99", b"after-reboot") in items
    cluster.stop()


def test_attrition_workload_under_load():
    """Storage reboots while a workload runs (MachineAttrition-style)."""
    sched, cluster, db = build(seed=4)

    async def attrition():
        for i in range(3):
            await sched.delay(0.08)
            cluster.reboot_storage(i % 2)

    async def body():
        att = sched.spawn(attrition())
        out = await _mixed_workload(sched, db, 20, seed=9)
        await att
        return out

    committed, aborted, model = run(sched, body())
    assert committed > 0

    async def verify():
        txn = db.create_transaction()
        return dict(await txn.get_range(b"k", b"l"))

    assert run(sched, verify()) == model
    cluster.stop()
