"""Ratekeeper admission control + watch tests."""

from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.cluster.ratekeeper import Ratekeeper
from foundationdb_tpu.cluster.sequencer import Sequencer
from foundationdb_tpu.runtime.flow import Scheduler


def run(sched, coro):
    return sched.run_until(sched.spawn(coro).done)


class FakeStorage:
    def __init__(self):
        from foundationdb_tpu.runtime.flow import Notified

        self.version = Notified(0)


def test_ratekeeper_control_law():
    sched = Scheduler(sim=True)
    seq = Sequencer(sched)
    ss = FakeStorage()
    rk = Ratekeeper(sched, seq, [ss], interval=0.1, max_tps=1000.0)
    rk.start()

    # healthy: no lag -> full budget
    sched.run_for(0.5)
    assert rk.get_rate_info() == 1000.0

    # storage falls past the hard lag limit -> clamped to min
    seq.report_live_committed_version(10_000_000)
    sched.run_for(0.5)
    assert rk.get_rate_info() == rk.min_tps
    assert rk.counters.get("throttled") > 0
    assert rk.law.limited_by["name"] == "storage_server_durability_lag"

    # mid-lag (over target, under the hard limit) with no admitted
    # traffic: the multiplicative law holds the clamp — recovery only
    # begins once the limiter RELEASES (hysteresis, not a memoryless
    # interpolation that would flap with the sensor)
    ss.version.set(10_000_000 - 3_000_000)
    sched.run_for(0.5)
    assert rk.get_rate_info() < 1000.0

    # catch up -> the budget recovers through INTERMEDIATE values
    # (bounded growth per interval, anti-windup), then reaches max
    ss.version.set(10_000_000)
    sched.run_for(0.15)  # one loop: partial recovery only
    mid = rk.get_rate_info()
    assert rk.min_tps < mid < 1000.0
    sched.run_for(1.5)
    assert rk.get_rate_info() == 1000.0
    assert rk.law.limited_by["name"] == "workload"
    rk.stop()


def test_grv_throttle_timing():
    sched, cluster, db = open_cluster(ClusterConfig(n_storage=1))
    cluster.ratekeeper.stop()
    cluster.ratekeeper.get_rate_info = lambda: 5.0  # 5 txn/s

    results = []

    async def one_grv(i):
        await db.grv_proxy.get_read_version().future
        results.append((i, sched.now()))

    tasks = [sched.spawn(one_grv(i)) for i in range(10)]
    from foundationdb_tpu.runtime.flow import all_of

    run(sched, _await_all([t.done for t in tasks]))
    elapsed = max(t for _, t in results) - min(t for _, t in results)
    # 10 requests at 5/s must spread over >= ~1.5s of virtual time
    assert elapsed > 1.0, f"throttle not applied: {elapsed}"
    cluster.stop()


async def _await_all(futs):
    from foundationdb_tpu.runtime.flow import all_of

    return await all_of(futs)


def test_watch_fires_on_change():
    sched, cluster, db = open_cluster(ClusterConfig())

    async def body():
        txn = db.create_transaction()
        txn.set(b"w", b"1")
        await txn.commit()

        txn = db.create_transaction()
        fut = await txn.watch(b"w")
        assert not fut.is_ready

        txn2 = db.create_transaction()
        txn2.set(b"w", b"2")
        await txn2.commit()
        v = await fut
        return v > 0

    assert run(sched, body())
    cluster.stop()


def test_watch_on_missing_key_and_clear():
    sched, cluster, db = open_cluster(ClusterConfig())

    async def body():
        txn = db.create_transaction()
        txn.set(b"wc", b"x")
        await txn.commit()

        txn = db.create_transaction()
        fut = await txn.watch(b"wc")
        txn2 = db.create_transaction()
        txn2.clear(b"wc")
        await txn2.commit()
        await fut  # clear changes the value -> fires
        return True

    assert run(sched, body())
    cluster.stop()
