"""System keyspace schema: keyServers/serverKeys reads + encodings
(fdbclient/SystemData.cpp parity — the shard-location schema every
locator/audit tool reads)."""

from foundationdb_tpu.cluster import system_data as SD
from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster


def drive(sched, coro):
    t = sched.spawn(coro, name="drive")
    sched.run_until(t.done)
    return t.done.get()


def test_value_encoding_roundtrip():
    v = SD.key_servers_value([3, 1, 2], [7, 8])
    src, dest = SD.decode_key_servers_value(v)
    assert src == [3, 1, 2] and dest == [7, 8]
    assert SD.decode_key_servers_value(SD.key_servers_value([0])) == ([0], [])
    assert SD.decode_key_servers_value(b"") == ([], [])


def test_key_servers_schema_reads():
    sched, cluster, db = open_cluster(
        ClusterConfig(
            n_commit_proxies=1, n_storage=4, replication_factor=2,
            storage_boundaries=[b"g", b"n", b"t"],
        )
    )
    try:
        async def body():
            txn = db.create_transaction()
            rows = await txn.get_range(
                SD.KEY_SERVERS_PREFIX, SD.KEY_SERVERS_END
            )
            return rows

        rows = drive(sched, body())
        # one row per shard, begin-keyed, decodable teams of size 2
        assert [k for k, _v in rows] == [
            SD.key_servers_key(b) for b in (b"", b"g", b"n", b"t")
        ]
        for k, v in rows:
            src, dest = SD.decode_key_servers_value(v)
            assert len(src) == 2 and dest == []
        # the row for a key's shard names the same team the router uses
        src0, _ = SD.decode_key_servers_value(rows[1][1])
        assert tuple(sorted(cluster.key_servers.team_of(b"hello"))) == tuple(
            src0
        )
    finally:
        cluster.stop()


def test_server_keys_schema_reads():
    sched, cluster, db = open_cluster(
        ClusterConfig(
            n_commit_proxies=1, n_storage=3,
            storage_boundaries=[b"g", b"n"],
        )
    )
    try:
        async def body():
            txn = db.create_transaction()
            return await txn.get_range(
                SD.server_keys_key(1, b""), SD.server_keys_key(1, b"\xff")
            )

        rows = drive(sched, body())
        # server 1 owns exactly [g, n): TRUE at g, FALSE at n
        assert rows == [
            (SD.server_keys_key(1, b"g"), SD.SERVER_KEYS_TRUE),
            (SD.server_keys_key(1, b"n"), SD.SERVER_KEYS_FALSE),
        ]
    finally:
        cluster.stop()


def test_schema_reflects_shard_moves():
    """After data distribution moves a shard, the schema rows change —
    the property DD audits rely on."""
    sched, cluster, db = open_cluster(
        ClusterConfig(
            n_commit_proxies=1, n_storage=3,
            storage_boundaries=[b"g", b"n"],
        )
    )
    try:
        async def body():
            txn = db.create_transaction()
            txn.set(b"h-key", b"v")
            await txn.commit()
            before = dict(await txn.get_range(
                SD.KEY_SERVERS_PREFIX, SD.KEY_SERVERS_END
            ))
            await cluster.data_distributor.move_shard(b"g", b"n", (2,))
            txn2 = db.create_transaction()
            after = dict(await txn2.get_range(
                SD.KEY_SERVERS_PREFIX, SD.KEY_SERVERS_END
            ))
            return before, after

        before, after = drive(sched, body())
        k = SD.key_servers_key(b"g")
        src_b, _ = SD.decode_key_servers_value(before[k])
        src_a, _ = SD.decode_key_servers_value(after[k])
        assert src_a == [2] and src_a != src_b
    finally:
        cluster.stop()


def test_cross_module_scan_refused():
    """A range straddling a materialized schema module raises (the
    reference's SpecialKeySpace CROSS_MODULE_READ discipline) instead
    of silently dropping stored rows."""
    import pytest

    sched, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=1, n_storage=2)
    )
    try:
        async def body():
            txn = db.create_transaction()
            with pytest.raises(ValueError, match="module"):
                await txn.get_range(SD.KEY_SERVERS_PREFIX, b"\xff\xff")
            # full serverKeys audit scan works within bounds
            rows = await txn.get_range(
                SD.SERVER_KEYS_PREFIX, SD.SERVER_KEYS_END
            )
            sids = {SD.decode_server_keys_key(k)[0] for k, _v in rows}
            assert sids == {0, 1}
            return True

        assert drive(sched, body())
    finally:
        cluster.stop()


def test_key_servers_scan_stays_inside_bounds():
    """Range-read contract regression (ADVICE: system_data range-read):
    a keyServers scan whose begin falls INSIDE a shard must not leak
    the straddling shard's row key below the requested bound — the row
    is clamped to `begin` (krmGetRanges alignment), and every returned
    key lies in [begin, end)."""
    sched, cluster, db = open_cluster(
        ClusterConfig(
            n_commit_proxies=1, n_storage=4, replication_factor=2,
            storage_boundaries=[b"g", b"n", b"t"],
        )
    )
    try:
        async def body():
            txn = db.create_transaction()
            # "hello" is inside the [g, n) shard: pre-clamp this scan
            # returned the row keyed at "g" — OUTSIDE the bound
            begin = SD.KEY_SERVERS_PREFIX + b"hello"
            end = SD.KEY_SERVERS_PREFIX + b"u"
            rows = await txn.get_range(begin, end)
            assert rows, "scan lost the straddling shard entirely"
            assert all(begin <= k < end for k, _v in rows), rows
            # the clamped row still names the team that owns `hello`
            src, _dest = SD.decode_key_servers_value(rows[0][1])
            assert rows[0][0] == SD.key_servers_key(b"hello")
            assert tuple(sorted(cluster.key_servers.team_of(b"hello"))) \
                == tuple(src)
            # reverse scan honors the same bounds and ordering
            rev = await txn.get_range(begin, end, reverse=True, limit=2)
            assert [k for k, _v in rev] == [
                k for k, _v in rows[-2:]
            ][::-1]
            return True

        assert drive(sched, body())
    finally:
        cluster.stop()
