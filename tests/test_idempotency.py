"""Idempotency-id tests (automatic commit idempotency)."""

from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster


def run(sched, coro):
    return sched.run_until(sched.spawn(coro).done)


def test_idempotency_record_written_and_detectable():
    sched, cluster, db = open_cluster(ClusterConfig())

    async def body():
        txn = db.create_transaction()
        ident = txn.set_idempotency_id()
        txn.set(b"idk", b"v")
        await txn.commit()

        # the retry probe a client would run after commit_unknown_result
        probe = db.create_transaction()
        mark = await probe.get(b"\xff/idmp/" + ident, snapshot=True)
        return mark

    assert run(sched, body()) == b"\x01"
    cluster.stop()


def test_run_idempotent_normal_path():
    sched, cluster, db = open_cluster(ClusterConfig())

    async def w(txn):
        txn.add(b"ict", 1)

    async def body():
        for _ in range(3):
            await db.run(w, idempotent=True)
        txn = db.create_transaction()
        return await txn.get(b"ict")

    assert int.from_bytes(run(sched, body()), "little") == 3
    cluster.stop()


def test_run_idempotent_skips_reapply_after_unknown_result():
    """Force the ambiguous case: the commit applies but the client sees
    commit_unknown_result — the idempotent retry must NOT double-apply."""
    sched, cluster, db = open_cluster(ClusterConfig())
    proxy = cluster.commit_proxies[0]
    real_commit = proxy.commit
    fired = []

    def sabotaged_commit(ctr):
        from foundationdb_tpu.cluster.commit_proxy import CommitUnknownResult
        from foundationdb_tpu.runtime.flow import Promise

        p = real_commit(ctr)
        if not fired:
            fired.append(True)
            # deliver the commit, but report ambiguity to the client
            broken = Promise()

            def relay(f):
                if not broken.is_set:
                    broken.send_error(CommitUnknownResult())

            p.future.add_done_callback(relay)
            return broken
        return p

    proxy.commit = sabotaged_commit

    async def w(txn):
        txn.add(b"amb", 1)

    async def body():
        await db.run(w, idempotent=True)
        await db.run(w, idempotent=True)
        txn = db.create_transaction()
        return await txn.get(b"amb")

    # two logical increments -> exactly 2, despite the ambiguous retry
    assert int.from_bytes(run(sched, body()), "little") == 2
    cluster.stop()


def test_default_idempotency_ids_deterministic_and_unique():
    """The uuid4 default is gone (flowcheck baseline burn-down): ids
    are per-client (origin, client, seq) nonces — unique within and
    across client handles, and REPLAYABLE: the same sim seed yields the
    same ids."""
    sched, cluster, db = open_cluster(ClusterConfig(sim_seed=42))
    ids = [db.create_transaction().set_idempotency_id() for _ in range(4)]
    db2 = cluster.database()  # a second client handle on the same cluster
    ids += [db2.create_transaction().set_idempotency_id() for _ in range(4)]
    assert len(set(ids)) == len(ids)
    cluster.stop()

    sched_b, cluster_b, db_b = open_cluster(ClusterConfig(sim_seed=42))
    replay = [db_b.create_transaction().set_idempotency_id() for _ in range(4)]
    assert replay == ids[:4]
    cluster_b.stop()
