"""Ratekeeper control law + tag throttling (VERDICT r2 task 9).

The control loop computes the admission budget from the worst storage
lag (Ratekeeper.actor.cpp:475's queue-health input, version-lag form);
a slow storage server must force throttling and the cluster must stay
inside the MVCC window. Per-tag quotas meter tagged transactions at the
GRV front door (GlobalTagThrottler's enforcement point) — throttled
tags are delayed, never dropped, and untagged traffic is unaffected.
"""

from __future__ import annotations

import pytest

from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster


@pytest.fixture
def world():
    sched, cluster, db = open_cluster(ClusterConfig(n_storage=2))
    yield sched, cluster, db
    cluster.stop()


def _run(sched, coro):
    t = sched.spawn(coro)
    sched.run_until(t.done)
    return t.done.get()


def test_slow_storage_forces_throttle_and_recovery(world):
    sched, cluster, db = world
    rk = cluster.ratekeeper
    # make the law bite quickly in test time
    rk.lag_target = 50_000
    rk.lag_limit = 400_000
    rk.interval = 0.05

    ss = cluster.storage_servers[0]
    ss.slowdown = 0.2  # ~5 pulls/s while versions advance at ~1e6/s

    # sample the budget DURING load: with adaptive proxy batching the
    # lag can drain (and the budget legally recover) before the last
    # commit returns, so asserting on the post-load snapshot races the
    # law's own recovery — the invariant is that throttling ENGAGED
    min_budget = [rk.max_tps]

    async def load():
        for i in range(30):
            txn = db.create_transaction()
            txn.set(b"rk%02d" % (i % 8), b"v%d" % i)
            await txn.commit()
            min_budget[0] = min(min_budget[0], rk.tps_budget)
            await sched.delay(0.02)
            min_budget[0] = min(min_budget[0], rk.tps_budget)

    _run(sched, load())
    assert rk.counters.get("throttled") > 0, "law never engaged"
    assert min_budget[0] < rk.max_tps

    # remove the fault: the lag drains and the budget recovers
    ss.slowdown = 0.0
    sched.run_for(3.0)
    assert rk.tps_budget == rk.max_tps, "budget never recovered"

    # the cluster stayed serviceable: a fresh txn commits
    async def probe():
        txn = db.create_transaction()
        txn.set(b"after", b"ok")
        await txn.commit()
        t2 = db.create_transaction()
        return await t2.get(b"after")

    assert _run(sched, probe()) == b"ok"


def test_tag_quota_delays_tagged_not_untagged(world):
    sched, cluster, db = world
    cluster.ratekeeper.set_tag_quota("batch", 5.0)  # 5 tps

    done = {"tagged": 0, "untagged": 0}

    async def tagged():
        for _ in range(12):
            txn = db.create_transaction(tag="batch")
            await txn.get_read_version()
            done["tagged"] += 1

    async def untagged():
        for _ in range(12):
            txn = db.create_transaction()
            await txn.get_read_version()
            done["untagged"] += 1

    t1 = sched.spawn(tagged())
    t2 = sched.spawn(untagged())
    sched.run_until(t2.done)
    # untagged finished at full speed while the tagged stream is still
    # being metered at ~5/s
    assert done["untagged"] == 12
    assert done["tagged"] < 12, "tag quota never delayed anything"
    sched.run_until(t1.done)  # delayed, never dropped
    assert done["tagged"] == 12
    from foundationdb_tpu.utils import probes

    assert probes.snapshot().get("ratekeeper.tag_throttled", 0) > 0


def test_auto_tag_throttle_from_busyness():
    """GlobalTagThrottler's AUTO tier (VERDICT r3 weak #7): a tag
    dominating admissions while the pipeline is stressed gets a derived
    quota — no management action — and the quota lifts again once the
    stress clears."""
    from foundationdb_tpu.cluster.ratekeeper import Ratekeeper
    from foundationdb_tpu.runtime.flow import Scheduler

    class SeqStub:
        class _N:
            def __init__(self):
                self.v = 0

            def get(self):
                return self.v

        def __init__(self):
            self.live_committed = self._N()

    class SSStub:
        def __init__(self):
            self.version = SeqStub._N()

    sched = Scheduler(sim=True)
    seq = SeqStub()
    ss = SSStub()
    rk = Ratekeeper(sched, seq, [ss], interval=0.05,
                    lag_target=1000, lag_limit=10_000)
    rk.start()

    async def drive():
        # stressed pipeline: storage 5000 versions behind
        seq.live_committed.v = 5000
        ss.version.v = 0
        # "batch" dominates admissions across several intervals
        for _ in range(6):
            for _ in range(90):
                rk.note_tag_admission("batch")
            for _ in range(10):
                rk.note_tag_admission("oltp")
            await sched.delay(0.05)
        assert rk.get_tag_quota("batch") < float("inf"), (
            "dominant tag under stress must be auto-throttled"
        )
        assert rk.get_tag_quota("oltp") == float("inf"), (
            "minority tag must not be throttled"
        )
        throttled_at = rk.get_tag_quota("batch")

        # stress clears: the quota relaxes and eventually lifts
        ss.version.v = 5000
        for _ in range(30):
            await sched.delay(0.05)
            if rk.get_tag_quota("batch") == float("inf"):
                break
        assert rk.get_tag_quota("batch") == float("inf"), (
            f"auto quota must lift after recovery (stuck at "
            f"{rk.get_tag_quota('batch')}, was {throttled_at})"
        )
        return True

    t = sched.spawn(drive())
    sched.run_until(t.done)
    assert t.done.get()
    rk.stop()
