"""OTEL-style spans: contexts propagate client->proxy->resolver.

The reference threads SpanContexts on every RPC and exports finished
spans (fdbclient/Tracing.actor.cpp; ResolverInterface.h:129 spanContext).
A commit through the sim cluster must yield a proxy commitBatch span
with resolver child spans in the same trace, timed in virtual time.
"""

from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.utils import spans


def test_commit_produces_span_tree():
    exporter = spans.SpanExporter()
    prev = spans.set_exporter(exporter)
    try:
        sched, cluster, db = open_cluster(
            ClusterConfig(n_commit_proxies=1, n_resolvers=2, n_storage=2)
        )

        async def go():
            t = db.create_transaction()
            t.set(b"k", b"v")
            await t.commit()
            return True

        task = sched.spawn(go(), name="drive")
        sched.run_until(task.done)
        assert task.done.get()
        cluster.stop()
    finally:
        spans.set_exporter(prev)

    proxy_spans = [s for s in exporter.finished
                   if s["location"].endswith("commitBatch")]
    assert proxy_spans, exporter.finished
    batch = next(s for s in proxy_spans if s["attributes"].get("txns"))
    children = [
        s for s in exporter.finished
        if s["parent_id"] == batch["span_id"]
        and s["trace_id"] == batch["trace_id"]
    ]
    # both resolver shards resolved under this batch span
    locs = {s["location"] for s in children}
    assert {"resolver0.resolveBatch", "resolver1.resolveBatch"} <= locs
    # spans are timed in virtual time: children nest inside the parent
    for c in children:
        assert batch["begin"] <= c["begin"] <= c["end"] <= batch["end"]


def test_span_codec_roundtrip():
    from foundationdb_tpu.models.types import ResolveTransactionBatchRequest
    from foundationdb_tpu.wire import codec

    req = ResolveTransactionBatchRequest(
        prev_version=0, version=10, last_received_version=0,
        span=(12345, 678),
    )
    got = codec.decode(codec.encode(req))
    assert got.span == (12345, 678)
    req2 = ResolveTransactionBatchRequest(
        prev_version=0, version=10, last_received_version=0)
    assert codec.decode(codec.encode(req2)).span is None
