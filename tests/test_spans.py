"""OTEL-style spans: contexts propagate client->proxy->resolver.

The reference threads SpanContexts on every RPC and exports finished
spans (fdbclient/Tracing.actor.cpp; ResolverInterface.h:129 spanContext).
A commit through the sim cluster must yield a proxy commitBatch span
with resolver child spans in the same trace, timed in virtual time.
"""

from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.utils import spans


def test_commit_produces_span_tree():
    exporter = spans.SpanExporter()
    prev = spans.set_exporter(exporter)
    try:
        sched, cluster, db = open_cluster(
            ClusterConfig(n_commit_proxies=1, n_resolvers=2, n_storage=2)
        )

        async def go():
            t = db.create_transaction()
            t.set(b"k", b"v")
            await t.commit()
            return True

        task = sched.spawn(go(), name="drive")
        sched.run_until(task.done)
        assert task.done.get()
        cluster.stop()
    finally:
        spans.set_exporter(prev)

    proxy_spans = [s for s in exporter.finished
                   if s["location"].endswith("commitBatch")]
    assert proxy_spans, exporter.finished
    batch = next(s for s in proxy_spans if s["attributes"].get("txns"))
    children = [
        s for s in exporter.finished
        if s["parent_id"] == batch["span_id"]
        and s["trace_id"] == batch["trace_id"]
    ]
    # both resolver shards resolved under this batch span
    locs = {s["location"] for s in children}
    assert {"resolver0.resolveBatch", "resolver1.resolveBatch"} <= locs
    # spans are timed in virtual time: children nest inside the parent
    for c in children:
        assert batch["begin"] <= c["begin"] <= c["end"] <= batch["end"]


def test_traced_commit_chains_client_proxy_resolver_tlog():
    """With db.tracing on, one trace runs transaction origin ->
    commitBatch -> resolver children + tlog push child — the
    span-threaded pipeline of ISSUE 5."""
    exporter = spans.SpanExporter()
    prev = spans.set_exporter(exporter)
    try:
        sched, cluster, db = open_cluster(
            ClusterConfig(n_commit_proxies=1, n_resolvers=1, n_storage=2)
        )
        db.tracing = True

        async def go():
            t = db.create_transaction()
            t.set(b"k", b"v")
            await t.commit()
            return True

        task = sched.spawn(go(), name="drive")
        sched.run_until(task.done)
        assert task.done.get()
        cluster.stop()
        sched.run_for(0.1)  # drain cancels: every span finishes in-run
    finally:
        spans.set_exporter(prev)

    by_loc = {}
    for s in exporter.finished:
        by_loc.setdefault(s["location"], []).append(s)
    (commit,) = by_loc["NativeAPI.commit"]
    batch = next(
        s for s in by_loc["proxy0.commitBatch"]
        if s["parent_id"] == commit["span_id"]
    )
    # same trace from origin through batching
    assert batch["trace_id"] == commit["trace_id"]
    resolver = [
        s for s in by_loc["resolver0.resolveBatch"]
        if s["parent_id"] == batch["span_id"]
    ]
    tlog = [
        s for s in by_loc["tlog.push"]
        if s["parent_id"] == batch["span_id"]
    ]
    assert resolver and tlog
    assert all(s["trace_id"] == commit["trace_id"] for s in resolver + tlog)
    # the GRV leg is threaded too: client GRV span -> proxy batch span
    (grv,) = by_loc["NativeAPI.getConsistentReadVersion"]
    grv_batches = [
        s for s in by_loc["GrvProxy.transactionStarter"]
        if s["parent_id"] == grv["span_id"]
    ]
    assert grv_batches
    assert grv_batches[0]["trace_id"] == grv["trace_id"]
    # and the chain passes the offline span checks
    from foundationdb_tpu.utils import commit_debug as cd

    assert cd.check_spans(exporter.finished) == []


def test_cluster_status_surfaces_telemetry():
    """cluster_status(): filled processes section, derived grv proxy
    count, latency bands, and the resolver kernel section (ISSUE 5
    satellite)."""
    import json

    from foundationdb_tpu.cluster.status import cluster_status

    sched, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=1, n_resolvers=1, n_storage=2)
    )

    async def go():
        t = db.create_transaction()
        t.set(b"sk", b"sv")
        await t.commit()
        t2 = db.create_transaction()
        return await t2.get(b"sk")

    task = sched.spawn(go(), name="drive")
    sched.run_until(task.done)
    assert task.done.get() == b"sv"
    status = cluster_status(cluster)["cluster"]
    json.dumps(status)  # JSON-able end to end
    assert status["configuration"]["grv_proxies"] == 1
    procs = status["processes"]
    roles = {p["role"] for p in procs.values()}
    assert roles >= {"resolver", "commit_proxy", "grv_proxy", "storage",
                     "log", "master"}
    # latency bands observed real traffic
    assert status["latency_bands"]["commit"]["total"] >= 1
    assert status["latency_bands"]["grv"]["total"] >= 1
    assert status["latency_bands"]["read"]["total"] >= 1
    assert procs["proxy0"]["latency"]["commit"]["count"] >= 1
    # the kernel stage metrics section exists per resolver
    kern = status["resolver_kernel"]["resolver0"]
    assert "resolveBatches" in kern or kern.get("backend") == "unrouted"
    cluster.stop()


def test_trace_counters_flush_on_virtual_clock():
    """The Scheduler-driven periodic trace_counters loop lands per-role
    counter events in the active TraceLog."""
    from foundationdb_tpu.utils import trace as _tr

    sched = None
    sink = _tr.TraceLog(min_severity=_tr.SEV_DEBUG)
    prev = _tr.install(sink, _tr.TraceBatch())
    try:
        sched, cluster, db = open_cluster(
            ClusterConfig(n_commit_proxies=1, n_resolvers=1, n_storage=2)
        )

        async def go():
            t = db.create_transaction()
            t.set(b"a", b"b")
            await t.commit()

        sched.run_until(sched.spawn(go(), name="drive").done)
        sched.run_for(2.5)  # two flush intervals of virtual time
        cluster.stop()
    finally:
        _tr.install(*prev)
    for ev_type in ("ProxyMetrics", "GrvProxyMetrics", "ResolverMetrics"):
        flushed = sink.find(ev_type)
        assert len(flushed) >= 2, ev_type
    # counter values are real: the proxy flushed its committed count
    assert sink.find("ProxyMetrics")[-1]["txnCommitOut"] >= 1


def test_span_codec_roundtrip():
    from foundationdb_tpu.models.types import ResolveTransactionBatchRequest
    from foundationdb_tpu.wire import codec

    req = ResolveTransactionBatchRequest(
        prev_version=0, version=10, last_received_version=0,
        span=(12345, 678),
    )
    got = codec.decode(codec.encode(req))
    assert got.span == (12345, 678)
    req2 = ResolveTransactionBatchRequest(
        prev_version=0, version=10, last_received_version=0)
    assert codec.decode(codec.encode(req2)).span is None
