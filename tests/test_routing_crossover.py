"""RESOLVER_TPU_MIN_BATCH is the MEASURED routing crossover, not a guess.

VERDICT r4 task 3. The sweep (scripts/sweep_small.py on the real v5e,
logs sweep_small_r5*.log) measured single-dispatch throughput per batch
size; the device first beats the CPU skiplist at n=65536 (347K vs 338K
txn/s device-resident; below that the CPU wins by 2-40x). This test
pins (a) the knob default to that measurement and (b) the
make_conflict_set routing decision on both sides of it.
"""

from __future__ import annotations

from foundationdb_tpu.config import KernelConfig
from foundationdb_tpu.models.conflict_set import (
    CpuConflictSet,
    TpuConflictSet,
    make_conflict_set,
)
from foundationdb_tpu.utils.knobs import SERVER_KNOBS

MEASURED_CROSSOVER = 65536  # scripts/sweep_small.py, r5 device run


def cfg(cap):
    return KernelConfig(
        max_key_bytes=8, max_txns=cap, max_reads=cap, max_writes=cap,
        history_capacity=12 * cap, window_versions=1_000_000,
    )


def test_knob_default_matches_measurement():
    SERVER_KNOBS.reset()
    assert SERVER_KNOBS.RESOLVER_TPU_MIN_BATCH == MEASURED_CROSSOVER


def test_routing_below_crossover_is_cpu():
    SERVER_KNOBS.reset()
    cs = make_conflict_set(cfg(MEASURED_CROSSOVER // 2), backend="tpu")
    assert isinstance(cs, CpuConflictSet)


def test_routing_at_crossover_is_tpu():
    SERVER_KNOBS.reset()
    cs = make_conflict_set(cfg(MEASURED_CROSSOVER), backend="tpu")
    assert isinstance(cs, TpuConflictSet)


def test_force_overrides_measurement():
    SERVER_KNOBS.reset()
    cs = make_conflict_set(cfg(1024), backend="tpu-force")
    assert isinstance(cs, TpuConflictSet)
