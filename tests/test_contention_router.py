"""Contention-profile routing picks the measured winner per regime
(VERDICT r4 task 2): the r5 device runs measured uniform ~2-3x FOR the
TPU kernel, zipf 0.68x and range-heavy 0.28x AGAINST it — so the router
must send hot-key and range-heavy streams to the CPU skiplist and
large-batch uniform streams to the device."""

import numpy as np

from foundationdb_tpu.config import KernelConfig
from foundationdb_tpu.models.conflict_set import (
    backend_for_profile,
    profile_batch,
    route_stream,
)
from foundationdb_tpu.testing.benchgen import skiplist_style_batch
from foundationdb_tpu.utils.knobs import SERVER_KNOBS


def cfg(cap=65536):
    return KernelConfig(
        max_key_bytes=8, max_txns=cap, max_reads=cap, max_writes=cap,
        history_capacity=12 * cap, window_versions=1_000_000,
    )


def gen(mode, n=65536, config=None):
    config = config or cfg()
    rng = np.random.default_rng(3)
    kw = {
        "uniform": dict(keyspace=1_000_000),
        "zipf": dict(zipf=1.1, keyspace=10_000_000),
        "range": dict(range_len=500, keyspace=1_000_000),
    }[mode]
    return [
        skiplist_style_batch(
            rng, config, n, version=(i + 1) * 200_000, key_bytes=8,
            snapshot_lag=400_000, **kw,
        )
        for i in range(2)
    ]


def test_profiles_match_bench_configs():
    assert profile_batch(gen("uniform")[0]) == "uniform"
    assert profile_batch(gen("zipf")[0]) == "hot_key"
    assert profile_batch(gen("range")[0]) == "range_heavy"


def test_router_picks_measured_winner():
    SERVER_KNOBS.reset()
    assert route_stream(gen("uniform"), cfg()) == "tpu"
    assert route_stream(gen("zipf"), cfg()) == "cpu"     # 0.68x measured
    assert route_stream(gen("range"), cfg()) == "cpu"    # 0.28x measured
    # small-batch uniform still routes CPU (the capacity gate)
    small = cfg(4096)
    assert route_stream(gen("uniform", 4096, small), small) == "cpu"


def test_backend_for_profile_table():
    assert backend_for_profile("uniform") == "tpu"
    assert backend_for_profile("hot_key") == "cpu"
    assert backend_for_profile("range_heavy") == "cpu"


def test_backend_for_profile_narrowed_by_kernel_config():
    """The router narrows as the kernel grows the structure each regime
    needs (ISSUE 14: nothing left to route away): tiered+dedup keeps
    hot_key on device, tiered+sweep keeps range_heavy on device; an
    unconfigured kernel still routes both away."""
    import dataclasses

    from foundationdb_tpu.models.conflict_set import fallback_free

    base = cfg()
    dedup = dataclasses.replace(base, delta_capacity=1024, dedup_reads=256)
    sweep = dataclasses.replace(base, delta_capacity=1024, range_sweep=True)
    assert backend_for_profile("hot_key", dedup) == "tpu"
    assert backend_for_profile("hot_key", sweep) == "cpu"
    assert backend_for_profile("range_heavy", sweep) == "tpu"
    assert backend_for_profile("range_heavy", dedup) == "cpu"
    assert backend_for_profile("range_heavy", base) == "cpu"
    # route_stream end-to-end: a range stream stays on device with the
    # sweep configured (the ISSUE-14 acceptance direction), and still
    # routes away without it (the measured-0.28x direction above)
    SERVER_KNOBS.reset()
    assert route_stream(gen("range"), sweep) == "tpu"
    assert not fallback_free(base)
    assert fallback_free(
        dataclasses.replace(sweep, delta_spill=True)
    )


def test_profile_classifiers_agree_on_shared_fixtures():
    """ISSUE 14 satellite bugfix: profile_batch (packed words) and
    profile_transactions (raw key bytes) must classify the SAME
    workload identically — including keyspaces with a LONG common
    prefix, where the old byte-granularity commonprefix strip put the
    two classifiers' 8-byte windows at different offsets (one folded
    the first varying WORD, the other stripped bytes), diverging the
    span/dup thresholds."""
    from foundationdb_tpu.models.conflict_set import (
        profile_transactions,
    )
    from foundationdb_tpu.models.types import CommitTransaction
    from foundationdb_tpu.utils.packing import pack_batch

    rng = np.random.default_rng(17)
    # 11-byte shared prefix + zero-heavy high int bytes: the byte-level
    # commonprefix is NOT word aligned (the old divergence trigger); a
    # 9-byte int keeps the whole key word-aligned so the low word is
    # pure key data (a trailing pad byte would scale every span by 256
    # — identically in both classifiers, but the fixture wants natural
    # spans)
    prefix = b"tenant/ab/\xff"

    def key(v):
        return prefix + int(v).to_bytes(9, "big")

    def txns(mode, n=256):
        out = []
        for i in range(n):
            if mode == "range":
                b = int(rng.integers(0, 1 << 20))
                reads = [(key(b), key(b + 500))]
                writes = [(key(int(rng.integers(0, 1 << 20))),
                           key(int(rng.integers(0, 1 << 20))) + b"\x00")]
            elif mode == "hot":
                hot = int(rng.integers(0, 4))
                reads = [(key(hot), key(hot) + b"\x00")]
                writes = [(key(hot), key(hot) + b"\x00")]
            else:  # uniform points
                b = int(rng.integers(0, 1 << 20)) * 7
                reads = [(key(b), key(b + 1))]
                writes = [(key(b + 1), key(b + 2))]
            out.append(CommitTransaction(
                read_conflict_ranges=reads,
                write_conflict_ranges=writes,
                read_snapshot=50,
            ))
        return out

    config = KernelConfig(
        max_key_bytes=20, max_txns=256, max_reads=256, max_writes=256,
        history_capacity=1 << 12, window_versions=1_000_000,
    )
    want = {"range": "range_heavy", "hot": "hot_key", "uniform": "uniform"}
    for mode, expect in want.items():
        t = txns(mode)
        from_txns = profile_transactions(t)
        from_batch = profile_batch(pack_batch(t, 100, 0, config))
        assert from_txns == from_batch == expect, (
            f"{mode}: txns={from_txns} batch={from_batch} want={expect}"
        )
    # and on the bench generator's zero-padded short keys (the packed
    # representation is WIDER than the raw keys — the other historical
    # divergence class: a constant zero successor word scaled spans)
    for mode in ("uniform", "zipf", "range"):
        b = gen(mode)[0]
        t = _batch_to_txns(b)
        assert profile_transactions(t) == profile_batch(b), mode


def test_dup_detection_is_exact_not_fold_windowed():
    """Keys shaped (few-valued word, constant word, unique word): a
    fold-window dup check collapses them to the few leading values and
    mis-fires hot_key; duplicate detection must compare FULL key rows
    (review finding r14) — and still agree across both classifiers."""
    from foundationdb_tpu.models.conflict_set import profile_transactions
    from foundationdb_tpu.models.types import CommitTransaction
    from foundationdb_tpu.utils.packing import pack_batch

    rng = np.random.default_rng(2)

    def key(i):
        region = int(rng.integers(0, 3))
        return (region.to_bytes(4, "big") + b"\x00\x00\x00\x00"
                + int(i).to_bytes(4, "big"))

    txns = [
        CommitTransaction(
            read_conflict_ranges=[(key(i), key(i) + b"\x00")],
            write_conflict_ranges=[(key(1000 + i), key(1000 + i) + b"\x00")],
            read_snapshot=50,
        )
        for i in range(256)
    ]
    config = KernelConfig(
        max_key_bytes=12, max_txns=256, max_reads=256, max_writes=256,
        history_capacity=1 << 12, window_versions=1_000_000,
    )
    pt = profile_transactions(txns)
    pb = profile_batch(pack_batch(txns, 100, 0, config))
    assert pt == pb == "uniform", (pt, pb)


def _batch_to_txns(batch):
    """Reconstruct CommitTransactions from a benchgen PackedBatch (keys
    unpack from the big-endian words + length word)."""
    from foundationdb_tpu.models.types import CommitTransaction

    def unpack(arr, r):
        row = arr[r]
        length = int(row[-1])
        raw = b"".join(int(w).to_bytes(4, "big") for w in row[:-1])
        return raw[:length]

    txns = {}
    for r in range(batch.n_reads):
        t = int(batch.read_txn[r])
        txns.setdefault(t, ([], []))[0].append(
            (unpack(batch.read_begin, r), unpack(batch.read_end, r))
        )
    for r in range(batch.n_writes):
        t = int(batch.write_txn[r])
        txns.setdefault(t, ([], []))[1].append(
            (unpack(batch.write_begin, r), unpack(batch.write_end, r))
        )
    return [
        CommitTransaction(
            read_conflict_ranges=txns[t][0],
            write_conflict_ranges=txns[t][1],
            read_snapshot=int(batch.snapshot[t]),
        )
        for t in sorted(txns)
    ]


def test_resolver_routes_on_first_batch():
    """The wiring: a Resolver with the tpu knob chooses its backend from
    the FIRST batch's contention profile (one-shot — switching later
    would discard MVCC history; drift only warns)."""
    from foundationdb_tpu.models.conflict_set import (
        CpuConflictSet,
        TpuConflictSet,
    )
    from foundationdb_tpu.models.types import (
        CommitTransaction,
        ResolveTransactionBatchRequest,
    )
    from foundationdb_tpu.resolver import Resolver
    from foundationdb_tpu.runtime.flow import Scheduler

    def hot_txns(n=64):
        return [
            CommitTransaction(
                read_conflict_ranges=[(b"hot", b"hot\x00")],
                write_conflict_ranges=[(b"hot", b"hot\x00")],
                read_snapshot=50,
            )
            for _ in range(n)
        ]

    def uni_txns(n=64):
        return [
            CommitTransaction(
                read_conflict_ranges=[
                    (b"u%06d" % (i * 7), b"u%06d\x00" % (i * 7))
                ],
                write_conflict_ranges=[
                    (b"u%06d" % (i * 7 + 1), b"u%06d\x00" % (i * 7 + 1))
                ],
                read_snapshot=50,
            )
            for i in range(n)
        ]

    def drive(txns):
        sched = Scheduler(sim=True)
        r = Resolver(sched, cfg(65536), backend="tpu")
        assert r.conflict_set is None  # lazily routed
        req = ResolveTransactionBatchRequest(
            prev_version=-1, version=100, last_received_version=-1,
            transactions=txns, proxy_id="p0",
        )
        t = sched.spawn(r.resolve(req))
        sched.run_until(t.done)
        t.done.get()
        return r.conflict_set

    assert isinstance(drive(hot_txns()), CpuConflictSet)
    assert isinstance(drive(uni_txns()), TpuConflictSet)
