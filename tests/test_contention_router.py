"""Contention-profile routing picks the measured winner per regime
(VERDICT r4 task 2): the r5 device runs measured uniform ~2-3x FOR the
TPU kernel, zipf 0.68x and range-heavy 0.28x AGAINST it — so the router
must send hot-key and range-heavy streams to the CPU skiplist and
large-batch uniform streams to the device."""

import numpy as np

from foundationdb_tpu.config import KernelConfig
from foundationdb_tpu.models.conflict_set import (
    backend_for_profile,
    profile_batch,
    route_stream,
)
from foundationdb_tpu.testing.benchgen import skiplist_style_batch
from foundationdb_tpu.utils.knobs import SERVER_KNOBS


def cfg(cap=65536):
    return KernelConfig(
        max_key_bytes=8, max_txns=cap, max_reads=cap, max_writes=cap,
        history_capacity=12 * cap, window_versions=1_000_000,
    )


def gen(mode, n=65536, config=None):
    config = config or cfg()
    rng = np.random.default_rng(3)
    kw = {
        "uniform": dict(keyspace=1_000_000),
        "zipf": dict(zipf=1.1, keyspace=10_000_000),
        "range": dict(range_len=500, keyspace=1_000_000),
    }[mode]
    return [
        skiplist_style_batch(
            rng, config, n, version=(i + 1) * 200_000, key_bytes=8,
            snapshot_lag=400_000, **kw,
        )
        for i in range(2)
    ]


def test_profiles_match_bench_configs():
    assert profile_batch(gen("uniform")[0]) == "uniform"
    assert profile_batch(gen("zipf")[0]) == "hot_key"
    assert profile_batch(gen("range")[0]) == "range_heavy"


def test_router_picks_measured_winner():
    SERVER_KNOBS.reset()
    assert route_stream(gen("uniform"), cfg()) == "tpu"
    assert route_stream(gen("zipf"), cfg()) == "cpu"     # 0.68x measured
    assert route_stream(gen("range"), cfg()) == "cpu"    # 0.28x measured
    # small-batch uniform still routes CPU (the capacity gate)
    small = cfg(4096)
    assert route_stream(gen("uniform", 4096, small), small) == "cpu"


def test_backend_for_profile_table():
    assert backend_for_profile("uniform") == "tpu"
    assert backend_for_profile("hot_key") == "cpu"
    assert backend_for_profile("range_heavy") == "cpu"


def test_resolver_routes_on_first_batch():
    """The wiring: a Resolver with the tpu knob chooses its backend from
    the FIRST batch's contention profile (one-shot — switching later
    would discard MVCC history; drift only warns)."""
    from foundationdb_tpu.models.conflict_set import (
        CpuConflictSet,
        TpuConflictSet,
    )
    from foundationdb_tpu.models.types import (
        CommitTransaction,
        ResolveTransactionBatchRequest,
    )
    from foundationdb_tpu.resolver import Resolver
    from foundationdb_tpu.runtime.flow import Scheduler

    def hot_txns(n=64):
        return [
            CommitTransaction(
                read_conflict_ranges=[(b"hot", b"hot\x00")],
                write_conflict_ranges=[(b"hot", b"hot\x00")],
                read_snapshot=50,
            )
            for _ in range(n)
        ]

    def uni_txns(n=64):
        return [
            CommitTransaction(
                read_conflict_ranges=[
                    (b"u%06d" % (i * 7), b"u%06d\x00" % (i * 7))
                ],
                write_conflict_ranges=[
                    (b"u%06d" % (i * 7 + 1), b"u%06d\x00" % (i * 7 + 1))
                ],
                read_snapshot=50,
            )
            for i in range(n)
        ]

    def drive(txns):
        sched = Scheduler(sim=True)
        r = Resolver(sched, cfg(65536), backend="tpu")
        assert r.conflict_set is None  # lazily routed
        req = ResolveTransactionBatchRequest(
            prev_version=-1, version=100, last_received_version=-1,
            transactions=txns, proxy_id="p0",
        )
        t = sched.spawn(r.resolve(req))
        sched.run_until(t.done)
        t.done.get()
        return r.conflict_set

    assert isinstance(drive(hot_txns()), CpuConflictSet)
    assert isinstance(drive(uni_txns()), TpuConflictSet)
