"""Live resource census (runtime/census.py): the dynamic half of the
resource-ownership gate.

The `res.*` flowcheck family (tests/test_flowcheck.py) proves no code
PATH leaks a resource; this file pins that no RUN does — and, just as
load-bearing, that ARMING the gate perturbs nothing: soak signatures
(trace digest included) must stay bit-identical with the census on,
because census reads never participate in scheduling or tracing."""

import asyncio

import pytest

from foundationdb_tpu.runtime import census


# ---------------------------------------------------------------------------
# Gauges + snapshot mechanics.


def test_gauge_and_snapshot_shape():
    g = census.Gauge("x")
    g.inc()
    g.inc()
    g.dec()
    assert g.value == 1
    snap = census.snapshot()
    assert set(snap) == {"fds", "connections", "servers", "tasks"}
    # /proc/self/fd exists on the CI hosts; elsewhere live_fds() must
    # degrade to the "not measurable" sentinel, never throw
    assert snap["fds"] >= -1
    assert snap["tasks"] == 0  # no Scheduler passed


def test_growth_and_check_drained_semantics():
    pre = {"fds": 8, "connections": 2, "servers": 1, "tasks": 3}
    post = {"fds": 9, "connections": 2, "servers": 0, "tasks": 5}
    leaks = census.growth(pre, post)
    assert leaks == ["fds grew 8 -> 9", "tasks grew 3 -> 5"]
    # ignore set, unmeasurable (-1), and missing keys are all skipped;
    # equality and shrinkage are clean
    assert census.growth(pre, post, ignore={"fds", "tasks"}) == []
    assert census.growth({"fds": -1}, {"fds": 100}) == []
    assert census.growth({"a": 1}, {"b": 2}) == []
    assert census.growth(pre, dict(pre)) == []
    census.check_drained(pre, dict(pre))  # no raise
    with pytest.raises(RuntimeError, match="tasks grew 3 -> 5"):
        census.check_drained(pre, post, ignore={"fds"}, label="unit")


# ---------------------------------------------------------------------------
# Transport gauges: the wire layer's own accounting.


def test_transport_gauges_track_connect_and_close(tmp_path):
    from foundationdb_tpu.cluster.multiprocess import TOKEN_PING, Ping, Pong
    from foundationdb_tpu.wire import transport

    sock = str(tmp_path / "role.sock")

    async def scenario():
        c0 = census.CONNECTIONS.value
        s0 = census.SERVERS.value
        server = transport.RpcServer(sock)

        async def ping(msg):
            return Pong(payload=msg.payload)

        server.register(TOKEN_PING, ping)
        await server.start()
        assert census.SERVERS.value == s0 + 1
        conn = transport.RpcConnection(sock)
        assert census.CONNECTIONS.value == c0  # constructed != activated
        await conn.connect()
        assert census.CONNECTIONS.value == c0 + 1
        await conn.call(TOKEN_PING, Ping(payload=b"x"))
        await conn.close()
        await conn.close()  # idempotent: the gauge must not go double-dec
        assert census.CONNECTIONS.value == c0
        await server.close()
        await server.close()
        assert census.SERVERS.value == s0

    asyncio.new_event_loop().run_until_complete(scenario())


# ---------------------------------------------------------------------------
# Scheduler task accounting: tasks_live retires exactly once.


def test_tasks_live_retires_on_every_terminal_path():
    from foundationdb_tpu.runtime.flow import ActorCancelled, Scheduler

    sched = Scheduler(sim=True)
    assert sched.run_loop_stats()["tasks_live"] == 0

    async def ok():
        await sched.delay(0.01)

    async def boom():
        await sched.delay(0.01)
        raise ValueError("x")

    async def forever():
        await sched.delay(10**6)

    t_ok = sched.spawn(ok())
    t_boom = sched.spawn(boom())
    t_fore = sched.spawn(forever())
    assert sched.run_loop_stats()["tasks_live"] == 3
    sched.run_for(0.1)
    # ok completed, boom errored — both retired; forever is still live
    assert sched.run_loop_stats()["tasks_live"] == 1
    t_fore.cancel()
    sched.run_for(0.1)
    assert sched.run_loop_stats()["tasks_live"] == 0
    # consume the futures so the error ledger stays clean
    async def drain():
        await t_ok
        with pytest.raises(ValueError):
            await t_boom
        with pytest.raises(ActorCancelled):
            await t_fore

    sched.run_until(sched.spawn(drain()).done)


# ---------------------------------------------------------------------------
# The armed gate: catches a leak, perturbs nothing.


def test_census_gate_fails_a_seed_with_a_lingering_task():
    """A fire-and-forget actor still live after drain is a TASK LEAK:
    the armed census gate must fail the seed, naming the gauge."""
    from foundationdb_tpu.testing.soak import run_seed

    async def linger(sched, cluster, db):
        await sched.delay(10**6)

    with pytest.raises(RuntimeError, match="tasks grew"):
        run_seed(3, spec="smoke", census=True, _inject_fault=linger)
    # and the same seed WITHOUT the lingering task passes armed
    assert run_seed(3, spec="smoke", census=True)


def test_census_armed_seed_is_bit_identical():
    """Fast shape of the determinism pin: arming the census gate leaves
    the signature (trace digest included) bit-identical, FIFO and
    perturbed. The 20-seed sweep lives in the slow lane below."""
    from foundationdb_tpu.testing.soak import run_seed

    for perturb in (0, 1):
        armed = run_seed(7, spec="smoke", trace=True, census=True,
                         perturb=perturb)
        plain = run_seed(7, spec="smoke", trace=True, perturb=perturb)
        assert armed == plain, f"census perturbed seed 7/{perturb}"


@pytest.mark.slow
def test_census_determinism_sweep_20_seeds():
    """The round-18 acceptance sweep: 20 seeds x 2 perturbations with
    the census gate ARMED — every (seed, perturb) passes the gate (no
    resource growth across the whole ensemble) and stays bit-identical
    with the unarmed run."""
    from foundationdb_tpu.testing.soak import run_seed

    for seed in range(20):
        for perturb in (0, 1):
            armed = run_seed(seed, spec="smoke", trace=True, census=True,
                             perturb=perturb)
            plain = run_seed(seed, spec="smoke", trace=True,
                             perturb=perturb)
            assert armed == plain, (
                f"seed {seed} perturb {perturb}: census-armed signature "
                "diverged from the unarmed run"
            )
