"""ISSUE 15: the ledger-driven knob autotuner (utils/autotune.py) and
the `experiment` row discipline in the perf schema (utils/perf.py).

Pinned here:

* resumability — killing a search mid-sweep and re-running completes
  ONLY the missing trials (fingerprint-cache hit counts pinned), both
  in-process and across "sessions" (fresh run_search over the same
  ledger file);
* experiment exclusion BOTH directions — a trial row is never selected
  into a normal candidate's baseline window, and a trial row can never
  be accepted as a committed baseline (perfcheck --accept exits 1);
* the promote flow — the winner re-emits without the experiment marker
  and then IS acceptable;
* schema byte-stability — records built without `experiment` carry no
  new key (the committed-ledger re-import contract is untouched).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from foundationdb_tpu.utils import autotune, perf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fake_row(knobs: dict, value: float, *, metric="txn_s",
             direction="higher", source="bench", workload=None) -> dict:
    return perf.make_record(
        source,
        {metric: perf.metric(value, "txn/s", direction,
                             tier="structural")},
        workload=workload or {"metric": "m"},
        knobs=knobs,
        fingerprint={
            "backend": "cpu", "device_kind": None, "device_count": 0,
            "jax_version": None, "jaxlib_version": None,
            "python_version": None, "machine": None,
        },
        git_sha="t", timestamp=0.0,
    )


@pytest.fixture
def ledger(tmp_path):
    return str(tmp_path / "search.jsonl")


# ---------------------------------------------------------------------------
# schema: the experiment field


def test_experiment_field_roundtrip_and_validation():
    rec = perf.make_record(
        "bench", {"m": perf.metric(1, "x", "higher", tier="structural")},
        fingerprint=fake_row({}, 0)["fingerprint"], git_sha="t",
        timestamp=0.0, experiment="s1",
    )
    assert rec["experiment"] == "s1"
    perf.validate_record(rec)
    bad = dict(rec, experiment="")
    with pytest.raises(ValueError, match="experiment"):
        perf.validate_record(bad)


def test_no_experiment_key_when_absent():
    """Byte-stability: non-trial rows must not grow a new key (the
    committed-ledger-matches-reimport pin depends on it)."""
    rec = fake_row({"fuse": 8}, 1.0)
    assert "experiment" not in rec
    assert "experiment" not in json.dumps(rec)


def test_baseline_window_excludes_experiment_rows():
    """Direction 1: trials never gate a normal candidate."""
    normal = [fake_row({"fuse": 8}, 100.0) for _ in range(3)]
    trial = dict(fake_row({"fuse": 8}, 5.0), experiment="s1")
    cand = fake_row({"fuse": 8}, 99.0)
    window = perf.baseline_window(
        normal + [trial], cand, tier="structural"
    )
    assert trial not in window and len(window) == 3
    # and through compare(): the trial's awful 5.0 must not drag the
    # median (structural exact compare would flag 99 vs median 5 as
    # improvement-or-regression depending on direction — either way a
    # polluted window changes the report)
    rep = perf.compare(cand, normal + [trial], tier="structural")
    rep2 = perf.compare(cand, normal, tier="structural")
    assert rep["metrics"] == rep2["metrics"]


def test_perfcheck_accept_refuses_experiment_rows(tmp_path):
    """Direction 2: a trial row can never become a committed baseline."""
    hist = tmp_path / "history.jsonl"
    cand_path = tmp_path / "cand.jsonl"
    trial = dict(fake_row({"fuse": 8}, 5.0), experiment="s1")
    with open(cand_path, "w") as f:
        f.write(json.dumps(trial, sort_keys=True) + "\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perfcheck.py"),
         "--check", str(cand_path), "--accept", "--history", str(hist)],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "experiment" in proc.stderr
    assert not os.path.exists(hist) or not perf.load_history(str(hist))
    # the promoted twin (marker stripped) IS acceptable
    promoted = autotune.promote_record(trial)
    with open(cand_path, "w") as f:
        f.write(json.dumps(promoted, sort_keys=True) + "\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perfcheck.py"),
         "--check", str(cand_path), "--accept", "--history", str(hist)],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert len(perf.load_history(str(hist))) == 1


# ---------------------------------------------------------------------------
# the search loop


def objective_table(table):
    """run_trial from a {trial_key: value} table, counting invocations."""
    calls = []

    def run(knobs):
        calls.append(dict(knobs))
        return fake_row(knobs, table[autotune.trial_key(knobs)])

    run.calls = calls
    return run


def test_search_space_enumeration_deterministic():
    space = autotune.SearchSpace({"a": (1, 2), "b": ("x", "y")})
    assert space.points() == [
        {"a": 1, "b": "x"}, {"a": 1, "b": "y"},
        {"a": 2, "b": "x"}, {"a": 2, "b": "y"},
    ]
    assert len(space) == 4


def test_run_search_lands_experiment_rows_and_picks_winner(ledger):
    space = autotune.SearchSpace({"fuse": (8, 16, 32)})
    table = {
        autotune.trial_key({"fuse": 8}): 10.0,
        autotune.trial_key({"fuse": 16}): 30.0,
        autotune.trial_key({"fuse": 32}): 20.0,
    }
    run = objective_table(table)
    rep = autotune.run_search(
        "s1", space, run, objective_metric="txn_s", ledger=ledger,
    )
    assert rep.best.knobs == {"fuse": 16}
    assert rep.ran == 3 and rep.cache_hits == 0
    assert rep.stopped == "exhausted"
    rows = perf.load_history(ledger)
    assert len(rows) == 3
    assert all(r["experiment"] == "s1" for r in rows)
    assert all(r["extra"]["trial_key"] for r in rows)


def test_resumability_mid_sweep_kill(ledger):
    """Kill the search after trial 2 of 4; the re-run completes only
    the missing trials — cache-hit counts pinned both runs."""
    space = autotune.SearchSpace({"fuse": (8, 16, 32, 64)})
    table = {
        autotune.trial_key({"fuse": f}): float(f) for f in (8, 16, 32, 64)
    }
    boom = RuntimeError("killed")

    killed = []

    def dying(knobs):
        if len(killed) >= 2:
            raise KeyboardInterrupt  # the mid-sweep kill
        killed.append(knobs)
        return fake_row(knobs, table[autotune.trial_key(knobs)])

    with pytest.raises(KeyboardInterrupt):
        autotune.run_search(
            "s2", space, dying, objective_metric="txn_s", ledger=ledger,
        )
    assert len(perf.load_history(ledger)) == 2  # two trials survived

    run = objective_table(table)
    rep = autotune.run_search(
        "s2", space, run, objective_metric="txn_s", ledger=ledger,
    )
    assert rep.cache_hits == 2 and rep.ran == 2
    assert run.calls == [{"fuse": 32}, {"fuse": 64}]  # ONLY the missing
    assert rep.best.knobs == {"fuse": 64}

    # third run: 100% cache hit
    run2 = objective_table(table)
    rep2 = autotune.run_search(
        "s2", space, run2, objective_metric="txn_s", ledger=ledger,
    )
    assert rep2.cache_hits == 4 and rep2.ran == 0 and not run2.calls
    assert rep2.best.knobs == {"fuse": 64}
    del boom


def test_cache_is_per_experiment(ledger):
    """Two searches over the same knob point do not share trials."""
    space = autotune.SearchSpace({"fuse": (8,)})
    table = {autotune.trial_key({"fuse": 8}): 1.0}
    autotune.run_search("a", space, objective_table(table),
                        objective_metric="txn_s", ledger=ledger)
    run = objective_table(table)
    rep = autotune.run_search("b", space, run,
                              objective_metric="txn_s", ledger=ledger)
    assert rep.ran == 1 and run.calls


def test_cache_scope_device_rejects_foreign_fingerprint(ledger):
    """A hardware-objective search must not resume from another
    machine's trial rows."""
    space = autotune.SearchSpace({"fuse": (8,)})
    foreign = dict(fake_row({"fuse": 8}, 1.0), experiment="s3")
    foreign["fingerprint"] = dict(
        foreign["fingerprint"], device_kind="TPU v5e", backend="tpu",
    )
    foreign["extra"] = {"trial_key": autotune.trial_key({"fuse": 8})}
    perf.append(foreign, path=ledger)
    run = objective_table({autotune.trial_key({"fuse": 8}): 2.0})
    rep = autotune.run_search(
        "s3", space, run, objective_metric="txn_s", ledger=ledger,
        cache_scope="device",
    )
    assert rep.ran == 1, "foreign-device trial must not satisfy the cache"
    # scope "any" DOES resume from it
    rep2 = autotune.run_search(
        "s3", space, objective_table({}), objective_metric="txn_s",
        ledger=ledger, cache_scope="any",
    )
    assert rep2.ran == 0 and rep2.cache_hits == 1


def test_lower_is_better_objective_negated(ledger):
    space = autotune.SearchSpace({"cap": (1, 2)})

    def run(knobs):
        return fake_row(knobs, {1: 5.0, 2: 2.0}[knobs["cap"]],
                        metric="spills", direction="lower")

    rep = autotune.run_search("s4", space, run,
                              objective_metric="spills", ledger=ledger)
    assert rep.best.knobs == {"cap": 2}  # fewer spills wins


def test_failed_trial_recorded_not_fatal(ledger):
    space = autotune.SearchSpace({"fuse": (8, 16)})

    def run(knobs):
        if knobs["fuse"] == 8:
            raise RuntimeError("harness exploded")
        return fake_row(knobs, 1.0)

    rep = autotune.run_search("s5", space, run,
                              objective_metric="txn_s", ledger=ledger)
    assert rep.trials[0].error and rep.trials[0].record is None
    assert rep.best.knobs == {"fuse": 16}
    assert len(perf.load_history(ledger)) == 1  # no row for the failure


def test_no_improve_stop(ledger):
    space = autotune.SearchSpace({"fuse": (1, 2, 3, 4, 5)})
    table = {autotune.trial_key({"fuse": f}): 10.0 - f for f in range(1, 6)}
    rep = autotune.run_search(
        "s6", space, objective_table(table), objective_metric="txn_s",
        ledger=ledger, no_improve_limit=2,
    )
    assert rep.stopped == "no_improve"
    assert len(rep.trials) == 3  # best at fuse=1, then 2 non-improving


def test_roofline_stop(ledger):
    """A trial achieving >= roofline_frac of the bytes-bound ceiling
    stops the search before exhaustion."""
    space = autotune.SearchSpace({"fuse": (8, 16, 32)})

    def run(knobs):
        rec = fake_row(knobs, 1000.0)
        rec["fingerprint"]["device_kind"] = "TPU v5e"
        rec["extra"] = {"hlo_cost": {"bytes_accessed": 8.19e8}}
        return rec

    # roofline = 1024 txns / (8.19e8 / 8.19e11 s) = 1.024e6 txn/s;
    # achieved 1000 of it -> tiny frac; arm a tiny roofline_frac so the
    # first trial satisfies it
    rep = autotune.run_search(
        "s7", space, run, objective_metric="txn_s", ledger=ledger,
        roofline_txns_per_dispatch=1024, roofline_frac=9e-4,
    )
    assert rep.stopped == "roofline"
    assert len(rep.trials) == 1
    assert rep.roofline == pytest.approx(1024 / (8.19e8 / 8.19e11))


def test_roofline_unavailable_on_unknown_device():
    assert autotune.roofline_txn_s(
        {"bytes_accessed": 1e9},
        {"device_kind": None}, 1024,
    ) is None
    assert autotune.roofline_txn_s({}, {"device_kind": "TPU v5e"}, 1024) \
        is None


def test_promote_record_strips_markers():
    trial = dict(fake_row({"fuse": 8}, 1.0), experiment="s8")
    trial["extra"] = {"trial_key": "k", "note": "keep"}
    out = autotune.promote_record(trial)
    assert "experiment" not in out
    assert out["extra"] == {"note": "keep"}
    trial2 = dict(fake_row({"fuse": 8}, 1.0), experiment="s8")
    trial2["extra"] = {"trial_key": "k"}
    assert "extra" not in autotune.promote_record(trial2)


def test_knob_env_override_hook():
    """The FDBTPU_KNOB_OVERRIDES hook the pipeline harness trials ride."""
    from foundationdb_tpu.utils.knobs import make_server_knobs

    k = make_server_knobs()
    default = k.COMMIT_TRANSACTION_BATCH_COUNT_MAX
    os.environ["FDBTPU_KNOB_OVERRIDES"] = (
        "COMMIT_TRANSACTION_BATCH_COUNT_MAX=1234"
    )
    try:
        applied = k.apply_env_overrides()
    finally:
        del os.environ["FDBTPU_KNOB_OVERRIDES"]
    assert applied == {"COMMIT_TRANSACTION_BATCH_COUNT_MAX": 1234}
    assert k.COMMIT_TRANSACTION_BATCH_COUNT_MAX == 1234 != default
    with pytest.raises(KeyError):
        os.environ["FDBTPU_KNOB_OVERRIDES"] = "NO_SUCH_KNOB=1"
        try:
            k.apply_env_overrides()
        finally:
            del os.environ["FDBTPU_KNOB_OVERRIDES"]


def test_knob_env_override_bool_parsing():
    """bool('False') is True — the env hook must parse boolean knobs
    for real, and reject unrecognized spellings instead of silently
    enabling them."""
    from foundationdb_tpu.utils.knobs import Knobs

    k = Knobs("test")
    k.define("FLAG", True)
    for spelling, want in (("false", False), ("0", False), ("off", False),
                           ("true", True), ("1", True), ("ON", True)):
        os.environ["FDBTPU_KNOB_OVERRIDES"] = f"FLAG={spelling}"
        try:
            applied = k.apply_env_overrides()
        finally:
            del os.environ["FDBTPU_KNOB_OVERRIDES"]
        assert applied == {"FLAG": want}, spelling
        assert k.FLAG is want
    os.environ["FDBTPU_KNOB_OVERRIDES"] = "FLAG=maybe"
    try:
        with pytest.raises(ValueError, match="boolean"):
            k.apply_env_overrides()
    finally:
        del os.environ["FDBTPU_KNOB_OVERRIDES"]


# ---------------------------------------------------------------------------
# the CLI layer: space-vs-harness validation + batch routing


def _load_cli():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "autotune_cli", os.path.join(REPO, "scripts", "autotune.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_validate_space_rejects_unconsumed_knob_family():
    """A knob the target harness silently ignores would make every
    trial measure the identical default configuration — the noise
    'winner' could then be promoted into the committed baseline. The
    CLI must reject the mismatch up front, both directions."""
    cli = _load_cli()
    # bench_pipeline reads no BENCH_* env var
    with pytest.raises(SystemExit, match="bench_pipeline reads no"):
        cli.validate_space({"fuse": (8, 64)}, "bench_pipeline")
    # bench.py consumes neither server knobs nor --batch
    with pytest.raises(SystemExit, match="consumes neither"):
        cli.validate_space(
            {"knob.COMMIT_TRANSACTION_BATCH_COUNT_MAX": (4096,)}, "bench"
        )
    with pytest.raises(SystemExit, match="consumes neither"):
        cli.validate_space({"batch": (64,)}, "bench")
    with pytest.raises(SystemExit, match="unknown bench knob"):
        cli.validate_space({"typo": (1,)}, "bench")
    # the legitimate families pass
    cli.validate_space(
        {"fuse": (8, 64), "path": ("range_sweep", "dedup")}, "bench"
    )
    cli.validate_space(
        {"knob.GRV_PROXY_MAX_QUEUE": (64,), "batch": (256, 1024)},
        "bench_pipeline",
    )


def test_pipeline_runner_routes_batch_to_cli(monkeypatch, tmp_path):
    """A `batch` grid point rides bench_pipeline's --batch flag, never
    the env builder (which would reject it as an unknown knob and kill
    the whole sweep on trial 1)."""
    cli = _load_cli()

    class _Args:
        mode = "cluster"
        clients = 2
        ops = 3
        backend = "native"
        trial_timeout = 5.0
        verbose = False

    seen = {}

    def fake_run(cmd, **kw):
        seen["cmd"] = cmd
        # the runner reads the trial row back from --perf-ledger
        ledger = cmd[cmd.index("--perf-ledger") + 1]
        with open(ledger, "w") as f:
            f.write(json.dumps(fake_row({"b": 1}, 5.0)) + "\n")

    monkeypatch.setattr(cli.subprocess, "run", fake_run)
    runner = cli.make_pipeline_runner(_Args())
    knobs = {"batch": 512, "knob.GRV_PROXY_MAX_QUEUE": 64}
    row = runner(dict(knobs))
    assert row["metrics"]["txn_s"]["value"] == 5.0
    cmd = seen["cmd"]
    assert cmd[cmd.index("--batch") + 1] == "512"
    # batch stayed off the env surface; the server knob rode it
    assert knobs == {"batch": 512, "knob.GRV_PROXY_MAX_QUEUE": 64}
