"""The shared generation/epoch state machine (cluster/generation.py):
the one module both the sim ClusterController and the wire
ClusterControllerRole drive, so sim and wire recovery cannot drift."""

import pytest

from foundationdb_tpu.cluster import generation as gen


def test_recovery_version_rule():
    assert gen.recovery_version_for(0) == gen.RECOVERY_VERSION_GAP
    assert gen.recovery_version_for(5, 9, 2) == 9 + gen.RECOVERY_VERSION_GAP
    # -1 (an empty tlog's version) never drags the version negative
    assert gen.recovery_version_for(-1) == gen.RECOVERY_VERSION_GAP


def test_conservative_recovery_transaction_shape():
    txn = gen.conservative_recovery_transaction(1_000_000)
    # the whole-keyspace blind write: no reads (always commits), one
    # write range covering everything, snapshot at the recovery version
    assert txn.read_conflict_ranges == []
    assert txn.write_conflict_ranges == [gen.CONSERVATIVE_ABORT_RANGE]
    assert txn.read_snapshot == 1_000_000
    assert gen.CONSERVATIVE_ABORT_RANGE == (b"", b"\xff\xff")
    txn.validate()


def test_stale_epoch_marker_roundtrip():
    msg = gen.stale_epoch_message(3, 7)
    assert gen.is_stale_epoch(msg)
    assert gen.is_stale_epoch(RuntimeError(msg))
    assert not gen.is_stale_epoch("connection lost")


def test_generation_state_walk_and_timeline():
    clock = iter(range(100))
    g = gen.GenerationState(epoch=1, clock=lambda: float(next(clock)))
    assert g.status == gen.FULLY_RECOVERED
    assert g.begin_recovery() == 2
    for s in gen.RECOVERY_STATES[1:]:
        g.transition(s)
    assert g.status == gen.FULLY_RECOVERED
    rows = g.timeline_dicts()
    assert [r["status"] for r in rows] == list(gen.RECOVERY_STATES)
    assert all(r["epoch"] == 2 for r in rows)
    # floor: a restarted controller with a persisted epoch always bumps
    # strictly past it
    assert g.begin_recovery(floor=10) == 11
    with pytest.raises(ValueError):
        g.transition("not_a_state")


def test_timeline_cap_bounds_memory():
    g = gen.GenerationState(epoch=1, clock=lambda: 0.0, timeline_cap=4)
    for _ in range(5):
        g.begin_recovery()
    assert len(g.timeline) == 4


def test_recovery_timeline_from_trace_records():
    records = [
        {"Type": "MasterRecoveryState", "Time": 2.0, "Epoch": 2,
         "StatusCode": gen.FULLY_RECOVERED},
        {"Type": "SomethingElse", "Time": 1.5},
        {"Type": "MasterRecoveryState", "Time": 1.0, "Epoch": 2,
         "StatusCode": gen.READING_TRANSACTION_SYSTEM_STATE},
    ]
    rows = gen.recovery_timeline_from_trace(records)
    assert [r["status"] for r in rows] == [
        gen.READING_TRANSACTION_SYSTEM_STATE, gen.FULLY_RECOVERED
    ]
    assert rows[0]["time"] == 1.0 and rows[1]["epoch"] == 2


def test_sim_controller_emits_shared_timeline():
    """The sim ClusterController walks the SHARED state machine: after
    a recovery, its GenerationState timeline holds the canonical walk
    at the bumped epoch — the same rows the wire controller serves in
    its status block."""
    from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster

    sched, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=2, n_resolvers=2, n_storage=2)
    )
    try:
        async def body():
            txn = db.create_transaction()
            txn.set(b"k", b"v")
            await txn.commit()
            p = cluster.commit_proxies[0]
            p.failed = RuntimeError("chaos")
            p.stop()
            await sched.delay(1.0)

        sched.run_until(sched.spawn(body()).done)
        cc = cluster.controller
        assert cc.epoch == 2
        walk = [
            r["status"] for r in cc.gen.timeline_dicts()
            if r["epoch"] == 2
        ]
        assert walk == list(gen.RECOVERY_STATES)
        assert cc.gen.recovery_version > 0
    finally:
        cluster.stop()
