"""Hermeticity contract for the graded multichip dryrun.

Rounds 1-2 both failed `dryrun_multichip` the same way: the parent
process's default backend is a tunneled TPU with a version-skewed AOT
libtpu, and some eager jnp op (state init, batch packing) escaped to it.
The contract now is: the dryrun body NEVER runs in a process whose
default backend could be anything but CPU — it unconditionally re-execs
into a child with ``JAX_PLATFORMS=cpu`` and the tunnel sitecustomize's
trigger variable stripped, and the child asserts its default backend.

Reference analog: the multi-resolver split these shardings implement is
`fdbserver/CommitProxyServer.actor.cpp:1551-1567`.
"""

from __future__ import annotations

import subprocess
import sys

import pytest


def _graft():
    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
    import __graft_entry__ as G

    return G


def test_dryrun_parent_never_runs_body_in_process(monkeypatch):
    """Without the sentinel, the parent must delegate — not build a mesh."""
    G = _graft()
    from foundationdb_tpu.parallel import mesh as M

    calls = []
    monkeypatch.delenv(M._SUBPROCESS_SENTINEL, raising=False)
    monkeypatch.setattr(
        M, "run_in_cpu_subprocess", lambda m, f, n: calls.append((m, f, n))
    )
    G.dryrun_multichip(8)
    assert calls == [("__graft_entry__", "dryrun_multichip", 8)]


def test_cpu_subprocess_env_is_hermetic(monkeypatch):
    """The child env pins CPU, strips the TPU-plugin trigger, sets the
    sentinel, and requests the right virtual device count."""
    from foundationdb_tpu.parallel import mesh as M

    captured = {}

    def fake_run(cmd, env=None, **kw):
        captured["cmd"], captured["env"] = cmd, env

        class P:
            returncode = 0
            stdout = ""
            stderr = ""

        return P()

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    M.run_in_cpu_subprocess("somemod", "somefunc", 4)

    env = captured["env"]
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "PALLAS_AXON_POOL_IPS" not in env
    assert env[M._SUBPROCESS_SENTINEL] == "1"
    assert "--xla_force_host_platform_device_count=4" in env["XLA_FLAGS"]
    assert captured["cmd"][0] == sys.executable


@pytest.mark.kernel
def test_dryrun_end_to_end(tmp_path, monkeypatch):
    """The real thing: exactly what the driver runs, asserting rc=0.

    Cheap because the child's tiny-shape compiles hit the persistent
    per-machine compile cache after the first run. The perf ledger is
    redirected to a tempfile (the env rides into the hermetic child):
    a DRIVER dryrun must land its fingerprinted multichip row in
    perf/history.jsonl, a TEST run must not dirty the committed
    history — and the row's shape is pinned here either way.
    """
    ledger = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("FDBTPU_PERF_LEDGER", ledger)
    G = _graft()
    G.dryrun_multichip(8)
    import json

    rows = [json.loads(x) for x in open(ledger)]
    assert len(rows) == 1 and rows[0]["source"] == "multichip"
    assert rows[0]["workload"]["n_devices"] == 8
    assert rows[0]["workload"]["kernel"] == "tiered_sharded"
    assert rows[0]["metrics"]["ok"]["value"] == 1
    assert rows[0]["metrics"]["txn_s"]["tier"] == "hardware"
    assert rows[0]["metrics"]["committed"]["tier"] == "structural"
