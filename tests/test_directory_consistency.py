"""Directory layer, special key space, and consistency check tests."""

import numpy as np
import pytest

from foundationdb_tpu.cluster.consistency import check_cluster
from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.layers.directory import (
    DirectoryAlreadyExists,
    DirectoryDoesNotExist,
    DirectoryLayer,
)


def run(sched, coro):
    return sched.run_until(sched.spawn(coro).done)


@pytest.fixture
def world():
    sched, cluster, db = open_cluster(ClusterConfig(n_storage=2))
    yield sched, cluster, db
    cluster.stop()


def test_directory_create_open_list(world):
    sched, cluster, db = world
    # seeded rng: deterministic-sim tests must replay identically
    dl = DirectoryLayer(rng=np.random.default_rng(0))

    async def body():
        txn = db.create_transaction()
        users = await dl.create_or_open(txn, ("app", "users"))
        logs = await dl.create_or_open(txn, ("app", "logs"))
        txn.set(users.pack((42,)), b"alice")
        txn.set(logs.pack((1,)), b"started")
        await txn.commit()

        txn = db.create_transaction()
        users2 = await dl.open(txn, ("app", "users"))
        assert users2.key == users.key
        val = await txn.get(users2.pack((42,)))
        children = await dl.list(txn, ("app",))
        top = await dl.list(txn)
        return val, sorted(children), top

    val, children, top = run(sched, body())
    assert val == b"alice"
    assert children == ["logs", "users"]
    assert top == ["app"]


def test_directory_errors_and_move_remove(world):
    sched, cluster, db = world
    # seeded rng: deterministic-sim tests must replay identically
    dl = DirectoryLayer(rng=np.random.default_rng(0))

    async def body():
        txn = db.create_transaction()
        d = await dl.create(txn, ("a", "b"))
        txn.set(d.pack(("k",)), b"v")
        await txn.commit()

        txn = db.create_transaction()
        with pytest.raises(DirectoryAlreadyExists):
            await dl.create(txn, ("a", "b"))
        with pytest.raises(DirectoryDoesNotExist):
            await dl.open(txn, ("nope",))

        moved = await dl.move(txn, ("a", "b"), ("a", "c"))
        assert await txn.get(moved.pack(("k",))) == b"v"
        await txn.commit()

        txn = db.create_transaction()
        assert await dl.find(txn, ("a", "b")) is None
        await dl.remove(txn, ("a",))
        await txn.commit()

        txn = db.create_transaction()
        return await dl.find(txn, ("a", "c")), await txn.get(moved.pack(("k",)))

    gone_dir, gone_val = run(sched, body())
    assert gone_dir is None
    assert gone_val is None


def test_special_key_space(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        txn.set(b"x", b"1")
        await txn.commit()
        txn = db.create_transaction()
        status = await txn.get(b"\xff\xff/status/json")
        epoch = await txn.get(b"\xff\xff/cluster/epoch")
        missing = await txn.get(b"\xff\xff/unknown")
        return status, epoch, missing

    status, epoch, missing = run(sched, body())
    import json

    assert json.loads(status)["cluster"]["configuration"]["resolver_backend"] == "tpu"
    assert epoch == b"1"
    assert missing is None


def test_consistency_check_clean_and_after_moves(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        for i in range(30):
            txn.set(b"cc%02d" % i, b"v")
        await txn.commit()
        await sched.delay(0.05)
        stats1 = check_cluster(cluster)

        await cluster.data_distributor.move_shard(b"cc10", b"cc20", 1)
        await sched.delay(0.2)  # let the deferred drop land
        stats2 = check_cluster(cluster)
        return stats1, stats2

    stats1, stats2 = run(sched, body())
    assert stats1["keys_checked"] >= 30
    assert stats2["shards_checked"] >= 3  # the move split the map


def test_consistency_check_detects_corruption(world):
    sched, cluster, db = world

    async def body():
        txn = db.create_transaction()
        txn.set(b"zz", b"v")
        await txn.commit()
        await sched.delay(0.05)

    run(sched, body())
    ss = cluster.storage_servers[cluster.key_servers.shard_of(b"zz")]
    ss._live_count += 1  # simulate accounting corruption
    with pytest.raises(Exception):
        check_cluster(cluster)
    ss._live_count -= 1
    check_cluster(cluster)  # clean again


def test_hca_concurrent_allocations_unique():
    """The high-contention allocator: concurrent transactions allocate
    DISTINCT prefixes, conflicting only on same-candidate collisions
    (the bindings' HighContentionAllocator semantics)."""
    import numpy as np

    from foundationdb_tpu.cluster.commit_proxy import NotCommitted
    from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
    from foundationdb_tpu.layers.directory import HighContentionAllocator

    sched, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=2, n_storage=2)
    )
    hca = HighContentionAllocator(np.random.default_rng(0))
    allocated = []
    conflicts = [0]

    async def worker(wid):
        for _ in range(15):
            while True:
                txn = db.create_transaction()
                n = await hca.allocate(txn)
                try:
                    await txn.commit()
                    allocated.append(n)
                    break
                except NotCommitted:
                    conflicts[0] += 1

    from foundationdb_tpu.runtime.flow import all_of

    tasks = [sched.spawn(worker(w), name=f"hca{w}") for w in range(6)]
    sched.run_until(all_of([t.done for t in tasks]))
    for t in tasks:
        t.done.get()
    assert len(allocated) == 90
    assert len(set(allocated)) == 90, "HCA handed out a duplicate"
    cluster.stop()


def test_hca_window_advances():
    import numpy as np

    from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
    from foundationdb_tpu.layers.directory import HighContentionAllocator

    sched, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=1, n_storage=2)
    )
    hca = HighContentionAllocator(np.random.default_rng(1))

    async def go():
        got = []
        for _ in range(100):  # > half of the initial 64-window
            txn = db.create_transaction()
            got.append(await hca.allocate(txn))
            await txn.commit()
        return got

    t = sched.spawn(go(), name="drive")
    sched.run_until(t.done)
    got = t.done.get()
    assert len(set(got)) == 100
    assert max(got) >= 64, "window never advanced"
    cluster.stop()
