"""TLog spill discipline (VERDICT r3 missing #3): a lagging consumer
bounds tlog MEMORY, not correctness.

fdbserver/TLogServer.actor.cpp:2311 + DiskQueue spill-by-reference: when
retained mutations exceed SERVER_KNOBS.TLOG_SPILL_THRESHOLD, the oldest
unpopped versions evict from memory; per-tag (version, seq) indexes
point into the DiskQueue and peeks read them back off "disk".
"""

from __future__ import annotations

import pytest

from foundationdb_tpu.cluster.tlog import TLog, TLogCommitRequest
from foundationdb_tpu.runtime.flow import Scheduler
from foundationdb_tpu.sim.diskqueue import SimDiskQueue
from foundationdb_tpu.utils.knobs import SERVER_KNOBS


@pytest.fixture
def small_budget():
    old = SERVER_KNOBS.TLOG_SPILL_THRESHOLD
    SERVER_KNOBS.set("TLOG_SPILL_THRESHOLD", 20)
    yield 20
    SERVER_KNOBS.set("TLOG_SPILL_THRESHOLD", old)


def run(sched, coro):
    t = sched.spawn(coro)
    sched.run_until(t.done)
    return t.done.get()


def commit_n(sched, log, n, *, start=0, per_version=4, tag=0):
    async def go():
        prev = start
        for i in range(n):
            v = start + (i + 1) * 10
            await log.commit(TLogCommitRequest(
                prev_version=prev,
                version=v,
                messages={tag: [("set", b"k%04d" % (i * 7 + j), b"v")
                                for j in range(per_version)]},
            ))
            prev = v
    run(sched, go())


def test_spill_bounds_memory_and_peek_reads_back(small_budget):
    sched = Scheduler(sim=True)
    log = TLog(sched, durable=SimDiskQueue())
    commit_n(sched, log, 30)  # 120 mutations through a 20-mutation budget

    assert log._mem_mutations <= small_budget
    assert log._spilled.get(0), "old versions must have spilled"

    entries, _v = run(sched, log.peek(0, 0))
    assert [v for v, _m in entries] == [(i + 1) * 10 for i in range(30)]
    # spilled versions carry their full payloads read back off the queue
    assert all(len(m) == 4 for _v, m in entries)


def test_pop_prunes_spilled_and_disk(small_budget):
    sched = Scheduler(sim=True)
    log = TLog(sched, durable=SimDiskQueue())
    commit_n(sched, log, 30)
    log.pop(0, 200)  # versions 10..200 consumed
    entries, _v = run(sched, log.peek(0, 200))
    assert [v for v, _m in entries] == [(i + 1) * 10 for i in range(20, 30)]
    assert all(v > 200 for v, _s in log._spilled.get(0, []))
    # physical pop must never run past unpopped SPILLED data: every
    # version above the floor stays recoverable from the queue (records
    # below it may linger — pops ride un-fsynced by design and recovery
    # dedups by version)
    recovered_versions = []
    import pickle
    for _seq, blob in log.dq.recovered:
        _p, v, _m = pickle.loads(blob)
        recovered_versions.append(v)
    assert set(recovered_versions) >= {(i + 1) * 10 for i in range(20, 30)}


def test_crash_recovery_respills_and_serves(small_budget):
    sched = Scheduler(sim=True)
    log = TLog(sched, durable=SimDiskQueue())
    commit_n(sched, log, 25)
    log.dq.crash(None)
    log.dq.recover()
    log.restore_from_disk()
    # the recovered tail exceeds the budget: it must re-spill, and the
    # merged peek view must still be complete
    assert log._mem_mutations <= small_budget
    entries, _v = run(sched, log.peek(0, 0))
    assert [v for v, _m in entries] == [(i + 1) * 10 for i in range(25)]


def test_catch_up_from_spilled_peer(small_budget):
    sched = Scheduler(sim=True)
    peer = TLog(sched, durable=SimDiskQueue())
    commit_n(sched, peer, 30)
    assert peer._spilled.get(0)

    rookie = TLog(sched, durable=SimDiskQueue())
    rookie.catch_up_from(peer)
    entries, _v = run(sched, rookie.peek(0, 0))
    assert [v for v, _m in entries] == [(i + 1) * 10 for i in range(30)]
    # and the rookie respected its own budget while catching up
    assert rookie._mem_mutations <= small_budget


def test_lagging_storage_follower_bounds_memory(small_budget):
    """The scenario the reference's spill exists for: one consumer stops
    popping; commits keep flowing; tlog memory stays bounded while the
    laggard can still catch up later with zero loss."""
    from foundationdb_tpu.cluster.logsystem import LogSystem

    sched = Scheduler(sim=True)
    ls = LogSystem(sched, 1)
    log = ls.tlogs[0]

    async def go():
        prev = 0
        for i in range(40):
            v = (i + 1) * 10
            await ls.commit(TLogCommitRequest(
                prev_version=prev, version=v,
                messages={0: [("set", b"lag%04d" % i, b"v%d" % i)]},
            ))
            prev = v
        # the laggard never popped: memory bounded anyway
        assert log._mem_mutations <= small_budget
        # now it wakes up and drains from version 0 — nothing lost
        entries, _v = await ls.peek(0, 0)
        assert [v for v, _m in entries] == [(i + 1) * 10 for i in range(40)]
        assert [m[0][1] for _v, m in entries] == [
            b"lag%04d" % i for i in range(40)
        ]
        return True

    assert run(sched, go())
