"""Randomized ensemble soak: everything at once, deterministically.

The miniature of the reference's Joshua ensemble (SURVEY.md §4): each
seed composes a correctness workload (ConflictRange-style model checks)
with concurrent fault injection — clogging, storage reboots, shard
moves, and a proxy kill that forces a full recovery — then verifies the
final state against the model and runs the consistency check. The same
seed must reproduce the same execution.
"""

import numpy as np
import pytest

from foundationdb_tpu.cluster.commit_proxy import (
    CommitUnknownResult,
    NotCommitted,
    TransactionTooOldError,
)
from foundationdb_tpu.cluster.consistency import check_cluster
from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.cluster.grv_proxy import GrvProxyFailedError
from foundationdb_tpu.runtime.flow import all_of

from foundationdb_tpu.cluster.failure_monitor import ProcessFailedError

RETRYABLE = (NotCommitted, TransactionTooOldError, CommitUnknownResult,
             GrvProxyFailedError, ProcessFailedError)


def soak(seed: int, *, kill_proxy: bool, rounds: int = 30,
         replication: int = 1, n_storage: int = 2, n_tlogs: int = 1,
         kill_tlog: bool = False):
    sched, cluster, db = open_cluster(
        ClusterConfig(
            n_commit_proxies=2, n_resolvers=2, n_storage=n_storage,
            replication_factor=replication, n_tlogs=n_tlogs, sim_seed=seed,
        )
    )
    rng = np.random.default_rng(seed)
    # commit_unknown_result makes single outcomes ambiguous (the killed
    # proxy's batch may have committed after the client saw the error),
    # so the model tracks the SET of possible values per key — the same
    # caveat the reference documents for that error code.
    possible: dict[bytes, set] = {}
    outcome = {"committed": 0, "aborted": 0, "read_checks": 0}

    def check(got: dict, lo: bytes, hi: bytes):
        keys = set(got) | {
            k for k in possible if lo <= k < hi
        }
        for k in keys:
            allowed = possible.get(k, {None})
            assert got.get(k) in allowed, (
                f"seed {seed}: key {k!r} = {got.get(k)!r} not in {allowed}"
            )

    async def workload():
        for i in range(rounds):
            txn = db.create_transaction()
            try:
                if rng.random() < 0.6:
                    a = int(rng.integers(0, 30))
                    b_ = a + int(rng.integers(1, 8))
                    lo, hi = b"s%02d" % a, b"s%02d" % b_
                    got = dict(await txn.get_range(lo, hi))
                    check(got, lo, hi)
                    outcome["read_checks"] += 1
                writes = {}
                for _ in range(int(rng.integers(1, 4))):
                    k = b"s%02d" % int(rng.integers(0, 30))
                    v = b"r%d" % i
                    txn.set(k, v)
                    writes[k] = v
                await txn.commit()
                for k, v in writes.items():
                    possible[k] = {v}
                outcome["committed"] += 1
            except CommitUnknownResult:
                # may or may not have applied
                for k, v in writes.items():
                    possible.setdefault(k, {None}).add(v)
                outcome["aborted"] += 1
                await sched.delay(0.01)
            except RETRYABLE:
                outcome["aborted"] += 1
                await sched.delay(0.01)

    async def chaos():
        await sched.delay(0.05)
        cluster.net.clog_pair("proxy0", "resolver0", 0.2)
        await sched.delay(0.1)
        cluster.reboot_storage(int(rng.integers(0, 2)))
        await sched.delay(0.1)
        try:
            await cluster.data_distributor.move_shard(b"s05", b"s15", 1)
        except Exception:
            pass
        if kill_tlog:
            await sched.delay(0.05)
            cluster.kill_tlog(0)
        if kill_proxy:
            await sched.delay(0.1)
            p = cluster.commit_proxies[0]
            p.failed = RuntimeError("soak kill")
            p.stop()

    w = sched.spawn(workload(), name="soak-load")
    c = sched.spawn(chaos(), name="soak-chaos")
    sched.run_until(all_of([w.done, c.done]))

    # settle (deferred drops, recovery tail), then global checks
    sched.run_for(1.0)

    async def final_verify():
        # a GRV delivered after a hard ratekeeper throttle can be older
        # than the MVCC window by the time the read lands — the client
        # contract is past_version/too_old => retry with a fresh GRV
        for _ in range(20):
            txn = db.create_transaction()
            try:
                return dict(await txn.get_range(b"s", b"t"))
            except RETRYABLE:
                await sched.delay(0.05)
        raise AssertionError("final verify never got a fresh-enough GRV")

    got = sched.run_until(sched.spawn(final_verify()).done)
    check(got, b"s", b"t")
    check_cluster(cluster)
    if kill_proxy:
        assert cluster.controller.epoch >= 2
    sig = (
        outcome["committed"], outcome["aborted"], outcome["read_checks"],
        round(sched.now(), 6), cluster.controller.epoch,
        tuple(sorted(got)),
    )
    cluster.stop()
    return sig


@pytest.mark.parametrize("seed", [11, 22])
def test_soak_with_faults(seed):
    assert soak(seed, kill_proxy=False)[0] > 0


def test_soak_with_recovery():
    sig = soak(33, kill_proxy=True)
    assert sig[0] > 0


def test_soak_rerun_is_identical():
    assert soak(44, kill_proxy=True) == soak(44, kill_proxy=True)


def test_soak_replicated():
    sig = soak(55, kill_proxy=True, replication=2, n_storage=3)
    assert sig[0] > 0


def test_soak_everything_at_once():
    """Replicated storage AND logs, with a log-replica kill, a storage
    reboot, a shard move, a proxy kill, recovery — one run."""
    sig = soak(
        66, kill_proxy=True, kill_tlog=True,
        replication=2, n_storage=3, n_tlogs=2,
    )
    assert sig[0] > 0


def test_ensemble_seeds_and_determinism():
    """The seed-sweep ensemble module (scripts/soak.py's engine): a few
    seeds with seed-derived shapes/knobs/faults, one determinism pair."""
    from foundationdb_tpu.testing.soak import plan_for_seed, run_seed

    sigs = [run_seed(s) for s in (3, 17)]
    assert all(sig[1] > 0 for sig in sigs)  # every seed commits work
    assert run_seed(17) == sigs[1]  # rerun-identical
    # seed plans genuinely vary
    plans = {str(plan_for_seed(s)) for s in range(12)}
    assert len(plans) >= 8


def test_unhandled_actor_error_fails_the_seed():
    """The silent-green killer: an injected actor whose error escapes
    the scheduler (nobody ever awaits it) must FAIL the seed — the
    round-5 soak printed 264 such tracebacks and still passed."""
    from foundationdb_tpu.testing.soak import run_seed

    async def boom(sched, cluster, db):
        await sched.delay(0.1)
        raise RuntimeError("injected unhandled actor error")

    with pytest.raises(AssertionError, match="unhandled actor error"):
        run_seed(3, _inject_fault=boom)
    # the same seed without the injection still passes
    assert run_seed(3)


def test_unhandled_error_ledger_semantics():
    """Scheduler.unhandled_errors: an escaped error counts; the same
    error consumed by a late awaiter does not (awaiting after the crash
    IS handling — the round-5 false-positive tracebacks)."""
    from foundationdb_tpu.runtime.flow import Scheduler

    sched = Scheduler(sim=True)

    async def dies():
        await sched.delay(0.01)
        raise ValueError("escaped")

    # escaped: spawned, never observed
    sched.spawn(dies(), name="fire-and-forget")  # flowcheck: ignore[actor.fire-and-forget]
    sched.run_for(0.1)
    assert [n for n, _e in sched.unhandled_errors()] == ["fire-and-forget"]
    sched.clear_unhandled()

    # observed late: the awaiter consumes the error after the crash
    t = sched.spawn(dies(), name="awaited-late")

    async def awaiter():
        await sched.delay(0.05)  # crash happens first
        try:
            await t.done
        except ValueError:
            return True

    a = sched.spawn(awaiter(), name="awaiter")
    sched.run_until(a.done)
    assert a.done.get() is True
    assert sched.unhandled_errors() == []


def test_combinator_delegation_consumes_sibling_errors():
    """Seed 159's false escape, pinned: two parallel actors both fail
    (two tlog replicas raising on the same epoch lock); all_of delivers
    the first error to the awaiter — the sibling's later error is
    DELEGATED to the aggregate, not 'unhandled'."""
    from foundationdb_tpu.runtime.flow import Scheduler, all_of

    sched = Scheduler(sim=True)

    async def dies(after):
        await sched.delay(after)
        raise RuntimeError(f"replica failed at {after}")

    t1 = sched.spawn(dies(0.01), name="commit")
    t2 = sched.spawn(dies(0.02), name="commit")

    async def caller():
        try:
            await all_of([t1.done, t2.done])
        except RuntimeError:
            return True

    c = sched.spawn(caller(), name="caller")
    sched.run_until(c.done)
    sched.run_for(0.1)  # let the sibling's error land
    assert c.done.get() is True
    assert sched.unhandled_errors() == []


def test_dropped_aggregate_does_not_consume_member_errors():
    """Delegation requires CONSUMPTION: building any_of/all_of over
    failing tasks and dropping the aggregate on the floor must leave
    the member errors on the unhandled ledger (else a dropped race
    would blind the gate)."""
    from foundationdb_tpu.runtime.flow import Scheduler, any_of

    sched = Scheduler(sim=True)

    async def dies():
        await sched.delay(0.01)
        raise RuntimeError("nobody is watching")

    t1 = sched.spawn(dies(), name="dropped-a")
    t2 = sched.spawn(dies(), name="dropped-b")
    any_of([t1.done, t2.done])  # aggregate built, never awaited
    sched.run_for(0.1)
    assert sorted(n for n, _e in sched.unhandled_errors()) == [
        "dropped-a", "dropped-b",
    ]


def test_cancelled_awaiter_abandons_the_await():
    """Recovery's shape: an actor cancelled while awaiting a fan-out
    (proxy batch actor awaiting LogSystem.commit's all_of over tlog
    replicas) abandons the pending future — replica errors delivered
    BEFORE or AFTER the cancel are consumed by it, not 'escaped'."""
    from foundationdb_tpu.runtime.flow import Scheduler, all_of

    sched = Scheduler(sim=True)

    async def replica(after):
        await sched.delay(after)
        raise RuntimeError("epoch locked")

    r1 = sched.spawn(replica(0.20), name="commit")
    r2 = sched.spawn(replica(0.50), name="commit")

    async def batch_actor():
        await all_of([r1.done, r2.done])

    b = sched.spawn(batch_actor(), name="batch")
    sched.run_for(0.1)   # batch is suspended on the fan-out
    b.cancel()           # recovery tears the batch actor down
    sched.run_for(0.8)   # BOTH replica errors land after the cancel
    assert sched.unhandled_errors() == []


def test_plans_are_spec_driven():
    """plan_for_seed derives everything from a named spec file — the
    same seed yields different plans under different specs, identical
    plans under the same spec, and the spec name rides on the plan."""
    from foundationdb_tpu.testing.soak import plan_for_seed

    d = plan_for_seed(9)
    assert d.spec_name == "default"
    assert plan_for_seed(9, "default") == d
    storm = plan_for_seed(9, "recovery_storm")
    assert storm.spec_name == "recovery_storm"
    assert storm != d
    # api_correctness runs the api workload on EVERY seed and
    # alternates resolver backends across seeds
    api_plans = [plan_for_seed(s, "api_correctness") for s in range(8)]
    assert all(p.api for p in api_plans)
    assert {p.resolver_backend for p in api_plans} == {"cpu", "tpu-force"}


def test_balancer_conservative_aborts_do_not_arm_strict_audit():
    """api_correctness seed 60, pinned: with two resolvers the
    ResolutionBalancer's range moves inject synthetic conservative
    writes (commit_proxy.conservative_writes) — a read below the
    transition version aborts with NO client writer to explain it, so
    the strict false-abort audit must not arm on multi-resolver plans.
    (Pre-existing escape, found by the PR-3 perturbation sweep.)"""
    from foundationdb_tpu.testing.soak import plan_for_seed, run_seed

    plan = plan_for_seed(60, "api_correctness")
    assert plan.n_resolvers == 2 and plan.api  # the shape that bit
    assert run_seed(60, spec="api_correctness")[1] > 0


def test_status_probe_keeps_traced_seeds_bit_identical():
    """Saturation-sensor determinism guard (fast shape): with the
    status probe sampling cluster_status() (every saturation() sensor,
    smoother decay, qos assembly) DURING a traced seed, the signature —
    trace digest included — stays bit-identical across reruns, for the
    FIFO schedule and a perturbed one. The 50-seed x 2-perturbation
    sweep shape lives in test_saturation_sensor_sweep (slow lane)."""
    from foundationdb_tpu.testing.soak import run_seed

    base = run_seed(7, spec="smoke", trace=True, status_probe=True)
    assert base == run_seed(7, spec="smoke", trace=True, status_probe=True)
    pert = run_seed(
        7, spec="smoke", trace=True, status_probe=True, perturb=1
    )
    assert pert == run_seed(
        7, spec="smoke", trace=True, status_probe=True, perturb=1
    )
    # the probe actor is a schedule participant: its digest legally
    # differs from an unprobed run, but each config reproduces exactly
    assert base[1] > 0  # the probed seed still commits work


@pytest.mark.slow
def test_saturation_sensor_sweep():
    """The PR-7 acceptance sweep: 50 seeds x 2 perturbations, traced,
    with the saturation sensors armed AND actively sampled — every
    (seed, perturb) bit-identical across a rerun (sha256 trace digest
    in the signature)."""
    from foundationdb_tpu.testing.soak import run_seed

    for seed in range(50):
        for perturb in (1, 2):
            sig = run_seed(seed, spec="smoke", trace=True,
                           status_probe=True, perturb=perturb)
            sig2 = run_seed(seed, spec="smoke", trace=True,
                            status_probe=True, perturb=perturb)
            assert sig == sig2, (
                f"seed {seed} perturb {perturb}: sensors-armed trace "
                f"digest not reproducible"
            )
