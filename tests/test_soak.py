"""Randomized ensemble soak: everything at once, deterministically.

The miniature of the reference's Joshua ensemble (SURVEY.md §4): each
seed composes a correctness workload (ConflictRange-style model checks)
with concurrent fault injection — clogging, storage reboots, shard
moves, and a proxy kill that forces a full recovery — then verifies the
final state against the model and runs the consistency check. The same
seed must reproduce the same execution.
"""

import numpy as np
import pytest

from foundationdb_tpu.cluster.commit_proxy import (
    CommitUnknownResult,
    NotCommitted,
    TransactionTooOldError,
)
from foundationdb_tpu.cluster.consistency import check_cluster
from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.cluster.grv_proxy import GrvProxyFailedError
from foundationdb_tpu.runtime.flow import all_of

from foundationdb_tpu.cluster.failure_monitor import ProcessFailedError

RETRYABLE = (NotCommitted, TransactionTooOldError, CommitUnknownResult,
             GrvProxyFailedError, ProcessFailedError)


def soak(seed: int, *, kill_proxy: bool, rounds: int = 30,
         replication: int = 1, n_storage: int = 2, n_tlogs: int = 1,
         kill_tlog: bool = False):
    sched, cluster, db = open_cluster(
        ClusterConfig(
            n_commit_proxies=2, n_resolvers=2, n_storage=n_storage,
            replication_factor=replication, n_tlogs=n_tlogs, sim_seed=seed,
        )
    )
    rng = np.random.default_rng(seed)
    # commit_unknown_result makes single outcomes ambiguous (the killed
    # proxy's batch may have committed after the client saw the error),
    # so the model tracks the SET of possible values per key — the same
    # caveat the reference documents for that error code.
    possible: dict[bytes, set] = {}
    outcome = {"committed": 0, "aborted": 0, "read_checks": 0}

    def check(got: dict, lo: bytes, hi: bytes):
        keys = set(got) | {
            k for k in possible if lo <= k < hi
        }
        for k in keys:
            allowed = possible.get(k, {None})
            assert got.get(k) in allowed, (
                f"seed {seed}: key {k!r} = {got.get(k)!r} not in {allowed}"
            )

    async def workload():
        for i in range(rounds):
            txn = db.create_transaction()
            try:
                if rng.random() < 0.6:
                    a = int(rng.integers(0, 30))
                    b_ = a + int(rng.integers(1, 8))
                    lo, hi = b"s%02d" % a, b"s%02d" % b_
                    got = dict(await txn.get_range(lo, hi))
                    check(got, lo, hi)
                    outcome["read_checks"] += 1
                writes = {}
                for _ in range(int(rng.integers(1, 4))):
                    k = b"s%02d" % int(rng.integers(0, 30))
                    v = b"r%d" % i
                    txn.set(k, v)
                    writes[k] = v
                await txn.commit()
                for k, v in writes.items():
                    possible[k] = {v}
                outcome["committed"] += 1
            except CommitUnknownResult:
                # may or may not have applied
                for k, v in writes.items():
                    possible.setdefault(k, {None}).add(v)
                outcome["aborted"] += 1
                await sched.delay(0.01)
            except RETRYABLE:
                outcome["aborted"] += 1
                await sched.delay(0.01)

    async def chaos():
        await sched.delay(0.05)
        cluster.net.clog_pair("proxy0", "resolver0", 0.2)
        await sched.delay(0.1)
        cluster.reboot_storage(int(rng.integers(0, 2)))
        await sched.delay(0.1)
        try:
            await cluster.data_distributor.move_shard(b"s05", b"s15", 1)
        except Exception:
            pass
        if kill_tlog:
            await sched.delay(0.05)
            cluster.kill_tlog(0)
        if kill_proxy:
            await sched.delay(0.1)
            p = cluster.commit_proxies[0]
            p.failed = RuntimeError("soak kill")
            p.stop()

    w = sched.spawn(workload(), name="soak-load")
    c = sched.spawn(chaos(), name="soak-chaos")
    sched.run_until(all_of([w.done, c.done]))

    # settle (deferred drops, recovery tail), then global checks
    sched.run_for(1.0)

    async def final_verify():
        # a GRV delivered after a hard ratekeeper throttle can be older
        # than the MVCC window by the time the read lands — the client
        # contract is past_version/too_old => retry with a fresh GRV
        for _ in range(20):
            txn = db.create_transaction()
            try:
                return dict(await txn.get_range(b"s", b"t"))
            except RETRYABLE:
                await sched.delay(0.05)
        raise AssertionError("final verify never got a fresh-enough GRV")

    got = sched.run_until(sched.spawn(final_verify()).done)
    check(got, b"s", b"t")
    check_cluster(cluster)
    if kill_proxy:
        assert cluster.controller.epoch >= 2
    sig = (
        outcome["committed"], outcome["aborted"], outcome["read_checks"],
        round(sched.now(), 6), cluster.controller.epoch,
        tuple(sorted(got)),
    )
    cluster.stop()
    return sig


@pytest.mark.parametrize("seed", [11, 22])
def test_soak_with_faults(seed):
    assert soak(seed, kill_proxy=False)[0] > 0


def test_soak_with_recovery():
    sig = soak(33, kill_proxy=True)
    assert sig[0] > 0


def test_soak_rerun_is_identical():
    assert soak(44, kill_proxy=True) == soak(44, kill_proxy=True)


def test_soak_replicated():
    sig = soak(55, kill_proxy=True, replication=2, n_storage=3)
    assert sig[0] > 0


def test_soak_everything_at_once():
    """Replicated storage AND logs, with a log-replica kill, a storage
    reboot, a shard move, a proxy kill, recovery — one run."""
    sig = soak(
        66, kill_proxy=True, kill_tlog=True,
        replication=2, n_storage=3, n_tlogs=2,
    )
    assert sig[0] > 0


def test_ensemble_seeds_and_determinism():
    """The seed-sweep ensemble module (scripts/soak.py's engine): a few
    seeds with seed-derived shapes/knobs/faults, one determinism pair."""
    from foundationdb_tpu.testing.soak import plan_for_seed, run_seed

    sigs = [run_seed(s) for s in (3, 17)]
    assert all(sig[1] > 0 for sig in sigs)  # every seed commits work
    assert run_seed(17) == sigs[1]  # rerun-identical
    # seed plans genuinely vary
    plans = {str(plan_for_seed(s)) for s in range(12)}
    assert len(plans) >= 8
