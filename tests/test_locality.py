"""Locality + replication policies (fdbrpc/ReplicationPolicy.cpp role).

PolicyAcross composition, team building across failure domains, cluster
teams honoring the policy, and locality-aware team repair after a
storage death.
"""

import pytest

from foundationdb_tpu.cluster.locality import (
    LocalityData,
    PolicyAcross,
    PolicyOne,
    PolicyUnsatisfiableError,
    build_team,
    validate_team,
)


def locs(spec):
    """spec: {server_id: (zone, dc)}"""
    return {
        s: LocalityData(
            process_id=f"p{s}", machine_id=f"m{s}", zone_id=z, dc_id=d
        )
        for s, (z, d) in spec.items()
    }


def test_policy_across_validation():
    L = locs({0: ("z1", "dc1"), 1: ("z1", "dc1"), 2: ("z2", "dc1"),
              3: ("z3", "dc2")})
    p = PolicyAcross(2, "zone_id")
    assert p.validate([L[0], L[2]])
    assert not p.validate([L[0], L[1]])  # same zone twice
    # nested: 2 DCs x (1 zone each)
    p2 = PolicyAcross(2, "dc_id", PolicyAcross(1, "zone_id"))
    assert p2.validate([L[0], L[3]])
    assert not p2.validate([L[0], L[2]])  # both dc1
    assert PolicyOne().validate([L[0]])


def test_build_team_across_zones():
    L = locs({0: ("z1", "dc"), 1: ("z1", "dc"), 2: ("z2", "dc"),
              3: ("z2", "dc"), 4: ("z3", "dc")})
    team = build_team(L, PolicyAcross(3, "zone_id"))
    zones = {L[s].zone_id for s in team}
    assert len(team) == 3 and len(zones) == 3
    # prefer steers selection when compatible
    team2 = build_team(L, PolicyAcross(2, "zone_id"), prefer=(1, 3))
    assert set(team2) == {1, 3}
    # exclusion can make it unsatisfiable
    with pytest.raises(PolicyUnsatisfiableError):
        build_team(L, PolicyAcross(3, "zone_id"),
                   exclude=frozenset({4}))


def test_unset_field_never_counts():
    L = {0: LocalityData(process_id="a"), 1: LocalityData(process_id="b")}
    assert not PolicyAcross(1, "zone_id").validate(list(L.values()))
    with pytest.raises(PolicyUnsatisfiableError):
        build_team(L, PolicyAcross(1, "zone_id"))


def test_cluster_teams_honor_policy_and_repair():
    from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster

    L = locs({0: ("z1", "dc"), 1: ("z1", "dc"), 2: ("z2", "dc"),
              3: ("z3", "dc")})
    policy = PolicyAcross(2, "zone_id")
    sched, cluster, db = open_cluster(
        ClusterConfig(
            n_commit_proxies=1, n_storage=4, replication_factor=2,
            storage_localities=L, replication_policy=policy,
        )
    )
    # every team spans two zones
    for team in cluster.key_servers.owners:
        assert validate_team(team, L, policy), team

    async def go():
        t = db.create_transaction()
        t.set(b"k1", b"v1")
        await t.commit()
        # kill server 2 (the sole z2 member): repair must rebuild each
        # affected team cross-zone from the z1/z3 survivors
        cluster.kill_storage(2)
        await cluster.data_distributor.repair(2)
        for team in cluster.key_servers.owners:
            assert 2 not in team
            assert validate_team(team, L, policy), team
        t = db.create_transaction()
        assert await t.get(b"k1") == b"v1"
        return True

    task = sched.spawn(go(), name="drive")
    sched.run_until(task.done)
    assert task.done.get()
    cluster.stop()
