"""cluster/sampling unit pins: the keyspace-skew sensing substrate.

The byte sample must be a PURE FUNCTION of (seed, key, size) — the
soak determinism pin (`--status-probe`) rides on that — its range
queries must be unbiased against exact byte counts, the tag counter
must decay and roll over deterministically under the virtual clock,
and the attribution rule must hold in BOTH directions (dominant flags,
flat stays quiet, starved range samples never flag).
"""

import random

import pytest

from foundationdb_tpu.cluster.sampling import (
    DOMINANCE_FRAC,
    HOT_RANGE_MIN_KEYS,
    ByteSample,
    TagCounter,
    attribute_hotspot,
    decay_key_sample,
    key_sample_qos,
    tag_of_key,
)


def _kv_stream(seed, n=4000, value_bytes=512):
    rng = random.Random(seed)
    out = []
    for _ in range(n):
        t = rng.randrange(8)
        k = f"tenant{t}/k{rng.randrange(500):05d}".encode()
        out.append((k, b"v" * value_bytes))
    return out


# ---------------------------------------------------------------------------
# ByteSample


def test_byte_sample_deterministic_and_order_independent():
    kvs = _kv_stream(1)
    a = ByteSample(seed=42)
    b = ByteSample(seed=42)
    for k, v in kvs:
        a.note_write(k, v)
    shuffled = list(kvs)
    random.Random(9).shuffle(shuffled)
    for k, v in shuffled:
        b.note_write(k, v)
    # same seed, same final (key, size) set -> bit-identical sample,
    # regardless of arrival order (keys repeat; last size wins — the
    # shuffle preserves per-key last-write order only by luck, so
    # compare on a dedup'd stream)
    dedup = {}
    for k, v in kvs:
        dedup[k] = v
    a2, b2 = ByteSample(seed=42), ByteSample(seed=42)
    items = list(dedup.items())
    for k, v in items:
        a2.note_write(k, v)
    random.Random(9).shuffle(items)
    for k, v in items:
        b2.note_write(k, v)
    assert a2.items() == b2.items()
    assert a2.total_bytes() == b2.total_bytes()
    # ...and a different seed draws a different sample
    c = ByteSample(seed=43)
    for k, v in dedup.items():
        c.note_write(k, v)
    assert c.items() != a2.items()


def test_sampled_bytes_range_accuracy_vs_exact():
    kvs = {}
    for k, v in _kv_stream(2, n=6000):
        kvs[k] = v
    bs = ByteSample(seed=7)
    for k, v in kvs.items():
        bs.note_write(k, v)
    exact_total = sum(len(k) + len(v) for k, v in kvs.items())
    est_total = bs.sampled_bytes()
    assert est_total == bs.total_bytes()
    # the weight sum is an unbiased estimator; at ~500 sampled keys the
    # relative error on the full range sits comfortably inside 15%
    assert abs(est_total - exact_total) / exact_total < 0.15
    # per-prefix range query (half-open [begin, end)) vs exact
    for t in ("tenant0", "tenant3", "tenant7"):
        begin = f"{t}/".encode()
        end = f"{t}0".encode()  # '0' > '/' — covers the whole prefix
        exact = sum(
            len(k) + len(v) for k, v in kvs.items()
            if begin <= k < end
        )
        est = bs.sampled_bytes(begin, end)
        assert abs(est - exact) / exact < 0.4
    # end=None reaches +inf (keys above any finite end still count)
    assert bs.sampled_bytes(b"tenant4/") == sum(
        len(k) + len(v) for k, v in kvs.items() if k >= b"tenant4/"
    ) or bs.sampled_bytes(b"tenant4/") > 0


def test_erase_and_erase_range():
    bs = ByteSample(seed=3, factor=1, overhead=0)
    for i in range(32):
        bs.note_write(b"e/%02d" % i, b"v" * 64)
    assert bs.count == 32  # factor=1: p >= 1, everything samples
    bs.erase(b"e/05")
    assert bs.count == 31
    bs.erase(b"e/05")  # idempotent
    assert bs.count == 31
    bs.erase_range(b"e/10", b"e/20")
    assert bs.count == 21
    assert bs.sampled_bytes(b"e/10", b"e/20") == 0


def test_overwrite_resamples_at_new_size():
    bs = ByteSample(seed=5, factor=1, overhead=0)
    bs.note_write(b"ow/key", b"v" * 100)
    assert bs.total_bytes() == 106
    bs.note_write(b"ow/key", b"v" * 10)  # shrink: old entry replaced
    assert bs.count == 1
    assert bs.total_bytes() == 16


def test_gc_halves_scale_and_stays_unbiased():
    bs = ByteSample(seed=11, factor=1, overhead=0, capacity=64)
    for i in range(256):
        bs.note_write(b"gc/%04d" % i, b"v" * 64)
    assert bs.gc_rounds >= 1
    assert bs.count <= 64
    assert bs.scale < 1.0
    # survivors' weights are scaled up so the estimator stays unbiased:
    # true bytes = 256 * (7 + 64) = 18176
    exact = 256 * (7 + 64)
    assert abs(bs.total_bytes() - exact) / exact < 0.5


def test_snapshot_restore_round_trip():
    bs = ByteSample(seed=13, factor=10, overhead=4, capacity=128)
    for i in range(400):
        bs.note_write(b"snap/%04d" % i, b"v" * 200)
    snap = bs.snapshot()
    other = ByteSample(seed=0)  # knobs must come FROM the snapshot
    other.restore(snap)
    assert other.seed == bs.seed
    assert other.factor == 10 and other.overhead == 4
    assert other.capacity == 128
    assert other.scale == bs.scale
    assert other.items() == bs.items()
    assert other.total_bytes() == bs.total_bytes()
    assert other.hot_ranges() == bs.hot_ranges()


def test_hot_ranges_rows_carry_key_support():
    bs = ByteSample(seed=17, factor=1, overhead=0)
    for i in range(20):
        bs.note_write(b"tenant0/k%02d" % i, b"v" * 64)
    for i in range(2):
        bs.note_write(b"tenant1/k%02d" % i, b"v" * 64)
    rows = bs.hot_ranges()
    assert rows[0]["range"] == "tenant0"
    assert rows[0]["keys"] == 20
    assert rows[0]["frac"] > 0.8
    assert rows[1] == {
        "range": "tenant1", "begin": "tenant1/k00", "end": "tenant1/k01",
        "bytes": rows[1]["bytes"], "keys": 2, "frac": rows[1]["frac"],
    }


# ---------------------------------------------------------------------------
# TagCounter


def test_tag_counter_decay_under_virtual_clock():
    t = [0.0]
    tc = TagCounter(folding_time=1.0, clock=lambda: t[0])
    for _ in range(10):
        tc.note("hot", 1000)
        t[0] += 0.1
    busy = tc.busiest()
    assert busy["tag"] == "hot"
    rate_now = busy["bytes_per_s"]
    assert rate_now > 0
    t[0] += 10.0  # ten folding times of silence
    assert tc.busiest()["bytes_per_s"] < rate_now / 100
    assert tc.bytes_noted == 10000  # the ledger counter never decays
    assert tc.notes == 10


def test_tag_counter_rollover_evicts_cold_half():
    t = [0.0]
    tc = TagCounter(capacity=4, folding_time=1.0, clock=lambda: t[0])
    for i in range(4):
        tc.note(f"cold{i}", 10)
    t[0] += 5.0  # cold tags decay
    tc.note("hot", 10000)  # 5th tag -> rollover first
    assert tc.rollovers == 1
    assert len(tc._rates) <= 3  # half of 4 evicted, then hot added
    assert tc.busiest()["tag"] == "hot"


def test_tag_counter_untagged_counts_toward_total_only():
    t = [0.0]
    tc = TagCounter(folding_time=1.0, clock=lambda: t[0])
    tc.note(None, 500)
    t[0] += 0.5
    tc.note("a", 500)
    t[0] += 0.5
    rows = tc.top()
    assert [r["tag"] for r in rows] == ["a"]
    assert rows[0]["frac"] < 0.9  # untagged bytes dilute the fraction


# ---------------------------------------------------------------------------
# tag derivation + key-sample helpers


def test_tag_of_key():
    assert tag_of_key(b"tenant3/k001") == "tenant3"
    assert tag_of_key(b"\x1etenant3/k001") == "tenant3"  # tenant prefix
    assert tag_of_key(b"noslashkey") is None
    assert tag_of_key(b"/leading") is None
    assert tag_of_key(b"x" * 40 + b"/k") is None  # prefix too long
    assert tag_of_key(b"a/b/c") == "a"  # first separator wins


def test_decay_key_sample_and_qos():
    sample = {b"a": 8, b"b": 3, b"c": 1}
    decay_key_sample(sample)
    assert sample == {b"a": 4, b"b": 1}  # zeros dropped
    wide = {b"k%04d" % i: 2 for i in range(100)}
    decay_key_sample(wide, limit=10)
    assert len(wide) == 5  # heaviest half of the limit kept
    qos = key_sample_qos({b"x/1": 5, b"x/2": 2}, top_n=1)
    assert qos == {"keys": 2, "top": [{"key": "x/1", "count": 5}]}


# ---------------------------------------------------------------------------
# attribution


def _status(tags=None, ranges=None):
    return {"cluster": {
        "busiest_tags": tags or [], "hot_ranges": ranges or [],
    }}


def test_attribute_dominant_tag():
    attr = attribute_hotspot(_status(
        tags=[{"tag": "tenant0", "bytes_per_s": 9e4, "frac": 0.7}],
    ))
    assert attr["attributed"]
    assert attr["hot_tag"]["tag"] == "tenant0"
    assert attr["hot_range"] is None
    assert attr["threshold"] == DOMINANCE_FRAC


def test_attribute_flat_mix_stays_quiet():
    attr = attribute_hotspot(_status(
        tags=[{"tag": f"t{i}", "bytes_per_s": 10.0, "frac": 0.125}
              for i in range(8)],
        ranges=[{"range": f"t{i}", "bytes": 100, "keys": 20,
                 "frac": 0.125} for i in range(8)],
    ))
    assert not attr["attributed"]


def test_attribute_hot_range_requires_key_support():
    # a 2-key sample putting half its weight in one range is noise —
    # the HOT_RANGE_MIN_KEYS floor must hold the verdict back...
    starved = attribute_hotspot(_status(
        ranges=[{"range": "tenant0", "bytes": 5000,
                 "keys": HOT_RANGE_MIN_KEYS - 1, "frac": 0.6}],
    ))
    assert not starved["attributed"]
    # ...and release it once the sample actually supports the fraction
    supported = attribute_hotspot(_status(
        ranges=[{"range": "tenant0", "bytes": 5000,
                 "keys": HOT_RANGE_MIN_KEYS, "frac": 0.6}],
    ))
    assert supported["attributed"]
    assert supported["hot_range"]["range"] == "tenant0"


def test_attribute_custom_threshold():
    st = _status(tags=[{"tag": "a", "bytes_per_s": 1.0, "frac": 0.4}])
    assert not attribute_hotspot(st)["attributed"]
    assert attribute_hotspot(st, threshold=0.3)["attributed"]


# ---------------------------------------------------------------------------
# the drill plan (testing/hotspot): seeded, direction-salted


def test_plan_workload_deterministic_and_skewed():
    from foundationdb_tpu.testing.hotspot import DEFAULTS, plan_workload

    cfg = dict(DEFAULTS)
    a = plan_workload(3, True, cfg)
    b = plan_workload(3, True, cfg)
    assert a == b
    assert plan_workload(4, True, cfg) != a
    uni = plan_workload(3, False, cfg)
    assert uni != a

    def frac0(keys):
        return sum(k.startswith(b"tenant0/") for k in keys) / len(keys)

    assert frac0(a) > DOMINANCE_FRAC  # zipf(2.0): top tenant dominates
    assert frac0(uni) < 0.3


@pytest.mark.slow
def test_hotspot_sim_gate_both_directions():
    from foundationdb_tpu.testing.hotspot import run_hotspot_sim

    zipf = run_hotspot_sim(seed=1, skewed=True, quick=True)
    assert zipf["ok"], zipf["why"]
    assert zipf["attribution"]["attributed"]
    flat = run_hotspot_sim(seed=1, skewed=False, quick=True)
    assert flat["ok"], flat["why"]
    assert not flat["attribution"]["attributed"]
