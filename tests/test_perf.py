"""The unified perf ledger (ISSUE 10): schema round-trip, fingerprint
matching, the noise-aware comparator BOTH directions, the historical
--import migration's byte stability, and every CLI's emit path.

The contract under test: all four perf CLIs emit schema-valid rows into
one ledger; scripts/perfcheck.py passes an unmodified tree against the
imported history and FAILS on an injected structural regression — the
check.sh lane's exit-code behavior, demonstrated here without the
15-second kernel_smoke run.
"""

import json
import os
import subprocess
import sys

import pytest

from foundationdb_tpu.utils import perf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PERFCHECK = os.path.join(REPO, "scripts", "perfcheck.py")


def _fp(**over):
    fp = {
        "backend": "cpu", "device_kind": "cpu", "device_count": 1,
        "jax_version": "0.0", "jaxlib_version": "0.0",
        "python_version": "3", "machine": "x",
    }
    fp.update(over)
    return fp


def _rec(value, *, source="t", tier="hardware", direction="higher",
         name="txn_s", fp=None, workload=None, knobs=None):
    return perf.make_record(
        source, {name: perf.metric(value, "txn/s", direction, tier=tier)},
        workload=workload or {"shape": 1}, knobs=knobs or {"k": 1},
        fingerprint=fp or _fp(), git_sha="deadbeef", timestamp=0.0,
    )


# ---------------------------------------------------------------------------
# Schema round-trip + validation.


def test_record_roundtrip_through_ledger(tmp_path):
    path = str(tmp_path / "history.jsonl")
    rec = _rec(100.0)
    perf.append(rec, path=path)
    back = perf.load_history(path)
    assert back == [rec]
    perf.validate_record(back[0])
    # the full fingerprint field set is present (the satellite fix:
    # backend alone cannot distinguish CPU-host from v5e rows)
    for key in ("backend", "device_kind", "device_count", "jax_version",
                "jaxlib_version"):
        assert key in back[0]["fingerprint"]


def test_device_fingerprint_live():
    fp = perf.device_fingerprint()
    assert fp["backend"] == "cpu"
    assert fp["device_count"] >= 1
    assert fp["jaxlib_version"]


@pytest.mark.parametrize("mutate, frag", [
    (lambda r: r["metrics"]["txn_s"].update(direction="sideways"),
     "direction"),
    (lambda r: r["metrics"]["txn_s"].update(tier="vibes"), "tier"),
    (lambda r: r["metrics"]["txn_s"].pop("unit"), "unit"),
    (lambda r: r["metrics"]["txn_s"].update(value="fast"), "number"),
    (lambda r: r.update(schema_version=99), "schema_version"),
    (lambda r: r.update(metrics={}), "metrics"),
    (lambda r: r["fingerprint"].pop("device_kind"), "device_kind"),
])
def test_validate_rejects_malformed(mutate, frag):
    rec = _rec(1.0)
    mutate(rec)
    with pytest.raises(ValueError, match=frag):
        perf.validate_record(rec)


def test_append_refuses_invalid(tmp_path):
    rec = _rec(1.0)
    rec["metrics"]["txn_s"]["direction"] = "bogus"
    with pytest.raises(ValueError):
        perf.append(rec, path=str(tmp_path / "h.jsonl"))
    assert not (tmp_path / "h.jsonl").exists()


def test_load_history_strict_on_corruption(tmp_path):
    path = tmp_path / "h.jsonl"
    path.write_text('{"ok": 1}\nnot json\n')
    with pytest.raises(ValueError, match="malformed"):
        perf.load_history(str(path))


def test_emit_honors_ledger_env(tmp_path, monkeypatch):
    path = str(tmp_path / "redirect.jsonl")
    monkeypatch.setenv("FDBTPU_PERF_LEDGER", path)
    rec = perf.emit("t", {"m": perf.metric(1, "count", "higher")})
    assert perf.load_history(path) == [rec]
    assert rec["timestamp"] is not None and rec["git_sha"]


# ---------------------------------------------------------------------------
# Fingerprint matching + baseline selection.


def test_hardware_baseline_ignores_mismatched_fingerprints():
    cand = _rec(100.0)
    same = _rec(90.0)
    other_dev = _rec(10.0, fp=_fp(device_kind="TPU v5e", backend="tpu"))
    other_jaxlib = _rec(10.0, fp=_fp(jaxlib_version="9.9"))
    other_workload = _rec(10.0, workload={"shape": 2})
    other_knobs = _rec(10.0, knobs={"k": 2})
    win = perf.baseline_window(
        [same, other_dev, other_jaxlib, other_workload, other_knobs],
        cand, tier="hardware",
    )
    assert win == [same]
    # structural matching crosses hosts (deterministic values) but
    # still keys on workload + knobs
    win_s = perf.baseline_window(
        [same, other_dev, other_jaxlib, other_workload, other_knobs],
        cand, tier="structural",
    )
    assert win_s == [same, other_dev, other_jaxlib]


def test_comparator_skips_mismatched_rows_entirely():
    """A regressed candidate PASSES when the only history rows carry a
    different fingerprint — wrong-host baselines must never gate."""
    cand = _rec(10.0)
    foreign = _rec(1000.0, fp=_fp(device_kind="TPU v5e", backend="tpu"))
    rep = perf.compare(cand, [foreign], tier="hardware")
    assert rep["baseline_rows"] == 0
    assert rep["metrics"]["txn_s"]["status"] == "new"
    assert rep["regressions"] == []


# ---------------------------------------------------------------------------
# The comparator, both directions.


def test_within_band_noise_passes():
    base = [_rec(v) for v in (95.0, 100.0, 103.0, 98.0, 101.0)]
    rep = perf.compare(_rec(93.0), base, tier="hardware")
    assert rep["metrics"]["txn_s"]["status"] == "ok"
    assert rep["regressions"] == []


def test_regression_outside_band_fails_higher_is_better():
    base = [_rec(v) for v in (95.0, 100.0, 103.0, 98.0, 101.0)]
    rep = perf.compare(_rec(50.0), base, tier="hardware")
    assert rep["metrics"]["txn_s"]["status"] == "regression"
    assert rep["regressions"] == ["txn_s"]


def test_regression_lower_is_better_direction():
    base = [_rec(v, direction="lower", name="p99_ms")
            for v in (10.0, 11.0, 10.5)]
    ok = perf.compare(_rec(10.4, direction="lower", name="p99_ms"),
                      base, tier="hardware")
    assert ok["regressions"] == []
    bad = perf.compare(_rec(30.0, direction="lower", name="p99_ms"),
                       base, tier="hardware")
    assert bad["regressions"] == ["p99_ms"]
    # an IMPROVEMENT (p99 down) never fails
    better = perf.compare(_rec(2.0, direction="lower", name="p99_ms"),
                          base, tier="hardware")
    assert better["metrics"]["p99_ms"]["status"] == "improved"
    assert better["regressions"] == []


def test_structural_tier_is_exact():
    """Structural values are deterministic: MAD 0, floor 0 — a doubled
    merge-row count fails even though it is 'only' 2x, and an
    identical value passes."""
    base = [_rec(121396, tier="structural", direction="lower",
                 name="merge_rows") for _ in range(3)]
    same = perf.compare(
        _rec(121396, tier="structural", direction="lower",
             name="merge_rows"), base, tier="structural")
    assert same["regressions"] == []
    doubled = perf.compare(
        _rec(242792, tier="structural", direction="lower",
             name="merge_rows"), base, tier="structural")
    assert doubled["regressions"] == ["merge_rows"]
    # structural compares cross-host: candidate from another machine
    cross = perf.compare(
        _rec(242792, tier="structural", direction="lower",
             name="merge_rows", fp=_fp(machine="arm64")),
        base, tier="structural")
    assert cross["regressions"] == ["merge_rows"]


def test_compare_only_reads_requested_tier():
    rec = perf.make_record(
        "t",
        {
            "rate": perf.metric(10.0, "txn/s", "higher", tier="hardware"),
            "rows": perf.metric(5, "rows", "lower", tier="structural"),
        },
        workload={"shape": 1}, knobs={}, fingerprint=_fp(),
        git_sha="d", timestamp=0.0,
    )
    base = json.loads(json.dumps(rec))
    base["metrics"]["rate"]["value"] = 1000.0  # hardware-tier collapse
    rep = perf.compare(rec, [base], tier="structural")
    assert set(rep["metrics"]) == {"rows"}
    assert rep["regressions"] == []


# ---------------------------------------------------------------------------
# --import: the historical-artifact migration.


def _perfcheck(*args, env=None):
    e = {**os.environ, "JAX_PLATFORMS": "cpu"}
    if env:
        e.update(env)
    return subprocess.run(
        [sys.executable, PERFCHECK, *args],
        capture_output=True, text=True, env=e, timeout=120,
    )


def test_import_is_byte_stable_and_reproduces_history(tmp_path):
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    r1 = _perfcheck("--import", "--history", a)
    r2 = _perfcheck("--import", "--history", b)
    assert r1.returncode == 0 and r2.returncode == 0, (r1.stderr, r2.stderr)
    assert open(a, "rb").read() == open(b, "rb").read()
    rows = perf.load_history(a)
    assert rows, "import produced no rows"
    for rec in rows:
        perf.validate_record(rec)
        assert rec["imported_from"]
        assert rec["timestamp"] is None  # byte-stability contract
    by_src = {r["source"] for r in rows}
    assert {"bench", "bench_pipeline", "saturation", "multichip"} <= by_src
    # spot-check: BENCH_r06's primary value survives the migration
    r06 = [r for r in rows if r.get("imported_from") == "BENCH_r06.json"]
    assert len(r06) == 1
    assert r06[0]["metrics"]["txn_s"]["value"] == pytest.approx(26437.6)
    assert r06[0]["metrics"]["merge_rows_tiered_live"]["value"] == 121396
    assert r06[0]["metrics"]["merge_rows_tiered_live"]["tier"] == (
        "structural"
    )
    # SATURATION_r08: one row per admission direction, structural tier
    sat = [r for r in rows if r["source"] == "saturation"]
    assert {r["workload"]["admission"] for r in sat} == {True, False}
    # re-import refuses without --force (double-append protection)
    r3 = _perfcheck("--import", "--history", a)
    assert r3.returncode == 1 and "--force" in r3.stderr


def test_committed_ledger_matches_reimport(tmp_path):
    """perf/history.jsonl's imported rows are EXACTLY what --import
    produces from the root artifacts today — the committed ledger
    cannot drift from its source artifacts."""
    fresh = str(tmp_path / "fresh.jsonl")
    assert _perfcheck("--import", "--history", fresh).returncode == 0
    committed = [
        r for r in perf.load_history(
            os.path.join(REPO, "perf", "history.jsonl"))
        if r.get("imported_from")
    ]
    assert committed == perf.load_history(fresh)


# ---------------------------------------------------------------------------
# The perfcheck CLI gate, both directions (the check.sh lane's
# exit-code contract).


def test_perfcheck_cli_passes_clean_and_fails_injected(tmp_path):
    hist = str(tmp_path / "history.jsonl")
    cand_path = str(tmp_path / "cand.jsonl")
    base = _rec(100, tier="structural", direction="lower", name="rows",
                source="kernel_smoke")
    perf.append(base, path=hist)
    # clean candidate: identical structural value -> exit 0
    perf.append(base, path=cand_path)
    r = _perfcheck("--check", cand_path, "--tier", "structural",
                   "--history", hist)
    assert r.returncode == 0, r.stderr
    assert "perfcheck ok" in r.stdout
    # injected regression: doubled rows -> exit 1
    bad_path = str(tmp_path / "bad.jsonl")
    perf.append(
        _rec(200, tier="structural", direction="lower", name="rows",
             source="kernel_smoke"), path=bad_path)
    r = _perfcheck("--check", bad_path, "--tier", "structural",
                   "--history", hist)
    assert r.returncode == 1
    assert "REGRESSED" in r.stderr


def test_perfcheck_unmodified_tree_passes_committed_history(tmp_path):
    """The acceptance pin: a kernel_smoke-shaped candidate REPLAYED
    from the committed ledger passes against that ledger (an
    unmodified tree is green), and the same candidate with one
    structural metric doubled fails."""
    committed = os.path.join(REPO, "perf", "history.jsonl")
    rows = [r for r in perf.load_history(committed)
            if r["source"] == "kernel_smoke"]
    assert rows, "committed ledger must hold a kernel_smoke baseline row"
    cand = json.loads(json.dumps(rows[-1]))
    cand_path = str(tmp_path / "cand.jsonl")
    perf.append(cand, path=cand_path)
    r = _perfcheck("--check", cand_path, "--tier", "structural",
                   "--history", committed)
    assert r.returncode == 0, (r.stdout, r.stderr)
    # inject: doubled merge-row capacity
    cand["metrics"]["merge_rows_tiered_cap"]["value"] *= 2
    bad_path = str(tmp_path / "bad.jsonl")
    perf.append(cand, path=bad_path)
    r = _perfcheck("--check", bad_path, "--tier", "structural",
                   "--history", committed)
    assert r.returncode == 1
    assert "merge_rows_tiered_cap" in r.stderr


def test_perfcheck_accept_appends_passing_candidate(tmp_path):
    hist = str(tmp_path / "history.jsonl")
    cand_path = str(tmp_path / "cand.jsonl")
    rec = _rec(7, tier="structural", direction="lower", name="rows")
    perf.append(rec, path=cand_path)
    r = _perfcheck("--check", cand_path, "--history", hist, "--accept")
    assert r.returncode == 0, r.stderr
    assert perf.load_history(hist) == [rec]


# ---------------------------------------------------------------------------
# Emitter converters: the four CLIs' row shapes.


def test_bench_row_converter_full_fingerprint():
    row = {
        "metric": "resolver_txns_per_sec_8k_batch", "value": 26437.6,
        "vs_baseline": 0.08, "baseline_txns_per_sec": 330626.7,
        "p50_ms": 301.0, "p99_ms": 496.4, "staging": "pipelined",
        "backend": "cpu", "kernel": "tiered", "delta_capacity": 98304,
        "dedup_reads": 0, "compact_interval": 8, "fused_dispatch": 8,
        "batches": 16, "device_resident_txn_s": 27940.6,
        "ablation": {"merge_rows_tiered_per_batch_live": 121396,
                     "pack_ms_per_group": 1.8},
        "compile_cache": {"misses": 3, "backend_compiles": 28},
        "hlo_cost": {"flops": 1e9, "bytes_accessed": 2e8},
    }
    rec = perf.bench_row_to_record(row, fingerprint=_fp())
    perf.validate_record(rec)
    m = rec["metrics"]
    assert m["txn_s"]["value"] == pytest.approx(26437.6)
    assert m["merge_rows_tiered_live"]["tier"] == "structural"
    # HLO cost numbers vary with backend/jaxlib -> hardware tier
    assert m["kernel_flops"]["tier"] == "hardware"
    assert m["compile_cache_misses"]["value"] == 3
    # compile counters depend on persistent-cache warmth (a hit skips
    # the backend compile entirely) -> hardware tier, informational:
    # a cold first run on a fresh clone must not fail the exact gate
    assert m["compile_count"] == {
        "value": 28, "unit": "count", "direction": "lower",
        "tier": "hardware",
    }
    assert m["compile_cache_misses"]["tier"] == "hardware"
    assert rec["fingerprint"]["device_kind"] == "cpu"
    assert rec["knobs"]["kernel"] == "tiered"


def test_pipeline_converter_tiers_by_mode():
    row = {
        "metric": "pipeline_commit_txn_s", "spec": "config5_ycsb_a",
        "mode": "wire", "inflight": 64, "ops_per_client": 2,
        "records": 100, "batch": 64, "kernel_txns": 64,
        "kernel": "tiered",
        "backends": {"native": {
            "txn_s": 100.0, "commit_p50_ms": 1.0, "commit_p99_ms": 2.0,
            "committed": 50, "conflicted": 5, "ops": 90,
        }},
    }
    wire = perf.pipeline_row_to_records(row)[0]
    perf.validate_record(wire)
    # wire retry counts ride real asyncio timing: hardware tier
    assert wire["metrics"]["committed"]["tier"] == "hardware"
    row["mode"] = "cluster"
    cluster = perf.pipeline_row_to_records(row)[0]
    # virtual-clock sim counts are deterministic: structural tier
    assert cluster["metrics"]["committed"]["tier"] == "structural"
    assert cluster["workload"]["resolver_backend"] == "native"


def test_saturation_converter_is_structural():
    rep = json.loads(open(os.path.join(REPO, "SATURATION_r08.json"))
                     .readline())
    rec = perf.saturation_report_to_record(rep, fingerprint=_fp())
    perf.validate_record(rec)
    assert all(m["tier"] == "structural"
               for m in rec["metrics"].values())
    assert rec["metrics"]["peak_goodput_tps"]["value"] == pytest.approx(
        221.0)
    assert rec["workload"]["admission"] is True


def test_soak_emitter_and_signature_metrics(tmp_path, monkeypatch):
    from foundationdb_tpu.testing.soak import signature_metrics

    sig = (7, 12, 3, 40, 1.25, 2, ("a",), None, "ff00", 9)
    sm = signature_metrics(sig)
    assert sm["committed"] == 12 and sm["aborted"] == 3
    assert sm["trace_digest"] == "ff00" and sm["traced_commits"] == 9
    short = signature_metrics(sig[:8])
    assert "traced_commits" not in short

    path = str(tmp_path / "soak.jsonl")
    monkeypatch.setenv("FDBTPU_PERF_LEDGER", path)
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "soak_cli", os.path.join(REPO, "scripts", "soak.py"))
        soak_cli = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(soak_cli)
        soak_cli._emit_perf_row(
            "default", [0, 1, 2], 1,
            {"committed": 30, "aborted": 2, "read_checks": 99,
             "api_acked": 4},
            17,
        )
    finally:
        sys.path.pop(0)
    rows = perf.load_history(path)
    assert len(rows) == 1
    assert rows[0]["source"] == "soak"
    assert rows[0]["metrics"]["committed"]["tier"] == "structural"
    assert rows[0]["metrics"]["traced_commits"]["value"] == 17
    assert rows[0]["workload"] == {
        "spec": "default", "seeds": [0, 2], "n_seeds": 3, "perturb": 1,
    }


# ---------------------------------------------------------------------------
# Profiling hooks.


def test_profile_trace_noop_without_dir():
    with perf.profile_trace(None):
        pass
    with perf.profile_trace(""):
        pass


def test_profile_trace_captures(tmp_path):
    import jax.numpy as jnp

    d = str(tmp_path / "prof")
    with perf.profile_trace(d):
        jnp.ones((4,)).sum().block_until_ready()
    captured = []
    for root, _dirs, files in os.walk(d):
        captured.extend(files)
    assert captured, "profiler trace produced no files"


def test_device_memory_stats_shape():
    stats = perf.device_memory_stats()
    # XLA:CPU reports nothing — the contract is 'empty dict, no error';
    # any reporting backend returns normalized int fields
    for v in stats.values():
        assert isinstance(v, int)


def test_cost_analysis_of_jitted():
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda x: (x * 2.0).sum())
    cost = perf.cost_analysis_of(fn, jnp.ones((16, 16)))
    assert cost.get("flops", 0) > 0
    assert cost.get("bytes_accessed", 0) > 0
    # failure path: a non-jitted object degrades to {}
    assert perf.cost_analysis_of(object()) == {}


def test_compile_cache_stats_surface(tmp_path, monkeypatch):
    from foundationdb_tpu.models.conflict_set import KernelStageMetrics
    from foundationdb_tpu.utils import compile_cache

    compile_cache.record_compile("sig/test", 1.25)
    st = compile_cache.stats()
    assert st["per_signature_compile_seconds"]["sig/test"] == 1.25
    before = st["cache_misses"]
    compile_cache._on_event(compile_cache._MISS_EVENT)
    compile_cache._on_duration(
        "/jax/core/compile/backend_compile_duration", 0.5)
    st2 = compile_cache.stats()
    assert st2["cache_misses"] == before + 1
    assert st2["last_compile_seconds"] == 0.5
    # the qos surface fdbtop renders (the kernel panel fields)
    qos = KernelStageMetrics().qos()
    for key in ("compile_cache_hits", "compile_cache_misses",
                "last_compile_seconds", "stage_p99_seconds",
                "device_bytes_in_use", "device_peak_bytes"):
        assert key in qos


# ---------------------------------------------------------------------------
# ISSUE 11: the per-chip scaling view + the compile-cache host scrub.


def _scaling_row(n_devices: int, txn_s: float, committed: int = 10):
    return perf.make_record(
        "multichip",
        {
            "committed": perf.metric(committed, "txns", "higher",
                                     tier="structural"),
            "txn_s": perf.metric(txn_s, "txn/s", "higher"),
        },
        workload={"n_devices": n_devices, "kernel": "tiered_sharded",
                  "batches": 8, "txns_per_batch": 12},
        knobs={"delta_capacity": 128},
        fingerprint={
            "backend": "cpu", "device_kind": "cpu", "device_count": 8,
            "jax_version": "x", "jaxlib_version": "y",
            "python_version": "z", "machine": "m",
        },
    )


def test_perfcheck_scaling_renders_curve(tmp_path):
    """--scaling groups txn_s rows by device count at a fixed
    fingerprint and prints txn/s per device + efficiency vs the
    smallest width."""
    hist = str(tmp_path / "hist.jsonl")
    for n, rate in ((1, 1000.0), (2, 1800.0), (4, 3000.0), (8, 4400.0)):
        perf.append(_scaling_row(n, rate), path=hist)
    r = _perfcheck("--scaling", "--history", hist)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "efficiency" in out
    for n in (1, 2, 4, 8):
        assert f"{n} device(s)" in out
    # efficiency vs the 1-chip row: 2 devices at 1800 -> 0.90
    assert "efficiency  0.90" in out
    # a single-width-only ledger renders the empty-state hint
    hist2 = str(tmp_path / "hist2.jsonl")
    perf.append(_scaling_row(8, 4400.0), path=hist2)
    r2 = _perfcheck("--scaling", "--history", hist2)
    assert r2.returncode == 0
    assert "no ledger group" in r2.stdout


def test_perfcheck_scaling_splits_on_knobs(tmp_path):
    """A knob change is a different experiment: rows with different
    knob fingerprints must land in different scaling groups."""
    hist = str(tmp_path / "hist.jsonl")
    perf.append(_scaling_row(1, 1000.0), path=hist)
    perf.append(_scaling_row(2, 1800.0), path=hist)
    other = _scaling_row(2, 900.0)
    other["knobs"] = {"delta_capacity": 512}
    perf.append(other, path=hist)
    r = _perfcheck("--scaling", "--history", hist)
    assert r.returncode == 0, r.stderr
    # only the delta_capacity=128 group spans two widths; the 512 row
    # alone cannot form a curve
    assert r.stdout.count("==") >= 1
    assert '"delta_capacity": 512' not in r.stdout


def test_compile_cache_scrub_on_host_mismatch(tmp_path):
    """A persistent-cache dir stamped by a DIFFERENT host — or holding
    entries with NO stamp at all (a container baked before the marker
    existed: it cannot be proven local) — is scrubbed, so stale
    XLA:CPU AOT entries never load (the MULTICHIP_r05 stderr-pollution
    fix); a dir stamped by THIS host is left alone; an EMPTY unstamped
    dir is just stamped."""
    from foundationdb_tpu.utils import compile_cache as cc

    d = tmp_path / "cache"
    d.mkdir()
    marker = d / "HOST_FINGERPRINT"
    # empty unstamped dir: stamp, nothing to scrub
    assert cc.scrub_on_host_mismatch(str(d)) is False
    assert marker.read_text().strip() == cc._host_fingerprint()
    # this host's stamp: untouched
    (d / "entry_a").write_bytes(b"aot blob")
    assert cc.scrub_on_host_mismatch(str(d)) is False
    assert (d / "entry_a").exists()
    # unstamped (legacy/pre-marker) dir WITH entries: provenance
    # unknown -> conservative scrub + stamp
    marker.unlink()
    assert cc.scrub_on_host_mismatch(str(d)) is True
    assert not (d / "entry_a").exists()
    assert marker.read_text().strip() == cc._host_fingerprint()
    # another host's stamp: entries scrubbed, marker re-stamped
    (d / "entry_a").write_bytes(b"aot blob")
    (d / "subdir").mkdir()
    (d / "subdir" / "entry_b").write_bytes(b"aot blob 2")
    marker.write_text("0" * 32 + "\n")
    assert cc.scrub_on_host_mismatch(str(d)) is True
    assert not (d / "entry_a").exists()
    assert not (d / "subdir").exists()
    assert marker.read_text().strip() == cc._host_fingerprint()
    # enable() routes through the scrub and still configures the cache
    marker.write_text("0" * 32 + "\n")
    (d / "entry_c").write_bytes(b"stale")
    path = cc.enable(str(d))
    assert path == str(d)
    assert not (d / "entry_c").exists()
