"""Metrics-layer contracts: LatencySample's relative-error guarantee,
TraceLog sink rolling (memory AND file), and the reference-style
LatencyBands — the pieces the telemetry pipeline (ISSUE 5) leans on,
previously untested."""

import json
import math

import numpy as np
import pytest

from foundationdb_tpu.utils.metrics import (
    COMMIT_LATENCY_BANDS,
    CounterCollection,
    LatencyBands,
    LatencySample,
)
from foundationdb_tpu.utils.trace import (
    SEV_DEBUG,
    SEV_INFO,
    TraceBatch,
    TraceEvent,
    TraceLog,
)

# -- LatencySample: the DDSketch relative-error contract --------------------


def _check_quantiles(samples, eps):
    """Estimated p50/p95/p99 must sit within the sketch's relative-error
    band of the EXACT empirical quantiles. The sketch guarantees every
    recorded value lands in a bucket whose midpoint is within eps of it
    (gamma = (1+eps)/(1-eps)); rank arithmetic differences add at most
    one bucket, so 3*eps is the honest tolerance."""
    s = LatencySample("t", eps=eps)
    for v in samples:
        s.sample(float(v))
    arr = np.sort(np.asarray(samples, dtype=float))
    for q in (0.50, 0.95, 0.99):
        exact = float(arr[min(len(arr) - 1, int(q * (len(arr) - 1)))])
        est = s.quantile(q)
        assert est == pytest.approx(exact, rel=3 * eps), (
            f"q={q}: est {est} vs exact {exact} (eps={eps})"
        )


@pytest.mark.parametrize("eps", [0.01, 0.05])
def test_latency_sample_uniform_distribution(eps):
    rng = np.random.default_rng(7)
    _check_quantiles(rng.uniform(0.001, 2.0, size=20_000), eps)


@pytest.mark.parametrize("eps", [0.01, 0.05])
def test_latency_sample_lognormal_distribution(eps):
    """Heavy tail: the regime latency distributions actually live in."""
    rng = np.random.default_rng(11)
    _check_quantiles(rng.lognormal(mean=-5.0, sigma=1.5, size=20_000), eps)


def test_latency_sample_exponential_and_constant():
    rng = np.random.default_rng(13)
    _check_quantiles(rng.exponential(0.01, size=20_000), 0.01)
    # constant stream: every quantile is the constant, within eps
    s = LatencySample("c", eps=0.01)
    for _ in range(1000):
        s.sample(0.125)
    for q in (0.5, 0.95, 0.99):
        assert s.quantile(q) == pytest.approx(0.125, rel=0.03)
    assert s.mean == pytest.approx(0.125)
    assert s.min == s.max == 0.125


def test_latency_sample_zero_and_negative_values():
    s = LatencySample("z")
    for v in (0.0, -1.0, 0.0, 5.0):
        s.sample(v)
    assert s.count == 4
    # zero/negative land in the zero bucket: quantiles whose rank falls
    # inside it report 0 (floor-rank convention), the top rank reaches
    # the positive bucket
    assert s.quantile(0.25) == 0.0
    assert s.quantile(1.0) == pytest.approx(5.0, rel=0.03)
    d = s.as_dict()
    assert d["count"] == 4 and d["max"] == 5.0


def test_latency_sample_wide_dynamic_range():
    """Microseconds to minutes in one sketch: the log bucketing must
    hold the relative error across ~8 decades."""
    s = LatencySample("w", eps=0.01)
    values = [10.0 ** e for e in range(-6, 3)]
    for v in values:
        s.sample(v)
    for i, v in enumerate(values):
        q = i / (len(values) - 1)
        assert s.quantile(q) == pytest.approx(v, rel=0.05)


# -- TraceLog rolling -------------------------------------------------------


def test_trace_log_memory_rolls_at_max_events():
    log = TraceLog(max_events=100)
    for i in range(1000):
        TraceEvent("E", logger=log).detail("I", i).log()
    assert len(log.events) <= 100
    # the newest events survive the roll
    assert log.events[-1]["I"] == 999


def test_trace_log_file_sink_rolls(tmp_path):
    """The file sink rotates current -> .1 at max_events: disk stays
    bounded at ~2x max_events lines, the newest generation is always in
    `path`, and every retained line is valid JSONL."""
    path = tmp_path / "trace.jsonl"
    log = TraceLog(path=str(path), max_events=10)
    for i in range(25):
        TraceEvent("E", logger=log).detail("I", i).log()
    log.close()
    rolled = tmp_path / "trace.jsonl.1"
    assert rolled.exists()
    cur = [json.loads(line) for line in path.read_text().splitlines()]
    old = [json.loads(line) for line in rolled.read_text().splitlines()]
    assert log.rolls == 2
    # events 0-9 rolled away entirely (one generation retained), 10-19
    # live in .1, 20-24 in the current file
    assert [e["I"] for e in old] == list(range(10, 20))
    assert [e["I"] for e in cur] == list(range(20, 25))


def test_trace_log_file_sink_bytes_jsonable(tmp_path):
    path = tmp_path / "t.jsonl"
    log = TraceLog(path=str(path))
    TraceEvent("E", logger=log).detail("Key", b"\xffbin").log()
    log.close()
    (rec,) = [json.loads(line) for line in path.read_text().splitlines()]
    assert rec["Key"] == b"\xffbin".decode("latin-1")


def test_trace_batch_renders_into_logger():
    """TraceBatch with a logger lands micro-events as structured records
    with the batch's own capture Time — the commit_debug input shape."""
    clock_val = [1.5]
    log = TraceLog(min_severity=SEV_DEBUG, clock=lambda: 9.9)
    tb = TraceBatch(clock=lambda: clock_val[0], logger=log)
    tb.add_event("CommitDebug", "d1", "X.Before")
    clock_val[0] = 2.5
    tb.add_attach("CommitAttachID", "d1", "b1")
    recs = log.events
    assert [r["Type"] for r in recs] == ["CommitDebug", "CommitAttachID"]
    # the explicit batch Time wins over the sink clock
    assert recs[0]["Time"] == 1.5 and recs[1]["Time"] == 2.5
    assert recs[0]["Location"] == "X.Before"
    assert recs[1]["Location"] == "attach:b1"
    # with a logger the TraceLog is the ONE sink: the unbounded
    # in-process buffer stays empty (long traced runs must not hold the
    # stream twice)
    assert tb.dump() == []
    # without a logger the buffer serves in-process readers
    tb2 = TraceBatch()
    tb2.add_event("CommitDebug", "d2", "Y.Before")
    assert [e[3] for e in tb2.dump()] == ["Y.Before"]


# -- LatencyBands -----------------------------------------------------------


def test_latency_bands_bucketing_and_overflow():
    b = LatencyBands("commit", bands=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.005, 0.05, 0.5, 5.0):
        b.add(v)
    d = b.as_dict()
    assert d["total"] == 5
    assert d["0.001"] == 1 and d["0.01"] == 1 and d["0.1"] == 1
    assert d["inf"] == 2  # past every threshold -> the overflow bucket
    assert sum(v for k, v in d.items() if k != "total") == d["total"]


def test_latency_bands_overflow_probe_fires():
    from foundationdb_tpu.utils import probes

    before = probes.snapshot().get("metrics.latency_band_overflow", 0)
    LatencyBands("x", bands=(0.001,)).add(10.0)
    after = probes.snapshot().get("metrics.latency_band_overflow", 0)
    assert after == before + 1


def test_default_band_edges_are_sorted_and_stable():
    assert list(COMMIT_LATENCY_BANDS) == sorted(COMMIT_LATENCY_BANDS)
    b = LatencyBands("c")
    assert len(b.counts) == len(COMMIT_LATENCY_BANDS) + 1


# -- KernelStageMetrics: the always-on resolver-kernel telemetry ------------


def test_kernel_stage_metrics_shape():
    from foundationdb_tpu.models.conflict_set import KernelStageMetrics

    m = KernelStageMetrics()
    d = m.as_dict()
    # counters flat, stage samples nested — the status-schema shape
    for key in ("resolveBatches", "compactions", "latchTrips",
                "exactFallbacks", "overflowRaised"):
        assert d[key] == 0
    for key in ("packSeconds", "transferSeconds", "kernelSeconds",
                "fenceSeconds", "deltaLiveBoundaries"):
        assert d[key]["count"] == 0


def test_cpu_conflict_set_counts_batches():
    from foundationdb_tpu.config import TEST_CONFIG
    from foundationdb_tpu.models.conflict_set import CpuConflictSet
    from foundationdb_tpu.models.types import CommitTransaction

    cs = CpuConflictSet(TEST_CONFIG)
    cs.resolve([CommitTransaction(write_conflict_ranges=[(b"a", b"b")])], 10)
    assert cs.metrics.counters.get("resolveBatches") == 1


@pytest.mark.kernel
def test_tpu_conflict_set_emits_stage_metrics():
    """resolve() continuously populates the pack/kernel/fence stage
    samples and the batch counter — bench.py and cluster_status read
    THESE, not private timers."""
    from foundationdb_tpu.config import TEST_CONFIG
    from foundationdb_tpu.models.conflict_set import TpuConflictSet
    from foundationdb_tpu.models.types import CommitTransaction

    cs = TpuConflictSet(TEST_CONFIG)
    for v in (10, 20, 30):
        cs.resolve(
            [CommitTransaction(
                read_conflict_ranges=[(b"k1", b"k2")],
                write_conflict_ranges=[(b"k1", b"k2")],
                read_snapshot=v - 10,
            )],
            v,
        )
    m = cs.metrics
    assert m.counters.get("resolveBatches") == 3
    assert m.pack.count == 3 and m.pack.total > 0
    assert m.kernel.count == 3 and m.kernel.total > 0
    assert m.fence.count == 3


def test_counter_flush_probe_fires():
    from foundationdb_tpu.utils import probes
    from foundationdb_tpu.utils.trace import trace_counters

    log = TraceLog(min_severity=SEV_INFO)
    c = CounterCollection("M", ["a"])
    before = probes.snapshot().get("metrics.counters_flushed", 0)
    trace_counters(log, "MetricsEvent", "r0", c)
    assert probes.snapshot()["metrics.counters_flushed"] == before + 1


# ---------------------------------------------------------------------------
# Saturation-telemetry primitives (PR 7): Smoother / TimerSmoother /
# Gauge / MetricHistory / sparkline.


def test_smoother_step_converges_to_closed_form():
    """Exponential decay vs the closed form: after a step from 0 to T,
    estimate(t) = T * (1 - exp(-t/tau)) for any sampling cadence."""
    from foundationdb_tpu.utils.metrics import Smoother

    clock = [0.0]
    for tau in (0.5, 1.0, 3.0):
        sm = Smoother(tau, clock=lambda: clock[0])
        clock[0] = 0.0
        sm.reset(0.0)
        sm.set_total(100.0)  # the step
        for t in (0.1, 0.25, tau, 2 * tau, 5 * tau):
            clock[0] = t
            want = 100.0 * (1.0 - math.exp(-t / tau))
            assert sm.smooth_total() == pytest.approx(want, rel=1e-9)
        # one folding time reflects ~63.2% of the step
        clock[0] = tau
        sm2 = Smoother(tau, clock=lambda: clock[0])
    # converged: far past tau the estimate is the total
    clock[0] = 50.0
    assert sm.smooth_total() == pytest.approx(100.0, rel=1e-6)


def test_smoother_closed_form_is_sampling_cadence_invariant():
    """Reading the estimate through many small steps must equal one
    big step (the exponential's semigroup property) — the property
    that makes status polling frequency irrelevant to the value."""
    from foundationdb_tpu.utils.metrics import Smoother

    clock = [0.0]
    a = Smoother(1.0, clock=lambda: clock[0])
    b = Smoother(1.0, clock=lambda: clock[0])
    a.set_total(42.0)
    b.set_total(42.0)
    # a: polled at every 0.01; b: read once at t=2
    for i in range(1, 201):
        clock[0] = i * 0.01
        a.smooth_total()
    assert a.smooth_total() == pytest.approx(b.smooth_total(), rel=1e-9)


def test_smoother_ramp_rate_tracks_input_rate():
    """A constant-rate ramp: smooth_rate converges to the true rate
    (the Ratekeeper's queue-bytes-per-second signal)."""
    from foundationdb_tpu.utils.metrics import Smoother

    clock = [0.0]
    sm = Smoother(1.0, clock=lambda: clock[0])
    for i in range(1, 501):
        clock[0] = i * 0.01
        sm.add_delta(5.0)  # 500/s
    assert sm.smooth_rate() == pytest.approx(500.0, rel=0.02)
    # rate decays back toward zero once input stops (exp(-10) of the
    # gap remains: ~0.023 of the 500/s peak)
    clock[0] += 10.0
    assert sm.smooth_rate() < 0.1


def test_smoother_non_advancing_clock_and_validation():
    from foundationdb_tpu.utils.metrics import Smoother

    sm = Smoother(1.0)  # default clock never advances
    sm.add_delta(10.0)
    sm.add_delta(5.0)
    assert sm.total == 15.0
    assert sm.smooth_total() == 0.0  # no time passed: no decay applied
    with pytest.raises(ValueError):
        Smoother(0.0)
    with pytest.raises(ValueError):
        Smoother(-1.0)


def test_timer_smoother_uses_wall_clock():
    import time as _time

    from foundationdb_tpu.utils.metrics import TimerSmoother

    sm = TimerSmoother(0.05)
    sm.set_total(10.0)
    _time.sleep(0.2)  # 4 folding times: ~98% reflected
    assert sm.smooth_total() > 9.0


def test_gauge_set_and_supplier():
    from foundationdb_tpu.utils.metrics import Gauge

    g = Gauge("depth")
    assert g.get() == 0.0
    g.set(7.0)
    assert g.get() == 7.0
    live = [1]
    g2 = Gauge("live", supplier=lambda: live[0] * 2.0)
    assert g2.get() == 2.0
    live[0] = 5
    assert g2.get() == 10.0


def test_metric_history_ring_wraparound():
    from foundationdb_tpu.utils.metrics import MetricHistory

    h = MetricHistory(4)
    assert len(h) == 0 and h.last() is None and h.samples() == []
    for i in range(3):
        h.append(float(i), float(i * 10))
    assert len(h) == 3
    assert h.values() == [0.0, 10.0, 20.0]
    assert h.last() == 20.0
    # wrap: capacity stays 4, oldest-first order preserved
    for i in range(3, 11):
        h.append(float(i), float(i * 10))
    assert len(h) == 4
    assert h.values() == [70.0, 80.0, 90.0, 100.0]
    assert h.samples()[0] == (7.0, 70.0)
    assert h.last() == 100.0
    with pytest.raises(ValueError):
        MetricHistory(0)


def test_sparkline_shape():
    from foundationdb_tpu.utils.metrics import sparkline

    assert sparkline([]) == ""
    assert sparkline([5.0, 5.0, 5.0]) == "▁▁▁"
    s = sparkline([0, 1, 2, 3, 4, 5, 6, 7])
    assert len(s) == 8
    assert s[0] == "▁" and s[-1] == "█"
    # width bound: only the trailing `width` samples render
    assert len(sparkline(list(range(100)), width=24)) == 24
