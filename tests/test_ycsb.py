"""YCSB letter-suite batch generation (ISSUE 14 workload breadth).

The generator must honor the packing layout contract (valid rows
contiguous, txn ids nondecreasing, padding ids == B), classify to the
expected contention profile (E = range_heavy — the profile that now
stays on device with the sweep configured), and resolve decision-
identically to the oracle through the sweep kernel.
"""

from __future__ import annotations

import numpy as np
import pytest

from foundationdb_tpu.config import KernelConfig
from foundationdb_tpu.models.conflict_set import (
    backend_for_profile,
    profile_batch,
)
from foundationdb_tpu.testing.benchgen import YCSB_MIXES, ycsb_batch


def cfg(cap=1024):
    return KernelConfig(
        max_key_bytes=8, max_txns=cap, max_reads=cap, max_writes=cap,
        history_capacity=12 * cap, window_versions=1_000_000,
    )


def gen(letter, n=1024, **kw):
    rng = np.random.default_rng(5)
    return ycsb_batch(
        rng, cfg(), n, letter, version=200_000, keyspace=1_000_000,
        snapshot_lag=400_000, insert_frontier=500_000, **kw,
    )


@pytest.mark.parametrize("letter", sorted(YCSB_MIXES))
def test_layout_contract(letter):
    b = gen(letter)
    cap = cfg().max_txns
    for txn, n, valid in (
        (b.read_txn, b.n_reads, b.read_valid),
        (b.write_txn, b.n_writes, b.write_valid),
    ):
        assert valid[:n].all() and not valid[n:].any()
        if n:
            assert (np.diff(txn[:n]) >= 0).all(), "txn ids nondecreasing"
            assert (txn[:n] < cap).all()
        assert (txn[n:] == cap).all(), "padding rows carry txn id == B"
    # read-only letters carry no write rows at all
    if YCSB_MIXES[letter][2] == 0.0:
        assert b.n_writes == 0
    # begins < ends on every valid row
    for beg, end, n in ((b.read_begin, b.read_end, b.n_reads),
                        (b.write_begin, b.write_end, b.n_writes)):
        for r in range(min(n, 64)):
            assert tuple(beg[r]) < tuple(end[r])


def test_profiles_and_routing():
    """E classifies range_heavy and stays on device exactly when the
    sweep is configured; B's zipf updates classify hot_key."""
    import dataclasses

    assert profile_batch(gen("ycsb_e", zipf=1.1, scan_max=100)) == (
        "range_heavy"
    )
    assert profile_batch(gen("ycsb_b", zipf=1.1)) == "hot_key"
    sweep = dataclasses.replace(
        cfg(), delta_capacity=4096, range_sweep=True, delta_spill=True
    )
    assert backend_for_profile("range_heavy", sweep) == "tpu"
    assert backend_for_profile("range_heavy", cfg()) == "cpu"


@pytest.mark.kernel
def test_ycsb_e_sweep_oracle_parity():
    """A YCSB-E stream through the sweep+spill kernel vs the native
    skip-list baseline (the bench's decision-parity contract at small
    shape)."""
    import dataclasses

    from foundationdb_tpu.models.conflict_set import TpuConflictSet
    from foundationdb_tpu.native import NativeSkipListConflictSet
    from foundationdb_tpu.testing.benchgen import flatten_for_native

    config = dataclasses.replace(
        KernelConfig(
            max_key_bytes=8, max_txns=64, max_reads=64, max_writes=64,
            history_capacity=1 << 10, window_versions=500_000,
        ),
        delta_capacity=256, compact_interval=0,
        range_sweep=True, delta_spill=True,
    )
    rng = np.random.default_rng(12)
    batches = [
        ycsb_batch(
            rng, config, 48, "ycsb_e", version=(i + 1) * 100_000,
            keyspace=100_000, zipf=1.1, scan_max=100, snapshot_lag=200_000,
        )
        for i in range(6)
    ]
    cpu = NativeSkipListConflictSet(window=config.window_versions)
    cs = TpuConflictSet(config)
    for b in batches:
        (rk, ro, rt), (wk, wo, wt) = (
            flatten_for_native(b, "r"), flatten_for_native(b, "w")
        )
        want = cpu.resolve_raw(
            int(b.version), b.snapshot[:48].astype(np.int64),
            rk, ro, rt, wk, wo, wt,
        )
        got = np.asarray(cs.resolve_packed(b).verdict)[:48]
        np.testing.assert_array_equal(got, want)
    assert cs.metrics.counters.get("sweepGroups") == len(batches)
    assert cs.metrics.counters.get("spills") > 0
    assert cs.metrics.counters.get("exactFallbacks") == 0
