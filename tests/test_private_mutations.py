"""Resolver private mutations + txnStateStore (VERDICT r1 task 8).

The PROXY_USE_RESOLVER_PRIVATE_MUTATIONS knob
(fdbclient/ServerKnobs.cpp:549-550; Resolver.actor.cpp:372-441): when on,
resolvers materialize committed state-transaction metadata into their own
txnStateStore and proxies consume resolver-generated private mutations
instead of re-deriving metadata. Acceptance: the same workload with the
knob on and off produces identical cluster txn-state stores and storage
state, and the resolver-side store matches the cluster's.
"""

import numpy as np
import pytest

from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.utils.knobs import SERVER_KNOBS


@pytest.fixture(autouse=True)
def _reset_knobs():
    yield
    SERVER_KNOBS.reset()


def run_workload(private: bool, n_resolvers: int = 2):
    SERVER_KNOBS.set("PROXY_USE_RESOLVER_PRIVATE_MUTATIONS", private)
    sched, cluster, db = open_cluster(
        ClusterConfig(
            n_commit_proxies=2, n_resolvers=n_resolvers, n_storage=2
        )
    )

    async def go():
        rng = np.random.default_rng(7)
        for i in range(30):
            t = db.create_transaction()
            if i % 3 == 0:
                # metadata write into the system keyspace (a state txn)
                t.set(b"\xff/conf/knob%02d" % (i % 7), b"v%d" % i)
            # ordinary data write in the same or separate txn
            t.set(b"user%03d" % int(rng.integers(0, 50)), b"d%d" % i)
            await t.commit()
        # a clear of part of the system keyspace (state txn with clear)
        t = db.create_transaction()
        t.clear_range(b"\xff/conf/knob00", b"\xff/conf/knob03")
        await t.commit()

    task = sched.spawn(go(), name="workload")
    sched.run_until(task.done)
    task.done.get()

    state_store = dict(cluster.txn_state_store)
    resolver_stores = [dict(r.txn_state_store) for r in cluster.resolvers]
    data = {}
    for ss in cluster.storage_servers:
        data.update(ss._data)
    cluster.stop()
    return state_store, resolver_stores, data


def test_knob_on_off_parity_multi_resolver():
    """Externally observable state identical knob on/off — including
    under multi-resolver sharding, where the proxy filters resolver
    candidates by the GLOBAL verdict."""
    off_state, off_res, off_data = run_workload(private=False)
    on_state, on_res, on_data = run_workload(private=True)

    assert on_state == off_state
    assert on_data == off_data
    assert len(on_state) > 0  # the workload actually exercised metadata
    # knob off: resolvers never materialize
    for store in off_res:
        assert store == {}


def test_knob_on_single_resolver_store_materializes():
    """With one resolver the local verdict IS the global one, so the
    resolver-side txnStateStore is authoritative and must equal the
    cluster's metadata store exactly."""
    off_state, _off_res, off_data = run_workload(
        private=False, n_resolvers=1
    )
    on_state, on_res, on_data = run_workload(private=True, n_resolvers=1)
    assert on_state == off_state
    assert on_data == off_data
    assert len(on_state) > 0
    assert on_res[0] == on_state


def test_private_mutations_in_reply():
    """With the knob on, replies carry this batch's committed metadata
    as resolver-generated private mutations."""
    from foundationdb_tpu.config import TEST_CONFIG
    from foundationdb_tpu.models.types import (
        CommitTransaction,
        ResolveTransactionBatchRequest,
        TransactionResult,
    )
    from foundationdb_tpu.resolver import Resolver
    from foundationdb_tpu.runtime.flow import Scheduler

    SERVER_KNOBS.set("PROXY_USE_RESOLVER_PRIVATE_MUTATIONS", True)
    sched = Scheduler(sim=True)
    res = Resolver(sched, TEST_CONFIG, backend="cpu")

    async def go():
        # master bootstrap batch
        await res.resolve(
            ResolveTransactionBatchRequest(
                prev_version=-1, version=0, last_received_version=-1
            )
        )
        rep = await res.resolve(
            ResolveTransactionBatchRequest(
                prev_version=0,
                version=10,
                last_received_version=0,
                transactions=[
                    CommitTransaction(
                        mutations=[
                            ("set", b"\xff/meta", b"m1"),
                            ("set", b"user", b"not-metadata"),
                        ]
                    )
                ],
                txn_state_transactions=[0],
                proxy_id="p0",
            )
        )
        return rep

    t = sched.spawn(go(), name="drive")
    sched.run_until(t.done)
    rep = t.done.get()
    assert rep.committed[0] == TransactionResult.COMMITTED
    # only the metadata mutation is private; the user write is not
    assert rep.private_mutations == {0: [("set", b"\xff/meta", b"m1")]}
    assert res.txn_state_store == {b"\xff/meta": b"m1"}
