"""Packed-key ordering must match Python bytes ordering exactly."""

import numpy as np
import jax.numpy as jnp
import pytest

from conftest import random_key
from foundationdb_tpu.ops import keys as K
from foundationdb_tpu.utils import packing

MAXB = 8


def _pack(bs):
    return jnp.asarray(packing.pack_keys(bs, MAXB))


def test_pack_unpack_roundtrip(rng):
    ks = [random_key(rng, MAXB, 256) for _ in range(200)]
    arr = packing.pack_keys(ks, MAXB)
    for i, k in enumerate(ks):
        assert packing.unpack_key(arr[i]) == k


def test_long_key_conservative_truncation():
    # over-width keys truncate; end keys round UP (length = max+1) so
    # packed ranges are supersets of the real ones
    begin = packing.pack_key(b"x" * 9, MAXB)
    end = packing.pack_key(b"x" * 9, MAXB, round_up=True)
    exact = packing.pack_key(b"x" * 8, MAXB)
    assert begin[-1] == MAXB
    assert end[-1] == MAXB + 1
    assert (begin[:-1] == exact[:-1]).all()
    # order: begin (len 8) < end (len 9) at equal bytes
    assert tuple(begin) < tuple(end)


def test_lex_less_matches_bytes(rng):
    ks = [random_key(rng, MAXB, 3) for _ in range(300)]
    a = [ks[int(i)] for i in rng.integers(0, len(ks), 500)]
    b = [ks[int(i)] for i in rng.integers(0, len(ks), 500)]
    got = np.asarray(K.lex_less(_pack(a), _pack(b)))
    want = np.array([x < y for x, y in zip(a, b)])
    np.testing.assert_array_equal(got, want)


def test_shorter_before_longer():
    a = _pack([b"a", b"a\x00", b"a\x00\x00"])
    assert bool(K.lex_less(a[0:1], a[1:2])[0])
    assert bool(K.lex_less(a[1:2], a[2:3])[0])
    assert not bool(K.lex_less(a[1:2], a[0:1])[0])


def test_searchsorted_matches_numpy(rng):
    ks = sorted({random_key(rng, MAXB, 4) for _ in range(100)})
    queries = [random_key(rng, MAXB, 4) for _ in range(400)] + list(ks)
    m = 128  # capacity > len(ks), tail = sentinel
    arr = np.full((m, MAXB // 4 + 1), 0xFFFFFFFF, np.uint32)
    arr[: len(ks)] = packing.pack_keys(ks, MAXB)
    q = _pack(queries)
    for side in ("left", "right"):
        got = np.asarray(K.searchsorted(jnp.asarray(arr), q, side=side))
        want = np.array([
            __import__("bisect").bisect_left(ks, x) if side == "left"
            else __import__("bisect").bisect_right(ks, x)
            for x in queries
        ])
        np.testing.assert_array_equal(got, want)


def test_sort_ranks(rng):
    ks = [random_key(rng, MAXB, 3) for _ in range(64)]
    valid = rng.random(64) < 0.8
    pts = _pack(ks)
    ranks, ukeys, ucount = K.sort_ranks(pts, jnp.asarray(valid))
    distinct = sorted({k for k, v in zip(ks, valid) if v})
    assert int(ucount) == len(distinct)
    for i, (k, v) in enumerate(zip(ks, valid)):
        if v:
            assert int(ranks[i]) == distinct.index(k)
            assert packing.unpack_key(np.asarray(ukeys[int(ranks[i])])) == k
