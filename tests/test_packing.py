"""Vectorized packer regression: byte-identical to the loop packer.

The r6 bulk-numpy pack_batch/pack_keys (repeat/cumsum over pre-flattened
range lists, one joined key blob) must produce EXACTLY the tensors of
the pre-r6 per-txn append-loop packer, kept verbatim as
pack_batch_reference / _pack_keys_reference — any drift here is a silent
kernel-input change, which is a decision change.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from foundationdb_tpu.config import KernelConfig
from foundationdb_tpu.models.types import CommitTransaction
from foundationdb_tpu.utils import packing


def small_config(**kw):
    d = dict(
        max_key_bytes=8,
        max_txns=64,
        max_reads=256,
        max_writes=256,
        history_capacity=1 << 10,
        window_versions=1000,
    )
    d.update(kw)
    return KernelConfig(**d)


def random_key(rng, max_len=12):
    # deliberately past max_key_bytes sometimes: the conservative
    # truncation path must match too
    n = int(rng.integers(0, max_len + 1))
    return bytes(rng.integers(0, 256, size=n, dtype=np.uint8))


def random_range(rng, max_len=12):
    a, b = sorted([random_key(rng, max_len), random_key(rng, max_len)])
    if a == b:
        b = a + b"\x00"
    return (a, b)


def random_txn(rng, snap_lo=-2000, snap_hi=5000):
    reads = [random_range(rng) for _ in range(int(rng.integers(0, 4)))]
    writes = [random_range(rng) for _ in range(int(rng.integers(0, 4)))]
    return CommitTransaction(
        read_conflict_ranges=reads,
        write_conflict_ranges=writes,
        read_snapshot=int(rng.integers(snap_lo, snap_hi)),
    )


def assert_batches_identical(a, b):
    for f in dataclasses.fields(packing.PackedBatch):
        va, vb = getattr(a, f.name), getattr(b, f.name)
        if isinstance(va, np.ndarray):
            np.testing.assert_array_equal(va, vb, err_msg=f.name)
            assert va.dtype == vb.dtype, f.name
        else:
            assert va == vb, f.name


@pytest.mark.parametrize("seed", range(12))
def test_pack_batch_byte_identical_random(seed):
    rng = np.random.default_rng(seed)
    config = small_config()
    n = int(rng.integers(0, config.max_txns + 1))
    txns = [random_txn(rng) for _ in range(n)]
    version = int(rng.integers(1000, 100000))
    base = int(rng.integers(0, 900))
    got = packing.pack_batch(txns, version, base, config)
    want = packing.pack_batch_reference(txns, version, base, config)
    assert_batches_identical(got, want)


def test_pack_batch_empty():
    config = small_config()
    assert_batches_identical(
        packing.pack_batch([], 100, 0, config),
        packing.pack_batch_reference([], 100, 0, config),
    )


def test_pack_batch_edge_shapes():
    """Blind writes, read-only txns, empty ranges lists, stale
    snapshots clamped at VERSION_NEG, keys exactly at/over the cap."""
    config = small_config()
    k8 = bytes(range(8))          # exactly max_key_bytes
    k9 = bytes(range(9))          # one over: conservative truncation
    txns = [
        CommitTransaction([], [(k8, k9)], read_snapshot=50),
        CommitTransaction([(k8, k8 + b"\x00")], [], read_snapshot=-(2**40)),
        CommitTransaction([], [], read_snapshot=70),
        CommitTransaction(
            [(b"", b"\x00"), (k9, k9 + b"\xff")], [(b"a", b"b")],
            read_snapshot=90,
        ),
    ]
    assert_batches_identical(
        packing.pack_batch(txns, 100, 0, config),
        packing.pack_batch_reference(txns, 100, 0, config),
    )


@pytest.mark.parametrize("round_up", [False, True])
def test_pack_keys_byte_identical(round_up):
    rng = np.random.default_rng(7)
    keys = [random_key(rng, max_len=20) for _ in range(200)] + [b"", b"\xff" * 8]
    got = packing.pack_keys(keys, 8, round_up=round_up)
    want = packing._pack_keys_reference(keys, 8, round_up=round_up)
    np.testing.assert_array_equal(got, want)
    assert got.dtype == want.dtype


def test_pack_batch_error_parity():
    config = small_config(max_txns=4, max_reads=4, max_writes=4)
    too_many = [random_txn(np.random.default_rng(0)) for _ in range(5)]
    for fn in (packing.pack_batch, packing.pack_batch_reference):
        with pytest.raises(ValueError, match="max_txns"):
            fn(too_many, 100, 0, config)
    crowded = [
        CommitTransaction(
            [(b"a", b"b")] * 3, [(b"a", b"b")], read_snapshot=1
        )
        for _ in range(2)
    ]
    for fn in (packing.pack_batch, packing.pack_batch_reference):
        with pytest.raises(ValueError, match="max_reads"):
            fn(crowded, 100, 0, config)
    writes_heavy = [
        CommitTransaction([], [(b"a", b"b")] * 3, read_snapshot=1)
        for _ in range(2)
    ]
    for fn in (packing.pack_batch, packing.pack_batch_reference):
        with pytest.raises(ValueError, match="max_writes"):
            fn(writes_heavy, 100, 0, config)
    overflow = [CommitTransaction([], [], read_snapshot=2**40)]
    for fn in (packing.pack_batch, packing.pack_batch_reference):
        with pytest.raises(OverflowError, match="rebase"):
            fn(overflow, 100, 0, config)


# ---------------------------------------------------------------------------
# Columnar packer (r12): the wire-to-kernel path must be byte-identical
# to pack_batch — three packers, one contract.


def random_report_txn(rng, snap_lo=-2000, snap_hi=5000):
    t = random_txn(rng, snap_lo, snap_hi)
    t.report_conflicting_keys = bool(rng.random() < 0.5)
    return t


@pytest.mark.parametrize("seed", range(12))
def test_pack_batch_columnar_byte_identical_random(seed):
    rng = np.random.default_rng(100 + seed)
    config = small_config()
    n = int(rng.integers(0, config.max_txns + 1))
    txns = [random_report_txn(rng) for _ in range(n)]
    version = int(rng.integers(1000, 100000))
    base = int(rng.integers(0, 1000))
    cols = packing.pack_columnar(txns)
    assert_batches_identical(
        packing.pack_batch(txns, version, base, config),
        packing.pack_batch_columnar(cols, version, base, config),
    )


def test_pack_batch_columnar_empty_and_edges():
    config = small_config()
    assert_batches_identical(
        packing.pack_batch([], 100, 0, config),
        packing.pack_batch_columnar(packing.pack_columnar([]), 100, 0, config),
    )
    # blind writes, read-only txns, long keys past max_key_bytes, and
    # snapshots clamped at the VERSION_NEG floor
    txns = [
        CommitTransaction([], [(b"w" * 20, b"w" * 30)], read_snapshot=1),
        CommitTransaction([(b"", b"\x00")], [], read_snapshot=-(2**33)),
        CommitTransaction(
            [(b"a", b"a" * 25), (b"b", b"c")], [(b"q", b"r")],
            read_snapshot=4000, report_conflicting_keys=True,
        ),
    ]
    assert_batches_identical(
        packing.pack_batch(txns, 100, 0, config),
        packing.pack_batch_columnar(
            packing.pack_columnar(txns), 100, 0, config
        ),
    )


def test_pack_batch_columnar_error_parity():
    config = small_config(max_txns=4, max_reads=4, max_writes=4)
    crowded = [
        CommitTransaction([(b"a", b"b")] * 3, [(b"a", b"b")], read_snapshot=1)
        for _ in range(2)
    ]
    with pytest.raises(ValueError, match="max_reads"):
        packing.pack_batch_columnar(
            packing.pack_columnar(crowded), 100, 0, config
        )
    overflow = [CommitTransaction([], [], read_snapshot=2**40)]
    with pytest.raises(OverflowError, match="rebase"):
        packing.pack_batch_columnar(
            packing.pack_columnar(overflow), 100, 0, config
        )


@pytest.mark.parametrize("round_up", [False, True])
def test_pack_keys_from_blob_byte_identical(round_up):
    rng = np.random.default_rng(9)
    keys = [random_key(rng) for _ in range(64)]
    lens = np.array([len(k) for k in keys], np.int64)
    cat = np.frombuffer(b"".join(keys), np.uint8)
    got = packing.pack_keys_from_blob(
        cat, np.cumsum(lens) - lens, lens, 8, round_up=round_up
    )
    want = packing._pack_keys_reference(keys, 8, round_up=round_up)
    np.testing.assert_array_equal(got, want)
    # and from a NON-tight blob (keys at arbitrary offsets, the wire
    # frame's shape when sliced views land mid-payload)
    pad = b"\xff" * 3
    blob2 = pad + pad.join(keys)
    starts2 = np.empty_like(lens)
    off = len(pad)
    for i, k in enumerate(keys):
        starts2[i] = off
        off += len(k) + len(pad)
    got2 = packing.pack_keys_from_blob(
        np.frombuffer(blob2, np.uint8), starts2, lens, 8, round_up=round_up
    )
    np.testing.assert_array_equal(got2, want)


def test_columnar_to_transactions_roundtrip():
    rng = np.random.default_rng(11)
    txns = [random_report_txn(rng) for _ in range(20)]
    back = packing.columnar_to_transactions(packing.pack_columnar(txns))
    assert len(back) == len(txns)
    for t0, t1 in zip(txns, back):
        assert t0.read_conflict_ranges == t1.read_conflict_ranges
        assert t0.write_conflict_ranges == t1.write_conflict_ranges
        assert t0.read_snapshot == t1.read_snapshot
        assert t0.report_conflicting_keys == t1.report_conflicting_keys
