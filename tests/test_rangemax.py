"""Sparse-table and segment-tree primitives vs. brute force."""

import numpy as np
import jax.numpy as jnp

from foundationdb_tpu.ops import rangemax, segtree


def test_rangemax_brute(rng):
    m = 64
    vals = jnp.asarray(rng.integers(-100, 100, m), jnp.int32)
    tab = rangemax.build(vals, op="max")
    lo = rng.integers(-2, m + 2, 300).astype(np.int32)
    hi = rng.integers(-2, m + 2, 300).astype(np.int32)
    got = np.asarray(rangemax.query(tab, jnp.asarray(lo), jnp.asarray(hi), op="max"))
    v = np.asarray(vals)
    for i in range(len(lo)):
        a, b = max(int(lo[i]), 0), min(int(hi[i]), m)
        want = v[a:b].max() if b > a else int(rangemax.INT32_NEG)
        assert got[i] == want, (lo[i], hi[i])


def test_rangemin_brute(rng):
    m = 32
    vals = jnp.asarray(rng.integers(-100, 100, m), jnp.int32)
    tab = rangemax.build(vals, op="min")
    lo = rng.integers(0, m, 200).astype(np.int32)
    hi = rng.integers(0, m + 1, 200).astype(np.int32)
    got = np.asarray(rangemax.query(tab, jnp.asarray(lo), jnp.asarray(hi), op="min"))
    v = np.asarray(vals)
    for i in range(len(lo)):
        a, b = int(lo[i]), int(hi[i])
        want = v[a:b].min() if b > a else int(rangemax.INT32_POS)
        assert got[i] == want


def test_segtree_min_cover_brute(rng):
    leaves = 64
    n = 50
    lo = rng.integers(0, leaves, n).astype(np.int32)
    hi = rng.integers(0, leaves + 1, n).astype(np.int32)
    val = rng.integers(0, 1000, n).astype(np.int32)
    # disable some updates
    val[rng.random(n) < 0.3] = int(segtree.INT32_POS)
    got = np.asarray(
        segtree.min_cover(leaves, jnp.asarray(lo), jnp.asarray(hi), jnp.asarray(val))
    )
    want = np.full(leaves, int(segtree.INT32_POS), np.int64)
    for j in range(n):
        for v in range(int(lo[j]), int(hi[j])):
            want[v] = min(want[v], int(val[j]))
    np.testing.assert_array_equal(got, want)


def test_segtree_empty_updates():
    leaves = 16
    lo = jnp.asarray([5, 9], jnp.int32)
    hi = jnp.asarray([5, 3], jnp.int32)  # empty and inverted
    val = jnp.asarray([1, 2], jnp.int32)
    got = np.asarray(segtree.min_cover(leaves, lo, hi, val))
    assert (got == int(segtree.INT32_POS)).all()


def test_two_level_table_matches_flat():
    """build2/query2 (the low-traffic two-level structure the group
    kernel's cross phase uses) must agree with the flat doubling table
    on every span class: sub-chunk, chunk-straddling, wide, clamped."""
    import numpy as np

    from foundationdb_tpu.ops import rangemax as rm

    rng = np.random.default_rng(5)
    for m in (100, 1024, 4097):
        vals = rng.integers(-2**30, 2**30, size=m).astype(np.int32)
        q = 512
        lo = rng.integers(-5, m + 5, size=q).astype(np.int32)
        length = np.where(
            rng.random(q) < 0.5,
            rng.integers(0, 40, size=q),      # sub/at-chunk spans
            rng.integers(40, m + 64, size=q),  # wide spans
        )
        hi = (lo + length).astype(np.int32)
        for op in ("max", "min"):
            flat = rm.build(jnp.asarray(vals), op=op)
            two = rm.build2(jnp.asarray(vals), op=op)
            want = np.asarray(rm.query(flat, lo, hi, op=op))
            got = np.asarray(rm.query2(two, lo, hi, op=op))
            assert (got == want).all(), (
                m, op, lo[got != want][:4], hi[got != want][:4]
            )


def test_radix4_parity_with_radix2():
    """build4/query4 and min_cover4 agree with the radix-2 structures
    on randomized ranges (the fixpoint switched to radix-4 in r5)."""
    import numpy as np

    from foundationdb_tpu.ops import rangemax, segtree

    rng = np.random.default_rng(42)
    for leaves in (1024, 4096, 131072):  # incl. an odd-log2 width
        vals = jnp.asarray(
            rng.integers(0, 1 << 30, leaves).astype(np.int32))
        q = 2048
        lo = jnp.asarray(rng.integers(0, leaves, q).astype(np.int32))
        ln = jnp.asarray(rng.integers(0, leaves, q).astype(np.int32))
        hi = jnp.minimum(lo + ln, leaves)
        for op in ("max", "min"):
            t2 = rangemax.build(vals, op=op)
            t4 = rangemax.build4(vals, op=op)
            g2 = np.asarray(rangemax.query(t2, lo, hi, op=op))
            g4 = np.asarray(rangemax.query4(t4, lo, hi, op=op))
            assert (g2 == g4).all(), (leaves, op)

        n_int = 4096
        ilo = jnp.asarray(rng.integers(0, leaves, n_int).astype(np.int32))
        iln = jnp.asarray(
            rng.integers(0, max(leaves // 4, 2), n_int).astype(np.int32))
        ihi = jnp.minimum(ilo + iln, leaves)
        ival = jnp.asarray(rng.integers(0, n_int, n_int).astype(np.int32))
        c2 = np.asarray(segtree.min_cover(leaves, ilo, ihi, ival))
        c4 = np.asarray(segtree.min_cover4(leaves, ilo, ihi, ival))
        assert (c2 == c4).all(), leaves
