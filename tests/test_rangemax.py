"""Sparse-table and segment-tree primitives vs. brute force."""

import numpy as np
import jax.numpy as jnp

from foundationdb_tpu.ops import rangemax, segtree


def test_rangemax_brute(rng):
    m = 64
    vals = jnp.asarray(rng.integers(-100, 100, m), jnp.int32)
    tab = rangemax.build(vals, op="max")
    lo = rng.integers(-2, m + 2, 300).astype(np.int32)
    hi = rng.integers(-2, m + 2, 300).astype(np.int32)
    got = np.asarray(rangemax.query(tab, jnp.asarray(lo), jnp.asarray(hi), op="max"))
    v = np.asarray(vals)
    for i in range(len(lo)):
        a, b = max(int(lo[i]), 0), min(int(hi[i]), m)
        want = v[a:b].max() if b > a else int(rangemax.INT32_NEG)
        assert got[i] == want, (lo[i], hi[i])


def test_rangemin_brute(rng):
    m = 32
    vals = jnp.asarray(rng.integers(-100, 100, m), jnp.int32)
    tab = rangemax.build(vals, op="min")
    lo = rng.integers(0, m, 200).astype(np.int32)
    hi = rng.integers(0, m + 1, 200).astype(np.int32)
    got = np.asarray(rangemax.query(tab, jnp.asarray(lo), jnp.asarray(hi), op="min"))
    v = np.asarray(vals)
    for i in range(len(lo)):
        a, b = int(lo[i]), int(hi[i])
        want = v[a:b].min() if b > a else int(rangemax.INT32_POS)
        assert got[i] == want


def test_segtree_min_cover_brute(rng):
    leaves = 64
    n = 50
    lo = rng.integers(0, leaves, n).astype(np.int32)
    hi = rng.integers(0, leaves + 1, n).astype(np.int32)
    val = rng.integers(0, 1000, n).astype(np.int32)
    # disable some updates
    val[rng.random(n) < 0.3] = int(segtree.INT32_POS)
    got = np.asarray(
        segtree.min_cover(leaves, jnp.asarray(lo), jnp.asarray(hi), jnp.asarray(val))
    )
    want = np.full(leaves, int(segtree.INT32_POS), np.int64)
    for j in range(n):
        for v in range(int(lo[j]), int(hi[j])):
            want[v] = min(want[v], int(val[j]))
    np.testing.assert_array_equal(got, want)


def test_segtree_empty_updates():
    leaves = 16
    lo = jnp.asarray([5, 9], jnp.int32)
    hi = jnp.asarray([5, 3], jnp.int32)  # empty and inverted
    val = jnp.asarray([1, 2], jnp.int32)
    got = np.asarray(segtree.min_cover(leaves, lo, hi, val))
    assert (got == int(segtree.INT32_POS)).all()
