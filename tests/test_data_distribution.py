"""ShardMap + DataDistribution / MoveKeys tests."""

import pytest

from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.cluster.shardmap import ShardMap


def run(sched, coro):
    return sched.run_until(sched.spawn(coro).done)


# -- ShardMap -------------------------------------------------------------

def test_shardmap_lookup_and_move():
    sm = ShardMap.even([b"h", b"p"])  # 3 shards: [..h) [h..p) [p..)
    assert sm.shard_of(b"a") == 0
    assert sm.shard_of(b"h") == 1
    assert sm.shard_of(b"z") == 2
    assert sm.shards_of_range(b"g", b"q") == [0, 1, 2]

    sm.move(b"j", b"m", 0)  # carve [j, m) out of shard 1 for server 0
    assert sm.shard_of(b"k") == 0
    assert sm.shard_of(b"i") == 1
    assert sm.shard_of(b"n") == 1
    assert sm.shards_of_range(b"i", b"n") == [0, 1]

    sm.move(b"", None, 2)  # everything to server 2 -> coalesces to 1 seg
    assert sm.boundaries == []
    assert sm.owners == [(2,)]


def test_shardmap_segments_in():
    sm = ShardMap.even([b"h"])
    segs = sm.segments_in(b"d", b"z")
    assert segs == [(b"d", b"h", (0,)), (b"h", b"z", (1,))]


def test_shardmap_teams():
    sm = ShardMap.even([b"h", b"p"], replication=2, n_servers=3)
    assert sm.owners == [(0, 1), (1, 2), (2, 0)]
    assert sm.team_of(b"a") == (0, 1)
    assert sm.shard_of(b"a") == 0
    assert sm.tags_of_range(b"a", b"z") == [0, 1, 2]


# -- MoveKeys through the live cluster ------------------------------------

@pytest.fixture
def world():
    sched, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=2, n_storage=2)
    )
    yield sched, cluster, db
    cluster.stop()


def test_move_shard_preserves_data_and_routing(world):
    sched, cluster, db = world
    dd = cluster.data_distributor

    async def body():
        txn = db.create_transaction()
        for i in range(20):
            txn.set(b"mv%02d" % i, b"v%d" % i)  # all on shard 0 (< 0x80)
        await txn.commit()
        assert cluster.key_servers.shard_of(b"mv05") == 0

        await dd.move_shard(b"mv05", b"mv15", 1)
        assert cluster.key_servers.shard_of(b"mv07") == 1
        assert cluster.key_servers.shard_of(b"mv04") == 0
        # the old owner drops once it has applied everything tagged to it
        # before the flip (the post-flip fence) — let that land
        await sched.delay(0.1)
        # moved span lives on server 1 now, dropped from server 0
        assert b"mv07" in cluster.storage_servers[1]._data
        assert b"mv07" not in cluster.storage_servers[0]._data
        assert b"mv04" in cluster.storage_servers[0]._data

        # reads still see everything, writes route to the new owner
        txn = db.create_transaction()
        items = await txn.get_range(b"mv", b"mw")
        txn.set(b"mv09", b"updated")
        await txn.commit()
        txn = db.create_transaction()
        return items, await txn.get(b"mv09")

    items, updated = run(sched, body())
    assert [k for k, _ in items] == [b"mv%02d" % i for i in range(20)]
    assert updated == b"updated"
    assert b"mv09" in cluster.storage_servers[1]._data


def test_move_shard_with_concurrent_writes(world):
    sched, cluster, db = world
    dd = cluster.data_distributor

    async def writer(stop_flag):
        i = 0
        while not stop_flag:
            txn = db.create_transaction()
            txn.set(b"cw%02d" % (i % 15), b"gen%d" % i)
            try:
                await txn.commit()
            except Exception:
                pass
            i += 1
            await sched.delay(0.002)

    async def body():
        txn = db.create_transaction()
        for i in range(15):
            txn.set(b"cw%02d" % i, b"init")
        await txn.commit()

        stop_flag = []
        w = sched.spawn(writer(stop_flag))
        await sched.delay(0.02)
        await dd.move_shard(b"cw", b"cx", 1)
        await sched.delay(0.05)  # writes continue against the new owner
        stop_flag.append(True)
        w.cancel()

        txn = db.create_transaction()
        items = await txn.get_range(b"cw", b"cx")
        # the new owner's data must match what clients read
        ss1 = {k: v for k, v in cluster.storage_servers[1]._data.items()
               if k.startswith(b"cw")}
        return items, ss1

    items, ss1 = run(sched, body())
    assert len(items) == 15
    assert dict(items) == ss1


def test_dd_balancer_moves_hot_shard(world):
    sched, cluster, db = world

    async def body():
        # pile 40 keys onto shard 0; shard 1 has 2 keys
        txn = db.create_transaction()
        for i in range(40):
            txn.set(b"hot%03d" % i, b"x")
        txn.set(b"\xf0a", b"x")
        txn.set(b"\xf0b", b"x")
        await txn.commit()
        await sched.delay(3.0)  # let the DD loop rebalance
        return cluster.data_distributor.key_counts()

    counts = run(sched, body())
    assert cluster.data_distributor.counters.get("moves") >= 1
    # no data lost
    assert sum(counts) == 42

    async def verify():
        txn = db.create_transaction()
        return len(await txn.get_range(b"hot", b"hou"))

    assert run(sched, verify()) == 40
