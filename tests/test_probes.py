"""CODE_PROBE coverage: every declared probe must be reachable.

The reference's CI asserts each CODE_PROBE fires somewhere across the
Joshua ensemble (flow/CodeProbe.h + coveragetool). Here: a few ensemble
seeds cover the common rare paths, targeted scenarios drive the rest,
and the test fails if ANY declared probe never fired — so a probe going
dark (dead path or broken randomization) is caught in CI.
"""

import numpy as np
import pytest

from foundationdb_tpu.utils import probes


@pytest.fixture(autouse=True)
def _fresh_probes():
    probes.reset()
    yield


def drive(sched, coro):
    t = sched.spawn(coro, name="drive")
    sched.run_until(t.done)
    return t.done.get()


def test_every_declared_probe_fires():
    from foundationdb_tpu.testing.soak import run_seed

    # -- ensemble seeds: recovery, state txns, conservative writes;
    # seed 29 draws atomic_ops + overload_burst under the r8 draw order
    # (the admission burst sheds at the bounded GRV queue and throttles
    # the budget) ------
    for seed in (3, 5, 29):
        run_seed(seed)

    # -- resolver rare paths --------------------------------------------
    from foundationdb_tpu.config import TEST_CONFIG
    from foundationdb_tpu.models.types import (
        CommitTransaction,
        ResolveTransactionBatchRequest,
    )
    from foundationdb_tpu.resolver import Resolver
    from foundationdb_tpu.runtime.flow import Scheduler

    sched = Scheduler(sim=True)
    # commit_proxy_count=2 so state is never trimmed (proxy 1 never
    # reports in) and total_state_bytes accumulates past the tiny limit
    res = Resolver(sched, TEST_CONFIG, backend="cpu",
                   state_memory_limit=10, commit_proxy_count=2)

    async def resolver_paths():
        await res.resolve(ResolveTransactionBatchRequest(
            prev_version=-1, version=0, last_received_version=-1))
        # state txn big enough to breach the tiny memory limit
        req1 = ResolveTransactionBatchRequest(
            prev_version=0, version=10, last_received_version=0,
            transactions=[CommitTransaction(
                mutations=[("set", b"\xff/big", b"x" * 64)])],
            txn_state_transactions=[0], proxy_id="p0")
        await res.resolve(req1)
        # duplicate of version 10: replayed from outstanding_batches
        dup = await res.resolve(ResolveTransactionBatchRequest(
            prev_version=0, version=10, last_received_version=0,
            transactions=[], proxy_id="p0"))
        assert dup is not None
        # ack version 10, then ask for it again: unknown duplicate, Never
        req2 = ResolveTransactionBatchRequest(
            prev_version=10, version=20, last_received_version=10,
            transactions=[], proxy_id="p0")
        # backpressure check happens at entry (probe fires); raising
        # needed_version first keeps the wait loop from blocking the
        # single-task test world
        res._set_needed_version(10**9)
        await res.resolve(req2)
        gone = await res.resolve(ResolveTransactionBatchRequest(
            prev_version=0, version=10, last_received_version=10,
            transactions=[], proxy_id="p0"))
        assert gone is None
        # tooOld: snapshot below the MVCC floor
        await res.resolve(ResolveTransactionBatchRequest(
            prev_version=20, version=TEST_CONFIG.window_versions + 500,
            last_received_version=20,
            transactions=[CommitTransaction(
                read_conflict_ranges=[(b"a", b"b")], read_snapshot=-5000)],
            proxy_id="p0"))

    drive(sched, resolver_paths())

    # -- coordination rare paths ----------------------------------------
    from foundationdb_tpu.cluster.coordination import (
        CoordinatedState,
        Coordinator,
        QuorumUnreachable,
        StaleGeneration,
    )

    coords = [Coordinator(f"c{i}") for i in range(3)]
    a = CoordinatedState(sched, coords, "a")
    b = CoordinatedState(sched, coords, "b")

    async def coordination_paths():
        await a.read()
        await b.read()
        await b.write("bv")  # commits between a's read and write
        try:
            await a.write("av")  # stale generation (b locked higher)
        except StaleGeneration:
            pass
        try:
            # retry with the adopted higher count: the lock now succeeds
            # but the replies reveal b's commit -> racing writer detected
            await a.write("av2")
        except StaleGeneration:
            pass
        coords[0].kill()
        coords[1].kill()
        try:
            await a.read()
        except (QuorumUnreachable, StaleGeneration):
            pass
        return True

    drive(sched, coordination_paths())

    # -- recovery under quorum loss -------------------------------------
    from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster

    sched2, cluster, db = open_cluster(
        ClusterConfig(n_commit_proxies=1, n_storage=2)
    )

    async def recovery_paths():
        t = db.create_transaction()
        t.set(b"k", b"v")
        await t.commit()
        # lease is won by the CC watch loop; now drop the quorum and fail
        # the proxy: the epoch lock (and lease renewal) must fail loudly
        for _ in range(40):
            await sched2.delay(0.05)
            if cluster.controller.lease is not None:
                break
        cluster.kill_coordinator(0)
        cluster.kill_coordinator(1)
        cluster.commit_proxies[0].failed = RuntimeError("probe kill")
        await sched2.delay(2.0)  # recover() runs -> epoch lock fails
        # revive the quorum: the CC re-wins the lease...
        cluster.revive_coordinator(0)
        cluster.revive_coordinator(1)
        cluster.commit_proxies[0].failed = None
        for _ in range(200):
            await sched2.delay(0.05)
            if cluster.controller.lease is not None:
                break
        # ...then loses the quorum again with the lease HELD: the renewal
        # near expiry fails -> leadership_lost
        cluster.kill_coordinator(0)
        cluster.kill_coordinator(1)
        await sched2.delay(4.0)
        return True

    t = sched2.spawn(recovery_paths(), name="drive")
    sched2.run_until(t.done)
    assert t.done.get()
    cluster.stop()

    # -- min-combine abort across resolver shards -----------------------
    sched3, cluster3, db3 = open_cluster(
        ClusterConfig(n_commit_proxies=1, n_resolvers=2, n_storage=2)
    )

    async def min_combine():
        t = db3.create_transaction()
        # first byte >= 0x80: resolver shard 1 (2-way even split)
        t.set(b"\xf0-right-shard", b"v1")
        await t.commit()
        # stale read of the shard-1 key + a write in shard 0: resolver 0
        # commits locally, resolver 1 conflicts -> min-combine abort
        from foundationdb_tpu.cluster.commit_proxy import NotCommitted

        t2 = db3.create_transaction()
        t2._read_version = 1  # force a stale snapshot
        t2.add_read_conflict_range(b"\xf0-right-shard", b"\xf0-right-shard\x00")
        t2.set(b"aa-left-shard", b"v2")
        try:
            await t2.commit()
        except NotCommitted:
            pass
        return True

    t = sched3.spawn(min_combine(), name="drive")
    sched3.run_until(t.done)
    assert t.done.get()
    cluster3.stop()

    # -- disk stack: torn tail + recovery scan ---------------------------
    import numpy as np

    from foundationdb_tpu.sim.diskqueue import SimDiskQueue

    for s in range(32):  # the tear branch is coin-flipped per crash
        q = SimDiskQueue()
        q.push(b"durable")
        q.commit()
        for i in range(4):
            q.push(b"unsynced%d" % i)
        q.crash(np.random.default_rng(s))

    # -- tlog spill: tiny budget + lagging consumer ----------------------
    from foundationdb_tpu.cluster.tlog import TLog, TLogCommitRequest
    from foundationdb_tpu.utils.knobs import SERVER_KNOBS as _SK

    _old_budget = _SK.TLOG_SPILL_THRESHOLD
    _SK.set("TLOG_SPILL_THRESHOLD", 8)
    try:
        sched_sp = Scheduler(sim=True)
        spill_log = TLog(sched_sp, durable=SimDiskQueue())

        async def spill_drive():
            prev = 0
            for i in range(12):
                v = (i + 1) * 10
                await spill_log.commit(TLogCommitRequest(
                    prev_version=prev, version=v,
                    messages={0: [("set", b"sp%d" % i, b"v")]},
                ))
                prev = v
            entries, _v = await spill_log.peek(0, 0)  # reads from spill
            return len(entries)

        t = sched_sp.spawn(spill_drive())
        sched_sp.run_until(t.done)
        assert t.done.get() == 12
    finally:
        _SK.set("TLOG_SPILL_THRESHOLD", _old_budget)

    sched4, cluster4, db4 = open_cluster(
        ClusterConfig(n_storage=2, n_tlogs=2, n_satellite_logs=1)
    )

    from foundationdb_tpu.cluster.multiregion import RemoteDC

    remote = RemoteDC(sched4, cluster4.tlog, n_storage=1)
    remote.start()

    async def disk_and_rates():
        for i in range(4):
            txn = db4.create_transaction()
            txn.set(b"dq%d" % i, b"v")
            await txn.commit()
        cluster4.crash_reboot_tlog(1, np.random.default_rng(0))
        await remote.wait_caught_up()
        # wedge the router and commit past it: the failover must pull
        # the acked suffix back off the satellite log
        # (multiregion.satellite_recovery)
        remote.router._task.cancel()
        remote.router._task = None
        for i in range(2):
            txn = db4.create_transaction()
            txn.set(b"sat%d" % i, b"v")
            await txn.commit()
        await remote.failover()
        # ratekeeper law: tighten + slow storage
        rk = cluster4.ratekeeper
        rk.lag_target, rk.lag_limit, rk.interval = 30_000, 200_000, 0.05
        cluster4.storage_servers[0].slowdown = 0.1
        for i in range(8):
            txn = db4.create_transaction(tag="batch")
            await txn.get_read_version()
        # auto tag throttling: a dominant tag during stressed intervals
        # earns a busyness-derived quota (ratekeeper.auto_tag_throttled)
        for _ in range(8):
            for _ in range(50):
                rk.note_tag_admission("batch")
            await sched4.delay(rk.interval)
            if rk.auto_tag_quotas:
                break
        assert rk.auto_tag_quotas, "auto tag throttle never engaged"
        cluster4.storage_servers[0].slowdown = 0.0
        # failure monitor: a SILENT kill must be detected by the ping
        # loop (failmon.detected_by_ping), and a revived process must be
        # marked live by a ping again (failmon.recovered)
        cluster4.kill_storage_silent(1)
        for _ in range(40):
            await sched4.delay(0.05)
            if cluster4.failure_monitor.is_failed("storage1"):
                break
        assert cluster4.failure_monitor.is_failed("storage1")
        cluster4.storage_servers[1].start()  # back from the dead
        for _ in range(40):
            await sched4.delay(0.05)
            if not cluster4.failure_monitor.is_failed("storage1"):
                break
        assert not cluster4.failure_monitor.is_failed("storage1")
        return True

    cluster4.ratekeeper.set_tag_quota("batch", 3.0)
    t = sched4.spawn(disk_and_rates(), name="drive")
    sched4.run_until(t.done)
    assert t.done.get()
    cluster4.stop()

    # -- dynamic-knob quorum: write / race / restore ----------------------
    from foundationdb_tpu.cluster.config_db import (
        CONF_PREFIX,
        PaxosConfigStore,
        restore_broadcast,
        set_knob,
    )

    sched5, cluster5, db5 = open_cluster(ClusterConfig(n_storage=2))
    wa = PaxosConfigStore(sched5, cluster5.config_nodes, "probe-a")
    wb = PaxosConfigStore(sched5, cluster5.config_nodes, "probe-b")

    async def knob_paths():
        cluster5.kill_coordinator(0)  # minority: writes must still land
        ta = sched5.spawn(wa.set("KA", b"1"))  # race at the RMW yield
        tb = sched5.spawn(wb.set("KB", b"2"))
        await ta.done
        await tb.done
        # MAJORITY down mid-write: the store must back off through the
        # transient QuorumUnreachable (config.quorum_write_retried) and
        # land once the quorum returns — the round-5 crash shape
        cluster5.kill_coordinator(1)
        tc = sched5.spawn(wa.set("KD", b"4"))
        await sched5.delay(0.3)
        cluster5.revive_coordinator(1)
        await tc.done
        cluster5.revive_coordinator(0)
        await set_knob(db5, "KC", 3)
        txn = db5.create_transaction()
        txn.clear_range(CONF_PREFIX, CONF_PREFIX + b"\xff")
        await txn.commit()
        restored = await restore_broadcast(db5)
        assert restored["KC"] == 3
        return True

    t = sched5.spawn(knob_paths(), name="drive")
    sched5.run_until(t.done)
    assert t.done.get()
    cluster5.stop()

    # -- TSS divergence: corrupt the mirror, sampled read flags it --------
    from foundationdb_tpu.cluster.tss import TSS_SAMPLE_EVERY

    sched_t, cluster_t, db_t = open_cluster(
        ClusterConfig(n_commit_proxies=1, n_storage=2, n_tss=1)
    )

    async def tss_paths():
        txn = db_t.create_transaction()
        txn.set(b"div", b"truth")
        await txn.commit()
        await sched_t.delay(0.2)  # mirror converges
        tss = cluster_t.tss_servers[0]
        for hist in tss._hist.values():
            hist[:] = [(v, b"LIES") for v, _val in hist]
        txn = db_t.create_transaction()
        for _ in range(4 * TSS_SAMPLE_EVERY):
            assert await txn.get(b"div") == b"truth"
        await sched_t.delay(0.2)  # comparisons drain
        return db_t.tss.mismatches

    t = sched_t.spawn(tss_paths(), name="drive")
    sched_t.run_until(t.done)
    assert t.done.get() >= 1
    cluster_t.stop()

    # -- QueueModel load balancing: backup request / shun -----------------
    sched6, cluster6, db6 = open_cluster(
        ClusterConfig(n_storage=2, replication_factor=2)
    )

    async def lb_paths():
        txn = db6.create_transaction()
        txn.set(b"lb", b"v")
        await txn.commit()
        cluster6.storage_servers[0].read_slowdown = 0.05
        for _ in range(10):
            t = db6.create_transaction()
            assert await t.get(b"lb") == b"v"
        # let the duplicated slow request COMPLETE so its latency lands
        # in the model (0.2s < STALE_AFTER: no decay) — the shun probe
        # requires a genuinely slow estimate, not a cold-start artifact
        await sched6.delay(0.2)
        for _ in range(10):
            t = db6.create_transaction()
            assert await t.get(b"lb") == b"v"
        return True

    t = sched6.spawn(lb_paths(), name="drive")
    sched6.run_until(t.done)
    assert t.done.get()
    cluster6.stop()

    # -- blob granules: flush / resnapshot / split / time travel ----------
    from foundationdb_tpu.cluster.backup import BackupContainer
    from foundationdb_tpu.cluster.blob_granules import BlobManager, BlobWorker

    sched7, cluster7, db7 = open_cluster(ClusterConfig(n_storage=2))
    bw = BlobWorker(sched7, cluster7.tlog, BackupContainer())
    bw.start()
    bmgr = BlobManager(db7, [bw])

    async def blob_paths():
        await bmgr.blobbify(b"", b"", {}, 0)
        txn = db7.create_transaction()
        txn.set(b"bg-first", b"1")
        await txn.commit()
        v_past = cluster7.tlog.version.get()
        await sched7.delay(0.05)
        val = b"z" * 512
        for i in range(160):  # crosses flush, resnapshot AND split bars
            txn = db7.create_transaction()
            txn.set(b"bg%04d" % i, val)
            await txn.commit()
        await sched7.delay(0.3)
        past = bmgr.read(b"", b"", v_past)
        assert past.get(b"bg-first") == b"1" and b"bg0000" not in past
        return True

    t = sched7.spawn(blob_paths(), name="drive")
    sched7.run_until(t.done)
    assert t.done.get()
    bw.stop()
    cluster7.stop()

    # -- TaskBucket: claim race / lease expiry / dependency release -------
    from foundationdb_tpu.layers.taskbucket import TaskBucket

    sched9, cluster9, db9 = open_cluster(ClusterConfig())
    tb = TaskBucket(db9)

    async def taskbucket_paths():
        for i in range(3):
            await tb.add(b"t%d" % i, {})
        await tb.add(b"dep", {}, after=b"t0")
        # two claimers race the same head task -> one commits, the
        # other's claim conflicts and retries onto the next task
        c1 = sched9.spawn(tb.get_one())
        c2 = sched9.spawn(tb.get_one())
        t1 = await c1.done
        t2 = await c2.done
        assert t1.key != t2.key
        await tb.finish(t1)  # t0 finish releases the parked dependent
        await sched9.delay(TaskBucket.LEASE + 0.1)
        await tb.check_timeouts()  # t2's lease expired: requeued
        return True

    t = sched9.spawn(taskbucket_paths(), name="drive")
    sched9.run_until(t.done)
    assert t.done.get()

    # -- BackupWorker displacement (per-epoch handoff) --------------------
    from foundationdb_tpu.cluster.backup import BackupContainer
    from foundationdb_tpu.cluster.backup_worker import BackupWorker

    bw_cont = BackupContainer()
    bwk = BackupWorker(
        sched9, cluster9.tlog, bw_cont, epoch=cluster9.tlog.epoch
    )
    bwk.start()

    async def displace_paths():
        txn = db9.create_transaction()
        txn.set(b"bw-probe", b"1")
        await txn.commit()
        await sched9.delay(0.1)
        # recovery-style epoch bump: the worker drains and hands off
        cluster9.tlog.lock(
            cluster9.tlog.epoch + 1, cluster9.tlog.version.get() + 1000
        )
        await bwk.displaced.future
        return True

    t = sched9.spawn(displace_paths(), name="drive")
    sched9.run_until(t.done)
    assert t.done.get()
    bwk.stop()
    cluster9.stop()

    # -- api workload: an unknown-result commit resolved by marker --------
    # (workload.api_unknown_resolved: a commit the client saw as
    # commit_unknown_result but that really landed must be resolved to
    # COMMITTED by its versionstamped marker)
    from test_api_workload import run_api

    api = run_api(seed=11, sabotage_first_commit=True)
    assert api.stats["unknown_resolved"] >= 1

    # -- slow-task detection ----------------------------------------------
    import time as _t

    sched8 = Scheduler(sim=True)

    async def _blocker():
        _t.sleep(Scheduler.SLOW_TASK_THRESHOLD + 0.01)

    sched8.run_until(sched8.spawn(_blocker(), name="probe-blocker").done)
    assert sched8.slow_tasks

    # -- telemetry probes (ISSUE 5) ---------------------------------------
    # latency band overflow: a sample past every threshold hits the inf
    # bucket; counter flush: one trace_counters call; span-chain gate:
    # the checker over a deliberately broken chain must trip
    from foundationdb_tpu.utils import commit_debug as cdbg
    from foundationdb_tpu.utils.metrics import (
        CounterCollection,
        LatencyBands,
    )
    from foundationdb_tpu.utils.trace import TraceLog, trace_counters

    LatencyBands("probe", bands=(0.001,)).add(9.0)
    trace_counters(
        TraceLog(), "ProbeMetrics", "r0", CounterCollection("m", ["a"])
    )
    broken = cdbg.check_chains(cdbg.TraceIndex([
        {"Type": "CommitDebug", "ID": "tp", "Time": 0.0,
         "Location": cdbg.COMMIT_BEFORE},
        {"Type": "CommitDebug", "ID": "tp", "Time": 0.1,
         "Location": cdbg.COMMIT_AFTER},
    ]))
    assert broken  # committed txn never attached to a batch

    # -- perf-ledger probes (ISSUE 10) ------------------------------------
    # regression gate: a candidate whose structural metric doubled
    # against its own baseline must trip the comparator; compile-cache
    # miss: the monitoring listener's miss event path (the same hook
    # jax.monitoring drives on a persistent-cache miss)
    from foundationdb_tpu.utils import compile_cache, perf

    base = perf.make_record(
        "probe_drive",
        {"rows": perf.metric(100, "rows", "lower", tier="structural")},
    )
    cand = perf.make_record(
        "probe_drive",
        {"rows": perf.metric(200, "rows", "lower", tier="structural")},
    )
    rep = perf.compare(cand, [base], tier="structural")
    assert rep["regressions"] == ["rows"]
    compile_cache._on_event(compile_cache._MISS_EVENT)

    # -- range-path probes (ISSUE 14) -------------------------------------
    # the sorted-endpoint sweep dispatching and the pressure spill
    # folding delta into MAIN (delta sized so the conservative bound
    # trips on the second batch)
    import dataclasses as _dc

    from foundationdb_tpu.config import KernelConfig
    from foundationdb_tpu.models.conflict_set import TpuConflictSet
    from foundationdb_tpu.models.types import CommitTransaction

    sweep_cfg = _dc.replace(
        KernelConfig(
            max_key_bytes=8, max_txns=8, max_reads=16, max_writes=16,
            history_capacity=256, window_versions=1000,
        ),
        delta_capacity=48, compact_interval=0,
        range_sweep=True, delta_spill=True,
    )
    cs = TpuConflictSet(sweep_cfg)
    for i in range(3):
        cs.resolve(
            [
                CommitTransaction(
                    read_conflict_ranges=[(bytes([0, j]), bytes([0, j + 40]))],
                    write_conflict_ranges=[
                        (bytes([1, 8 * i + j]), bytes([1, 8 * i + j, 1]))
                    ],
                    read_snapshot=900,
                )
                for j in range(4)
            ],
            1000 + 100 * i,
        )

    # -- ycsb_d soak twin (ISSUE 15) --------------------------------------
    # the read-latest check fires on most rounds; the frontier-persisted
    # probe needs a read landing >= 5 rounds behind the frontier, which
    # the exponential access law makes common per seed
    run_seed(1, spec="ycsb_d")

    # -- elasticity trigger (ISSUE 15) ------------------------------------
    # a resolver_busy binding streak past the threshold, on a healthy
    # (non-stale) feed, flags the elastic recruit walk
    from foundationdb_tpu.cluster.multiprocess import ClusterControllerRole

    ctrl = ClusterControllerRole(
        {"resolvers": 1, "elastic": True, "elastic_streak": 2}
    )
    ctrl._needs_recovery = False
    ctrl._rk_qos = {
        "binding_streak": {"name": "resolver_busy", "intervals": 5},
        "budget_stale": False,
    }
    ctrl._elastic_check()
    assert ctrl.elastic_recruits == 1

    # ...and the OFF direction (ISSUE 19): the recruit left resolvers
    # above the declared baseline; a "workload"-binding streak (nothing
    # structural binds) past the scale-down gate retires it
    ctrl._needs_recovery = False
    ctrl._rk_qos = {
        "binding_streak": {"name": "workload",
                           "intervals": ctrl.elastic_scale_down_streak},
        "budget_stale": False,
    }
    ctrl._elastic_check()
    assert ctrl.elastic_scale_downs == 1

    # -- autotune probes (ISSUE 15) ---------------------------------------
    # cache_hit: the second sweep over the same ledger resumes every
    # trial; roofline_stop: a trial achieving the (tiny) target frac of
    # the bytes-bound ceiling stops the search early
    import tempfile as _tf

    from foundationdb_tpu.utils import autotune

    def _trial(knobs):
        rec = perf.make_record(
            "probe_drive",
            {"txn_s": perf.metric(1000.0, "txn/s", "higher",
                                  tier="structural")},
            knobs=knobs,
            fingerprint={
                "backend": "tpu", "device_kind": "TPU v5e",
                "device_count": 1, "jax_version": None,
                "jaxlib_version": None, "python_version": None,
                "machine": None,
            },
            git_sha="t", timestamp=0.0,
        )
        rec["extra"] = {"hlo_cost": {"bytes_accessed": 8.19e8}}
        return rec

    with _tf.TemporaryDirectory() as td:
        ledger = f"{td}/search.jsonl"
        space = autotune.SearchSpace({"fuse": (8, 16)})
        autotune.run_search("probe", space, _trial,
                            objective_metric="txn_s", ledger=ledger)
        autotune.run_search("probe", space, _trial,
                            objective_metric="txn_s", ledger=ledger)
        rep = autotune.run_search(
            "probe-roofline", space, _trial, objective_metric="txn_s",
            ledger=ledger, roofline_txns_per_dispatch=1024,
            roofline_frac=9e-4,
        )
        assert rep.stopped == "roofline"

    # -- sampling probes (ISSUE 20) ---------------------------------------
    from foundationdb_tpu.cluster import sampling

    # byte_sample_gc: factor=1/overhead=0 puts p >= 1 on every write, so
    # a tiny capacity overflows and the deterministic halving GC runs
    bs = sampling.ByteSample(seed=7, factor=1, overhead=0, capacity=8)
    for i in range(64):
        bs.note_write(b"gc/%03d" % i, b"v" * 64)
    assert bs.gc_rounds >= 1

    # tag_counter_rollover: a 5th distinct tag against a 4-slot table
    # evicts the cold half first
    vt = [0.0]
    tc = sampling.TagCounter(capacity=4, clock=lambda: vt[0])
    for i in range(5):
        tc.note(f"tag{i}", 100)
        vt[0] += 0.1
    assert tc.rollovers >= 1

    # hot_range_attributed: a dominant rolled-up tag names a hotspot
    attr = sampling.attribute_hotspot({"cluster": {
        "busiest_tags": [
            {"tag": "tenant0", "bytes_per_s": 9e4, "frac": 0.8}
        ],
        "hot_ranges": [],
    }})
    assert attr["attributed"]

    assert probes.missed() == [], (
        f"declared CODE_PROBEs never fired: {probes.missed()}\n"
        f"fired: { {k: v for k, v in probes.snapshot().items() if v} }"
    )

    # -- the canonical manifest pin (flowcheck probe accounting) ----------
    # every probe this run touched must be statically declared, i.e.
    # present in analysis/probe_manifest.json — a name outside it is
    # invisible to the coveragetool-style ledger
    from foundationdb_tpu.analysis.manifest import load_manifest

    manifest = set(load_manifest())
    runtime_names = set(probes.snapshot())
    assert runtime_names <= manifest, (
        f"probes fired at runtime but missing from the static manifest "
        f"(run `python -m foundationdb_tpu.analysis --write-manifest`): "
        f"{sorted(runtime_names - manifest)}"
    )
