"""wire_fuzz: the codec fuzzer's own contract.

Pins (a) determinism — one seed, one byte-identical mutation stream and
verdict digest; (b) the committed rejecting corpus replays clean; and
(c) the two decoder bugs the fuzzer found stay fixed as CodecError
rejects: invalid UTF-8 inside a str field (r_str) and an out-of-range
TransactionResult verdict byte (r_resolve_reply).
"""

import importlib.util
import json
from pathlib import Path

import pytest

from foundationdb_tpu.cluster import multiprocess as mp
from foundationdb_tpu.wire import codec

REPO = Path(__file__).resolve().parents[1]
CORPUS = REPO / "tests" / "fixtures" / "wire_fuzz_corpus.json"


@pytest.fixture(scope="module")
def fuzz():
    spec = importlib.util.spec_from_file_location(
        "wire_fuzz", REPO / "scripts" / "wire_fuzz.py"
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


@pytest.fixture(scope="module")
def samples(fuzz):
    return fuzz.build_samples(fuzz.wr.load_repo_registry(REPO))


def test_every_registered_frame_has_a_roundtripping_sample(samples):
    assert len(samples) == len(codec._REGISTRY)
    for name, blob in samples.items():
        msg = codec.decode(blob)  # must not raise
        assert codec.encode(msg) == blob, name


def test_mutation_stream_is_deterministic_per_seed(fuzz, samples):
    for name, data in list(samples.items())[:6]:
        a = fuzz.mutations_for(name, data, 7, None)
        b = fuzz.mutations_for(name, data, 7, None)
        assert a == b, name  # byte-identical stream, same seed
    # and the seed actually steers the random stages
    name, data = next(iter(samples.items()))
    assert fuzz.mutations_for(name, data, 7, None) != \
        fuzz.mutations_for(name, data, 8, None)


def test_verdicts_are_deterministic(fuzz, samples):
    name, data = "ResolveTransactionBatchReply", \
        samples["ResolveTransactionBatchReply"]
    verdicts = [
        [fuzz.run_case(blob)[0]
         for _d, blob in fuzz.mutations_for(name, data, 3, 50)]
        for _ in range(2)
    ]
    assert verdicts[0] == verdicts[1]


def test_committed_corpus_replays_as_rejects(fuzz):
    corpus = json.loads(CORPUS.read_text(encoding="utf-8"))
    assert corpus["cases"], "empty corpus"
    for entry in corpus["cases"]:
        verdict, detail = fuzz.run_case(bytes.fromhex(entry["hex"]))
        assert verdict == entry["expect"], (
            f"{entry['frame']} [{entry['desc']}]: {verdict} {detail}"
        )


def test_regression_invalid_utf8_rejects_with_codec_error():
    blob = codec.encode(mp.StatusReply(payload="abcd"))
    bad = blob[:-2] + b"\xff\xfe"
    with pytest.raises(codec.CodecError):
        codec.decode(bad)


def test_regression_bad_verdict_byte_rejects_with_codec_error(samples):
    blob = samples["ResolveTransactionBatchReply"]
    # u16 type id + u32 count, then the first verdict byte at offset 6
    bad = blob[:6] + b"\x2a" + blob[7:]
    with pytest.raises(codec.CodecError):
        codec.decode(bad)


def test_smoke_lane_exits_zero(fuzz, capsys):
    assert fuzz.main(["--smoke"]) == 0
    out = capsys.readouterr().out
    assert "0 FAIL" in out and "digest" in out
