"""Durability: DiskQueue recovery + SaveAndKill-style cluster restart.

VERDICT r1 task 6. The native DiskQueue (native/diskqueue.cpp — the
fdbserver/DiskQueue.actor.cpp role) is tested directly for commit/crash/
recover semantics including torn tails; then the multiprocess cluster is
killed (SIGKILL) mid-workload and restarted from disk: the tlog recovers
its acked entries, storage restores its checkpoint and replays the tlog
tail, and every acked commit is present exactly once (unacked in-flight
commits may or may not be — commit_unknown_result semantics, like the
reference's SaveAndKill workload, fdbserver/workloads/SaveAndKill.actor.cpp).
"""

import asyncio
import os
import struct

import pytest

from foundationdb_tpu.cluster import multiprocess as mp
from foundationdb_tpu.models.types import CommitTransaction
from foundationdb_tpu.wire import transport
from foundationdb_tpu.wire.codec import Mutation

native = pytest.importorskip("foundationdb_tpu.native")


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# DiskQueue unit semantics.


def test_diskqueue_commit_recover_roundtrip(tmp_path):
    q = native.DiskQueue(str(tmp_path / "log"))
    assert q.recovered == []
    s0 = q.push(b"alpha")
    s1 = q.push(b"beta" * 100)
    assert q.commit() == s1
    q.push(b"NEVER-COMMITTED")  # buffered only: must not survive
    q.close()

    q2 = native.DiskQueue(str(tmp_path / "log"))
    assert q2.recovered == [(s0, b"alpha"), (s1, b"beta" * 100)]
    # appends continue after the recovered tail
    s2 = q2.push(b"gamma")
    assert s2 == s1 + 1
    q2.commit()
    q2.close()
    q3 = native.DiskQueue(str(tmp_path / "log"))
    assert [d for _s, d in q3.recovered] == [b"alpha", b"beta" * 100, b"gamma"]


def test_diskqueue_pop_discards_prefix(tmp_path):
    q = native.DiskQueue(str(tmp_path / "log"))
    for i in range(10):
        q.push(b"rec%d" % i)
    q.commit()
    q.pop(7)
    q.commit()
    q.close()
    q2 = native.DiskQueue(str(tmp_path / "log"))
    assert [d for _s, d in q2.recovered] == [b"rec7", b"rec8", b"rec9"]
    assert q2.pop_floor == 7


def test_diskqueue_torn_tail_truncated(tmp_path):
    q = native.DiskQueue(str(tmp_path / "log"))
    q.push(b"good-one")
    q.push(b"good-two")
    q.commit()
    q.close()
    # simulate a torn write: append garbage, then half a valid-looking frame
    with open(str(tmp_path / "log") + "-0.dq", "ab") as f:
        f.write(struct.pack("<IQII", 0xD15C0001, 2, 1000, 0xDEAD))
        f.write(b"short")  # claims 1000 bytes, delivers 5
    q2 = native.DiskQueue(str(tmp_path / "log"))
    assert [d for _s, d in q2.recovered] == [b"good-one", b"good-two"]
    # and the queue is usable after tail truncation
    q2.push(b"three")
    q2.commit()
    q2.close()
    q3 = native.DiskQueue(str(tmp_path / "log"))
    assert [d for _s, d in q3.recovered] == [b"good-one", b"good-two", b"three"]


def test_diskqueue_corrupt_record_ends_recovery(tmp_path):
    q = native.DiskQueue(str(tmp_path / "log"))
    q.push(b"aaaa")
    q.push(b"bbbb")
    q.push(b"cccc")
    q.commit()
    q.close()
    path = str(tmp_path / "log") + "-0.dq"
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size - 2)  # flip bits inside the last record's payload
        f.write(b"\xff")
    q2 = native.DiskQueue(str(tmp_path / "log"))
    assert [d for _s, d in q2.recovered] == [b"aaaa", b"bbbb"]


def test_diskqueue_midfile_corruption_refuses_open(tmp_path):
    """A bit flip in the OLDER file's interior is not a torn tail: acked
    records live after it, and truncating would destroy them. Recovery
    must fail loudly instead of silently dropping data (ADVICE r2)."""
    q = native.DiskQueue(str(tmp_path / "log"), rotate_bytes=4096)
    for i in range(8):
        q.push(b"x" * 700)
        q.commit()  # rotation happens at commit: file 0 fills, then 1
    q.close()
    p0 = str(tmp_path / "log") + "-0.dq"
    p1 = str(tmp_path / "log") + "-1.dq"
    assert os.path.getsize(p0) > 0 and os.path.getsize(p1) > 0
    # Corrupt the interior of the OLDER file (writes start in -0 and
    # rotate to -1, so -0 holds the older records); both files hold
    # live, unpopped records, and valid frames survive past the damage.
    with open(p0, "r+b") as f:
        f.seek(100)
        f.write(b"\xff\xff")
    with pytest.raises(native.NativeBuildError):
        native.DiskQueue(str(tmp_path / "log"))


def test_diskqueue_newest_file_interior_corruption_refuses(tmp_path):
    """Interior damage in the NEWEST file with acked frames still valid
    past it is corruption, not a torn tail — refuse, don't truncate
    away the surviving acked records."""
    q = native.DiskQueue(str(tmp_path / "log"))
    for i in range(6):
        q.push(b"rec%d" % i + b"y" * 200)
        q.commit()  # each record fsync-acked
    q.close()
    p0 = str(tmp_path / "log") + "-0.dq"
    with open(p0, "r+b") as f:
        f.seek(260)  # inside record 1's payload; records 2..5 intact
        f.write(b"\xff\xff")
    with pytest.raises(native.NativeBuildError):
        native.DiskQueue(str(tmp_path / "log"))


def test_diskqueue_rotation_bounds_disk(tmp_path):
    q = native.DiskQueue(str(tmp_path / "log"), rotate_bytes=4096)
    payload = b"x" * 256
    for i in range(200):
        s = q.push(payload)
        q.commit()
        q.pop(s)  # everything before the newest record is consumed
    q.close()
    total = sum(
        os.path.getsize(str(tmp_path / "log") + suf)
        for suf in ("-0.dq", "-1.dq")
    )
    assert total < 6 * 4096, total  # bounded, not 200*280 bytes
    q2 = native.DiskQueue(str(tmp_path / "log"), rotate_bytes=4096)
    # the final pop was buffered but never committed, so the last one or
    # two records survive — never the consumed prefix
    survivors = [d for _s, d in q2.recovered]
    assert 1 <= len(survivors) <= 2 and all(d == payload for d in survivors)


# ---------------------------------------------------------------------------
# Incremental storage durability: the mutation log makes every ACKED
# apply durable immediately (KeyValueStoreMemory's discipline) — not
# just the last checkpoint — and restart replays only the log tail.


def _role_get(role, key, version):
    async def go():
        return (await role.get(mp.StorageGet(key=key, version=version))).value
    return run(go())


@pytest.mark.parametrize("engine", ["memory", "lsm"])
def test_storage_mutation_log_tail_replay(tmp_path, engine):
    data_dir = str(tmp_path / "sdata")

    def applies(role, lo, hi):
        async def go():
            for i in range(lo, hi):
                await role.apply(mp.StorageApply(
                    version=(i + 1) * 10,
                    mutations=[Mutation(
                        0, b"k%02d" % i, b"v%d" % i)],
                ))
        run(go())

    role = mp.StorageRole(data_dir, engine=engine)
    applies(role, 0, 5)  # < CHECKPOINT_INTERVAL: no checkpoint yet
    # crash (no clean shutdown): a new role must recover every ACKED
    # apply from the mutation log alone — the old checkpoint-only
    # design lost everything since the last checkpoint
    role2 = mp.StorageRole(data_dir, engine=engine)
    assert role2.version == 50
    assert role2.replayed_on_restart == 5
    assert _role_get(role2, b"k04", 50) == b"v4"

    # push past the checkpoint interval: the checkpoint compacts the log
    applies(role2, 5, 5 + mp.StorageRole.CHECKPOINT_INTERVAL)
    role3 = mp.StorageRole(data_dir, engine=engine)
    v3 = (5 + mp.StorageRole.CHECKPOINT_INTERVAL) * 10
    assert role3.version == v3
    # restart cost proportional to the tail since the checkpoint, not
    # the dataset
    assert role3.replayed_on_restart <= 1, role3.replayed_on_restart
    assert _role_get(role3, b"k00", v3) == b"v0"  # from the checkpoint
    last = 4 + mp.StorageRole.CHECKPOINT_INTERVAL
    assert _role_get(role3, b"k%02d" % last, v3) == b"v%d" % last


def test_storage_lsm_dataset_beyond_memtable_kill9(tmp_path):
    """The LSM-backed role with data far past the flush budget: applies
    stream through WAL + memtable flushes into runs; an unclean restart
    replays only the WAL tail (∝ tail, not dataset) and serves reads
    off disk — the capability the reference gets from Redwood/sqlite
    (fdbserver/VersionedBTree.actor.cpp)."""
    data_dir = str(tmp_path / "sdata")
    role = mp.StorageRole(data_dir, engine="lsm")
    val = b"x" * 4096
    n_versions = 80  # 80 x 16 x 4KB = ~5MB through a 4MB budget

    async def load():
        for i in range(n_versions):
            await role.apply(mp.StorageApply(
                version=(i + 1) * 10,
                mutations=[
                    Mutation(0, b"big%05d" % (i * 16 + j), val)
                    for j in range(16)
                ],
            ))
    run(load())
    assert role._lsm.num_runs >= 1  # the budget forced real flushes

    # kill -9 equivalent: reopen with no clean shutdown
    role2 = mp.StorageRole(data_dir, engine="lsm")
    assert role2.version == n_versions * 10
    # restart replayed only the un-flushed tail, not the dataset
    assert role2.replayed_on_restart < n_versions / 2
    v = role2.version
    assert _role_get(role2, b"big%05d" % 0, v) == val
    assert _role_get(role2, b"big%05d" % (n_versions * 16 - 1), v) == val
    # versioned read: a key written at version 10 is absent at 9
    assert _role_get(role2, b"big%05d" % 0, 9) is None


# SaveAndKill: kill -9 the persistent roles mid-workload, restart, check.


@pytest.mark.parametrize("engine", ["memory", "lsm"])
def test_save_and_kill_restart(tmp_path, engine):
    sock_dir = str(tmp_path / "socks")
    os.makedirs(sock_dir)
    tlog_dir = str(tmp_path / "tlog-data")
    storage_dir = str(tmp_path / "storage-data")

    procs = {
        "resolver": mp.spawn_role("resolver", sock_dir),
        "tlog": mp.spawn_role("tlog", sock_dir, data_dir=tlog_dir),
        "storage": mp.spawn_role("storage", sock_dir, data_dir=storage_dir,
                                 storage_engine=engine),
    }
    acked: dict[bytes, int] = {}
    unknown: dict[bytes, int] = {}

    async def phase1():
        resolver = await mp.connect(procs["resolver"].address)
        tlog = await mp.connect(procs["tlog"].address)
        storage = await mp.connect(procs["storage"].address)
        pipe = mp.ProxyPipeline([resolver], tlog, storage,
                                batch_interval=0.001)
        pipe.start()
        for i in range(30):
            key = b"sk%02d" % (i % 5)
            kr = (key, key + b"\x00")
            rv = await pipe.get_read_version()
            cur = await pipe.read(key, rv)
            n = int.from_bytes(cur or b"\0" * 8, "little")
            try:
                await pipe.commit(
                    CommitTransaction(
                        read_conflict_ranges=[kr],
                        write_conflict_ranges=[kr],
                        read_snapshot=rv,
                        mutations=[Mutation(0, key, (n + 1).to_bytes(8, "little"))],
                    )
                )
                acked[key] = acked.get(key, 0) + 1
            except (mp.NotCommittedError, transport.RemoteError,
                    transport.TransportError, TimeoutError):
                unknown[key] = unknown.get(key, 0) + 1
        await pipe.stop()
        for c in (resolver, tlog, storage):
            await c.close()

    run(phase1())
    assert sum(acked.values()) > 0

    # --- SIGKILL the persistent roles (no clean shutdown) ----------------
    procs["tlog"].proc.kill()
    procs["storage"].proc.kill()
    procs["tlog"].proc.wait()
    procs["storage"].proc.wait()
    os.unlink(procs["tlog"].address)
    os.unlink(procs["storage"].address)

    # --- restart from disk; storage catches up from the recovered tlog --
    procs["tlog2"] = mp.spawn_role("tlog", sock_dir, index=2,
                                   data_dir=tlog_dir)
    procs["storage2"] = mp.spawn_role(
        "storage", sock_dir, index=2, data_dir=storage_dir,
        tlog_address=procs["tlog2"].address, storage_engine=engine,
    )

    async def phase2():
        resolver = await mp.connect(procs["resolver"].address)
        tlog = await mp.connect(procs["tlog2"].address)
        storage = await mp.connect(procs["storage2"].address)
        tv = (await tlog.call(mp.TOKEN_TLOG_VERSION,
                              mp.RoleVersionReq(pad=0))).version
        rv_res = (await resolver.call(mp.TOKEN_RESOLVER_VERSION,
                                      mp.RoleVersionReq(pad=0))).version
        sv = (await storage.call(mp.TOKEN_STORAGE_VERSION,
                                 mp.RoleVersionReq(pad=0))).version
        # storage caught up to everything the tlog recovered
        assert sv >= tv >= 0, (sv, tv)

        # every acked commit must be present; unknowns may add extras
        snap = await storage.call(
            mp.TOKEN_STORAGE_SNAPSHOT, mp.StorageSnapshotReq(version=sv)
        )
        got = {k: int.from_bytes(v, "little") for k, v in snap.kvs}
        for key, cnt in acked.items():
            lo, hi = cnt, cnt + unknown.get(key, 0)
            assert lo <= got.get(key, 0) <= hi, (
                f"{key}: storage={got.get(key, 0)} acked={cnt} "
                f"unknown={unknown.get(key, 0)}"
            )

        # the cluster keeps working after restart, resuming above every
        # recovered version
        start = max(tv, rv_res, sv, 0)
        pipe = mp.ProxyPipeline([resolver], tlog, storage,
                                batch_interval=0.001, start_version=start)
        pipe.start()
        key = b"post-restart"
        v = await pipe.commit(
            CommitTransaction(
                write_conflict_ranges=[(key, key + b"\x00")],
                mutations=[Mutation(0, key, b"alive")],
            )
        )
        assert v > start
        assert await pipe.read(key, v) == b"alive"
        await pipe.stop()
        for c in (resolver, tlog, storage):
            await c.close()

    try:
        run(phase2())
    finally:
        for p in procs.values():
            p.stop()


# ---------------------------------------------------------------------------
# Cross-version restart lane (VERDICT r4 task 6): the committed
# tests/fixtures/ondisk_r4/ directory holds data files written by the
# round-4 on-disk formats (scripts/make_restart_fixture.py). Current code
# must open them, see exactly the state EXPECT.json records, and keep
# operating (write + unclean reopen on top) — the reference's
# tests/restarting/from_7.3.0/ discipline.

import json
import shutil

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "fixtures", "ondisk_r4")


def _fixture(name, tmp_path):
    """Copy (opening mutates: appends, compactions) and load EXPECT."""
    dst = str(tmp_path / name)
    shutil.copytree(os.path.join(FIXTURE_DIR, name), dst)
    with open(os.path.join(FIXTURE_DIR, "EXPECT.json")) as f:
        return dst, json.load(f)[name]


def test_prior_format_diskqueue_opens(tmp_path):
    d, exp = _fixture("diskqueue", tmp_path)
    q = native.DiskQueue(os.path.join(d, "log"), rotate_bytes=2048)
    got = [rec.hex() for _s, rec in q.recovered]
    assert got == exp["records_hex"]  # committed prefix, uncommitted gone
    s = q.push(b"new-generation")
    q.commit()
    q.close()
    q2 = native.DiskQueue(os.path.join(d, "log"), rotate_bytes=2048)
    assert q2.recovered[-1] == (s, b"new-generation")


def test_prior_format_storage_memory_opens(tmp_path):
    d, exp = _fixture("memory", tmp_path)
    role = mp.StorageRole(d, engine="memory")
    assert role.version == exp["version"]
    v = role.version
    for key, val in exp["present"].items():
        assert _role_get(role, key.encode(), v) == val.encode(), key
    for key in exp["absent"]:
        assert _role_get(role, key.encode(), v) is None, key
    assert _role_get(role, b"shared", v) == exp["shared"].encode()

    # SaveAndKill on top: write under current code, unclean reopen
    run(role.apply(mp.StorageApply(
        version=v + 10, mutations=[Mutation(0, b"newgen", b"ng")])))
    role2 = mp.StorageRole(d, engine="memory")
    assert role2.version == v + 10
    assert _role_get(role2, b"newgen", v + 10) == b"ng"
    assert _role_get(role2, b"mem005", v + 10) == b"val-5"


def test_prior_format_storage_lsm_opens(tmp_path):
    d, exp = _fixture("lsm", tmp_path)
    role = mp.StorageRole(d, engine="lsm")
    assert role.version == exp["version"]
    v = role.version
    val = b"y" * exp["val_len"]
    assert _role_get(role, b"lsm0002", v) == val
    assert _role_get(role, exp["last_key"].encode(), v) == val
    for key in exp["absent"]:
        assert _role_get(role, key.encode(), v) is None, key

    # write + unclean reopen on top of the prior-format dataset
    run(role.apply(mp.StorageApply(
        version=v + 10, mutations=[Mutation(0, b"newgen", b"ng")])))
    role2 = mp.StorageRole(d, engine="lsm")
    assert role2.version == v + 10
    assert _role_get(role2, b"newgen", v + 10) == b"ng"
    assert _role_get(role2, b"lsm0002", v + 10) == val


FIXTURE_DIR_R5 = os.path.join(
    os.path.dirname(__file__), "fixtures", "ondisk_r5"
)


def test_prior_format_encrypted_lsm_opens(tmp_path):
    """Round-5's encrypted store format: a FRESH process (fresh cipher
    cache) must open the sealed dataset via the deterministic KMS's
    by-id derivation, serve plaintext through the API, keep the raw
    files ciphertext, and refuse an unencrypted open (marker)."""
    import shutil as _sh

    from foundationdb_tpu.cluster.encrypt_key_proxy import EncryptKeyProxy
    from foundationdb_tpu.cluster.kms import SimKmsConnector
    from foundationdb_tpu.crypto.at_rest import StorageEncryption

    d = str(tmp_path / "encrypted_lsm")
    _sh.copytree(os.path.join(FIXTURE_DIR_R5, "encrypted_lsm"), d)
    with open(os.path.join(FIXTURE_DIR_R5, "EXPECT.json")) as f:
        exp = json.load(f)["encrypted_lsm"]

    enc = StorageEncryption(
        EncryptKeyProxy(SimKmsConnector(), refresh_interval=10**9)
    )
    role = mp.StorageRole(d, engine="lsm", encryption=enc)
    assert role.version == exp["version"]
    for key, val in exp["present"].items():
        assert _role_get(role, key.encode(), role.version) == val.encode()
    # raw files stay ciphertext
    needle = exp["plaintext_absent"].encode()
    for root, _dirs, files in os.walk(d):
        for fn in files:
            with open(os.path.join(root, fn), "rb") as fh:
                assert needle not in fh.read(), fn
    # unencrypted open refused (marker survives the round boundary)
    with pytest.raises(RuntimeError, match="encryption"):
        mp.StorageRole(d, engine="lsm")
