"""History-capacity overflow must fail the SAME resolve() that overflows.

ADVICE r1 (medium): the interval-based check let up to 32 batches of
verdicts computed against a truncated history escape to clients. The
contract (HistoryOverflowError docstring: "never silent wrong answers")
requires the sync path to refuse on the spot; BatchVerdict now carries
the overflow latch so resolve() checks it on the verdict sync it already
pays.
"""

import numpy as np
import pytest

from foundationdb_tpu.config import KernelConfig
from foundationdb_tpu.models.conflict_set import (
    HistoryOverflowError,
    TpuConflictSet,
)
from foundationdb_tpu.models.types import CommitTransaction

# compile-heavy kernel tests: run with -m kernel (fast lane: -m 'not kernel')
pytestmark = pytest.mark.kernel


def k(i: int) -> bytes:
    return int(i).to_bytes(4, "big")


def make_cfg(capacity: int) -> KernelConfig:
    return KernelConfig(
        max_key_bytes=8,
        max_txns=16,
        max_reads=16,
        max_writes=16,
        history_capacity=capacity,
        window_versions=10_000_000,  # no GC relief inside the test
    )


def disjoint_write_batch(base: int, n: int):
    # n disjoint, non-adjacent single-key ranges -> 2n new boundaries.
    return [
        CommitTransaction(write_conflict_ranges=[(k(base + 10 * i), k(base + 10 * i + 1))])
        for i in range(n)
    ]


def test_overflow_raises_on_the_overflowing_batch():
    cs = TpuConflictSet(make_cfg(capacity=24))
    version = 0
    raised_at = None
    for step in range(12):
        version += 100
        try:
            cs.resolve(disjoint_write_batch(100_000 * step, 8), version)
        except HistoryOverflowError:
            raised_at = step
            break
    assert raised_at is not None, "capacity 24 never overflowed after 96 ranges"
    # 8 ranges x 2 boundaries per batch: capacity 24 must blow within the
    # first 2-3 batches, not OVERFLOW_CHECK_INTERVAL (32) batches later.
    assert raised_at <= 3


def test_no_overflow_below_capacity():
    cs = TpuConflictSet(make_cfg(capacity=256))
    version = 0
    for step in range(6):
        version += 100
        res = cs.resolve(disjoint_write_batch(100_000 * step, 8), version)
        assert len(res.verdicts) == 8
    cs.check_overflow()  # explicit check also clean
