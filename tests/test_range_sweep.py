"""Device-native range-overlap resolve + spill-and-compact (ISSUE 14).

Two escape hatches closed, each pinned both directions:

* `range_sweep` — the tiered kernel's main-tier probe as ONE per-group
  sorted-endpoint sweep (ops/delta.sweep_read_ranks) instead of
  per-read binary searches with a bounded probe window. Decision
  parity vs the probe path, the classic kernel, CpuConflictSet and the
  multi-resolver oracle on range-heavy / mixed / window-edge streams,
  single-device, sharded (n=2) and through the pipelined stream.
* `delta_spill` — delta-capacity pressure folds delta into MAIN (the
  compaction program, dispatched asynchronously) instead of
  latch-and-raise: a stream sized past delta_capacity completes on
  device with ZERO host exact-kernel re-dispatches (counter pinned),
  mid-stream with the staging thread active, during a sharded group,
  and across a rebase; decisions are invariant vs compact_interval.

Runs in the kernel parity lane (8-device CPU mesh, -m kernel).
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from foundationdb_tpu.config import KernelConfig
from foundationdb_tpu.models.conflict_set import (
    CpuConflictSet,
    HistoryOverflowError,
    TpuConflictSet,
)
from foundationdb_tpu.models.types import CommitTransaction
from foundationdb_tpu.utils import packing
from foundationdb_tpu.utils.packing import stack_device_args

pytestmark = pytest.mark.kernel


def sweep_config(**kw):
    d = dict(
        max_key_bytes=8,
        max_txns=16,
        max_reads=32,
        max_writes=32,
        history_capacity=512,
        window_versions=1000,
        delta_capacity=256,
        compact_interval=2,
        range_sweep=True,
    )
    d.update(kw)
    return KernelConfig(**d)


def probe_config(cfg, **kw):
    return dataclasses.replace(cfg, range_sweep=False, **kw)


def classic_config(cfg):
    return dataclasses.replace(
        cfg, delta_capacity=0, dedup_reads=0, range_sweep=False,
        delta_spill=False, compact_interval=1,
    )


def ikey(v, width=4):
    return int(v).to_bytes(width, "big")


def range_txn(rng, *, snap_lo, snap_hi, keyspace=1 << 20, max_span=4000,
              blind_prob=0.1, report_prob=0.5):
    """Range-heavy shape: wide read scans vs point-ish writes (the
    YCSB-E / BASELINE config-3 regime, the profile the router exiled)."""
    def scan():
        b = int(rng.integers(0, keyspace))
        return (ikey(b), ikey(b + int(rng.integers(1, max_span))))

    def point():
        b = int(rng.integers(0, keyspace))
        return (ikey(b), ikey(b + int(rng.integers(1, 8))))

    reads = [] if rng.random() < blind_prob else [
        scan() for _ in range(1 + int(rng.integers(0, 2)))
    ]
    return CommitTransaction(
        read_conflict_ranges=reads,
        write_conflict_ranges=[
            point() for _ in range(1 + int(rng.integers(0, 2)))
        ],
        read_snapshot=int(rng.integers(snap_lo, snap_hi)),
        report_conflicting_keys=bool(rng.random() < report_prob),
    )


def mixed_txn(rng, **kw):
    """Mixed shape: scans, points and duplicates interleaved."""
    if rng.random() < 0.5:
        return range_txn(rng, max_span=64, **kw)
    return range_txn(rng, max_span=2, **kw)


def gen_stream(rng, n_batches, txn_fn=range_txn, *, base=1000, step=100,
               n_txns=10):
    out = []
    for i in range(n_batches):
        version = base + (i + 1) * step
        out.append((
            [
                txn_fn(rng, snap_lo=max(0, base - 2 * step),
                       snap_hi=version)
                for _ in range(n_txns)
            ],
            version,
        ))
    return out


def run_resolve(cs, stream):
    return [cs.resolve(txns, v) for txns, v in stream]


def assert_results_match(a, b, label=""):
    for i, (ra, rb) in enumerate(zip(a, b)):
        assert ra.verdicts == rb.verdicts, f"{label} verdicts batch {i}"
        assert ra.conflicting_key_ranges == rb.conflicting_key_ranges, (
            f"{label} conflicting ranges batch {i}"
        )


# ---------------------------------------------------------------------------
# sweep parity


@pytest.mark.parametrize("seed", range(4))
def test_sweep_matches_probe_and_classic_range_heavy(seed):
    rng = np.random.default_rng(seed)
    cfg = sweep_config()
    stream = gen_stream(rng, 8)
    res_s = run_resolve(TpuConflictSet(cfg), stream)
    res_p = run_resolve(TpuConflictSet(probe_config(cfg)), stream)
    res_c = run_resolve(TpuConflictSet(classic_config(cfg)), stream)
    assert_results_match(res_s, res_p, "sweep vs probe")
    assert_results_match(res_s, res_c, "sweep vs classic")


@pytest.mark.parametrize("seed", range(3))
def test_sweep_matches_cpu_oracle_mixed(seed):
    rng = np.random.default_rng(50 + seed)
    cfg = sweep_config()
    stream = gen_stream(rng, 6, mixed_txn)
    res_s = run_resolve(TpuConflictSet(cfg), stream)
    res_o = run_resolve(CpuConflictSet(cfg), stream)
    assert_results_match(res_s, res_o, "sweep vs cpu oracle")
    # the sweep path actually ran (not silently the probe path)
    # — re-run on a fresh instance to read its counters
    cs = TpuConflictSet(cfg)
    run_resolve(cs, stream)
    assert cs.metrics.counters.get("sweepGroups") == len(stream)


def test_sweep_window_edge_versions():
    """Snapshots exactly at / beside the MVCC floor through the sweep
    probe: the too-old and GC boundaries must match the probe path."""
    cfg = sweep_config(window_versions=100)
    k = lambda i: bytes([i])
    streams = []
    for snap in (99, 100, 101, 199, 200):
        streams.append((
            [
                CommitTransaction([(k(1), k(9))], [(k(1), k(2))],
                                  read_snapshot=snap),
                CommitTransaction([], [(k(3), k(4))], read_snapshot=snap),
            ],
            200 + len(streams),
        ))
    res_s = run_resolve(TpuConflictSet(cfg), streams)
    res_p = run_resolve(TpuConflictSet(probe_config(cfg)), streams)
    assert_results_match(res_s, res_p, "sweep window edge")


def test_sweep_scan_straddles_many_boundaries():
    """A scan covering MANY main-tier boundaries (the regime the probe
    path's 4-wide window falls back to a second binary search for) must
    be exact through the sweep ranks."""
    cfg = sweep_config(compact_interval=1)  # every batch folds to main
    writers = [
        CommitTransaction([], [(ikey(10 * i), ikey(10 * i + 2))],
                          read_snapshot=900)
        for i in range(12)
    ]
    stream = [
        (writers, 1100),
        # one scan over ALL the boundaries, one beside them; stale
        # snapshots so the covered scan must conflict
        ([
            CommitTransaction([(ikey(0), ikey(500))], [(ikey(600), ikey(601))],
                              read_snapshot=1000),
            CommitTransaction([(ikey(700), ikey(900))],
                              [(ikey(910), ikey(911))], read_snapshot=1000),
        ], 1200),
    ]
    res_s = run_resolve(TpuConflictSet(cfg), stream)
    res_o = run_resolve(CpuConflictSet(cfg), stream)
    assert_results_match(res_s, res_o, "boundary straddle")
    assert res_s[1].verdicts[0].name == "CONFLICT"
    assert res_s[1].verdicts[1].name == "COMMITTED"


@pytest.mark.parametrize("seed", range(2))
def test_sweep_sharded_matches_multi_resolver_oracle(seed):
    from foundationdb_tpu.parallel.mesh import cpu_mesh
    from foundationdb_tpu.testing.oracle import MultiResolverOracle, OracleTxn

    rng = np.random.default_rng(70 + seed)
    cfg = sweep_config(n_shards=2)
    boundaries = [bytes([8])]  # interior split of the 20-bit keyspace
    stream = gen_stream(rng, 6)
    oracle = MultiResolverOracle(boundaries, window=cfg.window_versions)
    want = [
        oracle.resolve(
            [
                OracleTxn(t.read_conflict_ranges, t.write_conflict_ranges,
                          t.read_snapshot, t.report_conflicting_keys)
                for t in txns
            ],
            v,
        )
        for txns, v in stream
    ]
    cs = TpuConflictSet(cfg, mesh=cpu_mesh(2), shard_boundaries=boundaries)
    for i, (txns, v) in enumerate(stream):
        got = cs.resolve(txns, v)
        assert [int(x) for x in got.verdicts] == list(want[i].verdicts), (
            f"sharded sweep batch {i}"
        )
    assert cs.metrics.counters.get("sweepGroups") == len(stream)


def test_sweep_pipelined_stream_matches_sequential():
    rng = np.random.default_rng(9)
    cfg = sweep_config()
    stream = gen_stream(rng, 8, n_txns=8)
    batches = [packing.pack_batch(t, v, 0, cfg) for t, v in stream]
    classic = TpuConflictSet(classic_config(cfg))
    seq = [classic.resolve_args(b.device_args()) for b in batches]

    cs = TpuConflictSet(cfg)
    outs = cs.resolve_stream_pipelined(batches, chunk=3)
    flat = [
        (g, k)
        for g in range(len(outs))
        for k in range(np.asarray(outs[g].verdict).shape[0])
    ]
    assert len(flat) == len(batches)
    for i, (g, k) in enumerate(flat):
        np.testing.assert_array_equal(
            np.asarray(outs[g].verdict[k]), np.asarray(seq[i].verdict),
            err_msg=f"pipelined sweep batch {i}",
        )


def test_sweep_excludes_dedup():
    with pytest.raises(ValueError, match="range_sweep and dedup_reads"):
        sweep_config(dedup_reads=8)
    with pytest.raises(ValueError, match="range_sweep requires"):
        KernelConfig(range_sweep=True)
    with pytest.raises(ValueError, match="delta_spill requires"):
        KernelConfig(delta_spill=True)


# ---------------------------------------------------------------------------
# spill-and-compact


def spill_config(**kw):
    # delta holds ~1.5 batches' conservative bound (2*32 rows/batch), so
    # an 8-batch stream is sized well past delta_capacity
    d = dict(delta_capacity=96, compact_interval=0, delta_spill=True)
    d.update(kw)
    return sweep_config(**d)


def test_spill_stream_completes_with_zero_exact_fallbacks():
    """THE acceptance pin: a stream sized past delta_capacity completes
    on device with spill configured — no HistoryOverflowError, no host
    exact-kernel re-dispatch (counter pinned at zero) — and decisions
    match a delta tier big enough to never spill."""
    rng = np.random.default_rng(21)
    cfg = spill_config()
    stream = gen_stream(rng, 8)
    cs = TpuConflictSet(cfg)
    res = run_resolve(cs, stream)
    cs.check_overflow()  # no raise
    c = cs.metrics.counters
    assert c.get("spills") > 0, "stream was sized to spill"
    assert c.get("exactFallbacks") == 0
    assert c.get("latchTrips") == 0
    assert c.get("overflowRaised") == 0

    big = TpuConflictSet(
        dataclasses.replace(cfg, delta_capacity=4096, delta_spill=False)
    )
    assert_results_match(res, run_resolve(big, stream), "spill vs big delta")

    # the OFF direction: same stream, same capacity, spill off -> raises
    off = TpuConflictSet(dataclasses.replace(cfg, delta_spill=False))
    with pytest.raises(HistoryOverflowError):
        for txns, v in stream:
            off.resolve(txns, v)
        off.check_overflow()


def dup_txn(rng, *, snap_lo, snap_hi, **_kw):
    """Overlapping-write shape: every batch writes the SAME 8 keys, so
    the delta tier's REAL live boundary count stays ~constant while the
    conservative 2*max_writes-per-batch bound grows linearly."""
    k = int(rng.integers(0, 8)) * 16
    return CommitTransaction(
        read_conflict_ranges=[(ikey(k), ikey(k + 2))],
        write_conflict_ranges=[(ikey(k), ikey(k + 2)),
                               (ikey(k + 4), ikey(k + 6))],
        read_snapshot=int(rng.integers(snap_lo, snap_hi)),
    )


def test_spill_bound_anchors_to_live_occupancy(monkeypatch):
    """ISSUE 15 (ROADMAP PR-14 headroom (b)): the overflow-check sync's
    live boundary count re-anchors the host-side spill bound, so an
    overlapping-write stream spills strictly FEWER times than the
    conservative 2*max_writes accounting would — with decisions
    unchanged vs a never-spilling reference. The old accounting is
    replayed arithmetically here (that's all it was: host arithmetic)
    as the pinned worse-case."""
    from foundationdb_tpu.models import conflict_set as cs_mod

    monkeypatch.setattr(cs_mod, "OVERFLOW_CHECK_INTERVAL", 4)
    rng = np.random.default_rng(7)
    # capacity holds the REAL occupancy (~32 live rows) plus one
    # anchor interval's conservative accrual (4 * 2*max_writes = 256),
    # but NOT the unanchored linear accrual — exactly the regime the
    # measured count fixes
    cfg = spill_config(delta_capacity=320)
    n_batches = 16
    stream = gen_stream(rng, n_batches, dup_txn)
    cs = TpuConflictSet(cfg)
    res = run_resolve(cs, stream)
    c = cs.metrics.counters
    spills = c.get("spills")
    assert c.get("spillBoundAnchors") > 0, (
        "the overflow-check sync never tightened the bound"
    )
    # the conservative accounting this PR replaces, replayed exactly:
    # += 2*max_writes per batch, spill-and-reset when the next batch
    # could overflow
    bound = conservative_spills = 0
    for _ in range(n_batches):
        add = 2 * cfg.max_writes
        if bound + add > cfg.delta_capacity:
            conservative_spills += 1
            bound = 0
        bound += add
    assert conservative_spills >= 2 * max(1, spills), (
        f"tightened bound should spill ~2x less: measured {spills}, "
        f"conservative {conservative_spills}"
    )
    assert c.get("exactFallbacks") == 0
    assert c.get("overflowRaised") == 0

    ref = TpuConflictSet(
        dataclasses.replace(cfg, delta_capacity=4096, delta_spill=False)
    )
    assert_results_match(res, run_resolve(ref, stream),
                         "anchored spill vs big delta")


def test_spill_bound_anchor_never_loosens():
    """The re-anchor is min(bound, live): a live count ABOVE the
    accrued bound (impossible by construction, but the invariant is
    what keeps spill decisions conservative) must never raise it."""
    cfg = spill_config()
    cs = TpuConflictSet(cfg)
    cs._spill_bound_rows = 10
    cs._re_anchor_spill_bound(50.0)
    assert cs._spill_bound_rows == 10
    cs._re_anchor_spill_bound(3.0)
    assert cs._spill_bound_rows == 3


@pytest.mark.parametrize("interval", [0, 1, 4])
def test_spill_decisions_invariant_vs_compact_interval(interval):
    """Pressure spills interleave with (or replace) cadence compaction;
    decisions must not depend on either schedule."""
    rng = np.random.default_rng(33)
    stream = gen_stream(rng, 8, mixed_txn)
    res = run_resolve(
        TpuConflictSet(spill_config(compact_interval=interval)), stream
    )
    ref = run_resolve(
        TpuConflictSet(sweep_config(delta_capacity=4096, compact_interval=0)),
        stream,
    )
    assert_results_match(res, ref, f"spill interval={interval}")


def test_spill_mid_stream_with_staging_thread():
    """Overflow pressure mid-stream with the pipelined staging thread
    active: the spill compaction dispatches between chunk dispatches on
    the compute thread, the staging thread keeps feeding, nothing
    raises, and decisions match the sequential reference."""
    import threading

    rng = np.random.default_rng(41)
    cfg = spill_config()
    stream = gen_stream(rng, 10, n_txns=8)
    batches = [packing.pack_batch(t, v, 0, cfg) for t, v in stream]
    cs = TpuConflictSet(cfg)
    outs = cs.resolve_stream_pipelined(batches, chunk=2)
    assert not any(
        t.name == "resolver-staging" for t in threading.enumerate()
    )
    assert cs.metrics.counters.get("spills") > 0
    assert cs.metrics.counters.get("exactFallbacks") == 0
    cs.check_overflow()

    classic = TpuConflictSet(classic_config(cfg))
    seq = [classic.resolve_args(b.device_args()) for b in batches]
    vs = np.concatenate(
        [np.asarray(o.verdict).reshape(-1, cfg.max_txns) for o in outs]
    )
    for i in range(len(batches)):
        np.testing.assert_array_equal(
            vs[i], np.asarray(seq[i].verdict),
            err_msg=f"mid-stream spill batch {i}",
        )


def test_spill_during_sharded_group():
    """Per-shard delta tiers spill independently under the conservative
    host bound; a sharded group stream past delta_capacity completes
    with zero fallbacks and oracle-identical decisions."""
    from foundationdb_tpu.parallel.mesh import cpu_mesh
    from foundationdb_tpu.testing.oracle import MultiResolverOracle, OracleTxn

    rng = np.random.default_rng(55)
    cfg = spill_config(n_shards=2)
    boundaries = [bytes([8])]
    stream = gen_stream(rng, 8, n_txns=8)
    batches = [packing.pack_batch(t, v, 0, cfg) for t, v in stream]
    cs = TpuConflictSet(cfg, mesh=cpu_mesh(2), shard_boundaries=boundaries)
    outs = [
        cs.resolve_group_args(stack_device_args(batches[lo : lo + 2]))
        for lo in range(0, 8, 2)
    ]
    cs.check_overflow()
    assert cs.metrics.counters.get("spills") > 0
    assert cs.metrics.counters.get("exactFallbacks") == 0

    oracle = MultiResolverOracle(boundaries, window=cfg.window_versions)
    for i, (txns, v) in enumerate(stream):
        want = oracle.resolve(
            [
                OracleTxn(t.read_conflict_ranges, t.write_conflict_ranges,
                          t.read_snapshot, t.report_conflicting_keys)
                for t in txns
            ],
            v,
        )
        g, k = divmod(i, 2)
        got = [int(x) for x in np.asarray(outs[g].verdict[k])[: len(txns)]]
        assert got == list(want.verdicts), f"sharded spill batch {i}"


def test_spill_then_rebase():
    """A spill (delta folded to MAIN) followed by the int32 offset
    rebase: spilled segments must shift with main and still conflict
    correctly on the far side of the jump."""
    from foundationdb_tpu.models.conflict_set import REBASE_THRESHOLD

    cfg = spill_config(window_versions=1 << 33, delta_capacity=96)
    k = lambda i: bytes([i])
    v0 = 1000
    # enough writers to trip the conservative spill bound twice
    writers = [
        ([CommitTransaction([], [(k(5), k(6))], read_snapshot=v0 - 1)]
         + [
             CommitTransaction([], [(k(20 + j), k(21 + j))],
                               read_snapshot=v0 - 1)
             for j in range(8)
         ], v0 + i)
        for i in range(3)
    ]
    far = v0 + REBASE_THRESHOLD + (1 << 21)
    r_stale = CommitTransaction([(k(5), k(6))], [(k(9), k(10))],
                                read_snapshot=v0 - 1)
    r_fresh = CommitTransaction([(k(5), k(6))], [(k(11), k(12))],
                                read_snapshot=far - 1)
    stream = writers + [([r_stale, r_fresh], far)]
    cs = TpuConflictSet(cfg)
    res = run_resolve(cs, stream)
    assert cs.metrics.counters.get("spills") > 0
    assert cs.metrics.counters.get("rebases") > 0
    assert res[-1].verdicts[0].name == "CONFLICT"
    assert res[-1].verdicts[1].name == "COMMITTED"
    ref = run_resolve(
        TpuConflictSet(
            dataclasses.replace(cfg, delta_capacity=4096, delta_spill=False)
        ),
        stream,
    )
    assert_results_match(res, ref, "spill then rebase")


def test_single_group_past_capacity_still_raises():
    """The backstop: ONE batch whose conservative bound exceeds
    delta_capacity cannot be spilled around — the latch+raise remains
    (a configuration error, never a silent truncation)."""
    cfg = sweep_config(delta_capacity=4, compact_interval=0,
                       delta_spill=True)
    k = lambda i: bytes([i])
    txns = [
        CommitTransaction([], [(k(2 * i), k(2 * i + 1))], read_snapshot=50)
        for i in range(8)
    ]
    cs = TpuConflictSet(cfg)
    with pytest.raises(HistoryOverflowError):
        cs.resolve(txns, 100)


def test_wire_resolver_role_runs_sweep_kernel():
    """The wire threading: a ResolverRole whose RESOLVER_KERNEL env
    carries a sweep+spill config must dispatch through the sweep path
    (sweepGroups counting) and produce oracle-identical decisions —
    the same mechanism the chaos/bench wire clusters use."""
    import asyncio
    import os

    from foundationdb_tpu.cluster import multiprocess as mp
    from foundationdb_tpu.models.types import (
        ResolveTransactionBatchRequest,
        TransactionResult,
    )

    cfg = sweep_config(delta_capacity=96, compact_interval=0,
                       delta_spill=True)
    os.environ["RESOLVER_KERNEL"] = (
        "KernelConfig(max_key_bytes=8, max_txns=16, max_reads=32, "
        "max_writes=32, history_capacity=512, window_versions=1000, "
        "delta_capacity=96, compact_interval=0, range_sweep=True, "
        "delta_spill=True)"
    )
    try:
        role = mp.ResolverRole(backend="tpu-force")
    finally:
        os.environ.pop("RESOLVER_KERNEL", None)
    rng = np.random.default_rng(77)
    stream = gen_stream(rng, 5)
    oracle = CpuConflictSet(cfg)

    async def wire():
        prev = -1
        for txns, v in stream:
            rep = await role.resolve(ResolveTransactionBatchRequest(
                prev_version=prev, version=v, last_received_version=prev,
                transactions=txns, proxy_id="p0",
            ))
            want = oracle.resolve(txns, v)
            assert [TransactionResult(c) for c in rep.committed] == (
                want.verdicts
            ), f"wire sweep divergence at version {v}"
            prev = v

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(wire())
    finally:
        loop.close()
    c = role._cs.metrics.counters
    assert c.get("sweepGroups") == len(stream)
    assert c.get("spills") > 0
    assert c.get("exactFallbacks") == 0
