"""Knobs / BUGGIFY / trace-log unit tests."""

import numpy as np

from foundationdb_tpu.utils.knobs import Buggifier, Knobs, make_server_knobs
from foundationdb_tpu.utils.metrics import CounterCollection
from foundationdb_tpu.utils.trace import (
    SEV_DEBUG,
    SEV_WARN,
    TraceBatch,
    TraceEvent,
    TraceLog,
    trace_counters,
)


def test_knob_define_set_reset():
    k = Knobs("test")
    k.define("FOO", 10)
    k.define("BAR", 0.5)
    assert k.FOO == 10
    k.set("FOO", "25")  # string coerced like --knob_foo=25
    assert k.FOO == 25
    k.BAR = 0.75
    assert k.BAR == 0.75
    k.reset()
    assert k.FOO == 10 and k.BAR == 0.5


def test_knob_randomize_deterministic():
    def one(seed):
        k = make_server_knobs()
        chosen = k.randomize_under_test(np.random.default_rng(seed))
        return chosen

    assert one(3) == one(3)
    # across many seeds, at least one randomization fires
    assert any(one(s) for s in range(10))


def test_buggify_two_level_determinism():
    def fires(seed):
        b = Buggifier(seed, enabled=True, activation_prob=0.5, fire_prob=0.5)
        return [b("site1") for _ in range(20)] + [b("site2") for _ in range(20)]

    assert fires(1) == fires(1)
    b = Buggifier(0, enabled=False)
    assert not any(b("site") for _ in range(100))


def test_trace_log_severity_and_rolling():
    log = TraceLog(min_severity=SEV_WARN, max_events=10)
    TraceEvent("Quiet", severity=SEV_DEBUG, logger=log).log()
    for i in range(12):
        TraceEvent("Loud", severity=SEV_WARN, logger=log).detail("I", i).log()
    assert not log.find("Quiet")
    assert len(log.events) <= 10
    assert log.find("Loud")[-1]["I"] == 11


def test_trace_counters_snapshot():
    log = TraceLog()
    c = CounterCollection("M", ["a", "b"])
    c.add("a", 5)
    trace_counters(log, "MetricsEvent", "role0", c)
    (ev,) = log.find("MetricsEvent")
    assert ev["a"] == 5 and ev["b"] == 0 and ev["ID"] == "role0"


def test_trace_batch_locations():
    tb = TraceBatch()
    tb.add_event("CommitDebug", "d1", "Resolver.resolveBatch.Before")
    tb.add_event("CommitDebug", "d1", "Resolver.resolveBatch.After")
    evs = tb.dump()
    assert [e[3] for e in evs] == [
        "Resolver.resolveBatch.Before",
        "Resolver.resolveBatch.After",
    ]
    assert tb.dump() == []


def test_resolver_emits_trace_batch(request):
    from foundationdb_tpu.config import TEST_CONFIG
    from foundationdb_tpu.models.types import ResolveTransactionBatchRequest
    from foundationdb_tpu.resolver import Resolver
    from foundationdb_tpu.runtime.flow import Scheduler
    from foundationdb_tpu.utils import trace

    # the global batch sink ships disabled; runs that trace install or
    # enable it explicitly (testing/soak.py run_seed(trace=True))
    sched = Scheduler(sim=True)
    prev = trace.install(
        trace.TraceLog(clock=sched.now),
        trace.TraceBatch(clock=sched.now, enabled=True),
    )
    try:
        res = Resolver(sched, TEST_CONFIG)
        t = sched.spawn(
            res.resolve(
                ResolveTransactionBatchRequest(
                    prev_version=-1, version=0, last_received_version=-1,
                    transactions=[], debug_id="dbg1",
                )
            )
        )
        sched.run_until(t.done)
        locs = [e[3] for e in trace.g_trace_batch.dump() if e[2] == "dbg1"]
    finally:
        trace.install(*prev)
    assert locs == [
        "Resolver.resolveBatch.Before",
        "Resolver.resolveBatch.AfterQueueSizeCheck",
        "Resolver.resolveBatch.AfterOrderer",
        "Resolver.resolveBatch.After",
    ]
