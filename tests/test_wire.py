"""Serialized wire: codec roundtrips, frame integrity, RPC transport.

The cross-process seam (wire/codec.py + wire/transport.py — FlowTransport
discipline: protocol-version handshake, CRC32 frames, token-addressed
delivery, fdbrpc/FlowTransport.actor.cpp:427,1022,1119-1142)."""

import asyncio
import struct
import zlib

import pytest

from foundationdb_tpu.models.types import (
    CommitTransaction,
    ResolveTransactionBatchReply,
    ResolveTransactionBatchRequest,
    TransactionResult,
)
from foundationdb_tpu.wire import codec, transport
from foundationdb_tpu.wire.codec import Mutation


def roundtrip(msg):
    return codec.decode(codec.encode(msg))


def test_codec_commit_transaction_roundtrip():
    t = CommitTransaction(
        read_conflict_ranges=[(b"a", b"b"), (b"k\x00", b"k\x01")],
        write_conflict_ranges=[(b"x", b"y")],
        read_snapshot=123456789,
        report_conflicting_keys=True,
        mutations=[Mutation(0, b"key", b"value"), Mutation(1, b"a", b"z")],
    )
    got = roundtrip(t)
    assert got.read_conflict_ranges == t.read_conflict_ranges
    assert got.write_conflict_ranges == t.write_conflict_ranges
    assert got.read_snapshot == t.read_snapshot
    assert got.report_conflicting_keys is True
    assert got.mutations == t.mutations


def test_codec_resolve_request_roundtrip():
    req = ResolveTransactionBatchRequest(
        prev_version=-1,
        version=1000,
        last_received_version=-1,
        transactions=[
            CommitTransaction(
                read_conflict_ranges=[(b"a", b"c")], read_snapshot=5
            ),
            CommitTransaction(write_conflict_ranges=[(b"d", b"e")]),
        ],
        txn_state_transactions=[1],
        proxy_id="proxy0",
        debug_id=None,
    )
    got = roundtrip(req)
    assert got.version == 1000 and got.prev_version == -1
    assert len(got.transactions) == 2
    assert got.transactions[0].read_conflict_ranges == [(b"a", b"c")]
    assert got.txn_state_transactions == [1]
    assert got.proxy_id == "proxy0" and got.debug_id is None


def test_codec_resolve_reply_roundtrip():
    rep = ResolveTransactionBatchReply(
        committed=[TransactionResult.COMMITTED, TransactionResult.CONFLICT],
        conflicting_key_range_map={1: [0, 2]},
        state_mutations=[(500, [Mutation(0, b"\xff/k", b"v")])],
        debug_id="d1",
    )
    got = roundtrip(rep)
    assert got.committed == rep.committed
    assert got.conflicting_key_range_map == {1: [0, 2]}
    assert got.state_mutations[0][0] == 500
    assert got.state_mutations[0][1] == [Mutation(0, b"\xff/k", b"v")]


def test_codec_rejects_garbage():
    with pytest.raises(codec.CodecError):
        codec.decode(b"\xff\xff rest")
    with pytest.raises(codec.CodecError):
        codec.decode(b"\x01")
    # trailing junk after a valid message
    good = codec.encode(CommitTransaction())
    with pytest.raises(codec.CodecError):
        codec.decode(good + b"junk")
    # truncation anywhere in a valid message
    req = codec.encode(
        ResolveTransactionBatchRequest(
            prev_version=0, version=1, last_received_version=0,
            transactions=[CommitTransaction(read_conflict_ranges=[(b"a", b"b")])],
        )
    )
    with pytest.raises(codec.CodecError):
        codec.decode(req[: len(req) // 2])


# ---------------------------------------------------------------------------
# Transport.


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


@pytest.fixture
def sock(tmp_path):
    return str(tmp_path / "role.sock")


def test_rpc_echo_and_concurrency(sock):
    from foundationdb_tpu.cluster.multiprocess import (
        TOKEN_PING,
        Ping,
        Pong,
    )

    async def scenario():
        server = transport.RpcServer(sock)

        async def ping(msg):
            await asyncio.sleep(0.01 if msg.payload == b"slow" else 0)
            return Pong(payload=msg.payload)

        server.register(TOKEN_PING, ping)
        await server.start()
        conn = transport.RpcConnection(sock)
        await conn.connect()
        # concurrent requests over one connection correlate correctly
        slow = conn.call(TOKEN_PING, Ping(payload=b"slow"))
        fast = conn.call(TOKEN_PING, Ping(payload=b"fast"))
        rs, rf = await asyncio.gather(slow, fast)
        assert rs.payload == b"slow" and rf.payload == b"fast"
        await conn.close()
        await server.close()

    run(scenario())


def test_rpc_unknown_token_and_handler_error(sock):
    from foundationdb_tpu.cluster.multiprocess import TOKEN_PING, Ping

    async def scenario():
        server = transport.RpcServer(sock)

        async def boom(msg):
            raise ValueError("kaboom")

        server.register(TOKEN_PING, boom)
        await server.start()
        conn = transport.RpcConnection(sock)
        await conn.connect()
        with pytest.raises(transport.RemoteError, match="kaboom"):
            await conn.call(TOKEN_PING, Ping(payload=b"x"))
        with pytest.raises(transport.RemoteError):
            await conn.call(0xDEAD, Ping(payload=b"x"))
        await conn.close()
        await server.close()

    run(scenario())


def test_handshake_version_mismatch(sock):
    async def scenario():
        server = transport.RpcServer(sock)
        await server.start()
        reader, writer = await asyncio.open_unix_connection(path=sock)
        writer.write(transport.MAGIC + struct.pack("<Q", 0xBAD))
        await writer.drain()
        # server sends its handshake then closes on our bad version
        data = await reader.read(1024)
        assert data.startswith(transport.MAGIC)
        more = await reader.read(1024)
        assert more == b""  # closed
        writer.close()
        await server.close()

    run(scenario())


def test_corrupt_frame_rejected(sock):
    from foundationdb_tpu.cluster.multiprocess import TOKEN_PING, Ping, Pong

    async def scenario():
        server = transport.RpcServer(sock)

        async def ping(msg):
            return Pong(payload=msg.payload)

        server.register(TOKEN_PING, ping)
        await server.start()
        reader, writer = await asyncio.open_unix_connection(path=sock)
        writer.write(
            transport.MAGIC + struct.pack("<Q", codec.PROTOCOL_VERSION)
        )
        await writer.drain()
        await reader.readexactly(len(transport.MAGIC) + 8)
        body = (
            transport._REQ.pack(transport.KIND_REQUEST, 1, TOKEN_PING)
            + codec.encode(Ping(payload=b"x"))
        )
        # flip a payload bit but keep the stated crc of the original body
        bad = bytearray(body)
        bad[-1] ^= 0x40
        writer.write(
            transport._HDR.pack(len(bad), zlib.crc32(body) & 0xFFFFFFFF)
        )
        writer.write(bytes(bad))
        await writer.drain()
        # server must drop the connection, never answer
        data = await reader.read(1024)
        assert data == b""
        writer.close()
        await server.close()

    run(scenario())
