"""Version-vector wire surface (VERDICT r3 missing #4): tpcvMap +
writtenTags on the resolver reply, knob-gated like the reference
(ENABLE_VERSION_VECTOR_TLOG_UNICAST; ResolverInterface.h:140-151,
Resolver.actor.cpp:475-495).
"""

from __future__ import annotations

import pytest

from foundationdb_tpu.config import TEST_CONFIG
from foundationdb_tpu.models.types import (
    CommitTransaction,
    ResolveTransactionBatchRequest,
)
from foundationdb_tpu.resolver import Resolver
from foundationdb_tpu.runtime.flow import Scheduler
from foundationdb_tpu.utils.knobs import SERVER_KNOBS


@pytest.fixture
def vv_knob():
    old = SERVER_KNOBS.ENABLE_VERSION_VECTOR_TLOG_UNICAST
    SERVER_KNOBS.set("ENABLE_VERSION_VECTOR_TLOG_UNICAST", True)
    yield
    SERVER_KNOBS.set("ENABLE_VERSION_VECTOR_TLOG_UNICAST", old)


def run(sched, coro):
    t = sched.spawn(coro)
    sched.run_until(t.done)
    return t.done.get()


def test_tpcv_map_recurrence(vv_knob):
    """reply.tpcvMap[log] = the PREVIOUS version that wrote that log;
    the vector lazily fills with the first batch's prev_version."""
    sched = Scheduler(sim=True)
    res = Resolver(sched, TEST_CONFIG, backend="cpu", num_logs=3)

    def req(prev, version, tags, txns=()):
        return ResolveTransactionBatchRequest(
            prev_version=prev, version=version, last_received_version=prev,
            transactions=list(txns), written_tags=frozenset(tags),
            proxy_id="p0",
        )

    async def drive():
        # recovery batch from the master
        await res.resolve(req(-1, 0, ()))
        # batch v10 writes tags {0, 1} -> logs {0, 1}
        r1 = await res.resolve(req(0, 10, (0, 1)))
        assert r1.tpcv_map == {0: 0, 1: 0}
        assert r1.written_tags == frozenset((0, 1))
        # batch v20 writes tags {1, 2}: log1 last written at 10, log2
        # never since the fill (0)
        r2 = await res.resolve(req(10, 20, (1, 2)))
        assert r2.tpcv_map == {1: 10, 2: 0}
        # batch v30 writes tag 0 only: log0 last written at 10
        r3 = await res.resolve(req(20, 30, (0,)))
        assert r3.tpcv_map == {0: 10}
        return True

    assert run(sched, drive())


def test_tpcv_state_txns_broadcast(vv_knob):
    """Metadata/state batches touch EVERY log (the shardChanged ||
    privateMutationCount branch at :481-484)."""
    sched = Scheduler(sim=True)
    res = Resolver(sched, TEST_CONFIG, backend="cpu", num_logs=3)

    async def drive():
        await res.resolve(ResolveTransactionBatchRequest(
            prev_version=-1, version=0, last_received_version=-1,
        ))
        state_txn = CommitTransaction(
            mutations=[("set", b"\xff/conf/x", b"1")]
        )
        r = await res.resolve(ResolveTransactionBatchRequest(
            prev_version=0, version=10, last_received_version=0,
            transactions=[state_txn], txn_state_transactions=[0],
            written_tags=frozenset((1,)), proxy_id="p0",
        ))
        assert set(r.tpcv_map) == {0, 1, 2}
        return True

    assert run(sched, drive())


def test_knob_off_leaves_surface_empty():
    sched = Scheduler(sim=True)
    res = Resolver(sched, TEST_CONFIG, backend="cpu", num_logs=3)

    async def drive():
        await res.resolve(ResolveTransactionBatchRequest(
            prev_version=-1, version=0, last_received_version=-1,
        ))
        r = await res.resolve(ResolveTransactionBatchRequest(
            prev_version=0, version=10, last_received_version=0,
            written_tags=frozenset((0,)), proxy_id="p0",
        ))
        assert r.tpcv_map == {} and r.written_tags == frozenset()
        return True

    assert run(sched, drive())


def test_cluster_commits_with_version_vector_on(vv_knob):
    """End to end: the proxy computes written tags from the shard map
    and commits flow normally with the knob on."""
    from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster

    sched, cluster, db = open_cluster(ClusterConfig(n_storage=2))
    try:
        async def body():
            txn = db.create_transaction()
            txn.set(b"vv-key", b"1")
            await txn.commit()
            txn = db.create_transaction()
            return await txn.get(b"vv-key")

        assert run(sched, body()) == b"1"
    finally:
        cluster.stop()
