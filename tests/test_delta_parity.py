"""Tiered (delta + main) kernel parity: decisions identical to classic.

The r6 tiered path (ops/delta.py — G-independent scan body, delta-tier
merges, periodic compaction, optional device-side read dedup) must be
decision-identical to the classic sequential pipeline (ops/conflict.
resolve_batch per batch) and to the Python oracle, on the adversarial
shapes the design introduces new machinery for:

* duplicate/overlapping conflict ranges (the dedup sort+unique path),
* window-edge versions (snapshots at/beside the GC floor),
* compaction boundaries (delta folded into main mid-stream, at every
  cadence),
* latch/overflow trips (dedup latch: unconverged + state unchanged;
  delta capacity overflow: loud HistoryOverflowError, never silence).

Runs in the kernel parity lane (8-device CPU mesh, -m kernel).
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
import pytest

from foundationdb_tpu.config import KernelConfig
from foundationdb_tpu.models.conflict_set import (
    OVERFLOW_CHECK_INTERVAL,
    CpuConflictSet,
    HistoryOverflowError,
    TpuConflictSet,
)
from foundationdb_tpu.models.types import CommitTransaction
from foundationdb_tpu.ops import delta as D
from foundationdb_tpu.ops import history as H
from foundationdb_tpu.utils import packing
from foundationdb_tpu.utils.packing import stack_device_args

from conftest import random_range

# compile-heavy kernel tests: run with -m kernel (fast lane: -m 'not kernel')
pytestmark = pytest.mark.kernel


def tiered_config(**kw):
    d = dict(
        max_key_bytes=8,
        max_txns=16,
        max_reads=32,
        max_writes=32,
        history_capacity=512,
        window_versions=1000,
        delta_capacity=256,
        compact_interval=1,
    )
    d.update(kw)
    return KernelConfig(**d)


def classic_config(cfg):
    return dataclasses.replace(
        cfg, delta_capacity=0, dedup_reads=0, compact_interval=1
    )


def random_txn(rng, *, snap_lo, snap_hi, n_ranges=2, blind_prob=0.15,
               dup_pool=None, report_prob=0.5):
    def draw():
        if dup_pool is not None and rng.random() < 0.7:
            return dup_pool[int(rng.integers(0, len(dup_pool)))]
        return random_range(rng)

    reads = [] if rng.random() < blind_prob else [
        draw() for _ in range(1 + int(rng.integers(0, n_ranges)))
    ]
    writes = [draw() for _ in range(1 + int(rng.integers(0, n_ranges)))]
    return CommitTransaction(
        read_conflict_ranges=reads,
        write_conflict_ranges=writes,
        read_snapshot=int(rng.integers(snap_lo, snap_hi)),
        report_conflicting_keys=bool(rng.random() < report_prob),
    )


def gen_stream(rng, n_batches, *, base=1000, step=100, n_txns=10,
               dup_pool=None):
    out = []
    for i in range(n_batches):
        version = base + (i + 1) * step
        out.append((
            [
                random_txn(
                    rng, snap_lo=max(0, base - 2 * step), snap_hi=version,
                    dup_pool=dup_pool,
                )
                for _ in range(n_txns)
            ],
            version,
        ))
    return out


def run_resolve(cs, stream):
    return [cs.resolve(txns, v) for txns, v in stream]


def assert_results_match(a, b, label=""):
    for i, (ra, rb) in enumerate(zip(a, b)):
        assert ra.verdicts == rb.verdicts, f"{label} verdicts batch {i}"
        assert ra.conflicting_key_ranges == rb.conflicting_key_ranges, (
            f"{label} conflicting ranges batch {i}"
        )


def canonical_map(hist: H.VersionHistory):
    """(boundary key, version) pairs with redundant rows collapsed (the
    test_group_parity evaluation-equality form, for ONE tier)."""
    mk = np.asarray(hist.main_keys)
    mv = np.asarray(hist.main_ver)
    rows = []
    for j in range(mk.shape[0]):
        if all(x == 0xFFFFFFFF for x in mk[j]):
            continue
        rows.append((tuple(mk[j]), int(mv[j])))
    rows.sort()
    dedup = {}
    for k, v in rows:
        dedup[k] = v
    out = []
    for k in sorted(dedup):
        if not out or out[-1][1] != dedup[k]:
            out.append((k, dedup[k]))
    return out


@pytest.mark.parametrize("seed", range(6))
def test_tiered_matches_classic_random(seed):
    rng = np.random.default_rng(seed)
    cfg = tiered_config()
    stream = gen_stream(rng, 8)
    res_t = run_resolve(TpuConflictSet(cfg), stream)
    res_c = run_resolve(TpuConflictSet(classic_config(cfg)), stream)
    assert_results_match(res_t, res_c, "tiered vs classic")


@pytest.mark.parametrize("seed", range(4))
def test_tiered_matches_cpu_oracle(seed):
    """Full-stack parity against the CPU backend (the skiplist-semantics
    oracle behind the resolver_backend knob)."""
    rng = np.random.default_rng(100 + seed)
    cfg = tiered_config(dedup_reads=32)
    stream = gen_stream(rng, 6)
    res_t = run_resolve(TpuConflictSet(cfg), stream)
    res_o = run_resolve(CpuConflictSet(cfg), stream)
    assert_results_match(res_t, res_o, "tiered vs cpu oracle")


@pytest.mark.parametrize("seed", range(4))
def test_duplicate_and_overlapping_ranges_dedup_parity(seed):
    """Hot-key adversarial: most ranges drawn from a small duplicate
    pool (plus overlapping random ones). The dedup path must be
    decision-identical to dedup-off and to the classic kernel."""
    rng = np.random.default_rng(200 + seed)
    pool = [random_range(rng) for _ in range(4)]
    stream = gen_stream(rng, 6, dup_pool=pool)
    cfg_dedup = tiered_config(dedup_reads=16)
    res_d = run_resolve(TpuConflictSet(cfg_dedup), stream)
    res_p = run_resolve(TpuConflictSet(tiered_config()), stream)
    res_c = run_resolve(TpuConflictSet(classic_config(cfg_dedup)), stream)
    assert_results_match(res_d, res_p, "dedup vs plain tiered")
    assert_results_match(res_d, res_c, "dedup vs classic")


def test_window_edge_versions():
    """Snapshots exactly at / one beside the MVCC floor: the too-old
    boundary and the GC boundary must match the classic kernel."""
    cfg = tiered_config(window_versions=100)
    k = lambda i: bytes([i])
    streams = []
    for snap in (99, 100, 101, 199, 200):
        streams.append((
            [
                CommitTransaction([(k(1), k(2))], [(k(1), k(2))],
                                  read_snapshot=snap),
                CommitTransaction([], [(k(3), k(4))], read_snapshot=snap),
            ],
            200 + len(streams),  # versions ascend; floor = version - 100
        ))
    res_t = run_resolve(TpuConflictSet(cfg), streams)
    res_c = run_resolve(TpuConflictSet(classic_config(cfg)), streams)
    assert_results_match(res_t, res_c, "window edge")


@pytest.mark.parametrize("interval", [1, 2, 4, 0])
def test_compaction_cadence_invariance(interval):
    """Decisions must not depend on WHEN delta folds into main: every
    compaction cadence (incl. never) gives identical verdicts, and the
    combined key->version map after an explicit final compaction matches
    the classic single-tier map."""
    rng = np.random.default_rng(42)
    stream = gen_stream(rng, 8)
    cfg = tiered_config(compact_interval=interval, delta_capacity=512)
    cs = TpuConflictSet(cfg)
    res = run_resolve(cs, stream)
    classic = TpuConflictSet(classic_config(cfg))
    res_c = run_resolve(classic, stream)
    assert_results_match(res, res_c, f"interval={interval}")

    cs.compact_history()
    assert not bool(np.asarray(H.boundary_count(cs.state.delta)))
    got = canonical_map(cs.state.main)
    want = canonical_map(classic.state)
    assert got == want, "post-compaction combined map diverges"


def test_compaction_boundary_mid_group_stream():
    """Group-path compaction boundaries: groups resolved through
    resolve_group_args with auto-compaction between them must match the
    classic sequential path batch-for-batch."""
    rng = np.random.default_rng(7)
    cfg = tiered_config(compact_interval=1)
    stream = gen_stream(rng, 9, n_txns=8)
    batches = [
        packing.pack_batch(txns, v, 0, cfg) for txns, v in stream
    ]
    classic = TpuConflictSet(classic_config(cfg))
    seq = [classic.resolve_args(b.device_args()) for b in batches]

    cs = TpuConflictSet(cfg)
    outs = [
        cs.resolve_group_args(stack_device_args(batches[lo : lo + 3]))
        for lo in (0, 3, 6)
    ]
    for i in range(9):
        g, k = divmod(i, 3)
        np.testing.assert_array_equal(
            np.asarray(outs[g].verdict[k]), np.asarray(seq[i].verdict),
            err_msg=f"verdict batch {i}",
        )
        np.testing.assert_array_equal(
            np.asarray(outs[g].hist_conflict_read[k]),
            np.asarray(seq[i].hist_conflict_read),
            err_msg=f"hist_conflict_read batch {i}",
        )
        np.testing.assert_array_equal(
            np.asarray(outs[g].intra_first_range[k]),
            np.asarray(seq[i].intra_first_range),
            err_msg=f"intra_first_range batch {i}",
        )


def test_dedup_latch_trips_state_unchanged_and_fallback():
    """More distinct live read ranges than dedup_reads: the raw kernel
    must refuse (unconverged, BOTH tiers unchanged); the default host
    path must auto-redispatch the exact kernel and serve decisions
    identical to dedup-off."""
    rng = np.random.default_rng(3)
    cfg = tiered_config(dedup_reads=2, compact_interval=0)
    stream = gen_stream(rng, 3)
    batches = [packing.pack_batch(t, v, 0, cfg) for t, v in stream]
    stacked = stack_device_args(batches)

    cs_raw = TpuConflictSet(cfg)
    before = jax.tree.map(lambda x: np.asarray(x).copy(), cs_raw.state)
    outs_raw = cs_raw.resolve_group_args(stacked, check_latch=False)
    assert bool(np.asarray(outs_raw.unconverged).all())
    for a, b in zip(
        jax.tree_util.tree_leaves(before),
        jax.tree_util.tree_leaves(cs_raw.state),
    ):
        np.testing.assert_array_equal(a, np.asarray(b))

    cs = TpuConflictSet(cfg)
    outs = cs.resolve_group_args(stacked)
    assert not bool(np.asarray(outs.unconverged).any())
    ref = TpuConflictSet(tiered_config(compact_interval=0)).resolve_group_args(
        stacked
    )
    np.testing.assert_array_equal(
        np.asarray(outs.verdict), np.asarray(ref.verdict)
    )


def test_delta_overflow_raises_loudly():
    """A delta tier too small for the write load must latch overflow and
    raise HistoryOverflowError at the next check — never truncate."""
    cfg = tiered_config(delta_capacity=4, compact_interval=0)
    k = lambda i: bytes([i])
    txns = [
        CommitTransaction([], [(k(2 * i), k(2 * i + 1))], read_snapshot=50)
        for i in range(8)
    ]
    cs = TpuConflictSet(cfg)
    with pytest.raises(HistoryOverflowError):
        cs.resolve(txns, 100)


def test_compaction_overflow_folds_into_main():
    """A latched delta overflow must survive compaction (folded into
    main.overflow) so the raise can never be skipped by a compact."""
    cfg = tiered_config(delta_capacity=4, compact_interval=0)
    k = lambda i: bytes([i])
    txns = [
        CommitTransaction([], [(k(2 * i), k(2 * i + 1))], read_snapshot=50)
        for i in range(8)
    ]
    cs = TpuConflictSet(cfg)
    batch = packing.pack_batch(txns, 100, 0, cfg)
    cs.resolve_group_args(stack_device_args([batch]), check_latch=False)
    cs.compact_history()
    assert not bool(np.asarray(cs.state.delta.overflow))
    with pytest.raises(HistoryOverflowError):
        cs.check_overflow()


def test_pipelined_stream_matches_sequential():
    """resolve_stream_pipelined (staging-thread pack->copy->compute)
    must produce the classic sequential decisions, chunk by chunk."""
    rng = np.random.default_rng(11)
    cfg = tiered_config()
    stream = gen_stream(rng, 8, n_txns=8)
    batches = [packing.pack_batch(t, v, 0, cfg) for t, v in stream]
    classic = TpuConflictSet(classic_config(cfg))
    seq = [classic.resolve_args(b.device_args()) for b in batches]

    cs = TpuConflictSet(cfg)
    outs = cs.resolve_stream_pipelined(batches, chunk=3)
    flat = [
        (g, k)
        for g in range(len(outs))
        for k in range(np.asarray(outs[g].verdict).shape[0])
    ]
    assert len(flat) == len(batches)
    for i, (g, k) in enumerate(flat):
        np.testing.assert_array_equal(
            np.asarray(outs[g].verdict[k]), np.asarray(seq[i].verdict),
            err_msg=f"pipelined batch {i}",
        )


def test_pipelined_stream_overflow_joins_staging_thread():
    """A mid-stream HistoryOverflowError must not strand the staging
    thread on the bounded queue (it holds staged device buffers)."""
    import threading

    cfg = tiered_config(
        delta_capacity=8, compact_interval=0, window_versions=100000
    )
    k = lambda i: bytes([i % 250])
    batches = []
    for i in range(3 * OVERFLOW_CHECK_INTERVAL):
        txns = [
            CommitTransaction(
                [], [(k(3 * j + i), k(3 * j + i) + b"\x01")],
                read_snapshot=50,
            )
            for j in range(8)
        ]
        batches.append(packing.pack_batch(txns, 100 + i, 0, cfg))
    cs = TpuConflictSet(cfg)
    with pytest.raises(HistoryOverflowError):
        cs.resolve_stream_pipelined(batches, chunk=1, check_latch=False)
    assert not any(
        t.name == "resolver-staging" for t in threading.enumerate()
    )


def test_tiered_rebase_matches_classic():
    """The int32 offset rebase must shift BOTH tiers (a delta-tier
    segment surviving a rebase still conflicts correctly)."""
    from foundationdb_tpu.models.conflict_set import REBASE_THRESHOLD

    # window wider than the rebase jump so the old-snapshot reader is
    # judged on staleness (CONFLICT), not the too-old floor
    cfg = tiered_config(window_versions=1 << 33, compact_interval=0)
    ccfg = classic_config(cfg)
    k = lambda i: bytes([i])
    v0 = 1000
    w = CommitTransaction([], [(k(5), k(6))], read_snapshot=v0 - 1)
    far = v0 + REBASE_THRESHOLD + (1 << 21)
    r = CommitTransaction([(k(5), k(6))], [(k(9), k(10))],
                          read_snapshot=v0 - 1)  # stale: must conflict
    r2 = CommitTransaction([(k(5), k(6))], [(k(11), k(12))],
                           read_snapshot=far - 1)  # fresh: commits
    stream = [([w], v0), ([r, r2], far)]
    res_t = run_resolve(TpuConflictSet(cfg), stream)
    res_c = run_resolve(TpuConflictSet(ccfg), stream)
    assert_results_match(res_t, res_c, "rebase")
    assert res_t[1].verdicts[0].name == "CONFLICT"
    assert res_t[1].verdicts[1].name == "COMMITTED"
