"""Blob granules: snapshot+delta materialization, time travel, splits
(VERDICT r4 task 10; fdbserver/BlobWorker.actor.cpp,
fdbserver/BlobManager.actor.cpp, fdbclient/BlobGranuleFiles.cpp)."""

from __future__ import annotations

from foundationdb_tpu.cluster.backup import BackupContainer
from foundationdb_tpu.cluster.blob_granules import (
    MAPPING_PREFIX,
    BlobManager,
    BlobWorker,
)
from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster


def run(sched, coro):
    return sched.run_until(sched.spawn(coro).done)


def open_blobbed(n_workers=1):
    sched, cluster, db = open_cluster(ClusterConfig(n_storage=2))
    container = BackupContainer()
    workers = [
        BlobWorker(sched, cluster.tlog, container, name=f"blobworker{i}")
        for i in range(n_workers)
    ]
    for w in workers:
        w.start()
    mgr = BlobManager(db, workers)
    return sched, cluster, db, container, workers, mgr


def test_granule_files_written_under_load():
    sched, cluster, db, container, (w,), mgr = open_blobbed()

    async def body():
        await mgr.blobbify(b"", b"", {}, 0)
        for i in range(64):
            txn = db.create_transaction()
            txn.set(b"bk%03d" % i, b"x" * 128)
            await txn.commit()
        await sched.delay(0.3)  # worker drains the log
        return True

    assert run(sched, body())
    snaps = container.list_files("granules/0/snapshot/")
    deltas = container.list_files("granules/0/delta/")
    assert snaps, "no snapshot files written"
    assert deltas, "no delta files written under write load"
    # mapping persisted in the system keyspace
    async def mapping():
        txn = db.create_transaction()
        return await txn.get_range(MAPPING_PREFIX, MAPPING_PREFIX + b"\xff")
    assert run(sched, mapping())
    cluster.stop()


def test_point_in_time_granule_read():
    sched, cluster, db, container, (w,), mgr = open_blobbed()

    async def body():
        await mgr.blobbify(b"", b"", {}, 0)
        txn = db.create_transaction()
        txn.set(b"k1", b"old")
        await txn.commit()
        v1 = cluster.tlog.version.get()
        await sched.delay(0.1)
        txn = db.create_transaction()
        txn.set(b"k1", b"new")
        txn.set(b"k2", b"v2")
        await txn.commit()
        txn = db.create_transaction()
        txn.clear(b"k2")
        await txn.commit()
        await sched.delay(0.2)
        # time travel: the granule at v1 shows the OLD value and no k2
        past = mgr.read(b"", b"", v1)
        now = mgr.read(b"", b"")
        return v1, past, now

    v1, past, now = run(sched, body())
    assert past[b"k1"] == b"old" and b"k2" not in past
    assert now[b"k1"] == b"new" and b"k2" not in now  # cleared
    cluster.stop()


def test_granule_read_matches_database():
    """The files-only read agrees with the transactional view — the
    consistency contract blob analytics relies on."""
    sched, cluster, db, container, (w,), mgr = open_blobbed()

    import numpy as np

    async def body():
        await mgr.blobbify(b"", b"", {}, 0)
        rng = np.random.default_rng(7)
        model = {}
        for i in range(120):
            txn = db.create_transaction()
            k = b"g%02d" % rng.integers(0, 40)
            if rng.random() < 0.2:
                txn.clear(k)
                model.pop(k, None)
            else:
                val = b"v%d" % i
                txn.set(k, val)
                model[k] = val
            await txn.commit()
        await sched.delay(0.3)
        got = mgr.read(b"", b"")
        return model, got

    model, got = run(sched, body())
    assert got == model
    cluster.stop()


def test_granule_split_on_size():
    sched, cluster, db, container, (w,), mgr = open_blobbed()

    async def body():
        await mgr.blobbify(b"", b"", {}, 0)
        val = b"z" * 512
        for i in range(160):  # ~80KB through a 48KB split threshold
            txn = db.create_transaction()
            txn.set(b"s%04d" % i, val)
            await txn.commit()
        await sched.delay(0.4)
        return True

    assert run(sched, body())
    assert len(mgr.granules) >= 2, "granule never split under load"
    bounds = sorted(
        (g.begin, g.end) for g in mgr.granules.values()
    )
    # children tile the keyspace without overlap
    for (b1, e1), (b2, _e2) in zip(bounds, bounds[1:]):
        assert e1 == b2, bounds
    # reads remain correct across the split
    got = mgr.read(b"", b"")
    assert len(got) == 160
    assert got[b"s0000"] == b"z" * 512 and got[b"s0159"] == b"z" * 512
    cluster.stop()


def test_time_travel_survives_split():
    """A key living in the RIGHT half after a split must still be
    readable at versions BELOW the split: the child inherits the
    parent's file refs (the any-version-in-retention contract)."""
    sched, cluster, db, container, (w,), mgr = open_blobbed()

    async def body():
        await mgr.blobbify(b"", b"", {}, 0)
        txn = db.create_transaction()
        txn.set(b"zz-early", b"ancient")
        await txn.commit()
        await sched.delay(0.1)
        v_past = cluster.tlog.version.get()
        val = b"z" * 512
        for i in range(160):  # force a split well above v_past
            txn = db.create_transaction()
            txn.set(b"s%04d" % i, val)
            await txn.commit()
        await sched.delay(0.4)
        assert len(mgr.granules) >= 2, "split never happened"
        past = mgr.read(b"", b"", v_past)
        return past

    past = run(sched, body())
    assert past.get(b"zz-early") == b"ancient", past
    assert not any(k.startswith(b"s0") for k in past)
    cluster.stop()
