"""Multi-resolver sharding parity: shard_map kernel vs. per-shard oracles.

The sharded TPU path must reproduce the reference's multi-resolver
deployment bit-for-bit: independent per-shard histories over a keyspace
partition with min() verdict combine (CommitProxyServer.actor.cpp:
1551-1567). The oracle side (MultiResolverOracle) models exactly that, so
any divergence is a kernel bug, not a semantics choice.

Runs on the 8-virtual-device CPU mesh from conftest.
"""

import numpy as np
import pytest

from foundationdb_tpu.config import TEST_CONFIG
from foundationdb_tpu.parallel.mesh import cpu_mesh
from foundationdb_tpu.parallel.sharding import ShardedConflictSet
from foundationdb_tpu.testing.oracle import MultiResolverOracle, OracleTxn
from foundationdb_tpu.testing.workloads import WorkloadConfig, int_key, make_batch

# compile-heavy kernel tests: run with -m kernel (fast lane: -m 'not kernel')
pytestmark = pytest.mark.kernel


def make_mesh(n: int):
    # jax.devices("cpu"), never jax.devices(): the bench environment
    # force-registers a 1-chip TPU backend ahead of conftest's
    # JAX_PLATFORMS=cpu (VERDICT r1 weakness 2).
    return cpu_mesh(n)


def to_oracle(txns):
    return [
        OracleTxn(
            read_conflict_ranges=t.read_conflict_ranges,
            write_conflict_ranges=t.write_conflict_ranges,
            read_snapshot=t.read_snapshot,
            report_conflicting_keys=t.report_conflicting_keys,
        )
        for t in txns
    ]


@pytest.mark.parametrize("n_shards", [2, 4])
def test_sharded_parity_random_batches(n_shards):
    cfg = TEST_CONFIG
    wcfg = WorkloadConfig(n_txns=24, keyspace=48, key_width=6)
    boundaries = [
        int_key((i + 1) * wcfg.keyspace // n_shards, wcfg.key_width)
        for i in range(n_shards - 1)
    ]
    mesh = make_mesh(n_shards)
    dev = ShardedConflictSet(cfg, mesh, boundaries)
    oracle = MultiResolverOracle(boundaries, window=cfg.window_versions)

    rng = np.random.default_rng(7)
    version = 0
    for step in range(12):
        version += int(rng.integers(1, 40))
        txns = make_batch(rng, wcfg, version, cfg.window_versions)
        got = dev.resolve(txns, version)
        want = oracle.resolve(to_oracle(txns), version)
        verdicts = np.asarray(got.verdict)[: len(txns)].tolist()
        assert verdicts == want.verdicts, f"step {step}: {verdicts} != {want.verdicts}"


def test_sharded_matches_reference_combine_semantics():
    """A txn whose reads conflict on one shard but commit on another must
    abort globally, and its writes still merge on the committing shard
    (phantom-commit behavior)."""
    cfg = TEST_CONFIG
    boundaries = [b"m"]
    mesh = make_mesh(2)
    dev = ShardedConflictSet(cfg, mesh, boundaries)
    oracle = MultiResolverOracle(boundaries, window=cfg.window_versions)

    from foundationdb_tpu.models.types import CommitTransaction

    # v10: write a (shard 0) and z (shard 1)
    setup = [CommitTransaction(write_conflict_ranges=[(b"a", b"b"), (b"z", b"zz")])]
    dev.resolve(setup, 10)
    oracle.resolve(to_oracle(setup), 10)

    # txn 0: stale read of a (conflicts on shard 0), fresh write of q on
    #        shard 1 -> globally aborted, but q's write merges on shard 1.
    # txn 1 (same batch, later): reads q on shard 1 at snapshot 5 — shard 1
    #        considers txn 0 committed locally, so intra-batch conflict.
    batch = [
        CommitTransaction(
            read_conflict_ranges=[(b"a", b"b")],
            write_conflict_ranges=[(b"q", b"r")],
            read_snapshot=5,
        ),
        CommitTransaction(
            read_conflict_ranges=[(b"q", b"r")],
            write_conflict_ranges=[(b"s", b"t")],
            read_snapshot=5,
        ),
    ]
    got = dev.resolve(batch, 20)
    want = oracle.resolve(to_oracle(batch), 20)
    verdicts = np.asarray(got.verdict)[:2].tolist()
    assert verdicts == want.verdicts
    assert verdicts == [0, 0]  # both CONFLICT — the phantom cascade


def test_sharded_zipf_contention_parity():
    cfg = TEST_CONFIG
    wcfg = WorkloadConfig(
        n_txns=24, keyspace=32, zipf=1.3, key_width=6, stale_fraction=0.05
    )
    boundaries = [int_key(4, 6), int_key(12, 6), int_key(24, 6)]
    mesh = make_mesh(4)
    dev = ShardedConflictSet(cfg, mesh, boundaries)
    oracle = MultiResolverOracle(boundaries, window=cfg.window_versions)

    rng = np.random.default_rng(11)
    version = 0
    for _ in range(10):
        version += int(rng.integers(1, 30))
        txns = make_batch(rng, wcfg, version, cfg.window_versions)
        got = dev.resolve(txns, version)
        want = oracle.resolve(to_oracle(txns), version)
        assert np.asarray(got.verdict)[: len(txns)].tolist() == want.verdicts


@pytest.mark.parametrize("n_shards", [2, 4])
def test_sharded_group_matches_sequential(n_shards):
    """The GROUP kernel under shard_map (VERDICT r3 weak #3): resolving
    G stacked batches in one SPMD program must be decision-identical to
    the per-batch sharded path AND to the multi-resolver oracle."""
    cfg = TEST_CONFIG
    wcfg = WorkloadConfig(n_txns=16, keyspace=40, key_width=6)
    boundaries = [
        int_key((i + 1) * wcfg.keyspace // n_shards, wcfg.key_width)
        for i in range(n_shards - 1)
    ]
    mesh = make_mesh(n_shards)
    grouped = ShardedConflictSet(cfg, mesh, boundaries)
    seq = ShardedConflictSet(cfg, mesh, boundaries)
    oracle = MultiResolverOracle(boundaries, window=cfg.window_versions)

    rng = np.random.default_rng(23)
    version = 0
    for step in range(4):
        batches, versions = [], []
        for _g in range(3):
            version += int(rng.integers(1, 30))
            versions.append(version)
            batches.append(make_batch(rng, wcfg, version, cfg.window_versions))
        got = grouped.resolve_group(batches, versions)
        for gi, (txns, v) in enumerate(zip(batches, versions)):
            want = oracle.resolve(to_oracle(txns), v)
            seq_got = seq.resolve(txns, v)
            group_verdicts = np.asarray(got.verdict[gi])[: len(txns)].tolist()
            seq_verdicts = np.asarray(seq_got.verdict)[: len(txns)].tolist()
            assert group_verdicts == want.verdicts, (
                f"step {step} batch {gi}: group {group_verdicts} "
                f"!= oracle {want.verdicts}"
            )
            assert group_verdicts == seq_verdicts, (
                f"step {step} batch {gi}: group vs sequential mismatch"
            )
            gf = np.asarray(got.intra_first_range[gi])[: len(txns)].tolist()
            sf = np.asarray(seq_got.intra_first_range)[: len(txns)].tolist()
            assert gf == sf, f"step {step} batch {gi}: first-range mismatch"
