"""Resolver-role semantics tests.

Each test mirrors a CODE_PROBE / behavior of resolveBatch
(fdbserver/Resolver.actor.cpp:219-540): version chaining, duplicate
replay, ack-based trimming, state-transaction forwarding across proxies,
too-old classification through the role (not just the kernel).
"""

import pytest

from foundationdb_tpu.config import TEST_CONFIG
from foundationdb_tpu.models.types import (
    CommitTransaction,
    ResolveTransactionBatchRequest,
    TransactionResult,
)
from foundationdb_tpu.resolver import Resolver
from foundationdb_tpu.runtime.flow import Scheduler, all_of


def mkreq(prev, version, txns, *, proxy="p0", last_received=0, state_idx=()):
    return ResolveTransactionBatchRequest(
        prev_version=prev,
        version=version,
        last_received_version=last_received,
        transactions=txns,
        txn_state_transactions=list(state_idx),
        proxy_id=proxy,
    )


def txn(reads=(), writes=(), snapshot=0, report=False):
    return CommitTransaction(
        read_conflict_ranges=list(reads),
        write_conflict_ranges=list(writes),
        read_snapshot=snapshot,
        report_conflicting_keys=report,
    )


def bootstrap(res, sched):
    """The master's recovery request (prev_version < 0) — creates the
    master entry in proxy_info and sets the initial version, as in the
    reference recovery flow (masterserver -> resolver first batch)."""
    t = sched.spawn(
        res.resolve(
            ResolveTransactionBatchRequest(
                prev_version=-1, version=0, last_received_version=-1,
                transactions=[],
            )
        )
    )
    sched.run_until(t.done)


@pytest.fixture
def world():
    sched = Scheduler(sim=True)
    res = Resolver(sched, TEST_CONFIG)
    bootstrap(res, sched)
    return sched, res


def resolve(sched, res, req):
    t = sched.spawn(res.resolve(req))
    return sched.run_until(t.done)


def test_simple_commit_then_conflict(world):
    sched, res = world
    r1 = resolve(sched, res, mkreq(0, 10, [txn(writes=[(b"a", b"b")], snapshot=5)]))
    assert r1.committed == [TransactionResult.COMMITTED]
    # reads (a,b) at snapshot 5 < write version 10 -> conflict
    r2 = resolve(
        sched, res, mkreq(10, 20, [txn(reads=[(b"a", b"b")], snapshot=5)])
    )
    assert r2.committed == [TransactionResult.CONFLICT]
    # fresh snapshot reads fine
    r3 = resolve(
        sched, res, mkreq(20, 30, [txn(reads=[(b"a", b"b")], snapshot=20)])
    )
    assert r3.committed == [TransactionResult.COMMITTED]


def test_version_chain_waits_for_prev(world):
    sched, res = world
    order = []

    async def send(req, tag):
        out = await res.resolve(req)
        order.append(tag)
        return out

    # Send the later batch first; it must wait for the 0->10 batch.
    t2 = sched.spawn(send(mkreq(10, 20, [txn(writes=[(b"c", b"d")])]), "second"))
    t1 = sched.spawn(send(mkreq(0, 10, [txn(writes=[(b"a", b"b")])]), "first"))
    sched.run_until(all_of([t1.done, t2.done]))
    assert order == ["first", "second"]
    assert res.version.get() == 20


def test_duplicate_request_replays_cached_reply(world):
    sched, res = world
    req = mkreq(0, 10, [txn(writes=[(b"a", b"b")], snapshot=5)])
    r1 = resolve(sched, res, req)
    # Same request again (e.g. proxy retry): must replay, not recompute.
    r2 = resolve(sched, res, req)
    assert r2 is r1
    # bootstrap + the real batch computed once; the duplicate did not
    assert res.counters.get("resolveBatchStart") == 2
    assert res.counters.get("resolveBatchIn") == 3


def test_acked_replies_are_trimmed_then_unknown_dup_gets_never(world):
    sched, res = world
    resolve(sched, res, mkreq(0, 10, [txn(writes=[(b"a", b"b")])]))
    # next request acks version 10
    resolve(
        sched, res, mkreq(10, 20, [txn(writes=[(b"c", b"d")])], last_received=10)
    )
    info = res.proxy_info["p0"]
    assert 10 not in info.outstanding_batches
    assert 20 in info.outstanding_batches
    # duplicate of the acked version: reference replies Never() (-> None)
    r = resolve(sched, res, mkreq(0, 10, [txn(writes=[(b"a", b"b")])]))
    assert r is None


def test_too_old_through_role(world):
    sched, res = world
    w = TEST_CONFIG.window_versions
    resolve(sched, res, mkreq(0, w + 100, [txn(writes=[(b"a", b"b")])]))
    r = resolve(
        sched,
        res,
        mkreq(w + 100, w + 200, [txn(reads=[(b"x", b"y")], snapshot=50)]),
    )
    assert r.committed == [TransactionResult.TOO_OLD]
    assert res.counters.get("transactionsTooOld") == 1


def test_state_transactions_forwarded_to_other_proxy():
    sched = Scheduler(sim=True)
    res = Resolver(sched, TEST_CONFIG, commit_proxy_count=2)
    bootstrap(res, sched)
    mut = ("set", b"\xffkey", b"value")
    state_txn = CommitTransaction(
        write_conflict_ranges=[(b"\xffk", b"\xffl")], mutations=[mut]
    )
    # proxy A commits a state transaction at version 10
    resolve(sched, res, mkreq(0, 10, [state_txn], proxy="A", state_idx=[0]))
    # proxy B's first batch at version 20 must receive A's state txn,
    # grouped per version (nested-list wire shape). B's first_unseen is 0,
    # so it also sees the bootstrap version's (empty) group — the reference
    # inserts a map entry for every version (getStateTransactionsRef).
    rb = resolve(sched, res, mkreq(10, 20, [txn(writes=[(b"m", b"n")])], proxy="B"))
    assert len(rb.state_mutations) == 2  # versions 0 (empty) and 10
    v0, v10 = rb.state_mutations
    assert v0 == []
    assert len(v10) == 1
    assert v10[0].committed
    assert v10[0].mutations == [mut]
    # proxy A's own next batch must NOT get its own state txn back — only
    # B's v20 group (empty) lands in the reply
    ra = resolve(
        sched, res, mkreq(20, 30, [txn(writes=[(b"o", b"p")])], proxy="A",
                          last_received=10)
    )
    assert ra.state_mutations == [[]]


def test_state_trimmed_once_all_proxies_caught_up():
    sched = Scheduler(sim=True)
    res = Resolver(sched, TEST_CONFIG, commit_proxy_count=2)
    bootstrap(res, sched)
    state_txn = CommitTransaction(
        write_conflict_ranges=[(b"\xffk", b"\xffl")],
        mutations=[("set", b"\xffkey", b"value")],
    )
    resolve(sched, res, mkreq(0, 10, [state_txn], proxy="A", state_idx=[0]))
    assert res.recent_state.size == 1
    # Once B has also advanced past v10, every proxy has seen it -> trimmed.
    resolve(sched, res, mkreq(10, 20, [txn(writes=[(b"m", b"n")])], proxy="B"))
    assert res.recent_state.size == 0
    assert res.total_state_bytes == 0


def test_conflicting_key_range_report_via_role(world):
    sched, res = world
    resolve(sched, res, mkreq(0, 10, [txn(writes=[(b"a", b"c")])]))
    r = resolve(
        sched,
        res,
        mkreq(
            10,
            20,
            [
                txn(
                    reads=[(b"x", b"y"), (b"a", b"b")],
                    snapshot=5,
                    report=True,
                )
            ],
        ),
    )
    assert r.committed == [TransactionResult.CONFLICT]
    assert r.conflicting_key_range_map == {0: [1]}


def test_counters(world):
    sched, res = world
    resolve(
        sched,
        res,
        mkreq(
            0,
            10,
            [
                txn(writes=[(b"a", b"b")], snapshot=0),
                txn(reads=[(b"q", b"r")], writes=[(b"q", b"r")], snapshot=0),
            ],
        ),
    )
    c = res.counters
    assert c.get("resolvedTransactions") == 2
    assert c.get("resolvedReadConflictRanges") == 1
    assert c.get("resolvedWriteConflictRanges") == 2
    assert c.get("transactionsAccepted") == 2
    # bootstrap batch + this batch
    assert res.compute_time.count == 2
    assert res.resolver_latency.count == 2


def test_key_sample_stays_bounded():
    """Multi-resolver key sampling must not grow without bound on long
    runs (VERDICT r1 weakness 7): decay keeps it O(KEY_SAMPLE_LIMIT)."""
    from foundationdb_tpu import resolver as R
    from foundationdb_tpu.config import TEST_CONFIG
    from foundationdb_tpu.models.types import (
        CommitTransaction,
        ResolveTransactionBatchRequest,
    )

    sched = Scheduler(sim=True)
    res = R.Resolver(
        sched, TEST_CONFIG, resolver_count=2, backend="cpu"
    )

    async def go():
        prev = -1
        for i in range(80):
            version = (i + 1) * 10
            txns = [
                CommitTransaction(
                    write_conflict_ranges=[
                        (b"k%06d" % (i * 200 + j), b"k%06d\x00" % (i * 200 + j))
                    ]
                )
                for j in range(200)
            ]
            await res.resolve(
                ResolveTransactionBatchRequest(
                    prev_version=prev, version=version,
                    last_received_version=prev, transactions=txns,
                )
            )
            prev = version
        return len(res._key_sample)

    t = sched.spawn(go(), name="drive")
    sched.run_until(t.done)
    # 80 batches x 200 unique keys = 16K distinct keys seen; the sample
    # must stay near its cap, not track them all
    assert t.done.get() <= R.KEY_SAMPLE_LIMIT + 200
    # split-point queries still work on the decayed sample
    sp = res.split_point(b"k", b"l", 0.5)
    assert b"k" <= sp <= b"l"
