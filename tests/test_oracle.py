"""Sanity tests for the Python semantic oracle itself (hand-built scenarios)."""

from foundationdb_tpu.testing.oracle import (
    COMMITTED,
    CONFLICT,
    TOO_OLD,
    ConflictOracle,
    OracleTxn,
    VersionMap,
)


def T(reads=(), writes=(), snap=0, report=False):
    return OracleTxn(list(reads), list(writes), snap, report)


def test_versionmap_write_query():
    m = VersionMap()
    m.write(b"b", b"d", 10)
    assert m.max_over(b"a", b"b") == 0      # ends before the write
    assert m.max_over(b"a", b"b\x00") == 10  # touches [b, d)
    assert m.max_over(b"c", b"z") == 10
    assert m.max_over(b"d", b"z") == 0      # starts at exclusive end
    m.write(b"c", b"e", 20)
    assert m.max_over(b"b", b"c") == 10
    assert m.max_over(b"c", b"d") == 20
    assert m.max_over(b"d", b"e") == 20
    m.write(b"a", b"z", 30)                  # full overwrite
    assert m.max_over(b"b", b"d") == 30


def test_versionmap_exact_end_boundary():
    m = VersionMap()
    m.write(b"a", b"c", 5)
    m.write(b"c", b"e", 7)   # adjacent: boundary at c exists
    m.write(b"a", b"c", 9)   # rewrite first — must not duplicate boundary c
    assert m.max_over(b"b", b"c") == 9
    assert m.max_over(b"c", b"d") == 7
    assert m.boundaries == sorted(set(m.boundaries))


def test_blind_write_always_commits():
    o = ConflictOracle(window=100)
    r = o.resolve([T(writes=[(b"a", b"b")], snap=-10**9)], version=1000)
    assert r.verdicts == [COMMITTED]  # no reads -> never tooOld, never conflicts


def test_read_write_conflict_across_batches():
    o = ConflictOracle(window=10**6)
    o.resolve([T(writes=[(b"k", b"k\x00")])], version=100)
    r = o.resolve([T(reads=[(b"k", b"k\x00")], snap=50)], version=200)
    assert r.verdicts == [CONFLICT]
    r2 = o.resolve([T(reads=[(b"k", b"k\x00")], snap=150)], version=300)
    assert r2.verdicts == [COMMITTED]  # snapshot after the write


def test_intra_batch_order_dependence():
    o = ConflictOracle(window=10**6)
    # t0 writes k; t1 reads k -> t1 conflicts with the *earlier* t0
    r = o.resolve(
        [
            T(writes=[(b"k", b"k\x00")], snap=10),
            T(reads=[(b"k", b"k\x00")], writes=[(b"m", b"n")], snap=10),
            T(reads=[(b"m", b"n")], snap=10),  # t1 aborted, so its write is absent
        ],
        version=100,
    )
    assert r.verdicts == [COMMITTED, CONFLICT, COMMITTED]


def test_too_old():
    o = ConflictOracle(window=100)
    r = o.resolve(
        [
            T(reads=[(b"a", b"b")], snap=10),    # 10 < 1000-100 -> tooOld
            T(reads=[(b"a", b"b")], snap=950),
        ],
        version=1000,
    )
    assert r.verdicts == [TOO_OLD, COMMITTED]


def test_report_conflicting_keys_first_hit_only_intra():
    o = ConflictOracle(window=10**6)
    r = o.resolve(
        [
            T(writes=[(b"a", b"b"), (b"c", b"d")], snap=1),
            # both ranges would hit, but the reference records only the first
            T(reads=[(b"a", b"b"), (b"c", b"d")], snap=1, report=True),
        ],
        version=10,
    )
    assert r.verdicts == [COMMITTED, CONFLICT]
    assert r.conflicting_ranges == {1: [0]}


def test_report_conflicting_keys_all_hits_history():
    o = ConflictOracle(window=10**6)
    o.resolve([T(writes=[(b"a", b"b"), (b"c", b"d")])], version=10)
    r = o.resolve(
        [T(reads=[(b"c", b"d"), (b"a", b"b")], snap=5, report=True)], version=20
    )
    assert r.verdicts == [CONFLICT]
    # history phase records every hit, ordered by begin key: (a,b)=idx1, (c,d)=idx0
    assert r.conflicting_ranges == {0: [1, 0]}


def test_gc_drops_dead_segments():
    o = ConflictOracle(window=10)
    for v in range(1, 40):
        o.resolve([T(writes=[(bytes([v % 7]), bytes([v % 7]) + b"\x00")])], version=v * 10)
    assert len(o.history.boundaries) < 20  # bounded by live window, not 39 writes
