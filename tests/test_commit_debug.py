"""commit_debug reconstruction + the span-chain soak gate.

Unit layer: synthetic TraceLog records through utils/commit_debug —
timelines, waterfall, every chain-integrity check in BOTH directions
(clean input passes; each corruption class is caught). Integration
layer: run_seed(trace=True) — a real soak seed must reconstruct a
complete GRV -> commit -> resolve -> tlog -> storage timeline for every
committed transaction, bit-reproducibly, and the gate's divergence
self-test (_corrupt_trace) must fail the seed.
"""

import pytest

from foundationdb_tpu.utils import commit_debug as cd

# -- synthetic-chain helpers ------------------------------------------------


def micro(loc, ident, t, name="CommitDebug"):
    return {"Type": name, "ID": ident, "Location": loc, "Time": t}


def full_chain(txn="t1", batch="b1", version=100, messages=2):
    """One committed transaction's complete event set."""
    return [
        micro(cd.GRV_BEFORE, txn, 0.00, "TransactionDebug"),
        micro(cd.GRV_REPLY, txn, 0.008, "TransactionDebug"),
        micro(cd.GRV_AFTER, txn, 0.01, "TransactionDebug"),
        micro(cd.COMMIT_BEFORE, txn, 0.02),
        micro(f"attach:{batch}", txn, 0.03, "CommitAttachID"),
        micro(cd.BATCH_BEFORE, batch, 0.03),
        micro(cd.BATCH_GETTING_VERSION, batch, 0.031),
        micro(cd.BATCH_GOT_VERSION, batch, 0.032),
        micro(cd.RESOLVER_BEFORE, batch, 0.033),
        micro(cd.RESOLVER_AFTER_QUEUE, batch, 0.0335),
        micro(cd.RESOLVER_AFTER_ORDERER, batch, 0.034),
        micro(cd.RESOLVER_AFTER, batch, 0.035),
        micro(cd.BATCH_AFTER_RESOLUTION, batch, 0.036),
        {"Type": "CommitDebugVersion", "ID": batch, "Version": version,
         "Messages": messages, "Time": 0.036},
        micro(cd.TLOG_BEFORE_WAIT, batch, 0.0365),
        micro(cd.TLOG_AFTER_COMMIT, batch, 0.037),
        micro(cd.BATCH_AFTER_LOG_PUSH, batch, 0.038),
        micro(cd.STORAGE_APPLIED, cd.version_id(version), 0.04),
        micro(cd.COMMIT_AFTER, txn, 0.05),
    ]


def violations_of(records):
    return cd.check_chains(cd.TraceIndex(records))


# -- reconstruction ---------------------------------------------------------


def test_full_chain_reconstructs_clean():
    idx = cd.TraceIndex(full_chain())
    assert idx.committed_ids() == ["t1"]
    (tl,) = idx.timelines()
    assert tl.batch_id == "b1" and tl.version == 100
    # every stage present, time-ascending
    times = [t for t, _loc in tl.events]
    assert times == sorted(times)
    stages = tl.stage_durations()
    assert set(stages) >= {
        "grv", "batching", "get_version", "resolution", "logging",
        "reply", "total",
    }
    assert stages["total"] == pytest.approx(0.03)
    assert stages["grv"] == pytest.approx(0.01)
    assert violations_of(full_chain()) == []


def test_two_txns_share_a_batch():
    recs = full_chain("t1", "b1") + [
        micro(cd.COMMIT_BEFORE, "t2", 0.021),
        micro("attach:b1", "t2", 0.03, "CommitAttachID"),
        micro(cd.COMMIT_AFTER, "t2", 0.051),
    ]
    idx = cd.TraceIndex(recs)
    assert idx.committed_ids() == ["t1", "t2"]
    assert violations_of(recs) == []
    wf = cd.waterfall(idx.timelines())
    assert wf["total"]["count"] == 2


def test_waterfall_and_render():
    idx = cd.TraceIndex(full_chain())
    wf = cd.waterfall(idx.timelines())
    assert wf["resolution"]["count"] == 1
    assert wf["logging"]["mean"] > 0
    out = cd.render_timeline(idx.timelines()[0])
    assert "t1" in out and cd.RESOLVER_BEFORE in out


def test_uncommitted_txn_not_gated():
    """No COMMIT_AFTER -> not a committed chain, nothing required."""
    recs = [
        micro(cd.COMMIT_BEFORE, "t9", 0.0),
        micro("attach:b9", "t9", 0.001, "CommitAttachID"),
    ]
    idx = cd.TraceIndex(recs)
    assert idx.committed_ids() == []
    assert violations_of(recs) == []


# -- each corruption class is caught ---------------------------------------


@pytest.mark.parametrize("drop", [
    cd.BATCH_BEFORE,
    cd.BATCH_GOT_VERSION,
    cd.BATCH_AFTER_RESOLUTION,
    cd.BATCH_AFTER_LOG_PUSH,
    cd.RESOLVER_BEFORE,
    cd.RESOLVER_AFTER,
    cd.TLOG_AFTER_COMMIT,
])
def test_missing_pipeline_stage_is_a_violation(drop):
    recs = [r for r in full_chain() if r.get("Location") != drop]
    vs = violations_of(recs)
    assert vs and "missing pipeline stage" in vs[0]
    assert drop in vs[0]


def test_missing_storage_apply_is_a_violation_iff_messages():
    no_storage = [
        r for r in full_chain()
        if r.get("Location") != cd.STORAGE_APPLIED
    ]
    vs = violations_of(no_storage)
    assert vs and "storage message tag" in vs[0]
    # a batch with ZERO storage messages (conflict-range-only commits)
    # legitimately has no storage apply
    empty = [
        r for r in full_chain(messages=0)
        if r.get("Location") != cd.STORAGE_APPLIED
    ]
    assert violations_of(empty) == []


def test_orphan_commit_and_half_grv_are_violations():
    # committed but never attached to any batch
    recs = [
        micro(cd.COMMIT_BEFORE, "tx", 0.0),
        micro(cd.COMMIT_AFTER, "tx", 0.01),
    ]
    vs = violations_of(recs)
    assert vs and "never attached" in vs[0]
    # GRV issued but never answered
    recs2 = full_chain()
    recs2 = [r for r in recs2 if r.get("Location") != cd.GRV_AFTER]
    assert any("GRV issued" in v for v in violations_of(recs2))
    # missing CommitDebugVersion join record
    recs3 = [
        r for r in full_chain() if r["Type"] != "CommitDebugVersion"
    ]
    assert any("CommitDebugVersion" in v for v in violations_of(recs3))


def test_span_checks_orphan_and_time_inversion():
    spans = [
        {"location": "a.commitBatch", "span_id": 1, "parent_id": 0,
         "begin": 0.0, "end": 1.0},
        {"location": "r.resolveBatch", "span_id": 2, "parent_id": 1,
         "begin": 0.1, "end": 0.9},
    ]
    assert cd.check_spans(spans) == []
    orphan = spans + [
        {"location": "x", "span_id": 3, "parent_id": 99,
         "begin": 0.0, "end": 0.1},
    ]
    assert any("orphan parent 99" in v for v in cd.check_spans(orphan))
    inverted = spans + [
        {"location": "y", "span_id": 4, "parent_id": 0,
         "begin": 0.5, "end": 0.2},
    ]
    assert any("before begin" in v for v in cd.check_spans(inverted))
    # the TraceLog "Span" sink shape (CamelCase keys) parses identically
    camel = [
        {"Location": "a.commitBatch", "SpanID": 1, "ParentID": 0,
         "Begin": 0.0, "End": 1.0},
    ]
    assert cd.check_spans(camel) == []


def test_gate_probe_fires_on_violation():
    from foundationdb_tpu.utils import probes

    before = probes.snapshot().get("trace.span_chain_gate_tripped", 0)
    violations_of(full_chain())  # clean: no hit
    assert probes.snapshot().get(
        "trace.span_chain_gate_tripped", 0) == before
    violations_of([
        micro(cd.COMMIT_BEFORE, "tx", 0.0),
        micro(cd.COMMIT_AFTER, "tx", 0.01),
    ])
    assert probes.snapshot()["trace.span_chain_gate_tripped"] == before + 1


def test_load_jsonl_roundtrip(tmp_path):
    import json

    p = tmp_path / "t.jsonl"
    p.write_text(
        "\n".join(json.dumps(r) for r in full_chain()) + "\n"
    )
    assert violations_of(cd.load_jsonl([str(p)])) == []


# -- wire codec: the per-txn telemetry fields travel ------------------------


def test_commit_transaction_codec_carries_debug_id_and_span():
    from foundationdb_tpu.models.types import CommitTransaction
    from foundationdb_tpu.wire import codec

    t = CommitTransaction(
        read_conflict_ranges=[(b"a", b"b")],
        debug_id="origin-1-7",
        span=(123456, 789),
    )
    got = codec.decode(codec.encode(t))
    assert got.debug_id == "origin-1-7"
    assert got.span == (123456, 789)
    bare = codec.decode(codec.encode(CommitTransaction()))
    assert bare.debug_id is None and bare.span is None


# -- the traced soak seed (integration) -------------------------------------


def test_traced_seed_reconstructs_every_commit():
    """The acceptance shape: a traced soak seed yields a complete
    pipeline timeline for every committed transaction, and the trace
    digest is bit-identical across a re-run."""
    from foundationdb_tpu.testing.soak import run_seed
    from foundationdb_tpu.utils import trace as _tr

    captured = {}
    orig = _tr.install

    def spy(log, batch):
        captured.setdefault("log", log)
        return orig(log, batch)

    _tr.install = spy
    try:
        sig = run_seed(1, spec="smoke", trace=True)
    finally:
        _tr.install = orig
    digest, n_chains = sig[-2], sig[-1]
    assert n_chains >= 1
    idx = cd.TraceIndex(captured["log"].events)
    assert cd.check_chains(idx) == []
    # every committed txn's timeline covers resolve AND logging
    for tl in idx.timelines():
        assert cd.RESOLVER_BEFORE in tl.locations()
        assert cd.BATCH_AFTER_LOG_PUSH in tl.locations()
    # bit-reproducible: same seed, same digest
    sig2 = run_seed(1, spec="smoke", trace=True)
    assert sig2[-2] == digest


def test_corrupt_trace_fails_the_seed():
    from foundationdb_tpu.testing.soak import run_seed

    with pytest.raises(AssertionError, match="span-chain violation"):
        run_seed(1, spec="smoke", trace=True, _corrupt_trace=True)


def test_untraced_seed_signature_shape_unchanged():
    """trace=False keeps the 8-tuple signature (no digest appended):
    existing determinism tooling reads fixed positions."""
    from foundationdb_tpu.testing.soak import run_seed

    sig = run_seed(1, spec="smoke")
    assert len(sig) == 8
