"""QueueModel load balancing: latency-estimate replica choice + backup
requests (VERDICT r4 task 8; fdbrpc/QueueModel.cpp, LoadBalance.actor.h).

The graded behavior: a slow-but-ALIVE replica — invisible to the failure
monitor — stops receiving the bulk of reads, purely from its measured
latency; a recovered replica is re-probed after its estimate goes stale;
a stalled primary gets a duplicated backup request whose reply wins.
"""

from __future__ import annotations

from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.cluster.queue_model import (
    QueueModel,
    load_balanced_call,
)
from foundationdb_tpu.runtime.flow import Scheduler


def run(sched, coro):
    return sched.run_until(sched.spawn(coro).done)


def test_ewma_and_staleness():
    sched = Scheduler(sim=True)
    m = QueueModel(sched)

    async def body():
        t0 = m.start("a")
        await sched.delay(0.1)
        m.finish("a", t0)
        assert m.expected("a") > m.expected("b")  # b is cold/prior
        assert m.order(["a", "b"]) == ["b", "a"]
        # outstanding requests inflate the estimate before replies return
        t1 = m.start("b")
        t2 = m.start("b")
        inflated = m.expected("b")
        m.finish("b", t1)
        m.finish("b", t2)
        assert inflated > m.expected("b")
        # after STALE_AFTER with no data, a slow replica reads as cold
        # again (re-probe a recovered process)
        await sched.delay(QueueModel.STALE_AFTER + 0.1)
        assert m.expected("a") <= QueueModel.PRIOR
        return True

    assert run(sched, body())


def test_slow_replica_stops_receiving_bulk():
    sched, cluster, db = open_cluster(
        ClusterConfig(n_storage=2, replication_factor=2)
    )
    calls = [0, 0]
    real = list(cluster.client_storages)
    for s in (0, 1):
        class Counting:
            def __init__(self, idx, inner):
                self.idx, self.inner = idx, inner

            def get_value(self, key, rv):
                calls[self.idx] += 1
                return self.inner.get_value(key, rv)

            def __getattr__(self, name):
                return getattr(self.inner, name)

        cluster.client_storages[s] = Counting(s, real[s])

    async def body():
        txn = db.create_transaction()
        txn.set(b"k", b"v")
        await txn.commit()
        cluster.storage_servers[0].read_slowdown = 0.05  # slow but ALIVE
        for _ in range(40):
            t = db.create_transaction()
            assert await t.get(b"k") == b"v"
        slow_share = calls[0] / sum(calls)
        # the slow replica got probed, then shunned: well under half
        assert slow_share < 0.25, (calls, slow_share)
        assert not cluster.failure_monitor.is_failed("storage0")
        # recovery: slowdown removed + estimates gone stale -> the
        # replica serves reads again
        cluster.storage_servers[0].read_slowdown = 0.0
        await sched.delay(QueueModel.STALE_AFTER + 0.1)
        before = calls[0]
        for _ in range(20):
            t = db.create_transaction()
            assert await t.get(b"k") == b"v"
        assert calls[0] > before, calls
        return True

    assert run(sched, body())
    cluster.stop()


def test_backup_request_wins_over_stalled_primary():
    sched = Scheduler(sim=True)
    m = QueueModel(sched)

    async def issue(ep):
        if ep == "stalled":
            await sched.delay(5.0)
            return "late"
        await sched.delay(0.001)
        return "fast"

    async def body():
        # prime 'stalled' as the apparent best so it is chosen primary
        t0 = m.start("stalled")
        m.finish("stalled", t0)  # ~0 observed latency
        t0 = m.start("other")
        await sched.delay(0.05)
        m.finish("other", t0)
        t_start = sched.now()
        r = await load_balanced_call(
            sched, m, ["stalled", "other"], issue
        )
        took = sched.now() - t_start
        assert r == "fast"
        assert took < 1.0, took  # did NOT wait out the stalled primary
        return True

    assert run(sched, body())


def test_error_from_primary_falls_to_backup():
    sched = Scheduler(sim=True)
    m = QueueModel(sched)

    async def issue(ep):
        if ep == "bad":
            await sched.delay(0.01)
            raise RuntimeError("replica exploded")
        await sched.delay(0.05)
        return "ok"

    async def body():
        # 'bad' looks fastest -> primary; its failure after the backup
        # was armed must fall through to the backup's reply
        t0 = m.start("bad")
        m.finish("bad", t0)
        t1 = m.start("good")
        await sched.delay(0.2)
        m.finish("good", t1)
        r = await load_balanced_call(sched, m, ["bad", "good"], issue)
        assert r == "ok"
        return True

    assert run(sched, body())
