"""Coordination quorum: generation protocol, leader election, recovery.

VERDICT r1 task 7. CoordinatedState's two-phase generation discipline
(Coordination.actor.cpp:864 / CoordinatedState.actor.cpp), lease-based
leader election (LeaderElection.actor.cpp), and the acceptance case:
cluster recovery proceeds with a minority of coordinators dead, is
blocked (safely) without a quorum, and two would-be controllers can
never both commit an epoch.
"""

import pytest

from foundationdb_tpu.cluster.coordination import (
    CoordinatedState,
    Coordinator,
    Generation,
    LeaderElection,
    QuorumUnreachable,
    StaleGeneration,
)
from foundationdb_tpu.runtime.flow import Scheduler


def drive(sched, coro):
    t = sched.spawn(coro, name="test")
    sched.run_until(t.done)
    return t.done.get()


@pytest.fixture
def sched():
    return Scheduler()


@pytest.fixture
def coords():
    return [Coordinator(f"c{i}") for i in range(3)]


def test_read_write_roundtrip(sched, coords):
    cs = CoordinatedState(sched, coords, "a")

    async def go():
        assert await cs.read() is None
        await cs.write({"epoch": 1})
        return await cs.read()

    assert drive(sched, go()) == {"epoch": 1}


def test_minority_death_tolerated(sched, coords):
    cs = CoordinatedState(sched, coords, "a")

    async def go():
        await cs.write("v1")
        coords[0].kill()
        assert await cs.read() == "v1"
        await cs.write("v2")
        # the dead coordinator missed v2; a majority still agrees
        coords[0].revive()
        coords[1].kill()  # different minority dead now
        return await cs.read()

    # c0 (revived, stale) + c2 (has v2): majority read must return v2,
    # because the newest write_gen wins
    assert drive(sched, go()) == "v2"


def test_majority_death_blocks(sched, coords):
    cs = CoordinatedState(sched, coords, "a")

    async def go():
        await cs.write("v1")
        coords[0].kill()
        coords[1].kill()
        with pytest.raises(QuorumUnreachable):
            await cs.read()
        with pytest.raises(QuorumUnreachable):
            await cs.write("v2")
        return True

    assert drive(sched, go())


def test_racing_writer_detected(sched, coords):
    """B commits between A's read and write: A's write must fail."""
    a = CoordinatedState(sched, coords, "a")
    b = CoordinatedState(sched, coords, "b")

    async def go():
        await a.read()
        await b.read()
        await b.write("from-b")
        with pytest.raises(StaleGeneration):
            await a.write("from-a")
        # after re-reading, A sees B's value and may write over it
        assert await a.read() == "from-b"
        await a.write("from-a-2")
        return await b.read()

    assert drive(sched, go()) == "from-a-2"


def test_generation_ordering():
    assert Generation(1, "a") < Generation(1, "b") < Generation(2, "a")


def test_election_single_winner(sched, coords):
    ea = LeaderElection(sched, coords, "A", lease=10.0)
    eb = LeaderElection(sched, coords, "B", lease=10.0)

    async def go():
        la = await ea.try_become_leader()
        lb = await eb.try_become_leader()
        return la, lb

    la, lb = drive(sched, go())
    winners = [x for x in (la, lb) if x is not None]
    assert len(winners) == 1 and winners[0].leader == "A"


def test_election_takeover_after_expiry(sched, coords):
    ea = LeaderElection(sched, coords, "A", lease=0.5)
    eb = LeaderElection(sched, coords, "B", lease=0.5)

    async def go():
        la = await ea.try_become_leader()
        assert la is not None and la.epoch == 1
        # A dies silently; B must wait out the lease
        assert await eb.try_become_leader() is None
        await sched.delay(1.0)
        lb = await eb.try_become_leader()
        assert lb is not None and lb.leader == "B" and lb.epoch == 2
        # A's stale lease can no longer renew or bump
        assert await ea.renew(la) is None
        assert await ea.bump_epoch(la) is None
        return True

    assert drive(sched, go())


def test_epoch_bump_requires_leadership(sched, coords):
    ea = LeaderElection(sched, coords, "A", lease=10.0)

    async def go():
        la = await ea.try_become_leader()
        l2 = await ea.bump_epoch(la)
        assert l2.epoch == la.epoch + 1
        # bump with the superseded lease handle fails
        assert await ea.bump_epoch(la) is None
        return True

    assert drive(sched, go())


# ---------------------------------------------------------------------------
# Acceptance: recovery through the quorum in the simulated cluster.


def _mk_cluster(**kw):
    from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster

    return open_cluster(ClusterConfig(n_commit_proxies=1, n_storage=2, **kw))


def test_recovery_with_dead_minority():
    sched, cluster, db = _mk_cluster()
    cluster.kill_coordinator(0)  # minority down

    async def go():
        t = db.create_transaction()
        t.set(b"k1", b"v1")
        await t.commit()
        epoch_before = cluster.controller.epoch
        # kill the proxy: CC must detect and recover THROUGH the quorum
        cluster.commit_proxies[0].failed = RuntimeError("test-kill")
        for _ in range(400):
            await sched.delay(0.05)
            if cluster.controller.epoch > epoch_before and not \
                    cluster.controller._recovering:
                break
        assert cluster.controller.epoch > epoch_before
        # cluster serves traffic in the new epoch
        t = db.create_transaction()
        t.set(b"k2", b"v2")
        await t.commit()
        t = db.create_transaction()
        assert await t.get(b"k2") == b"v2"
        return True

    t = sched.spawn(go(), name="drive")
    sched.run_until(t.done)
    assert t.done.get()
    cluster.stop()


def test_recovery_blocked_without_quorum():
    sched, cluster, db = _mk_cluster()
    cluster.kill_coordinator(0)
    cluster.kill_coordinator(1)  # majority down: epoch can never commit

    async def go():
        epoch_before = cluster.controller.epoch
        cluster.commit_proxies[0].failed = RuntimeError("test-kill")
        await sched.delay(10.0)
        # no recovery happened (and no split brain): epoch unchanged
        assert cluster.controller.epoch == epoch_before
        # reviving one coordinator restores the majority -> recovery runs
        cluster.revive_coordinator(0)
        for _ in range(600):
            await sched.delay(0.05)
            if cluster.controller.epoch > epoch_before and not \
                    cluster.controller._recovering:
                break
        assert cluster.controller.epoch > epoch_before
        t = db.create_transaction()
        t.set(b"back", b"alive")
        await t.commit()
        return True

    t = sched.spawn(go(), name="drive")
    sched.run_until(t.done)
    assert t.done.get()
    cluster.stop()
