"""Encryption-at-rest through the storage role: ciphertext on disk,
plaintext through the API, keys recovered from the KMS after kill-9.

The at-rest guarantee the reference gets from BlobCipher + encrypted
storage engines (fdbclient/BlobCipher.cpp, Redwood's encrypted pager):
a disk image leak must not expose values. The strongest assertion here
is the raw-file scan — the plaintext sentinel bytes must appear in NO
file the role wrote.
"""

import asyncio
import os

import pytest

pytest.importorskip("cryptography")

from foundationdb_tpu.cluster import multiprocess as mp
from foundationdb_tpu.cluster.encrypt_key_proxy import EncryptKeyProxy
from foundationdb_tpu.cluster.kms import SimKmsConnector
from foundationdb_tpu.crypto.at_rest import StorageEncryption
from foundationdb_tpu.wire.codec import Mutation

native = pytest.importorskip("foundationdb_tpu.native")

SENTINEL = b"TOP-SECRET-PLAINTEXT-VALUE"


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _get(role, key, version):
    return run(role.get(mp.StorageGet(key=key, version=version))).value


def _enc():
    return StorageEncryption(
        EncryptKeyProxy(SimKmsConnector(), refresh_interval=600)
    )


def _scan_dir_for(data_dir: str, needle: bytes) -> list[str]:
    hits = []
    for root, _dirs, files in os.walk(data_dir):
        for f in files:
            p = os.path.join(root, f)
            with open(p, "rb") as fh:
                if needle in fh.read():
                    hits.append(p)
    return hits


@pytest.mark.parametrize("engine", ["memory", "lsm"])
def test_no_plaintext_on_disk_and_kill9_recovery(tmp_path, engine):
    data_dir = str(tmp_path / "sdata")
    role = mp.StorageRole(data_dir, engine=engine, encryption=_enc())

    async def load(r, lo, hi):
        for i in range(lo, hi):
            await r.apply(mp.StorageApply(
                version=(i + 1) * 10,
                mutations=[Mutation(0, b"k%03d" % i, SENTINEL + b"%d" % i)],
            ))

    # enough applies to force a checkpoint/flush AND leave a WAL tail
    n = mp.StorageRole.CHECKPOINT_INTERVAL + 5
    run(load(role, 0, n))
    assert _get(role, b"k000", n * 10) == SENTINEL + b"0"

    # the at-rest guarantee: no file under data_dir carries the plaintext
    hits = _scan_dir_for(data_dir, SENTINEL)
    assert hits == [], f"plaintext leaked to disk: {hits}"

    # kill -9 equivalent: a FRESH role with a FRESH key cache must
    # recover via the KMS by-id path (the derived keys' salts live only
    # in record headers)
    role2 = mp.StorageRole(data_dir, engine=engine, encryption=_enc())
    assert role2.version == n * 10
    assert _get(role2, b"k000", n * 10) == SENTINEL + b"0"
    assert _get(role2, b"k%03d" % (n - 1), n * 10) == SENTINEL + b"%d" % (n - 1)


def test_mixed_mode_legacy_plaintext_readable(tmp_path):
    """Records written before encryption was enabled must stay readable
    after it turns on (the reference's rollout path: encryption_at_rest
    mode switches, existing data upgrades lazily)."""
    data_dir = str(tmp_path / "sdata")
    role = mp.StorageRole(data_dir, engine="lsm")

    async def one(r, version, key, val):
        await r.apply(mp.StorageApply(
            version=version, mutations=[Mutation(0, key, val)]
        ))

    run(one(role, 10, b"old", b"legacy-plain"))
    # restart WITH encryption: old plaintext record + new sealed record
    role2 = mp.StorageRole(data_dir, engine="lsm", encryption=_enc())
    run(one(role2, 20, b"new", b"sealed-value"))
    assert _get(role2, b"old", 20) == b"legacy-plain"
    assert _get(role2, b"new", 20) == b"sealed-value"


def test_snapshot_decrypts(tmp_path):
    data_dir = str(tmp_path / "sdata")
    role = mp.StorageRole(data_dir, engine="lsm", encryption=_enc())

    async def go():
        await role.apply(mp.StorageApply(
            version=10,
            mutations=[Mutation(0, b"a", SENTINEL), Mutation(0, b"b", b"v2")],
        ))
        return await role.snapshot(mp.StorageSnapshotReq(version=10))

    rep = run(go())
    assert dict(rep.kvs) == {b"a": SENTINEL, b"b": b"v2"}


def test_encrypted_cluster_end_to_end(tmp_path):
    """Full multiprocess pipeline with --encrypt storage: commits land,
    reads round-trip, and the storage data dir carries no plaintext."""
    import shutil

    from foundationdb_tpu.models.types import CommitTransaction

    socket_dir = str(tmp_path / "socks")
    data_dir = str(tmp_path / "storedata")
    os.makedirs(socket_dir, exist_ok=True)
    roles = []
    try:
        tlog = mp.spawn_role("tlog", socket_dir)
        storage = mp.spawn_role(
            "storage", socket_dir, data_dir=data_dir,
            storage_engine="lsm", encrypt=True,
        )
        resolver = mp.spawn_role("resolver", socket_dir, backend="native")
        roles = [tlog, storage, resolver]

        async def go():
            rconn = await mp.connect(resolver.address)
            tconn = await mp.connect(tlog.address)
            sconn = await mp.connect(storage.address)
            pipe = mp.ProxyPipeline([rconn], tconn, sconn)
            pipe.start()
            try:
                v = await pipe.commit(CommitTransaction(
                    read_conflict_ranges=[], write_conflict_ranges=[],
                    mutations=[(0, b"ek", SENTINEL)], read_snapshot=0,
                ))
                rep = await sconn.call(
                    mp.TOKEN_STORAGE_GET,
                    mp.StorageGet(key=b"ek", version=v),
                )
                assert rep.value == SENTINEL
            finally:
                await pipe.stop()
                for c in (rconn, tconn, sconn):
                    await c.close()

        run(go())
        hits = _scan_dir_for(data_dir, SENTINEL)
        assert hits == [], f"plaintext leaked to disk: {hits}"
    finally:
        for r in roles:
            r.stop()
        shutil.rmtree(socket_dir, ignore_errors=True)


def test_mode_flip_refused(tmp_path):
    """A store written encrypted must refuse to open unencrypted —
    serving sealed bytes as values would be silent corruption (the
    reference persists encryptionAtRestMode and rejects flips)."""
    data_dir = str(tmp_path / "sdata")
    role = mp.StorageRole(data_dir, engine="lsm", encryption=_enc())

    async def one():
        await role.apply(mp.StorageApply(
            version=10, mutations=[Mutation(0, b"k", SENTINEL)]
        ))

    run(one())
    with pytest.raises(RuntimeError, match="encryption"):
        mp.StorageRole(data_dir, engine="lsm")


def test_magic_collision_legacy_value_readable(tmp_path):
    """An UNENCRYPTED user value that happens to start with the header
    magic must stay readable in both modes (parse-based disambiguation
    in StorageEncryption.open; version byte 0xFF is not ours)."""
    from foundationdb_tpu.crypto.blob_cipher import ENCRYPT_HEADER_MAGIC

    weird = ENCRYPT_HEADER_MAGIC + b"\xff" + b"z" * 120
    data_dir = str(tmp_path / "sdata")
    role = mp.StorageRole(data_dir, engine="lsm")

    async def one(r, version, key, val):
        await r.apply(mp.StorageApply(
            version=version, mutations=[Mutation(0, key, val)]
        ))

    run(one(role, 10, b"weird", weird))
    assert _get(role, b"weird", 10) == weird
    # after enabling encryption the legacy record still reads back
    role2 = mp.StorageRole(data_dir, engine="lsm", encryption=_enc())
    assert _get(role2, b"weird", 10) == weird


def test_expired_key_not_resurrected():
    """expire_interval is enforced: a record whose key generation
    passed its expire deadline refuses to decrypt even though the KMS
    could re-derive it (key retirement, code review r5)."""
    import time as _time

    from foundationdb_tpu.crypto.blob_cipher import (
        SYSTEM_DOMAIN_ID,
        CipherKeyExpiredError,
    )
    from foundationdb_tpu.crypto import encrypt as _encrypt

    proxy = EncryptKeyProxy(
        SimKmsConnector(), refresh_interval=600, expire_interval=0.05
    )
    enc = StorageEncryption(proxy)
    key = proxy.get_latest_cipher(enc.domain_id)
    auth = proxy.get_latest_cipher(SYSTEM_DOMAIN_ID)
    blob = _encrypt(SENTINEL, key, auth)
    assert enc.open(blob) == SENTINEL
    _time.sleep(0.06)
    with pytest.raises(CipherKeyExpiredError):
        enc.open(blob)


def test_tlog_disk_sealed_and_recovers(tmp_path):
    """The tlog persists the same mutation bytes storage seals — its
    DiskQueue must be ciphertext too (second review pass), and a fresh
    role must recover the entries through the KMS."""
    data_dir = str(tmp_path / "tdata")
    role = mp.TLogRole(data_dir=data_dir, encryption=_enc())

    async def pushes(r, lo, hi):
        for i in range(lo, hi):
            await r.push(mp.TLogPush(
                version=(i + 1) * 10, prev_version=i * 10,
                mutations=[Mutation(0, b"tk%02d" % i, SENTINEL)],
            ))

    run(pushes(role, 0, 10))
    hits = _scan_dir_for(data_dir, SENTINEL)
    assert hits == [], f"plaintext leaked to tlog disk: {hits}"

    role2 = mp.TLogRole(data_dir=data_dir, encryption=_enc())
    assert role2.version == 100
    rep = run(role2.peek(mp.TLogPeek(after_version=95)))
    assert rep.mutations[0].param2 == SENTINEL

    # mode flip refused for the tlog too
    with pytest.raises(RuntimeError, match="encryption"):
        mp.TLogRole(data_dir=data_dir)
