"""Metacluster: tenant management across data clusters
(fdbclient/Metacluster*.cpp / MetaclusterManagement capability)."""

import pytest

from foundationdb_tpu.cluster import tenant as T
from foundationdb_tpu.cluster.database import ClusterConfig, open_cluster
from foundationdb_tpu.cluster.metacluster import (
    ClusterAlreadyRegistered,
    ClusterNotEmpty,
    Metacluster,
    MetaclusterCapacityExceeded,
)
from foundationdb_tpu.runtime.flow import Scheduler


@pytest.fixture
def world():
    sched = Scheduler(sim=True)
    cfg = ClusterConfig(n_commit_proxies=1, n_storage=2)
    _s, mgmt_cluster, mgmt_db = open_cluster(cfg, sched=sched)
    _s, d1_cluster, d1 = open_cluster(
        ClusterConfig(n_commit_proxies=1, n_storage=2), sched=sched
    )
    _s, d2_cluster, d2 = open_cluster(
        ClusterConfig(n_commit_proxies=1, n_storage=2), sched=sched
    )
    yield sched, Metacluster(mgmt_db), d1, d2
    for c in (mgmt_cluster, d1_cluster, d2_cluster):
        c.stop()


def drive(sched, coro):
    t = sched.spawn(coro, name="drive")
    sched.run_until(t.done)
    return t.done.get()


def test_assignment_balancing_and_data_isolation(world):
    sched, mc, d1, d2 = world

    async def body():
        await mc.register_cluster(b"dc1", d1, capacity=2)
        await mc.register_cluster(b"dc2", d2, capacity=2)
        # least-loaded assignment alternates
        placed = [await mc.create_tenant(b"t%d" % i) for i in range(4)]
        assert sorted(placed) == [b"dc1", b"dc1", b"dc2", b"dc2"]
        # capacity exhausted -> loud refusal
        try:
            await mc.create_tenant(b"overflow")
            raise AssertionError("capacity not enforced")
        except MetaclusterCapacityExceeded:
            pass
        # tenant handles bind to the RIGHT data cluster and isolate
        t0 = await mc.open_tenant(b"t0")
        async def w(txn):
            await txn.set(b"k", b"from-t0")
        await t0.run(w)
        t1 = await mc.open_tenant(b"t1")
        txn = t1.create_transaction()
        assert await txn.get(b"k") is None  # t1 sees its own keyspace
        txn0 = t0.create_transaction()
        assert await txn0.get(b"k") == b"from-t0"
        assignments = await mc.list_tenants()
        assert assignments[b"t0"] in (b"dc1", b"dc2")
        return True

    assert drive(sched, body())


def test_double_registration_refused(world):
    sched, mc, d1, _d2 = world

    async def body():
        await mc.register_cluster(b"dc1", d1)
        mc2 = Metacluster(mc.db)
        try:
            await mc2.register_cluster(b"other-name", d1)
            raise AssertionError("double registration allowed")
        except ClusterAlreadyRegistered:
            return True

    assert drive(sched, body())


def test_remove_cluster_requires_empty(world):
    sched, mc, d1, _d2 = world

    async def body():
        await mc.register_cluster(b"dc1", d1, capacity=5)
        await mc.create_tenant(b"occupied")
        try:
            await mc.remove_cluster(b"dc1")
            raise AssertionError("non-empty removal allowed")
        except ClusterNotEmpty:
            pass
        # deleting a tenant with data refuses; empty delete then works
        t = await mc.open_tenant(b"occupied")
        async def w(txn):
            await txn.set(b"x", b"1")
        await t.run(w)
        try:
            await mc.delete_tenant(b"occupied")
            raise AssertionError("non-empty tenant deleted")
        except T.TenantNotEmpty:
            pass
        async def clr(txn):
            await txn.clear_range(b"", b"\xff")
        await t.run(clr)
        await mc.delete_tenant(b"occupied")
        await mc.remove_cluster(b"dc1")
        assert await mc.list_clusters() == {}
        # the data cluster is registerable again after removal
        await mc.register_cluster(b"dc1-again", d1)
        return True

    assert drive(sched, body())


def test_concurrent_creates_never_overcommit(world):
    """Two racing create_tenant calls must serialize through read
    conflicts — capacity 1 admits exactly one (second review pass:
    the counter-row design lost updates)."""
    sched, mc, d1, _d2 = world

    async def body():
        await mc.register_cluster(b"dc1", d1, capacity=1)
        results = []

        async def one(i):
            try:
                results.append(await mc.create_tenant(b"race%d" % i))
            except MetaclusterCapacityExceeded:
                results.append(None)

        t1 = sched.spawn(one(0))
        t2 = sched.spawn(one(1))
        await t1.done
        await t2.done
        return results

    results = drive(sched, body())
    assert sorted(results, key=str) == [None, b"dc1"], results


def test_crash_mid_create_repairs(world):
    """A CREATING assignment left by a crash is finished by the next
    open/create (staged create; second review pass: pre-commit data-
    cluster creation orphaned tenants)."""
    sched, mc, d1, _d2 = world

    async def body():
        await mc.register_cluster(b"dc1", d1, capacity=5)
        # simulate the crash window: phase-1 committed, nothing else
        txn = mc.db.create_transaction()
        txn.set(b"\xff/metacluster/tenants/limbo", b"\x00creating/dc1")
        await txn.commit()
        t = await mc.open_tenant(b"limbo")  # repairs then binds
        async def w(tx):
            await tx.set(b"k", b"alive")
        await t.run(w)
        assignments = await mc.list_tenants()
        assert assignments[b"limbo"] == b"dc1"
        return True

    assert drive(sched, body())
