"""Kernel-vs-oracle parity: the L1 harness of SURVEY.md §7.2.

Randomized multi-batch workloads through both the TPU kernel
(TpuConflictSet) and the Python semantic oracle; verdicts and
conflicting-key-range reports must match bit-for-bit.
"""

import numpy as np
import pytest

from foundationdb_tpu.config import KernelConfig
from foundationdb_tpu.models.conflict_set import TpuConflictSet
from foundationdb_tpu.models.types import CommitTransaction, TransactionResult
from foundationdb_tpu.testing.oracle import ConflictOracle, OracleTxn
from foundationdb_tpu.testing import workloads

# compile-heavy kernel tests: run with -m kernel (fast lane: -m 'not kernel')
pytestmark = pytest.mark.kernel

CFG = KernelConfig(
    max_key_bytes=12,
    max_txns=64,
    max_reads=256,
    max_writes=256,
    history_capacity=1 << 11,
    window_versions=50,
)


def run_parity(seed, wcfg, n_batches, version_step=7, kcfg=CFG):
    rng = np.random.default_rng(seed)
    cs = TpuConflictSet(kcfg)
    oracle = ConflictOracle(window=kcfg.window_versions)
    version = 100
    for b in range(n_batches):
        version += version_step
        txns = workloads.make_batch(rng, wcfg, version, kcfg.window_versions)
        got = cs.resolve(txns, version)
        want = oracle.resolve(
            [
                OracleTxn(
                    t.read_conflict_ranges,
                    t.write_conflict_ranges,
                    t.read_snapshot,
                    t.report_conflicting_keys,
                )
                for t in txns
            ],
            version,
        )
        got_v = [int(v) for v in got.verdicts]
        assert got_v == want.verdicts, (
            f"seed={seed} batch={b}: verdict mismatch\n"
            f"got  {got_v}\nwant {want.verdicts}"
        )
        want_ckr = {
            t: idxs
            for t, idxs in want.conflicting_ranges.items()
            if txns[t].report_conflicting_keys
            and want.verdicts[t] == int(TransactionResult.CONFLICT)
        }
        assert got.conflicting_key_ranges == want_ckr, (
            f"seed={seed} batch={b}: conflicting-range mismatch\n"
            f"got  {got.conflicting_key_ranges}\nwant {want_ckr}"
        )
    cs.check_overflow()


@pytest.mark.parametrize("seed", range(8))
def test_parity_uniform(seed):
    run_parity(seed, workloads.WorkloadConfig(n_txns=24, keyspace=32), n_batches=6)


@pytest.mark.parametrize("seed", range(4))
def test_parity_hot_keys(seed):
    # heavy contention: tiny keyspace, wide ranges
    w = workloads.WorkloadConfig(
        n_txns=20, keyspace=8, point_fraction=0.3, max_read_ranges=2,
        max_write_ranges=2,
    )
    run_parity(seed + 100, w, n_batches=6)


@pytest.mark.parametrize("seed", range(4))
def test_parity_stale_snapshots(seed):
    # exercises tooOld classification and GC interaction
    w = workloads.WorkloadConfig(n_txns=16, keyspace=16, stale_fraction=0.3)
    run_parity(seed + 200, w, n_batches=8, version_step=13)


def test_parity_long_run_with_gc():
    # enough batches that the MVCC window slides and merged history GCs
    w = workloads.WorkloadConfig(n_txns=16, keyspace=24, stale_fraction=0.1)
    run_parity(300, w, n_batches=24, version_step=11)


def test_parity_blind_writes_and_reports():
    w = workloads.WorkloadConfig(
        n_txns=24, keyspace=16, blind_write_fraction=0.4, report_fraction=1.0
    )
    run_parity(500, w, n_batches=6)


def test_intra_batch_chain():
    """A dependency chain: t0 commits, t1 conflicts on t0, t2 commits
    because t1 aborted, t3 conflicts on t2 — exercises fixpoint depth > 2."""
    cs = TpuConflictSet(CFG)
    k = workloads.int_key

    def T(reads=(), writes=(), snap=99):
        return CommitTransaction(
            read_conflict_ranges=[(k(a), k(a) + b"\x00") for a in reads],
            write_conflict_ranges=[(k(a), k(a) + b"\x00") for a in writes],
            read_snapshot=snap,
        )

    txns = [
        T(writes=[1]),
        T(reads=[1], writes=[2]),   # conflicts with t0
        T(reads=[2], writes=[3]),   # t1 aborted -> commits
        T(reads=[3], writes=[4]),   # conflicts with t2
        T(reads=[4], writes=[5]),   # t3 aborted -> commits
    ]
    got = cs.resolve(txns, version=100)
    want = [
        TransactionResult.COMMITTED,
        TransactionResult.CONFLICT,
        TransactionResult.COMMITTED,
        TransactionResult.CONFLICT,
        TransactionResult.COMMITTED,
    ]
    assert got.verdicts == want


def test_scan_fused_path_matches_sequential(rng):
    """resolve_args_scan (K batches, one dispatch) must produce exactly
    the sequential per-batch decisions — the state chains inside the
    scan."""
    import numpy as np

    from foundationdb_tpu.config import TEST_CONFIG
    from foundationdb_tpu.models.conflict_set import TpuConflictSet
    from foundationdb_tpu.testing.benchgen import skiplist_style_batch

    config = TEST_CONFIG
    batches = [
        skiplist_style_batch(
            rng, config, 48, version=(i + 1) * 100, keyspace=300,
            key_bytes=4, snapshot_lag=150,
        )
        for i in range(6)
    ]
    seq = TpuConflictSet(config)
    seq_verdicts = [
        np.asarray(seq.resolve_packed(b).verdict) for b in batches
    ]
    from foundationdb_tpu.utils.packing import stack_device_args

    fused = TpuConflictSet(config)
    for gi, g in enumerate((batches[:3], batches[3:])):
        outs = fused.resolve_args_scan(stack_device_args(g))
        base = gi * 3
        for j in range(3):
            got = np.asarray(outs.verdict[j])
            assert (got == seq_verdicts[base + j]).all(), (base + j)
